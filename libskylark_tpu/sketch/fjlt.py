"""RFUT (randomized fast unitary transform) and FJLT.

TPU-native analogs of ref: sketch/RFUT_data.hpp:20-55, sketch/RFUT_Elemental.hpp:15-310,
sketch/FJLT_data.hpp:25-98, sketch/FJLT_Elemental.hpp:13-555.

RFUT: X → F·D·X with D a random (Rademacher) diagonal and F a fast unitary
transform scaled to near-orthonormality.

FJLT (subsampled randomized DCT/DHT): S = sqrt(N/S_dim) · R · F · D — mix with
RFUT, then uniformly sample S_dim coordinates
(ref: FJLT_Elemental.hpp:144-174: per-rank local FUT, then sample with scale
sqrt(N/S)). Under a sharded input the FUT runs independently per column shard
(the transform acts along the N axis, which is materialized locally when the
input is column-sharded; for row-sharded inputs XLA re-lays out, the analog of
the reference's [VC,*] → [*,VR] redistribution).
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.fut import make_fut
from libskylark_tpu.sketch.transform import SketchTransform, register


@register
class RFUT(SketchTransform):
    """X → F·D·X (output dim == input dim). ``dist`` fixed to Rademacher, the
    only use in the reference (FJLT's underlying mixer)."""

    sketch_type = "RFUT"

    def __init__(self, N, S=None, context=None, fut: str = "dct"):
        # RFUT preserves dimension; accept (N, context) calling style too.
        if context is None:
            context = S
            S = N
        self._fut_name = fut
        super().__init__(N, N, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, alloc, fut=d.get("fut", "dct"))


@register
class FJLT(SketchTransform):
    """Fast Johnson-Lindenstrauss transform (ref: sketch/FJLT_data.hpp)."""

    sketch_type = "FJLT"

    def __init__(self, N, S, context, fut: str = "dct"):
        self._fut_name = fut
        super().__init__(N, S, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        """Rademacher mixing diagonal (sub-stream 0; the underlying RFUT's D)."""
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def sample_indices(self) -> jnp.ndarray:
        """Uniform coordinate samples (sub-stream 1; ref: FJLT_data.hpp:83-86)."""
        return randgen.stream_slice(
            self.subkey(1),
            randgen.UniformInt(0, self._N - 1),
            0,
            self._S,
            dtype=jnp.int32,
        )

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[self.sample_indices(), :]

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[:, self.sample_indices()]

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, fut=d.get("fut", "dct"))
