"""RFUT (randomized fast unitary transform) and FJLT.

TPU-native analogs of ref: sketch/RFUT_data.hpp:20-55, sketch/RFUT_Elemental.hpp:15-310,
sketch/FJLT_data.hpp:25-98, sketch/FJLT_Elemental.hpp:13-555.

RFUT: X → F·D·X with D a random (Rademacher) diagonal and F a fast unitary
transform scaled to near-orthonormality.

FJLT (subsampled randomized DCT/DHT): S = sqrt(N/S_dim) · R · F · D — mix with
RFUT, then uniformly sample S_dim coordinates
(ref: FJLT_Elemental.hpp:144-174: per-rank local FUT, then sample with scale
sqrt(N/S)). Under a sharded input the FUT runs independently per column shard
(the transform acts along the N axis, which is materialized locally when the
input is column-sharded; for row-sharded inputs XLA re-lays out, the analog of
the reference's [VC,*] → [*,VR] redistribution).
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.sketch import fut as _fut
from libskylark_tpu.sketch.fut import make_fut
from libskylark_tpu.sketch.transform import SketchTransform, register


def srht_serve_apply(key_data, A, *, s_dim: int, rowwise: bool):
    """Panel-free SRHT serve program (the ``sketch_apply`` executable
    body for the FJLT/``wht`` family, docs/serving).

    Rebuilds the Rademacher diagonal (sub-stream 0) and the sampled
    coordinates (sub-stream 1) from the raw key data with the same
    positional :func:`randgen.stream_slice` calls the transform's own
    ``diagonal()`` / ``sample_indices()`` make — bit-identical streams
    — then contracts through :func:`fut.fwht_sketch` instead of a
    materialized operator panel. The transform axis is the exact
    (never padded) extent: the FWHT length defines the operator, so
    ``_sketch_statics`` pads only the free axis for this family."""
    import jax
    import jax.random as jr

    key = jr.wrap_key_data(jnp.asarray(key_data))
    n = A.shape[1] if rowwise else A.shape[0]
    if n & (n - 1):
        raise ValueError(f"SRHT serve requires power-of-2 n, got {n}")
    D = randgen.stream_slice(
        jax.random.fold_in(key, 0), randgen.Rademacher(), 0, n,
        dtype=A.dtype)
    idx = randgen.stream_slice(
        jax.random.fold_in(key, 1), randgen.UniformInt(0, n - 1),
        0, s_dim, dtype=jnp.int32)
    return _fut.fwht_sketch(
        A, D, idx, 1.0 / math.sqrt(n), math.sqrt(n / s_dim),
        axis=1 if rowwise else 0)


def _popcount_parity(a: np.ndarray) -> np.ndarray:
    """Elementwise popcount parity of a uint64 array. ``np.bitwise_count``
    when this numpy has it (>= 2.0); otherwise the xor-fold parity
    trick (six shifts — parity is all the Hadamard sign needs)."""
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(a) & np.uint64(1)
    for shift in (32, 16, 8, 4, 2, 1):
        a = a ^ (a >> np.uint64(shift))
    return a & np.uint64(1)


@register
class RFUT(SketchTransform):
    """X → F·D·X (output dim == input dim). ``dist`` fixed to Rademacher, the
    only use in the reference (FJLT's underlying mixer)."""

    sketch_type = "RFUT"

    def __init__(self, N, S=None, context=None, fut: str = "dct"):
        # RFUT preserves dimension; accept (N, context) calling style too.
        if context is None:
            context = S
            S = N
        self._fut_name = fut
        super().__init__(N, N, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, alloc, fut=d.get("fut", "dct"))


@register
class FJLT(SketchTransform):
    """Fast Johnson-Lindenstrauss transform (ref: sketch/FJLT_data.hpp)."""

    sketch_type = "FJLT"

    def __init__(self, N, S, context, fut: str = "dct"):
        self._fut_name = fut
        super().__init__(N, S, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        """Rademacher mixing diagonal (sub-stream 0; the underlying RFUT's D)."""
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def sample_indices(self) -> jnp.ndarray:
        """Uniform coordinate samples (sub-stream 1; ref: FJLT_data.hpp:83-86)."""
        return randgen.stream_slice(
            self.subkey(1),
            randgen.UniformInt(0, self._N - 1),
            0,
            self._S,
            dtype=jnp.int32,
        )

    def operator_panel(self, col_start: int, col_stop: int,
                       dtype=jnp.float32,
                       diagonal=None) -> np.ndarray:
        """Columns ``[col_start, col_stop)`` of the sampled-WHT operator
        in closed form, as a host array:
        ``S[k, j] = D[j] · (−1)^popcount(idx_k & j) / sqrt(s)`` — the
        Sylvester Hadamard entry at (sampled row ``idx_k``, position
        ``j``) times the Rademacher diagonal, scaled to ``1/sqrt(s)``
        (the FJLT's ``sqrt(n/s)`` times the WHT's ``1/sqrt(n)``).

        This is the positional column-panel stream the streaming SRHT
        appenders (:mod:`libskylark_tpu.sessions`) and the row-sharded
        partial sketches (:mod:`libskylark_tpu.dist`) fold against: a
        pure function of ``(seed, col_start, col_stop)``, so any
        process recomputes a shard's panel bit-identically. Only the
        ``wht`` mixer has this closed form (``n`` a power of two).

        ``diagonal`` lets a long-lived caller amortize the Rademacher
        stream: pass the FULL host :meth:`diagonal` (length ``n``,
        panel dtype) and only its slice is used — the sessions
        appender generates it once at open (thousands of small
        appends), while shard tasks omit it and materialize just their
        own O(shard) slice (``n`` may dwarf any one task). Both paths
        are bit-identical (positional streams)."""
        if self._fut_name != "wht":
            raise errors.UnsupportedError(
                "operator_panel is closed-form only for the 'wht' "
                f"(Sylvester-Hadamard) mixer, not {self._fut_name!r}")
        dt = np.dtype(dtype)
        # the s sampled rows never change for this instance: memoize
        # the host copy so a long panel stream pays that PRNG
        # generation and device->host transfer once, not per panel.
        # Runtime state only — never serialized (the OperatorCache
        # discipline).
        idx = self._host_sample_indices()
        cols = np.arange(col_start, col_stop, dtype=np.uint64)
        par = _popcount_parity(idx[:, None] & cols[None, :])
        signs = (1.0 - 2.0 * par).astype(dt)
        if diagonal is not None:
            diag = np.asarray(diagonal, dtype=dt)[col_start:col_stop]
        else:
            diag = np.asarray(randgen.stream_slice(
                self.subkey(0), randgen.Rademacher(), col_start,
                col_stop, dtype=dt))
        return (signs * diag) / np.asarray(math.sqrt(self._S), dt)

    def _host_sample_indices(self) -> np.ndarray:
        """Host uint64 copy of :meth:`sample_indices`, memoized (the
        ``operator_panel`` cache — shared so the panel oracle and the
        panel-free fold gather from literally the same host array)."""
        idx = getattr(self, "_panel_idx_cache", None)
        if idx is None:
            idx = np.asarray(self.sample_indices()).astype(np.uint64)
            self._panel_idx_cache = idx
        return idx

    def fold_rows(self, X, row_start: int, row_stop: int,
                  dtype=jnp.float32, diagonal=None) -> jnp.ndarray:
        """Panel-free partial fold: ``operator_panel(row_start,
        row_stop) @ X`` without materializing the O(rows·s) panel.

        The row range decomposes greedily into ≤ 2·log2(n) aligned
        power-of-two blocks ``[b, b+L)`` (``b % L == 0``); within one,
        ``popcount(idx_k & (b+j)) = popcount(idx_k & b) +
        popcount((idx_k mod L) & j)``, so the block's contribution is
        ``(−1)^popcount(idx_k & b) · FWHT_L(D_blk ⊙ X_blk)[idx_k mod
        L]`` — an O(L·log L·m) transform instead of an O(L·s) panel
        generation plus an O(L·s·m) contraction. Per-block signs and
        gather coordinates come host-side from the memoized sample
        indices (the same array the panel oracle uses), so the fold is
        the panel's bit pattern whenever every intermediate is exactly
        representable (integer-valued data, ``n``/``s`` even powers of
        two — the regression battery in tests/test_fwht.py), and
        allclose otherwise. ``diagonal`` follows the
        :meth:`operator_panel` contract: the FULL host diagonal, of
        which only ``[row_start:row_stop)`` is read."""
        if self._fut_name != "wht":
            raise errors.UnsupportedError(
                "fold_rows is closed-form only for the 'wht' "
                f"(Sylvester-Hadamard) mixer, not {self._fut_name!r}")
        dt = np.dtype(dtype)
        lo, hi = int(row_start), int(row_stop)
        X = jnp.asarray(X)
        if X.dtype != dt:
            X = X.astype(dt)
        if X.shape[0] != hi - lo:
            raise ValueError(
                f"operand rows {X.shape[0]} != range extent {hi - lo}")
        idx = self._host_sample_indices()
        out = jnp.zeros((self._S,) + X.shape[1:], dt)
        off = lo
        while off < hi:
            rem = hi - off
            block = 1 << (rem.bit_length() - 1)
            if off:
                block = min(block, off & -off)
            par = _popcount_parity(idx & np.uint64(off))
            signs = jnp.asarray((1.0 - 2.0 * par).astype(dt))
            gidx = jnp.asarray((idx & np.uint64(block - 1))
                               .astype(np.int32))
            if diagonal is not None:
                d = np.asarray(diagonal, dtype=dt)[off:off + block]
            else:
                d = randgen.stream_slice(
                    self.subkey(0), randgen.Rademacher(), off,
                    off + block, dtype=dt)
            w = d[:, None] * X[off - lo:off - lo + block]
            if block > 1:
                w = _fut.fwht(w, axis=0)
            out = out + signs[:, None] * w[gidx]
            off += block
        return (1.0 / math.sqrt(self._S)) * out

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[self.sample_indices(), :]

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[:, self.sample_indices()]

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, fut=d.get("fut", "dct"))
