"""RFUT (randomized fast unitary transform) and FJLT.

TPU-native analogs of ref: sketch/RFUT_data.hpp:20-55, sketch/RFUT_Elemental.hpp:15-310,
sketch/FJLT_data.hpp:25-98, sketch/FJLT_Elemental.hpp:13-555.

RFUT: X → F·D·X with D a random (Rademacher) diagonal and F a fast unitary
transform scaled to near-orthonormality.

FJLT (subsampled randomized DCT/DHT): S = sqrt(N/S_dim) · R · F · D — mix with
RFUT, then uniformly sample S_dim coordinates
(ref: FJLT_Elemental.hpp:144-174: per-rank local FUT, then sample with scale
sqrt(N/S)). Under a sharded input the FUT runs independently per column shard
(the transform acts along the N axis, which is materialized locally when the
input is column-sharded; for row-sharded inputs XLA re-lays out, the analog of
the reference's [VC,*] → [*,VR] redistribution).
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.sketch.fut import make_fut
from libskylark_tpu.sketch.transform import SketchTransform, register


def _popcount_parity(a: np.ndarray) -> np.ndarray:
    """Elementwise popcount parity of a uint64 array. ``np.bitwise_count``
    when this numpy has it (>= 2.0); otherwise the xor-fold parity
    trick (six shifts — parity is all the Hadamard sign needs)."""
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(a) & np.uint64(1)
    for shift in (32, 16, 8, 4, 2, 1):
        a = a ^ (a >> np.uint64(shift))
    return a & np.uint64(1)


@register
class RFUT(SketchTransform):
    """X → F·D·X (output dim == input dim). ``dist`` fixed to Rademacher, the
    only use in the reference (FJLT's underlying mixer)."""

    sketch_type = "RFUT"

    def __init__(self, N, S=None, context=None, fut: str = "dct"):
        # RFUT preserves dimension; accept (N, context) calling style too.
        if context is None:
            context = S
            S = N
        self._fut_name = fut
        super().__init__(N, N, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        return self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, alloc, fut=d.get("fut", "dct"))


@register
class FJLT(SketchTransform):
    """Fast Johnson-Lindenstrauss transform (ref: sketch/FJLT_data.hpp)."""

    sketch_type = "FJLT"

    def __init__(self, N, S, context, fut: str = "dct"):
        self._fut_name = fut
        super().__init__(N, S, context)

    def _build(self):
        self._fut = make_fut(self._fut_name, self._N)

    def diagonal(self, dtype=jnp.float32) -> jnp.ndarray:
        """Rademacher mixing diagonal (sub-stream 0; the underlying RFUT's D)."""
        return randgen.stream_slice(
            self.subkey(0), randgen.Rademacher(), 0, self._N, dtype=dtype
        )

    def sample_indices(self) -> jnp.ndarray:
        """Uniform coordinate samples (sub-stream 1; ref: FJLT_data.hpp:83-86)."""
        return randgen.stream_slice(
            self.subkey(1),
            randgen.UniformInt(0, self._N - 1),
            0,
            self._S,
            dtype=jnp.int32,
        )

    def operator_panel(self, col_start: int, col_stop: int,
                       dtype=jnp.float32,
                       diagonal=None) -> np.ndarray:
        """Columns ``[col_start, col_stop)`` of the sampled-WHT operator
        in closed form, as a host array:
        ``S[k, j] = D[j] · (−1)^popcount(idx_k & j) / sqrt(s)`` — the
        Sylvester Hadamard entry at (sampled row ``idx_k``, position
        ``j``) times the Rademacher diagonal, scaled to ``1/sqrt(s)``
        (the FJLT's ``sqrt(n/s)`` times the WHT's ``1/sqrt(n)``).

        This is the positional column-panel stream the streaming SRHT
        appenders (:mod:`libskylark_tpu.sessions`) and the row-sharded
        partial sketches (:mod:`libskylark_tpu.dist`) fold against: a
        pure function of ``(seed, col_start, col_stop)``, so any
        process recomputes a shard's panel bit-identically. Only the
        ``wht`` mixer has this closed form (``n`` a power of two).

        ``diagonal`` lets a long-lived caller amortize the Rademacher
        stream: pass the FULL host :meth:`diagonal` (length ``n``,
        panel dtype) and only its slice is used — the sessions
        appender generates it once at open (thousands of small
        appends), while shard tasks omit it and materialize just their
        own O(shard) slice (``n`` may dwarf any one task). Both paths
        are bit-identical (positional streams)."""
        if self._fut_name != "wht":
            raise errors.UnsupportedError(
                "operator_panel is closed-form only for the 'wht' "
                f"(Sylvester-Hadamard) mixer, not {self._fut_name!r}")
        dt = np.dtype(dtype)
        # the s sampled rows never change for this instance: memoize
        # the host copy so a long panel stream pays that PRNG
        # generation and device->host transfer once, not per panel.
        # Runtime state only — never serialized (the OperatorCache
        # discipline).
        idx = getattr(self, "_panel_idx_cache", None)
        if idx is None:
            idx = np.asarray(self.sample_indices()).astype(np.uint64)
            self._panel_idx_cache = idx
        cols = np.arange(col_start, col_stop, dtype=np.uint64)
        par = _popcount_parity(idx[:, None] & cols[None, :])
        signs = (1.0 - 2.0 * par).astype(dt)
        if diagonal is not None:
            diag = np.asarray(diagonal, dtype=dt)[col_start:col_stop]
        else:
            diag = np.asarray(randgen.stream_slice(
                self.subkey(0), randgen.Rademacher(), col_start,
                col_stop, dtype=dt))
        return (signs * diag) / np.asarray(math.sqrt(self._S), dt)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[:, None] * A, axis=0)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[self.sample_indices(), :]

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        D = self.diagonal(A.dtype)
        mixed = self._fut.apply(self._fut.scale() * D[None, :] * A, axis=1)
        scale = math.sqrt(self._N / self._S)
        return scale * mixed[:, self.sample_indices()]

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, fut=d.get("fut", "dct"))
