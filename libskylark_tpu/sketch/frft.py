"""Fastfood random features: FastGaussianRFT, FastMaternRFT.

TPU-native analog of ref: sketch/FRFT_data.hpp:26-291, sketch/FRFT_Elemental.hpp.
Le-Sarlos-Smola Fastfood: each block of NB features is
Sm ⊙ F(G ⊙ Π(F(B ⊙ x))) — two fast unitary transforms around a random
permutation and three random diagonals, giving an implicit Gaussian-like
frequency matrix in O(NB log NB) per block instead of O(NB²). Output is
scale·cos(w + shifts) like RFT.

Differences from the reference, by design:
- The block permutation is a uniform permutation from a sub-stream key
  (jax.random.permutation) rather than the reference's hand-rolled
  Fisher-Yates swap records (ref: FRFT_data.hpp:105-113) — same distribution,
  TPU-friendly gather.
- All columns and all blocks are processed batched (vmapped FUT over a
  (numblks, NB, m) tensor) instead of the reference's per-column OpenMP loop
  (ref: FRFT_Elemental.hpp:77-160).

Sub-streams: 0=shifts, 1=B, 2=G, 3=permutations, 4=Sm (Matern).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import jax.random as jr

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.fut import make_fut
from libskylark_tpu.sketch.transform import SketchTransform, register


def fut_apply_policy(fut_obj, fut_name: str, W):
    """The FUT along the contiguous feature axis. The WHT core opts
    into Precision.HIGH (TPU: 3-pass bf16 — near-lossless for ±1
    Hadamard factors, ~2× the full-f32 MXU rate; analysis at
    fut._wht_matmul) UNLESS the user pinned an explicit policy —
    via SKYLARK_MATMUL_PRECISION, jax.config.update, or an active
    jax.default_matmul_precision(...) context (r4 advisor) — which
    then governs here too. Runtime tuning only — never serialized,
    like the pallas regime knobs. Shared by the transform method and
    the serve-layer pure apply so the two paths cannot drift."""
    if fut_name != "wht":
        return fut_obj.apply(W, axis=-1)
    from libskylark_tpu.base import env as _env
    from libskylark_tpu.base import precision as bprec

    prec = (None if _env.MATMUL_PRECISION.raw()
            or bprec.ambient_precision_pinned_by_user()
            else jax.lax.Precision.HIGH)
    return fut_obj.apply(W, axis=-1, precision=prec)


def _chain_rows(Ap, bdiag, gdiag, smdiag, perms, shifts, out_scale,
                scal, NB: int, nb: int, fut_apply):
    """The SHGΠHB chain on padded row-major input (m, NB) — ONE
    definition shared by ``FastRFT._features_rows`` and the pure serve
    apply (:func:`fastfood_serve_apply`), so the served features are
    the transform's features by construction. Laid out for HBM economy
    (see the ``_features_rows`` docstring): (blocks, rows, NB) with the
    transform length contiguous; block-major feature order, truncation
    to S = ``shifts.shape[0]``."""
    W = bdiag[:, None, :] * Ap[None, :, :]                # (nb, m, NB)
    W = fut_apply(W)
    W = jnp.take_along_axis(W, perms[:, None, :], axis=-1)
    W = (scal * gdiag)[:, None, :] * W
    W = fut_apply(W)
    W = (scal * smdiag.reshape(nb, 1, NB)) * W
    # block-major feature order (matches the serialized definition);
    # for nb == 1 the moveaxis is a free squeeze
    W = jnp.moveaxis(W, 0, 1).reshape(Ap.shape[0], nb * NB)
    W = W[:, : shifts.shape[0]]
    return out_scale * jnp.cos(W + shifts[None, :])


def block_geometry(n_dim: int, s_dim: int, fut: str = "wht"
                   ) -> tuple[int, int]:
    """(NB, numblks) for a Fastfood transform of these dimensions —
    the ``FastRFT._build`` rule as a pure function (the serve layer
    recomputes geometry from bucket statics)."""
    NB = (1 << max(0, (n_dim - 1).bit_length())) if fut == "wht" \
        else n_dim
    return NB, 1 + (s_dim - 1) // NB


def serve_streams(key, dtype, *, NB: int, nb: int, s_dim: int,
                  sm_kind: str, sm_param):
    """Every Fastfood stream as a pure function of the transform's
    allocation key: (bdiag, gdiag, smdiag, perms, shifts) — identical
    bits to ``_B``/``_G``/``_Sm``/``_perms``/``shifts`` (sub-streams
    1/2/4-spec/3/0 of the key; pinned by tests). vmap-safe, so the
    microbatch serve executable rebuilds a whole cohort's streams from
    the stacked raw keys."""
    def sub(tag):
        return jr.fold_in(key, tag)

    bdiag = randgen.stream_slice(
        sub(1), randgen.Rademacher(), 0, nb * NB, dtype=dtype,
    ).reshape(nb, NB)
    gdiag = randgen.stream_slice(
        sub(2), randgen.Normal(), 0, nb * NB, dtype=dtype,
    ).reshape(nb, NB)
    pkey = sub(3)
    perms = jnp.stack(
        [jr.permutation(jr.fold_in(pkey, i), NB) for i in range(nb)])
    shifts = randgen.stream_slice(
        sub(0), randgen.Uniform(0.0, 2.0 * math.pi), 0, s_dim,
        dtype=dtype)
    if sm_kind == "ones":
        smdiag = jnp.ones((nb * NB,), dtype)
    elif sm_kind == "gauss":
        smdiag = jnp.full(
            (nb * NB,), 1.0 / (float(sm_param) * math.sqrt(NB)), dtype)
    elif sm_kind == "matern":
        nu, el = sm_param
        chi2 = randgen.stream_slice(
            sub(4), randgen.Gamma(shape_param=float(nu), scale=2.0),
            0, nb * NB, dtype=dtype)
        smdiag = jnp.sqrt(
            2.0 * float(nu) / jnp.maximum(chi2, jnp.finfo(dtype).tiny)
        ) / (float(el) * math.sqrt(NB))
    else:
        raise ValueError(f"unknown Sm spec kind {sm_kind!r}")
    return bdiag, gdiag, smdiag, perms, shifts


def fastfood_serve_apply(key_data, A, *, n_dim: int, s_dim: int,
                         fut: str = "wht", sm_kind: str = "ones",
                         sm_param=None) -> jnp.ndarray:
    """Pure, vmap-batchable Fastfood feature map for the microbatch
    serving layer: one request's (m, S) features as a function of the
    transform's raw key data ((2,) uint32) and static geometry. Rows
    are independent lanes, so zero-padding the row extent past the true
    request is exact for the real rows (padded rows are sliced away by
    the executor); the column extent must equal ``n_dim`` (the chain's
    own NB-padding is part of the feature definition)."""
    key = jr.wrap_key_data(jnp.asarray(key_data))
    NB, nb = block_geometry(n_dim, s_dim, fut)
    dt = A.dtype
    pad = NB - n_dim
    Ap = jnp.pad(A, ((0, 0), (0, pad))) if pad else A
    fut_obj = make_fut(fut, NB)
    scal = math.sqrt(NB) * fut_obj.scale()
    bdiag, gdiag, smdiag, perms, shifts = serve_streams(
        key, dt, NB=NB, nb=nb, s_dim=s_dim, sm_kind=sm_kind,
        sm_param=sm_param)
    return _chain_rows(
        Ap, bdiag, gdiag, smdiag, perms, shifts,
        math.sqrt(2.0 / s_dim), scal, NB, nb,
        lambda W: fut_apply_policy(fut_obj, fut, W))


class FastRFT(SketchTransform):
    """Base Fastfood transform (ref: sketch/FRFT_data.hpp:26-139).

    Default FUT is the Walsh-Hadamard transform — the reference's
    preferred Fastfood core when SpiralWHT is available
    (ref: FRFT_data.hpp:125, sketch/FUT.hpp:225-347); here it runs as the
    kron-factored MXU matmul (fut.py _wht_matmul), which is what makes
    Fastfood *fast* on TPU. ``fut="dct"`` keeps the FFT-based FFTW-analog
    path (any N without padding)."""

    sketch_type = "FastRFT"

    def __init__(self, N, S, context, fut: str = "wht"):
        self._fut_name = fut
        super().__init__(N, S, context)

    def _build(self):
        # DCT works for any N (FFTW analog, NB=N); WHT needs power-of-2
        # blocks (SpiralWHT analog) — ref: FRFT_data.hpp block_size().
        # One rule, shared with the serve layer's bucket-statics
        # recomputation (:func:`block_geometry`), so the two can never
        # drift apart.
        self._NB, self._numblks = block_geometry(
            self._N, self._S, self._fut_name)
        self._fut = make_fut(self._fut_name, self._NB)

    def _fut_apply(self, W):
        """The FUT along the contiguous feature axis — one shared
        definition with the serve-layer pure apply
        (:func:`fut_apply_policy`)."""
        return fut_apply_policy(self._fut, self._fut_name, W)

    def _sm_spec(self) -> tuple:
        """(kind, param) descriptor of the per-feature Sm scaling — the
        static the serve layer buckets on and rebuilds ``_Sm`` from in
        :func:`serve_streams` (base: all-ones)."""
        return ("ones", None)

    @property
    def scale(self) -> float:
        return math.sqrt(2.0 / self._S)

    def shifts(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(0), randgen.Uniform(0.0, 2.0 * math.pi), 0, self._S,
            dtype=dtype,
        )

    def _B(self, dtype) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(1), randgen.Rademacher(), 0, self._numblks * self._NB,
            dtype=dtype,
        ).reshape(self._numblks, self._NB)

    def _G(self, dtype) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(2), randgen.Normal(), 0, self._numblks * self._NB,
            dtype=dtype,
        ).reshape(self._numblks, self._NB)

    def _perms(self) -> jnp.ndarray:
        key = self.subkey(3)
        return jnp.stack(
            [jr.permutation(jr.fold_in(key, i), self._NB) for i in range(self._numblks)]
        )

    def _Sm(self, dtype) -> jnp.ndarray:
        """Kernel-specific per-feature scaling (numblks·NB,); subclasses override
        (ref: FRFT_data.hpp:118 — base fills 1)."""
        return jnp.ones((self._numblks * self._NB,), dtype)

    def _features_rows(self, At: jnp.ndarray) -> jnp.ndarray:
        """The (m, S) feature map for ROW-major input At (m, N).

        Laid out for HBM economy (the r3 on-CPU finding was Fastfood
        losing to the dense gemm on data movement, not FLOPs): the whole
        SHGΠHB chain runs in (blocks, rows, NB) layout with the
        transform length CONTIGUOUS, so the kron-factored WHT's two
        batched matmuls (fut._wht_matmul) touch no transposes, the
        permutation gathers along the minor axis, and the diagonals
        (B, G, Sm) fuse into the adjacent contractions. The rowwise
        apply — the ML feature-map case — moves no axis at all for a
        single block (numblks == 1 whenever S <= NB): input is consumed
        and features are produced in their natural layouts."""
        dt = At.dtype
        NB, nb = self._NB, self._numblks
        pad = NB - self._N
        Ap = jnp.pad(At, ((0, 0), (0, pad))) if pad else At
        scal = math.sqrt(NB) * self._fut.scale()
        return _chain_rows(
            Ap, self._B(dt), self._G(dt), self._Sm(dt), self._perms(),
            self.shifts(dt), self.scale, scal, NB, nb, self._fut_apply)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        # route through the rowwise dispatch so the fused kernel serves
        # this orientation too (the transpose feeds the kernel's
        # row-major tile layout either way)
        return self._apply_rowwise(A.T).T

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        # fused single-kernel chain on TPU (one HBM read of A, one write
        # of the features — the XLA chain re-touches the intermediate
        # ~9×; BASELINE.md crossover analysis); any decline or Mosaic
        # failure falls back to the XLA chain below. features_rows
        # consults the autotuner plan cache (libskylark_tpu/tune/)
        # first: a cached plan picks the fused/split variant and regime,
        # or certifies the XLA chain for this workload (it then declines
        # and the chain below serves).
        from libskylark_tpu.sketch import params as sketch_params

        if sketch_params.get_use_pallas():
            from libskylark_tpu.sketch import pallas_fastfood

            out = pallas_fastfood.features_rows(self, A)
            if out is not None:
                return out
        return self._features_rows(A)

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}


@register
class FastGaussianRFT(FastRFT):
    """Fastfood for the Gaussian kernel: Sm = 1/(σ√N)
    (ref: FRFT_data.hpp:196-203)."""

    sketch_type = "FastGaussianRFT"

    def __init__(self, N, S, context, sigma: float = 1.0, fut: str = "wht"):
        self._sigma = float(sigma)
        super().__init__(N, S, context, fut=fut)

    def _Sm(self, dtype) -> jnp.ndarray:
        # Normalize by the padded block length NB, not N: pre-Sm feature
        # variance is NB·‖x‖² (the reference always has NB == N via FFTW,
        # ref: FRFT_data.hpp:196-203; with WHT padding NB > N and using N
        # would bias the kernel bandwidth by NB/N).
        v = 1.0 / (self._sigma * math.sqrt(self._NB))
        return jnp.full((self._numblks * self._NB,), v, dtype)

    def _sm_spec(self) -> tuple:
        return ("gauss", self._sigma)

    def _extra_params(self) -> dict[str, Any]:
        return {"sigma": self._sigma, "fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, sigma=float(d.get("sigma", 1.0)),
                   fut=d.get("fut", "wht"))


@register
class FastMaternRFT(FastRFT):
    """Fastfood for the Matern kernel: Sm = sqrt(2ν/χ²(2ν))/(l√N)
    (ref: FRFT_data.hpp:268-277)."""

    sketch_type = "FastMaternRFT"

    def __init__(self, N, S, context, nu: float = 1.0, l: float = 1.0,
                 fut: str = "wht"):
        self._nu = float(nu)
        self._l = float(l)
        super().__init__(N, S, context, fut=fut)

    def _Sm(self, dtype) -> jnp.ndarray:
        chi2 = randgen.stream_slice(
            self.subkey(4),
            randgen.Gamma(shape_param=self._nu, scale=2.0),
            0,
            self._numblks * self._NB,
            dtype=dtype,
        )
        return jnp.sqrt(
            2.0 * self._nu / jnp.maximum(chi2, jnp.finfo(dtype).tiny)
        ) / (self._l * math.sqrt(self._NB))

    def _sm_spec(self) -> tuple:
        return ("matern", (self._nu, self._l))

    def _extra_params(self) -> dict[str, Any]:
        return {"nu": self._nu, "l": self._l, "fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, nu=float(d.get("nu", 1.0)),
                   l=float(d.get("l", 1.0)), fut=d.get("fut", "wht"))
