"""Fastfood random features: FastGaussianRFT, FastMaternRFT.

TPU-native analog of ref: sketch/FRFT_data.hpp:26-291, sketch/FRFT_Elemental.hpp.
Le-Sarlos-Smola Fastfood: each block of NB features is
Sm ⊙ F(G ⊙ Π(F(B ⊙ x))) — two fast unitary transforms around a random
permutation and three random diagonals, giving an implicit Gaussian-like
frequency matrix in O(NB log NB) per block instead of O(NB²). Output is
scale·cos(w + shifts) like RFT.

Differences from the reference, by design:
- The block permutation is a uniform permutation from a sub-stream key
  (jax.random.permutation) rather than the reference's hand-rolled
  Fisher-Yates swap records (ref: FRFT_data.hpp:105-113) — same distribution,
  TPU-friendly gather.
- All columns and all blocks are processed batched (vmapped FUT over a
  (numblks, NB, m) tensor) instead of the reference's per-column OpenMP loop
  (ref: FRFT_Elemental.hpp:77-160).

Sub-streams: 0=shifts, 1=B, 2=G, 3=permutations, 4=Sm (Matern).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import jax.random as jr

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.fut import make_fut
from libskylark_tpu.sketch.transform import SketchTransform, register


class FastRFT(SketchTransform):
    """Base Fastfood transform (ref: sketch/FRFT_data.hpp:26-139).

    Default FUT is the Walsh-Hadamard transform — the reference's
    preferred Fastfood core when SpiralWHT is available
    (ref: FRFT_data.hpp:125, sketch/FUT.hpp:225-347); here it runs as the
    kron-factored MXU matmul (fut.py _wht_matmul), which is what makes
    Fastfood *fast* on TPU. ``fut="dct"`` keeps the FFT-based FFTW-analog
    path (any N without padding)."""

    sketch_type = "FastRFT"

    def __init__(self, N, S, context, fut: str = "wht"):
        self._fut_name = fut
        super().__init__(N, S, context)

    def _build(self):
        # DCT works for any N (FFTW analog, NB=N); WHT needs power-of-2
        # blocks (SpiralWHT analog) — ref: FRFT_data.hpp block_size().
        if self._fut_name == "wht":
            self._NB = 1 << max(0, (self._N - 1).bit_length())
        else:
            self._NB = self._N
        self._numblks = 1 + (self._S - 1) // self._NB
        self._fut = make_fut(self._fut_name, self._NB)

    def _fut_apply(self, W):
        """The FUT along the contiguous feature axis. The WHT core opts
        into Precision.HIGH (TPU: 3-pass bf16 — near-lossless for ±1
        Hadamard factors, ~2× the full-f32 MXU rate; analysis at
        fut._wht_matmul) UNLESS the user pinned an explicit policy —
        via SKYLARK_MATMUL_PRECISION, jax.config.update, or an active
        jax.default_matmul_precision(...) context (r4 advisor) — which
        then governs here too. Runtime tuning only — never serialized,
        like the pallas regime knobs."""
        if self._fut_name != "wht":
            return self._fut.apply(W, axis=-1)
        import os

        from libskylark_tpu.base import precision as bprec

        prec = (None if os.environ.get("SKYLARK_MATMUL_PRECISION")
                or bprec.ambient_precision_pinned_by_user()
                else jax.lax.Precision.HIGH)
        return self._fut.apply(W, axis=-1, precision=prec)

    @property
    def scale(self) -> float:
        return math.sqrt(2.0 / self._S)

    def shifts(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(0), randgen.Uniform(0.0, 2.0 * math.pi), 0, self._S,
            dtype=dtype,
        )

    def _B(self, dtype) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(1), randgen.Rademacher(), 0, self._numblks * self._NB,
            dtype=dtype,
        ).reshape(self._numblks, self._NB)

    def _G(self, dtype) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(2), randgen.Normal(), 0, self._numblks * self._NB,
            dtype=dtype,
        ).reshape(self._numblks, self._NB)

    def _perms(self) -> jnp.ndarray:
        key = self.subkey(3)
        return jnp.stack(
            [jr.permutation(jr.fold_in(key, i), self._NB) for i in range(self._numblks)]
        )

    def _Sm(self, dtype) -> jnp.ndarray:
        """Kernel-specific per-feature scaling (numblks·NB,); subclasses override
        (ref: FRFT_data.hpp:118 — base fills 1)."""
        return jnp.ones((self._numblks * self._NB,), dtype)

    def _features_rows(self, At: jnp.ndarray) -> jnp.ndarray:
        """The (m, S) feature map for ROW-major input At (m, N).

        Laid out for HBM economy (the r3 on-CPU finding was Fastfood
        losing to the dense gemm on data movement, not FLOPs): the whole
        SHGΠHB chain runs in (blocks, rows, NB) layout with the
        transform length CONTIGUOUS, so the kron-factored WHT's two
        batched matmuls (fut._wht_matmul) touch no transposes, the
        permutation gathers along the minor axis, and the diagonals
        (B, G, Sm) fuse into the adjacent contractions. The rowwise
        apply — the ML feature-map case — moves no axis at all for a
        single block (numblks == 1 whenever S <= NB): input is consumed
        and features are produced in their natural layouts."""
        dt = At.dtype
        NB, nb = self._NB, self._numblks
        pad = NB - self._N
        Ap = jnp.pad(At, ((0, 0), (0, pad))) if pad else At
        scal = math.sqrt(NB) * self._fut.scale()

        W = self._B(dt)[:, None, :] * Ap[None, :, :]          # (nb, m, NB)
        W = self._fut_apply(W)
        W = jnp.take_along_axis(W, self._perms()[:, None, :], axis=-1)
        W = (scal * self._G(dt))[:, None, :] * W
        W = self._fut_apply(W)
        W = (scal * self._Sm(dt).reshape(nb, 1, NB)) * W
        # block-major feature order (matches the serialized definition);
        # for nb == 1 the moveaxis is a free squeeze
        W = jnp.moveaxis(W, 0, 1).reshape(Ap.shape[0], nb * NB)
        W = W[:, : self._S]
        return self.scale * jnp.cos(W + self.shifts(dt)[None, :])

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        # route through the rowwise dispatch so the fused kernel serves
        # this orientation too (the transpose feeds the kernel's
        # row-major tile layout either way)
        return self._apply_rowwise(A.T).T

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        # fused single-kernel chain on TPU (one HBM read of A, one write
        # of the features — the XLA chain re-touches the intermediate
        # ~9×; BASELINE.md crossover analysis); any decline or Mosaic
        # failure falls back to the XLA chain below. features_rows
        # consults the autotuner plan cache (libskylark_tpu/tune/)
        # first: a cached plan picks the fused/split variant and regime,
        # or certifies the XLA chain for this workload (it then declines
        # and the chain below serves).
        from libskylark_tpu.sketch import params as sketch_params

        if sketch_params.get_use_pallas():
            from libskylark_tpu.sketch import pallas_fastfood

            out = pallas_fastfood.features_rows(self, A)
            if out is not None:
                return out
        return self._features_rows(A)

    def _extra_params(self) -> dict[str, Any]:
        return {"fut": self._fut_name}


@register
class FastGaussianRFT(FastRFT):
    """Fastfood for the Gaussian kernel: Sm = 1/(σ√N)
    (ref: FRFT_data.hpp:196-203)."""

    sketch_type = "FastGaussianRFT"

    def __init__(self, N, S, context, sigma: float = 1.0, fut: str = "wht"):
        self._sigma = float(sigma)
        super().__init__(N, S, context, fut=fut)

    def _Sm(self, dtype) -> jnp.ndarray:
        # Normalize by the padded block length NB, not N: pre-Sm feature
        # variance is NB·‖x‖² (the reference always has NB == N via FFTW,
        # ref: FRFT_data.hpp:196-203; with WHT padding NB > N and using N
        # would bias the kernel bandwidth by NB/N).
        v = 1.0 / (self._sigma * math.sqrt(self._NB))
        return jnp.full((self._numblks * self._NB,), v, dtype)

    def _extra_params(self) -> dict[str, Any]:
        return {"sigma": self._sigma, "fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, sigma=float(d.get("sigma", 1.0)),
                   fut=d.get("fut", "wht"))


@register
class FastMaternRFT(FastRFT):
    """Fastfood for the Matern kernel: Sm = sqrt(2ν/χ²(2ν))/(l√N)
    (ref: FRFT_data.hpp:268-277)."""

    sketch_type = "FastMaternRFT"

    def __init__(self, N, S, context, nu: float = 1.0, l: float = 1.0,
                 fut: str = "wht"):
        self._nu = float(nu)
        self._l = float(l)
        super().__init__(N, S, context, fut=fut)

    def _Sm(self, dtype) -> jnp.ndarray:
        chi2 = randgen.stream_slice(
            self.subkey(4),
            randgen.Gamma(shape_param=self._nu, scale=2.0),
            0,
            self._numblks * self._NB,
            dtype=dtype,
        )
        return jnp.sqrt(
            2.0 * self._nu / jnp.maximum(chi2, jnp.finfo(dtype).tiny)
        ) / (self._l * math.sqrt(self._NB))

    def _extra_params(self) -> dict[str, Any]:
        return {"nu": self._nu, "l": self._l, "fut": self._fut_name}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, nu=float(d.get("nu", 1.0)),
                   l=float(d.get("l", 1.0)), fut=d.get("fut", "wht"))
