"""Fast unitary transforms: DCT, DHT, WHT.

TPU-native analog of the reference's FFTW/SpiralWHT plan wrappers
(ref: sketch/FUT.hpp:21-347). The reference wraps FFTW r2r plans (REDFT10 =
unnormalized DCT-II, REDFT01 = DCT-III, FFTW_DHT) and SpiralWHT; here the
transforms are XLA ops — ``jax.scipy.fft.dct`` matches FFTW's unnormalized
convention exactly, DHT is Re(FFT) − Im(FFT), and WHT is a log2(N) reshape
butterfly that XLA maps onto the VPU.

Scale convention matches the reference (ref: sketch/FUT.hpp:55-56): each FUT
exposes ``scale() = 1/sqrt(ScaleVal·N)`` with ScaleVal 2 for DCT, 1 for
DHT/WHT, making scale·F approximately orthonormal.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import jax.scipy.fft as jfft


def dct(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Unnormalized DCT-II (FFTW REDFT10 analog)."""
    return jfft.dct(A, type=2, axis=axis)


def idct(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Unnormalized DCT-III = FFTW REDFT01 (inverse of REDFT10 up to 2N)."""
    # jax idct(type=2) inverts dct including normalization; FFTW's REDFT01 is
    # unnormalized: REDFT01(REDFT10(x)) = 2N x. Match FFTW.
    n = A.shape[axis]
    return jfft.idct(A, type=2, axis=axis) * (2.0 * n)


def dht(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Discrete Hartley transform (FFTW_DHT analog): cas-kernel, self-inverse
    up to N."""
    F = jnp.fft.fft(A, axis=axis)
    return jnp.real(F) - jnp.imag(F)


def wht(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Fast Walsh-Hadamard transform (natural/Hadamard ordering), N = 2^k
    (SpiralWHT analog, ref: sketch/FUT.hpp:225-347). Unnormalized, self-inverse
    up to N."""
    if axis != 0:
        return jnp.moveaxis(wht(jnp.moveaxis(A, axis, 0)), 0, axis)
    n = A.shape[0]
    if n & (n - 1):
        raise ValueError(f"WHT requires power-of-2 length, got {n}")
    orig_shape = A.shape
    x = A.reshape(n, -1)
    h = 1
    while h < n:
        x = x.reshape(n // (2 * h), 2, h, -1)
        a, b = x[:, 0], x[:, 1]
        x = jnp.stack([a + b, a - b], axis=1).reshape(n, -1)
        h *= 2
    return x.reshape(orig_shape)


class FUT:
    """A fast unitary transform with the reference's scale convention."""

    def __init__(self, n: int):
        self.n = int(n)

    def scale(self) -> float:
        raise NotImplementedError

    def apply(self, A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
        raise NotImplementedError

    def apply_inverse(self, A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
        raise NotImplementedError


class DCT(FUT):
    """ScaleVal=2 (ref: sketch/FUT.hpp:138-140)."""

    name = "dct"

    def scale(self) -> float:
        return 1.0 / math.sqrt(2.0 * self.n)

    def apply(self, A, axis=0):
        return dct(A, axis)

    def apply_inverse(self, A, axis=0):
        return idct(A, axis)


class DHT(FUT):
    """ScaleVal=1 (ref: sketch/FUT.hpp:142-143)."""

    name = "dht"

    def scale(self) -> float:
        return 1.0 / math.sqrt(self.n)

    def apply(self, A, axis=0):
        return dht(A, axis)

    apply_inverse = apply


class WHT(FUT):
    """Walsh-Hadamard; requires power-of-2 n (ref: sketch/FUT.hpp:225-347)."""

    name = "wht"

    def scale(self) -> float:
        return 1.0 / math.sqrt(self.n)

    def apply(self, A, axis=0):
        return wht(A, axis)

    apply_inverse = apply


_FUTS = {"dct": DCT, "dht": DHT, "wht": WHT}


def make_fut(name: str, n: int) -> FUT:
    return _FUTS[name](n)
