"""Fast unitary transforms: DCT, DHT, WHT.

TPU-native analog of the reference's FFTW/SpiralWHT plan wrappers
(ref: sketch/FUT.hpp:21-347). The reference wraps FFTW r2r plans (REDFT10 =
unnormalized DCT-II, REDFT01 = DCT-III, FFTW_DHT) and SpiralWHT; here the
transforms are XLA ops — ``jax.scipy.fft.dct`` matches FFTW's unnormalized
convention exactly, DHT is Re(FFT) − Im(FFT), and WHT is a log2(N) reshape
butterfly that XLA maps onto the VPU.

Scale convention matches the reference (ref: sketch/FUT.hpp:55-56): each FUT
exposes ``scale() = 1/sqrt(ScaleVal·N)`` with ScaleVal 2 for DCT, 1 for
DHT/WHT, making scale·F approximately orthonormal.
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _dct2_last(x: jnp.ndarray) -> jnp.ndarray:
    """Unnormalized DCT-II along the last axis, via Makhoul's single-FFT
    decomposition: v = [x_even, reverse(x_odd)], y_k = 2·Re(FFT(v)_k·W_k),
    W_k = exp(−iπk/2N). Written out by hand because this backend supports
    lax.fft but not jax.scipy.fft.dct's lowering."""
    n = x.shape[-1]
    v = jnp.concatenate([x[..., ::2], jnp.flip(x[..., 1::2], -1)], -1)
    V = jnp.fft.fft(v, axis=-1)
    k = jnp.arange(n, dtype=jnp.float32)
    W = jnp.exp((-1j * math.pi / (2.0 * n)) * k).astype(V.dtype)
    return (2.0 * (V * W).real).astype(x.dtype)


def _dct3_last(y: jnp.ndarray) -> jnp.ndarray:
    """Unnormalized DCT-III (FFTW REDFT01) along the last axis — the exact
    inverse of :func:`_dct2_last` up to the FFTW 2N factor."""
    n = y.shape[-1]
    yr = jnp.concatenate(
        [jnp.zeros_like(y[..., :1]), jnp.flip(y[..., 1:], -1)], -1)
    k = jnp.arange(n, dtype=jnp.float32)
    W = jnp.exp((1j * math.pi / (2.0 * n)) * k)
    V = 0.5 * (y - 1j * yr).astype(W.dtype) * W
    v = jnp.fft.ifft(V, axis=-1).real.astype(y.dtype)
    m = (n + 1) // 2
    x = jnp.zeros_like(y)
    x = x.at[..., ::2].set(v[..., :m])
    x = x.at[..., 1::2].set(jnp.flip(v[..., m:], -1))
    return 2.0 * n * x


@partial(jax.jit, static_argnames="axis")
def dct(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Unnormalized DCT-II (FFTW REDFT10 analog).

    Jitted unconditionally: the twiddle factors are complex constants, and
    on the axon TPU backend complex arrays cannot cross host↔device — under
    jit they are baked into the compiled program instead of transferred."""
    return jnp.moveaxis(_dct2_last(jnp.moveaxis(A, axis, -1)), -1, axis)


@partial(jax.jit, static_argnames="axis")
def idct(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Unnormalized DCT-III = FFTW REDFT01 (inverse of REDFT10 up to 2N)."""
    return jnp.moveaxis(_dct3_last(jnp.moveaxis(A, axis, -1)), -1, axis)


def dht(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Discrete Hartley transform (FFTW_DHT analog): cas-kernel, self-inverse
    up to N."""
    F = jnp.fft.fft(A, axis=axis)
    return jnp.real(F) - jnp.imag(F)


# Transform length at which the WHT switches from the VPU butterfly to
# the kron-factored matmul formulation (H_N = H_a ⊗ H_b, a·b = N): two
# dense contractions against small ±1 Hadamard factors that run on the
# MXU. N(√N+√N) MXU FLOPs beat N·log2(N) VPU passes (each a strided
# reshape across the whole array) well before N = 512 on TPU; the two
# paths are exact-arithmetic-identical (±1 entries, f32 adds).
_MATMUL_MIN_N = 512


@functools.lru_cache(maxsize=None)
def _hadamard_np(n: int):
    """Dense Sylvester Hadamard H_n (±1, natural ordering), n = 2^k."""
    H = np.ones((1, 1), np.float32)
    while H.shape[0] < n:
        H = np.block([[H, H], [H, -H]])
    return H


@functools.partial(jax.jit, static_argnames=("axis", "precision"))
def _wht_matmul(A: jnp.ndarray, axis: int, precision=None) -> jnp.ndarray:
    """WHT along ``axis`` as H_a · X · H_b over the (a, b)-folded axis.

    Sylvester ordering is kron-associative (H_{2^k} = H_2^{⊗k}), so for
    any split a·b = N, row-major folding x[p·b+q] = X[p, q] gives
    (H_a ⊗ H_b)x = vec(H_a X H_bᵀ); H is symmetric, hence H_a X H_b.
    Jitted so the Hadamard factors are baked into the program as
    constants.

    ``precision`` threads to the contractions; None inherits the
    ambient policy (the library-wide HIGHEST default / the
    SKYLARK_MATMUL_PRECISION knob / any ``default_matmul_precision``
    context). ``Precision.HIGH`` (TPU: 3-pass bf16) is a near-lossless
    speed regime HERE because every Hadamard entry is ±1 — exactly
    representable in bfloat16 — so the only term the 3-pass split drops
    is the X-residual×H product at ~2⁻¹⁶ relative; FastRFT opts in by
    default (see frft.py)."""
    x = jnp.moveaxis(A, axis, -1)
    n = x.shape[-1]
    k = n.bit_length() - 1
    a = 1 << (k - k // 2)
    b = 1 << (k // 2)
    Ha = jnp.asarray(_hadamard_np(a), x.dtype)
    Hb = jnp.asarray(_hadamard_np(b), x.dtype)
    X = x.reshape(x.shape[:-1] + (a, b))
    Y = jnp.einsum("ia,...ab,bj->...ij", Ha, X, Hb, precision=precision)
    return jnp.moveaxis(Y.reshape(x.shape), -1, axis)


def _wht_butterfly(A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """log2(N) in-register butterfly passes (the SpiralWHT shape)."""
    if axis != 0:
        return jnp.moveaxis(_wht_butterfly(jnp.moveaxis(A, axis, 0)), 0, axis)
    n = A.shape[0]
    orig_shape = A.shape
    x = A.reshape(n, -1)
    h = 1
    while h < n:
        x = x.reshape(n // (2 * h), 2, h, -1)
        a, b = x[:, 0], x[:, 1]
        x = jnp.stack([a + b, a - b], axis=1).reshape(n, -1)
        h *= 2
    return x.reshape(orig_shape)


def wht(A: jnp.ndarray, axis: int = 0, precision=None) -> jnp.ndarray:
    """Fast Walsh-Hadamard transform (natural/Hadamard ordering), N = 2^k
    (SpiralWHT analog, ref: sketch/FUT.hpp:225-347). Unnormalized,
    self-inverse up to N. Large lengths take the MXU matmul formulation
    (:func:`_wht_matmul`, ``precision`` threads to its contractions);
    small ones the VPU butterfly (exact adds; precision n/a)."""
    n = A.shape[axis]
    if n & (n - 1):
        raise ValueError(f"WHT requires power-of-2 length, got {n}")
    if n >= _MATMUL_MIN_N:
        return _wht_matmul(A, axis, precision)
    return _wht_butterfly(A, axis)


#: The promoted serve-program name for the panel-free Hadamard lowering
#: (docs/performance, "In-kernel FWHT and compressed matmul"): the SRHT
#: serve/dist/session paths contract through ``fwht`` instead of
#: materializing ``FJLT.operator_panel`` columns. Same function as
#: :func:`wht` — the alias marks the serve-surface contract: its
#: lowering (butterfly or kron matmul) must stay exact-arithmetic-
#: identical to the dense Sylvester reference ``_hadamard_np``.
fwht = wht


def fwht_sketch(A: jnp.ndarray, diag: jnp.ndarray, idx: jnp.ndarray,
                fut_scale: float, samp_scale: float, axis: int = 0,
                precision=None) -> jnp.ndarray:
    """Fused sign→FWHT→sample composition: the panel-free SRHT program.

    Computes ``samp_scale · gather(fwht(fut_scale · diag ⊙ A, axis),
    idx)`` with the multiplications and the gather composed in exactly
    the order of ``FJLT._apply_columnwise`` / ``_apply_rowwise`` — the
    fused path is *bit-equal* to the separate diag→FWHT→gather
    composition (same op sequence, just one traced program), and
    bit-equal to the ``operator_panel`` matmul reference whenever every
    intermediate is exactly representable (integer-valued operands with
    ``n`` and ``s`` even powers of two; the dyadic battery in
    tests/test_fwht.py pins this).

    ``diag`` is the length-``n`` Rademacher sign diagonal fused into
    the first butterfly stage; ``idx`` the ``s`` sampled coordinates
    gathered out of the last. ``axis`` is the contracted (transform)
    axis: 0 for columnwise operands ``(n, m)``, 1 for rowwise
    ``(m, n)``."""
    if axis == 0:
        mixed = wht(fut_scale * diag[:, None] * A, axis=0,
                    precision=precision)
        return samp_scale * mixed[idx, :]
    mixed = wht(fut_scale * diag[None, :] * A, axis=1,
                precision=precision)
    return samp_scale * mixed[:, idx]


class FUT:
    """A fast unitary transform with the reference's scale convention."""

    def __init__(self, n: int):
        self.n = int(n)

    def scale(self) -> float:
        raise NotImplementedError

    def apply(self, A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
        raise NotImplementedError

    def apply_inverse(self, A: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
        raise NotImplementedError


class DCT(FUT):
    """ScaleVal=2 (ref: sketch/FUT.hpp:138-140)."""

    name = "dct"

    def scale(self) -> float:
        return 1.0 / math.sqrt(2.0 * self.n)

    def apply(self, A, axis=0):
        return dct(A, axis)

    def apply_inverse(self, A, axis=0):
        return idct(A, axis)


class DHT(FUT):
    """ScaleVal=1 (ref: sketch/FUT.hpp:142-143)."""

    name = "dht"

    def scale(self) -> float:
        return 1.0 / math.sqrt(self.n)

    def apply(self, A, axis=0):
        return dht(A, axis)

    apply_inverse = apply


class WHT(FUT):
    """Walsh-Hadamard; requires power-of-2 n (ref: sketch/FUT.hpp:225-347)."""

    name = "wht"

    def scale(self) -> float:
        return 1.0 / math.sqrt(self.n)

    def apply(self, A, axis=0, precision=None):
        return wht(A, axis, precision)

    apply_inverse = apply


_FUTS = {"dct": DCT, "dht": DHT, "wht": WHT}


def make_fut(name: str, n: int) -> FUT:
    return _FUTS[name](n)
