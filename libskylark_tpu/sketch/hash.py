"""Hash-based sparse-embedding sketches: CWT (CountSketch), MMT, WZT.

TPU-native analog of the reference's hash_transform family
(ref: sketch/hash_transform_data.hpp:21-104, sketch/CWT_data.hpp:23-70,
sketch/MMT_data.hpp:22-60, sketch/WZT_data.hpp:27-124).

The transform is defined by two virtual streams over the allocation key:
``row_idx`` — a uniform bucket in [0, S) per input coordinate — and
``row_value`` — a per-coordinate scaling (Rademacher for CWT, Cauchy for MMT,
signed reciprocal-exponential for WZT). Where the reference applies these with
O(nnz) CSC scatter loops (ref: sketch/hash_transform_Elemental.hpp:83-124),
the TPU-native formulation is a ``segment_sum`` — a dataflow scatter-add XLA
maps onto the VPU, and which under a sharded input becomes a local
segment-sum + psum exactly like the reference's local-accumulate + all_reduce
pattern (ref: sketch/hash_transform_Elemental.hpp:427-607).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.sketch.transform import SketchTransform, register


def cwt_serve_apply(key_data, A, *, s_dim: int, rowwise: bool) -> jnp.ndarray:
    """Pure, vmap-batchable CWT apply for the microbatch serving layer
    (:mod:`libskylark_tpu.engine.serve`): one request's CountSketch as a
    function of the transform's raw key data ((2,) uint32 from
    ``jax.random.key_data``). The bucket/value streams are positional —
    identical to :meth:`HashTransform.bucket_indices` /
    :meth:`CWT.values` over the first N coordinates — so zero-padding
    the operand past the transform's true N leaves the result bit-equal:
    padded coordinates scatter-add exact zeros."""
    import jax.random as jr

    key = jr.wrap_key_data(jnp.asarray(key_data))
    n = A.shape[1] if rowwise else A.shape[0]
    h = randgen.stream_slice(
        jax.random.fold_in(key, 0), randgen.UniformInt(0, s_dim - 1),
        0, n, dtype=jnp.int32)
    v = randgen.stream_slice(
        jax.random.fold_in(key, 1), randgen.Rademacher(), 0, n,
        dtype=A.dtype)
    if rowwise:
        return jax.ops.segment_sum(v[:, None] * A.T, h,
                                   num_segments=s_dim).T
    return jax.ops.segment_sum(v[:, None] * A, h, num_segments=s_dim)


class HashTransform(SketchTransform):
    """Base: SA[h[j], :] += v[j] * A[j, :] (columnwise)."""

    sketch_type = "HashTransform"

    def _value_stream(self, dtype) -> jnp.ndarray:
        """Per-coordinate scaling values v[0:N]; overridden per transform."""
        raise NotImplementedError

    def bucket_indices(self) -> jnp.ndarray:
        """h[0:N] — bucket of each input coordinate (sub-stream 0)."""
        return randgen.stream_slice(
            self.subkey(0), randgen.UniformInt(0, self._S - 1), 0, self._N,
            dtype=jnp.int32,
        )

    def values(self, dtype=jnp.float32) -> jnp.ndarray:
        return self._value_stream(dtype)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        out = self._try_kernel(A, rowwise=False)
        if out is not None:
            return out
        h = self.bucket_indices()
        v = self.values(A.dtype)
        return jax.ops.segment_sum(v[:, None] * A, h, num_segments=self._S)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        out = self._try_kernel(A, rowwise=True)
        if out is not None:
            return out
        h = self.bucket_indices()
        v = self.values(A.dtype)
        return jax.ops.segment_sum(v[:, None] * A.T, h, num_segments=self._S).T

    def _try_kernel(self, A, *, rowwise: bool):
        """Scatter-free Pallas dispatch (sketch/pallas_hash.py) — CWT on
        a qualifying TPU operand, routed only by an explicit override
        (``SKYLARK_HASH_KERNEL``) or a certified plan-cache entry;
        None declines and the ``segment_sum`` scatter below serves
        (see the kernel module's dispatch doc)."""
        from libskylark_tpu.sketch import pallas_hash

        return pallas_hash.try_apply(self, A, rowwise=rowwise)

    # -- sparse input: O(nnz) scatter-add over COO triplets (the dataflow
    # form of ref: sketch/hash_transform_local_sparse.hpp:12-152) --

    def _apply_columnwise_sparse(self, A) -> jnp.ndarray:
        r, c, v = A.coo()
        h = self.bucket_indices()
        vs = self.values(v.dtype)
        out = jnp.zeros((self._S, A.width), v.dtype)
        return out.at[h[r], c].add(vs[r] * v)

    def _apply_rowwise_sparse(self, A) -> jnp.ndarray:
        r, c, v = A.coo()
        h = self.bucket_indices()
        vs = self.values(v.dtype)
        out = jnp.zeros((A.height, self._S), v.dtype)
        return out.at[r, h[c]].add(vs[c] * v)

    # -- distributed sparse input (P4/P5): local scatter + psum (ref:
    # sketch/hash_transform_CombBLAS.hpp:16-632) --

    def _apply_columnwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.hash_columnwise(self, A)

    def _apply_rowwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.hash_rowwise(self, A)

    def apply_sparse(self, A, dimension=None):
        """Sparse→sparse apply: returns a :class:`SparseMatrix` with
        duplicate-summed CSC structure (ref:
        sketch/hash_transform_local_sparse.hpp — the sparse-output path).
        Runs on host; the bucket/value streams are identical to the device
        path, so results match ``apply`` elementwise. A
        :class:`DistSparseMatrix` input returns a distributed sparse
        result (the SpParMat→SpParMat analog, all device-side)."""
        import numpy as np

        from libskylark_tpu.base.dist_sparse import DistSparseMatrix
        from libskylark_tpu.base.sparse import SparseMatrix
        from libskylark_tpu.sketch.transform import COLUMNWISE, Dimension

        if isinstance(A, DistSparseMatrix):
            from libskylark_tpu.sketch import dist_sparse_apply as dsa

            cw = (dimension or COLUMNWISE) == Dimension.COLUMNWISE
            return dsa.hash_apply_sparse(self, A, columnwise=cw)

        dimension = dimension or COLUMNWISE
        if dimension == Dimension.COLUMNWISE:
            if A.height != self._N:
                raise errors.SketchError(
                    f"columnwise apply expects {self._N} rows, got {A.shape}"
                )
        elif A.width != self._N:
            raise errors.SketchError(
                f"rowwise apply expects {self._N} cols, got {A.shape}"
            )
        h = np.asarray(self.bucket_indices())
        sp = A.to_scipy().tocoo()
        v = np.asarray(self.values(A.device_dtype))
        if dimension == Dimension.COLUMNWISE:
            rows = h[sp.row]
            vals = v[sp.row] * sp.data
            return SparseMatrix.from_coo(
                rows, sp.col, vals, (self._S, A.width)
            )
        cols = h[sp.col]
        vals = v[sp.col] * sp.data
        return SparseMatrix.from_coo(sp.row, cols, vals, (A.height, self._S))


@register
class CWT(HashTransform):
    """Clarkson-Woodruff CountSketch: ±1 values (OSNAP s=1)
    (ref: sketch/CWT_data.hpp:23-70)."""

    sketch_type = "CWT"

    def _value_stream(self, dtype):
        return randgen.stream_slice(
            self.subkey(1), randgen.Rademacher(), 0, self._N, dtype=dtype
        )


@register
class MMT(HashTransform):
    """Meng-Mahoney transform: CountSketch with Cauchy values for l1 embedding
    (ref: sketch/MMT_data.hpp:22-60)."""

    sketch_type = "MMT"

    def _value_stream(self, dtype):
        return randgen.stream_slice(
            self.subkey(1), randgen.Cauchy(), 0, self._N, dtype=dtype
        )


@register
class WZT(HashTransform):
    """Woodruff-Zhang transform for lp (p in [1,2]): values are
    ±(1/Exp(1))^(1/p) (ref: sketch/WZT_data.hpp:106-124 — base exponential
    stream reshaped to the target distribution, signed by a Rademacher
    stream)."""

    sketch_type = "WZT"

    def __init__(self, N, S, context, p: float = 2.0):
        if p < 1 or p > 2:
            from libskylark_tpu.base import errors

            raise errors.InvalidParametersError(
                "WZT parameter p has to be in [1, 2]"
            )
        self._p = float(p)
        super().__init__(N, S, context)

    def _value_stream(self, dtype):
        e = randgen.stream_slice(
            self.subkey(1), randgen.Exponential(), 0, self._N, dtype=dtype
        )
        pm = randgen.stream_slice(
            self.subkey(2), randgen.Rademacher(), 0, self._N, dtype=dtype
        )
        return pm * jnp.power(1.0 / e, 1.0 / self._p)

    def _extra_params(self) -> dict[str, Any]:
        return {"P": self._p}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, p=float(d.get("P", 2.0)))
