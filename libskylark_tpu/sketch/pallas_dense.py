"""Pallas TPU kernel: fused on-the-fly sketch generation + matmul.

The hot primitive of the framework (ref: SURVEY.md §3.1 — the reference's
blocked panel algorithm in sketch/dense_transform_Elemental_mc_mr.hpp with
``realize_matrix_view`` generating S panels on demand). The XLA path pays
for panel generation (Threefry + inverse-CDF on the VPU) serialized against
the matmul; this kernel generates each (S_dim × BLOCK_COLS) panel of S in
VMEM — exact same bits as :func:`randgen.dense_block`, via the shared
integer-op Threefry in base/threefry.py — while the MXU contracts the
previous panels, so generation rides under the matmul.

Rowwise (out = A·Sᵀ, the regime of BASELINE config 1) and columnwise
(out = S·A) applies, both with optional pipelined generation; inputs the
kernel can't take fall back to the XLA path in sketch/dense.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import randgen, threefry as tf

try:  # import guarded so non-TPU environments can import the module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

from libskylark_tpu.sketch.dense import BLOCK_COLS  # the stream format's
# panel width — single source of truth (dense.py imports this module only
# lazily, so no cycle)

_HALF = BLOCK_COLS // 2


def _DEFAULT_M_TILE() -> int:
    """Tuning knob lives in sketch/params.py (runtime get/set, env-seeded
    via SKYLARK_PALLAS_MTILE)."""
    from libskylark_tpu.sketch import params as sketch_params

    return sketch_params.get_pallas_m_tile()


def available() -> bool:
    """True when the default backend can run the Mosaic kernel."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _gen_block(dist_kind, s_dim, keys_ref, k):
    """Generate operator column block k (s_dim, BLOCK_COLS) in VMEM —
    bit-identical to randgen.dense_block's threefry-pair layout."""
    k0 = keys_ref[k, 0]
    k1 = keys_ref[k, 1]
    c = (
        jax.lax.broadcasted_iota(jnp.uint32, (s_dim, _HALF), 0) * _HALF
        + jax.lax.broadcasted_iota(jnp.uint32, (s_dim, _HALF), 1)
    )
    b0, b1 = tf.threefry2x32(k0, k1, c, c + s_dim * _HALF)
    if dist_kind == "normal":
        s0, s1 = tf.bits_to_normal(b0), tf.bits_to_normal(b1)
    elif dist_kind == "cauchy":
        s0, s1 = tf.bits_to_cauchy(b0), tf.bits_to_cauchy(b1)
    elif dist_kind == "rademacher":
        s0, s1 = tf.bits_to_rademacher(b0), tf.bits_to_rademacher(b1)
    else:
        raise NotImplementedError(dist_kind)
    return jnp.concatenate([s0, s1], axis=1)  # (s_dim, BLOCK_COLS)


def _accumulate(out_ref, acc, k):
    @pl.when(k == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(k != 0)
    def _acc():
        out_ref[:] += acc


def _dot(lhs, rhs, dims, precision, gen_side=1):
    """MXU contraction at the requested precision regime.

    ``gen_side`` names the operand (0=lhs, 1=rhs) that is the GENERATED
    operator block — only the "bf16gen2" regime uses it: the operator
    is rounded to bf16 (by that regime's definition the rounded values
    ARE the operator — exact in every later bf16 pass), so only the
    data side needs the error-compensated hi/lo split: 2 MXU passes
    for f32-grade accuracy w.r.t. the rounded operator, vs bf16x3's 3
    passes for the f32 operator.

    ``"bf16x3"`` (the default, set in sketch/params.py): 3-pass
    error-compensated bf16 split (spelled out below; Mosaic has no
    ``Precision.HIGH`` lowering) — f32-grade rounding at roughly twice
    the MXU rate of HIGHEST, oracle-certified on chip
    (benchmarks/tpu_validation_r03.txt). The explicit hi/lo split
    performs real bf16 rounding in interpret mode too, so both the
    interpreter and the on-chip test exercise the same arithmetic.
    ``"f32"``: full-f32 passes (``Precision.HIGHEST``) — the conservative
    regime; keeps the fused apply inside the framework's 1e-4
    determinism oracle vs the XLA/CPU path on deep contractions.
    ``"bf16"``: single-pass bf16 inputs + f32 accumulation — the fastest
    MXU regime; contraction rounds at ~2⁻⁸ relative, which EXCEEDS the
    1e-4 oracle for large N (quantified in tests/test_pallas_dense.py), so
    callers opt in explicitly for throughput-only work."""

    def bf16_dot(a, b):
        # precision pinned explicitly: the package-level default matmul
        # precision is "highest", which on bf16 operands asks Mosaic for
        # an fp32 contraction it can't lower ("Bad lhs type")
        return jax.lax.dot_general(
            a.astype(jnp.bfloat16),
            b.astype(jnp.bfloat16),
            dims,
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        )

    if precision == "bf16":
        return bf16_dot(lhs, rhs)
    if precision == "bf16gen2":
        if gen_side == 0:
            rhs_hi = rhs.astype(jnp.bfloat16).astype(jnp.float32)
            return bf16_dot(lhs, rhs_hi) + bf16_dot(lhs, rhs - rhs_hi)
        lhs_hi = lhs.astype(jnp.bfloat16).astype(jnp.float32)
        return bf16_dot(lhs_hi, rhs) + bf16_dot(lhs - lhs_hi, rhs)
    if precision == "bf16x3":
        # Error-compensated 3-pass split. Mosaic has no lowering for
        # Precision.HIGH (verified on v5e: "Unsupported dot precision:
        # HIGH"), so the split is spelled out: x = hi + lo with hi the
        # bf16 rounding of x; hi·hi + hi·lo + lo·hi recovers all but the
        # lo·lo term (~2⁻¹⁶ relative) — f32-grade for the 1e-4 oracle.
        lhs_hi = lhs.astype(jnp.bfloat16).astype(jnp.float32)
        rhs_hi = rhs.astype(jnp.bfloat16).astype(jnp.float32)
        lhs_lo = lhs - lhs_hi
        rhs_lo = rhs - rhs_hi
        return bf16_dot(lhs_hi, rhs_hi) + (
            bf16_dot(lhs_hi, rhs_lo) + bf16_dot(lhs_lo, rhs_hi)
        )
    return jax.lax.dot_general(
        lhs,
        rhs,
        dims,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


# Per-core VMEM budget the kernel plans against. ~16 MiB/core is the
# common figure across current generations (v4/v5e/v5p; pallas_guide.md
# memory-hierarchy table) — there is no runtime query API, so the default
# is conservative and env-overridable for parts that have more.
_VMEM_BUDGET_BYTES = _env.PALLAS_VMEM_BUDGET.get()

# VMEM budget for caching the generated operator across m-tiles. When the
# full virtual S fits, each block is generated ONCE (first m-tile sweep)
# and every later tile contracts against the cached copy — generation cost
# amortizes over m instead of being paid per tile. Larger operators fall
# back to per-tile regeneration. Must leave room for the pipeline's
# double-buffered A/out tiles inside _VMEM_BUDGET_BYTES (advisor r2
# medium finding: the old 48 MiB default exceeded whole-VMEM on v5e and
# could fail Mosaic compilation outright on the shard_map path).
_SCRATCH_CAP_BYTES = _env.PALLAS_SCRATCH_CAP.get()


def _vmem_estimate(m_tile: int, s_dim: int, scratch_bytes: int) -> int:
    """Rough per-core VMEM plan for one grid step: double-buffered A tile
    (m_tile × BLOCK_COLS) and out tile (m_tile × s_dim), the generated
    operator block + generation temporaries (~4 × s_dim × BLOCK_COLS),
    plus the optional operator-cache scratch."""
    return 4 * (
        2 * m_tile * BLOCK_COLS
        + 2 * m_tile * s_dim
        + 4 * s_dim * BLOCK_COLS
    ) + scratch_bytes


def _resolve_block(dist_kind, s_dim, keys_ref, k, s_scr):
    """Operator block k: from the VMEM cache when present (filled during
    the first m-tile sweep), else regenerated in place."""
    if s_scr is None:
        return _gen_block(dist_kind, s_dim, keys_ref, k)

    @pl.when(pl.program_id(0) == 0)
    def _gen():
        s_scr[:, pl.ds(k * BLOCK_COLS, BLOCK_COLS)] = _gen_block(
            dist_kind, s_dim, keys_ref, k
        )

    return s_scr[:, pl.ds(k * BLOCK_COLS, BLOCK_COLS)]


def _apply_epilogue(out_ref, epilogue, k, n_blocks):
    """Fused in-VMEM finish after the LAST operator block accumulates
    (shared by the plain and pipelined kernels). ``epilogue("cos",
    inscale, outscale, sc_ref, sh_ref)`` → outscale·cos(acc·inscale·sc
    + sh) (ref: RFT_Elemental.hpp:83-156)."""
    kind, inscale, outscale, sc_ref, sh_ref = epilogue
    assert kind == "cos"

    @pl.when(k == n_blocks - 1)
    def _epilogue():
        z = out_ref[:] * inscale * sc_ref[:] + sh_ref[:]
        out_ref[:] = outscale * jnp.cos(z)


def _kernel_pipe(dist_kind, s_dim, n_blocks, precision, keys_ref, a_ref,
                 out_ref, s_buf, *, rowwise=True, epilogue=None):
    """Kernel with software-pipelined generation: block k+1 is generated
    into the other half of a double buffer BETWEEN the MXU contraction of
    block k being issued and its result being consumed — the generation
    is dataflow-independent of the in-flight matmul, so the scheduler can
    run the VPU (Threefry + inverse-CDF) under the MXU. At the headline
    config generation is the dominant non-MXU cost (one full operator
    regeneration per m-tile sweep), so the overlap bounds the step at
    max(gen, matmul) instead of their sum. One body serves both
    orientations (``rowwise``: out += A·S_blkᵀ, else out += S_blk·A).
    Opt-in via SKYLARK_PALLAS_PIPELINE=1 pending an on-chip A/B
    (scheduling is the compiler's call; interpret-mode equivalence is
    exact either way)."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _first():
        s_buf[0] = _gen_block(dist_kind, s_dim, keys_ref, 0)

    S_blk = s_buf[k % 2]
    if rowwise:
        acc = _dot(a_ref[:], S_blk, (((1,), (1,)), ((), ())), precision,
                   gen_side=1)
    else:
        acc = _dot(S_blk, a_ref[:], (((1,), (0,)), ((), ())), precision,
                   gen_side=0)

    @pl.when(k + 1 < n_blocks)
    def _next():
        s_buf[(k + 1) % 2] = _gen_block(dist_kind, s_dim, keys_ref, k + 1)

    _accumulate(out_ref, acc, k)
    if epilogue is not None:
        _apply_epilogue(out_ref, epilogue, k, n_blocks)


def _pipeline_env() -> bool | None:
    """Tri-state SKYLARK_PALLAS_PIPELINE: None when unset (a cached
    plan may decide), True for "1", False for any other set value — an
    EXPLICITLY set env must beat a cached plan in either direction
    (=0 is the escape hatch when a cached pipelined plan misbehaves).
    Read at TRACE time: _fused_call's jit cache is keyed by shapes and
    static args only, so toggle the env before the first call of a
    given shape (the bench A/Bs in separate processes)."""
    # deliberate trace-time env read (see docstring): the pipeline
    # regime is resolved once per (shape, statics) trace and the env
    # contract is toggle-before-first-call — not a flapping key
    v = _env.PALLAS_PIPELINE.raw()  # skylark-lint: disable=jit-purity
    if v is None:
        return None
    return v == "1"


def _kernel(dist_kind, s_dim, m_tile, precision, keys_ref, a_ref, out_ref,
            s_scr=None, *, epilogue=None, n_blocks=None):
    """Rowwise: out_tile += A_tile @ S_blkᵀ (S entries are bit-exact; only
    the contraction rounds, per the ``precision`` regime).

    Optional fused epilogue, applied in VMEM after the LAST operator
    block accumulates — the output never makes the extra HBM round-trip a
    separate elementwise op would cost. ``epilogue("cos", inscale,
    outscale)`` finishes the tile as ``outscale·cos(acc·inscale·sc + sh)``
    (the random-Fourier featurization; ref: RFT_Elemental.hpp:83-156, the
    reference's fused elementwise loops) with sc/sh (1, s_dim) VMEM refs
    threaded by the caller."""
    k = pl.program_id(1)
    S_blk = _resolve_block(dist_kind, s_dim, keys_ref, k, s_scr)
    acc = _dot(a_ref[:], S_blk, (((1,), (1,)), ((), ())), precision,
               gen_side=1)
    _accumulate(out_ref, acc, k)
    if epilogue is not None:
        _apply_epilogue(out_ref, epilogue, k, n_blocks)


def _kernel_cos(dist_kind, s_dim, m_tile, n_blocks, precision, inscale,
                outscale, keys_ref, a_ref, sc_ref, sh_ref, out_ref,
                s_scr=None):
    """Rowwise + cos featurization (see _kernel's epilogue doc)."""
    _kernel(dist_kind, s_dim, m_tile, precision, keys_ref, a_ref, out_ref,
            s_scr, epilogue=("cos", inscale, outscale, sc_ref, sh_ref),
            n_blocks=n_blocks)


def _kernel_cw(dist_kind, s_dim, m_tile, precision, keys_ref, a_ref, out_ref,
               s_scr=None):
    """Columnwise: out_tile += S_blk @ A_blk (same precision regime)."""
    k = pl.program_id(1)
    S_blk = _resolve_block(dist_kind, s_dim, keys_ref, k, s_scr)
    acc = _dot(S_blk, a_ref[:], (((1,), (0,)), ((), ())), precision,
               gen_side=0)
    _accumulate(out_ref, acc, k)


def _kernel_pipe_cw(dist_kind, s_dim, n_blocks, precision, keys_ref,
                    a_ref, out_ref, s_buf):
    """Columnwise orientation of :func:`_kernel_pipe`."""
    _kernel_pipe(dist_kind, s_dim, n_blocks, precision, keys_ref, a_ref,
                 out_ref, s_buf, rowwise=False)


def _scratch(s_dim: int, n: int, m: int, m_tile: int):
    """Scratch shapes for the operator cache, or [] when it doesn't pay
    (single m-tile → no reuse) or doesn't fit the cap / the whole-kernel
    VMEM budget."""
    n_blocks = n // BLOCK_COLS
    if m // m_tile <= 1:
        return []
    scratch_bytes = s_dim * n_blocks * BLOCK_COLS * 4
    if scratch_bytes > _SCRATCH_CAP_BYTES:
        return []
    if _vmem_estimate(m_tile, s_dim, scratch_bytes) > _VMEM_BUDGET_BYTES:
        return []
    return [pltpu.VMEM((s_dim, n_blocks * BLOCK_COLS), jnp.float32)]


def _pipe_fits(scratch, s_dim: int, m_tile: int,
               pipeline: bool | None = None) -> bool:
    """Pipelined-generation selection predicate — the SINGLE source of
    truth shared by the kernel call sites (via :func:`_select_pipe`) and
    :func:`effective_plan`, so the reported plan can't drift from the
    executed one: engage when the operator-cache scratch doesn't apply
    (the big-operator regime), the pipeline is requested — an
    explicitly set SKYLARK_PALLAS_PIPELINE wins in either direction,
    else a cached plan's ``pipeline`` flag decides — and the double
    buffer fits the same VMEM budget _qualify planned against."""
    env = _pipeline_env()
    enabled = env if env is not None else bool(pipeline)
    pipe_bytes = 2 * s_dim * BLOCK_COLS * 4
    return (not scratch and enabled
            and _vmem_estimate(m_tile, s_dim, pipe_bytes)
            <= _VMEM_BUDGET_BYTES)


def _select_pipe(kern, pipe_kern, scratch, s_dim: int, m_tile: int,
                 pipeline: bool | None = None):
    """Swap in the pipelined kernel + generation double buffer when
    :func:`_pipe_fits` says so — over budget, stay on the plain kernel
    (no fallback seam exists on the shard_map path)."""
    if pipe_kern is not None and _pipe_fits(scratch, s_dim, m_tile,
                                            pipeline):
        return pipe_kern, [pltpu.VMEM((2, s_dim, BLOCK_COLS), jnp.float32)]
    return kern, scratch


def _grid_params(scratch):
    """dimension_semantics for pallas_call: the operator cache needs
    strictly sequential grid order (the i==0 sweep fills it) — no megacore
    splitting over the m-tile dimension."""
    return _CompilerParams(
        dimension_semantics=(
            ("arbitrary", "arbitrary") if scratch
            else ("parallel", "arbitrary")
        ),
    )


def _rowwise_pallas_call(A, keys, extra_operands, kern, *, s_dim, m_tile,
                         interpret, pipe_kern=None, pipeline=None):
    """Shared rowwise pallas_call plumbing: grid, key-table SMEM spec,
    A-tile spec, accumulator out spec, operator scratch, compiler params.
    ``extra_operands`` are (1, s_dim) VMEM vectors threaded to the kernel
    between a_ref and out_ref (epilogue operands).

    When the operator-cache scratch doesn't apply (the big-operator
    regime) and SKYLARK_PALLAS_PIPELINE=1, ``pipe_kern`` runs instead
    with a 2-slot generation double buffer; the grid stays parallel over
    m-tiles (each core's k-sweep is self-contained — the k == 0 prologue
    refills the buffer per sweep)."""
    m, n = A.shape
    n_blocks = n // BLOCK_COLS
    grid = (m // m_tile, n_blocks)
    scratch = _scratch(s_dim, n, m, m_tile)
    grid_params = _grid_params(scratch)
    kern, scratch = _select_pipe(kern, pipe_kern, scratch, s_dim, m_tile,
                                 pipeline)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            # whole key table in SMEM every step (tiny); indexed by k
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (m_tile, BLOCK_COLS), lambda i, k: (i, k),
                memory_space=pltpu.VMEM,
            ),
        ] + [
            pl.BlockSpec((1, s_dim), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM)
            for _ in extra_operands
        ],
        out_specs=pl.BlockSpec(
            (m_tile, s_dim), lambda i, k: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, s_dim), jnp.float32),
        scratch_shapes=scratch,
        compiler_params=grid_params,
        interpret=interpret,
    )(keys, A, *extra_operands)


def _kernel_pipe_cos(dist_kind, s_dim, n_blocks, precision, inscale,
                     outscale, keys_ref, a_ref, sc_ref, sh_ref, out_ref,
                     s_buf):
    """Pipelined rowwise + cos featurization."""
    _kernel_pipe(dist_kind, s_dim, n_blocks, precision, keys_ref, a_ref,
                 out_ref, s_buf,
                 epilogue=("cos", inscale, outscale, sc_ref, sh_ref))


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "dist_kind", "m_tile", "precision",
                     "interpret", "pipeline"),
)
def _fused_call(A, keys, *, s_dim, dist_kind, m_tile, precision="f32",
                interpret=False, pipeline=None):
    kern = functools.partial(_kernel, dist_kind, s_dim, m_tile, precision)
    pipe = functools.partial(_kernel_pipe, dist_kind, s_dim,
                             A.shape[1] // BLOCK_COLS, precision)
    return _rowwise_pallas_call(A, keys, (), kern, s_dim=s_dim,
                                m_tile=m_tile, interpret=interpret,
                                pipe_kern=pipe, pipeline=pipeline)


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "dist_kind", "m_tile", "precision",
                     "inscale", "outscale", "interpret", "pipeline"),
)
def _fused_call_cos(A, keys, sc, sh, *, s_dim, dist_kind, m_tile,
                    precision="f32", inscale=1.0, outscale=1.0,
                    interpret=False, pipeline=None):
    n_blocks = A.shape[1] // BLOCK_COLS
    kern = functools.partial(_kernel_cos, dist_kind, s_dim, m_tile,
                             n_blocks, precision, inscale, outscale)
    pipe = functools.partial(_kernel_pipe_cos, dist_kind, s_dim, n_blocks,
                             precision, inscale, outscale)
    return _rowwise_pallas_call(A, keys, (sc, sh), kern, s_dim=s_dim,
                                m_tile=m_tile, interpret=interpret,
                                pipe_kern=pipe, pipeline=pipeline)


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "dist_kind", "m_tile", "precision",
                     "interpret", "pipeline"),
)
def _fused_call_cw(A, keys, *, s_dim, dist_kind, m_tile, precision="f32",
                   interpret=False, pipeline=None):
    n, m = A.shape
    n_blocks = n // BLOCK_COLS
    grid = (m // m_tile, n_blocks)
    scratch = _scratch(s_dim, n, m, m_tile)
    grid_params = _grid_params(scratch)
    kern = functools.partial(_kernel_cw, dist_kind, s_dim, m_tile, precision)
    pipe = functools.partial(_kernel_pipe_cw, dist_kind, s_dim, n_blocks,
                             precision)
    kern, scratch = _select_pipe(kern, pipe, scratch, s_dim, m_tile,
                                 pipeline)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (BLOCK_COLS, m_tile), lambda j, k: (k, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (s_dim, m_tile), lambda j, k: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((s_dim, m), jnp.float32),
        scratch_shapes=scratch,
        compiler_params=grid_params,
        interpret=interpret,
    )(keys, A)


_DIST_KINDS = {
    randgen.Normal: "normal",
    randgen.Cauchy: "cauchy",
    randgen.Rademacher: "rademacher",
}


def _consult_cache(dist, shape, dtype, s_dim: int, seq_axis: int,
                   rft: bool = False):
    """Cached autotuner plan for this apply, or None. Gated on
    params.use_plan_cache; never raises (a broken cache must not take
    down a sketch apply)."""
    from libskylark_tpu.sketch import params as sketch_params

    if not sketch_params.get_use_plan_cache():
        return None
    kind = _DIST_KINDS.get(type(dist))
    if kind is None or not supported(dist, dtype):
        return None
    try:
        from libskylark_tpu import tune

        return tune.plan_for(tune.dense_workload(
            kind, shape, dtype, s_dim, seq_axis, rft=rft))
    except Exception:
        return None


# marker: the cached plan says the XLA path serves this workload better
# than the kernel — dispatch declines and the caller falls back
_TAKE_XLA = object()


def _resolve_knobs(dist, shape, dtype, s_dim: int, seq_axis: int,
                   m_tile, precision, rft: bool = False):
    """Apply the documented dispatch precedence (sketch/params.py
    ``use_plan_cache`` doc) to the two tuning knobs: explicit call-site
    argument > explicit user override (env/setter) > cached plan >
    heuristic default. Returns ``(m_tile, precision, pipeline, source)``
    with ``pipeline`` None (env decides) unless a cached plan pins it,
    or the :data:`_TAKE_XLA` marker when a consulted plan certifies the
    XLA path for this workload (only when the user overrode NO knob —
    m-tile, precision, or the pipeline env; an explicit override means
    a sweep/pin and must reach the kernel)."""
    from libskylark_tpu.sketch import params as sketch_params

    mt_open = m_tile is None and not sketch_params.pallas_m_tile_overridden()
    prec_open = (precision is None
                 and not sketch_params.pallas_precision_overridden())
    plan = (_consult_cache(dist, shape, dtype, s_dim, seq_axis, rft=rft)
            if mt_open or prec_open else None)
    if plan is not None and plan.backend != "pallas":
        if mt_open and prec_open and _pipeline_env() is None:
            return _TAKE_XLA
        plan = None
    source = "heuristic"
    pipeline = None
    if plan is not None:
        source = "cache"
        if mt_open and plan.m_tile:
            m_tile = plan.m_tile
        # oracle-grade regimes ONLY: the cache file is a committed,
        # hand-editable artifact, and the default dispatch must never
        # auto-select a regime outside the 1e-4 determinism oracle
        # (bf16/bf16gen2 stay call-site/setter opt-in) — nor pass an
        # unknown string through _dot's silent HIGHEST fall-through
        # under a mislabeling plan_id
        from libskylark_tpu.tune.plans import ORACLE_PRECISIONS

        if prec_open and plan.precision in ORACLE_PRECISIONS:
            precision = plan.precision
        pipeline = plan.pipeline or None
    if m_tile is None:
        m_tile = _DEFAULT_M_TILE()
    if precision is None:
        precision = _default_precision()
    return m_tile, precision, pipeline, source


def supported(dist, dtype) -> bool:
    kind = _DIST_KINDS.get(type(dist))
    if kind is None:
        return False
    # only the standard forms share the plain bit transforms
    if kind == "normal" and (dist.mean != 0.0 or dist.std != 1.0):
        return False
    if kind == "cauchy" and (dist.loc != 0.0 or dist.scale != 1.0):
        return False
    return jnp.dtype(dtype) == jnp.float32


def _pad_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _qualify(dist, A, seq_axis: int, m_tile: int, interpret: bool,
             s_dim: int = 0):
    """Common qualification: backend + distribution. Returns the m-tile
    size for the (possibly padded) m extent, or None for fallback.

    The returned tile is pre-shrunk so the kernel's VMEM plan
    (:func:`_vmem_estimate`, scratch excluded — _scratch checks itself)
    fits ``_VMEM_BUDGET_BYTES``: a Mosaic VMEM-exhaustion failure inside a
    jitted shard_map pipeline has no catchable fallback seam, so the
    pre-flight must make compilation succeed, not try/except it (advisor
    r2 medium finding).

    Ragged shapes are handled by the callers via zero-padding (exact for
    these contractions: padded A columns multiply virtual S columns by
    zero; padded A rows produce output rows that are sliced away) — the
    parity requirement the reference exercises at np∈{5,7}
    (ref: tests/unit/CMakeLists.txt:31-33)."""
    if not _HAVE_PALLAS:
        return None
    if not interpret and not available():
        return None
    if not supported(dist, A.dtype):
        return None
    m = _pad_to(max(A.shape[1 - seq_axis], 8), 8)
    # power-of-two tile ≥ 8: the halving search below then always
    # terminates at a divisor of the 8-aligned m (a non-pow2 request,
    # e.g. SKYLARK_PALLAS_MTILE=100, would otherwise collapse to 1)
    m_tile = max(8, 1 << (max(m_tile, 8).bit_length() - 1))
    m_tile = min(m_tile, m)
    while m % m_tile:
        m_tile //= 2
    if _vmem_estimate(m_tile, s_dim, 0) > _VMEM_BUDGET_BYTES:
        # scan smaller valid tiles — ≥ 8, multiples of 8 (sublane
        # tiling), divisors of the padded m — largest first. (m_tile may
        # be the non-power-of-2 m itself via min(m_tile, m), so blind
        # halving could skip valid tiles or land misaligned.)
        for t in range(min(m_tile - 8, _pad_to(m_tile // 2, 8)), 7, -8):
            if m % t == 0 and _vmem_estimate(t, s_dim, 0) <= _VMEM_BUDGET_BYTES:
                return t
        # no valid tile fits (the generation term scales with s_dim
        # alone) — XLA fallback instead of a Mosaic abort
        return None
    return m_tile


def _block_keys(key, n: int) -> jnp.ndarray:
    """uint32 (n_blocks, 2) Threefry key table for column blocks 0..n/BC."""
    n_blocks = -(-n // BLOCK_COLS)
    return jax.vmap(lambda b: jr_key_data(randgen.chunk_key(key, b)))(
        jnp.arange(n_blocks, dtype=jnp.int32)
    ).astype(jnp.uint32)


def _padded_extents(n: int, m: int, mt: int) -> tuple[int, int]:
    """Padded (seq, other) extents of an apply: seq to a BLOCK_COLS
    multiple, the other to an mt multiple — shared by :func:`_padded`
    and :func:`effective_plan` so the plan sees the kernel's real
    shapes."""
    return _pad_to(n, BLOCK_COLS), _pad_to(max(m, 8), mt)


def _padded(A, seq_axis: int, mt: int):
    """Zero-pad A so seq axis % BLOCK_COLS == 0 and the other % mt == 0."""
    n, m = A.shape[seq_axis], A.shape[1 - seq_axis]
    n_p, m_p = _padded_extents(n, m, mt)
    pn, pm = n_p - n, m_p - m
    if pn == 0 and pm == 0:
        return A
    pads = [(0, pn), (0, pm)] if seq_axis == 0 else [(0, pm), (0, pn)]
    return jnp.pad(A, pads)


def rowwise_apply(
    key: jax.Array,
    dist,
    A: jnp.ndarray,
    s_dim: int,
    scale: float,
    m_tile: int | None = None,
    precision: str | None = None,
    interpret: bool = False,
) -> Optional[jnp.ndarray]:
    """out = scale · A @ Sᵀ with S the virtual (s_dim × N) matrix of
    :func:`randgen.dense_block`. Returns None when not applicable (caller
    falls back to the XLA path) — including when a cached autotuner plan
    certifies the XLA path for this workload."""
    knobs = _resolve_knobs(dist, A.shape, A.dtype, s_dim, 1, m_tile,
                           precision)
    if knobs is _TAKE_XLA:
        return None
    m_tile, precision, pipeline, _src = knobs
    mt = _qualify(dist, A, seq_axis=1, m_tile=m_tile, interpret=interpret,
                  s_dim=s_dim)
    if mt is None:
        return None
    m = A.shape[0]
    Ap = _padded(A, seq_axis=1, mt=mt)
    try:
        out = _fused_call(Ap, _block_keys(key, A.shape[1]), s_dim=s_dim,
                          dist_kind=_DIST_KINDS[type(dist)], m_tile=mt,
                          precision=precision, interpret=interpret,
                          pipeline=pipeline)
    except jax.errors.JaxRuntimeError:
        # eager-mode Mosaic compile failure (e.g. VMEM exhaustion on a
        # small-VMEM part) → let the caller take the XLA path
        return None
    return scale * out[:m]


def columnwise_apply(
    key: jax.Array,
    dist,
    A: jnp.ndarray,
    s_dim: int,
    scale: float,
    m_tile: int | None = None,
    precision: str | None = None,
    interpret: bool = False,
) -> Optional[jnp.ndarray]:
    """out = scale · S @ A for A (N, m); same fused generation, transposed
    contraction."""
    knobs = _resolve_knobs(dist, A.shape, A.dtype, s_dim, 0, m_tile,
                           precision)
    if knobs is _TAKE_XLA:
        return None
    m_tile, precision, pipeline, _src = knobs
    mt = _qualify(dist, A, seq_axis=0, m_tile=m_tile, interpret=interpret,
                  s_dim=s_dim)
    if mt is None:
        return None
    m = A.shape[1]
    Ap = _padded(A, seq_axis=0, mt=mt)
    try:
        out = _fused_call_cw(Ap, _block_keys(key, A.shape[0]), s_dim=s_dim,
                             dist_kind=_DIST_KINDS[type(dist)], m_tile=mt,
                             precision=precision, interpret=interpret,
                             pipeline=pipeline)
    except jax.errors.JaxRuntimeError:
        return None
    return scale * out[:, :m]


def rft_rowwise_apply(
    key: jax.Array,
    dist,
    A: jnp.ndarray,
    s_dim: int,
    inscale: float,
    outscale: float,
    sc: jnp.ndarray,
    sh: jnp.ndarray,
    m_tile: int | None = None,
    precision: str | None = None,
    interpret: bool = False,
) -> Optional[jnp.ndarray]:
    """Fused random-Fourier-feature rowwise apply:
    ``outscale · cos((A @ (inscale·S)ᵀ) ⊙ sc + sh)`` with the cos
    epilogue applied in VMEM (no extra HBM round-trip of the feature
    matrix). ``sc``/``sh`` are (s_dim,) per-feature scales/shifts.
    Returns None when not applicable."""
    knobs = _resolve_knobs(dist, A.shape, A.dtype, s_dim, 1, m_tile,
                           precision, rft=True)
    if knobs is _TAKE_XLA:
        return None
    m_tile, precision, pipeline, _src = knobs
    mt = _qualify(dist, A, seq_axis=1, m_tile=m_tile, interpret=interpret,
                  s_dim=s_dim)
    if mt is None:
        return None
    m = A.shape[0]
    Ap = _padded(A, seq_axis=1, mt=mt)
    try:
        out = _fused_call_cos(
            Ap, _block_keys(key, A.shape[1]),
            jnp.asarray(sc, jnp.float32).reshape(1, s_dim),
            jnp.asarray(sh, jnp.float32).reshape(1, s_dim),
            s_dim=s_dim, dist_kind=_DIST_KINDS[type(dist)], m_tile=mt,
            precision=precision, inscale=float(inscale),
            outscale=float(outscale), interpret=interpret,
            pipeline=pipeline)
    except jax.errors.JaxRuntimeError:
        return None
    return out[:m]


def _default_precision() -> str:
    from libskylark_tpu.sketch import params as sketch_params

    return sketch_params.get_pallas_precision()


def fused_partial(
    keys: jax.Array,
    dist,
    A_loc: jnp.ndarray,
    s_dim: int,
    seq_axis: int,
    m_tile: int | None = None,
    precision: str | None = None,
    interpret: bool = False,
) -> Optional[jnp.ndarray]:
    """UNSCALED contraction of a local shard against the operator blocks
    keyed by ``keys`` (n_blocks_local, 2) — the building block that lets
    the ``shard_map`` panel pipeline (parallel/shard_apply.py) run the
    fused kernel per device: each device passes its own slice of the
    global key table, contracts its shard, and the caller psums.

    ``seq_axis`` is the contracted axis of ``A_loc`` (1 → A·Sᵀ partial,
    0 → S·A partial). The shard's sequence extent must equal
    ``keys.shape[0] * BLOCK_COLS`` (callers pre-pad to block multiples).
    Returns None when the kernel isn't applicable (caller falls back;
    backend/distribution qualification is _qualify's)."""
    if A_loc.shape[seq_axis] != keys.shape[0] * BLOCK_COLS:
        return None
    knobs = _resolve_knobs(dist, A_loc.shape, A_loc.dtype, s_dim,
                           seq_axis, m_tile, precision)
    if knobs is _TAKE_XLA:
        return None
    m_tile, precision, pipeline, _src = knobs
    mt = _qualify(dist, A_loc, seq_axis=seq_axis, m_tile=m_tile,
                  interpret=interpret, s_dim=s_dim)
    if mt is None:
        return None
    m = A_loc.shape[1 - seq_axis]
    Ap = _padded(A_loc, seq_axis=seq_axis, mt=mt)
    kw = dict(s_dim=s_dim, dist_kind=_DIST_KINDS[type(dist)], m_tile=mt,
              precision=precision, interpret=interpret,
              pipeline=pipeline)
    if seq_axis == 1:
        return _fused_call(Ap, keys, **kw)[:m]
    return _fused_call_cw(Ap, keys, **kw)[:, :m]


def effective_plan(dist, shape, dtype, s_dim: int, seq_axis: int,
                   m_tile: int | None = None,
                   interpret: bool = False,
                   precision: str | None = None) -> dict:
    """The plan a fused apply with these arguments would actually run —
    WITHOUT running it. Both tuning knobs can be silently adjusted
    downstream (:func:`_qualify` shrinks an over-budget m-tile;
    :func:`_select_pipe` drops the pipelined kernel when its buffer
    doesn't fit), so anything recording a measurement labeled with the
    REQUESTED knobs must ask for the EFFECTIVE ones or the record lies
    about what was measured (e.g. the m-tile/pipeline sweep rows in
    benchmarks/). Runs the SAME plan-cache resolution as the dispatch
    (:func:`_resolve_knobs`), so the report reflects cached plans too.

    Returns ``{"kernel": False, "plan_id": "xla"}`` when the apply would
    take the XLA fallback, else ``kernel/m_tile/operator_cache/
    pipelined/precision/plan_id/plan_source``."""
    knobs = _resolve_knobs(dist, tuple(shape), jnp.dtype(dtype), s_dim,
                           seq_axis, m_tile, precision)
    if knobs is _TAKE_XLA:
        return {"kernel": False, "plan_id": "xla",
                "plan_source": "cache"}
    m_tile, precision, pipeline, source = knobs
    A = jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))
    mt = _qualify(dist, A, seq_axis=seq_axis, m_tile=m_tile,
                  interpret=interpret, s_dim=s_dim)
    if mt is None:
        return {"kernel": False, "plan_id": "xla",
                "plan_source": source}
    # the same padding/scratch/pipeline helpers the pallas_call sites use
    n_p, m_p = _padded_extents(shape[seq_axis], shape[1 - seq_axis], mt)
    scratch = _scratch(s_dim, n_p, m_p, mt)
    pipelined = _pipe_fits(scratch, s_dim, mt, pipeline)
    # single source of the id format: the same Plan the cache stores
    from libskylark_tpu.tune.plans import Plan

    plan_id = Plan("pallas", m_tile=mt, precision=precision,
                   pipeline=pipelined).plan_id()
    return {"kernel": True, "m_tile": mt,
            "operator_cache": bool(scratch),
            "pipelined": pipelined,
            "precision": precision,
            "plan_id": plan_id,
            "plan_source": source}


def jr_key_data(k):
    import jax.random as jr

    return jr.key_data(k)


# ---------------------------------------------------------------------------
# batched (microbatch-flush) launchers: one kernel over a stacked cohort
# ---------------------------------------------------------------------------
#
# The serve layer (engine/serve.py) flushes a cohort as ONE executable.
# These launchers give that executable a single pallas_call whose grid
# carries the batch as its leading (parallel) axis — batch lanes tile
# innermost against the same VMEM budget as the unbatched kernel (one
# lane's working set per grid step; _qualify's shrink-don't-fail plan
# applies unchanged), and every lane contracts against its OWN virtual
# operator (per-lane key table, per-lane scale) so transforms differing
# only by seed coexist in one flush. Per-lane bits are capacity-
# invariant: lanes run the same fixed-tile program independently.


def _kernel_batched_rw(dist_kind, s_dim, n_blocks, precision, keys_ref,
                       scale_ref, a_ref, out_ref):
    """Batched rowwise: out[b] += A[b]_tile @ (scale[b]·S_blk[b])ᵀ.
    Grid (batch, m_tiles, n_blocks); key table flattened (B·nb, 2)."""
    b = pl.program_id(0)
    k = pl.program_id(2)
    S_blk = _gen_block(dist_kind, s_dim, keys_ref, b * n_blocks + k)
    S_blk = S_blk * scale_ref[b]
    acc = _dot(a_ref[0], S_blk, (((1,), (1,)), ((), ())), precision,
               gen_side=1)
    _accumulate(out_ref, acc[None], k)


def _kernel_batched_cw(dist_kind, s_dim, n_blocks, precision, keys_ref,
                       scale_ref, a_ref, out_ref):
    """Batched columnwise: out[b] += (scale[b]·S_blk[b]) @ A[b]_blk."""
    b = pl.program_id(0)
    k = pl.program_id(2)
    S_blk = _gen_block(dist_kind, s_dim, keys_ref, b * n_blocks + k)
    S_blk = S_blk * scale_ref[b]
    acc = _dot(S_blk, a_ref[0], (((1,), (0,)), ((), ())), precision,
               gen_side=0)
    _accumulate(out_ref, acc[None], k)


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "dist_kind", "m_tile", "precision",
                     "rowwise", "interpret"),
)
def _batched_call(A, keys, scale, *, s_dim, dist_kind, m_tile,
                  precision, rowwise, interpret):
    B = A.shape[0]
    n = A.shape[2] if rowwise else A.shape[1]
    m = A.shape[1] if rowwise else A.shape[2]
    n_blocks = n // BLOCK_COLS
    grid = (B, m // m_tile, n_blocks)
    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    if rowwise:
        kern = functools.partial(_kernel_batched_rw, dist_kind, s_dim,
                                 n_blocks, precision)
        a_spec = pl.BlockSpec((1, m_tile, BLOCK_COLS),
                              lambda b, i, k: (b, i, k),
                              memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((1, m_tile, s_dim),
                                lambda b, i, k: (b, i, 0),
                                memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((B, m, s_dim), jnp.float32)
    else:
        kern = functools.partial(_kernel_batched_cw, dist_kind, s_dim,
                                 n_blocks, precision)
        a_spec = pl.BlockSpec((1, BLOCK_COLS, m_tile),
                              lambda b, i, k: (b, k, i),
                              memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((1, s_dim, m_tile),
                                lambda b, i, k: (b, 0, i),
                                memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((B, s_dim, m), jnp.float32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # keys (B·nb, 2)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # scale (B,)
            a_spec,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        compiler_params=params,
        interpret=interpret,
    )(keys, scale, A)


def serve_qualify(dist, s_dim: int, n: int, m: int, dtype,
                  interpret: bool = False,
                  m_tile: Optional[int] = None) -> tuple[bool, str]:
    """Host-side qualification for the batched serve launcher:
    (ok, reason) — the serve layer's decline counter wants the why."""
    if not _HAVE_PALLAS:
        return False, "pallas unavailable"
    if not interpret and not available():
        return False, "backend is not a TPU (interpret-mode only here)"
    if not supported(dist, dtype):
        return False, f"distribution/dtype unsupported ({dtype})"
    lane = jax.ShapeDtypeStruct((m, n), jnp.dtype(dtype))
    mt = _qualify(dist, lane, seq_axis=1,
                  m_tile=m_tile or _DEFAULT_M_TILE(),
                  interpret=interpret, s_dim=s_dim)
    if mt is None:
        return False, "no m-tile fits the VMEM budget"
    return True, "ok"


def serve_batched_apply(key_data, scale, A, *, dist, s_dim: int,
                        rowwise: bool, m_tile: Optional[int] = None,
                        precision: Optional[str] = None,
                        interpret: bool = False) -> jnp.ndarray:
    """Batched fused generate+matmul for a microbatch flush: the
    stacked-cohort analog of :func:`rowwise_apply`/:func:`columnwise_
    apply`, fully traceable (the serve builder compiles it into the
    bucket's batched executable). ``key_data`` (B, 2) uint32,
    ``scale`` (B,), ``A`` (B, m, n) rowwise / (B, n, m) columnwise.
    The scale multiplies the generated operator entries — the same
    elementwise order as ``serve_apply``'s scaled virtual panel.
    Raises on unqualified input: callers gate on
    :func:`serve_qualify` first."""
    import jax.random as jr

    A = jnp.asarray(A)
    n_axis = 2 if rowwise else 1
    n, m = A.shape[n_axis], A.shape[3 - n_axis]
    lane = jax.ShapeDtypeStruct(
        (m, n) if rowwise else (n, m), A.dtype)
    mt = _qualify(dist, lane, seq_axis=1 if rowwise else 0,
                  m_tile=m_tile or _DEFAULT_M_TILE(),
                  interpret=interpret, s_dim=s_dim)
    if mt is None:
        raise ValueError(
            f"batched dense kernel unqualified for s_dim={s_dim} "
            f"shape {A.shape}")
    if precision is None:
        precision = _default_precision()
    n_p, m_p = _padded_extents(n, m, mt)
    pads = [(0, 0), (0, 0), (0, 0)]
    pads[n_axis] = (0, n_p - n)
    pads[3 - n_axis] = (0, m_p - m)
    Ap = jnp.pad(A, pads) if (n_p != n or m_p != m) else A
    B = A.shape[0]
    keys = jax.vmap(
        lambda k: _block_keys(jr.wrap_key_data(k), n))(
            jnp.asarray(key_data, jnp.uint32))
    out = _batched_call(
        Ap, keys.reshape(B * keys.shape[1], 2),
        jnp.asarray(scale, jnp.float32).reshape(B),
        s_dim=s_dim, dist_kind=_DIST_KINDS[type(dist)], m_tile=mt,
        precision=precision, rowwise=rowwise, interpret=interpret)
    return out[:, :m, :] if rowwise else out[:, :, :m]
