"""Pallas TPU kernel: fused on-the-fly sketch generation + matmul.

The hot primitive of the framework (ref: SURVEY.md §3.1 — the reference's
blocked panel algorithm in sketch/dense_transform_Elemental_mc_mr.hpp with
``realize_matrix_view`` generating S panels on demand). The XLA path pays
for panel generation (Threefry + inverse-CDF on the VPU) serialized against
the matmul; this kernel generates each (S_dim × BLOCK_COLS) panel of S in
VMEM — exact same bits as :func:`randgen.dense_block`, via the shared
integer-op Threefry in base/threefry.py — while the MXU contracts the
previous panels, so generation rides under the matmul.

Rowwise apply only (out = A·Sᵀ, the regime of BASELINE config 1); other
layouts fall back to the XLA path in sketch/dense.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.base import randgen, threefry as tf

try:  # import guarded so non-TPU environments can import the module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

from libskylark_tpu.sketch.dense import BLOCK_COLS  # the stream format's
# panel width — single source of truth (dense.py imports this module only
# lazily, so no cycle)

_HALF = BLOCK_COLS // 2


def available() -> bool:
    """True when the default backend can run the Mosaic kernel."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _gen_block(dist_kind, s_dim, keys_ref, k):
    """Generate operator column block k (s_dim, BLOCK_COLS) in VMEM —
    bit-identical to randgen.dense_block's threefry-pair layout."""
    k0 = keys_ref[k, 0]
    k1 = keys_ref[k, 1]
    c = (
        jax.lax.broadcasted_iota(jnp.uint32, (s_dim, _HALF), 0) * _HALF
        + jax.lax.broadcasted_iota(jnp.uint32, (s_dim, _HALF), 1)
    )
    b0, b1 = tf.threefry2x32(k0, k1, c, c + s_dim * _HALF)
    if dist_kind == "normal":
        s0, s1 = tf.bits_to_normal(b0), tf.bits_to_normal(b1)
    elif dist_kind == "cauchy":
        s0, s1 = tf.bits_to_cauchy(b0), tf.bits_to_cauchy(b1)
    elif dist_kind == "rademacher":
        s0, s1 = tf.bits_to_rademacher(b0), tf.bits_to_rademacher(b1)
    else:
        raise NotImplementedError(dist_kind)
    return jnp.concatenate([s0, s1], axis=1)  # (s_dim, BLOCK_COLS)


def _accumulate(out_ref, acc, k):
    @pl.when(k == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(k != 0)
    def _acc():
        out_ref[:] += acc


def _kernel(dist_kind, s_dim, m_tile, keys_ref, a_ref, out_ref):
    """Rowwise: out_tile += A_tile @ S_blkᵀ. bf16 inputs + f32
    accumulation: the MXU-native regime, matching XLA's DEFAULT matmul
    precision on TPU (the S entries themselves stay bit-exact; only the
    contraction rounds at hardware precision)."""
    k = pl.program_id(1)
    S_blk = _gen_block(dist_kind, s_dim, keys_ref, k)
    acc = jax.lax.dot_general(
        a_ref[:].astype(jnp.bfloat16),
        S_blk.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    _accumulate(out_ref, acc, k)


def _kernel_cw(dist_kind, s_dim, m_tile, keys_ref, a_ref, out_ref):
    """Columnwise: out_tile += S_blk @ A_blk (same precision regime)."""
    k = pl.program_id(1)
    S_blk = _gen_block(dist_kind, s_dim, keys_ref, k)
    acc = jax.lax.dot_general(
        S_blk.astype(jnp.bfloat16),
        a_ref[:].astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    _accumulate(out_ref, acc, k)


@functools.partial(
    jax.jit, static_argnames=("s_dim", "dist_kind", "m_tile")
)
def _fused_call(A, keys, *, s_dim, dist_kind, m_tile):
    m, n = A.shape
    n_blocks = n // BLOCK_COLS
    grid = (m // m_tile, n_blocks)
    kern = functools.partial(_kernel, dist_kind, s_dim, m_tile)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            # whole key table in SMEM every step (tiny); indexed by k
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (m_tile, BLOCK_COLS), lambda i, k: (i, k),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (m_tile, s_dim), lambda i, k: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, s_dim), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(keys, A)


@functools.partial(
    jax.jit, static_argnames=("s_dim", "dist_kind", "m_tile")
)
def _fused_call_cw(A, keys, *, s_dim, dist_kind, m_tile):
    n, m = A.shape
    n_blocks = n // BLOCK_COLS
    grid = (m // m_tile, n_blocks)
    kern = functools.partial(_kernel_cw, dist_kind, s_dim, m_tile)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (BLOCK_COLS, m_tile), lambda j, k: (k, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (s_dim, m_tile), lambda j, k: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((s_dim, m), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(keys, A)


_DIST_KINDS = {
    randgen.Normal: "normal",
    randgen.Cauchy: "cauchy",
    randgen.Rademacher: "rademacher",
}


def supported(dist, dtype) -> bool:
    kind = _DIST_KINDS.get(type(dist))
    if kind is None:
        return False
    # only the standard forms share the plain bit transforms
    if kind == "normal" and (dist.mean != 0.0 or dist.std != 1.0):
        return False
    if kind == "cauchy" and (dist.loc != 0.0 or dist.scale != 1.0):
        return False
    return jnp.dtype(dtype) == jnp.float32


def _qualify(dist, A, seq_axis: int, m_tile: int):
    """Common qualification: backend, distribution, shape divisibility.
    Returns (m_tile, block keys) or None."""
    if not (_HAVE_PALLAS and available() and supported(dist, A.dtype)):
        return None
    n = A.shape[seq_axis]
    m = A.shape[1 - seq_axis]
    if n % BLOCK_COLS or m < 8:
        return None
    m_tile = min(m_tile, m)
    while m % m_tile:
        m_tile //= 2
    if m_tile < 8:
        return None
    return m_tile


def _block_keys(key, n: int) -> jnp.ndarray:
    n_blocks = n // BLOCK_COLS
    return jax.vmap(lambda b: jr_key_data(randgen.chunk_key(key, b)))(
        jnp.arange(n_blocks, dtype=jnp.int32)
    ).astype(jnp.uint32)


def rowwise_apply(
    key: jax.Array,
    dist,
    A: jnp.ndarray,
    s_dim: int,
    scale: float,
    m_tile: int = 256,
) -> Optional[jnp.ndarray]:
    """out = scale · A @ Sᵀ with S the virtual (s_dim × N) matrix of
    :func:`randgen.dense_block`. Returns None when not applicable (caller
    falls back to the XLA path)."""
    mt = _qualify(dist, A, seq_axis=1, m_tile=m_tile)
    if mt is None:
        return None
    out = _fused_call(A, _block_keys(key, A.shape[1]), s_dim=s_dim,
                      dist_kind=_DIST_KINDS[type(dist)], m_tile=mt)
    return scale * out


def columnwise_apply(
    key: jax.Array,
    dist,
    A: jnp.ndarray,
    s_dim: int,
    scale: float,
    m_tile: int = 256,
) -> Optional[jnp.ndarray]:
    """out = scale · S @ A for A (N, m); same fused generation, transposed
    contraction."""
    mt = _qualify(dist, A, seq_axis=0, m_tile=m_tile)
    if mt is None:
        return None
    out = _fused_call_cw(A, _block_keys(key, A.shape[0]), s_dim=s_dim,
                         dist_kind=_DIST_KINDS[type(dist)], m_tile=mt)
    return scale * out


def jr_key_data(k):
    import jax.random as jr

    return jr.key_data(k)
