"""Fused Fastfood feature map: the whole SHGΠHB chain in one Mosaic
kernel.

Motivation (BASELINE.md crossover analysis; ref: sketch/FRFT_Elemental.hpp,
sketch/FUT.hpp:225-347): the XLA Fastfood chain is bandwidth-bound — at
(16384, 4096 → 4096) it moves 4.83 GB for 34.8 GFLOP (hlo_cost_r05.json)
because every stage re-touches the whole (rows, NB) intermediate in HBM,
while dense RFT's single gemm moves 3.31 GB. This kernel keeps one m-tile
of the input resident in VMEM through the ENTIRE chain:

    read X tile → B⊙ → WHT → Π-gather → (scal·G)⊙ → WHT → (scal·Sm)⊙
      → scale·cos(· + shifts) → write F tile

so HBM traffic is one read of X plus one write of F (~0.54 GB at the
flagship config — ~9× less than the XLA chain, ~6× less than the dense
gemm) while the WHT matmuls ride the MXU. Each WHT runs as the same
kron-factored two-dot form as fut._wht_matmul (Ha·X·Hb over the
(a, b)-folded axis) with the contractions always on a minor axis — the
(a, b) fold is transposed between the dots with a rank-3 minor-axes swap.
Contractions use pallas_dense._dot, the on-chip-certified bf16x3 /
f32 / bf16 regime set (±1 Hadamard factors are bf16-exact, so bf16x3 is
f32-grade here).

Like pallas_dense, the kernel is planned against the ~16 MiB VMEM budget
(the m-tile shrinks rather than failing Mosaic) and every caller falls
back to the XLA chain when the kernel declines or fails to compile —
the permutation gather (`jnp.take_along_axis` along the lane axis with
trace-constant indices) is the one op in this kernel without a
certified precedent in this repo; until a live window compile-checks
it, the dispatch treats Mosaic rejection as a normal decline. Exact
semantics vs the XLA chain are pinned by interpret-mode oracles in
tests/test_pallas_fastfood.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from libskylark_tpu.base import env as _env
from libskylark_tpu.sketch.fut import _hadamard_np
from libskylark_tpu.sketch.pallas_dense import (_VMEM_BUDGET_BYTES, _dot,
                                                available)

try:  # same import seam as pallas_dense: CPU-only hosts lack TPU pallas
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401 — availability probe

    _PALLAS = True
except Exception:  # pragma: no cover
    _PALLAS = False


def _wht_split(NB: int) -> tuple[int, int]:
    """The (a, b) kron fold — SAME split rule as fut._wht_matmul so the
    kernel and the XLA path accumulate in comparable order."""
    k = NB.bit_length() - 1
    a = 1 << (k - k // 2)
    return a, NB // a


def _wht2(W, Ha, Hb, mt: int, a: int, b: int, precision: str):
    """Ha·X·Hb over the (a, b)-folded minor axis of W (mt, a·b): two 2-D
    MXU dots with the fold transposed between them (math identical to
    fut._wht_matmul's einsum; exact-arithmetic wise both are ±1-weighted
    f32 sums).

    The Hadamard operand is ±1 — EXACT in bfloat16, so its lo term is
    identically zero and bf16x3's middle pass (X_hi·H_lo) contributes
    exact zeros: the 2-pass split with the H side as the "generated"
    operand is bit-identical to bf16x3 here at 2/3 the MXU passes.
    ``_dot("bf16gen2", gen_side=1)`` is exactly that split."""
    if precision == "bf16x3":
        precision = "bf16gen2"  # bit-identical for ±1 rhs, one less pass
    dims = (((1,), (0,)), ((), ()))
    Z = _dot(W.reshape(mt * a, b), Hb, dims, precision,
             gen_side=1).reshape(mt, a, b)
    Zt = jnp.swapaxes(Z, 1, 2)
    Y = _dot(Zt.reshape(mt * b, a), Ha, dims, precision,
             gen_side=1).reshape(mt, b, a)
    return jnp.swapaxes(Y, 1, 2).reshape(mt, a * b)


def _stage_pre(x, bdiag, Ha, Hb, mt, NB, precision):
    """Everything before the Π gather: B⊙x → WHT. Shared verbatim by
    the fused kernel and the split variant's stage-1 kernel — one
    definition so the two variants cannot drift apart."""
    a, b = _wht_split(NB)
    return _wht2(bdiag * x, Ha, Hb, mt, a, b, precision)


def _stage_post(W, gdiag, smdiag, shift, Ha, Hb, mt, NB, precision,
                scale):
    """Everything after the Π gather: (scal·G)⊙ → WHT → (scal·Sm)⊙ →
    scale·cos(·+shifts). Shared by both variants like _stage_pre."""
    a, b = _wht_split(NB)
    W = _wht2(gdiag * W, Ha, Hb, mt, a, b, precision)
    return scale * jnp.cos(smdiag * W + shift)


def _kernel_pre(mt, NB, precision,
                x_ref, bdiag_ref, ha_ref, hb_ref, out_ref):
    """Split-variant stage-1 kernel. Exists because the fused kernel's
    in-kernel lane gather is the one op without certified Mosaic
    precedent: if Mosaic rejects it, the dispatch falls back to this
    two-kernel pipeline with the gather done by XLA between the calls —
    still ~3× less HBM traffic than the all-XLA chain (~1.6 GB modeled
    vs 4.83 GB at the flagship config)."""
    out_ref[:] = _stage_pre(x_ref[:], bdiag_ref[:], ha_ref[:], hb_ref[:],
                            mt, NB, precision).astype(out_ref.dtype)[None]


def _kernel_post(mt, NB, precision, scale,
                 w_ref, gdiag_ref, smdiag_ref, shift_ref,
                 ha_ref, hb_ref, out_ref):
    """Split-variant stage-2 kernel."""
    out_ref[:] = _stage_post(
        w_ref[0], gdiag_ref[:], smdiag_ref[:], shift_ref[:],
        ha_ref[:], hb_ref[:], mt, NB, precision, scale,
    ).astype(out_ref.dtype)[None]


def _kernel(mt, NB, precision, scale,
            x_ref, bdiag_ref, perm_ref, gdiag_ref, smdiag_ref, shift_ref,
            ha_ref, hb_ref, out_ref):
    """One (block, m-tile) grid step: the full chain in VMEM, composed
    from the SAME stage helpers the split variant runs.

    Refs: x (mt, NB) padded input rows; bdiag/gdiag/smdiag/shift
    (1, NB) this block's diagonals (g/sm pre-scaled by √NB·fut.scale);
    perm (1, NB) int32 gather indices; ha/hb the ±1 Hadamard kron
    factors (pallas requires trace constants as inputs); out (mt, NB)
    features before block-order interleave/truncation (done by the
    caller in XLA)."""
    Ha, Hb = ha_ref[:], hb_ref[:]
    W = _stage_pre(x_ref[:], bdiag_ref[:], Ha, Hb, mt, NB, precision)
    W = jnp.take_along_axis(W, perm_ref[:], axis=1)
    out_ref[:] = _stage_post(
        W, gdiag_ref[:], smdiag_ref[:], shift_ref[:], Ha, Hb,
        mt, NB, precision, scale,
    ).astype(out_ref.dtype)[None]


def plan_m_tile(NB: int, m: int) -> int | None:
    """Largest m-tile whose working set fits the VMEM budget: double-
    buffered in/out tiles plus ~4 chain temporaries, all (mt, NB) f32.
    None when even the minimum tile doesn't fit (NB too large)."""
    per_row = NB * 4 * (2 + 2 + 4)
    mt = _VMEM_BUDGET_BYTES // per_row
    mt = min(int(mt), m, 512)
    mt -= mt % 8
    return mt if mt >= 8 else None


@functools.partial(jax.jit, static_argnames=("mt", "NB", "nb",
                                             "precision", "scale",
                                             "interpret"))
def _launch(X, bdiag, perms, gdiag, smdiag, shifts, mt, NB, nb,
            precision, scale, interpret):
    n_tiles = X.shape[0] // mt
    a, b = _wht_split(NB)
    Ha = jnp.asarray(_hadamard_np(a), jnp.float32)
    Hb = jnp.asarray(_hadamard_np(b), jnp.float32)
    return pl.pallas_call(
        functools.partial(_kernel, mt, NB, precision, scale),
        grid=(nb, n_tiles),
        in_specs=[
            pl.BlockSpec((mt, NB), lambda blk, t: (t, 0)),
            pl.BlockSpec((1, NB), lambda blk, t: (blk, 0)),
            pl.BlockSpec((1, NB), lambda blk, t: (blk, 0)),
            pl.BlockSpec((1, NB), lambda blk, t: (blk, 0)),
            pl.BlockSpec((1, NB), lambda blk, t: (blk, 0)),
            pl.BlockSpec((1, NB), lambda blk, t: (blk, 0)),
            pl.BlockSpec((a, a), lambda blk, t: (0, 0)),
            pl.BlockSpec((b, b), lambda blk, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, mt, NB), lambda blk, t: (blk, t, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, X.shape[0], NB), X.dtype),
        interpret=interpret,
    )(X, bdiag, perms, gdiag, smdiag, shifts, Ha, Hb)


@functools.partial(jax.jit, static_argnames=("mt", "NB", "nb",
                                             "precision", "scale",
                                             "interpret"))
def _launch_split(X, bdiag, perms, gdiag, smdiag, shifts, mt, NB, nb,
                  precision, scale, interpret):
    """Two-kernel pipeline: K1 (B⊙ + WHT) → XLA Π gather → K2
    (G⊙ + WHT + Sm⊙ + cos). The gather runs exactly as in the XLA
    chain; everything else stays in VMEM-resident kernels."""
    n_tiles = X.shape[0] // mt
    a, b = _wht_split(NB)
    Ha = jnp.asarray(_hadamard_np(a), jnp.float32)
    Hb = jnp.asarray(_hadamard_np(b), jnp.float32)
    diag_spec = pl.BlockSpec((1, NB), lambda blk, t: (blk, 0))
    ha_spec = pl.BlockSpec((a, a), lambda blk, t: (0, 0))
    hb_spec = pl.BlockSpec((b, b), lambda blk, t: (0, 0))
    out3 = pl.BlockSpec((1, mt, NB), lambda blk, t: (blk, t, 0))
    W1 = pl.pallas_call(
        functools.partial(_kernel_pre, mt, NB, precision),
        grid=(nb, n_tiles),
        in_specs=[pl.BlockSpec((mt, NB), lambda blk, t: (t, 0)),
                  diag_spec, ha_spec, hb_spec],
        out_specs=out3,
        out_shape=jax.ShapeDtypeStruct((nb, X.shape[0], NB), X.dtype),
        interpret=interpret,
    )(X, bdiag, Ha, Hb)
    Wg = jnp.take_along_axis(W1, perms[:, None, :], axis=-1)
    return pl.pallas_call(
        functools.partial(_kernel_post, mt, NB, precision, scale),
        grid=(nb, n_tiles),
        in_specs=[out3, diag_spec, diag_spec, diag_spec,
                  ha_spec, hb_spec],
        out_specs=out3,
        out_shape=jax.ShapeDtypeStruct((nb, X.shape[0], NB), X.dtype),
        interpret=interpret,
    )(Wg, gdiag, smdiag, shifts, Ha, Hb)


def _kernel_batched(mt, NB, precision, scale,
                    x_ref, bdiag_ref, perm_ref, gdiag_ref, smdiag_ref,
                    shift_ref, ha_ref, hb_ref, out_ref):
    """Batched-cohort grid step: the SAME fused chain (shared stage
    helpers) with the microbatch lane as the leading grid axis — refs
    carry one lane's block, indexed off their unit batch dim."""
    Ha, Hb = ha_ref[:], hb_ref[:]
    W = _stage_pre(x_ref[0], bdiag_ref[0], Ha, Hb, mt, NB, precision)
    W = jnp.take_along_axis(W, perm_ref[0], axis=1)
    out_ref[:] = _stage_post(
        W, gdiag_ref[0], smdiag_ref[0], shift_ref[0], Ha, Hb,
        mt, NB, precision, scale,
    ).astype(out_ref.dtype)[None, None]


@functools.partial(jax.jit, static_argnames=("mt", "NB", "nb",
                                             "precision", "scale",
                                             "interpret"))
def _launch_batched(X, bdiag, perms, gdiag, smdiag, shifts, mt, NB, nb,
                    precision, scale, interpret):
    """One pallas_call over a stacked cohort: X (B, m_p, NB), per-lane
    diagonal/permutation/shift streams (B, nb, NB). Grid (B, nb,
    m-tiles) — batch lanes tile innermost against the same VMEM plan
    as the single-request launcher (one lane's chain working set per
    step; ``plan_m_tile`` unchanged)."""
    from libskylark_tpu.sketch.fut import _hadamard_np

    B = X.shape[0]
    n_tiles = X.shape[1] // mt
    a, b = _wht_split(NB)
    Ha = jnp.asarray(_hadamard_np(a), jnp.float32)
    Hb = jnp.asarray(_hadamard_np(b), jnp.float32)
    diag_spec = pl.BlockSpec((1, 1, NB), lambda i, blk, t: (i, blk, 0))
    return pl.pallas_call(
        functools.partial(_kernel_batched, mt, NB, precision, scale),
        grid=(B, nb, n_tiles),
        in_specs=[
            pl.BlockSpec((1, mt, NB), lambda i, blk, t: (i, t, 0)),
            diag_spec, diag_spec, diag_spec, diag_spec, diag_spec,
            pl.BlockSpec((a, a), lambda i, blk, t: (0, 0)),
            pl.BlockSpec((b, b), lambda i, blk, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, mt, NB),
                               lambda i, blk, t: (i, blk, t, 0)),
        out_shape=jax.ShapeDtypeStruct((B, nb, X.shape[1], NB), X.dtype),
        interpret=interpret,
    )(X, bdiag, perms, gdiag, smdiag, shifts, Ha, Hb)


def serve_qualify(n_dim: int, s_dim: int, m: int, dtype, fut: str,
                  interpret: bool = False) -> tuple[bool, str]:
    """Host-side qualification for the batched serve launcher:
    (ok, reason) — mirrors :func:`supported` for the stacked-cohort
    case (the serve layer's decline counter wants the why)."""
    from libskylark_tpu.sketch.frft import block_geometry

    if not _PALLAS:
        return False, "pallas unavailable"
    if not interpret and not available():
        return False, "backend is not a TPU (interpret-mode only here)"
    if fut != "wht":
        return False, f"fut {fut!r} has no kernel (WHT core only)"
    NB, _nb = block_geometry(n_dim, s_dim, fut)
    if NB < 512 or NB & (NB - 1):
        return False, f"NB={NB} outside the MXU-matmul regime (>=512 pow2)"
    if jnp.dtype(dtype) != jnp.float32:
        return False, f"dtype {jnp.dtype(dtype).name} != float32"
    if plan_m_tile(NB, max(int(m), 8)) is None:
        return False, "no m-tile fits the VMEM budget"
    return True, "ok"


def serve_features_batched(key_data, A, *, n_dim: int, s_dim: int,
                           fut: str = "wht", sm_kind: str = "ones",
                           sm_param=None,
                           precision: str | None = None,
                           interpret: bool = False) -> jnp.ndarray:
    """Batched fused Fastfood chain for a microbatch flush: the
    stacked-cohort analog of :func:`features_rows`, fully traceable
    (compiled into the bucket's batched executable by engine/serve).
    ``key_data`` (B, 2) uint32, ``A`` (B, m, n_dim). Per-lane streams
    are rebuilt inline from the raw keys (``frft.serve_streams`` — the
    bit-pinned pure form), so one kernel serves transforms differing
    only by seed. Raises on unqualified input: callers gate on
    :func:`serve_qualify` first."""
    import math

    import jax.random as jr

    from libskylark_tpu.sketch.frft import block_geometry, serve_streams
    from libskylark_tpu.sketch.fut import make_fut

    A = jnp.asarray(A)
    B, m, d = A.shape
    if d != n_dim:
        raise ValueError(f"operand cols {d} != n_dim {n_dim}")
    NB, nb = block_geometry(n_dim, s_dim, fut)
    mt = plan_m_tile(NB, max(m, 8))
    if mt is None:
        raise ValueError(f"no VMEM plan for NB={NB}")
    if precision is None:
        precision = "bf16x3"
    dt = A.dtype
    fut_obj = make_fut(fut, NB)
    scal = math.sqrt(NB) * fut_obj.scale()

    def lane_streams(kd):
        bd, gd, sm, pm, sh = serve_streams(
            jr.wrap_key_data(kd), dt, NB=NB, nb=nb, s_dim=s_dim,
            sm_kind=sm_kind, sm_param=sm_param)
        # shifts indexed by final feature position; features past S are
        # computed then sliced — pad their shifts with zeros (same
        # epilogue as features_rows)
        sh = jnp.pad(sh, (0, nb * NB - s_dim)).reshape(nb, NB)
        return (bd, pm.astype(jnp.int32), scal * gd,
                scal * sm.reshape(nb, NB), sh)

    bdiag, perms, gdiag, smdiag, shifts = jax.vmap(lane_streams)(
        jnp.asarray(key_data, jnp.uint32))

    pad_rows = (-m) % mt
    pad_cols = NB - d
    Ap = (jnp.pad(A, ((0, 0), (0, pad_rows), (0, pad_cols)))
          if pad_rows or pad_cols else A)
    F = _launch_batched(Ap, bdiag, perms, gdiag, smdiag, shifts,
                        mt=mt, NB=NB, nb=nb, precision=precision,
                        scale=float(math.sqrt(2.0 / s_dim)),
                        interpret=interpret)
    # (B, nb, m_p, NB) → block-major feature order, un-pad, truncate
    return jnp.moveaxis(F, 1, 2).reshape(B, Ap.shape[1], nb * NB)[
        :, :m, :s_dim]


def supported(transform, A) -> bool:
    """Whether the fused kernel may serve this FastRFT apply: WHT core
    in its MXU-matmul regime, f32 single-device eager input (sharded
    applies keep the XLA path, whose partitioning XLA handles)."""
    if not (_PALLAS and available()):
        return False
    if getattr(transform, "_fut_name", None) != "wht":
        return False
    if transform._NB < 512 or transform._NB & (transform._NB - 1):
        return False
    if isinstance(A, jax.core.Tracer):
        return False
    if not isinstance(A, jax.Array) or A.dtype != jnp.float32:
        return False
    try:
        if len(A.sharding.device_set) != 1:
            return False
    except Exception:
        return False
    return plan_m_tile(transform._NB, int(A.shape[0])) is not None


# which launcher served the last successful features_rows call
# ("fused" | "split") — diagnostics for the on-chip certification and
# the bench record; never consulted for dispatch decisions
last_served_variant: str | None = None


def _consult_cache(transform, At):
    """Cached autotuner plan for this Fastfood feature map, or None.
    Same precedence/gating as pallas_dense._consult_cache."""
    from libskylark_tpu.sketch import params as sketch_params

    if not sketch_params.get_use_plan_cache():
        return None
    try:
        from libskylark_tpu import tune

        return tune.plan_for(tune.fastfood_workload(
            type(transform).sketch_type, At.shape, At.dtype,
            transform._S))
    except Exception:
        return None


def features_rows(transform, At, *, interpret: bool = False,
                  precision: str | None = None,
                  variant: str = "auto"):
    """The (m, S) Fastfood feature map for row-major input At (m, N)
    through the fused kernel, or None when the kernel declines or fails
    (caller falls back to the XLA chain — mirror of
    pallas_dense.rowwise_apply's contract). ``interpret`` runs the
    pallas interpreter (CPU-testable exact semantics).

    ``variant``: "fused" (single kernel, in-kernel Π gather), "split"
    (two kernels around an XLA gather — the fallback if Mosaic rejects
    the in-kernel gather), or "auto" (a cached autotuner plan first —
    which may also certify the XLA chain, declining the kernel — then
    fused, then split on failure; under ``interpret`` a fused failure
    re-raises instead — the interpreter has no Mosaic to reject, so any
    exception there is a plain bug that must not be masked by the
    fallback)."""
    import math

    if variant not in ("auto", "fused", "split"):
        raise ValueError(
            f"variant must be 'auto', 'fused' or 'split', got {variant!r}")
    if not interpret and not supported(transform, At):
        return None
    T = transform
    NB, nb = T._NB, T._numblks
    m, d = At.shape
    mt = plan_m_tile(NB, m)
    if mt is None:
        return None
    # cached plan: consulted only for the decisions the caller left open
    # (explicit variant/precision arguments and the env override below
    # always win — the documented dispatch precedence,
    # sketch/params.py ``use_plan_cache``)
    prec_open = (precision is None
                 and _env.FASTFOOD_PRECISION.raw() is None)
    plan = (_consult_cache(T, At)
            if variant == "auto" or prec_open else None)
    cache_pinned_variant = False
    if plan is not None and variant == "auto":
        if plan.backend == "xla_chain":
            if prec_open:
                return None  # certified: the XLA chain serves this
            # the caller pinned a kernel regime explicitly (argument or
            # SKYLARK_FASTFOOD_PRECISION): a sweep/pin must reach the
            # kernel — the cached decline applies only to fully-open
            # dispatch (mirrors pallas_dense._resolve_knobs' _TAKE_XLA
            # condition)
            plan = None
        elif plan.backend in ("fused", "split"):
            variant = plan.backend
            cache_pinned_variant = True
    if plan is not None and plan.backend != variant:
        # a plan certified for a DIFFERENT backend must not donate its
        # regime to an explicitly requested variant (e.g. cached split/
        # f32 would silently run an explicit fused certification at f32)
        plan = None
    if precision is None:
        precision = _env.FASTFOOD_PRECISION.raw()
    if precision is None:
        # honor an explicit user matmul-precision policy exactly like
        # the XLA chain does (frft._fut_apply / r4 advisor): pins with
        # a kernel-equivalent regime map to it — "highest"/"float32" →
        # full-f32 passes, "high"/"bfloat16_3x" → the 3-pass bf16
        # split (the same arithmetic _dot("bf16x3") implements),
        # "bfloat16" → single-pass bf16 — anything else (e.g.
        # "tensorfloat32", "default") has no kernel equivalent, so
        # decline and let the XLA chain run under the ambient setting
        from libskylark_tpu.base import precision as bprec

        pinned = (_env.MATMUL_PRECISION.raw()
                  or (bprec.ambient_matmul_precision()
                      if bprec.ambient_precision_pinned_by_user()
                      else None))
        _PIN_REGIME = {"highest": "f32", "float32": "f32",
                       "high": "bf16x3", "bfloat16_3x": "bf16x3",
                       "bfloat16": "bf16"}
        if pinned is None:
            # no user pin: a cached plan's regime (oracle-grade only —
            # same read-time guard as pallas_dense._resolve_knobs; the
            # committed cache file must not be able to opt the default
            # dispatch into bf16), else the default
            from libskylark_tpu.tune.plans import ORACLE_PRECISIONS

            precision = (plan.precision if plan is not None
                         and plan.precision in ORACLE_PRECISIONS
                         else "bf16x3")
        elif pinned in _PIN_REGIME:
            precision = _PIN_REGIME[pinned]
        else:
            return None
    dt = At.dtype
    scal = math.sqrt(NB) * T._fut.scale()

    pad_rows = (-m) % mt
    pad_cols = NB - d
    Ap = (jnp.pad(At, ((0, pad_rows), (0, pad_cols)))
          if pad_rows or pad_cols else At)

    bdiag = T._B(dt)
    gdiag = scal * T._G(dt)
    smdiag = scal * T._Sm(dt).reshape(nb, NB)
    perms = T._perms().astype(jnp.int32)
    sh = T.shifts(dt)
    # shifts indexed by FINAL feature position f = blk·NB + j; features
    # past S are computed then sliced off — pad their shifts with zeros
    sh = jnp.pad(sh, (0, nb * NB - T._S)).reshape(nb, NB)

    global last_served_variant
    launchers = {"fused": (_launch,), "split": (_launch_split,),
                 "auto": (_launch, _launch_split)}[variant]
    if cache_pinned_variant and variant == "fused":
        # a cache-pinned fused plan keeps "auto"'s split fallback: the
        # cache key is a pow2 shape BUCKET, so a different concrete
        # shape (or toolchain rev) can still hit the one op without
        # certified Mosaic precedent (the in-kernel gather) — degrading
        # to the split kernel (~3x traffic) beats falling all the way
        # to the XLA chain (~9x). An EXPLICIT variant="fused" argument
        # stays exact (a certification run must not silently switch).
        launchers = (_launch, _launch_split)
    F = None
    for launch in launchers:
        try:
            F = launch(Ap, bdiag, perms, gdiag, smdiag, sh,
                       mt=mt, NB=NB, nb=nb, precision=precision,
                       scale=float(T.scale), interpret=interpret)
            last_served_variant = (
                "fused" if launch is _launch else "split")
            break
        except Exception:
            if interpret:
                # the interpreter has no Mosaic rejection to tolerate:
                # an exception here is a plain bug — surface it rather
                # than silently degrading the oracle to the other
                # variant (review finding)
                raise
    if F is None:
        return None
    # (nb, m_p, NB) → block-major feature order, un-pad, truncate —
    # identical to FastRFT._features_rows' epilogue
    return jnp.moveaxis(F, 0, 1).reshape(Ap.shape[0], nb * NB)[
        :m, : T._S]
