"""Pallas TPU kernel: panel-free fused SRHT (sign → FWHT → sample).

The FJLT/``wht`` family's serve path contracts operands through the
XLA twin :func:`libskylark_tpu.sketch.fut.fwht_sketch` — a diag
multiply, a Walsh-Hadamard transform, and a row gather, three separate
HLOs with the full (m, n) mixed intermediate written back between
them. This kernel fuses the whole program into one pallas_call so the
intermediate never leaves VMEM:

1. **In-kernel stream generation.** The Rademacher sign diagonal
   (sub-stream 0) and the sampled coordinates (sub-stream 1) are
   regenerated inside the kernel from the transform's raw Threefry
   key, replicating ``randgen.stream_slice``'s chunk format exactly —
   the same discipline as ``pallas_hash`` (per-chunk derived keys in a
   tiny SMEM table, the wide ciphers in VMEM per grid step), so the
   kernel's streams are **bit-identical** to the XLA path's.
   ``jax.random.randint``'s double-draw multiplier is zero for every
   power-of-two span (:func:`pallas_hash._randint_multiplier`), and
   the FWHT length is a power of two by construction, so the
   coordinate stream needs only the low cipher.

2. **In-kernel butterfly.** The n-point transform factors as
   H_n = (H_{n/128} ⊗ I_128) · (I_{n/128} ⊗ H_128): the inner factor
   is one MXU contraction of each 128-lane block against H_128 (built
   in-register from an iota-parity identity — no large constants baked
   into the program), the outer factor is log2(n/128) lane-aligned
   butterfly stages whose minor dimension stays 128. The sign diagonal
   is folded into the first stage's operand load; the ``1/sqrt(n)``
   scale multiplies the diagonal first (the twin's op order).

3. **Fused sample gather.** The s sampled rows come out of the last
   stage as a fori_loop of 128-wide signed-one-hot MXU dots — each
   output coordinate meets exactly one nonzero across the loop, and
   ``x + 0.0`` / ``0.0 · x`` are exact for finite x, so the dot
   sequence is bit-equal to a true gather.

Both stream generation and the butterfly are exact-arithmetic
programs, so on dyadic data (integer-valued f32 operands, n and s
even powers of two) the kernel is **bit-equal** to the XLA twin and
to the ``FJLT.operator_panel`` matmul oracle; on general floats the
summation order differs from the kron-matmul lowering and agreement
is allclose (tests/test_fwht.py pins both regimes in interpret mode).

Like every kernel in this tree, dispatch DECLINES (``qualify``
explains why) rather than failing: off-TPU callers keep the XLA twin.
The bench tunnel is down (ROADMAP), so Mosaic has no certified
on-chip precedent yet; until a live window certifies it, only an
explicit override (``SKYLARK_FWHT_KERNEL``) or a measured plan-cache
entry routes serve traffic here, and a Mosaic rejection at compile
time falls back.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.base import threefry as tf

try:  # same import seam as pallas_dense: non-TPU builds may lack pallas
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

from libskylark_tpu.sketch.pallas_dense import (_VMEM_BUDGET_BYTES,
                                                available)
from libskylark_tpu.sketch.pallas_hash import (CHUNK, _GEN_COLS, _HALF,
                                               _hot_dot, _mod_span)

# Default rows-per-grid-step of the free (m) axis; shrunk (never
# failed) against the VMEM budget like pallas_hash's m-tile.
_DEFAULT_M_TILE = 256

# The coordinate stream must fit one cipher sweep (positions 0.._HALF-1
# of chunk 0 ride the low Threefry lane alone) — comfortably above any
# serve-realistic SRHT sketch dimension.
_MAX_S_DIM = _HALF


# ---------------------------------------------------------------------------
# stream replication: host/XLA side (tiny per-chunk key table)
# ---------------------------------------------------------------------------


def fwht_key_table(key, n_chunks: int) -> jnp.ndarray:
    """(n_chunks, 6) uint32 table of the derived keys the kernel needs:
    cols 0:2 the sign stream's chunk key (sub-stream 0, ``Rademacher``
    — used directly, like ``pallas_hash``'s value stream), cols 2:4 /
    4:6 the coordinate stream's ``randint`` split pair (sub-stream 1,
    chunk 0 — one chunk covers the whole sample vector; the high key
    in 4:6 rides along unused because the span is a power of two).
    Exactly the keys ``randgen.stream_slice`` derives via
    ``fold_in(fold_in(subkey, hi), lo)`` (hi == 0 below 2³¹ chunks)
    and ``jax.random`` derives inside ``randint``. Traced and
    vmappable — the serve executable computes the whole cohort's
    tables inline."""
    import jax.random as jr

    dkey = jr.fold_in(key, 0)
    ikey = jr.fold_in(key, 1)
    ick = jr.fold_in(jr.fold_in(ikey, 0), 0)
    k_hi, k_lo = jr.split(ick)
    tail = jnp.concatenate(
        [jr.key_data(k_lo), jr.key_data(k_hi)]).astype(jnp.uint32)

    def one(c):
        dck = jr.fold_in(jr.fold_in(dkey, 0), c)
        return jnp.concatenate(
            [jr.key_data(dck).astype(jnp.uint32), tail])

    return jax.vmap(one)(jnp.arange(n_chunks, dtype=jnp.int32))


# ---------------------------------------------------------------------------
# in-kernel generation
# ---------------------------------------------------------------------------


def _row_bits(k0, k1, length: int):
    """uint32 draws for the leading ``length`` positions of one chunk,
    laid out (1, length): the same counter pairs (j, j + _HALF) as
    ``pallas_hash._chunk_bits`` — the cipher is elementwise in the
    counters, so the flat row layout carries identical values — kept
    as a single lane row because the consumer broadcasts against
    minor-axis-n operand tiles."""
    cw = min(length, _HALF)
    c = jax.lax.broadcasted_iota(jnp.uint32, (1, cw), 1)
    x0, x1 = tf.threefry2x32(k0, k1, c, c + _HALF)
    if length > _HALF:
        return jnp.concatenate([x0, x1], axis=1)
    return x0


def _gen_diag(keys_ref, base, n: int, n_chunks: int):
    """(1, n) ±1 f32 sign diagonal: sub-stream 0's leading n draws,
    bit-identical to ``FJLT.diagonal()``'s ``stream_slice``."""
    parts = []
    for c in range(n_chunks):
        parts.append(_row_bits(keys_ref[base + c, 0],
                               keys_ref[base + c, 1], min(n, CHUNK)))
    bits = parts[0] if n_chunks == 1 else jnp.concatenate(parts, axis=1)
    return tf.bits_to_rademacher(bits)


def _gen_idx(keys_ref, base, n: int, s_pad: int):
    """(1, s_pad) int32 sampled coordinates: sub-stream 1's leading
    draws through ``randint``'s modular map. The power-of-two span
    kills the double-draw multiplier, so only the low cipher runs;
    positions past the true s_dim carry real stream values that gather
    real rows — the wrapper slices them off."""
    lo = _row_bits(keys_ref[base, 2], keys_ref[base, 3], s_pad)
    return _mod_span(lo, n).astype(jnp.int32)


def _h128():
    """H_128 (Sylvester natural ordering) in-register: the entry at
    (i, j) is (−1)^popcount(i & j), a five-shift xor parity fold —
    cheaper than baking a 64 KiB constant into every program."""
    i = jax.lax.broadcasted_iota(jnp.int32, (_GEN_COLS, _GEN_COLS), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (_GEN_COLS, _GEN_COLS), 1)
    x = i & j
    for shift in (16, 8, 4, 2, 1):
        x = x ^ (x >> shift)
    return (1 - 2 * (x & 1)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _kernel(s_pad, n, n_chunks, m_tile, fut_scale, samp_scale,
            keys_ref, a_ref, out_ref):
    """One (batch lane, m-tile) grid step: out[b] (m_tile, s_pad) =
    samp_scale · gather(FWHT_n((fut_scale · D) ⊙ a[b]), idx) with the
    transform along the minor axis. Grid (B, m_tiles), both parallel —
    every step owns its whole output block."""
    b = pl.program_id(0)
    base = b * n_chunks
    D = _gen_diag(keys_ref, base, n, n_chunks)
    idx = _gen_idx(keys_ref, base, n, s_pad)

    # sign + 1/sqrt(n) fused into the load, the twin's op order:
    # (fut_scale * diag) * A
    W = (fut_scale * D) * a_ref[0]

    # H_n = (H_K ⊗ I_128)(I_K ⊗ H_128): inner factor as one MXU
    # contraction per 128-lane block...
    K = n // _GEN_COLS
    W = W.reshape(m_tile, K, _GEN_COLS)
    W = _hot_dot(W, _h128(), (((2,), (0,)), ((), ())))
    # ...outer factor as log2(K) butterfly stages over the block
    # index; the minor dimension stays 128 throughout.
    g = 1
    while g < K:
        Wr = W.reshape(m_tile, K // (2 * g), 2, g, _GEN_COLS)
        hi, lo = Wr[:, :, 0], Wr[:, :, 1]
        W = jnp.concatenate([hi + lo, hi - lo], axis=2).reshape(
            m_tile, K, _GEN_COLS)
        g *= 2

    # fused sample gather: 128 source rows per one-hot MXU dot; each
    # output coordinate meets exactly one nonzero across the loop, so
    # the accumulation is bit-equal to a true gather on finite data.
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (_GEN_COLS, s_pad), 0)

    def body(c, acc):
        wc = jax.lax.dynamic_slice(
            W, (0, c, 0), (m_tile, 1, _GEN_COLS)
        ).reshape(m_tile, _GEN_COLS)
        onehot = ((iota_l + c * _GEN_COLS) == idx).astype(jnp.float32)
        return acc + _hot_dot(wc, onehot, (((1,), (0,)), ((), ())))

    acc = jax.lax.fori_loop(
        0, K, body, jnp.zeros((m_tile, s_pad), jnp.float32))
    out_ref[:] = (samp_scale * acc)[None]


# ---------------------------------------------------------------------------
# planning + launch
# ---------------------------------------------------------------------------


def _pad_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _vmem_estimate(m_tile: int, n: int, s_pad: int) -> int:
    """Per-grid-step VMEM plan: double-buffered input tile, the
    working transform array plus one stage temporary, double-buffered
    output block plus the gather accumulator, the one-hot and H_128
    tiles, and the generated sign/coordinate rows with their cipher
    temporaries."""
    return 4 * (
        2 * m_tile * n
        + 2 * m_tile * n
        + 3 * m_tile * s_pad
        + _GEN_COLS * s_pad
        + _GEN_COLS * _GEN_COLS
        + 6 * n
        + 4 * s_pad
    )


def plan_tiles(n: int, m: int, s_dim: int,
               m_tile: Optional[int] = None) -> Optional[tuple]:
    """(m_pad, m_tile, s_pad) under the VMEM budget, or None when even
    the minimum tile doesn't fit — shrink-don't-fail, the same
    discipline as ``pallas_hash.plan_tiles``. The transform axis is
    NEVER padded: the FWHT length defines the operator."""
    s_pad = _pad_to(s_dim, _GEN_COLS)
    mt = m_tile or _DEFAULT_M_TILE
    mt = max(8, 1 << (max(int(mt), 8).bit_length() - 1))
    while mt > 8 and _vmem_estimate(mt, n, s_pad) > _VMEM_BUDGET_BYTES:
        mt //= 2
    if _vmem_estimate(mt, n, s_pad) > _VMEM_BUDGET_BYTES:
        return None
    m_pad = _pad_to(max(m, 8), mt)
    mt = min(mt, m_pad)
    while m_pad % mt:
        mt //= 2
    return m_pad, mt, s_pad


def qualify(s_dim: int, n: int, m: int, dtype,
            interpret: bool = False) -> tuple[bool, str]:
    """Host-side qualification: (ok, reason). The serve layer counts
    declined reasons (``serve.kernel_declined``) so operators can see
    WHY a replica is not on the fast path."""
    if not _HAVE_PALLAS:
        return False, "pallas unavailable"
    if not interpret and not available():
        return False, "backend is not a TPU (interpret-mode only here)"
    if jnp.dtype(dtype) != jnp.float32:
        return False, f"dtype {jnp.dtype(dtype).name} != float32"
    if s_dim < 1 or n < 1 or m < 1:
        return False, "degenerate shape"
    if n & (n - 1):
        return False, f"transform length {n} is not a power of two"
    if n < _GEN_COLS:
        return False, f"transform length {n} below one lane block"
    if s_dim > _MAX_S_DIM:
        return False, (f"s_dim {s_dim} exceeds one cipher sweep "
                       f"({_MAX_S_DIM})")
    if plan_tiles(n, m, s_dim) is None:
        return False, "no tile fits the VMEM budget"
    return True, "ok"


@functools.partial(
    jax.jit, static_argnames=("s_dim", "s_pad", "m_tile", "interpret"))
def _fwht_call(A, keys, *, s_dim, s_pad, m_tile, interpret):
    """One pallas_call over the stacked, rowwise-natural (B, m, n)
    operand (already padded along m). ``keys`` is the flattened
    (B * n_chunks, 6) key table."""
    B, m, n = A.shape
    n_chunks = max(1, n // CHUNK)
    fut_scale = 1.0 / math.sqrt(n)
    samp_scale = math.sqrt(n / s_dim)
    kern = functools.partial(_kernel, s_pad, n, n_chunks, m_tile,
                             fut_scale, samp_scale)
    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel"))
    return pl.pallas_call(
        kern,
        grid=(B, m // m_tile),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # whole key table
            pl.BlockSpec((1, m_tile, n), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m_tile, s_pad),
                               lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, m, s_pad), jnp.float32),
        compiler_params=params,
        interpret=interpret,
    )(keys, A)


def srht_apply_batched(key_data, A, *, s_dim: int, rowwise: bool,
                       m_tile: Optional[int] = None,
                       interpret: bool = False) -> jnp.ndarray:
    """Batched panel-free SRHT: one kernel over a stacked cohort.
    ``key_data`` (B, 2) uint32 raw keys (one transform per lane),
    ``A`` (B, n, m) columnwise / (B, m, n) rowwise — the same contract
    as :func:`fjlt.srht_serve_apply` per lane. The kernel is
    rowwise-natural (transform along the minor axis); columnwise
    cohorts transpose around it, which is exact. Fully traceable — the
    serve layer calls this inside its engine-compiled batched
    executable. Raises on unqualified input (callers gate on
    :func:`qualify` first); per-lane bits are capacity-invariant
    because every lane runs the same fixed-tile program."""
    import jax.random as jr

    A = jnp.asarray(A)
    kd = jnp.asarray(key_data, jnp.uint32)
    B = A.shape[0]
    n_axis = 2 if rowwise else 1
    n, m = A.shape[n_axis], A.shape[3 - n_axis]
    if n & (n - 1):
        raise ValueError(f"SRHT kernel requires power-of-2 n, got {n}")
    plan = plan_tiles(n, m, s_dim, m_tile)
    if plan is None:
        raise ValueError(f"no VMEM plan for s_dim={s_dim} n={n} m={m}")
    m_pad, mt, s_pad = plan
    if not rowwise:
        A = jnp.transpose(A, (0, 2, 1))
    if m_pad != m:
        A = jnp.pad(A, ((0, 0), (0, m_pad - m), (0, 0)))
    n_chunks = max(1, n // CHUNK)
    keys = jax.vmap(
        lambda k: fwht_key_table(jr.wrap_key_data(k), n_chunks))(kd)
    out = _fwht_call(A, keys.reshape(B * n_chunks, 6), s_dim=s_dim,
                     s_pad=s_pad, m_tile=mt, interpret=interpret)
    out = out[:, :m, :s_dim]
    return jnp.transpose(out, (0, 2, 1)) if not rowwise else out


def srht_apply(key_data, A, *, s_dim: int, rowwise: bool,
               m_tile: Optional[int] = None,
               interpret: bool = False) -> jnp.ndarray:
    """Single-request form: the batched kernel at B == 1 (bit-identical
    lanes either way)."""
    A = jnp.asarray(A)
    kd = jnp.asarray(key_data, jnp.uint32).reshape(1, 2)
    out = srht_apply_batched(kd, A[None], s_dim=s_dim, rowwise=rowwise,
                             m_tile=m_tile, interpret=interpret)
    return out[0]
