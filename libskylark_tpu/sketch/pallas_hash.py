"""Pallas TPU kernel: scatter-free CWT/CountSketch apply.

The hash sketch is the framework's cheapest transform — O(nnz) work, one
±1 multiply and one add per input coordinate — yet it was the LEAST
kernel-optimized: ``HashTransform.apply`` / ``hash.cwt_serve_apply`` are
``jax.ops.segment_sum`` scatters, which XLA lowers to a serialized
scatter-add on every backend (the TPU scatter unit retires one update
row at a time, so the MXU idles through the whole apply). Per the
FlashSketch sketch-kernel co-design line (PAPERS.md), this kernel
replaces the scatter with MXU work it can pipeline:

1. **On-the-fly stream generation.** The (h, v) bucket/value streams are
   regenerated in-kernel from the transform's raw Threefry key — the
   same discipline as ``pallas_dense._gen_block``, but replicating
   ``randgen.stream_slice``'s *chunk* format (jax.random's own
   fold_in/split/randint/rademacher pipeline, spelled out in the shared
   integer-op cipher of ``base/threefry.py``) so the kernel's streams
   are **bit-identical** to the XLA path's. The per-chunk derived keys
   (a handful of tiny fold_in/split ciphers) are precomputed by the
   traced wrapper into an SMEM table (:func:`chunk_key_table`); the
   per-entry work (one or two 2048-wide Threefry sweeps + the
   ``randint`` modular math + a sign map) runs in VMEM per grid step.

2. **Bucket-tiled one-hot contraction** (``accum="mxu"``, the TPU fast
   path): each 128-entry row of the generated chunk becomes a signed
   one-hot matrix ``Hv`` (s_dim × 128) contracted against the matching
   input rows on the MXU — the sketch *is* a matmul against a matrix the
   kernel never stores globally. f32 operands at ``Precision.HIGHEST``;
   the one-hot entries and ±1 values are exact, so only the contraction
   ORDER differs from the scatter — last-ulp differences on float data,
   bit-equal on any data whose bucket sums are exact (the lattice-valued
   battery in tests/test_pallas_hash.py pins the whole dataflow bitwise
   this way).

3. **Exact sequential accumulation** (``accum="exact"``): a fori_loop
   masked-broadcast add that reproduces the scatter's
   increasing-coordinate accumulation order term by term — **bit-equal
   to ``HashTransform.apply`` and ``cwt_serve_apply``** including
   zero-padded serve lanes (padded coordinates contribute exact ±0.0,
   which can never flip an accumulator bit). This is the interpret-mode
   correctness surface CPU tier-1 pins and the CI serve gate's
   bit-equality leg; it is VPU-serial over coordinates, so the
   autotuner never selects it for throughput (on TPU the mxu mode
   serves; on CPU the tuner correctly keeps XLA).

The batched entry point (:func:`cwt_apply_batched`) adds a leading
cohort dimension as a grid axis — one ``pallas_call`` flushes a whole
microbatch cohort (``engine/serve.py``) instead of vmap-of-XLA — with
the same shrink-don't-fail VMEM planning as ``pallas_dense._qualify``.
Lanes are computed independently at fixed tile sizes, so per-lane bits
are invariant to the capacity class, which is the serve layer's lane-
invariance contract.

Non-finite caveat: the scatter touches only bucket ``h[j]`` with row
``j``, while both kernel modes multiply every bucket by a 0/±1 mask —
``0 · inf = nan``, so a non-finite input coordinate poisons all buckets
of its output column, not just its own. Finite inputs are unaffected.

Like every kernel in this tree, dispatch DECLINES (returns None /
``qualify`` explains why) rather than failing: callers keep the XLA
scatter. Mosaic has no certified on-chip precedent for this kernel yet
(the bench tunnel is down — ROADMAP); until a live window certifies it,
only an explicit override or a measured plan-cache entry routes serve
traffic here, and a Mosaic rejection at compile time falls back.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.base import randgen
from libskylark_tpu.base import threefry as tf

try:  # same import seam as pallas_dense: non-TPU builds may lack pallas
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

from libskylark_tpu.sketch.pallas_dense import (_VMEM_BUDGET_BYTES,
                                                available)

# Stream chunk width — randgen's CHUNK is part of the stream format; the
# kernel's n-axis tile is one chunk (or a pow2 prefix of one).
CHUNK = randgen.CHUNK

# jax.random materializes a chunk's 32-bit draws as threefry2x32 over
# counter pairs (j, j + CHUNK//2): position j < half rides the cipher's
# first output lane, position j + half the second. Fixed by the format.
_HALF = CHUNK // 2

# Lane width of the in-kernel generation grid: chunk positions are laid
# out row-major over (rows, _GEN_COLS) so every Threefry/randint op is a
# native 2-D vector op (Mosaic has no 1-D iota).
_GEN_COLS = 128

# Default rows-per-grid-step of the non-contracted axis; shrunk (never
# failed) against the VMEM budget like pallas_dense's m-tile.
_DEFAULT_M_TILE = 256

_MODES = ("mxu", "exact")


# ---------------------------------------------------------------------------
# stream replication: host/XLA side (tiny per-chunk key table)
# ---------------------------------------------------------------------------


def chunk_key_table(key, n_chunks: int) -> jnp.ndarray:
    """(n_chunks, 6) uint32 table of the derived keys the kernel needs
    per stream chunk: the ``randint`` split pair for the bucket stream
    (sub-stream 0) and the chunk key for the value stream (sub-stream
    1). Exactly the keys ``randgen.stream_slice`` derives via
    ``fold_in(fold_in(subkey, hi), lo)`` (hi == 0 below 2³¹ chunks) and
    ``jax.random`` derives inside ``randint`` — a few 2-wide ciphers
    per chunk, traced and vmappable (the serve executable computes the
    whole cohort's tables inline)."""
    import jax.random as jr

    hkey = jr.fold_in(key, 0)
    vkey = jr.fold_in(key, 1)

    def one(c):
        hck = jr.fold_in(jr.fold_in(hkey, 0), c)
        k1, k2 = jr.split(hck)
        vck = jr.fold_in(jr.fold_in(vkey, 0), c)
        return jnp.concatenate([
            jr.key_data(k1), jr.key_data(k2), jr.key_data(vck),
        ]).astype(jnp.uint32)

    return jax.vmap(one)(jnp.arange(n_chunks, dtype=jnp.int32))


def _randint_multiplier(s_dim: int) -> int:
    """jax.random.randint's double-draw modular multiplier for span
    ``s_dim`` — static Python math. Zero exactly when 2¹⁶ % span == 0
    (every pow2 span ≤ 2¹⁶), where the high draw cancels and the
    kernel can skip its cipher."""
    m = (1 << 16) % s_dim
    return (m * m) % s_dim


# ---------------------------------------------------------------------------
# in-kernel generation
# ---------------------------------------------------------------------------


def _chunk_bits(k0, k1, rows: int, cols: int, both: bool):
    """uint32 draws for the leading ``rows*cols`` (× 2 when ``both``)
    positions of one chunk, row-major (rows, cols) — the
    ``random_bits(key, 32, (CHUNK,))`` layout: counter pairs
    (j, j + _HALF) with position j on the first cipher lane and
    position j + _HALF on the second."""
    c = (
        jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 0) * cols
        + jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 1)
    )
    x0, x1 = tf.threefry2x32(k0, k1, c, c + _HALF)
    if both:
        return jnp.concatenate([x0, x1], axis=0)
    return x0


def _gen_hv(keys_ref, kidx, s_dim: int, length: int, cols: int):
    """(h, v) for the leading ``length`` positions of chunk ``kidx`` of
    the key table, as row-major (length // cols, cols) grids — h the
    int32 bucket stream (``UniformInt(0, s_dim-1)``), v the ±1 f32
    value stream (``Rademacher``), both bit-identical to
    ``randgen.stream_slice`` (tests pin this through an identity-input
    apply)."""
    cipher_rows = min(length, _HALF) // cols
    both = length > _HALF
    mult = _randint_multiplier(s_dim)
    lo = _chunk_bits(keys_ref[kidx, 2], keys_ref[kidx, 3],
                     cipher_rows, cols, both)
    if mult == 0:
        mixed = _mod_span(lo, s_dim)
    else:
        hi = _chunk_bits(keys_ref[kidx, 0], keys_ref[kidx, 1],
                         cipher_rows, cols, both)
        mixed = _mod_span(
            _mod_span(hi, s_dim) * mult + _mod_span(lo, s_dim), s_dim)
    h = mixed.astype(jnp.int32)
    vbits = _chunk_bits(keys_ref[kidx, 4], keys_ref[kidx, 5],
                        cipher_rows, cols, both)
    v = tf.bits_to_rademacher(vbits)
    return h, v


def _mod_span(x, s_dim: int):
    """x % s_dim on uint32 — a lane mask for pow2 spans (the common
    serve case; Mosaic-native), the general remainder otherwise."""
    if s_dim & (s_dim - 1) == 0:
        return x & (s_dim - 1)
    return x % s_dim


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _mxu_rows(h, v, s_dim: int, cols: int, rows: int, contract):
    """Σ over generation rows of the signed-one-hot contraction:
    ``contract(Hv, r)`` supplies each row's dot against the matching
    input slice. The one-hot build is pure VPU compare/select; the
    contraction is the MXU's."""
    acc = None
    for r in range(rows):
        hr = h[r:r + 1, :]
        vr = v[r:r + 1, :]
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (s_dim, cols), 0)
                  == hr).astype(jnp.float32)
        part = contract(onehot * vr, r)
        acc = part if acc is None else acc + part
    return acc


def _hot_dot(lhs, rhs, dims):
    return jax.lax.dot_general(
        lhs, rhs, dims, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def _kernel_cw(s_dim, n_tile, n_chunks, cols, accum, keys_ref, a_ref,
               out_ref):
    """Columnwise: out[b] (s_dim, m_tile) += CWT over one chunk of
    a[b] (n_tile, m_tile). Grid (batch, m_tiles, n_chunks); the chunk
    axis is sequential (accumulation), batch/m parallel."""
    b = pl.program_id(0)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    h, v = _gen_hv(keys_ref, b * n_chunks + c, s_dim, n_tile, cols)
    if accum == "mxu":
        A = a_ref[0]

        def contract(hv, r):
            return _hot_dot(hv, A[r * cols:(r + 1) * cols, :],
                            (((1,), (0,)), ((), ())))

        out_ref[:] += _mxu_rows(h, v, s_dim, cols, n_tile // cols,
                                contract)[None]
    else:
        # exact scatter order: one coordinate at a time, increasing j —
        # the mask lanes contribute ±0.0, which never perturbs a sum
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (s_dim, 1), 0)

        def body(j, _):
            r = j // cols
            col = j % cols
            hj = jax.lax.dynamic_slice(h, (r, col), (1, 1))
            vj = jax.lax.dynamic_slice(v, (r, col), (1, 1))
            arow = a_ref[0, pl.ds(j, 1), :]
            mask = (iota_s == hj).astype(jnp.float32)
            out_ref[:] += (mask * (vj * arow))[None]
            return 0

        jax.lax.fori_loop(0, n_tile, body, 0)


def _kernel_rw(s_dim, n_tile, n_chunks, cols, accum, keys_ref, a_ref,
               out_ref):
    """Rowwise orientation: out[b] (m_tile, s_dim) += a[b] (m_tile,
    n_tile) · signed-one-hot."""
    b = pl.program_id(0)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    h, v = _gen_hv(keys_ref, b * n_chunks + c, s_dim, n_tile, cols)
    if accum == "mxu":
        A = a_ref[0]

        def contract(hv, r):
            return _hot_dot(A[:, r * cols:(r + 1) * cols], hv,
                            (((1,), (1,)), ((), ())))

        out_ref[:] += _mxu_rows(h, v, s_dim, cols, n_tile // cols,
                                contract)[None]
    else:
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (1, s_dim), 1)

        def body(j, _):
            r = j // cols
            col = j % cols
            hj = jax.lax.dynamic_slice(h, (r, col), (1, 1))
            vj = jax.lax.dynamic_slice(v, (r, col), (1, 1))
            acol = a_ref[0, :, pl.ds(j, 1)]
            mask = (iota_s == hj).astype(jnp.float32)
            out_ref[:] += (acol * (vj * mask))[None]
            return 0

        jax.lax.fori_loop(0, n_tile, body, 0)


# ---------------------------------------------------------------------------
# planning + launch
# ---------------------------------------------------------------------------


def _pad_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _padded_n(n: int) -> int:
    """Stream-axis extent the kernel runs at: next pow2 (min 8) below
    one chunk, else the next whole-chunk multiple. Zero-padding is
    exact — padded coordinates carry real stream values but multiply
    zero data."""
    if n <= 8:
        return 8
    if n < CHUNK:
        return 1 << (n - 1).bit_length()
    return _pad_to(n, CHUNK)


def _vmem_estimate(m_tile: int, s_dim: int, n_tile: int) -> int:
    """Per-grid-step VMEM plan: double-buffered input tile and output
    accumulator, the generated h/v grids and cipher temporaries
    (~6 chunk-sized u32/f32 arrays), and the (s_dim × _GEN_COLS)
    one-hot."""
    return 4 * (
        2 * n_tile * m_tile
        + 2 * s_dim * m_tile
        + 6 * n_tile
        + 2 * s_dim * _GEN_COLS
    )


def plan_tiles(n: int, m: int, s_dim: int,
               m_tile: Optional[int] = None) -> Optional[tuple]:
    """(n_pad, n_tile, m_pad, m_tile) under the VMEM budget, or None
    when even the minimum tile doesn't fit — shrink-don't-fail, the
    same discipline as ``pallas_dense._qualify``."""
    n_pad = _padded_n(n)
    n_tile = min(n_pad, CHUNK)
    mt = m_tile or _DEFAULT_M_TILE
    mt = max(8, 1 << (max(int(mt), 8).bit_length() - 1))
    while mt > 8 and _vmem_estimate(mt, s_dim, n_tile) > _VMEM_BUDGET_BYTES:
        mt //= 2
    if _vmem_estimate(mt, s_dim, n_tile) > _VMEM_BUDGET_BYTES:
        return None
    m_pad = _pad_to(max(m, 8), mt)
    mt = min(mt, m_pad)
    while m_pad % mt:
        mt //= 2
    return n_pad, n_tile, m_pad, mt


def qualify(s_dim: int, n: int, m: int, dtype,
            interpret: bool = False,
            accum: str = "mxu") -> tuple[bool, str]:
    """Host-side qualification: (ok, reason). The serve layer counts
    declined reasons (``serve.kernel_declined``) so operators can see
    WHY a replica is not on the fast path."""
    if accum not in _MODES:
        return False, f"unknown accum mode {accum!r}"
    if not _HAVE_PALLAS:
        return False, "pallas unavailable"
    if not interpret and not available():
        return False, "backend is not a TPU (interpret-mode only here)"
    if jnp.dtype(dtype) != jnp.float32:
        return False, f"dtype {jnp.dtype(dtype).name} != float32"
    if s_dim < 1 or n < 1 or m < 1:
        return False, "degenerate shape"
    if plan_tiles(n, m, s_dim) is None:
        return False, "no tile fits the VMEM budget"
    return True, "ok"


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "rowwise", "accum", "m_tile", "interpret"),
)
def _hash_call(A, keys, *, s_dim, rowwise, accum, m_tile, interpret):
    """One pallas_call over the stacked (B, ...) operand (already
    padded). ``keys`` is the flattened (B * n_chunks, 6) chunk-key
    table."""
    B = A.shape[0]
    n = A.shape[2] if rowwise else A.shape[1]
    m = A.shape[1] if rowwise else A.shape[2]
    n_tile = min(n, CHUNK)
    n_chunks = n // n_tile
    cols = min(n_tile, _GEN_COLS)
    grid = (B, m // m_tile, n_chunks)
    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    if rowwise:
        kern = functools.partial(_kernel_rw, s_dim, n_tile, n_chunks,
                                 cols, accum)
        a_spec = pl.BlockSpec((1, m_tile, n_tile),
                              lambda b, i, c: (b, i, c),
                              memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((1, m_tile, s_dim),
                                lambda b, i, c: (b, i, 0),
                                memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((B, m, s_dim), jnp.float32)
    else:
        kern = functools.partial(_kernel_cw, s_dim, n_tile, n_chunks,
                                 cols, accum)
        a_spec = pl.BlockSpec((1, n_tile, m_tile),
                              lambda b, i, c: (b, c, i),
                              memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((1, s_dim, m_tile),
                                lambda b, i, c: (b, 0, i),
                                memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((B, s_dim, m), jnp.float32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # whole key table
            a_spec,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        compiler_params=params,
        interpret=interpret,
    )(keys, A)


def cwt_apply_batched(key_data, A, *, s_dim: int, rowwise: bool,
                      accum: str = "mxu",
                      m_tile: Optional[int] = None,
                      interpret: bool = False) -> jnp.ndarray:
    """Batched scatter-free CountSketch: one kernel over a stacked
    cohort. ``key_data`` (B, 2) uint32 raw keys (one transform per
    lane), ``A`` (B, n, m) columnwise / (B, m, n) rowwise. Fully
    traceable — the serve layer calls this inside its engine-compiled
    batched executable. Raises on unqualified input (callers gate on
    :func:`qualify` first); per-lane bits are capacity-invariant
    because every lane runs the same fixed-tile program."""
    import jax.random as jr

    if accum not in _MODES:
        raise ValueError(f"accum must be one of {_MODES}, got {accum!r}")
    A = jnp.asarray(A)
    kd = jnp.asarray(key_data, jnp.uint32)
    B = A.shape[0]
    n_axis = 2 if rowwise else 1
    n, m = A.shape[n_axis], A.shape[3 - n_axis]
    plan = plan_tiles(n, m, s_dim, m_tile)
    if plan is None:
        raise ValueError(
            f"no VMEM plan for s_dim={s_dim} n={n} m={m}")
    n_pad, n_tile, m_pad, mt = plan
    pads = [(0, 0), (0, 0), (0, 0)]
    pads[n_axis] = (0, n_pad - n)
    pads[3 - n_axis] = (0, m_pad - m)
    Ap = jnp.pad(A, pads) if (n_pad != n or m_pad != m) else A
    n_chunks = n_pad // n_tile
    keys = jax.vmap(
        lambda k: chunk_key_table(jr.wrap_key_data(k), n_chunks))(kd)
    out = _hash_call(Ap, keys.reshape(B * n_chunks, 6), s_dim=s_dim,
                     rowwise=rowwise, accum=accum, m_tile=mt,
                     interpret=interpret)
    return out[:, :m, :] if rowwise else out[:, :, :m]


def cwt_apply(key_data, A, *, s_dim: int, rowwise: bool,
              accum: str = "mxu", m_tile: Optional[int] = None,
              interpret: bool = False) -> jnp.ndarray:
    """Single-request form: the batched kernel at B == 1 (bit-identical
    lanes either way). Same contract as ``hash.cwt_serve_apply`` —
    zero-padding the operand past the transform's true N leaves the
    result bit-equal (``accum="exact"``) / ulp-close (``"mxu"``)."""
    A = jnp.asarray(A)
    kd = jnp.asarray(key_data, jnp.uint32).reshape(1, 2)
    out = cwt_apply_batched(kd, A[None], s_dim=s_dim, rowwise=rowwise,
                            accum=accum, m_tile=m_tile,
                            interpret=interpret)
    return out[0]


def try_apply(transform, A, *, rowwise: bool) -> Optional[jnp.ndarray]:
    """Direct-apply dispatch hook for ``HashTransform``: run the kernel
    when (a) it's a CWT on a qualifying f32 single-device operand on a
    TPU backend, and (b) an explicit override (``SKYLARK_HASH_KERNEL``
    = pallas | pallas_exact) or a measured plan-cache entry picks it.
    Returns None to decline — the caller keeps the XLA scatter. The
    conservative default (no plan, no override → decline) matches the
    module's not-yet-on-chip-certified status."""
    from libskylark_tpu.base import env as _env
    from libskylark_tpu.sketch import params as sketch_params

    if type(transform).__name__ != "CWT":
        return None
    if not sketch_params.get_use_pallas():
        return None
    from libskylark_tpu.sketch.dense import pallas_ambient_ok

    if not pallas_ambient_ok(A):
        return None
    accum = None
    env = _env.HASH_KERNEL.raw()
    if env is not None:
        env = env.strip().lower()
        if env in ("pallas", "mxu", "1"):
            accum = "mxu"
        elif env in ("pallas_exact", "exact"):
            accum = "exact"
        else:
            return None  # explicit xla/off
    elif sketch_params.get_use_plan_cache():
        try:
            from libskylark_tpu import tune

            w = tune.hash_workload(
                "CWT", A.shape, A.dtype, transform.sketch_dim,
                seq_axis=1 if rowwise else 0)
            plan = tune.plan_for(w)
        except Exception:
            plan = None
        if plan is not None and plan.backend == "pallas":
            accum = "mxu"
    if accum is None:
        return None
    n = A.shape[1] if rowwise else A.shape[0]
    m = A.shape[0] if rowwise else A.shape[1]
    ok, _why = qualify(transform.sketch_dim, n, m, A.dtype)
    if not ok:
        return None
    import numpy as np

    kd = np.asarray(jax.random.key_data(transform.allocation.key),
                    dtype=np.uint32)
    try:
        return cwt_apply(kd, A, s_dim=transform.sketch_dim,
                         rowwise=rowwise, accum=accum)
    except Exception:  # noqa: BLE001 — decline, don't fail (module
        # contract): Mosaic rejects as JaxRuntimeError, the Pallas
        # lowering rules as trace-time NotImplementedError /
        # LoweringError — all mean "keep the XLA scatter"
        return None
