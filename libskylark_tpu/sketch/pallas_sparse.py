"""Pallas TPU kernel: scatter-free sparse (CSR-lane) CountSketch.

The sparse serve path's XLA program (:mod:`libskylark_tpu.sketch
.sparse_serve`) is an O(nnz) ``scatter-add`` — on TPU the scatter unit
retires one update row at a time, so even at 0.1% density the MXU
idles through the whole flush. Per the FlashSketch sketch-kernel
co-design line (PAPERS.md), this kernel restates the sparse CountSketch
as MXU work over the nonzeros only:

1. **In-kernel stream regeneration** — the (h, v) bucket/value streams
   are rebuilt from the transform's raw Threefry key with the exact
   r12 discipline (:mod:`libskylark_tpu.sketch.pallas_hash`'s
   ``chunk_key_table`` + ``_gen_hv``: per-chunk fold_in/split key table
   in SMEM, 2048-wide Threefry sweeps + ``randint`` modular math in
   VMEM), bit-identical to ``randgen.stream_slice``.

2. **Gather-on-coordinates** — the generated streams are gathered at
   the lane's nonzero coordinates (``h[rows]``/``v[rows]`` columnwise,
   ``h[cols]``/``v[cols]`` rowwise): O(nnz) stream reads instead of the
   dense kernel's O(N) sweep.

3. **Bucket-tiled one-hot MXU contraction** (``accum="mxu"``) — each
   128-nonzero tile becomes two one-hot factors: a signed bucket
   one-hot ``Hv`` (s_dim × 128, carrying v·val) and a coordinate
   one-hot (128 × m), contracted on the MXU at ``Precision.HIGHEST``.
   The one-hots are exact, so only the contraction ORDER differs from
   the scatter — last-ulp on float data, bit-equal on lattice data
   (the test battery pins the dataflow this way).

4. **Exact sequential accumulation** (``accum="exact"``) — a fori_loop
   masked outer-product add reproducing the scatter's CSR row-major
   accumulation order term by term: **bit-equal to
   ``sparse_serve.cwt_sparse_serve_apply``** (and therefore to the
   dense reference — docs/serving) including padded lane entries,
   whose 0.0 values contribute exact ±0.0.

Dispatch: :func:`qualify` **declines on CPU** — unlike the dense-lane
``pallas_hash`` exact mode, interpret-mode execution of this kernel has
no role on the serve hot path (the XLA scatter IS already the exact
reference there), so off-TPU the serve layer's qualification keeps the
scatter and the tune ladder's interpret penalty certifies XLA. Tests
exercise the kernel directly with ``interpret=True``. On TPU, routing
is autotuned per (bucket, capacity, nnz class) through the serve ladder
(``tune._serve_candidates`` / ``cost._sparse_lane_cost``) and certified
by ``bench.py --certify-kernels``; Mosaic compile-time rejection
declines back to XLA (the serve layer's poison-for-the-fingerprint-era
rule), never fails a request.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.sketch.pallas_dense import (_VMEM_BUDGET_BYTES,
                                                available)
from libskylark_tpu.sketch.pallas_hash import (CHUNK, _GEN_COLS,
                                               _MODES, _gen_hv,
                                               _padded_n,
                                               chunk_key_table)

try:  # same import seam as pallas_dense: non-TPU builds may lack pallas
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# nonzeros contracted per one-hot MXU tile (the lane width of the
# bucket-tiled contraction)
NNZ_TILE = 128


# ---------------------------------------------------------------------------
# planning + qualification
# ---------------------------------------------------------------------------


def _vmem_estimate(s_dim: int, n_stream: int, m: int,
                   nnz_pad: int) -> int:
    """Per-lane VMEM plan: the three CSR lane arrays, the regenerated
    h/v streams (plus ~6 chunk-sized cipher temporaries), the output
    accumulator, and the two one-hot tile factors."""
    n_tile = min(n_stream, CHUNK)
    return 4 * (
        3 * nnz_pad
        + 2 * n_stream
        + 6 * n_tile
        + s_dim * m
        + s_dim * NNZ_TILE
        + NNZ_TILE * m
    )


def qualify(s_dim: int, n: int, m: int, nnz: int, dtype,
            interpret: bool = False,
            accum: str = "mxu") -> tuple[bool, str]:
    """Host-side qualification: (ok, reason). Declines on CPU even in
    interpret mode (module doc — the XLA scatter already serves the
    exact surface there); the serve layer counts the reasons in its
    ``by_reason`` decline labels."""
    if accum not in _MODES:
        return False, f"unknown accum mode {accum!r}"
    if not _HAVE_PALLAS:
        return False, "pallas unavailable"
    if interpret or not available():
        return False, ("backend is not a TPU (sparse kernel has no "
                       "interpret-mode serve surface — xla scatter "
                       "serves)")
    if jnp.dtype(dtype) != jnp.float32:
        return False, f"dtype {jnp.dtype(dtype).name} != float32"
    if s_dim < 1 or n < 1 or m < 1 or nnz < 1:
        return False, "degenerate shape"
    if _vmem_estimate(s_dim, _padded_n(n), m,
                      _pad_nnz(nnz)) > _VMEM_BUDGET_BYTES:
        return False, "lane does not fit the VMEM budget"
    return True, "ok"


def _pad_nnz(nnz: int) -> int:
    return -(-max(int(nnz), 1) // NNZ_TILE) * NNZ_TILE


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _gen_streams(keys_ref, b, s_dim: int, n_stream: int):
    """Flat (n_stream,) h/v streams for lane ``b`` — the Python loop
    over the (static) chunk count concatenates the per-chunk 2-D
    generation grids; bit-identical to ``randgen.stream_slice`` via
    the shared ``_gen_hv`` cipher."""
    n_tile = min(n_stream, CHUNK)
    n_chunks = n_stream // n_tile
    cols = min(n_tile, _GEN_COLS)
    hs, vs = [], []
    for c in range(n_chunks):
        h, v = _gen_hv(keys_ref, b * n_chunks + c, s_dim, n_tile, cols)
        hs.append(h.reshape(-1))
        vs.append(v.reshape(-1))
    if n_chunks == 1:
        return hs[0], vs[0]
    return jnp.concatenate(hs), jnp.concatenate(vs)


def _kernel_sparse(s_dim, n_stream, m, nnz_pad, rowwise, accum,
                   keys_ref, data_ref, rows_ref, cols_ref, out_ref):
    """One lane's sparse CountSketch. Columnwise: out (s_dim, m) with
    buckets gathered at the row coordinate; rowwise: out (m, s_dim)
    with buckets gathered at the column coordinate."""
    b = pl.program_id(0)
    h, v = _gen_streams(keys_ref, b, s_dim, n_stream)
    data = data_ref[0]
    rows = rows_ref[0]
    cols = cols_ref[0]
    hashed = cols if rowwise else rows
    kept = rows if rowwise else cols
    hj = h[hashed]
    vj = v[hashed] * data
    if accum == "mxu":
        acc = None
        for t in range(nnz_pad // NNZ_TILE):
            sl = slice(t * NNZ_TILE, (t + 1) * NNZ_TILE)
            ht, vt, kt = hj[sl], vj[sl], kept[sl]
            onehot_b = (jax.lax.broadcasted_iota(
                jnp.int32, (s_dim, NNZ_TILE), 0) == ht[None, :])
            hv = onehot_b.astype(jnp.float32) * vt[None, :]
            onehot_k = (kt[:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (NNZ_TILE, m), 1)).astype(jnp.float32)
            part = jax.lax.dot_general(
                hv, onehot_k, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
            if rowwise:
                part = part.T
            acc = part if acc is None else acc + part
        out_ref[:] = acc[None]
    else:
        # exact scatter order: one nonzero at a time in CSR row-major
        # order — the masked lanes contribute ±0.0, which never
        # perturbs a sum
        out_ref[:] = jnp.zeros_like(out_ref)
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (s_dim, 1), 0)
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)

        def body(j, _):
            hjj = jax.lax.dynamic_slice(hj, (j,), (1,))[0]
            vjj = jax.lax.dynamic_slice(vj, (j,), (1,))[0]
            kjj = jax.lax.dynamic_slice(kept, (j,), (1,))[0]
            mask_s = (iota_s == hjj).astype(jnp.float32)
            mask_m = (iota_m == kjj).astype(jnp.float32)
            upd = mask_s * (vjj * mask_m)
            out_ref[:] += (upd.T if rowwise else upd)[None]
            return 0

        jax.lax.fori_loop(0, nnz_pad, body, 0)


# ---------------------------------------------------------------------------
# launch
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("s_dim", "n_stream", "m", "rowwise", "accum",
                     "interpret"),
)
def _sparse_call(keys, data, rows, cols, *, s_dim, n_stream, m,
                 rowwise, accum, interpret):
    B, nnz_pad = data.shape
    out_shape = ((B, m, s_dim) if rowwise else (B, s_dim, m))
    kern = functools.partial(_kernel_sparse, s_dim, n_stream, m,
                             nnz_pad, rowwise, accum)
    lane = pl.BlockSpec((1, nnz_pad), lambda b: (b, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # whole key table
            lane, lane, lane,
        ],
        out_specs=pl.BlockSpec(
            (1,) + out_shape[1:], lambda b: (b, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(keys, data, rows, cols)


def cwt_sparse_apply_batched(key_data, data, rows, cols, *, s_dim: int,
                             rowwise: bool, shape: tuple,
                             accum: str = "mxu",
                             interpret: bool = False) -> jnp.ndarray:
    """Batched scatter-free sparse CountSketch: one kernel over a
    stacked CSR-lane cohort. ``key_data`` (B, 2) uint32 raw keys,
    ``data``/``rows``/``cols`` (B, nnz_pad) value / row-id / column-id
    lanes (row ids pre-expanded from the indptr lanes —
    ``sparse_serve.csr_row_ids``), ``shape`` the padded (rows, cols)
    lane class. Fully traceable — the serve flush builder calls this
    inside its engine-compiled batched executable. Per-lane bits are
    capacity-invariant: every lane runs the same fixed-tile program."""
    import jax.random as jr

    if accum not in _MODES:
        raise ValueError(f"accum must be one of {_MODES}, got {accum!r}")
    data = jnp.asarray(data, jnp.float32)
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    kd = jnp.asarray(key_data, jnp.uint32)
    B, nnz = data.shape
    n_rows, n_cols = int(shape[0]), int(shape[1])
    n = n_cols if rowwise else n_rows
    m = n_rows if rowwise else n_cols
    n_stream = _padded_n(n)
    nnz_pad = _pad_nnz(nnz)
    if nnz_pad != nnz:
        padw = ((0, 0), (0, nnz_pad - nnz))
        data = jnp.pad(data, padw)      # 0.0 values: exact no-ops
        rows = jnp.pad(rows, padw)
        cols = jnp.pad(cols, padw)
    n_tile = min(n_stream, CHUNK)
    n_chunks = n_stream // n_tile
    keys = jax.vmap(
        lambda k: chunk_key_table(jr.wrap_key_data(k), n_chunks))(kd)
    return _sparse_call(keys.reshape(B * n_chunks, 6), data, rows, cols,
                        s_dim=s_dim, n_stream=n_stream, m=m,
                        rowwise=rowwise, accum=accum,
                        interpret=interpret)


def cwt_sparse_apply(key_data, data, rows, cols, *, s_dim: int,
                     rowwise: bool, shape: tuple, accum: str = "mxu",
                     interpret: bool = False) -> jnp.ndarray:
    """Single-request form: the batched kernel at B == 1 (bit-identical
    lanes either way). Same contract as
    ``sparse_serve.cwt_sparse_serve_apply`` under ``accum="exact"``."""
    kd = jnp.asarray(key_data, jnp.uint32).reshape(1, 2)
    out = cwt_sparse_apply_batched(
        kd, jnp.asarray(data)[None], jnp.asarray(rows)[None],
        jnp.asarray(cols)[None], s_dim=s_dim, rowwise=rowwise,
        shape=shape, accum=accum, interpret=interpret)
    return out[0]
