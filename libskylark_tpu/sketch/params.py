"""Global sketch tuning knobs (ref: sketch/sketch_params.hpp:15-36).

``blocksize`` — column-panel width for memory-bounded dense apply (0 disables
blocking: "better performance, much more memory", ref comment). The reference
default is 1000 columns; we default to 0 (unblocked) because XLA fuses
generation into the matmul and HBM is large — callers with huge N opt in.

``factor`` — regime-selection threshold for distributed apply
(ref: sketch/sketch_params.hpp:19).
"""

from libskylark_tpu.base import env as _env

_blocksize = 0
_factor = 20


def get_blocksize() -> int:
    return _blocksize


def set_blocksize(b: int) -> None:
    global _blocksize
    _blocksize = int(b)


def get_factor() -> int:
    return _factor


def set_factor(f: int) -> None:
    global _factor
    _factor = int(f)


# ``auto_block_bytes`` — even with ``blocksize`` unset (0), a dense apply
# whose full virtual operator would exceed this many bytes switches to
# the panel-blocked path automatically (the memory-safety default the
# reference gets from blocksize=1000; our default unblocked mode is the
# fast path for everything that fits comfortably in HBM).
_auto_block_bytes = 2 << 30  # 2 GiB


def get_auto_block_bytes() -> int:
    return _auto_block_bytes


def set_auto_block_bytes(b: int) -> None:
    b = int(b)
    if b <= 0:
        raise ValueError(f"auto_block_bytes must be positive, got {b}")
    global _auto_block_bytes
    _auto_block_bytes = b


# ``use_pallas`` — route dense-transform applies through the fused Pallas
# TPU kernel (sketch/pallas_dense.py) when the input/backend qualify. The
# sketch operator entries are bit-exact either way; only the contraction
# precision differs (see ``pallas_precision``).
_use_pallas = True


def get_use_pallas() -> bool:
    return _use_pallas


def set_use_pallas(on: bool) -> None:
    global _use_pallas
    _use_pallas = bool(on)


# ``use_plan_cache`` — consult the persistent autotuner plan cache
# (libskylark_tpu/tune/) at dispatch time, BEFORE the heuristic
# defaults below. Precedence at every dispatch site: explicit call-site
# argument > explicit user override (env SKYLARK_PALLAS_MTILE /
# set_pallas_m_tile / set_pallas_precision — a sweep or a pin must beat
# a cached winner) > cached plan > heuristic default. Disabled entirely
# with SKYLARK_USE_PLAN_CACHE=0 (or set_use_plan_cache(False)); the
# cache file location is SKYLARK_PLAN_CACHE (tune/cache.py).
_use_plan_cache = _env.USE_PLAN_CACHE.get()


def get_use_plan_cache() -> bool:
    return _use_plan_cache


def set_use_plan_cache(on: bool) -> None:
    global _use_plan_cache
    _use_plan_cache = bool(on)


# ``pallas_precision`` — contraction regime inside the fused kernel.
# "bf16x3" (default): 3-pass error-compensated bf16 split — f32-grade
# rounding at roughly twice the MXU rate of full-f32 passes;
# oracle-certified ON CHIP against the XLA path at 1e-4
# (tests/test_pallas_dense.py::test_fused_on_chip_matches_xla,
# benchmarks/tpu_validation_r03.txt — the certification the r2 plan
# required before making it the default). "f32": full-f32 passes
# (Precision.HIGHEST), the conservative regime. "bf16": single-pass bf16
# inputs + f32 accumulation — fastest, but rounds the contraction at
# ~2⁻⁸ relative (outside the oracle for large N); throughput-only work
# opts in explicitly. "bf16gen2": the OPERATOR is defined as
# scale × bf16-rounding of the UNIT generated stream — rounding applies
# to the unit-variance entries before the f32 scale multiply
# (statistically equivalent sketch — a Gaussian rounded at 2⁻⁸ keeps
# its JL guarantee; deterministic and seed-reproducible like every
# regime) — and only the DATA side is
# error-compensated (hi/lo, 2 passes): f32-grade accuracy w.r.t. that
# operator at 2/3 the MXU passes of bf16x3 (pass-count ceiling 216 vs
# 144 GB/s on the headline config). Because its operator VALUES differ
# from the f32 stream at ~2⁻⁸, it is strictly opt-in and its oracle
# compares against an XLA apply of the SAME rounded operator
# (tests/test_pallas_dense.py).
_PALLAS_PRECISION_DEFAULT = "bf16x3"
_pallas_precision = _PALLAS_PRECISION_DEFAULT


def get_pallas_precision() -> str:
    return _pallas_precision


def pallas_precision_overridden() -> bool:
    """True when the runtime regime differs from the shipping default —
    an explicit pin beats a cached plan's precision (``use_plan_cache``
    precedence). A pin whose value EQUALS the default is
    indistinguishable and not detected (the same documented limit as
    base/precision.ambient_precision_pinned_by_user; such callers pass
    ``precision=`` at the call site, which always wins)."""
    return _pallas_precision != _PALLAS_PRECISION_DEFAULT


def set_pallas_precision(p: str) -> None:
    if p not in ("f32", "bf16x3", "bf16", "bf16gen2"):
        raise ValueError(
            "pallas_precision must be 'f32', 'bf16x3', 'bf16' or "
            f"'bf16gen2', got {p!r}"
        )
    global _pallas_precision
    _pallas_precision = p


# ``pallas_m_tile`` — rows of A per fused-kernel grid step. Larger tiles
# amortize operator generation over more MXU work at the cost of VMEM:
# each grid sweep regenerates the whole virtual operator on the VPU
# (Threefry + inverse-CDF ≈ 50 ops/entry), so at the headline config the
# generation bill is ~m/m_tile × 0.1 ms/MB — the dominant non-MXU cost
# (r2 on-chip numbers). 512 halves it vs 256 while keeping the VMEM plan
# (_vmem_estimate) ≈ 9 MiB at s_dim=1024, inside the 16 MiB budget;
# _qualify still shrinks per-call when s_dim is larger. Seeded from
# SKYLARK_PALLAS_MTILE for on-chip sweeps without code changes; invalid
# values fall back to the default.
_PALLAS_M_TILE_DEFAULT = 512


def _env_m_tile() -> int:
    v = _env.PALLAS_MTILE.get(_PALLAS_M_TILE_DEFAULT)
    return v if v >= 8 else _PALLAS_M_TILE_DEFAULT


_pallas_m_tile = _env_m_tile()


def get_pallas_m_tile() -> int:
    return _pallas_m_tile


def pallas_m_tile_overridden() -> bool:
    """True when the user set the tile explicitly — a one-shot
    SKYLARK_PALLAS_MTILE (valid value; a typo degrades to the default
    INCLUDING cache consultation) or a runtime set_pallas_m_tile away
    from the shipping default. An on-chip sweep's env override must
    beat a cached winner or the sweep can't explore."""
    if _pallas_m_tile != _PALLAS_M_TILE_DEFAULT:
        return True
    v = _env.PALLAS_MTILE.get()
    return v is not None and v >= 8


def set_pallas_m_tile(t: int) -> None:
    t = int(t)
    if t < 8:
        raise ValueError(f"pallas_m_tile must be >= 8, got {t}")
    global _pallas_m_tile
    _pallas_m_tile = t


# ``auto_materialize`` — automatic materialize-and-reuse dispatch for
# OperatorCache transforms: the Nth EAGER apply of one transform
# instance pins its operator in device memory (jit-traced applies never
# count — a trace runs once). The steady-state-serving complement of the
# virtual-operator default: one-shot sketches keep paying zero HBM,
# repeated applies amortize generation to zero automatically. Bounded by
# ``auto_materialize_bytes`` so huge operators (which the blocked apply
# exists for) never pin. Auto-pinning only ever happens where the
# materialized apply is the SAME contraction as the virtual one (the
# plain XLA path); applies that route through the fused TPU kernel are
# never auto-switched — the kernel's bf16x3/accumulation-order numerics
# differ from a cached gemm, and the Nth eager apply must not silently
# change results vs the first (OperatorCache._materialize_changes_numerics;
# explicit materialize() remains the visible way to choose the cached
# regime on TPU). SKYLARK_AUTO_MATERIALIZE=0 disables the dispatch.
_auto_materialize = _env.AUTO_MATERIALIZE.get()
_auto_materialize_after = 3
_auto_materialize_bytes = 64 * 1024 * 1024


def get_auto_materialize() -> bool:
    return _auto_materialize


def set_auto_materialize(on: bool) -> None:
    global _auto_materialize
    _auto_materialize = bool(on)


def get_auto_materialize_after() -> int:
    return _auto_materialize_after


def set_auto_materialize_after(n: int) -> None:
    n = int(n)
    if n < 1:
        raise ValueError(f"auto_materialize_after must be >= 1, got {n}")
    global _auto_materialize_after
    _auto_materialize_after = n


def get_auto_materialize_bytes() -> int:
    return _auto_materialize_bytes


def set_auto_materialize_bytes(b: int) -> None:
    b = int(b)
    if b <= 0:
        raise ValueError(
            f"auto_materialize_bytes must be > 0, got {b} "
            "(use set_auto_materialize(False) to disable the dispatch)")
    global _auto_materialize_bytes
    _auto_materialize_bytes = b
