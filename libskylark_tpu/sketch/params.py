"""Global sketch tuning knobs (ref: sketch/sketch_params.hpp:15-36).

``blocksize`` — column-panel width for memory-bounded dense apply (0 disables
blocking: "better performance, much more memory", ref comment). The reference
default is 1000 columns; we default to 0 (unblocked) because XLA fuses
generation into the matmul and HBM is large — callers with huge N opt in.

``factor`` — regime-selection threshold for distributed apply
(ref: sketch/sketch_params.hpp:19).
"""

_blocksize = 0
_factor = 20


def get_blocksize() -> int:
    return _blocksize


def set_blocksize(b: int) -> None:
    global _blocksize
    _blocksize = int(b)


def get_factor() -> int:
    return _factor


def set_factor(f: int) -> None:
    global _factor
    _factor = int(f)


# ``use_pallas`` — route dense-transform applies through the fused Pallas
# TPU kernel (sketch/pallas_dense.py) when the input/backend qualify. On
# TPU the contraction then runs at MXU-native precision (bf16 inputs, f32
# accumulate — identical to XLA's DEFAULT matmul precision); the sketch
# operator entries are bit-exact either way.
_use_pallas = True


def get_use_pallas() -> bool:
    return _use_pallas


def set_use_pallas(on: bool) -> None:
    global _use_pallas
    _use_pallas = bool(on)
