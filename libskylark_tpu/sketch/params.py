"""Global sketch tuning knobs (ref: sketch/sketch_params.hpp:15-36).

``blocksize`` — column-panel width for memory-bounded dense apply (0 disables
blocking: "better performance, much more memory", ref comment). The reference
default is 1000 columns; we default to 0 (unblocked) because XLA fuses
generation into the matmul and HBM is large — callers with huge N opt in.

``factor`` — regime-selection threshold for distributed apply
(ref: sketch/sketch_params.hpp:19).
"""

_blocksize = 0
_factor = 20


def get_blocksize() -> int:
    return _blocksize


def set_blocksize(b: int) -> None:
    global _blocksize
    _blocksize = int(b)


def get_factor() -> int:
    return _factor


def set_factor(f: int) -> None:
    global _factor
    _factor = int(f)


# ``auto_block_bytes`` — even with ``blocksize`` unset (0), a dense apply
# whose full virtual operator would exceed this many bytes switches to
# the panel-blocked path automatically (the memory-safety default the
# reference gets from blocksize=1000; our default unblocked mode is the
# fast path for everything that fits comfortably in HBM).
_auto_block_bytes = 2 << 30  # 2 GiB


def get_auto_block_bytes() -> int:
    return _auto_block_bytes


def set_auto_block_bytes(b: int) -> None:
    b = int(b)
    if b <= 0:
        raise ValueError(f"auto_block_bytes must be positive, got {b}")
    global _auto_block_bytes
    _auto_block_bytes = b


# ``use_pallas`` — route dense-transform applies through the fused Pallas
# TPU kernel (sketch/pallas_dense.py) when the input/backend qualify. The
# sketch operator entries are bit-exact either way; only the contraction
# precision differs (see ``pallas_precision``).
_use_pallas = True


def get_use_pallas() -> bool:
    return _use_pallas


def set_use_pallas(on: bool) -> None:
    global _use_pallas
    _use_pallas = bool(on)


# ``pallas_precision`` — contraction regime inside the fused kernel.
# "f32" (default): full-f32 MXU passes (Precision.HIGHEST); the fused
# apply stays within the framework's 1e-4 determinism oracle vs the XLA
# path. "bf16x3": 3-pass bf16 (Precision.HIGH) — f32-grade rounding at
# roughly half the cost, pending on-chip oracle validation
# (tests/test_pallas_dense.py::test_fused_on_chip_*). "bf16": single-pass
# bf16 inputs + f32 accumulation — fastest, but rounds the contraction at
# ~2⁻⁸ relative (outside the oracle for large N); throughput-only work
# opts in explicitly.
_pallas_precision = "f32"


def get_pallas_precision() -> str:
    return _pallas_precision


def set_pallas_precision(p: str) -> None:
    if p not in ("f32", "bf16x3", "bf16"):
        raise ValueError(
            f"pallas_precision must be 'f32', 'bf16x3' or 'bf16', got {p!r}"
        )
    global _pallas_precision
    _pallas_precision = p


# ``pallas_m_tile`` — rows of A per fused-kernel grid step. Larger tiles
# amortize operator generation/caching over more MXU work at the cost of
# VMEM. Seeded from SKYLARK_PALLAS_MTILE for on-chip sweeps without code
# changes; invalid values fall back to the default.
def _env_m_tile() -> int:
    import os

    try:
        v = int(os.environ.get("SKYLARK_PALLAS_MTILE", 256))
    except ValueError:
        return 256
    return v if v >= 8 else 256


_pallas_m_tile = _env_m_tile()


def get_pallas_m_tile() -> int:
    return _pallas_m_tile


def set_pallas_m_tile(t: int) -> None:
    t = int(t)
    if t < 8:
        raise ValueError(f"pallas_m_tile must be >= 8, got {t}")
    global _pallas_m_tile
    _pallas_m_tile = t
