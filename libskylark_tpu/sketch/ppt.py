"""PPT (TensorSketch) — Pham-Pagh polynomial kernel sketch.

TPU-native analog of ref: sketch/PPT_data.hpp:24-120, sketch/PPT_Elemental.hpp:16-870.
Approximates the polynomial kernel (γ·xᵀy + c)^q: q independent CountSketches
of x, each lifted by the homogeneity term √c·e_{h_i}·s_i, FFT'd, multiplied
elementwise across q, and inverse-FFT'd. The reference loops columns with
per-column FFTW plans; here the whole (S × m) batch goes through jnp.fft along
the feature axis in one shot.

Sub-allocations: child(i) = i-th internal CWT; sub-streams 100/101 = the
homogeneity hash (idx, val) (ref: PPT_data.hpp:100-106).
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.hash import CWT
from libskylark_tpu.sketch.transform import SketchTransform, register


@register
class PPT(SketchTransform):
    sketch_type = "PPT"

    def __init__(self, N, S, context, q: int = 3, c: float = 1.0,
                 gamma: float = 1.0):
        from libskylark_tpu.base import errors

        if q < 1:
            raise errors.InvalidParametersError(f"PPT degree q must be >= 1, got {q}")
        if c < 0 or gamma < 0:
            raise errors.InvalidParametersError(
                f"PPT parameters c and gamma must be nonnegative, got c={c}, gamma={gamma}"
            )
        self._q = int(q)
        self._c = float(c)
        self._gamma = float(gamma)
        super().__init__(N, S, context)

    def _build(self):
        self._cwts = [
            CWT(self._N, self._S, self._alloc.child(i)) for i in range(self._q)
        ]

    def _hash_idx(self) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(100), randgen.UniformInt(0, self._S - 1), 0, self._q,
            dtype=jnp.int32,
        )

    def _hash_val(self, dtype) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(101), randgen.Rademacher(), 0, self._q, dtype=dtype
        )

    def _sketch_columns(self, A: jnp.ndarray) -> jnp.ndarray:
        """Columnwise TensorSketch of A (N, m) -> (S, m)
        (ref: PPT_Elemental.hpp:155-185)."""
        dt = A.dtype
        hidx = self._hash_idx()
        hval = self._hash_val(dt)
        sqrt_gamma = math.sqrt(self._gamma)
        sqrt_c = math.sqrt(self._c)
        P = None
        for i, cwt in enumerate(self._cwts):
            W = sqrt_gamma * cwt.apply(A)                     # (S, m)
            W = W.at[hidx[i], :].add(sqrt_c * hval[i])
            FW = jnp.fft.fft(W, axis=0)
            P = FW if P is None else P * FW
        return jnp.real(jnp.fft.ifft(P, axis=0)).astype(dt)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        return self._sketch_columns(A)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        return self._sketch_columns(A.T).T

    def _extra_params(self) -> dict[str, Any]:
        return {"q": self._q, "c": self._c, "gamma": self._gamma}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, q=int(d.get("q", 3)), c=float(d.get("c", 1.0)),
                   gamma=float(d.get("gamma", 1.0)))
