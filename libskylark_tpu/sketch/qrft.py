"""Quasi-random feature transforms: GaussianQRFT, LaplacianQRFT, ExpSemigroupQRLT.

TPU-native analog of ref: sketch/QRFT_data.hpp:27-290, sketch/QRLT_data.hpp:35-150.
Same feature maps as RFT/RLT, but frequencies come from a leaped Halton QMC
sequence pushed through the kernel distribution's inverse CDF instead of
pseudo-random draws: W[i, j] = inscale · quantile(dist, seq(skip+i, j)), and
the phase shift uses the extra sequence dimension N
(ref: QRFT_data.hpp:91-93: shifts[i] = 2π·seq(skip+i, N)).

W is built host-side in float64 numpy (it is a deterministic function of
(sequence, skip) — no RNG involved) and shipped to device once.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np
from scipy import special as sps

from libskylark_tpu.base.quasirand import LeapedHaltonSequence, QMCSequence
from libskylark_tpu.sketch.transform import (OperatorCache,
                                             SketchTransform, register)


def _normal_quantile(p: np.ndarray) -> np.ndarray:
    return sps.ndtri(p)


def _cauchy_quantile(p: np.ndarray) -> np.ndarray:
    return np.tan(np.pi * (p - 0.5))


def _levy_quantile(p: np.ndarray) -> np.ndarray:
    """Standard Levy quantile: 1/(2·erfcinv(p)²)
    (ref: sketch/QRLT_data.hpp:137-146)."""
    v = sps.erfcinv(p)
    return 1.0 / (2.0 * v * v)


class QRFT(OperatorCache, SketchTransform):
    """Base quasi-random Fourier features. W lives on HOST
    (quasi-Monte-Carlo points are built in f64 numpy); each apply
    re-uploads it — ``materialize()`` (OperatorCache) pins the device
    copy for repeated applies."""

    def _full_operator(self, dtype):
        return self.w_matrix(dtype)

    sketch_type = "QRFT"
    _quantile = staticmethod(_normal_quantile)

    def __init__(self, N, S, context, sequence: Optional[QMCSequence] = None,
                 skip: int = 0):
        self._sequence = sequence or LeapedHaltonSequence(N + 1)
        self._skip = int(skip)
        super().__init__(N, S, context)

    @property
    def inscale(self) -> float:
        raise NotImplementedError

    @property
    def outscale(self) -> float:
        return math.sqrt(2.0 / self._S)

    def _build(self):
        # Coordinates for features [skip, skip+S) over dims [0, N] — last dim
        # feeds the shifts (ref: QRFT_data.hpp qmc_sequence_dim = N+1).
        panel = self._sequence.panel(self._skip, self._skip + self._S, self._N + 1)
        # Clamp away from {0,1} where quantiles blow up.
        eps = np.finfo(np.float64).tiny
        coords = np.clip(panel[:, : self._N], eps, 1 - 1e-16)
        self._W_host = self.inscale * self._quantile(coords)
        self._shifts_host = 2.0 * math.pi * panel[:, self._N]

    def w_matrix(self, dtype=jnp.float32) -> jnp.ndarray:
        return jnp.asarray(self._W_host, dtype=dtype)

    def shifts(self, dtype=jnp.float32) -> jnp.ndarray:
        return jnp.asarray(self._shifts_host, dtype=dtype)

    def _device_W(self, dtype) -> jnp.ndarray:
        return self._op_or(dtype, self.w_matrix)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A)
        W = self._device_W(A.dtype)
        return self.outscale * jnp.cos(W @ A + self.shifts(A.dtype)[:, None])

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A)
        W = self._device_W(A.dtype)
        return self.outscale * jnp.cos(A @ W.T + self.shifts(A.dtype)[None, :])

    def _extra_params(self) -> dict[str, Any]:
        return {"sequence": self._sequence.to_dict(), "skip": self._skip}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        seq = QMCSequence.from_dict(d["sequence"]) if "sequence" in d else None
        return cls(N, S, alloc, sequence=seq, skip=int(d.get("skip", 0)),
                   **cls._extra_kernel_params(d))

    @staticmethod
    def _extra_kernel_params(d) -> dict[str, Any]:
        return {}


@register
class GaussianQRFT(QRFT):
    """Gaussian kernel, normal inverse-CDF (ref: QRFT_data.hpp:107-180)."""

    sketch_type = "GaussianQRFT"
    _quantile = staticmethod(_normal_quantile)

    def __init__(self, N, S, context, sigma: float = 1.0, sequence=None,
                 skip: int = 0):
        self._sigma = float(sigma)
        super().__init__(N, S, context, sequence=sequence, skip=skip)

    @property
    def inscale(self) -> float:
        return 1.0 / self._sigma

    def _extra_params(self):
        d = super()._extra_params()
        d["sigma"] = self._sigma
        return d

    @staticmethod
    def _extra_kernel_params(d):
        return {"sigma": float(d.get("sigma", 1.0))}


@register
class LaplacianQRFT(QRFT):
    """Laplacian kernel, Cauchy inverse-CDF (ref: QRFT_data.hpp:183-290)."""

    sketch_type = "LaplacianQRFT"
    _quantile = staticmethod(_cauchy_quantile)

    def __init__(self, N, S, context, sigma: float = 1.0, sequence=None,
                 skip: int = 0):
        self._sigma = float(sigma)
        super().__init__(N, S, context, sequence=sequence, skip=skip)

    @property
    def inscale(self) -> float:
        return 1.0 / self._sigma

    def _extra_params(self):
        d = super()._extra_params()
        d["sigma"] = self._sigma
        return d

    @staticmethod
    def _extra_kernel_params(d):
        return {"sigma": float(d.get("sigma", 1.0))}


@register
class ExpSemigroupQRLT(QRFT):
    """Quasi-random Laplace features for the exponential semigroup kernel
    (ref: sketch/QRLT_data.hpp:35-150): z(x) = sqrt(1/S)·exp(−(W x)),
    W from the Levy quantile with inscale β²/2."""

    sketch_type = "ExpSemigroupQRLT"
    _quantile = staticmethod(_levy_quantile)

    def __init__(self, N, S, context, beta: float = 1.0, sequence=None,
                 skip: int = 0):
        self._beta = float(beta)
        super().__init__(N, S, context, sequence=sequence, skip=skip)

    @property
    def inscale(self) -> float:
        return self._beta * self._beta / 2.0

    @property
    def outscale(self) -> float:
        return math.sqrt(1.0 / self._S)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A)
        W = self._device_W(A.dtype)
        return self.outscale * jnp.exp(-(W @ A))

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A)
        W = self._device_W(A.dtype)
        return self.outscale * jnp.exp(-(A @ W.T))

    def _extra_params(self):
        d = super()._extra_params()
        d["beta"] = self._beta
        return d

    @staticmethod
    def _extra_kernel_params(d):
        return {"beta": float(d.get("beta", 1.0))}
