"""Random Fourier feature transforms: GaussianRFT, LaplacianRFT, MaternRFT.

TPU-native analog of ref: sketch/RFT_data.hpp:25-354, sketch/RFT_Elemental.hpp:62-332.
Rahimi-Recht random features: z(x) = outscale · cos(scales ⊙ (W x) + b), with
W an i.i.d. dense matrix scaled by ``inscale`` (kernel-specific distribution),
b ~ U[0, 2π), and per-row ``scales`` that default to 1 (Matern overrides them
with sqrt(2ν / χ²(2ν)) samples to realize multivariate-t frequencies,
ref: RFT_data.hpp:335-346).

The cos is fused by XLA into the matmul epilogue — the hand-written OpenMP
elementwise loops of the reference (ref: RFT_Elemental.hpp:83-156) disappear.

Sub-streams of the allocation: 0 = W entries, 1 = shifts, 2 = scales (Matern).
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.dense import BLOCK_COLS
from libskylark_tpu.sketch.transform import (OperatorCache,
                                             SketchTransform, register)


class RFT(OperatorCache, SketchTransform):
    """Base random-Fourier-feature transform. ``materialize()`` pins the
    frequency matrix W (OperatorCache) — the serving-predict /
    repeated-featurization reuse regime."""

    def _full_operator(self, dtype) -> jnp.ndarray:
        return self.w_panel(0, self._N, dtype)

    def _materialize_changes_numerics(self, A, seq_axis=None) -> bool:
        from libskylark_tpu.sketch.dense import pallas_serves_eager

        return pallas_serves_eager(A, self.dist, self._S, seq_axis)

    sketch_type = "RFT"
    dist: randgen.Distribution = randgen.Normal()

    @property
    def inscale(self) -> float:
        raise NotImplementedError

    @property
    def outscale(self) -> float:
        return math.sqrt(2.0 / self._S)

    def w_panel(self, col_start: int, col_stop: int, dtype=jnp.float32) -> jnp.ndarray:
        """W[:, col_start:col_stop] — lazy (S × N) frequency matrix
        (the 'underlying dense transform', ref: RFT_data.hpp:76-80)."""
        return self.inscale * randgen.dense_panel(
            self.subkey(0), self.dist, self._S, col_start, col_stop, BLOCK_COLS, dtype
        )

    def s_block(self, block_id, dtype=jnp.float32) -> jnp.ndarray:
        """Column block of W (traced id ok) — the DenseTransform block
        protocol, so the distributed-sparse panel machinery
        (sketch/dist_sparse_apply.py) applies to frequency matrices too."""
        return self.inscale * randgen.dense_block(
            self.subkey(0), self.dist, self._S, block_id, BLOCK_COLS, dtype
        )

    def shifts(self, dtype=jnp.float32) -> jnp.ndarray:
        return randgen.stream_slice(
            self.subkey(1), randgen.Uniform(0.0, 2.0 * math.pi), 0, self._S,
            dtype=dtype,
        )

    def row_scales(self, dtype=jnp.float32) -> jnp.ndarray:
        """Per-feature scaling; 1 unless a kernel subclass overrides
        (ref: RFT_data.hpp:84-86)."""
        return jnp.ones((self._S,), dtype)

    def _featurize(self, WA: jnp.ndarray, feature_axis: int) -> jnp.ndarray:
        dt = WA.dtype
        shape = [1, 1]
        shape[feature_axis] = self._S
        sc = self.row_scales(dt).reshape(shape)
        sh = self.shifts(dt).reshape(shape)
        return self.outscale * jnp.cos(WA * sc + sh)

    def _project_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        """W·A — the pinned W when materialized; on TPU via the fused
        generation+matmul kernel (W is in the same dense-block stream
        format as the dense transforms); XLA panel materialization
        otherwise."""
        from libskylark_tpu.sketch.dense import try_pallas_apply

        W = self._cached_op(A.dtype)
        if W is not None:
            return W @ A
        out = try_pallas_apply(
            self.subkey(0), self.dist, A, self._S, self.inscale,
            "columnwise_apply",
        )
        if out is not None:
            return out
        return self.w_panel(0, self._N, A.dtype) @ A

    def _project_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        from libskylark_tpu.sketch.dense import try_pallas_apply

        W = self._cached_op(A.dtype)
        if W is not None:
            return A @ W.T
        out = try_pallas_apply(
            self.subkey(0), self.dist, A, self._S, self.inscale,
            "rowwise_apply",
        )
        if out is not None:
            return out
        return A @ self.w_panel(0, self._N, A.dtype).T

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A, seq_axis=0)
        return self._featurize(self._project_columnwise(A), feature_axis=0)

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A, seq_axis=1)
        if self._op_cache is None:
            out = self._try_fused_rowwise(A)
            if out is not None:
                return out
        return self._featurize(self._project_rowwise(A), feature_axis=1)

    def _try_fused_rowwise(self, A):
        """Fully-fused TPU path: generation + matmul + cos epilogue in one
        kernel (pallas_dense.rft_rowwise_apply) — the feature matrix never
        round-trips HBM between projection and featurization.

        Normal-frequency transforms only (Gaussian/Matern): Cauchy
        frequencies (Laplacian) produce heavy-tailed phases where f32
        ``cos`` is ill-conditioned, so tiny contraction-order differences
        break the 1e-4 oracle — those keep the two-step path whose
        projection is bit-compatible with the XLA panels."""
        from libskylark_tpu.sketch.dense import pallas_ambient_ok

        if type(self.dist) is not randgen.Normal:
            return None
        if not pallas_ambient_ok(A):
            return None
        from libskylark_tpu.sketch import pallas_dense

        out = pallas_dense.rft_rowwise_apply(
            self.subkey(0), self.dist, A, self._S,
            self.inscale, self.outscale,
            self.row_scales(jnp.float32), self.shifts(jnp.float32),
        )
        if out is None:
            return None
        return out.astype(A.dtype)

    # -- sparse input: project with the segment-sum spmm kernels --

    def _apply_columnwise_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.base.sparse import spmm_t

        W = self._op_or(A.device_dtype,
                        lambda dt: self.w_panel(0, self._N, dt))
        return self._featurize(spmm_t(A, W.T).T, feature_axis=0)

    def _apply_rowwise_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.base.sparse import spmm

        W = self._op_or(A.device_dtype,
                        lambda dt: self.w_panel(0, self._N, dt))
        return self._featurize(spmm(A, W.T), feature_axis=1)

    # -- distributed sparse input: project with the per-cell virtual
    # panel machinery, then featurize (ref: the mixed sparse-input
    # RFT specializations, sketch/RFT.hpp dispatch) --

    def _apply_columnwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return self._featurize(dsa.dense_columnwise(self, A),
                               feature_axis=0)

    def _apply_rowwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return self._featurize(dsa.dense_rowwise(self, A), feature_axis=1)


@register
class GaussianRFT(RFT):
    """Gaussian-kernel random features: W ~ N(0,1), inscale 1/σ
    (ref: RFT_data.hpp:117-145)."""

    sketch_type = "GaussianRFT"
    dist = randgen.Normal()

    def __init__(self, N, S, context, sigma: float = 1.0):
        self._sigma = float(sigma)
        super().__init__(N, S, context)

    @property
    def inscale(self) -> float:
        return 1.0 / self._sigma

    def _extra_params(self) -> dict[str, Any]:
        return {"sigma": self._sigma}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, sigma=float(d.get("sigma", 1.0)))


@register
class LaplacianRFT(RFT):
    """Laplacian-kernel random features: W ~ Cauchy, inscale 1/σ
    (ref: RFT_data.hpp:192-247)."""

    sketch_type = "LaplacianRFT"
    dist = randgen.Cauchy()

    def __init__(self, N, S, context, sigma: float = 1.0):
        self._sigma = float(sigma)
        super().__init__(N, S, context)

    @property
    def inscale(self) -> float:
        return 1.0 / self._sigma

    def _extra_params(self) -> dict[str, Any]:
        return {"sigma": self._sigma}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, sigma=float(d.get("sigma", 1.0)))


@register
class MaternRFT(RFT):
    """Matern-kernel random features: multivariate-t frequencies — normal W
    with per-row scales sqrt(2ν / χ²(2ν)) (ref: RFT_data.hpp:320-346)."""

    sketch_type = "MaternRFT"
    dist = randgen.Normal()

    def __init__(self, N, S, context, nu: float = 1.0, l: float = 1.0):
        self._nu = float(nu)
        self._l = float(l)
        super().__init__(N, S, context)

    @property
    def inscale(self) -> float:
        return 1.0 / self._l

    def row_scales(self, dtype=jnp.float32) -> jnp.ndarray:
        # chi^2(2nu) == Gamma(shape=nu, scale=2)
        chi2 = randgen.stream_slice(
            self.subkey(2),
            randgen.Gamma(shape_param=self._nu, scale=2.0),
            0,
            self._S,
            dtype=dtype,
        )
        return jnp.sqrt(2.0 * self._nu / jnp.maximum(chi2, jnp.finfo(dtype).tiny))

    def _extra_params(self) -> dict[str, Any]:
        return {"nu": self._nu, "l": self._l}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, nu=float(d.get("nu", 1.0)), l=float(d.get("l", 1.0)))


@register
class ExpSemigroupRLT(RFT):
    """Random Laplace features for the exponential semigroup kernel
    (Yang et al., ref: sketch/RLT_data.hpp:94-160, sketch/RLT_Elemental.hpp:77):
    z(x) = sqrt(1/S) · exp(−(W x)), W ~ (β²/2)·StandardLevy.

    Inputs must be nonnegative (the semigroup kernel's domain is R+); negative
    coordinates make −Wx arbitrarily large and overflow exp, exactly as the
    reference's ``exp(-val)`` would. Shares RFT's lazy-W machinery; only the
    elementwise feature map differs (exp(−·) instead of cos(·+shift))."""

    sketch_type = "ExpSemigroupRLT"
    dist = randgen.StandardLevy()

    def __init__(self, N, S, context, beta: float = 1.0):
        self._beta = float(beta)
        super().__init__(N, S, context)

    @property
    def inscale(self) -> float:
        return self._beta * self._beta / 2.0

    @property
    def outscale(self) -> float:
        return math.sqrt(1.0 / self._S)

    def _featurize(self, WA: jnp.ndarray, feature_axis: int) -> jnp.ndarray:
        return self.outscale * jnp.exp(-WA)

    def _extra_params(self) -> dict[str, Any]:
        return {"beta": self._beta}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, beta=float(d.get("beta", 1.0)))
