"""Pure, vmap-batchable sparse (CSR-lane) serve endpoints.

The microbatch serving layer (:mod:`libskylark_tpu.engine.serve`)
accepts sparse operands as padded **(data, indices, indptr) CSR lanes**:
``data``/``indices`` zero-padded to the bucket's pow2 nnz class,
``indptr`` monotone-padded with the true nnz to the padded row extent
(so ragged-nnz cohorts coalesce into one flush executable — docs/
serving, "Sparse operands on the serve path"). The functions here are
the per-lane programs those flushes vmap over; each is a pure function
of the transform's raw key data plus the CSR lanes, with every shape
static, mirroring ``sketch.hash.cwt_serve_apply`` / ``sketch.dense
.serve_apply`` for dense operands.

Exactness contract (the CI sparse-serve gate pins it):

- **CWT** (:func:`cwt_sparse_serve_apply`): the scatter-add runs over
  the CSR nonzeros in row-major order — exactly the order in which the
  dense reference's ``segment_sum`` retires the same nonzero terms
  (dense zero entries contribute exact ±0.0, which never perturbs an
  accumulator) — so the sparse flush is **bit-equal** to
  ``transform.apply(A.todense())`` at any shape and to the densified
  request through the dense serve path. Padded lane entries carry
  value 0.0 at clamped position 0: exact zeros, any capacity class.
- **dense families** (:func:`dense_sparse_serve_apply`, JLT/CT): the
  lanes are scattered to the padded dense class shape *inside the
  executable* (the integer scatter reproduces ``todense()`` exactly)
  and the request then runs the literal dense serve program
  (``dense.serve_apply``) on it — bit-equal to the densified request
  by construction, with the client-side densify + dense-operand
  stacking cost (the flush hot path's host bytes) eliminated. Against
  the *eager* ``transform.apply`` this coincides bitwise when the
  stream extent is its own pow2 class and otherwise sits in the dense
  serve endpoint's documented float-epsilon band (padding the
  reduction length re-blocks an f32 dot), exactly like the dense
  buckets themselves.
- **sketched least-squares** (:func:`sparse_solve_serve`): the sketch
  stage is one of the above; equal sketch bits feed the identical
  ``solve_l2_exact``, so the solve inherits the sketch's contract.

The CWT path is where sparsity pays: O(nnz) scatter work instead of the
dense path's O(N·m) segment-sum — the committed
``benchmarks/results_sparse_cpu.json`` A/B quantifies it. On TPU the
scatter-free Pallas sparse kernel (:mod:`libskylark_tpu.sketch
.pallas_sparse`) replaces this scatter per the serve ladder's
autotuned selection.

The CSR lane format (and :func:`scatter_dense`) is also the intake of
the **graph serve endpoints** (docs/qos): ``submit_graph_ase`` /
``submit_graph_ppr`` pack adjacency matrices — the sparse regime this
module optimizes for — as the same padded (data, indices, indptr)
lanes with a pow2 nnz class, densifying in-executable through the
identical integer scatter (:mod:`libskylark_tpu.ml.graph`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from libskylark_tpu.base import randgen


def csr_row_ids(indptr, nnz_pad: int) -> jnp.ndarray:
    """Expand a (rows+1,) CSR ``indptr`` into per-nonzero row ids for
    the leading ``nnz_pad`` lane positions (int32). Positions past the
    true nnz (the lane padding; ``indptr`` is monotone-padded with nnz)
    clamp to the last row — their data is 0.0, so the clamped target
    accumulates exact zeros. Jittable: one ``searchsorted`` over the
    static lane extent."""
    j = jnp.arange(nnz_pad, dtype=indptr.dtype)
    rows = jnp.searchsorted(indptr[1:], j, side="right")
    return jnp.minimum(rows, indptr.shape[0] - 2).astype(jnp.int32)


def cwt_sparse_serve_apply(key_data, data, indices, indptr, *,
                           s_dim: int, rowwise: bool,
                           shape: tuple) -> jnp.ndarray:
    """One request's CountSketch of a CSR operand: O(nnz) scatter-add,
    bit-equal to ``cwt_serve_apply`` on the densified operand (module
    doc). ``shape`` is the padded (rows, cols) class shape the lanes
    describe; the sketched extent (rows columnwise, cols rowwise) is
    stream-exact under zero-padding, the kept extent is sliced by the
    caller. Returns (s_dim, cols) columnwise / (rows, s_dim) rowwise.
    """
    import jax.random as jr

    key = jr.wrap_key_data(jnp.asarray(key_data))
    n_rows, n_cols = int(shape[0]), int(shape[1])
    n = n_cols if rowwise else n_rows
    h = randgen.stream_slice(
        jax.random.fold_in(key, 0), randgen.UniformInt(0, s_dim - 1),
        0, n, dtype=jnp.int32)
    v = randgen.stream_slice(
        jax.random.fold_in(key, 1), randgen.Rademacher(), 0, n,
        dtype=data.dtype)
    rows = csr_row_ids(indptr, data.shape[0])
    cols = indices
    if rowwise:
        # out[r, h[c]] += v[c]·val — CSR row-major order IS the dense
        # segment-sum's coordinate order per output cell
        out = jnp.zeros((n_rows, s_dim), data.dtype)
        return out.at[rows, h[cols]].add(v[cols] * data)
    out = jnp.zeros((s_dim, n_cols), data.dtype)
    return out.at[h[rows], cols].add(v[rows] * data)


def scatter_dense(data, indices, indptr, *, shape: tuple) -> jnp.ndarray:
    """Densify CSR lanes to the padded class shape on device — the
    integer scatter reproduces ``SparseMatrix.todense()`` exactly
    (canonical CSR has no duplicate coordinates, so accumulation order
    is irrelevant; padded entries add 0.0 at a clamped coordinate)."""
    rows = csr_row_ids(indptr, data.shape[0])
    return jnp.zeros(tuple(int(e) for e in shape),
                     data.dtype).at[rows, indices].add(data)


def dense_sparse_serve_apply(key_data, scale, data, indices, indptr, *,
                             dist, s_dim: int, rowwise: bool,
                             shape: tuple) -> jnp.ndarray:
    """One request's dense-family (JLT/CT) sketch of a CSR operand:
    in-executable densify + the literal dense serve program — bit-equal
    to the densified request (module doc)."""
    from libskylark_tpu.sketch.dense import serve_apply

    A = scatter_dense(data, indices, indptr, shape=shape)
    return serve_apply(key_data, scale, A, dist=dist, s_dim=s_dim,
                       rowwise=rowwise)


def sparse_solve_serve(key_data, scale, data, indices, indptr, B, *,
                       sketch_type: str, s_dim: int, method: str,
                       shape: tuple) -> jnp.ndarray:
    """Sketch-and-solve with a CSR design matrix: SA from the sparse
    columnwise sketch above, SB from the dense serve sketch of the
    (dense) target block, then the identical ``solve_l2_exact`` the
    dense serve endpoint runs — so equal sketch bits mean equal
    solutions. Zero-padded rows contribute nothing through either
    family; the feature/target extents are exact bucket components
    (a zero feature column would make the compressed problem
    singular)."""
    from libskylark_tpu.algorithms.regression import solve_l2_exact
    from libskylark_tpu.base import errors
    from libskylark_tpu.sketch import dense, hash as sketch_hash

    if sketch_type == "CWT":
        SA = cwt_sparse_serve_apply(key_data, data, indices, indptr,
                                    s_dim=s_dim, rowwise=False,
                                    shape=shape)
        SB = sketch_hash.cwt_serve_apply(key_data, B, s_dim=s_dim,
                                         rowwise=False)
    elif sketch_type == "JLT":
        SA = dense_sparse_serve_apply(
            key_data, scale, data, indices, indptr,
            dist=randgen.Normal(), s_dim=s_dim, rowwise=False,
            shape=shape)
        SB = dense.serve_apply(key_data, scale, B,
                               dist=randgen.Normal(), s_dim=s_dim,
                               rowwise=False)
    else:
        raise errors.InvalidParametersError(
            f"sparse solve serve path supports JLT/CWT sketches, got "
            f"{sketch_type!r}")
    return solve_l2_exact(SA, SB, method=method)
