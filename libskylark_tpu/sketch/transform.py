"""Sketch transform protocol, dimension tags, and serialization registry.

TPU-native analog of the reference's sketch architecture
(ref: sketch/sketch_transform.hpp:60-92, sketch/sketch_transform_data.hpp:28-87,
sketch/transforms.hpp:12-18, sketch/sketch_add.hpp:15-55).

Where the reference pairs a matrix-type-agnostic ``X_data_t`` with per-layout
``X_t<In,Out>`` apply engines, here a single transform object covers all
layouts: the apply methods are pure jnp functions, so input sharding flows
through and XLA inserts the collectives that Elemental's per-distribution
specializations hand-coded. The type-erased ``boost::any`` dispatch layer
(ref: sketch/sketch_transform.hpp:187-221) has no analog — Python is already
dynamically typed.

Dimension convention (ref: sketch/transforms.hpp:12-18):
- ``COLUMNWISE``: sketch_of_A = S · A   (compresses the column dimension: A is N×m)
- ``ROWWISE``:    sketch_of_A = A · Sᵀ  (compresses the row dimension: A is m×N)
"""

from __future__ import annotations

import enum
import json
from typing import Any, Union

import jax
import jax.numpy as jnp

from libskylark_tpu import __version__
from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Allocation, Context


class Dimension(enum.Enum):
    COLUMNWISE = "columnwise"
    ROWWISE = "rowwise"


COLUMNWISE = Dimension.COLUMNWISE
ROWWISE = Dimension.ROWWISE

_REGISTRY: dict[str, type["SketchTransform"]] = {}


def register(cls: type["SketchTransform"]) -> type["SketchTransform"]:
    """Register a transform class for deserialization
    (ref: sketch/sketch_add.hpp:15-55 from_ptree registry)."""
    _REGISTRY[cls.sketch_type] = cls
    return cls


class OperatorCache:
    """Opt-in materialize-and-reuse for transforms whose operator is a
    lazily generated dense matrix (DenseTransform's S, RFT's frequency
    matrix W).

    The virtual-operator design pays generation on EVERY apply — the
    right trade for one-shot sketches of huge operands. Workloads that
    apply the same transform repeatedly (feature maps inside solver
    iterations, ref: ml/BlockADMM.hpp:434 cached transforms; serving
    predict paths) call ``materialize()`` to pin the operator in device
    memory and amortize generation to zero, at rows×N×itemsize bytes.
    The cache is runtime state — never serialized (serialization stays
    (seed, counter)-based)."""

    _op_cache = None
    _eager_applies = 0

    def _full_operator(self, dtype) -> jnp.ndarray:
        raise NotImplementedError

    def materialize(self, dtype=jnp.float32):
        """Pin the full operator; later applies contract against the
        cached array instead of regenerating. Returns ``self``."""
        self._op_cache = self._full_operator(dtype)
        return self

    def dematerialize(self):
        """Drop the pinned operator (and the auto-dispatch apply count —
        an explicit drop means 'stop amortizing', not 'repin at once')."""
        self._op_cache = None
        self._eager_applies = 0
        return self

    def _op_bytes(self, dtype) -> int:
        """Pinned-operator size for the auto-materialize budget; the
        cached operator is (sketch_dim × N) for every current user."""
        return int(self._S) * int(self._N) * jnp.dtype(dtype).itemsize

    def _note_eager_apply(self, A, seq_axis: int | None = None) -> None:
        """Auto-materialize dispatch (see sketch/params.py): the Nth
        EAGER dense apply of this instance pins the operator when it
        fits the budget. Applies under a jit trace never count — the
        trace runs once, and materializing inside it would pin a tracer.
        Steady-state reuse (a serving predict path, a feature map inside
        an eager solver loop) thus amortizes generation to zero without
        anyone calling :meth:`materialize`."""
        dtype = A.dtype
        if self._op_cache is not None and \
                jnp.dtype(dtype).itemsize <= self._op_cache.dtype.itemsize:
            return
        # (a cache NARROWER than this request doesn't serve it —
        # _cached_op refuses to upcast — so wide applies keep counting
        # and re-pin at the wider dtype rather than regenerate forever)
        if isinstance(A, jax.core.Tracer):
            return
        from libskylark_tpu.sketch import params as sketch_params

        if not sketch_params.get_auto_materialize():
            return
        if self._materialize_changes_numerics(A, seq_axis):
            # never auto-switch a path whose numerics differ from the
            # cached gemm (the fused TPU kernel's bf16x3/accumulation
            # order): two identical eager applies must not differ by
            # prior call count. Explicit materialize() remains available
            # — an explicit call is a visible regime choice.
            return
        self._eager_applies += 1
        if self._eager_applies < sketch_params.get_auto_materialize_after():
            return
        if self._op_bytes(dtype) > sketch_params.get_auto_materialize_bytes():
            return
        self.materialize(dtype)

    def _materialize_changes_numerics(self, A, seq_axis=None) -> bool:
        """True when auto-pinning would CHANGE the numerics of later
        eager applies (e.g. the apply currently routes through the fused
        Pallas kernel, whose contraction regime differs from the
        materialized XLA gemm). ``seq_axis`` is the apply orientation
        (0 columnwise, 1 rowwise, None unknown) so overrides can ask the
        kernel dispatch for its real decision. Default False: on the
        plain XLA path the materialized contraction is the same
        computation."""
        return False

    def _cached_op(self, dtype):
        """The pinned operator, cast to the apply dtype if needed (the
        cast is O(elements) — noise next to the gemm; silently skipping
        the cache on a narrower dtype would defeat the explicitly
        requested amortization). A request WIDER than the cache returns
        None — upcasting a truncated cache would silently degrade e.g.
        f64 applies (QRFT builds W in host f64; under jax x64 the
        virtual path is full-precision), so wide applies regenerate."""
        c = self._op_cache
        if c is None:
            return None
        want = jnp.dtype(dtype)
        if want.itemsize > c.dtype.itemsize:
            return None
        return c if c.dtype == want else c.astype(want)

    def _op_or(self, dtype, build):
        """The cached operator for ``dtype``, else ``build(dtype)``."""
        c = self._cached_op(dtype)
        return c if c is not None else build(dtype)


class SketchTransform:
    """A sketching transform S: R^N -> R^S_dim.

    Mathematical definition lives in the (seed, counter) allocation plus the
    hyper-params — matrix-free and serializable, like the reference's
    ``_data_t`` classes. Construction advances the context's counter
    (ref: sketch/sketch_transform_data.hpp ``build``).
    """

    sketch_type = "SketchTransform"

    def __init__(self, N: int, S: int, context: Union[Context, Allocation]):
        if N <= 0 or S <= 0:
            raise errors.InvalidParametersError(
                f"sketch dims must be positive, got N={N}, S={S}"
            )
        self._N = int(N)
        self._S = int(S)
        if isinstance(context, Context):
            self._alloc = context.allocate()
        else:
            self._alloc = context
        self._build()

    def _build(self) -> None:
        """Derive any host-side sample arrays. Default: nothing."""

    # -- structural queries (ref: sketch_transform.hpp getindim/getsketchdim) --

    @property
    def input_dim(self) -> int:
        return self._N

    @property
    def sketch_dim(self) -> int:
        return self._S

    @property
    def allocation(self) -> Allocation:
        return self._alloc

    def subkey(self, tag: int) -> jax.Array:
        """Sub-stream key ``tag`` of this transform's allocation; the analog
        of the reference's sequential counter advancement during build."""
        return jax.random.fold_in(self._alloc.key, tag)

    # -- apply --

    def apply(self, A, dimension: Dimension = COLUMNWISE) -> jnp.ndarray:
        """Apply the sketch (ref: sketch/sketch_transform.hpp:60-92).

        COLUMNWISE: A is (N, m) -> (S, m).  ROWWISE: A is (m, N) -> (m, S).
        Works on any jax.Array regardless of sharding; XLA handles the
        distributed contraction. A :class:`~libskylark_tpu.base.sparse.SparseMatrix`
        input routes to the transform's sparse kernel (ref: the reference's
        per-(input,output)-type specializations, e.g.
        sketch/hash_transform_local_sparse.hpp) and produces a dense result.
        """
        from libskylark_tpu.base.dist_sparse import DistSparseMatrix
        from libskylark_tpu.base.sparse import SparseMatrix

        if isinstance(A, DistSparseMatrix):
            # dimension validation lives in dist_sparse_apply._check_dim
            if dimension == Dimension.COLUMNWISE:
                return self._apply_columnwise_dist_sparse(A)
            return self._apply_rowwise_dist_sparse(A)
        if isinstance(A, SparseMatrix):
            if dimension == Dimension.COLUMNWISE:
                if A.height != self._N:
                    raise errors.SketchError(
                        f"columnwise apply expects {self._N} rows, got {A.shape}"
                    )
                return self._apply_columnwise_sparse(A)
            if A.width != self._N:
                raise errors.SketchError(
                    f"rowwise apply expects {self._N} cols, got {A.shape}"
                )
            return self._apply_rowwise_sparse(A)
        A = jnp.asarray(A)
        if A.ndim == 1:
            A = A[:, None] if dimension == COLUMNWISE else A[None, :]
        if dimension == COLUMNWISE:
            if A.shape[0] != self._N:
                raise errors.SketchError(
                    f"columnwise apply expects A with {self._N} rows, got {A.shape}"
                )
            return self._apply_columnwise(A)
        else:
            if A.shape[1] != self._N:
                raise errors.SketchError(
                    f"rowwise apply expects A with {self._N} cols, got {A.shape}"
                )
            return self._apply_rowwise(A)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: columnwise apply not implemented"
        )

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: rowwise apply not implemented"
        )

    def _apply_columnwise_sparse(self, A) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: columnwise sparse apply not implemented"
        )

    def _apply_rowwise_sparse(self, A) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: rowwise sparse apply not implemented"
        )

    def _apply_columnwise_dist_sparse(self, A) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: columnwise distributed-sparse apply "
            "not implemented"
        )

    def _apply_rowwise_dist_sparse(self, A) -> jnp.ndarray:
        raise errors.NotImplementedYetError(
            f"{self.sketch_type}: rowwise distributed-sparse apply "
            "not implemented"
        )

    # -- serialization (ref: sketch_transform_data.hpp:64-71 add_common) --

    def _extra_params(self) -> dict[str, Any]:
        """Transform-specific hyper-params to serialize."""
        return {}

    # Stream-format generation: bumped whenever the bit-level definition of
    # the virtual random streams changes (chunk size, dense-block threefry
    # pair layout — see base/randgen.py). Deserialization rejects a
    # mismatch rather than silently producing a different operator.
    STREAM_FORMAT = 2

    def to_dict(self) -> dict[str, Any]:
        d = {
            "skylark_object_type": "sketch",
            "sketch_type": self.sketch_type,
            "skylark_version": __version__,
            "stream_format": self.STREAM_FORMAT,
            "N": self._N,
            "S": self._S,
            "creation_context": self._alloc.to_dict(),
        }
        d.update(self._extra_params())
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def _from_parts(
        cls, N: int, S: int, alloc: Allocation, d: dict[str, Any]
    ) -> "SketchTransform":
        return cls(N, S, alloc)

    def __repr__(self) -> str:
        return f"{self.sketch_type}(N={self._N}, S={self._S})"


def deserialize_sketch(obj: Union[str, dict[str, Any]]) -> SketchTransform:
    """Reconstruct a transform from its JSON form
    (ref: sketch/sketch_add.hpp from_ptree; python sketch.py deserialize_sketch:118)."""
    d = json.loads(obj) if isinstance(obj, str) else obj
    stype = d.get("sketch_type")
    cls = _REGISTRY.get(stype)
    if cls is None:
        raise errors.SketchError(f"unknown sketch type {stype!r}")
    # A missing field means a pre-versioning serialization — those were
    # written under the original (format-1) stream layout, so they must be
    # rejected too, not defaulted to the current format.
    fmt = int(d.get("stream_format", 1))
    if fmt != SketchTransform.STREAM_FORMAT:
        raise errors.SketchError(
            f"sketch was serialized with stream format {fmt}; this build "
            f"implements format {SketchTransform.STREAM_FORMAT} — the "
            "operator would not reproduce"
        )
    alloc = Allocation.from_dict(d["creation_context"])
    return cls._from_parts(int(d["N"]), int(d["S"]), alloc, d)
