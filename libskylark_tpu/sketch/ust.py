"""Uniform sampling transform (UST): S = row-sampling operator.

TPU-native analog of ref: sketch/UST_data.hpp:19-130, sketch/UST_Elemental.hpp.
With replacement: S_dim independent uniform indices. Without replacement:
the first S_dim entries of a random permutation of [0, N) — semantically
matching the reference's inside-out Fisher-Yates (ref: UST_data.hpp:90-99),
realized here with jax.random.permutation on a sub-stream key.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import jax.random as jr

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch.transform import SketchTransform, register


@register
class UST(SketchTransform):
    sketch_type = "UST"

    def __init__(self, N, S, context, replace: bool = True):
        self._replace = bool(replace)
        super().__init__(N, S, context)

    def sample_indices(self) -> jnp.ndarray:
        if self._replace:
            return randgen.stream_slice(
                self.subkey(0),
                randgen.UniformInt(0, self._N - 1),
                0,
                self._S,
                dtype=jnp.int32,
            )
        return jr.permutation(self.subkey(1), self._N)[: self._S].astype(jnp.int32)

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        return A[self.sample_indices(), :]

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        return A[:, self.sample_indices()]

    # -- sparse input: host-side row/column gather (sampling preserves
    # sparsity; the small sampled result is densified on device,
    # ref: sketch/UST_Elemental.hpp:69-87 local gather) --

    def _apply_columnwise_sparse(self, A) -> jnp.ndarray:
        import numpy as np

        idx = np.asarray(self.sample_indices())
        return jnp.asarray(
            A.to_scipy()[idx, :].toarray().astype(A.device_dtype)
        )

    def _apply_rowwise_sparse(self, A) -> jnp.ndarray:
        import numpy as np

        idx = np.asarray(self.sample_indices())
        return jnp.asarray(
            A.to_scipy()[:, idx].toarray().astype(A.device_dtype)
        )

    # -- distributed sparse input: per-cell one-hot selection + psum
    # (the redistribute-then-sample pattern of ref:
    # sketch/UST_Elemental.hpp:144-174, without the redistribution —
    # each cell contributes the sampled slice of its own rows/cols) --

    def _apply_columnwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.ust_columnwise(self, A)

    def _apply_rowwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.ust_rowwise(self, A)

    def _extra_params(self) -> dict[str, Any]:
        return {"replace": self._replace}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, replace=bool(d.get("replace", True)))
