"""Telemetry subsystem: unified metrics registry, structured request
tracing, and exporters.

The one observability surface for the whole stack (``docs/
observability.rst``). Three modules:

- :mod:`~libskylark_tpu.telemetry.metrics` — a thread-safe
  process-wide registry of labeled counters/gauges/histograms, plus
  **collector adapters** that re-home the pre-existing stats blocks
  (``engine.stats()``, ``serve_stats()``, resilience fault log, tune
  plan-cache lookups, WebHDFS reconnects) so every number the system
  already tracks appears once, under one schema, via
  :func:`snapshot`.
- :mod:`~libskylark_tpu.telemetry.trace` — ``with telemetry.span(...)``
  with contextvar parent/child linkage, explicit cross-thread
  :class:`SpanContext` handoff (a request id attached at
  ``MicrobatchExecutor.submit`` survives into the flush thread and the
  bisection-isolation retries), and mirroring of every span into
  ``jax.profiler.TraceAnnotation``.
- :mod:`~libskylark_tpu.telemetry.export` — JSONL span/metric sink
  under ``SKYLARK_TELEMETRY_DIR`` with a background flusher that also
  runs synchronously on the resilience preemption teardown, and the
  Prometheus text renderer :func:`prometheus_text`.

Enablement: ``SKYLARK_TELEMETRY=1`` (record, in-memory only),
``SKYLARK_TELEMETRY_DIR=<dir>`` (record + JSONL export), or
:func:`set_enabled`. Disabled cost is one branch per record/span —
cheap enough that the timing-sensitive tier-1 tests run with it off.
"""

from __future__ import annotations

from libskylark_tpu.base import env as _env
from libskylark_tpu.telemetry.metrics import (
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry, counter,
    enabled, gauge, histogram, register_collector, registry, set_enabled,
    snapshot,
)
from libskylark_tpu.telemetry.trace import (
    Span, SpanContext, add_event, add_sink, attach, clear_finished,
    current_span, finished_spans, get_context, new_request_id, span,
)
from libskylark_tpu.telemetry.export import (
    JsonlExporter, get_exporter, install_exporter, prometheus_text,
    shutdown_exporter,
)

# Auto-install the JSONL exporter when the environment asks for it —
# first telemetry import (the engine pulls this package) wires the
# whole export path with zero host code.
if _env.TELEMETRY_DIR.get():
    install_exporter()

__all__ = [
    "Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram", "JsonlExporter",
    "MetricsRegistry", "Span", "SpanContext", "add_event", "add_sink",
    "attach", "clear_finished", "counter", "current_span", "enabled",
    "finished_spans", "gauge", "get_context", "get_exporter", "histogram",
    "install_exporter", "new_request_id", "prometheus_text",
    "register_collector", "registry", "set_enabled", "shutdown_exporter",
    "snapshot", "span",
]
