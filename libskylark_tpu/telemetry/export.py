"""Telemetry exporters: JSONL span/metric sink and Prometheus text.

**JSONL** (:class:`JsonlExporter`, auto-installed when
``SKYLARK_TELEMETRY_DIR`` is set): every finished span becomes one JSON
line in ``spans-<pid>.jsonl`` and every metrics flush one line in
``metrics-<pid>.jsonl`` under the directory. Writes happen on a
background flusher thread (the span hot path only appends to an
in-memory queue); :meth:`JsonlExporter.flush_sync` drains
synchronously, and the exporter registers it with the resilience
preemption teardown (:func:`libskylark_tpu.resilience.on_preemption`)
plus ``atexit``, so a SIGTERM'd serving process loses no spans.

Line schema (``docs/observability.rst`` is the reference):

- span lines: ``{"kind": "span", "name", "trace_id", "span_id",
  "parent_id", "t_wall", "duration_s", "status", "thread",
  "request_id"?, "attrs"?, "events"?, "error"?}``
- metric lines: ``{"kind": "metrics", "t_wall", "snapshot": <the
  telemetry.snapshot() document>}``

**Prometheus** (:func:`prometheus_text`): the registry's counters,
gauges and histograms in text exposition format, plus every collector
block flattened to gauges — one scrape surface carrying the unified
engine/serve/resilience/tune/io numbers. Naming: ``skylark_`` prefix,
dots to underscores, counters get ``_total``, histograms the classic
``_bucket``/``_sum``/``_count`` triplet. Collector sub-blocks named
``by_<label>`` (``serve_stats()``'s ``by_replica``, ``fleet_stats()``'s
ditto) render as *label sets* — ``skylark_serve_submitted{replica=
"r0"}`` — so N executors disaggregate per replica on the scrape
surface instead of silently summing.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
from typing import Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# ---------------------------------------------------------------------------
# JSONL exporter
# ---------------------------------------------------------------------------


class JsonlExporter:
    """Background-flushed JSONL sink under ``directory``."""

    def __init__(self, directory: str, flush_interval_s: float = 0.5):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        pid = os.getpid()
        self.span_path = os.path.join(directory, f"spans-{pid}.jsonl")
        self.metrics_path = os.path.join(directory, f"metrics-{pid}.jsonl")
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._flush_interval = float(flush_interval_s)
        self._closed = False
        self._io_lock = _locks.make_lock("telemetry.export_io")
        self._wake = threading.Event()
        self._unsink = _trace.add_sink(self._on_span)
        self._unhook = self._register_preemption()
        self._flusher = threading.Thread(
            target=self._flusher_loop, name="skylark-telemetry-flusher",
            daemon=True)
        self._flusher.start()

    def _register_preemption(self):
        """A preempted serving process must not lose its tail spans:
        the final synchronous flush rides the resilience teardown
        (after the serve drain resolves the in-flight futures — hook
        order — so the drained flush spans are in the file)."""
        try:
            from libskylark_tpu.resilience.preemption import on_preemption

            return on_preemption(self.flush_sync)
        except Exception:  # pragma: no cover - resilience always present
            return lambda: None

    # -- span intake (hot path: enqueue only) --

    def _on_span(self, span) -> None:
        if not self._closed:
            self._q.put(span.to_dict())

    # -- flushing --

    def _drain(self) -> list:
        docs = []
        while True:
            try:
                docs.append(self._q.get_nowait())
            except queue.Empty:
                return docs

    def _write_spans(self, docs: list) -> None:
        if not docs:
            return
        with self._io_lock:
            with open(self.span_path, "a") as fh:
                for doc in docs:
                    fh.write(json.dumps(doc, sort_keys=True,
                                        default=str) + "\n")

    def _flusher_loop(self) -> None:
        while not self._closed:
            self._wake.wait(timeout=self._flush_interval)
            self._wake.clear()
            try:
                self._write_spans(self._drain())
            except Exception:  # noqa: BLE001 — exporter never kills work
                pass

    def flush_sync(self) -> None:
        """Drain every queued span and append a metrics-snapshot line,
        synchronously (preemption teardown / atexit / tests)."""
        try:
            self._write_spans(self._drain())
            with self._io_lock:
                with open(self.metrics_path, "a") as fh:
                    fh.write(json.dumps(
                        {"kind": "metrics", "t_wall": round(time.time(), 6),
                         "snapshot": _metrics.snapshot()},
                        sort_keys=True, default=str) + "\n")
        except Exception:  # noqa: BLE001
            pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._unsink()
        try:
            self._unhook()
        except Exception:  # pragma: no cover
            pass
        self._wake.set()
        self._flusher.join(timeout=5.0)
        self.flush_sync()


_EXPORTER: Optional[JsonlExporter] = None
_EXPORTER_LOCK = _locks.make_lock("telemetry.exporter")


def install_exporter(directory: Optional[str] = None) -> Optional[JsonlExporter]:
    """Install (or return) the process JSONL exporter. ``directory``
    defaults to ``SKYLARK_TELEMETRY_DIR``; returns ``None`` when
    neither names a directory. Idempotent: one exporter per process
    (a second call with a different directory closes the first)."""
    global _EXPORTER
    directory = directory or _env.TELEMETRY_DIR.get()
    if not directory:
        return None
    with _EXPORTER_LOCK:
        if _EXPORTER is not None:
            if _EXPORTER.directory == directory and not _EXPORTER._closed:
                return _EXPORTER
            _EXPORTER.close()
        _EXPORTER = JsonlExporter(directory)
        return _EXPORTER


def get_exporter() -> Optional[JsonlExporter]:
    return _EXPORTER


def shutdown_exporter() -> None:
    """Close the process exporter (tests; reconfiguration)."""
    global _EXPORTER
    with _EXPORTER_LOCK:
        if _EXPORTER is not None:
            _EXPORTER.close()
            _EXPORTER = None


@atexit.register
def _atexit_flush() -> None:  # pragma: no cover - process teardown
    ex = _EXPORTER
    if ex is not None and not ex._closed:
        ex.flush_sync()


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _prom_name(*parts: str) -> str:
    out = "_".join(p for p in parts if p)
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in out)
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return "skylark_" + out


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    items = []
    for k, v in sorted(merged.items()):
        v = str(v).replace("\\", "\\\\").replace('"', '\\"')
        v = v.replace("\n", "\\n")
        items.append(f'{k}="{v}"')
    return "{" + ",".join(items) + "}"


def _prom_number(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _flatten_numeric(doc: dict, prefix: str, out: list) -> None:
    for k, v in sorted(doc.items()):
        if str(k).startswith("by_") and isinstance(v, dict):
            continue       # labeled sub-blocks render separately
        key = f"{prefix}_{k}" if prefix else str(k)
        if isinstance(v, bool):
            out.append((key, 1.0 if v else 0.0))
        elif isinstance(v, (int, float)):
            out.append((key, float(v)))
        elif isinstance(v, dict):
            _flatten_numeric(v, key, out)
        # strings / lists / None: not scrape-able scalars — skip


def _labeled_blocks(doc: dict, prefix: str = ""):
    """Yield ``(key_prefix, label, member, block)`` for every
    ``by_<label>`` convention sub-dict in a collector block: a dict
    named ``by_replica`` (say) maps member name -> numeric sub-block,
    and renders as ``{replica="<member>"}``-labeled gauges instead of
    flattening the member name into the metric name — the per-replica
    disaggregation contract of ``serve_stats()`` / ``fleet_stats()``
    (docs/observability)."""
    for k, v in sorted(doc.items()):
        if not isinstance(v, dict):
            continue
        key = f"{prefix}_{k}" if prefix else str(k)
        if str(k).startswith("by_") and len(str(k)) > 3:
            label = str(k)[3:]
            for member, block in sorted(v.items()):
                if isinstance(block, dict):
                    yield prefix, label, str(member), block
        else:
            yield from _labeled_blocks(v, key)


def prometheus_text() -> str:
    """The registry + collector adapters in Prometheus text format."""
    lines: list[str] = []
    snap = _metrics.snapshot()

    for name, doc in snap["metrics"].items():
        kind = doc["type"]
        base = _prom_name(name.replace(".", "_"))
        if kind == "counter":
            base += "_total"
        if doc.get("help"):
            lines.append(f"# HELP {base} {doc['help']}")
        lines.append(f"# TYPE {base} "
                     f"{'gauge' if kind == 'gauge' else kind}")
        if kind == "histogram":
            buckets = doc["buckets"]
            for cell in doc["values"]:
                labels = cell["labels"]
                cum = 0
                for b, c in zip(buckets, cell["counts"]):
                    cum += c
                    lines.append(
                        f"{base}_bucket"
                        f"{_prom_labels(labels, {'le': _prom_number(b)})}"
                        f" {cum}")
                cum += cell["counts"][-1]
                lines.append(
                    f"{base}_bucket{_prom_labels(labels, {'le': '+Inf'})}"
                    f" {cum}")
                lines.append(f"{base}_sum{_prom_labels(labels)}"
                             f" {_prom_number(cell['sum'])}")
                lines.append(f"{base}_count{_prom_labels(labels)} {cum}")
        else:
            for cell in doc["values"]:
                lines.append(f"{base}{_prom_labels(cell['labels'])}"
                             f" {_prom_number(cell['value'])}")

    # collector adapters: every numeric leaf becomes a gauge under the
    # collector's namespace — the re-homed engine/serve/resilience/...
    # counters on one scrape surface. ``by_<label>`` sub-blocks render
    # as label sets (one series per replica), not name-mangled gauges.
    for cname, block in snap["collectors"].items():
        if not isinstance(block, dict):
            continue
        # group every series by metric family FIRST: the exposition
        # format requires all lines of a family contiguous under one
        # TYPE line — an aggregate gauge and its labeled per-replica
        # series are ONE family, and interleaving families fails
        # strict parsers (promtool/OpenMetrics)
        families: dict = {}     # base -> [(labels-or-None, value)]
        flat: list = []
        _flatten_numeric(block, "", flat)
        for key, value in flat:
            base = _prom_name(cname.replace(".", "_"),
                              key.replace(".", "_"))
            families.setdefault(base, []).append((None, value))
        for kprefix, label, member, sub in _labeled_blocks(block):
            flat = []
            _flatten_numeric(sub, "", flat)
            for key, value in flat:
                base = _prom_name(cname.replace(".", "_"),
                                  (f"{kprefix}_{key}" if kprefix
                                   else key).replace(".", "_"))
                families.setdefault(base, []).append(
                    ({label: member}, value))
        for base in sorted(families):
            lines.append(f"# TYPE {base} gauge")
            for lbls, value in families[base]:
                lines.append(
                    f"{base}{_prom_labels(lbls) if lbls else ''}"
                    f" {_prom_number(value)}")

    return "\n".join(lines) + ("\n" if lines else "")


__all__ = [
    "JsonlExporter", "get_exporter", "install_exporter",
    "prometheus_text", "shutdown_exporter",
]
