"""Process-wide metrics registry: labeled counters, gauges, histograms.

The r8/r9 rounds grew observability piecemeal — ``engine.stats()``,
``serve_stats()``, the resilience ``fired()`` log, tune plan-cache
lookups, WebHDFS reconnect counting — each with a private schema and no
common export path. This registry is the one schema they all surface
through: subsystems either **record directly** (a
:class:`Counter`/:class:`Gauge`/:class:`Histogram` created once at
module import) or **register a collector** (a zero-argument callable
re-homing an existing stats block at snapshot time, so numbers the
system already tracks appear exactly once instead of being counted
twice). :func:`snapshot` returns everything under one document;
:func:`libskylark_tpu.telemetry.prometheus_text` renders the same data
in Prometheus text exposition format.

Cost discipline (the tier-1 timing-sensitive tests run with telemetry
off): a disabled ``inc``/``set``/``observe`` is **one attribute read
and one branch** — no lock, no dict lookup, no allocation. Collectors
run only at snapshot time and are *always* consulted (they read
counters the host subsystems maintain anyway), so a disabled-mode
snapshot still carries the unified engine/serve/resilience numbers —
which is what lets ``bench.py`` embed a snapshot in every benchmarks
record without turning telemetry on.

Enablement: ``SKYLARK_TELEMETRY=1`` or ``SKYLARK_TELEMETRY_DIR=<dir>``
(the latter also installs the JSONL exporter —
:mod:`libskylark_tpu.telemetry.export`), or :func:`set_enabled`
programmatically.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks

# ---------------------------------------------------------------------------
# enablement: one module-level bool, read without a lock on the hot path
# ---------------------------------------------------------------------------

_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """Whether telemetry recording is on (``SKYLARK_TELEMETRY=1`` /
    ``SKYLARK_TELEMETRY_DIR`` set / :func:`set_enabled`)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = (bool(_env.TELEMETRY.get())
                    or bool(_env.TELEMETRY_DIR.get()))
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic switch (overrides the environment gate)."""
    global _ENABLED
    _ENABLED = bool(on)


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

#: Default histogram bucket bounds (seconds-flavored: compile times,
#: flush latencies). A fixed, shared vector keeps every histogram
#: mergeable and the record path allocation-free.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """Common base: name, help text, a lock-guarded per-label store."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",  # noqa: A002 - prom idiom
                 registry: "Optional[MetricsRegistry]" = None):
        self.name = name
        self.help = help
        self._lock = _locks.make_lock("telemetry.metric")
        self._values: Dict[Tuple, float] = {}
        self._registry = registry

    def _base_doc(self) -> dict:
        return {"type": self.kind, "help": self.help}

    def to_dict(self) -> dict:
        with self._lock:
            doc = self._base_doc()
            doc["values"] = [
                {"labels": dict(k), "value": v}
                for k, v in sorted(self._values.items())
            ]
        return doc

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class LifetimeCounter:
    """Process-lifetime event totals that survive their owning object.

    Collectors report *live* objects only (routers, autoscalers live
    in WeakSets), so a snapshot taken after an episode's object is
    gone would silently drop its events; subsystems keep one of these
    at module level and fold :meth:`snapshot` into their collector
    block. Always on (the counted event dwarfs the bump), never
    reset."""

    __slots__ = ("_lock", "_values")

    def __init__(self, site: str, kinds: Sequence[str] = ()):
        self._lock = _locks.make_lock(site)
        # pre-seeded kinds always appear in the snapshot, zero or not
        # — consumers (benchmark records) key off their presence
        self._values: Dict[str, int] = {k: 0 for k in kinds}

    def inc(self, kind: str, n: int = 1) -> None:
        with self._lock:
            self._values[kind] = self._values.get(kind, 0) + n

    def get(self, kind: str) -> int:
        with self._lock:
            return self._values.get(kind, 0)

    def snapshot(self, prefix: str = "lifetime_") -> Dict[str, int]:
        with self._lock:
            return {prefix + k: v for k, v in sorted(self._values.items())}


class Counter(Metric):
    """Monotonically increasing count. ``inc()`` is the only mutator."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if not enabled():
            return
        self.inc_always(n, **labels)

    def inc_always(self, n: float = 1, **labels) -> None:
        """Record regardless of the global gate — for adapters counting
        events a host subsystem already pays for (e.g. a WebHDFS
        reconnect: the reconnect itself dwarfs the counter bump)."""
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)


class Gauge(Metric):
    """A value that goes up and down (queue depth, last objective)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        if not enabled():
            return
        self.set_always(v, **labels)

    def set_always(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def add(self, n: float = 1, **labels) -> None:
        if not enabled():
            return
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0) + n

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(_label_key(labels))


class Histogram(Metric):
    """Fixed-bucket histogram: cumulative bucket counts + sum + count
    per label set (the Prometheus classic-histogram layout)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",  # noqa: A002
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 registry: "Optional[MetricsRegistry]" = None):
        super().__init__(name, help, registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per-label-key: [bucket counts..., +Inf count], sum
        self._hist: Dict[Tuple, list] = {}

    def observe(self, v: float, **labels) -> None:
        if not enabled():
            return
        self.observe_always(v, **labels)

    def observe_always(self, v: float, **labels) -> None:
        v = float(v)
        k = _label_key(labels)
        with self._lock:
            cell = self._hist.get(k)
            if cell is None:
                cell = self._hist[k] = [[0] * (len(self.buckets) + 1), 0.0]
            counts, _ = cell
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            cell[1] += v

    def to_dict(self) -> dict:
        with self._lock:
            doc = self._base_doc()
            doc["buckets"] = list(self.buckets)
            doc["values"] = [
                {"labels": dict(k),
                 "counts": list(counts),
                 "count": sum(counts),
                 "sum": total}
                for k, (counts, total) in sorted(self._hist.items())
            ]
        return doc

    def reset(self) -> None:
        with self._lock:
            self._hist.clear()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Get-or-create store of instruments plus named collectors.

    Instruments are created once (idempotent by name — a second
    ``counter("x")`` returns the first) and live for the process;
    collectors are ``name -> zero-arg callable`` returning a JSON-able
    dict, consulted at :meth:`snapshot` time. A collector that raises
    contributes an ``{"error": ...}`` block instead of failing the
    snapshot — telemetry must never be a failure mode.
    """

    def __init__(self):
        self._lock = _locks.make_lock("telemetry.registry")
        self._metrics: Dict[str, Metric] = {}
        self._collectors: Dict[str, Callable[[], dict]] = {}

    def _get_or_create(self, cls, name: str, help: str,  # noqa: A002
                       **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, registry=self,
                                              **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",  # noqa: A002
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def register_collector(self, name: str,
                           fn: Callable[[], dict]) -> None:
        """Adapter seam: re-home an existing stats block (engine cache
        counters, serve executor stats, ...) under the unified snapshot
        without double-counting. Idempotent per name (latest wins, so a
        test can stub one out)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def metrics(self) -> Dict[str, Metric]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> dict:
        """The whole registry as one JSON-able document: direct
        instruments under ``"metrics"``, adapter blocks under
        ``"collectors"``."""
        with self._lock:
            metrics = dict(self._metrics)
            collectors = dict(self._collectors)
        doc: dict = {
            "enabled": enabled(),
            "metrics": {name: m.to_dict()
                        for name, m in sorted(metrics.items())},
            "collectors": {},
        }
        for name, fn in sorted(collectors.items()):
            try:
                doc["collectors"][name] = fn()
            except Exception as e:  # noqa: BLE001 — snapshot never fails
                doc["collectors"][name] = {"error": repr(e)}
        return doc

    def reset(self) -> None:
        """Zero every instrument's values (tests). Instruments and
        collectors stay registered — module-level handles must survive
        a reset."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every wired subsystem records to."""
    return _REGISTRY


# module-level conveniences bound to the global registry


def counter(name: str, help: str = "") -> Counter:  # noqa: A002
    return _REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:  # noqa: A002
    return _REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",  # noqa: A002
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, help, buckets)


def register_collector(name: str, fn: Callable[[], dict]) -> None:
    _REGISTRY.register_collector(name, fn)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


__all__ = [
    "Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram",
    "LifetimeCounter", "Metric", "MetricsRegistry", "counter",
    "enabled", "gauge", "histogram", "register_collector", "registry",
    "set_enabled", "snapshot",
]
