"""Declared metric names: the single source of truth the
``metric-names`` lint rule checks call sites against.

Every counter/gauge/histogram recorded anywhere in
``libskylark_tpu`` must be declared here once — (name, kind, one-line
role) — and created at exactly one call site. The rule
(:mod:`libskylark_tpu.analysis.rules.metric_names`) flags:

- a creation call whose name is not declared here (typo'd or
  undocumented metric);
- a name created at more than one site (two sites would silently share
  one instrument — or worse, disagree on its kind and raise at import);
- a declaration with no remaining call site (stale — delete it);
- a name that cannot render as a valid Prometheus metric (the exporter
  maps ``.`` to ``_``; everything else must already conform).

Naming convention: ``<subsystem>.<noun>`` (dots become underscores on
the Prometheus surface, and counters grow ``_total`` there —
``engine.compile_seconds`` scrapes as
``skylark_engine_compile_seconds``).
"""

from __future__ import annotations

from typing import Dict

#: name -> kind ("counter" | "gauge" | "histogram")
METRICS: Dict[str, str] = {
    # engine (engine/compiled.py)
    "engine.compile_seconds": "histogram",
    "engine.load_seconds": "histogram",
    "engine.persistent_cache_failures": "counter",
    # telemetry's own bookkeeping (telemetry/trace.py)
    "telemetry.spans": "counter",
    # tune (tune/cache.py)
    "tune.plan_cache_lookups": "counter",
    # ml (ml/admm.py)
    "ml.admm.iterations": "counter",
    "ml.admm.objective": "gauge",
    "ml.admm.reldel": "gauge",
    # io (io/chunked.py, io/webhdfs.py)
    "io.chunked.batches": "counter",
    "io.webhdfs.reconnects": "counter",
    # resilience (resilience/faults.py, policy.py, health.py)
    "resilience.faults_fired": "counter",
    "resilience.retries": "counter",
    "resilience.health_transitions": "counter",
    # sparse serve operands (engine/serve.py, docs/serving)
    "serve.sparse_submits": "counter",
    "serve.sparse_densified": "counter",
    "serve.sparse_kernel_flushes": "counter",
    "serve.sparse_nnz_class": "histogram",
    # FWHT serve tier (engine/serve.py, docs/performance)
    "serve.fwht_flushes": "counter",
    "serve.compressed_matmul_submits": "counter",
    # stateful serve sessions (sessions/registry.py)
    "sessions.opened": "counter",
    "sessions.appends": "counter",
    "sessions.finalized": "counter",
    "sessions.evicted": "counter",
    "sessions.resumed": "counter",
    "sessions.replayed_records": "counter",
    "sessions.checkpoints": "counter",
    "sessions.fenced": "counter",
    "sessions.live": "gauge",
    # distributed sketching (dist/coordinator.py)
    "dist.shards_dispatched": "counter",
    "dist.shards_retried": "counter",
    "dist.shards_reassigned": "counter",
    "dist.shards_abandoned": "counter",
    "dist.merges": "counter",
    "dist.coverage": "gauge",
    # pipelined dist-serve jobs (dist/serve.py, docs/distributed)
    "dist.shard_tasks": "counter",
    "dist.merge_depth": "gauge",
    "dist.jobs": "counter",
    "dist.early_resolves": "counter",
    # multi-tenant QoS (qos/tenants.py, qos/controller.py,
    # engine/serve.py — docs/qos)
    "qos.admitted": "counter",
    "qos.shed": "counter",
    "qos.rate_limited": "counter",
    "qos.queue_depth": "gauge",
    "qos.request_latency": "histogram",
    "qos.linger_target": "gauge",
    "qos.batch_target": "gauge",
    # content-addressed result cache (engine/resultcache.py,
    # docs/caching) — rendered as skylark_cache_* on Prometheus
    "cache.hits": "counter",
    "cache.misses": "counter",
    "cache.bytes_saved": "counter",
    "cache.evicted": "counter",
    "cache.single_flight_coalesced": "counter",
    "cache.resident_operands": "gauge",
    # fleet (fleet/router.py)
    "fleet.session_handoffs": "counter",
    "fleet.routed": "counter",
    "fleet.affinity_hit": "counter",
    "fleet.failover": "counter",
    "fleet.spilled": "counter",
    "fleet.hedged": "counter",
    "fleet.hedge_wins": "counter",
    "fleet.hedge_mismatches": "counter",
    # fleet shared-memory transport (fleet/shm.py)
    "fleet.shm_sends": "counter",
    "fleet.shm_fallbacks": "counter",
    # fleet autoscaler (fleet/autoscale.py)
    "fleet.autoscale_up": "counter",
    "fleet.autoscale_down": "counter",
    "fleet.replicas": "gauge",
    # training jobs (train/jobs.py, docs/training)
    "train.jobs_submitted": "counter",
    "train.slices_run": "counter",
    "train.preemptions": "counter",
    "train.resumes": "counter",
    "train.budget_exhausted": "counter",
    "train.progress": "gauge",
    "train.residual": "gauge",
    # network serve front door (net/server.py, docs/networking) —
    # rendered as skylark_net_* on Prometheus via the net collector
    "net.connections": "gauge",
    "net.requests": "counter",
    "net.wire_errors": "counter",
    "net.bytes_in": "counter",
    "net.bytes_out": "counter",
    "net.drains": "counter",
}

__all__ = ["METRICS"]
