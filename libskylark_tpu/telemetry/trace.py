"""Structured request tracing: spans, contextvar linkage, cross-thread
handoff, and a ``jax.profiler.TraceAnnotation`` mirror.

A **span** is one named, timed unit of work with parent/child linkage::

    with telemetry.span("serve.flush", attrs={"capacity": 8}) as sp:
        ...  # children opened inside nest under sp automatically

Linkage is :mod:`contextvars`-based, so nesting works across the async
boundaries jax cares about within one thread. Threads do **not**
inherit context — which is correct for the serve layer (a flush worker
must not accidentally parent under whatever the submitting thread was
doing) — so crossing a thread is *explicit*: capture
:func:`get_context` where the request is born, hand the
:class:`SpanContext` over with the work item, and :func:`attach` it in
the executing thread (or pass it as ``parent=`` to the next span).
``MicrobatchExecutor.submit`` does exactly this: the request id minted
at submit rides the queued request into the flush thread and every
bisection-isolation retry.

Every real span also enters a ``jax.profiler.TraceAnnotation`` with its
name, so host-side spans line up with the device timeline under
``jax.profiler.trace`` — the bridge that makes per-stage device
timelines first-class (FlashSketch's argument: sketch-kernel perf work
is only trustworthy with them).

Cost discipline: a disabled :func:`span` is one branch returning a
shared no-op context manager — no allocation, no contextvar write.
``force=True`` opens a real span regardless of the global gate; the
:class:`~libskylark_tpu.utility.timer.PhaseTimer` shim uses it so the
``SKYLARK_TPU_PROFILE`` phase timers keep their own independent
enablement.

Finished spans go to the bounded in-memory ring (:func:`finished_spans`
— tests, debugging) and to every registered sink
(:func:`add_sink`; the JSONL exporter in
:mod:`libskylark_tpu.telemetry.export` is one).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional

from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics

# ---------------------------------------------------------------------------
# ids
# ---------------------------------------------------------------------------

_ids = itertools.count(1)
# full pid + 32 random bits, drawn ONCE: ids stay cheap per span (no
# urandom syscall on the hot path) yet unique across the processes that
# share one SKYLARK_TELEMETRY_DIR — a truncated pid would collide for
# pids congruent mod the truncation under Linux's large pid_max
_ID_PREFIX = f"{os.getpid():x}-{os.urandom(4).hex()}"


def _new_id() -> str:
    return f"{_ID_PREFIX}-{next(_ids):08x}"


def new_request_id() -> str:
    """Mint a request id (the serve layer calls this at submit when the
    caller didn't provide one)."""
    return f"req-{_new_id()}"


# ---------------------------------------------------------------------------
# span + context
# ---------------------------------------------------------------------------


class SpanContext:
    """The portable identity of a span: what crosses threads/processes.
    Carries the trace id, the span id (the future parent), and the
    request id baggage the serve pipeline threads end to end."""

    __slots__ = ("trace_id", "span_id", "request_id")

    def __init__(self, trace_id: str, span_id: str,
                 request_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.request_id = request_id

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"SpanContext(trace={self.trace_id}, span={self.span_id}, "
                f"request={self.request_id})")


class Span:
    """One in-flight (then finished) traced operation."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "request_id",
                 "attrs", "events", "t_wall", "duration_s", "status",
                 "error", "thread")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 request_id: Optional[str], attrs: Optional[dict]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.request_id = request_id
        self.attrs = dict(attrs) if attrs else {}
        self.events: list = []
        self.t_wall = time.time()
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self.error: Optional[str] = None
        self.thread = threading.current_thread().name

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, attrs: Optional[dict] = None) -> None:
        self.events.append({"name": name, "t": time.time(),
                            "attrs": dict(attrs) if attrs else {}})

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.request_id)

    def to_dict(self) -> dict:
        doc = {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_wall": round(self.t_wall, 6),
            "duration_s": (round(self.duration_s, 9)
                           if self.duration_s is not None else None),
            "status": self.status,
            "thread": self.thread,
        }
        if self.request_id is not None:
            doc["request_id"] = self.request_id
        if self.attrs:
            doc["attrs"] = self.attrs
        if self.events:
            doc["events"] = self.events
        if self.error is not None:
            doc["error"] = self.error
        return doc


# the active span (or attached remote SpanContext) for this context
_CURRENT: "contextvars.ContextVar[Optional[object]]" = \
    contextvars.ContextVar("skylark_telemetry_span", default=None)

_FINISHED: "deque[Span]" = deque(maxlen=2048)
_SINKS: "list[Callable[[Span], None]]" = []
_SINK_LOCK = _locks.make_lock("telemetry.sink")

_span_count = _metrics.counter(
    "telemetry.spans", "Finished telemetry spans, by name and status")


def current_span() -> Optional[Span]:
    cur = _CURRENT.get()
    return cur if isinstance(cur, Span) else None


def get_context() -> Optional[SpanContext]:
    """The calling context's span identity, for explicit cross-thread
    handoff (``None`` outside any span)."""
    cur = _CURRENT.get()
    if isinstance(cur, Span):
        return cur.context()
    if isinstance(cur, SpanContext):
        return cur
    return None


@contextlib.contextmanager
def attach(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Adopt a :class:`SpanContext` captured in another thread: spans
    opened inside the block parent under it (and inherit its request
    id). ``attach(None)`` is a no-op block."""
    if ctx is None:
        yield
        return
    token = _CURRENT.set(ctx)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def _jax_annotation(name: str):
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax always importable here
        return contextlib.nullcontext()


class _NoopSpanCm:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpanCm()


class _SpanCm:
    """Real-span context manager (class, not @contextmanager: the
    serve submit path opens one per request and the generator protocol
    costs ~2x a plain __enter__/__exit__ pair)."""

    __slots__ = ("span", "_token", "_ann", "_t0")

    def __init__(self, name: str, attrs: Optional[dict],
                 parent: Optional[SpanContext],
                 request_id: Optional[str]):
        cur = _CURRENT.get()
        if parent is None and cur is not None:
            parent = cur.context() if isinstance(cur, Span) else cur
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
            if request_id is None:
                request_id = parent.request_id
        else:
            trace_id = _new_id()
            parent_id = None
        self.span = Span(name, trace_id, parent_id, request_id, attrs)
        self._token = None
        self._ann = None
        self._t0 = 0.0

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self.span)
        self._ann = _jax_annotation(self.span.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self.span
        s.duration_s = time.perf_counter() - self._t0
        try:
            self._ann.__exit__(exc_type, exc, tb)
        except Exception:  # pragma: no cover - profiler teardown
            pass
        if exc is not None:
            s.status = "error"
            s.error = repr(exc)
        _CURRENT.reset(self._token)
        _finish(s)
        return False


def span(name: str, attrs: Optional[dict] = None, *,
         parent: Optional[SpanContext] = None,
         request_id: Optional[str] = None,
         force: bool = False):
    """Open a span (context manager yielding the :class:`Span`, or
    ``None`` when telemetry is disabled and ``force`` is not set).

    ``parent`` overrides the ambient contextvar parent (cross-thread
    handoff); ``request_id`` pins the id explicitly (else inherited
    from the parent); ``force`` opens a real span regardless of the
    global gate (the PhaseTimer shim's hook — phase timers keep their
    own ``SKYLARK_TPU_PROFILE`` enablement)."""
    if not (force or _metrics.enabled()):
        return _NOOP
    return _SpanCm(name, attrs, parent, request_id)


def add_event(name: str, attrs: Optional[dict] = None) -> None:
    """Append an event to the current span (no-op outside one, or
    disabled) — e.g. a resilience retry attempt recording itself on
    whatever span is executing."""
    cur = current_span()
    if cur is not None:
        cur.add_event(name, attrs)


# ---------------------------------------------------------------------------
# finished-span fanout
# ---------------------------------------------------------------------------


def _finish(s: Span) -> None:
    _FINISHED.append(s)
    _span_count.inc_always(name=s.name, status=s.status)
    with _SINK_LOCK:
        sinks = list(_SINKS)
    for sink in sinks:
        try:
            sink(s)
        except Exception:  # noqa: BLE001 — a sink must never fail work
            pass


def add_sink(fn: Callable[[Span], None]) -> Callable[[], None]:
    """Register a finished-span consumer; returns the unregister
    callable."""
    with _SINK_LOCK:
        _SINKS.append(fn)

    def unregister() -> None:
        with _SINK_LOCK:
            try:
                _SINKS.remove(fn)
            except ValueError:
                pass

    return unregister


def finished_spans(n: Optional[int] = None) -> list:
    """The most recent finished spans (bounded ring; tests/debug)."""
    spans = list(_FINISHED)
    return spans if n is None else spans[-n:]


def clear_finished() -> None:
    _FINISHED.clear()


__all__ = [
    "Span", "SpanContext", "add_event", "add_sink", "attach",
    "clear_finished", "current_span", "finished_spans", "get_context",
    "new_request_id", "span",
]
