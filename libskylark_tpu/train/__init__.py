"""Training-as-a-service: preemptible, crash-survivable iterative
solver jobs inside the serve tier (docs/training).

- :mod:`libskylark_tpu.train.slices` — pure bounded-iteration slice
  engines over the foreground solvers (ADMM-KRR, LSQR, CG, randomized
  block Gauss–Seidel) plus the deterministic state byte codec;
- :mod:`libskylark_tpu.train.state` — the session-state adapter that
  makes a job a ``kind="train"`` session (journal, checkpoint, lease
  fencing all inherited);
- :mod:`libskylark_tpu.train.jobs` — the per-executor manager that
  schedules slices as best-effort work and owns retry/budget/resume
  semantics.
"""

from libskylark_tpu.train.jobs import (TrainJobHandle, TrainJobSpec,
                                       TrainManager, train_stats)
from libskylark_tpu.train.slices import (SOLVERS, decode_state,
                                         encode_state, make_engine,
                                         step_bytes)
from libskylark_tpu.train.state import TrainSessionState

__all__ = [
    "SOLVERS", "TrainJobHandle", "TrainJobSpec", "TrainManager",
    "TrainSessionState", "decode_state", "encode_state", "make_engine",
    "step_bytes", "train_stats",
]
