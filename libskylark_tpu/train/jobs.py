"""Training jobs: preemptible, crash-survivable solver runs inside the
serve tier.

The manager here fuses three existing subsystems into
training-as-a-service (ROADMAP item 4; docs/training):

- **durability** — a job is a session of kind ``"train"``
  (:mod:`libskylark_tpu.train.state`): every slice is journaled before
  it acks, state checkpoints on a cadence, and lease-generation
  fencing arbitrates ownership — so ``kill -9`` loses nothing past the
  last acked slice and any replica resumes bit-equal;
- **scheduling** — slices run as ``best_effort`` work: the
  microbatch flusher offers the :class:`~libskylark_tpu.qos.scheduler.
  DeficitScheduler` a train sentinel only when no higher class has
  backlog, so training soaks idle slots and yields at slice
  boundaries, never mid-step (the preemption contract);
- **reporting** — per-job progress/residual gauges, job counters in
  ``stats()["train"]`` / ``serve_stats()`` / Prometheus, and a
  terminal :class:`~libskylark_tpu.base.errors.
  TrainBudgetExhaustedError` carrying exact iterations completed.

Threading contract: the executor's flusher consults
:meth:`TrainManager.has_runnable` / :meth:`claim_next` /
:meth:`note_deferred` under the serve lock (lock order
``engine.serve → train.manager``); :meth:`run_slice` executes on a
dispatch worker with NO serve lock held and takes the manager lock
only for queue bookkeeping — never across the solver step or any
session verb, so ``train.manager`` sits above the ``sessions.*``
locks in the order graph and the witness stays acyclic.
"""

from __future__ import annotations

import collections
import dataclasses
import time
import uuid
import weakref
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.resilience import faults
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.train import slices as _slices
from libskylark_tpu.train import state as _tstate

_JOBS = _metrics.counter(
    "train.jobs_submitted", "Training jobs submitted to the serve tier")
_SLICES = _metrics.counter(
    "train.slices_run", "Training slices executed (journaled and "
    "acked)")
_PREEMPTIONS = _metrics.counter(
    "train.preemptions", "Training slices displaced at a slice "
    "boundary by higher-class pressure (one per deferral episode)")
_RESUMES = _metrics.counter(
    "train.resumes", "Training jobs resumed from disk on a surviving "
    "replica (drain handoff or crash replay)")
_BUDGET = _metrics.counter(
    "train.budget_exhausted", "Training jobs terminated by iteration "
    "budget or wall-clock deadline before convergence")
_PROGRESS = _metrics.gauge(
    "train.progress", "Per-job training progress: solver iterations "
    "completed over the iteration budget, in [0, 1]")
_RESIDUAL = _metrics.gauge(
    "train.residual", "Per-job most recent convergence signal "
    "(solver-specific residual)")


@dataclasses.dataclass(frozen=True)
class TrainJobSpec:
    """Everything a replica needs to run — or resume — a training job.

    ``solver`` names a slice engine (:data:`libskylark_tpu.train.
    slices.SOLVERS`); ``hyper`` its hyperparameters (including the
    seed every transform derives from). Budgets speak the QoS
    vocabulary: ``budget_iters`` is the iteration budget (the
    session's declared extent — slices past it refuse),
    ``deadline_s`` the wall-clock budget measured from
    ``submitted_at`` (stamped at submit, so a resume on another
    replica enforces the ORIGINAL deadline, not a fresh one). ``None``
    knobs fall back to their ``SKYLARK_TRAIN_*`` defaults at use."""

    solver: str
    hyper: dict = dataclasses.field(default_factory=dict)
    budget_iters: int = 256
    slice_iters: Optional[int] = None
    deadline_s: Optional[float] = None
    retry_budget: Optional[int] = None
    checkpoint_every: Optional[int] = None
    tenant: str = ""
    ttl_s: Optional[float] = None
    submitted_at: Optional[float] = None
    operand_digests: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> "TrainJobSpec":
        if self.solver not in _slices.SOLVERS:
            raise errors.InvalidParametersError(
                f"unknown train solver {self.solver!r}; expected one "
                f"of {_slices.SOLVERS}")
        if self.budget_iters < 1:
            raise errors.InvalidParametersError(
                f"budget_iters must be positive, got "
                f"{self.budget_iters}")
        if self.slice_iters is not None and self.slice_iters < 1:
            raise errors.InvalidParametersError(
                f"slice_iters must be positive, got {self.slice_iters}")
        return self

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TrainJobSpec":
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls)
                      if f.name in d}).validate()

    # effective knobs (env-defaulted)

    @property
    def eff_slice_iters(self) -> int:
        return int(self.slice_iters
                   if self.slice_iters is not None
                   else _env.TRAIN_SLICE_ITERS.get())

    @property
    def eff_deadline_s(self) -> float:
        return float(self.deadline_s
                     if self.deadline_s is not None
                     else _env.TRAIN_DEADLINE_S.get())

    @property
    def eff_retry_budget(self) -> int:
        return int(self.retry_budget
                   if self.retry_budget is not None
                   else _env.TRAIN_RETRY_BUDGET.get())

    @property
    def eff_checkpoint_every(self) -> int:
        return int(self.checkpoint_every
                   if self.checkpoint_every is not None
                   else _env.TRAIN_CKPT_EVERY.get())


class TrainJobHandle:
    """What ``submit_train_job`` returns: the job/session id plus a
    future resolving to the trained model dict (``iterations``,
    ``residual``, ``converged`` included) or the terminal error."""

    __slots__ = ("job_id", "session_id", "future")

    def __init__(self, job_id: str, future: Future):
        self.job_id = job_id
        self.session_id = job_id
        self.future = future

    def result(self, timeout: Optional[float] = None):
        return self.future.result(timeout)


class _Job:
    """Mutable runtime record of one job on THIS replica. Queue/state
    transitions happen under the manager lock; slice-local fields
    (``slices_done``, ``rows``, ``residual``) are touched only by the
    single in-flight slice runner (at most one slice of a job runs at
    a time, by construction)."""

    __slots__ = ("sid", "spec", "future", "slices_done", "rows",
                 "retries_left", "running", "queued", "done",
                 "deferred", "tags", "residual", "resumed")

    def __init__(self, sid: str, spec: TrainJobSpec,
                 slices_done: int = 0, rows: int = 0,
                 resumed: bool = False):
        self.sid = sid
        self.spec = spec
        self.future: Future = Future()
        self.slices_done = int(slices_done)
        self.rows = int(rows)
        self.retries_left = spec.eff_retry_budget
        self.running = False
        self.queued = False
        self.done = False
        self.deferred = False
        self.tags = faults.current_tags()
        self.residual: Optional[float] = None
        self.resumed = bool(resumed)

    @property
    def deadline_exceeded(self) -> bool:
        t0 = self.spec.submitted_at
        return (t0 is not None
                and time.time() - float(t0) > self.spec.eff_deadline_s)


class TrainManager:
    """Per-executor training job manager (built lazily by
    :attr:`MicrobatchExecutor.train_jobs`)."""

    def __init__(self, executor):
        self._ex = weakref.proxy(executor)
        self._lock = _locks.make_lock("train.manager")
        self._jobs: Dict[str, _Job] = {}
        self._queue: "collections.deque[_Job]" = collections.deque()
        self._counts = {"jobs_submitted": 0, "slices_run": 0,
                        "preemptions": 0, "resumes": 0,
                        "budget_exhausted": 0, "completed": 0,
                        "failed": 0, "retries": 0}
        _MANAGERS.add(self)

    # -- submission ------------------------------------------------------

    def _resolve_operands(self, operands: dict) -> tuple:
        """Materialize operand values: residency refs (r20
        ``OperandRef``) resolve against the executor's residency
        table; arrays pass through. Returns ``(arrays, digests)`` —
        the digests ride the spec as the job's operand identity."""
        from libskylark_tpu.engine import resultcache as _rcache
        from libskylark_tpu.utility.checkpoint import sample_digest

        arrays, digests = {}, {}
        for name, val in (operands or {}).items():
            if _rcache.is_ref(val):
                ref = _rcache.as_ref(val)
                val = self._ex._residency.resolve(ref.digest)
                digests[name] = str(ref.digest)
            arr = np.asarray(val)
            digests.setdefault(name, sample_digest(arr))
            arrays[name] = arr
        return arrays, digests

    def submit(self, spec, operands: Optional[dict] = None,
               session_id: Optional[str] = None) -> TrainJobHandle:
        """Open the job's session (operands persisted durably FIRST,
        then the session with the spec in ``extra``), pin it against
        TTL eviction for the job's lifetime, and enqueue the first
        slice. Returns immediately; the handle's future resolves when
        the job converges, exhausts its budget, or fails terminally."""
        from libskylark_tpu.sessions.state import SessionSpec

        if isinstance(spec, dict):
            spec = TrainJobSpec.from_dict(spec)
        spec.validate()
        sid = str(session_id) if session_id \
            else f"train-{uuid.uuid4().hex[:12]}"
        arrays, digests = self._resolve_operands(operands)
        if not arrays:
            raise errors.InvalidParametersError(
                "train jobs need operands (solver inputs)")
        spec = dataclasses.replace(
            spec,
            submitted_at=(spec.submitted_at
                          if spec.submitted_at is not None
                          else time.time()),
            operand_digests=digests)
        reg = self._ex.sessions
        _tstate.save_operands(reg.directory, sid, arrays, digests)
        sspec = SessionSpec(kind="train", n=int(spec.budget_iters),
                            s_dim=1, d=1,
                            seed=int(spec.hyper.get("seed", 0)),
                            ttl_s=spec.ttl_s, extra=spec.to_dict())
        try:
            reg.open(sspec, session_id=sid)
        except BaseException:
            _tstate.remove_operands(reg.directory, sid)
            raise
        reg.pin(sid)
        job = _Job(sid, spec)
        with self._lock:
            self._jobs[sid] = job
            self._enqueue_locked(job)
            self._counts["jobs_submitted"] += 1
        _JOBS.inc(solver=spec.solver)
        self._ex._wake_flusher()
        return TrainJobHandle(sid, job.future)

    def resume(self, session_id: str) -> TrainJobHandle:
        """Adopt a job from its on-disk session (drain handoff or
        crash replay): the registry resume rebuilds the solver state
        bit-equal from checkpoint + journal tail; the job continues
        from its last acked slice under its ORIGINAL deadline. A job
        already live on this manager returns its existing handle (the
        router's failover may race a redundant resume)."""
        sid = str(session_id)
        with self._lock:
            existing = self._jobs.get(sid)
            if existing is not None and not existing.done:
                return TrainJobHandle(sid, existing.future)
        reg = self._ex.sessions
        desc = reg.describe(sid)            # triggers the disk resume
        extra = (desc.get("spec") or {}).get("extra")
        if not extra:
            raise errors.InvalidParametersError(
                f"session {sid!r} is not a train session")
        spec = TrainJobSpec.from_dict(extra)
        reg.pin(sid)
        job = _Job(sid, spec, slices_done=int(desc.get("seq", 0)),
                   rows=int(desc.get("rows", 0)), resumed=True)
        info = desc.get("info") or {}
        job.residual = info.get("residual")
        with self._lock:
            raced = self._jobs.get(sid)
            if raced is not None and not raced.done:
                reg.unpin(sid)
                return TrainJobHandle(sid, raced.future)
            self._jobs[sid] = job
            self._enqueue_locked(job)
            self._counts["resumes"] += 1
        _RESUMES.inc()
        self._ex._wake_flusher()
        return TrainJobHandle(sid, job.future)

    def status(self, session_id: str) -> dict:
        """Progress snapshot of a job known to this manager."""
        sid = str(session_id)
        with self._lock:
            job = self._jobs.get(sid)
            if job is None:
                raise errors.SessionEvictedError(
                    f"train job {sid!r} is not live on this replica")
            return {
                "job_id": sid,
                "solver": job.spec.solver,
                "slices_done": job.slices_done,
                "iterations_requested": job.rows,
                "budget_iters": job.spec.budget_iters,
                "residual": job.residual,
                "queued": job.queued,
                "running": job.running,
                "done": job.done,
                "retries_left": job.retries_left,
            }

    # -- scheduling hooks (called by the flusher under the serve lock) --

    def _enqueue_locked(self, job: _Job) -> None:
        if not job.queued and not job.done:
            job.queued = True
            self._queue.append(job)

    def has_runnable(self) -> bool:
        with self._lock:
            return bool(self._queue)

    def claim_next(self) -> Optional[_Job]:
        """Pop the next runnable job and mark its slice in flight."""
        with self._lock:
            while self._queue:
                job = self._queue.popleft()
                job.queued = False
                if job.done:
                    continue
                job.running = True
                job.deferred = False
                return job
        return None

    def note_deferred(self) -> None:
        """Runnable training work yielded its slot to higher-class
        pressure — the preemption counter's boundary event. Counted
        once per deferral EPISODE (per queued job), not once per
        flusher pass, so a long interactive storm reads as one
        preemption per displaced job rather than thousands."""
        n = 0
        with self._lock:
            for job in self._queue:
                if not job.deferred:
                    job.deferred = True
                    n += 1
            if n:
                self._counts["preemptions"] += n
        if n:
            _PREEMPTIONS.inc(n)

    # -- slice execution (dispatch worker; NO serve lock held) -----------

    def run_slice(self, job: _Job) -> None:
        """Execute one slice of ``job``: fault seam → journaled append
        (the fold runs the solver) → gauges → cadence checkpoint →
        terminal/requeue decision. Every error path resolves the job
        future or requeues — a slice never leaves the job wedged."""
        reg = self._ex.sessions
        sid = job.sid
        try:
            if job.deadline_exceeded:
                self._exhaust(job, reason=(
                    f"wall-clock deadline "
                    f"{job.spec.eff_deadline_s:.6g}s exceeded"))
                return
            k = min(job.spec.eff_slice_iters,
                    job.spec.budget_iters - job.rows)
            if k <= 0:
                self._exhaust(job, reason=(
                    f"iteration budget {job.spec.budget_iters} "
                    "exhausted before convergence"))
                return
            target = job.slices_done + 1
            # the crash seam fires BEFORE the append: a ``crash`` spec
            # kills the replica with the slice NOT yet durable, so the
            # resume replays exactly the acked prefix (never a torn
            # half-slice) — benchmarks/train_smoke.py drives this
            faults.check("train.slice", tags=job.tags,
                         detail=f"{sid}#{target}")
            seq, rows = reg.append(
                sid, np.asarray([[k]], dtype=np.int64), seq=target,
                tags=job.tags)
            job.slices_done, job.rows = int(seq), int(rows)
            with self._lock:
                self._counts["slices_run"] += 1
            _SLICES.inc(solver=job.spec.solver)
            desc = reg.describe(sid)
            info = desc.get("info") or {}
            job.residual = info.get("residual")
            _PROGRESS.set(
                min(1.0, job.rows / max(1, job.spec.budget_iters)),
                job=sid)
            if job.residual is not None:
                _RESIDUAL.set(float(job.residual), job=sid)
            if job.slices_done % job.spec.eff_checkpoint_every == 0:
                reg.checkpoint(sid)
            if info.get("converged"):
                result = reg.finalize(sid)
                self._finish(job, result=result)
            elif job.rows >= job.spec.budget_iters:
                self._exhaust(job, reason=(
                    f"iteration budget {job.spec.budget_iters} "
                    "exhausted before convergence"))
            else:
                self._requeue(job)
        except errors.SessionEvictedError as e:
            # fenced (a peer adopted the job) or evicted: terminal
            # HERE — retrying would ping-pong the lease with the new
            # owner. The future only errors if no peer will resolve
            # it (the router resolves the client future through
            # whichever replica finishes the job).
            self._finish(job, error=e)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — contain, retry
            self._retry_or_fail(job, e)

    # -- terminal transitions -------------------------------------------

    def _settle(self, job: _Job) -> None:
        with self._lock:
            job.running = False
            job.queued = False
            job.done = True

    def _finish(self, job: _Job, result=None, error=None) -> None:
        self._settle(job)
        self._ex.sessions.unpin(job.sid)
        with self._lock:
            self._jobs.pop(job.sid, None)
            if error is None:
                self._counts["completed"] += 1
            else:
                self._counts["failed"] += 1
        if not job.future.done():
            if error is None:
                job.future.set_result(result)
            else:
                job.future.set_exception(error)

    def _exhaust(self, job: _Job, reason: str) -> None:
        """Terminal budget/deadline exhaustion: checkpoint what we
        have (the caller may resubmit with a larger budget against a
        fresh id), evict the session, and report EXACT progress —
        never a silent failure."""
        reg = self._ex.sessions
        iterations = job.rows
        residual = job.residual
        try:
            desc = reg.describe(job.sid)
            info = desc.get("info") or {}
            iterations = int(info.get("iterations", iterations))
            residual = info.get("residual", residual)
        except errors.SkylarkError:
            pass
        err = errors.TrainBudgetExhaustedError(
            f"train job {job.sid!r} ({job.spec.solver}): {reason}; "
            f"{iterations} iterations over {job.slices_done} slices "
            f"completed, last residual {residual}",
            iterations=iterations, residual=residual,
            slices=job.slices_done)
        with self._lock:
            self._counts["budget_exhausted"] += 1
        _BUDGET.inc(solver=job.spec.solver)
        try:
            reg.evict(job.sid, reason="train_budget")
        except errors.SkylarkError:
            pass
        self._finish(job, error=err)

    def _retry_or_fail(self, job: _Job, exc: BaseException) -> None:
        job.retries_left -= 1
        if job.retries_left >= 0:
            with self._lock:
                self._counts["retries"] += 1
            self._requeue(job)
            return
        try:
            self._ex.sessions.evict(job.sid, reason="train_failed")
        except errors.SkylarkError:
            pass
        self._finish(job, error=exc)

    def _requeue(self, job: _Job) -> None:
        with self._lock:
            job.running = False
            self._enqueue_locked(job)
        self._ex._wake_flusher()

    def release_jobs(self, message: str) -> None:
        """Stop owning every live job WITHOUT deciding its outcome —
        the drain/shutdown path. The sessions stay on disk (the drain
        hook already checkpointed them) and the pins release; each
        unresolved job future breaks with
        :class:`~libskylark_tpu.base.errors.CommunicationError`, the
        signal a fleet router's resume chain treats as "re-home the
        job on a surviving replica"."""
        with self._lock:
            jobs = list(self._jobs.values())
            self._queue.clear()
            self._jobs.clear()
            for j in jobs:
                j.queued = False
                j.done = True
        for j in jobs:
            try:
                self._ex.sessions.unpin(j.sid)
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
            if not j.future.done():
                j.future.set_exception(
                    errors.CommunicationError(
                        f"train job {j.sid!r}: {message}"))

    # -- stats -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["active"] = len(self._jobs)
            out["queued"] = len(self._queue)
            out["by_job"] = {
                sid: {"solver": j.spec.solver,
                      "slices_done": j.slices_done,
                      "iterations_requested": j.rows,
                      "budget_iters": j.spec.budget_iters,
                      "residual": j.residual,
                      "running": j.running,
                      "queued": j.queued}
                for sid, j in self._jobs.items()}
        return out


_MANAGERS: "weakref.WeakSet[TrainManager]" = weakref.WeakSet()

_SUM_KEYS = ("jobs_submitted", "slices_run", "preemptions", "resumes",
             "budget_exhausted", "completed", "failed", "retries",
             "active", "queued")


def train_stats() -> dict:
    """Aggregate train counters over every live manager (the ``train``
    telemetry collector block)."""
    agg = {"managers": 0}
    for k in _SUM_KEYS:
        agg[k] = 0
    for mgr in list(_MANAGERS):
        try:
            s = mgr.stats()
        except ReferenceError:   # executor proxy died mid-iteration
            continue
        agg["managers"] += 1
        for k in _SUM_KEYS:
            agg[k] += int(s.get(k, 0))
    return agg


_metrics.register_collector("train", train_stats)


__all__ = ["TrainJobSpec", "TrainJobHandle", "TrainManager",
           "train_stats"]
