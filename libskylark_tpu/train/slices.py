"""Sliceable solvers: the paper's iterative trainers as pure
bounded-iteration steps.

libSkylark ships its ml/algorithms solvers as one-shot calls
(``skylark_ml``; PAPER.md layer map): a training run is a foreground
loop that dies with its process. This module refactors each solver
into a **slice engine** — an object whose ``step(state, k)`` advances
the iteration at most ``k`` steps and returns the new state, as a
*pure deterministic function of its inputs*. That single property buys
the whole robustness story (docs/training):

- a job is a sequence of slices, so the serve tier can run it in idle
  scheduler slots and preempt it **at slice boundaries, never
  mid-step**;
- a slice journaled as "advance k from seq s" replays bit-equal, so
  the r16 journal/checkpoint path makes the job survive ``kill -9``:
  any replica resumes from the last checkpoint + journal tail and
  continues **bit-identical** to the uninterrupted run;
- replay idempotency falls out of the journal's seq cursor — the
  solver itself needs no retry logic.

Engines do not invent numerics: they are built from the SAME parts as
the foreground solvers — :func:`libskylark_tpu.algorithms.krylov.
lsqr_parts` / ``cg_parts`` (the one-iteration bodies the
``lax.while_loop`` entry points run), :meth:`libskylark_tpu.ml.admm.
BlockADMMSolver.make_step` (the consensus-ADMM iteration), and
:class:`libskylark_tpu.algorithms.asynch._BlockSystem.sweep` (the
randomized block Gauss-Seidel primitive). A sliced job and a
foreground call iterate identical math; per-iteration bit-equality is
pinned by tests/test_train.py.

Engine contract
===============

``init() -> state``           initial solver state (dict name -> host
                              ndarray; includes the iteration counter)
``step(state, k) -> state``   advance ≤ k iterations (fewer only when
                              the convergence test inside the state
                              fires); pure + deterministic
``info(state) -> dict``       {"iterations", "residual", "converged"}
``result(state) -> dict``     terminal host arrays (the model)

State dicts hold **host numpy arrays only** — they are what the
registry checkpoints and what :func:`encode_state` frames for the
byte-level ``step(state_bytes, k) -> state_bytes`` contract.
``encode_state`` is deliberately *not* ``np.savez`` (zip members carry
wall-clock timestamps, so equal states would encode to unequal bytes);
it frames raw ``.npy`` records, which are bit-stable.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Dict

import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.precision import with_solver_precision

SOLVERS = ("admm_krr", "lsqr", "cg", "rand_gs")


# -- byte framing -------------------------------------------------------


def encode_state(state: Dict[str, np.ndarray]) -> bytes:
    """Deterministic bytes for a state dict: a key manifest + one raw
    ``.npy`` record per array, in sorted key order. Equal states encode
    to equal bytes (the replay bit-equality tests compare these)."""
    out = io.BytesIO()
    keys = sorted(state)
    manifest = json.dumps(keys).encode("utf-8")
    out.write(struct.pack("<I", len(manifest)))
    out.write(manifest)
    for k in keys:
        arr = np.asarray(state[k])
        if arr.ndim and not arr.flags.c_contiguous:
            # NOT np.ascontiguousarray: that promotes 0-d to 1-d, and
            # the shape must round-trip exactly (scalar counters like
            # ``it`` feed shape-sensitive while_loop conditions)
            arr = arr.copy(order="C")
        np.lib.format.write_array(out, arr, allow_pickle=False)
    return out.getvalue()


def decode_state(data: bytes) -> Dict[str, np.ndarray]:
    buf = io.BytesIO(data)
    (mlen,) = struct.unpack("<I", buf.read(4))
    keys = json.loads(buf.read(mlen).decode("utf-8"))
    return {k: np.lib.format.read_array(buf, allow_pickle=False)
            for k in keys}


def step_bytes(engine, state_bytes: bytes, k: int) -> bytes:
    """The ISSUE's literal contract: ``step(state_bytes, k) ->
    state_bytes``, deterministic so replay is bit-equal."""
    return encode_state(engine.step(decode_state(state_bytes), int(k)))


# -- shared helpers -----------------------------------------------------


def _host(state) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in state.items()}


def _build_precond(kind, A, hyper):
    """Optional sketched right-preconditioner for the Krylov engines —
    the Blendenpik/LSRN build from algorithms/regression.py, seeded
    from the job spec so a resume rebuilds the same operator."""
    if not kind:
        return None
    from libskylark_tpu.algorithms import regression as _reg
    from libskylark_tpu.base.context import Context

    params = _reg.AcceleratedParams(
        sketch_size_factor=float(hyper.get("sketch_size_factor", 4.0)),
        sketch=str(hyper.get("sketch", "jlt")),
    )
    ctx = Context(seed=int(hyper.get("seed", 0)))
    if kind == "blendenpik":
        precond, _ = _reg.build_blendenpik_precond(A, ctx, params)
        return precond
    if kind == "lsrn":
        precond, _ = _reg.build_lsrn_precond(A, ctx, params)
        return precond
    raise errors.InvalidParametersError(
        f"unknown train preconditioner {kind!r} "
        "(expected 'blendenpik', 'lsrn', or none)")


class _KrylovEngine:
    """Shared machinery for the LSQR/CG engines: the solver's
    ``*_parts`` body run under a *bounded* while-loop cond
    ``(it < limit) & ~all(done)`` with the limit a traced argument —
    one compile per job serves every slice size."""

    solver = ""

    def _parts(self, A, B, params, precond):
        raise NotImplementedError

    def __init__(self, hyper: dict, operands: dict):
        import jax
        import jax.numpy as jnp

        from libskylark_tpu.algorithms.krylov import KrylovParams

        if "A" not in operands or "B" not in operands:
            raise errors.InvalidParametersError(
                f"{self.solver} jobs need operands A and B")
        self.hyper = dict(hyper or {})
        A = jnp.asarray(operands["A"])
        B = jnp.asarray(operands["B"])
        params = KrylovParams(
            tolerance=float(self.hyper.get("tolerance", 1e-6)))
        # the whole build runs under solver precision, exactly as the
        # decorated one-shot entry point computes its initial vectors —
        # the engine's iteration 0..i bytes must equal lsqr/cg's
        state0, body, meta = with_solver_precision(self._parts)(
            A, B, params,
            _build_precond(self.hyper.get("precond"), A, self.hyper))
        self._state0, self._meta = state0, meta

        def run(state, limit):
            from jax import lax

            def cond(s):
                return (s["it"] < limit) & (~jnp.all(s["done"]))

            return lax.while_loop(cond, body, state)

        # with_solver_precision INSIDE the jit boundary: the precision
        # context is applied while the body traces, matching the
        # decorated one-shot entry points' numerics exactly
        self._run = jax.jit(with_solver_precision(run))
        self._jnp = jnp

    def init(self) -> Dict[str, np.ndarray]:
        return _host(self._state0)

    def step(self, state: Dict[str, np.ndarray], k: int
             ) -> Dict[str, np.ndarray]:
        jnp = self._jnp
        dev = {key: jnp.asarray(v) for key, v in state.items()}
        limit = dev["it"] + jnp.int32(int(k))
        return _host(self._run(dev, limit))

    def _residual(self, state) -> float:
        if "nrm_r" in state:  # lsqr carries the residual norms directly
            return float(np.max(np.asarray(state["nrm_r"])))
        return float(np.max(np.sqrt(np.sum(
            np.asarray(state["R"]) ** 2, axis=0))))

    def info(self, state) -> dict:
        return {
            "iterations": int(np.asarray(state["it"])),
            "residual": self._residual(state),
            "converged": bool(np.all(np.asarray(state["done"]))),
        }

    def result(self, state) -> dict:
        jnp = self._jnp
        dev = {key: jnp.asarray(v) for key, v in state.items()}
        X = np.asarray(self._meta["extract"](dev))
        out = {"X": X, "iterations": int(np.asarray(state["it"]))}
        info = self.info(state)
        out["converged"] = info["converged"]
        out["residual"] = info["residual"]
        return out


class LsqrEngine(_KrylovEngine):
    solver = "lsqr"

    def _parts(self, A, B, params, precond):
        from libskylark_tpu.algorithms import krylov

        return krylov.lsqr_parts(A, B, params=params, precond=precond,
                                 shape=A.shape)


class CgEngine(_KrylovEngine):
    solver = "cg"

    def _parts(self, A, B, params, precond):
        from libskylark_tpu.algorithms import krylov

        return krylov.cg_parts(A, B, params=params, precond=precond)


class AdmmKrrEngine:
    """BlockADMM kernel-ridge training in slices: the SAME
    ``make_step``/``build_caches``/``init_carry`` parts the foreground
    :meth:`BlockADMMSolver.train` composes, driven one iteration at a
    time so a slice boundary can fall after any iteration. The python
    loop here mirrors train()'s loop exactly (same step function, same
    convergence test at the same point), so the sliced job's carry is
    bit-equal to the uninterrupted run at every iteration count."""

    solver = "admm_krr"
    _CARRY = ("Wbar", "O", "Obar", "nu", "mu", "mu_ij", "ZtObar_ij",
              "del_o")

    def __init__(self, hyper: dict, operands: dict):
        import jax
        import jax.numpy as jnp

        from libskylark_tpu.algorithms import prox
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.ml import kernels
        from libskylark_tpu.ml.admm import BlockADMMSolver

        if "X" not in operands or "Y" not in operands:
            raise errors.InvalidParametersError(
                "admm_krr jobs need operands X and Y")
        h = dict(hyper or {})
        self.hyper = h
        X = jnp.asarray(operands["X"])
        Y = jnp.asarray(operands["Y"]).reshape(-1)
        n, d = X.shape
        self._regression = bool(h.get("regression", True))
        if self._regression:
            k = 1
        else:
            k = int(h.get("num_targets") or int(jnp.max(Y)) + 1)
        kernel = kernels.Gaussian(d, float(h.get("sigma", 1.0)))
        solver = BlockADMMSolver.from_kernel(
            Context(seed=int(h.get("seed", 0))),
            prox.SquaredLoss(), prox.L2Regularizer(),
            float(h.get("lam", 1e-3)),
            int(h.get("num_features", 64)),
            kernel,
            num_partitions=int(h.get("num_partitions", 1)),
        )
        solver.rho = float(h.get("rho", 1.0))
        solver.tol = float(h.get("tol", 1e-6))
        self._solver = solver
        self._X, self._Y = X, Y
        self._n, self._k, self._dt = n, k, X.dtype
        # caches + step are deterministic given (operands, hyper): a
        # resume on another replica rebuilds the same factor bytes.
        # Built under solver precision like the decorated train() —
        # the factors feed every iteration
        self._cache_mats, lowers, self._Zs = with_solver_precision(
            solver.build_caches)(X, X.dtype)
        self._step = jax.jit(solver.make_step(n, k, X.dtype, lowers))
        self._jnp = jnp

    def init(self) -> Dict[str, np.ndarray]:
        carry = self._solver.init_carry(self._n, self._k, self._dt)
        state = {name: np.asarray(a)
                 for name, a in zip(self._CARRY, carry)}
        state["it"] = np.int64(0)
        state["reldel"] = np.asarray(np.inf, np.float64)
        state["objective"] = np.asarray(np.inf, np.float64)
        state["done"] = np.asarray(False)
        return state

    @with_solver_precision
    def step(self, state: Dict[str, np.ndarray], k: int
             ) -> Dict[str, np.ndarray]:
        jnp = self._jnp
        carry = tuple(jnp.asarray(state[name]) for name in self._CARRY)
        it = int(np.asarray(state["it"]))
        reldel = float(np.asarray(state["reldel"]))
        objective = float(np.asarray(state["objective"]))
        done = bool(np.asarray(state["done"]))
        tol = self._solver.tol
        for _ in range(int(k)):
            if done:
                break
            carry, (obj, rd) = self._step(
                carry, self._X, self._Y, self._cache_mats, self._Zs)
            it += 1
            reldel = float(rd)
            objective = float(obj)
            # the foreground loop's convergence test, verbatim
            if tol > 0 and it > 1 and reldel <= tol:
                done = True
        out = {name: np.asarray(a)
               for name, a in zip(self._CARRY, carry)}
        out["it"] = np.int64(it)
        out["reldel"] = np.asarray(reldel, np.float64)
        out["objective"] = np.asarray(objective, np.float64)
        out["done"] = np.asarray(done)
        return out

    def info(self, state) -> dict:
        return {
            "iterations": int(np.asarray(state["it"])),
            "residual": float(np.asarray(state["reldel"])),
            "converged": bool(np.asarray(state["done"])),
        }

    def result(self, state) -> dict:
        out = {"coef": np.asarray(state["Wbar"]),
               "objective": float(np.asarray(state["objective"]))}
        out.update({"iterations": int(np.asarray(state["it"])),
                    "converged": bool(np.asarray(state["done"])),
                    "residual": float(np.asarray(state["reldel"]))})
        return out

    def model(self, state):
        """The trained :class:`HilbertModel` (prediction-ready), for
        callers that want more than raw coefficients."""
        from libskylark_tpu.ml.model import HilbertModel

        m = HilbertModel(self._solver.feature_maps,
                         self._solver.scale_maps,
                         self._solver.num_features, self._k,
                         self._regression,
                         input_size=self._X.shape[1])
        m.coef = self._jnp.asarray(state["Wbar"])
        return m


class RandGsEngine:
    """Randomized block Gauss-Seidel (the AsyRGS analog) in slices:
    one iteration = one sweep, keyed by ``fold_in(key, sweeps_done)``
    exactly as :func:`algorithms.asynch.rand_block_gauss_seidel` keys
    its sweeps — the block visit order depends only on the absolute
    sweep index, so a resumed job draws the same orders."""

    solver = "rand_gs"

    def __init__(self, hyper: dict, operands: dict):
        import jax
        import jax.numpy as jnp
        import jax.random as jr

        from libskylark_tpu.algorithms.asynch import _BlockSystem
        from libskylark_tpu.base.context import Context

        if "A" not in operands or "B" not in operands:
            raise errors.InvalidParametersError(
                "rand_gs jobs need operands A and B")
        h = dict(hyper or {})
        self.hyper = h
        A = jnp.asarray(operands["A"])
        B = jnp.asarray(operands["B"])
        self._squeeze = B.ndim == 1
        if self._squeeze:
            B = B[:, None]
        self._tol = float(h.get("tolerance", 1e-6))
        sys_ = _BlockSystem(A, int(h.get("block_size", 64)))
        key = Context(seed=int(h.get("seed", 0))).allocate().key
        B_p = sys_.pad_cols(B)
        self._sys, self._B_p = sys_, B_p
        self._nrm_b = jnp.maximum(jnp.linalg.norm(B_p),
                                  jnp.finfo(B.dtype).eps)

        def sweep(X, idx):
            return sys_.sweep(X, B_p, jr.fold_in(key, idx))

        def residual(X):
            return jnp.linalg.norm(B_p - sys_.A_p @ X) / self._nrm_b

        # NOT under solver precision: the foreground
        # rand_block_gauss_seidel runs at ambient precision, and the
        # engine must iterate the same bytes it does
        self._sweep = jax.jit(sweep)
        self._residual = jax.jit(residual)
        self._B_shape = B.shape
        self._jnp = jnp

    def init(self) -> Dict[str, np.ndarray]:
        jnp = self._jnp
        n, k = self._B_shape
        X = self._sys.pad_cols(jnp.zeros((n, k), self._B_p.dtype))
        return {"X": np.asarray(X), "it": np.int64(0),
                "res": np.asarray(np.inf, np.float64),
                "done": np.asarray(False)}

    def step(self, state: Dict[str, np.ndarray], k: int
             ) -> Dict[str, np.ndarray]:
        jnp = self._jnp
        X = jnp.asarray(state["X"])
        it = int(np.asarray(state["it"]))
        done = bool(np.asarray(state["done"]))
        res = float(np.asarray(state["res"]))
        for _ in range(int(k)):
            if done:
                break
            X = self._sweep(X, np.int32(it))
            it += 1
            res = float(self._residual(X))
            done = res <= self._tol
        return {"X": np.asarray(X), "it": np.int64(it),
                "res": np.asarray(res, np.float64),
                "done": np.asarray(done)}

    def info(self, state) -> dict:
        return {
            "iterations": int(np.asarray(state["it"])),
            "residual": float(np.asarray(state["res"])),
            "converged": bool(np.asarray(state["done"])),
        }

    def result(self, state) -> dict:
        n = self._sys.n
        X = np.asarray(state["X"])[:n, :]
        if self._squeeze:
            X = X[:, 0]
        return {"X": X, "iterations": int(np.asarray(state["it"])),
                "converged": bool(np.asarray(state["done"])),
                "residual": float(np.asarray(state["res"]))}


_ENGINES = {
    "admm_krr": AdmmKrrEngine,
    "lsqr": LsqrEngine,
    "cg": CgEngine,
    "rand_gs": RandGsEngine,
}


def make_engine(solver: str, hyper: dict, operands: dict):
    """Engine factory keyed by :data:`SOLVERS` name."""
    cls = _ENGINES.get(solver)
    if cls is None:
        raise errors.InvalidParametersError(
            f"unknown train solver {solver!r}; expected one of "
            f"{SOLVERS}")
    return cls(hyper, operands)


__all__ = [
    "SOLVERS", "make_engine", "encode_state", "decode_state",
    "step_bytes", "AdmmKrrEngine", "LsqrEngine", "CgEngine",
    "RandGsEngine",
]
