"""Training jobs as sessions: the solver slice engine behind the
session-state protocol.

A train job IS a serve session of kind ``"train"`` — it reuses the r16
durability stack verbatim instead of growing a parallel one. The
mapping:

=================  ====================================================
session concept    train meaning
=================  ====================================================
``spec``           :class:`SessionSpec` with ``kind="train"``;
                   ``spec.extra`` carries the ``TrainJobSpec`` dict,
                   ``spec.n`` the iteration budget (the "stream
                   extent" appends may not pass)
``append batch``   one **slice directive**: a (1, 1) int64 array
                   holding k, "advance the solver ≤ k iterations"
``fold``           run ``engine.step(state, k)`` — pure and
                   deterministic, so journal replay re-executes the
                   slices bit-equal (the replay invariant of
                   :mod:`sessions.state`, inherited wholesale)
``rows``           the slice-position cursor: requested iterations so
                   far (budget accounting; the engine's own ``it``
                   counter tracks iterations actually run, which is
                   smaller once converged)
``checkpoint``     the engine state dict (host numpy arrays, exact
                   bytes) through ``utility.checkpoint.save_sync``
``finalize``       ``engine.result(state)`` — the trained model
=================  ====================================================

Operands (the training data / system matrices) are too large to ride
the spec, so they are persisted ONCE at submit as a sidecar
``<sid>.operands.npz`` next to the journal (same atomic
``save_sync`` discipline, written durable BEFORE the session opens).
Rebuild-at-resume then needs nothing but the directory: any replica
that owns the session files can reconstruct the engine — transforms
and caches are deterministic given (operands, hyper) — and continue
bit-equal from the last acked slice.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.sessions.state import SessionSpec
from libskylark_tpu.train import slices as _slices

#: sidecar basename suffix (``save_sync`` adds .npz/.json)
OPERANDS_SUFFIX = ".operands"


def operands_path(directory: str, sid: str) -> str:
    return os.path.join(directory, sid + OPERANDS_SUFFIX)


def save_operands(directory: str, sid: str, operands: dict,
                  digests: Optional[dict] = None) -> None:
    """Persist the job's operand arrays durably (atomic npz +
    forensics sidecar), BEFORE the session opens — a session whose
    journal exists but whose operands don't would be unresumable."""
    from libskylark_tpu.utility import checkpoint as _ckpt

    _ckpt.save_sync(operands_path(directory, sid),
                    {k: np.asarray(v) for k, v in operands.items()},
                    {"digests": digests or {}})


def load_operands(directory: str, sid: str) -> dict:
    from libskylark_tpu.utility import checkpoint as _ckpt

    loaded = _ckpt.load_sync(operands_path(directory, sid))
    if loaded is None:
        raise errors.SessionEvictedError(
            f"train session {sid}: no operand sidecar at "
            f"{operands_path(directory, sid)}.npz — the job cannot be "
            "rebuilt (submit persists operands before opening the "
            "session, so this means the artifacts were removed)")
    arrays, _meta = loaded
    return arrays


def remove_operands(directory: str, sid: str) -> None:
    base = operands_path(directory, sid)
    for suffix in (".npz", ".json"):
        try:
            os.unlink(base + suffix)
        except FileNotFoundError:
            pass


class TrainSessionState:
    """Session-state protocol over a solver slice engine (built by
    :func:`sessions.state.make_state` for ``kind="train"``)."""

    def __init__(self, spec: SessionSpec, directory: Optional[str] = None,
                 sid: Optional[str] = None):
        self.spec = spec.validate()
        if directory is None or sid is None:
            raise errors.InvalidParametersError(
                "train sessions need a registry directory and session "
                "id (the operand sidecar lives there); open them "
                "through a SessionRegistry")
        job = dict(spec.extra)
        self._job = job
        operands = load_operands(directory, sid)
        self._engine = _slices.make_engine(
            str(job["solver"]), dict(job.get("hyper") or {}), operands)
        self._state = self._engine.init()
        self.rows = 0
        self.seq = 0

    # -- batch intake (slice directives) --------------------------------

    def coerce_batch(self, X, Y=None):
        """A train append is a slice directive: a positive iteration
        count k, canonicalized to a (1, 1) int64 array (the journal
        record payload). Budget is enforced here — BEFORE the journal
        write, like every batch validation — so a slice that would
        exceed ``spec.n`` total iterations is refused, not journaled."""
        if Y is not None:
            raise errors.InvalidParametersError(
                "train sessions take no Y batch")
        k = np.asarray(X)
        if k.size != 1:
            raise errors.InvalidParametersError(
                f"train append payload must be a single iteration "
                f"count, got shape {k.shape}")
        kval = int(k.reshape(()))
        if kval < 1:
            raise errors.InvalidParametersError(
                f"train slice must advance >= 1 iteration, got {kval}")
        if self.rows + kval > self.spec.n:
            raise errors.InvalidParametersError(
                f"slice past the job's iteration budget: "
                f"{self.rows} + {kval} > budget={self.spec.n}")
        return np.asarray([[kval]], dtype=np.int64), None

    def fold(self, X: np.ndarray, Y) -> None:
        """Advance the solver ≤ k iterations — the deterministic
        replay unit. ``rows`` tracks *requested* iterations (the
        budget cursor); once the engine's convergence test fires,
        extra requested iterations are no-ops inside ``step``."""
        del Y
        k = int(np.asarray(X).reshape(()))
        self._state = self._engine.step(self._state, k)
        self.rows += k

    # -- checkpoint round trip ------------------------------------------

    def arrays(self) -> dict:
        return {k: np.asarray(v) for k, v in self._state.items()}

    def load(self, arrays: dict, rows: int, seq: int) -> None:
        expected = set(self._state)
        got = set(arrays)
        if expected != got:
            raise errors.InvalidParametersError(
                f"train checkpoint state keys {sorted(got)} do not "
                f"match the engine's {sorted(expected)} — checkpoint "
                "from a different solver or build")
        self._state = {k: np.asarray(v) for k, v in arrays.items()}
        self.rows = int(rows)
        self.seq = int(seq)

    # -- progress / terminal --------------------------------------------

    def info(self) -> dict:
        """{"iterations", "residual", "converged"} — the progress/
        residual gauges' source of truth."""
        return self._engine.info(self._state)

    @property
    def converged(self) -> bool:
        return bool(self.info().get("converged"))

    def finalize(self) -> dict:
        out = dict(self._engine.result(self._state))
        out.setdefault("rows", self.rows)
        return out


__all__ = [
    "OPERANDS_SUFFIX", "TrainSessionState", "operands_path",
    "save_operands", "load_operands", "remove_operands",
]
