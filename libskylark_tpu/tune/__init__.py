"""Sketch-apply autotuner: candidate plans, offline cost ranking, and a
persistent plan cache the dispatchers consult before their heuristics.

The flow (designed for scarce TPU access — see ISSUE/ROADMAP):

1. **Offline** (any host, no TPU): :func:`enumerate_candidates` lists
   every plan for a workload; :func:`rank_candidates` orders them with
   the hardware-free cost model (:mod:`tune.cost`); :func:`autotune_topk`
   returns the short list a live window should actually measure.
2. **Live window**: measure the top-k (bench.py does this for the
   headline config) and :func:`record_measurement` the winner — the
   cache persists to disk (``benchmarks/plan_cache.json`` by default).
3. **Dispatch**: the sketch dispatchers (sketch/pallas_dense.py,
   sketch/pallas_fastfood.py via sketch/frft.py) call :func:`plan_for`
   before falling back to their heuristics. Explicit call-site
   arguments and the one-shot env overrides (``SKYLARK_PALLAS_MTILE``
   et al.) still take precedence — the cache fills in only what the
   caller left unspecified.

``SKYLARK_PLAN_CACHE`` points the cache elsewhere (or ``0`` disables
persistence); :func:`libskylark_tpu.sketch.params.set_use_plan_cache`
gates dispatch-time consultation at runtime.
"""

from __future__ import annotations

from typing import Optional

from libskylark_tpu.tune.cache import (PlanCache, default_cache_path,
                                       get_cache, set_cache)
from libskylark_tpu.tune.cost import (RATES, analyze_jitted,
                                      effective_rates, plan_cost,
                                      rank_plans, rate_provenance)
from libskylark_tpu.tune.plans import (Plan, Workload, bucket_dim,
                                       current_device_kind,
                                       enumerate_candidates,
                                       normalize_device_kind)

__all__ = [
    "Plan", "PlanCache", "Workload", "analyze_jitted", "autotune_topk",
    "bucket_dim", "current_device_kind", "default_cache_path",
    "dense_workload", "effective_rates", "enumerate_candidates",
    "fastfood_workload", "get_cache", "hash_workload",
    "normalize_device_kind", "plan_cost", "plan_for",
    "plan_fingerprint", "rank_candidates", "rank_plans",
    "rate_provenance", "record_measurement", "record_ranked",
    "serve_workload", "set_cache", "RATES",
]


def plan_fingerprint() -> str:
    """Content fingerprint of the global plan cache's *plans* — the
    component the solver engine folds into its executable cache keys
    (see :meth:`PlanCache.fingerprint`). Never raises."""
    try:
        return get_cache().fingerprint()
    except Exception:
        return "no-plan-cache"


# -- workload constructors (the dispatchers' vocabulary) --

def dense_workload(dist_kind: str, shape, dtype, s_dim: int,
                   seq_axis: int, *, rft: bool = False,
                   device_kind: Optional[str] = None) -> Workload:
    """Workload for a dense virtual-operator apply. ``shape`` is the
    2-D input's shape; ``seq_axis`` its contracted axis (1 → rowwise
    A·Sᵀ, 0 → columnwise S·A); ``rft`` marks the cos-epilogue variant."""
    m = int(shape[1 - seq_axis])
    n = int(shape[seq_axis])
    op = ("rft_rowwise" if rft
          else ("dense_rowwise" if seq_axis == 1 else "dense_columnwise"))
    return Workload(
        device_kind=device_kind or current_device_kind(),
        op=op, transform=str(dist_kind), dtype=str(dtype),
        shape=(m, n, int(s_dim)))


def fastfood_workload(transform_type: str, shape, dtype, s_dim: int, *,
                      device_kind: Optional[str] = None) -> Workload:
    """Workload for a Fastfood feature map on row-major (m, d) input."""
    return Workload(
        device_kind=device_kind or current_device_kind(),
        op="fastfood_rows", transform=str(transform_type),
        dtype=str(dtype), shape=(int(shape[0]), int(shape[1]),
                                 int(s_dim)))


def hash_workload(sketch_type: str, shape, dtype, s_dim: int,
                  seq_axis: int, *,
                  device_kind: Optional[str] = None) -> Workload:
    """Workload for a hash-sketch (CWT/CountSketch) direct apply —
    the scatter-free kernel (sketch/pallas_hash.py) vs the XLA
    ``segment_sum`` scatter. ``shape`` is the 2-D input's shape;
    ``seq_axis`` its contracted (hashed) axis."""
    m = int(shape[1 - seq_axis])
    n = int(shape[seq_axis])
    op = "hash_rowwise" if seq_axis == 1 else "hash_columnwise"
    return Workload(
        device_kind=device_kind or current_device_kind(),
        op=op, transform=str(sketch_type), dtype=str(dtype),
        shape=(m, n, int(s_dim)))


def serve_workload(endpoint: str, family: str, dtype, lane_shape,
                   s_dim: int, capacity: int, *, rowwise: bool = True,
                   nnz: int = 0,
                   device_kind: Optional[str] = None) -> Workload:
    """Workload for one microbatch serve bucket (engine/serve.py flush
    builders): a batched-kernel-vs-vmapped-XLA decision per (endpoint /
    orientation, transform family, dtype, pow2 lane shape class, batch
    capacity class). ``lane_shape`` is ONE lane's padded class shape
    ((m, n) rowwise / (n, m) columnwise for sketch_apply and
    sparse_sketch_apply; (m, n_dim) for fastfood_features);
    ``capacity`` the pow2 batch class. Sparse buckets additionally
    carry their pow2 ``nnz`` class — the sparse ladder's costs are
    nnz-proportional, so two density regimes of one dense shape class
    tune independently."""
    if endpoint == "sketch_apply":
        op = "serve_sketch_rw" if rowwise else "serve_sketch_cw"
        m = int(lane_shape[0]) if rowwise else int(lane_shape[1])
        n = int(lane_shape[1]) if rowwise else int(lane_shape[0])
    elif endpoint == "sparse_sketch_apply":
        op = "serve_sparse_rw" if rowwise else "serve_sparse_cw"
        m = int(lane_shape[0]) if rowwise else int(lane_shape[1])
        n = int(lane_shape[1]) if rowwise else int(lane_shape[0])
    elif endpoint == "fastfood_features":
        op = "serve_fastfood"
        m, n = int(lane_shape[0]), int(lane_shape[1])
    elif endpoint == "compressed_matmul":
        # lane_shape is (m_pad, n); the kept extent of B (p_pad) rides
        # the nnz slot — the shape triple only has room for (m, n, s).
        op = "serve_cmm"
        m, n = int(lane_shape[0]), int(lane_shape[1])
    else:
        raise ValueError(
            f"endpoint {endpoint!r} has no serve-bucket workload")
    return Workload(
        device_kind=device_kind or current_device_kind(),
        op=op, transform=str(family), dtype=str(dtype),
        shape=(m, n, int(s_dim)), batch=int(capacity), nnz=int(nnz))


# -- the three public verbs --

def plan_for(w: Workload) -> Optional[Plan]:
    """Cached plan for ``w``, or None (dispatcher keeps its heuristic).
    Never raises: a broken cache must not take down a sketch apply."""
    try:
        return get_cache().lookup(w)
    except Exception:
        return None


def rank_candidates(w: Workload, allow_fast: bool = False,
                    rates: Optional[dict] = None):
    """(plan, cost-record) pairs, best modeled plan first."""
    return rank_plans(w, enumerate_candidates(w, allow_fast=allow_fast),
                      rates)


def autotune_topk(w: Workload, k: int = 3,
                  allow_fast: bool = False) -> list[Plan]:
    """The k plans a live TPU window should measure for ``w``, best
    modeled first — the offline half of the tuner."""
    return [p for p, _ in rank_candidates(w, allow_fast=allow_fast)[:k]]


def record_ranked(w: Workload, allow_fast: bool = False):
    """Offline half of the serve tuner: rank ``w``'s candidates with
    the hardware-free model and persist the winner as a ``"ranked"``
    cache entry — never displacing a measured one (a live window's
    certification always outranks the model). Returns the ``(plan,
    cost-record)`` winner either way."""
    plan, cost = rank_candidates(w, allow_fast=allow_fast)[0]
    cache = get_cache()
    cur = cache.entry(w)
    if cur is None or cur.get("source") != "measured":
        cache.put(w, plan, source="ranked",
                  extra={"modeled_s": cost["modeled_s"]})
        cache.save()
    return plan, cost


def record_measurement(w: Workload, plan: Plan, value: float,
                       unit: str = "GB/s",
                       extra: Optional[dict] = None) -> bool:
    """Feed a measured result into the global cache and persist it.
    Returns whether the cache changed (see
    :meth:`PlanCache.record_measurement` for the better-only rule)."""
    cache = get_cache()
    changed = cache.record_measurement(w, plan, value, unit=unit,
                                       extra=extra)
    if changed:
        cache.save()
    return changed
