"""Persistent on-disk plan cache: certified winners survive the process.

TPU windows are scarce (four straight wedged-tunnel rounds); a live
window that measures a best plan must leave it somewhere the next
process — and the next round — can serve from. The cache is one JSON
document, schema-versioned, keyed by :meth:`Workload.key`:

.. code-block:: json

    {"schema": 1,
     "entries": {
       "tpu_v5_lite|dense_rowwise|normal|float32|8192x8192x1024": {
         "plan": {"backend": "pallas", "m_tile": 512,
                  "precision": "bf16x3"},
         "source": "measured",
         "value": 86.269, "unit": "GB/s",
         "recorded": "2026-07-31T03:23:42+00:00"}}}

``source``: "measured" (a live window timed it — authoritative;
:meth:`record_measurement` only replaces a measured entry with a BETTER
measured value) or "ranked" (offline cost-model winner — any
measurement replaces it).

Location: ``SKYLARK_PLAN_CACHE`` env (a path; ``0``/``off`` disables
persistence entirely), defaulting to ``benchmarks/plan_cache.json`` in
the repo tree when that directory exists (certified plans ride the
repo like the other benchmark artifacts), else
``~/.cache/libskylark_tpu/plan_cache.json``. Schema mismatches load as
EMPTY and never save over the newer file (a downgrade must not destroy
a newer cache); unreadable/corrupt files load empty too — the cache is
an optimization and must never be a failure mode.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.tune.plans import Plan, Workload

SCHEMA = 1

# Unified-registry adapter (docs/observability): the dispatchers'
# cache-consultation outcomes, previously untracked. Always counted —
# a lookup happens once per dispatch (host-side key build dwarfs it)
# and the benchmarks snapshot carries tune counters even with
# telemetry off.
_LOOKUPS = _metrics.counter(
    "tune.plan_cache_lookups",
    "Plan-cache consultations by the sketch-apply dispatchers, "
    "by outcome (hit / miss / malformed)")


def _utcnow() -> str:
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")


def default_cache_path() -> Optional[str]:
    """Resolved cache location, or None when persistence is disabled
    (SKYLARK_PLAN_CACHE=0/off/empty)."""
    if _env.PLAN_CACHE.is_set():
        # set: the parsed value (an off-word parses to None — disabled)
        return _env.PLAN_CACHE.get()
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    repo_bench = os.path.join(here, "benchmarks")
    if os.path.isdir(repo_bench):
        return os.path.join(repo_bench, "plan_cache.json")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "libskylark_tpu", "plan_cache.json")


class PlanCache:
    """In-memory view of the JSON cache document. Thread-safe for the
    dispatch path (lookup) and the bench feedback path (record+save)."""

    def __init__(self, path: Optional[str] = None,
                 entries: Optional[dict] = None):
        self.path = path
        self.entries: dict[str, dict] = dict(entries or {})
        self._lock = _locks.make_lock("tune.plan_cache")
        self._fingerprint: Optional[str] = None
        self.load_error: Optional[str] = None

    # -- persistence --

    @classmethod
    def load(cls, path: Optional[str]) -> "PlanCache":
        cache = cls(path)
        if path is None:
            return cache
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return cache
        except Exception as e:  # corrupt file: serve empty, keep file
            cache.load_error = f"{type(e).__name__}: {e}"
            return cache
        if doc.get("schema") != SCHEMA:
            cache.load_error = (f"schema {doc.get('schema')!r} != "
                                f"{SCHEMA} (newer build?) — ignoring")
            return cache
        entries = doc.get("entries")
        if isinstance(entries, dict):
            cache.entries = entries
        return cache

    @staticmethod
    def _prefer(mine: dict, theirs: dict) -> dict:
        """Merge rule for one key present in memory AND on disk (another
        process wrote between our load and save): measured beats
        ranked; among measured with comparable units, the better value
        wins; ties keep ours."""
        m_meas = mine.get("source") == "measured"
        t_meas = theirs.get("source") == "measured"
        if m_meas != t_meas:
            return mine if m_meas else theirs
        mv, tv = mine.get("value"), theirs.get("value")
        if (isinstance(mv, (int, float)) and isinstance(tv, (int, float))
                and mine.get("unit") == theirs.get("unit")
                and tv > mv):
            return theirs
        return mine

    def save(self, path: Optional[str] = None) -> bool:
        """Atomic write (tmp + replace), sorted keys for stable diffs.
        The on-disk document is RE-READ and merged under an advisory
        file lock first: two processes certifying different workloads
        in one window (the bench-A/B-in-separate-processes pattern)
        must not lose each other's winners to a stale-snapshot
        rewrite. Returns False (without writing) when persistence is
        disabled or the on-disk document has a different schema (never
        clobber a newer cache)."""
        path = path or self.path
        if path is None:
            return False
        with self._lock:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            except OSError:
                return False
            lock_fh = None
            try:
                try:  # advisory lock; best-effort where flock exists
                    import fcntl

                    lock_fh = open(f"{path}.lock", "w")
                    fcntl.flock(lock_fh, fcntl.LOCK_EX)
                except Exception:
                    if lock_fh is not None:  # opened but flock failed
                        lock_fh.close()      # (e.g. ENOLCK on NFS)
                    lock_fh = None
                try:
                    with open(path) as fh:
                        disk = json.load(fh)
                    if disk.get("schema") != SCHEMA:
                        return False
                    for key, ent in (disk.get("entries") or {}).items():
                        if key not in self.entries:
                            self.entries[key] = ent
                        else:
                            self.entries[key] = self._prefer(
                                self.entries[key], ent)
                    self._fingerprint = None  # merge may have changed plans
                except Exception:
                    pass  # absent or unreadable: safe to (re)create
                doc = {"schema": SCHEMA, "entries": self.entries}
                tmp = f"{path}.tmp.{os.getpid()}"
                try:
                    with open(tmp, "w") as fh:
                        json.dump(doc, fh, indent=1, sort_keys=True)
                        fh.write("\n")
                    os.replace(tmp, path)
                except OSError:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    return False
                return True
            finally:
                if lock_fh is not None:
                    lock_fh.close()

    # -- lookup / record --

    def fingerprint(self) -> str:
        """Content hash over the *plans* in the cache (not the
        measurement metadata): the static-key component the solver
        engine (libskylark_tpu/engine) folds into every executable key.
        Hashing only the plan part means re-recording a better
        measurement of the SAME plan leaves every executable valid,
        while editing a cached plan invalidates the engine-served
        pipelines (conservatively: the fingerprint is global, so an
        unrelated-workload plan write also recompiles — over-
        invalidation is a wasted compile, a stale serve would be a
        wrong dispatch).

        Memoized — this sits on the engine's per-call key path — and
        invalidated by :meth:`put` (which every write funnels through).
        Code that mutates ``entries`` directly must call
        :meth:`invalidate_fingerprint`."""
        with self._lock:
            if self._fingerprint is not None:
                return self._fingerprint
            plans = {k: ent.get("plan") for k, ent in
                     sorted(self.entries.items())}
            doc = json.dumps(plans, sort_keys=True, default=str)
            import hashlib

            self._fingerprint = hashlib.sha256(
                doc.encode()).hexdigest()[:16]
            return self._fingerprint

    def invalidate_fingerprint(self) -> None:
        with self._lock:
            self._fingerprint = None

    def lookup(self, w: Workload) -> Optional[Plan]:
        ent = self.entries.get(w.key())
        if not ent:
            _LOOKUPS.inc_always(outcome="miss")
            return None
        try:
            plan = Plan.from_dict(ent["plan"])
        except Exception:
            _LOOKUPS.inc_always(outcome="malformed")
            return None  # malformed entry: heuristic fallback
        _LOOKUPS.inc_always(outcome="hit")
        return plan

    def entry(self, w: Workload) -> Optional[dict]:
        return self.entries.get(w.key())

    def put(self, w: Workload, plan: Plan, *, source: str = "ranked",
            value: Optional[float] = None, unit: Optional[str] = None,
            extra: Optional[dict] = None) -> dict:
        ent = {"plan": plan.to_dict(), "source": source,
               "recorded": _utcnow()}
        if value is not None:
            ent["value"] = float(value)
            ent["unit"] = unit or "GB/s"
        if extra:
            ent.update(extra)
        with self._lock:
            self.entries[w.key()] = ent
            self._fingerprint = None
        return ent

    def record_measurement(self, w: Workload, plan: Plan, value: float,
                           unit: str = "GB/s",
                           extra: Optional[dict] = None) -> bool:
        """Feed one measured result back. A measured entry is only
        replaced by a BETTER measured value (higher, for throughput
        units); ranked entries always yield to measurements. Returns
        whether the cache changed."""
        cur = self.entries.get(w.key())
        if (cur and cur.get("source") == "measured"
                and isinstance(cur.get("value"), (int, float))
                and cur.get("unit", unit) == unit
                and float(value) <= float(cur["value"])):
            return False
        self.put(w, plan, source="measured", value=value, unit=unit,
                 extra=extra)
        return True


# -- process-global cache used by the dispatchers --

_global: Optional[PlanCache] = None
_global_lock = _locks.make_lock("tune.global_cache")


def get_cache() -> PlanCache:
    """The process-global cache, lazily loaded from
    :func:`default_cache_path`."""
    global _global
    with _global_lock:
        if _global is None:
            _global = PlanCache.load(default_cache_path())
        return _global


def set_cache(cache: Optional[PlanCache]) -> Optional[PlanCache]:
    """Inject a cache (tests; also the reload seam after an external
    process rewrote the file). Returns the previous cache. Pass None to
    drop back to lazy-load-from-disk."""
    global _global
    with _global_lock:
        prev, _global = _global, cache
        return prev


def _telemetry_block() -> dict:
    """Snapshot adapter: the ALREADY-LOADED global cache's shape (no
    lazy disk load at snapshot time — a snapshot must not have side
    effects)."""
    with _global_lock:
        c = _global
    if c is None:
        return {"loaded": False}
    return {"loaded": True, "entries": len(c.entries),
            "load_error": c.load_error}


_metrics.register_collector("tune.plan_cache", _telemetry_block)
