"""Hardware-free cost models for plan ranking.

Two complementary models live here:

1. :func:`analyze_jitted` — XLA's own compiled-HLO cost analysis
   (flops / bytes-accessed / memory split), promoted into the package
   from ``benchmarks/hlo_cost.py`` (which now imports it from here).
   For a fixed jitted computation at fixed shapes these numbers are
   deterministic properties of the lowered HLO — the drift-proof perf
   signal the CI ratchet gates on, and the cost oracle for XLA-path
   plans.

2. :func:`plan_cost` / :func:`rank_plans` — an **analytic** roofline
   model for Pallas kernel plans, which XLA cannot cost (the Mosaic
   kernel only compiles on TPU). It prices the exact quantities the
   kernel's own documentation identifies as the cost structure
   (sketch/pallas_dense.py, sketch/params.py): MXU passes per
   contraction regime, operator generation on the VPU (~50 ops/entry,
   one full regeneration per m-tile sweep unless the operator-cache
   scratch fits), HBM traffic, and generation/matmul overlap when the
   pipelined kernel engages.

Absolute times from the analytic model are NOT predictions — only the
ORDERING is consumed (rank the candidates, measure the top-k in a live
window). The rate constants are v5e headline figures; override via the
``RATES`` mapping for other parts. Ranking is deterministic: stable
sort on (modeled seconds, plan_id).
"""

from __future__ import annotations

from typing import Optional, Sequence

from libskylark_tpu.tune.plans import (FASTFOOD_OPS, Plan, Workload)

# --------------------------------------------------------------------------
# compiled-HLO analysis (promoted from benchmarks/hlo_cost.py)
# --------------------------------------------------------------------------


def analyze_jitted(name: str, jitted, *avals) -> dict:
    """Lower+compile ``jitted`` at ``avals`` and return its XLA cost /
    memory analysis as a flat record. Deterministic for fixed shapes and
    toolchain — zero hardware, zero timing noise."""
    compiled = jitted.lower(*avals).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # older jax returned [dict]
        ca = ca[0]
    mem = compiled.memory_analysis()
    return {
        "config": name,
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
    }


# --------------------------------------------------------------------------
# analytic kernel-plan roofline
# --------------------------------------------------------------------------

# v5e headline rates. Ranking consumes ratios, not absolutes, so these
# only need to be right RELATIVE to each other at the order-of-magnitude
# level the plan axes move (MXU pass count, generation sweeps, HBM).
RATES = {
    "mxu_flops_per_s": 197e12,   # one bf16 MXU pass
    "vpu_ops_per_s": 5e12,       # effective generation issue rate —
    # calibrated against the r03 on-chip headline (86.3 GB/s = 3.50
    # ms/apply at mt512/bf16x3: 2.09 ms MXU + ~1.4 ms generation, 16
    # sweeps × 8192·1024 entries × ~50 ops); the model then reproduces
    # the measured f32 regime within ~20%
    "hbm_bytes_per_s": 820e9,    # HBM bandwidth
}

# VPU ops per generated operator entry: Threefry + inverse-CDF ≈ 50
# (sketch/params.py m-tile analysis; SURVEY §3.1).
GEN_OPS_PER_ENTRY = 50

# MXU passes per logical f32 contraction at each kernel regime
# (sketch/pallas_dense._dot): bf16 single pass; bf16gen2 two;
# bf16x3 three; "f32" lowers to Precision.HIGHEST ≈ 6 bf16 passes.
MXU_PASSES = {"bf16": 1, "bf16gen2": 2, "bf16x3": 3, "f32": 6}

# The XLA paths' measured-regime factors, relative to the fused kernel's
# single-gemm traffic at the same shapes (BASELINE.md / hlo_cost_r05:
# the XLA Fastfood chain re-touches the (rows, NB) intermediate ~9x;
# the split variant ~3x).
_FASTFOOD_TRAFFIC_X = {"fused": 1.0, "split": 3.0, "xla_chain": 9.0}


def _dense_operator_cached(m: int, n: int, s: int, m_tile: int) -> bool:
    """Whether the kernel would serve this plan from the VMEM operator
    cache — the kernel's OWN decision logic and env-resolved budgets
    (pallas_dense._scratch / SKYLARK_PALLAS_SCRATCH_CAP /
    SKYLARK_PALLAS_VMEM_BUDGET), imported lazily so ranking can't
    drift from dispatch on parts whose budgets were overridden. The
    import is cycle-safe: pallas_dense only reaches tune lazily inside
    its dispatch functions."""
    from libskylark_tpu.sketch.pallas_dense import (_SCRATCH_CAP_BYTES,
                                                    _VMEM_BUDGET_BYTES,
                                                    _vmem_estimate)

    if m // m_tile <= 1:
        return False
    scratch_bytes = s * n * 4
    if scratch_bytes > _SCRATCH_CAP_BYTES:
        return False
    return _vmem_estimate(m_tile, s, scratch_bytes) <= _VMEM_BUDGET_BYTES


def plan_cost(w: Workload, p: Plan, rates: Optional[dict] = None) -> dict:
    """Modeled cost record for serving ``w`` with ``p``:
    ``{flops, bytes, gen_entries, modeled_s}``. See module doc — only
    the ordering of ``modeled_s`` across plans is meaningful."""
    rates = rates or RATES
    m, n, s = w.shape
    if w.op in FASTFOOD_OPS:
        return _fastfood_cost(w, p, rates)

    bytes_moved = 4.0 * (m * n + m * s)
    hbm_s = bytes_moved / rates["hbm_bytes_per_s"]
    if p.backend == "xla":
        # materialize S (one more operator-sized HBM round trip) + one
        # HIGHEST-precision gemm; generation runs once, fused by XLA
        flops = 2.0 * m * n * s * MXU_PASSES["f32"]
        gen_entries = float(n * s)
        xla_bytes = bytes_moved + 2.0 * 4.0 * n * s
        compute_s = (flops / rates["mxu_flops_per_s"]
                     + gen_entries * GEN_OPS_PER_ENTRY
                     / rates["vpu_ops_per_s"])
        modeled = max(xla_bytes / rates["hbm_bytes_per_s"], compute_s)
        return {"flops": flops, "bytes": xla_bytes,
                "gen_entries": gen_entries, "modeled_s": modeled}

    if p.backend != "pallas":
        raise ValueError(f"unknown dense backend {p.backend!r}")
    m_tile = p.m_tile or 512
    precision = p.precision or "bf16x3"
    flops = 2.0 * m * n * s * MXU_PASSES[precision]
    sweeps = 1 if _dense_operator_cached(m, n, s, m_tile) \
        else max(1, -(-m // m_tile))
    gen_entries = float(n * s * sweeps)
    mxu_s = flops / rates["mxu_flops_per_s"]
    gen_s = gen_entries * GEN_OPS_PER_ENTRY / rates["vpu_ops_per_s"]
    # the pipelined kernel hides generation under the matmul; the plain
    # kernel serializes them (sketch/pallas_dense._kernel_pipe doc)
    compute_s = max(mxu_s, gen_s) if p.pipeline else mxu_s + gen_s
    modeled = max(hbm_s, compute_s)
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries, "modeled_s": modeled}


def _fastfood_cost(w: Workload, p: Plan, rates: dict) -> dict:
    m, _d, s = w.shape
    # block length NB ≥ s for the single-block case; the chain computes
    # nb·NB ≥ s features. Use s rounded to the bucket as the effective
    # feature extent — exact block math is the kernel's business.
    nb_feats = max(s, 512)
    base_bytes = 4.0 * m * nb_feats  # one intermediate-sized touch
    traffic_x = _FASTFOOD_TRAFFIC_X.get(p.backend)
    if traffic_x is None:
        raise ValueError(f"unknown fastfood backend {p.backend!r}")
    bytes_moved = base_bytes * (1.0 + traffic_x)
    # two WHTs as kron-factored dots: 2 · 2·m·NB·(√NB+√NB) ≈
    # 4·m·NB^1.5 flops per pass
    passes = MXU_PASSES[p.precision or "bf16x3"] if p.backend != \
        "xla_chain" else MXU_PASSES["f32"]
    flops = 4.0 * m * nb_feats * (nb_feats ** 0.5) * passes
    modeled = max(bytes_moved / rates["hbm_bytes_per_s"],
                  flops / rates["mxu_flops_per_s"])
    return {"flops": flops, "bytes": bytes_moved, "gen_entries": 0.0,
            "modeled_s": modeled}


def rank_plans(w: Workload, plans: Sequence[Plan],
               rates: Optional[dict] = None
               ) -> list[tuple[Plan, dict]]:
    """Deterministically rank ``plans`` for ``w``: ascending modeled
    seconds, ties broken by plan_id. The offline pre-ranking a live TPU
    window's top-k measurement starts from."""
    scored = [(p, plan_cost(w, p, rates)) for p in plans]
    scored.sort(key=lambda pc: (pc[1]["modeled_s"], pc[0].plan_id()))
    return scored
