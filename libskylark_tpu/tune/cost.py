"""Hardware-free cost models for plan ranking.

Two complementary models live here:

1. :func:`analyze_jitted` — XLA's own compiled-HLO cost analysis
   (flops / bytes-accessed / memory split), promoted into the package
   from ``benchmarks/hlo_cost.py`` (which now imports it from here).
   For a fixed jitted computation at fixed shapes these numbers are
   deterministic properties of the lowered HLO — the drift-proof perf
   signal the CI ratchet gates on, and the cost oracle for XLA-path
   plans.

2. :func:`plan_cost` / :func:`rank_plans` — an **analytic** roofline
   model for Pallas kernel plans, which XLA cannot cost (the Mosaic
   kernel only compiles on TPU). It prices the exact quantities the
   kernel's own documentation identifies as the cost structure
   (sketch/pallas_dense.py, sketch/params.py): MXU passes per
   contraction regime, operator generation on the VPU (~50 ops/entry,
   one full regeneration per m-tile sweep unless the operator-cache
   scratch fits), HBM traffic, and generation/matmul overlap when the
   pipelined kernel engages.

Absolute times from the analytic model are NOT predictions — only the
ORDERING is consumed (rank the candidates, measure the top-k in a live
window). The rate constants are v5e headline figures; override via the
``RATES`` mapping for other parts, or let measured ``cost_calib_*``
records from ``benchmarks/ledger.json`` recalibrate them per host
class (:func:`effective_rates` / ``SKYLARK_COST_CALIB`` — provenance
per rate via :func:`rate_provenance`, analytic fallback whenever no
measurement exists). Ranking is deterministic: stable sort on
(modeled seconds, plan_id).
"""

from __future__ import annotations

import json
import math
import os
from typing import Optional, Sequence

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.tune.plans import (FASTFOOD_OPS, HASH_OPS,
                                       SERVE_DENSE_FAMILIES, SERVE_OPS,
                                       SPARSE_SERVE_OPS, Plan, Workload,
                                       normalize_device_kind)

# --------------------------------------------------------------------------
# compiled-HLO analysis (promoted from benchmarks/hlo_cost.py)
# --------------------------------------------------------------------------


def analyze_jitted(name: str, jitted, *avals) -> dict:
    """Lower+compile ``jitted`` at ``avals`` and return its XLA cost /
    memory analysis as a flat record. Deterministic for fixed shapes and
    toolchain — zero hardware, zero timing noise."""
    compiled = jitted.lower(*avals).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # older jax returned [dict]
        ca = ca[0]
    mem = compiled.memory_analysis()
    return {
        "config": name,
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
    }


# --------------------------------------------------------------------------
# analytic kernel-plan roofline
# --------------------------------------------------------------------------

# v5e headline rates. Ranking consumes ratios, not absolutes, so these
# only need to be right RELATIVE to each other at the order-of-magnitude
# level the plan axes move (MXU pass count, generation sweeps, HBM).
RATES = {
    "mxu_flops_per_s": 197e12,   # one bf16 MXU pass
    "vpu_ops_per_s": 5e12,       # effective generation issue rate —
    # calibrated against the r03 on-chip headline (86.3 GB/s = 3.50
    # ms/apply at mt512/bf16x3: 2.09 ms MXU + ~1.4 ms generation, 16
    # sweeps × 8192·1024 entries × ~50 ops); the model then reproduces
    # the measured f32 regime within ~20%
    "hbm_bytes_per_s": 820e9,    # HBM bandwidth
    # XLA scatter-add update retire rate: the TPU scatter unit is
    # row-serial (~1 update row/cycle at ~1 GHz-ish issue) — the cost
    # structure that makes segment_sum the hash sketch's bottleneck.
    # Only the ORDER vs the kernel's MXU one-hot contraction matters.
    "scatter_rows_per_s": 1.2e9,
}

# VPU ops per generated operator entry: Threefry + inverse-CDF ≈ 50
# (sketch/params.py m-tile analysis; SURVEY §3.1).
GEN_OPS_PER_ENTRY = 50

# MXU passes per logical f32 contraction at each kernel regime
# (sketch/pallas_dense._dot): bf16 single pass; bf16gen2 two;
# bf16x3 three; "f32" lowers to Precision.HIGHEST ≈ 6 bf16 passes.
MXU_PASSES = {"bf16": 1, "bf16gen2": 2, "bf16x3": 3, "f32": 6}

# The XLA paths' measured-regime factors, relative to the fused kernel's
# single-gemm traffic at the same shapes (BASELINE.md / hlo_cost_r05:
# the XLA Fastfood chain re-touches the (rows, NB) intermediate ~9x;
# the split variant ~3x).
_FASTFOOD_TRAFFIC_X = {"fused": 1.0, "split": 3.0, "xla_chain": 9.0}


# --------------------------------------------------------------------------
# measured calibration: ledger records -> per-rate constants
# --------------------------------------------------------------------------
#
# ``bench.py`` modes append ``cost_calib_<rate>`` records to
# ``benchmarks/ledger.json`` (e.g. ``cost_calib_scatter_rows_per_s``
# from the timed scatter microbench in ``--dist-serve``). When
# ``SKYLARK_COST_CALIB`` points at such a ledger (``auto`` = the repo
# copy), :func:`effective_rates` overlays those measurements on the
# analytic ``RATES`` — but ONLY records whose ``host_class`` matches
# this host (same platform + core-count formula as the ledger writer):
# a rate measured on a 16-core TPU runner must never recalibrate a
# 1-core CPU ranking. Latest matching record wins. Every rate carries
# provenance (:func:`rate_provenance`): ``analytic`` until a
# measurement says otherwise, so rankings only move when a measured
# number moved them — the property the tune tests pin.

# sentinel: "resolve the path from the env knob" (distinct from None,
# which callers may pass to mean "no calibration, pure RATES")
_CALIB_AUTO = object()

_calib_lock = _locks.make_lock("tune.cost.calib")
_calib_cache: dict = {}  # abspath -> (stat_sig, overlay, provenance)


def _host_class() -> str:
    """This host's comparability class — the exact formula
    ``bench.py._ledger_append`` stamps on every record."""
    try:
        import jax

        plat = jax.default_backend()
    except Exception:  # noqa: BLE001 — classification, not a gate
        plat = "unknown"
    return f"{plat}-{os.cpu_count()}c"


def _repo_ledger_path() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "benchmarks", "ledger.json")


def _resolve_calib_path(path) -> Optional[str]:
    if path is _CALIB_AUTO:
        path = _env.COST_CALIB.get()
    if path is None:
        return None
    if str(path).strip().lower() == "auto":
        return _repo_ledger_path()
    return str(path)


def _read_calibration(path: str, host_class: str) -> tuple[dict, dict]:
    """Parse one ledger file into ``(overlay, provenance)``. Tolerant
    of junk lines (the ledger is telemetry); only ``cost_calib_<rate>``
    records for a known rate, with a finite positive value and a
    matching host class, participate. Later records shadow earlier
    ones (latest measurement wins)."""
    overlay: dict = {}
    prov: dict = {}
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return overlay, prov
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        metric = str(rec.get("metric", ""))
        if not metric.startswith("cost_calib_"):
            continue
        rate_name = metric[len("cost_calib_"):]
        if rate_name not in RATES:
            continue
        if rec.get("host_class") != host_class:
            continue
        try:
            value = float(rec.get("value"))
        except (TypeError, ValueError):
            continue
        if not math.isfinite(value) or value <= 0.0:
            continue
        overlay[rate_name] = value
        prov[rate_name] = {"source": "measured", "metric": metric,
                           "value": value, "host_class": host_class,
                           "path": path, "line": lineno}
    return overlay, prov


def _calibration(path) -> tuple[dict, dict]:
    """(overlay, measured-provenance) for ``path`` (env-resolved when
    the ``_CALIB_AUTO`` sentinel), memoized on the file's stat
    signature so repeated rankings don't re-read the ledger but a
    fresh bench append is picked up immediately."""
    resolved = _resolve_calib_path(path)
    if resolved is None:
        return {}, {}
    resolved = os.path.abspath(resolved)
    try:
        st = os.stat(resolved)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        sig = None
    with _calib_lock:
        hit = _calib_cache.get(resolved)
        if hit is not None and hit[0] == sig:
            return hit[1], hit[2]
    if sig is None:
        overlay, prov = {}, {}
    else:
        overlay, prov = _read_calibration(resolved, _host_class())
    with _calib_lock:
        _calib_cache[resolved] = (sig, overlay, prov)
    return overlay, prov


def effective_rates(path=_CALIB_AUTO) -> dict:
    """The rate table rankings actually consume: analytic ``RATES``
    overlaid with any matching measured ``cost_calib_*`` ledger
    records. Default resolves the ledger from ``SKYLARK_COST_CALIB``
    (unset → no overlay → exactly ``RATES``, so the analytic model is
    the fallback whenever no measurement exists); pass an explicit
    ledger path to calibrate from a specific file, or ``None`` for the
    pure analytic table."""
    overlay, _prov = _calibration(path)
    rates = dict(RATES)
    rates.update(overlay)
    return rates


def rate_provenance(path=_CALIB_AUTO) -> dict:
    """Per-rate provenance for :func:`effective_rates` at the same
    ``path``: ``{"source": "analytic"}`` for hand-set roofline
    constants, else ``{"source": "measured", "metric", "value",
    "host_class", "path", "line"}`` naming the ledger record that set
    it."""
    _overlay, prov = _calibration(path)
    return {name: dict(prov.get(name, {"source": "analytic"}))
            for name in RATES}


def _dense_operator_cached(m: int, n: int, s: int, m_tile: int) -> bool:
    """Whether the kernel would serve this plan from the VMEM operator
    cache — the kernel's OWN decision logic and env-resolved budgets
    (pallas_dense._scratch / SKYLARK_PALLAS_SCRATCH_CAP /
    SKYLARK_PALLAS_VMEM_BUDGET), imported lazily so ranking can't
    drift from dispatch on parts whose budgets were overridden. The
    import is cycle-safe: pallas_dense only reaches tune lazily inside
    its dispatch functions."""
    from libskylark_tpu.sketch.pallas_dense import (_SCRATCH_CAP_BYTES,
                                                    _VMEM_BUDGET_BYTES,
                                                    _vmem_estimate)

    if m // m_tile <= 1:
        return False
    scratch_bytes = s * n * 4
    if scratch_bytes > _SCRATCH_CAP_BYTES:
        return False
    return _vmem_estimate(m_tile, s, scratch_bytes) <= _VMEM_BUDGET_BYTES


def plan_cost(w: Workload, p: Plan, rates: Optional[dict] = None) -> dict:
    """Modeled cost record for serving ``w`` with ``p``:
    ``{flops, bytes, gen_entries, modeled_s}``. See module doc — only
    the ordering of ``modeled_s`` across plans is meaningful. When
    ``rates`` is None the table comes from :func:`effective_rates`
    (analytic ``RATES`` unless ``SKYLARK_COST_CALIB`` names a ledger
    with matching measured records)."""
    rates = effective_rates() if rates is None else rates
    m, n, s = w.shape
    if w.op in FASTFOOD_OPS:
        return _fastfood_cost(w, p, rates)
    if w.op in HASH_OPS or w.op in SERVE_OPS:
        return _hash_or_serve_cost(w, p, rates)

    bytes_moved = 4.0 * (m * n + m * s)
    hbm_s = bytes_moved / rates["hbm_bytes_per_s"]
    if p.backend == "xla":
        # materialize S (one more operator-sized HBM round trip) + one
        # HIGHEST-precision gemm; generation runs once, fused by XLA
        flops = 2.0 * m * n * s * MXU_PASSES["f32"]
        gen_entries = float(n * s)
        xla_bytes = bytes_moved + 2.0 * 4.0 * n * s
        compute_s = (flops / rates["mxu_flops_per_s"]
                     + gen_entries * GEN_OPS_PER_ENTRY
                     / rates["vpu_ops_per_s"])
        modeled = max(xla_bytes / rates["hbm_bytes_per_s"], compute_s)
        return {"flops": flops, "bytes": xla_bytes,
                "gen_entries": gen_entries, "modeled_s": modeled}

    if p.backend != "pallas":
        raise ValueError(f"unknown dense backend {p.backend!r}")
    m_tile = p.m_tile or 512
    precision = p.precision or "bf16x3"
    flops = 2.0 * m * n * s * MXU_PASSES[precision]
    sweeps = 1 if _dense_operator_cached(m, n, s, m_tile) \
        else max(1, -(-m // m_tile))
    gen_entries = float(n * s * sweeps)
    mxu_s = flops / rates["mxu_flops_per_s"]
    gen_s = gen_entries * GEN_OPS_PER_ENTRY / rates["vpu_ops_per_s"]
    # the pipelined kernel hides generation under the matmul; the plain
    # kernel serializes them (sketch/pallas_dense._kernel_pipe doc)
    compute_s = max(mxu_s, gen_s) if p.pipeline else mxu_s + gen_s
    modeled = max(hbm_s, compute_s)
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries, "modeled_s": modeled}


def _device_runs_mosaic(device_kind: str) -> bool:
    """Whether ``device_kind`` compiles Mosaic kernels natively. Off-TPU
    a "pallas" plan means the pallas *interpreter* — a correctness
    surface, not a speed surface — so the model must never rank it
    above any XLA lowering there."""
    kind = normalize_device_kind(device_kind)
    return kind.startswith("tpu") or kind.startswith("axon")


# Interpret-mode multiplier for pallas plans costed on a non-Mosaic
# host. The exact value is irrelevant (only ordering is consumed); it
# just has to dwarf every real kernel-vs-XLA ratio, so the serve tuner
# on a CPU host ALWAYS certifies the XLA path — the honest outcome the
# bench record and the CI pallas-serve gate pin.
INTERPRET_PENALTY = 1e4


def _hash_lane_cost(m: int, n: int, s: int, p: Plan,
                    rates: dict) -> dict:
    """One CWT/CountSketch lane (m non-contracted, n coordinates,
    s buckets). XLA: the ``segment_sum`` scatter — n update rows
    retired serially by the scatter unit, stream generation on the
    VPU. Pallas: the scatter-free one-hot contraction — 2·m·n·s MXU
    flops at HIGHEST (~6 bf16 passes), same generation bill, gen and
    matmul serialized (the hash kernel has no pipelined variant)."""
    bytes_moved = 4.0 * (m * n + m * s)
    hbm_s = bytes_moved / rates["hbm_bytes_per_s"]
    gen_entries = 2.0 * n          # h (bucket) + v (value) streams
    gen_s = gen_entries * GEN_OPS_PER_ENTRY / rates["vpu_ops_per_s"]
    if p.backend == "xla":
        scatter_s = n / rates["scatter_rows_per_s"]
        return {"flops": 2.0 * n * m, "bytes": bytes_moved,
                "gen_entries": gen_entries,
                "modeled_s": max(hbm_s, scatter_s + gen_s)}
    flops = 2.0 * m * n * s * MXU_PASSES["f32"]
    mxu_s = flops / rates["mxu_flops_per_s"]
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries,
            "modeled_s": max(hbm_s, mxu_s + gen_s)}


def _serve_dense_lane_cost(m: int, n: int, s: int, p: Plan,
                           rates: dict) -> dict:
    """One dense-family serve lane. XLA: materialize the operator +
    HIGHEST gemm (the vmapped ``serve_apply``). Pallas: the batched
    fused kernel at bf16x3 — no operator-cache scratch in the batched
    launcher, so generation is paid once per m-tile sweep and
    serialized against the MXU."""
    bytes_moved = 4.0 * (m * n + m * s)
    if p.backend == "xla":
        flops = 2.0 * m * n * s * MXU_PASSES["f32"]
        gen_entries = float(n * s)
        xla_bytes = bytes_moved + 2.0 * 4.0 * n * s
        compute_s = (flops / rates["mxu_flops_per_s"]
                     + gen_entries * GEN_OPS_PER_ENTRY
                     / rates["vpu_ops_per_s"])
        return {"flops": flops, "bytes": xla_bytes,
                "gen_entries": gen_entries,
                "modeled_s": max(xla_bytes / rates["hbm_bytes_per_s"],
                                 compute_s)}
    m_tile = p.m_tile or 256
    flops = 2.0 * m * n * s * MXU_PASSES["bf16x3"]
    sweeps = max(1, -(-m // m_tile))
    gen_entries = float(n * s * sweeps)
    compute_s = (flops / rates["mxu_flops_per_s"]
                 + gen_entries * GEN_OPS_PER_ENTRY
                 / rates["vpu_ops_per_s"])
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries,
            "modeled_s": max(bytes_moved / rates["hbm_bytes_per_s"],
                             compute_s)}


def _sparse_lane_cost(m: int, n: int, s: int, nnz: int, p: Plan,
                      rates: dict) -> dict:
    """One sparse-CSR serve lane (m kept extent, n sketched extent, s
    buckets, nnz the pow2 nonzero class — the quantity every term here
    scales with, which is the whole point of the sparse path). XLA: the
    O(nnz) ``scatter-add`` — nnz update rows retired serially by the
    scatter unit — plus the 2·n stream generation. Pallas (sketch/
    pallas_sparse.py): ceil(nnz/128) bucket-tiled one-hot MXU
    contractions at HIGHEST (~6 bf16 passes of (s×128)·(128×m) each),
    same generation bill, gather on the VPU; no pipelined variant, so
    generation serializes against the MXU."""
    bytes_moved = 4.0 * (3 * nnz + m * s)  # CSR lanes in, dense out
    hbm_s = bytes_moved / rates["hbm_bytes_per_s"]
    gen_entries = 2.0 * n                  # h + v streams (full extent)
    gen_s = gen_entries * GEN_OPS_PER_ENTRY / rates["vpu_ops_per_s"]
    if p.backend == "xla":
        scatter_s = nnz / rates["scatter_rows_per_s"]
        return {"flops": 2.0 * nnz, "bytes": bytes_moved,
                "gen_entries": gen_entries,
                "modeled_s": max(hbm_s, scatter_s + gen_s)}
    tiles = max(1, -(-nnz // 128))
    flops = 2.0 * s * 128.0 * m * tiles * MXU_PASSES["f32"]
    mxu_s = flops / rates["mxu_flops_per_s"]
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries,
            "modeled_s": max(hbm_s, mxu_s + gen_s)}


def _srht_lane_cost(m: int, n: int, s: int, p: Plan,
                    rates: dict) -> dict:
    """One SRHT serve lane (m kept extent, n pow2 transform extent, s
    sampled rows). XLA: the panel-free ``fwht_sketch`` lowering — the
    kron-factored WHT is two HIGHEST matmuls against factors of size
    ~sqrt(n) each (4·m·n·sqrt(n) flops), the sign diagonal and sample
    gather ride the VPU. Pallas (sketch/pallas_fwht.py): log-n
    butterfly sweeps fold into one H_128 MXU factor plus the one-hot
    sample gather, all at HIGHEST; the Threefry streams regenerate
    once per m-tile sweep and serialize against the MXU (no pipelined
    variant)."""
    bytes_moved = 4.0 * (m * n + m * s)
    hbm_s = bytes_moved / rates["hbm_bytes_per_s"]
    if p.backend == "xla":
        root = math.sqrt(float(n))
        flops = 4.0 * m * n * root * MXU_PASSES["f32"]
        gen_entries = float(n + s)     # sign diagonal + sample indices
        compute_s = (flops / rates["mxu_flops_per_s"]
                     + gen_entries * GEN_OPS_PER_ENTRY
                     / rates["vpu_ops_per_s"])
        return {"flops": flops, "bytes": bytes_moved,
                "gen_entries": gen_entries,
                "modeled_s": max(hbm_s, compute_s)}
    m_tile = p.m_tile or 256
    flops = (2.0 * m * n * 128.0 + 2.0 * m * n * s) * MXU_PASSES["f32"]
    sweeps = max(1, -(-m // m_tile))
    gen_entries = float((n + s) * sweeps)
    compute_s = (flops / rates["mxu_flops_per_s"]
                 + gen_entries * GEN_OPS_PER_ENTRY
                 / rates["vpu_ops_per_s"])
    return {"flops": flops, "bytes": bytes_moved,
            "gen_entries": gen_entries,
            "modeled_s": max(hbm_s, compute_s)}


def _cmm_cost(w: Workload, p: Plan, rates: dict) -> dict:
    """One compressed-approximate-matmul lane: sketch both operands
    down the shared contraction (A·Sᵀ and S·B) and multiply the
    (m×s)·(s×p) estimates. Always-XLA (the flush composes two existing
    sketch programs plus a small GEMM — there is no fused kernel), so
    a pallas plan is a caller bug, not a rankable candidate. The
    workload's ``nnz`` slot carries the kept extent of B (p) — the
    shape triple only has room for (m, n, s)."""
    if p.backend != "xla":
        raise ValueError(
            "serve_cmm has no pallas kernel; only the XLA flush exists")
    m, n, s = w.bucket()
    pk = max(int(w.nnz), 1)            # kept extent of B, pow2 class
    lane = _srht_lane_cost if w.transform == "SRHT" else _hash_lane_cost
    ska = lane(m, n, s, p, rates)
    skb = lane(pk, n, s, p, rates)
    gemm_flops = 2.0 * m * s * pk * MXU_PASSES["f32"]
    gemm_bytes = 4.0 * (m * s + s * pk + m * pk)
    gemm_s = max(gemm_flops / rates["mxu_flops_per_s"],
                 gemm_bytes / rates["hbm_bytes_per_s"])
    return {"flops": ska["flops"] + skb["flops"] + gemm_flops,
            "bytes": ska["bytes"] + skb["bytes"] + gemm_bytes,
            "gen_entries": ska["gen_entries"] + skb["gen_entries"],
            "modeled_s": ska["modeled_s"] + skb["modeled_s"] + gemm_s}


def _hash_or_serve_cost(w: Workload, p: Plan, rates: dict) -> dict:
    """Cost record for the hash direct-apply sites and the serve-bucket
    sites. Serve workloads scale one lane's cost by the batch capacity
    class (``w.batch``); pallas plans costed for a non-Mosaic device
    kind carry the interpret-mode penalty, so an offline ranking run on
    a CPU host correctly certifies XLA for every serve bucket."""
    if p.backend not in ("pallas", "xla"):
        raise ValueError(
            f"unknown {w.op} backend {p.backend!r} (pallas|xla)")
    m, n, s = w.bucket()
    if w.op == "serve_cmm":
        rec = _cmm_cost(w, p, rates)
    elif w.op == "serve_fastfood":
        ff = Plan("fused" if p.backend == "pallas" else "xla_chain",
                  precision=p.precision)
        rec = _fastfood_cost(w, ff, rates)
    elif w.op in SPARSE_SERVE_OPS:
        rec = _sparse_lane_cost(m, n, s, max(int(w.nnz), 1), p, rates)
    elif w.op in HASH_OPS or w.transform == "CWT":
        rec = _hash_lane_cost(m, n, s, p, rates)
    elif w.transform == "SRHT":
        rec = _srht_lane_cost(m, n, s, p, rates)
    elif w.transform in SERVE_DENSE_FAMILIES:
        rec = _serve_dense_lane_cost(m, n, s, p, rates)
    else:
        raise ValueError(
            f"serve workload family {w.transform!r} has no cost model")
    lanes = max(int(w.batch), 1) if w.op in SERVE_OPS else 1
    if lanes > 1:
        rec = {k: v * lanes for k, v in rec.items()}
    if p.backend == "pallas" and not _device_runs_mosaic(w.device_kind):
        rec["modeled_s"] *= INTERPRET_PENALTY
        rec["interpret"] = True
    return rec


def _fastfood_cost(w: Workload, p: Plan, rates: dict) -> dict:
    m, _d, s = w.shape
    # block length NB ≥ s for the single-block case; the chain computes
    # nb·NB ≥ s features. Use s rounded to the bucket as the effective
    # feature extent — exact block math is the kernel's business.
    nb_feats = max(s, 512)
    base_bytes = 4.0 * m * nb_feats  # one intermediate-sized touch
    traffic_x = _FASTFOOD_TRAFFIC_X.get(p.backend)
    if traffic_x is None:
        raise ValueError(f"unknown fastfood backend {p.backend!r}")
    bytes_moved = base_bytes * (1.0 + traffic_x)
    # two WHTs as kron-factored dots: 2 · 2·m·NB·(√NB+√NB) ≈
    # 4·m·NB^1.5 flops per pass
    passes = MXU_PASSES[p.precision or "bf16x3"] if p.backend != \
        "xla_chain" else MXU_PASSES["f32"]
    flops = 4.0 * m * nb_feats * (nb_feats ** 0.5) * passes
    modeled = max(bytes_moved / rates["hbm_bytes_per_s"],
                  flops / rates["mxu_flops_per_s"])
    return {"flops": flops, "bytes": bytes_moved, "gen_entries": 0.0,
            "modeled_s": modeled}


def rank_plans(w: Workload, plans: Sequence[Plan],
               rates: Optional[dict] = None
               ) -> list[tuple[Plan, dict]]:
    """Deterministically rank ``plans`` for ``w``: ascending modeled
    seconds, ties broken by plan_id. The offline pre-ranking a live TPU
    window's top-k measurement starts from. ``rates=None`` resolves
    through :func:`effective_rates`, so a measured ``cost_calib_*``
    ledger record can flip a ranking — and nothing else can."""
    rates = effective_rates() if rates is None else rates
    scored = [(p, plan_cost(w, p, rates)) for p in plans]
    scored.sort(key=lambda pc: (pc[1]["modeled_s"], pc[0].plan_id()))
    return scored
