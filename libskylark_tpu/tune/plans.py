"""Kernel-plan and workload descriptors for the sketch-apply autotuner.

A **workload** names a sketch-apply hot-path invocation abstractly enough
to be cached across processes: ``(device_kind, op, transform, dtype,
shape bucket)``. A **plan** names every tuning decision the dispatchers
can make for it: which backend serves the apply (fused Pallas kernel vs
the XLA path; fused vs split Fastfood variant), the Pallas ``m_tile``,
the contraction-precision regime, and whether the pipelined-generation
kernel engages.

Shapes are bucketed to the next power of two so one certified plan
serves a neighborhood of shapes — the kernels' own qualification
(``pallas_dense._qualify``) re-validates the concrete shape at dispatch
and shrinks/declines as needed, so a bucket can never force an invalid
configuration, only a suboptimal one.

Candidate enumeration is the offline half of the tuner: it lists every
plan worth considering for a workload so :mod:`tune.cost` can pre-rank
them without hardware and a live TPU window measures only the top-k
(TPU windows have been scarce for four straight rounds — a window must
certify the best config, not probe for it). Accuracy-opt-in regimes
("bf16", "bf16gen2" on data contractions) are enumerated only with
``allow_fast=True``: the autotuner must never auto-select a regime the
1e-4 determinism oracle doesn't cover.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

# Dense-kernel m-tile candidates: powers of two spanning the regimes the
# r2/r3 on-chip sweeps explored. _qualify pre-shrinks over-budget tiles,
# so enumeration may include tiles a given s_dim can't hold.
DENSE_M_TILES = (128, 256, 512, 1024)

# Oracle-grade contraction regimes (auto-selectable) vs throughput
# regimes (opt-in via allow_fast; see sketch/params.py regime docs).
ORACLE_PRECISIONS = ("bf16x3", "f32")
FAST_PRECISIONS = ("bf16gen2", "bf16")

# ops the dense kernel can serve
DENSE_OPS = ("dense_rowwise", "dense_columnwise", "rft_rowwise")
FASTFOOD_OPS = ("fastfood_rows",)

# hash (CWT/CountSketch) direct-apply dispatch sites — the scatter-free
# kernel (sketch/pallas_hash.py) vs the XLA segment_sum scatter
HASH_OPS = ("hash_rowwise", "hash_columnwise")

# serve-bucket dispatch sites (engine/serve.py flush builders): one
# workload per (endpoint/orientation, transform family, dtype, pow2
# shape class, batch capacity class). The ``batch`` field carries the
# capacity class; backends are "pallas" (the endpoint's batched kernel
# — hash, dense, fused-fastfood, or sparse-CSR) vs "xla" (the vmapped
# XLA flush). The sparse ops additionally carry the pow2 **nnz class**
# (``Workload.nnz``) — the sparse kernel's cost is a function of the
# nonzero count, not the dense extents.
SERVE_OPS = ("serve_sketch_cw", "serve_sketch_rw", "serve_fastfood",
             "serve_sparse_cw", "serve_sparse_rw", "serve_cmm")

# the sparse-CSR serve sites (subset of SERVE_OPS): scatter-free
# sparse-CountSketch kernel (sketch/pallas_sparse.py) vs the XLA
# O(nnz) scatter
SPARSE_SERVE_OPS = ("serve_sparse_cw", "serve_sparse_rw")

# dense-family and SRHT serve buckets enumerate a small m-tile ladder
# (the batched kernel's only knob); CWT/fastfood serve kernels are
# knobless.
SERVE_DENSE_M_TILES = (128, 256, 512)

# serve families whose sketch operator is a dense virtual stream, and
# the dense-kernel distribution each maps onto (the serve workload's
# ``transform`` field carries the FAMILY tag, the cost model prices the
# underlying stream)
SERVE_DENSE_FAMILIES = {"JLT": "normal", "CT": "cauchy"}


def bucket_dim(x: int) -> int:
    """Next power of two ≥ x (min 8): one cache entry serves the whole
    bucket; concrete-shape feasibility stays the dispatcher's job."""
    x = max(int(x), 8)
    return 1 << (x - 1).bit_length()


def normalize_device_kind(kind: str) -> str:
    """Canonical cache-key form of ``jax.Device.device_kind`` (or
    "cpu"): lowercased, runs of non-alphanumerics collapsed to one
    underscore, so "TPU v5 lite" and "tpu-v5-lite" key identically."""
    import re

    return re.sub(r"[^a-z0-9]+", "_", str(kind).lower()).strip("_")


def current_device_kind() -> str:
    try:
        import jax

        return normalize_device_kind(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


@dataclasses.dataclass(frozen=True)
class Workload:
    """One cacheable hot-path invocation class.

    ``op``: dispatch site — one of DENSE_OPS / FASTFOOD_OPS.
    ``transform``: the operator stream kind — a distribution kind
    ("normal"/"cauchy"/"rademacher") for dense ops, the transform's
    ``sketch_type`` for Fastfood.
    ``shape``: (m, n, s) — m the non-contracted input extent, n the
    contracted (sketched) extent, s the sketch/feature dimension.
    """

    device_kind: str
    op: str
    transform: str
    dtype: str
    shape: tuple[int, int, int]
    # batch capacity class (serve workloads only; 0 = not batched).
    # Appended to the key only when set, so every pre-serve cache key —
    # including the committed benchmarks/plan_cache.json entries —
    # is unchanged.
    batch: int = 0
    # pow2 nnz class (sparse serve workloads only; 0 = dense). Same
    # append-only key rule as ``batch``: pre-sparse keys are unchanged.
    nnz: int = 0

    def bucket(self) -> tuple[int, int, int]:
        return tuple(bucket_dim(d) for d in self.shape)

    def key(self) -> str:
        b = "x".join(str(d) for d in self.bucket())
        base = "|".join((normalize_device_kind(self.device_kind),
                         self.op, self.transform, str(self.dtype), b))
        if self.batch:
            base = f"{base}|b{self.batch}"
        if self.nnz:
            base = f"{base}|z{self.nnz}"
        return base


@dataclasses.dataclass(frozen=True)
class Plan:
    """One complete tuning decision for a workload.

    ``backend``: "pallas" | "xla" for dense ops; "fused" | "split" |
    "xla_chain" for Fastfood. The XLA backends carry no knobs — they
    mean "take the existing non-kernel path".
    """

    backend: str
    m_tile: Optional[int] = None
    precision: Optional[str] = None
    pipeline: bool = False

    def plan_id(self) -> str:
        """Deterministic short id — the label bench records carry and
        tie-break ranking sorts by."""
        parts = [self.backend]
        if self.m_tile is not None:
            parts.append(f"mt{self.m_tile}")
        if self.precision is not None:
            parts.append(self.precision)
        if self.pipeline:
            parts.append("pipe")
        return "/".join(parts)

    @classmethod
    def from_plan_id(cls, token: str,
                     known_backends=("pallas", "xla")) -> "Plan | None":
        """Invert :meth:`plan_id` (the warmup-pack manifests persist
        plan ids as the per-bucket kernel decision). Kept next to the
        encoder so the two formats cannot drift apart silently.
        Returns None for a token this build does not understand — an
        unknown backend, an empty part, or (since every known
        component is matched explicitly) more than one free-form
        precision part."""
        parts = str(token).split("/")
        if not parts or parts[0] not in known_backends:
            return None
        m_tile = None
        precision = None
        pipeline = False
        for p in parts[1:]:
            if p.startswith("mt") and p[2:].isdigit():
                m_tile = int(p[2:])
            elif p == "pipe":
                pipeline = True
            elif p and precision is None:
                precision = p
            else:
                return None
        return cls(backend=parts[0], m_tile=m_tile,
                   precision=precision, pipeline=pipeline)

    def to_dict(self) -> dict:
        d = {"backend": self.backend}
        if self.m_tile is not None:
            d["m_tile"] = int(self.m_tile)
        if self.precision is not None:
            d["precision"] = self.precision
        if self.pipeline:
            d["pipeline"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        return cls(
            backend=str(d["backend"]),
            m_tile=(int(d["m_tile"]) if d.get("m_tile") is not None
                    else None),
            precision=d.get("precision"),
            pipeline=bool(d.get("pipeline", False)),
        )


def _dense_candidates(w: Workload, precisions: Sequence[str]
                      ) -> Iterator[Plan]:
    m, _n, _s = w.bucket()
    for prec in precisions:
        for mt in DENSE_M_TILES:
            if mt > m:
                continue
            for pipe in (False, True):
                yield Plan("pallas", m_tile=mt, precision=prec,
                           pipeline=pipe)
    yield Plan("xla")


def _fastfood_candidates(precisions: Sequence[str]) -> Iterator[Plan]:
    for prec in precisions:
        yield Plan("fused", precision=prec)
        yield Plan("split", precision=prec)
    yield Plan("xla_chain")


def _serve_candidates(w: Workload) -> Iterator[Plan]:
    """Kernel-vs-XLA candidates for one serve bucket. The dense
    families enumerate the batched kernel's m-tile ladder; the hash,
    fastfood and sparse serve kernels are knobless — precision stays
    the serve layer's own policy (oracle regimes only), so a committed
    cache entry can never opt a flush into bf16. Sparse buckets whose
    family is not CWT have no kernel (the dense-family sparse flush is
    an in-executable densify + the dense program) and enumerate only
    the XLA path. The compressed-matmul endpoint is always-XLA (two
    sketch programs plus a small GEMM; no fused kernel exists), so it
    enumerates exactly one plan. SRHT buckets ride the same m-tile
    ladder as the dense families: the in-kernel FWHT sweeps the batch
    in row panels and the panel height is its only knob."""
    if w.op == "serve_cmm":
        yield Plan("xla")
        return
    if w.op in SPARSE_SERVE_OPS:
        if w.transform == "CWT":
            yield Plan("pallas")
        yield Plan("xla")
        return
    if w.transform in SERVE_DENSE_FAMILIES or w.transform == "SRHT":
        m, _n, _s = w.bucket()
        for mt in SERVE_DENSE_M_TILES:
            if mt <= max(m, SERVE_DENSE_M_TILES[0]):
                yield Plan("pallas", m_tile=mt)
    else:
        yield Plan("pallas")
    yield Plan("xla")


def enumerate_candidates(w: Workload,
                         allow_fast: bool = False) -> list[Plan]:
    """Every plan worth ranking for ``w``. The dense list crosses
    m-tiles × precision regimes × pipeline on/off, plus the XLA
    fallback; Fastfood crosses variant × precision plus the XLA chain;
    hash and serve buckets cross the scatter-free kernel vs the XLA
    path. ``allow_fast`` adds the accuracy-opt-in regimes (never
    auto-selected by default — see module doc)."""
    precisions = ORACLE_PRECISIONS + (FAST_PRECISIONS if allow_fast
                                      else ())
    if w.op in DENSE_OPS:
        return list(_dense_candidates(w, precisions))
    if w.op in FASTFOOD_OPS:
        return list(_fastfood_candidates(precisions))
    if w.op in HASH_OPS:
        return [Plan("pallas"), Plan("xla")]
    if w.op in SERVE_OPS:
        return list(_serve_candidates(w))
    raise ValueError(f"unknown workload op {w.op!r}")
