"""Utility layer: profiling/timers and observability helpers
(SURVEY.md §2.6/§5; ref: utility/timer.hpp, utility/external/print.hpp)."""

from libskylark_tpu.utility.timer import (
    PhaseTimer,
    get_timer,
    set_enabled,
    timers_enabled,
)

__all__ = ["PhaseTimer", "get_timer", "set_enabled", "timers_enabled"]
