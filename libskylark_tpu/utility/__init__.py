"""Utility layer: profiling/timers, observability, and training-state
checkpointing (SURVEY.md §2.6/§5; ref: utility/timer.hpp,
utility/external/print.hpp — checkpoint/resume has no reference
counterpart: the §5 aux-subsystem row is empty there)."""

from libskylark_tpu.utility.timer import (
    PhaseTimer,
    get_timer,
    set_enabled,
    timers_enabled,
)

__all__ = [
    "PhaseTimer",
    "TrainCheckpointer",
    "as_checkpointer",
    "device_state",
    "get_timer",
    "load_sync",
    "save_sync",
    "set_enabled",
    "timers_enabled",
]

_CHECKPOINT_NAMES = ("TrainCheckpointer", "as_checkpointer",
                     "device_state", "save_sync", "load_sync")


def __getattr__(name):
    # PEP 562 lazy re-export: checkpoint.py imports orbax (~seconds of
    # startup), which must not be paid by every `import libskylark_tpu`
    # that never checkpoints
    if name in _CHECKPOINT_NAMES:
        from libskylark_tpu.utility import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(name)
