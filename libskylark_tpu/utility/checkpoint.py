"""Training-state checkpoint/resume for host-loop solvers.

The reference has no training-state persistence (its aux-subsystem
survey row "failure detection / checkpoint-resume" is empty — SURVEY.md
§5); models and sketches serialize, but a killed 1000-iteration ADMM run
restarts from zero. On TPU this matters operationally: long solves on
preemptible capacity are the norm, so the solver state (the ADMM
consensus carry, a restarted-Krylov basis, a streaming-sketch
accumulator) must outlive the process.

Design: a thin wrapper over orbax (the JAX-ecosystem checkpointer) —
async by default so the save streams out of HBM while the next
iterations compute, atomic + versioned on disk, with a JSON metadata
sidecar validated on restore. Anything shaped like a pytree of arrays
checkpoints; solvers opt in by taking a ``checkpoint=`` argument (see
``BlockADMMSolver.train``).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from libskylark_tpu.base import errors

try:  # pragma: no cover - exercised via the public API below
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False


class TrainCheckpointer:
    """Versioned training-state store under one directory.

    ``save(step, state, metadata)`` persists a pytree of arrays plus a
    small JSON dict; ``restore()`` returns the newest ``(step, state,
    metadata)``. Saves are asynchronous (compute overlaps the HBM→disk
    stream) unless ``async_save=False``; in-flight writes are finalized
    on ``close()`` / context-manager exit / before a dependent
    ``restore``.

    ``keep`` bounds disk usage to the newest N steps.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 async_save: bool = True):
        if not _HAVE_ORBAX:  # pragma: no cover
            raise errors.UnsupportedError(
                "orbax-checkpoint is required for TrainCheckpointer")
        # Initialize the CONFIGURED default backend before orbax's
        # manager construction touches jax: its process/distributed
        # detection can otherwise trigger backend discovery that
        # initializes a non-default platform plugin (observed on the
        # axon image: a cpu-configured process hung initializing the
        # wedged TPU tunnel inside CheckpointManager.__init__).
        jax.devices()
        self._dir = os.path.abspath(str(directory))
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=int(keep),
                enable_async_checkpointing=bool(async_save),
            ),
        )

    # -- write side --

    def save(self, step: int, state: Any,
             metadata: Optional[dict] = None) -> None:
        """Persist ``state`` (pytree of arrays) at ``step``. Returns
        immediately in async mode; the write is crash-consistent (orbax
        commits atomically per step directory)."""
        from libskylark_tpu.resilience import faults

        faults.check("checkpoint.save", detail=f"step={int(step)}")
        self._mngr.save(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                metadata=ocp.args.JsonSave(metadata or {}),
            ),
        )

    def save_sync(self, step: int, state: Any,
                  metadata: Optional[dict] = None, retry=None) -> None:
        """The preemption-handler save: blocks until the step is durable
        on disk, retrying transient failures under ``retry`` (default: 3
        attempts, short backoff — a SIGTERM grace window is seconds, not
        minutes). Used by
        :func:`libskylark_tpu.resilience.register_checkpoint`; a normal
        training loop wants the async :meth:`save` instead."""
        from libskylark_tpu.resilience.policy import RetryPolicy

        retry = retry or RetryPolicy(max_attempts=3, base_delay=0.1,
                                     max_delay=1.0)

        def attempt():
            self.save(step, state, metadata)
            self._mngr.wait_until_finished()

        retry.call(attempt)

    # -- read side --

    def latest_step(self) -> Optional[int]:
        self._mngr.wait_until_finished()
        return self._mngr.latest_step()

    def restore(self, step: Optional[int] = None, target: Any = None):
        """(step, state, metadata) for ``step`` (default: newest).

        ``target`` — a pytree of like-structured arrays (e.g. the
        freshly-initialized solver state) — restores directly into that
        structure/dtype/sharding; without it, arrays come back as numpy
        and orbax warns that the topology is unverified."""
        self._mngr.wait_until_finished()
        step = self._mngr.latest_step() if step is None else int(step)
        if step is None:
            raise errors.InvalidParametersError(
                f"no checkpoint found under {self._dir}")
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target),
                metadata=ocp.args.JsonRestore(),
            ),
        )
        return step, out["state"], dict(out["metadata"] or {})

    def metadata(self, step: Optional[int] = None):
        """(step, metadata) WITHOUT touching the state arrays — callers
        validate identity/compatibility first, then ``restore`` with a
        ``target`` (a mismatched state would fail inside orbax with a
        shape error before any friendly validation could run)."""
        self._mngr.wait_until_finished()
        step = self._mngr.latest_step() if step is None else int(step)
        if step is None:
            raise errors.InvalidParametersError(
                f"no checkpoint found under {self._dir}")
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(metadata=ocp.args.JsonRestore()),
        )
        return step, dict(out["metadata"] or {})

    def all_steps(self) -> list[int]:
        self._mngr.wait_until_finished()
        return sorted(self._mngr.all_steps())

    # -- lifecycle --

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# synchronous state snapshots: the serve-session twin of save_sync
# ---------------------------------------------------------------------------
#
# ``TrainCheckpointer.save_sync`` is the right tool for long training
# loops (async by default, orbax-managed step history). The stateful
# serve sessions (:mod:`libskylark_tpu.sessions`) need something much
# smaller inside a SIGTERM drain hook: one atomic, durable, dependency-
# light snapshot of a dict of host arrays plus a JSON sidecar — written
# in milliseconds, readable by a peer process with nothing but numpy.
# These module-level twins provide exactly that (npz + json, tmp-file +
# rename atomicity, fsync before rename) and are what
# ``SessionRegistry.checkpoint`` calls from the r9 drain path.


def save_sync(path: str, arrays: dict, metadata: Optional[dict] = None
              ) -> None:
    """Atomically persist ``arrays`` (name -> host ndarray) at ``path``
    (``<path>.npz`` + ``<path>.json``), durable before return — the
    drain-hook discipline of :meth:`TrainCheckpointer.save_sync`
    without the orbax machinery. Byte-exact round trip: ``np.save``
    stores raw array bytes, so a restored accumulator continues
    bit-equal. The ``checkpoint.save`` fault site fires here too, so
    chaos plans cover session checkpoints and training saves alike."""
    import json

    import numpy as np

    from libskylark_tpu.resilience import faults

    faults.check("checkpoint.save", detail=f"sync:{os.path.basename(path)}")
    # the metadata rides INSIDE the npz (a reserved key), so one
    # os.replace commits arrays and metadata together — a two-file
    # scheme can crash between renames and pair a new-generation npz
    # with the previous generation's sidecar, which a resume would
    # read as "replay from the OLD seq" and double-fold the journal
    # tail (review finding). The .json twin below is human forensics
    # only; load_sync never trusts it.
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    if "__meta__" in payload:
        raise ValueError("'__meta__' is a reserved checkpoint key")
    payload["__meta__"] = np.frombuffer(
        json.dumps(metadata or {}).encode("utf-8"), dtype=np.uint8)
    npz_tmp = path + ".npz.tmp"
    with open(npz_tmp, "wb") as fh:
        np.savez(fh, **payload)
        fh.flush()
        os.fsync(fh.fileno())
    json_tmp = path + ".json.tmp"
    with open(json_tmp, "w") as fh:
        json.dump(metadata or {}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(npz_tmp, path + ".npz")
    os.replace(json_tmp, path + ".json")


def load_sync(path: str):
    """``(arrays, metadata)`` written by :func:`save_sync`, or ``None``
    when no committed snapshot exists at ``path``. The npz is the one
    unit of atomicity — metadata comes from its embedded ``__meta__``
    record, never from the forensics sidecar, so arrays and metadata
    can never be read from different checkpoint generations."""
    import json

    import numpy as np

    if not os.path.exists(path + ".npz"):
        return None
    with np.load(path + ".npz") as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        metadata = json.loads(bytes(z["__meta__"]).decode("utf-8"))
    return arrays, metadata


def as_checkpointer(obj) -> TrainCheckpointer:
    """Coerce a path-or-checkpointer argument (solver ``checkpoint=``
    convenience: pass a directory string and get defaults)."""
    if isinstance(obj, TrainCheckpointer):
        return obj
    return TrainCheckpointer(str(obj))


@jax.jit
def _spanning_stat(a):
    """Position-weighted f32 reduction, jit-compiled so host-spanning
    operands are legal; the scalar result is replicated everywhere."""
    w = jnp.cos(jnp.arange(a.shape[0], dtype=jnp.float32) * 0.73 + 0.2)
    if a.ndim == 2:
        w = w[:, None] * jnp.cos(
            jnp.arange(a.shape[1], dtype=jnp.float32) * 1.37 + 0.4
        )[None, :]
    return jnp.sum(a * w, dtype=jnp.float32)


def _fully_addressable(a) -> bool:
    """Whether every shard of ``a`` is host-readable (host arrays: yes;
    jax.Arrays spanning other processes' devices: no). Seam for tests —
    multi-host topologies can't be constructed in a unit process."""
    if isinstance(a, jax.Array):
        return a.is_fully_addressable
    return True


def sample_digest(a, rows: int | None = None,
                  byte_budget: int = 64 << 20) -> str:
    """Exact, platform-independent data identity for resume checks
    (ADMM data, streaming batch 0): sha256 over the f32 BYTES of a
    deterministic sample of leading-axis slices plus the full shape.

    Sampling policy (r4 advisor — a fixed 16-row sample let a one-row
    edit in a 1e6-row operand pass the resume check ~99.998% of the
    time): hash ALL bytes whenever the f32 view fits ``byte_budget``
    (64 MiB default — an (n, d) float32 design matrix up to ~16M
    elements is fully covered); above the budget, sample as many evenly
    strided leading-axis slices as the budget buys — the budget bounds
    SAMPLED BYTES, so wide-row operands gather few rows (never fewer
    than 16) rather than blowing past it. ``rows`` overrides the
    computed sample size when given (bounded callers). Byte equality is
    exact and identical across TPU/CPU and JAX versions. Coverage limit
    above the budget (documented trade): content changes confined to
    unsampled rows are not caught; shape changes and any change
    touching a sampled row (including permutations that move sampled
    rows) are."""
    import hashlib

    import numpy as np

    if not _fully_addressable(a):
        # Multi-host-sharded operand: a host gather of even a few rows
        # would raise (spans non-addressable devices), and so would any
        # EAGER op — multi-process arrays compute only under jit. Fall
        # back to a jitted device-side global f32 reduction whose scalar
        # output is fully replicated (hence host-readable on every
        # process, and identical across them). Position-weighted along
        # both axes so a row/column permutation — which would misalign
        # restored state — changes it. Pinned to the platform/JAX
        # version (reduction order): multi-host checkpoints resume only
        # on the topology they were saved under. Single-host keeps the
        # portable byte digest below.
        stat = float(_spanning_stat(a))
        return hashlib.sha256(
            repr((tuple(a.shape), "device_stat", stat)).encode()
        ).hexdigest()

    n = int(a.shape[0]) if getattr(a, "ndim", 0) else 1
    if rows is None:
        row_bytes = 4 * int(np.prod(
            [int(d) for d in getattr(a, "shape", ())[1:]], dtype=np.int64)
            or 1)
        # byte-bounded, never fewer than 16 rows: a (4k, 4M) operand
        # must not be forced to gather 1024 × 16 MB rows (review
        # finding — a row-count floor inverts the byte budget)
        rows = max(16, byte_budget // max(row_bytes, 1))
    idx = sorted(set(
        int(i) for i in np.linspace(0, max(n - 1, 0), num=min(rows, n))))
    idx_arr = np.asarray(idx, dtype=np.intp)  # empty axis: valid no-op
    sample = np.ascontiguousarray(
        np.asarray(a[idx_arr] if getattr(a, "ndim", 0) else a,
                   np.float32))
    h = hashlib.sha256()
    h.update(repr((tuple(getattr(a, "shape", ())), idx)).encode())
    h.update(sample.tobytes())
    return h.hexdigest()


def device_state(state, dtype=None):
    """Restore helper: a pytree of host arrays → device arrays,
    floating-point leaves cast to ``dtype`` when given. Integer/bool
    leaves keep their stored dtype — a step counter or index array in a
    general training state must not be silently cast to the float
    compute dtype (r3 advisor)."""
    def put(x):
        if not hasattr(x, "shape"):
            return x
        if dtype is not None and jnp.issubdtype(
                getattr(x, "dtype", jnp.float32), jnp.floating):
            return jnp.asarray(x, dtype)
        return jnp.asarray(x)
    return jax.tree_util.tree_map(put, state)
