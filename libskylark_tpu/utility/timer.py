"""Phase timers: accumulate per-phase wall time across an algorithm run.

TPU-native analog of ref: utility/timer.hpp:6-64 — the
SKYLARK_TIMER_{INITIALIZE,RESTART,ACCUMULATE,PRINT} macro family that
BlockADMM uses to profile its phases (ref: ml/BlockADMM.hpp:357-365,573+).
Where the reference reduces min/max/avg over MPI ranks at print time, the
TPU runtime is single-controller: per-phase host wall time is the profile,
and each phase also enters a ``jax.profiler.TraceAnnotation`` so the same
phase names appear on the device timeline when tracing with
``jax.profiler.trace`` (the deeper equivalent of the reference's profiler
integration).

Enablement mirrors the reference's compile-time SKYLARK_HAVE_PROFILER gate
(ref: config.h.in:107-108) as a runtime switch: the SKYLARK_TPU_PROFILE=1
environment variable or :func:`set_enabled`. Disabled timers cost one dict
lookup and one branch per phase.

Since the telemetry subsystem landed, :class:`PhaseTimer` is a thin
shim over :func:`libskylark_tpu.telemetry.span` — each phase IS a span
(``force=True``: phase timers keep this module's own enablement gate,
independent of the global ``SKYLARK_TELEMETRY`` switch), so phases
flow to the JSONL exporter and nest under whatever span is active,
while the ``TraceAnnotation`` mirroring this module always did now
lives in the span layer. The public API (``phase`` / ``accumulate`` /
``report`` / ``reset`` / the :func:`get_timer` registry) is unchanged.

Timing note: phases measure *host* wall time. JAX dispatch is async — a
phase that only enqueues device work appears near-free while the next
synchronizing phase absorbs its cost. Phases that must attribute device
time accurately should end with a ``block_until_ready`` on their outputs
(the ADMM instrumentation does this for the iteration phase only, to avoid
serializing the pipeline the rest of the time).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional

from libskylark_tpu.base import env as _env

_ENABLED: Optional[bool] = None


def timers_enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = bool(_env.TPU_PROFILE.get())
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic switch (overrides the environment gate)."""
    global _ENABLED
    _ENABLED = bool(on)


class PhaseTimer:
    """Named accumulators: ``with timer.phase("TRANSFORM"): ...``
    (ref: SKYLARK_TIMER_RESTART/ACCUMULATE, utility/timer.hpp:23-42)."""

    def __init__(self, name: str = ""):
        self.name = name
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextmanager
    def phase(self, label: str):
        if not timers_enabled():
            yield
            return
        from libskylark_tpu.telemetry import trace

        # force=True: the phase gate is THIS module's enablement, not
        # the global telemetry switch; the span carries the
        # TraceAnnotation mirroring (device-timeline alignment) and
        # flows to any installed exporter
        with trace.span(label, attrs={"phase_timer": self.name or
                                      "default"}, force=True) as sp:
            yield
        self.totals[label] = self.totals.get(label, 0.0) + sp.duration_s
        self.counts[label] = self.counts.get(label, 0) + 1

    def accumulate(self, label: str, seconds: float) -> None:
        """Manual accumulation for phases timed externally."""
        if not timers_enabled():
            return
        self.totals[label] = self.totals.get(label, 0.0) + float(seconds)
        self.counts[label] = self.counts.get(label, 0) + 1

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()

    def report(self, stream=None) -> str:
        """Format (and optionally print) the phase table
        (ref: SKYLARK_TIMER_PRINT, utility/timer.hpp:44-53)."""
        lines = [f"== phase timings{' [' + self.name + ']' if self.name else ''} =="]
        width = max((len(k) for k in self.totals), default=5)
        for label in sorted(self.totals, key=self.totals.get, reverse=True):
            t, c = self.totals[label], self.counts[label]
            lines.append(
                f"{label.ljust(width)}  total {t:10.4f}s  "
                f"calls {c:6d}  avg {t / c:10.6f}s"
            )
        text = "\n".join(lines)
        if stream is not None:
            print(text, file=stream)
        return text


_REGISTRY: Dict[str, PhaseTimer] = {}


def get_timer(name: str = "default") -> PhaseTimer:
    """Process-wide named timer registry (the reference's file-scope timer
    variables declared by SKYLARK_TIMER_INITIALIZE)."""
    if name not in _REGISTRY:
        _REGISTRY[name] = PhaseTimer(name)
    return _REGISTRY[name]
