"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed behavior with
``mpirun -np N`` on a single box (ref: tests/unit/CMakeLists.txt:10-46);
here N virtual XLA host devices play the role of MPI ranks. Must run before
jax initializes its backends, hence the env mutation at import time.
"""

import os

_ON_CHIP = os.environ.get("SKYLARK_TEST_TPU") == "1"

if not _ON_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _ON_CHIP:
    # The axon sitecustomize pre-imports jax with the TPU platform pinned;
    # the config update (post-import, pre-backend-init) overrides it
    # reliably. SKYLARK_TEST_TPU=1 leaves the real backend in place so the
    # @pytest.mark.tpu on-chip oracle tests (the run-on-target discipline of
    # ref: tests/unit/CMakeLists.txt:10-46) execute on hardware.
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass  # older jax: the XLA_FLAGS above covers it

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    if _ON_CHIP and len(devs) != 8:
        pytest.skip(
            "mesh tests need the 8-device virtual CPU mesh; run without "
            "SKYLARK_TEST_TPU=1 (on-chip runs select -m tpu)"
        )
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh1d(devices):
    from libskylark_tpu.parallel import make_mesh

    return make_mesh()


@pytest.fixture()
def mesh2d(devices):
    from libskylark_tpu.parallel import make_mesh

    return make_mesh((2, 4))
