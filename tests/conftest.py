"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed behavior with
``mpirun -np N`` on a single box (ref: tests/unit/CMakeLists.txt:10-46);
here N virtual XLA host devices play the role of MPI ranks. Must run before
jax initializes its backends, hence the env mutation at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize pre-imports jax with the TPU platform pinned; the
# config update (post-import, pre-backend-init) overrides it reliably.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # older jax: the XLA_FLAGS above covers it

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh1d():
    from libskylark_tpu.parallel import make_mesh

    return make_mesh()


@pytest.fixture()
def mesh2d():
    from libskylark_tpu.parallel import make_mesh

    return make_mesh((2, 4))
