"""Must-pass: registry reads, non-SKYLARK reads, env writes."""

import os

from libskylark_tpu.base import env as _env


def read_ok():
    a = _env.TELEMETRY.get()                  # registry accessor
    b = os.environ.get("JAX_PLATFORMS")       # non-SKYLARK literal
    return a, b


def write_ok(snapshot):
    # writes and whole-env snapshots are allowed (replica apply path)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_TRACEBACK_FILTERING", None)
    return dict(os.environ), snapshot
