"""Must-flag: raw SKYLARK_* env reads outside base/env.py."""

import os


def read_flag():
    # each of these is one env-registry finding
    a = os.environ.get("SKYLARK_BOGUS_FLAG")
    b = os.environ["SKYLARK_BOGUS_SUBSCRIPT"]
    c = os.getenv("SKYLARK_BOGUS_GETENV")
    d = "SKYLARK_BOGUS_MEMBER" in os.environ
    e = os.environ.get(compute_name())        # dynamic key
    return a, b, c, d, e


def compute_name():
    return "SKYLARK_" + "DYNAMIC"
