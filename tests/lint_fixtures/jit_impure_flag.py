"""Must-flag: a jit-impure closure — the traced function reads the
environment, a wall clock, host RNG, and a mutable module global
(directly and through a helper)."""

import os
import random
import time

import jax

_KNOB = 1.0


def set_knob(v):
    global _KNOB
    _KNOB = v


def _helper():
    # impurity reached transitively from the root
    return float(os.environ.get("SKYLARK_BOGUS_JIT", "0"))


@jax.jit
def impure_root(x):
    # env via helper, clock, host RNG, and a mutable module global
    return x * _helper() * time.time() * random.random() * _KNOB


def build():
    def inner(x):
        return x + _helper()

    return jax.jit(inner)
