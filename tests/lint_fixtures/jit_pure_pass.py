"""Must-pass: pure traced functions; impure work outside the trace."""

import time

import jax
import jax.numpy as jnp

_SCALE = 2.0  # assigned once, never rebound — not a mutable global


@jax.jit
def pure_root(x):
    return jnp.sin(x) * _SCALE


def timed_call(x):
    # clocks OUTSIDE the traced function are fine
    t0 = time.perf_counter()
    y = pure_root(x)
    return y, time.perf_counter() - t0
