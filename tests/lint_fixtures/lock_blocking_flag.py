"""Must-flag: blocking calls and callback fan-out under a held lock,
and a direct ``threading.Lock()`` construction."""

import threading
import time

from libskylark_tpu.base import locks as _locks

_LOCK = _locks.make_lock("fixture.blocking")
_BARE = threading.Lock()          # must-flag: unnamed, invisible to
#                                   the witness and the static graph
_CALLBACKS = []


def bad_result(fut):
    with _LOCK:
        return fut.result()       # must-flag: Future.result under lock


def bad_sleep():
    with _LOCK:
        time.sleep(0.1)           # must-flag: sleep under lock


def bad_fanout(event):
    with _LOCK:
        for cb in _CALLBACKS:
            cb(event)             # must-flag: callbacks under lock
