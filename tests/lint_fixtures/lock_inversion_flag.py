"""Must-flag (static) AND must-detect (runtime): a deliberate
two-lock order inversion.

``path_one`` acquires fixture.alpha -> fixture.beta; ``path_two``
acquires fixture.beta -> fixture.alpha. Run sequentially this never
deadlocks — which is exactly why the ordering, not the deadlock, is
what both the static ``lock-discipline`` rule and the runtime witness
must catch. ``tests/test_analysis.py`` asserts both do, on this same
file.
"""

from libskylark_tpu.base import locks as _locks

_ALPHA = _locks.make_lock("fixture.alpha")
_BETA = _locks.make_lock("fixture.beta")


def path_one():
    with _ALPHA:
        with _BETA:
            return 1


def path_two():
    with _BETA:
        with _ALPHA:
            return 2


def run_inversion():
    """Exercise both orders (sequentially — safe) so an instrumented-
    lock run records the cycle."""
    return path_one() + path_two()
