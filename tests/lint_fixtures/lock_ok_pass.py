"""Must-pass: consistent nesting order and condition waits."""

import threading

from libskylark_tpu.base import locks as _locks

_OUTER = _locks.make_lock("fixture.outer")
_INNER = _locks.make_lock("fixture.inner")


class Worker:
    def __init__(self):
        self._lock = _locks.make_lock("fixture.worker")
        self._cv = threading.Condition(self._lock)

    def both(self):
        with _OUTER:
            with _INNER:       # always outer -> inner: no cycle
                return 1

    def wait_ok(self, pred):
        with self._lock:
            while not pred():
                self._cv.wait(timeout=0.01)   # condition wait is fine
