"""Must-flag creations: undeclared name, kind mismatch, duplicate
site, bad Prometheus name, dynamic name."""

from libskylark_tpu.telemetry import metrics as _metrics

_BOGUS = _metrics.counter("demo.bogus", "Not declared")
_WRONG = _metrics.gauge("demo.requests", "Declared as counter")
_BADCHARS = _metrics.counter("Demo-Bad.Name", "Invalid characters")


def dynamic(name):
    return _metrics.counter(name, "Unauditable")
