"""Must-pass creations: declared names, one site each, right kinds."""

from libskylark_tpu.telemetry import metrics as _metrics

_REQS = _metrics.counter("demo.requests", "Requests served")
_DEPTH = _metrics.gauge("demo.depth", "Queue depth")
