"""Mini names module for the metric-names fixture project."""

from typing import Dict

METRICS: Dict[str, str] = {
    "demo.requests": "counter",
    "demo.depth": "gauge",
    "demo.never_created": "counter",   # stale on purpose (must-flag)
}
