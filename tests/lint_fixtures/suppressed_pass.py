"""Must-pass: violations neutralized by per-line suppressions."""

import os


def justified_read():
    # (a justification comment belongs here in real code)
    v = os.environ.get("SKYLARK_BOGUS_OK")  # skylark-lint: disable=env-registry
    # standalone-comment form covers the NEXT line:
    # skylark-lint: disable=env-registry
    w = os.environ.get("SKYLARK_BOGUS_NEXT_LINE")
    return v, w
