"""Worker for tests/test_multihost.py: one simulated HOST process.

Run as ``python multihost_worker.py <pid> <nprocs> <port>
[devices_per_proc]``. Joins the pool through the framework's own bootstrap
(``parallel.multihost.initialize_distributed`` — the MPI_Init analog,
ref: ml/skylark_ml.cpp:17-20), builds a mesh spanning every process's
devices, and checks the framework oracle ACROSS HOSTS: a sketch applied
to a row-sharded global array equals the local same-seed apply; a
cross-host psum reduction agrees with the analytic value. Prints
``MULTIHOST_OK`` on success — the parent test asserts it from every
process."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# >1 virtual devices per process → the mesh crosses hosts AND has
# intra-host device parallelism (2 hosts × 4 devices, or 4 hosts × 2 —
# the 4-host shape puts THREE host boundaries in the mesh, catching
# axis-ordering/non-adjacent-shard bugs the pairwise case can't)
DPP = int(sys.argv[4]) if len(sys.argv) > 4 else 4
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={DPP}").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from libskylark_tpu.parallel import multihost

    multihost.initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert multihost.process_count() == nprocs
    assert multihost.process_index() == pid
    assert multihost.is_root() == (pid == 0)

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.sketch import COLUMNWISE, CWT, JLT

    devs = jax.devices()
    n_dev = len(devs)
    assert n_dev == nprocs * DPP, \
        f"expected {nprocs * DPP} devices, {n_dev}"
    mesh = Mesh(np.array(devs), ("d",))

    # Global problem, identical in every process (same seed); each
    # process contributes only its local row shards.
    n, d, s = 64 * n_dev, 16, 32
    rng = np.random.default_rng(42)
    X = rng.standard_normal((n, d)).astype(np.float32)
    sharding = NamedSharding(mesh, P("d"))
    Xs = jax.make_array_from_callback(
        (n, d), sharding, lambda idx: X[idx])

    for name, T in (("CWT", CWT(n, s, Context(seed=3))),
                    ("JLT", JLT(n, s, Context(seed=4)))):
        want = np.asarray(T.apply(jnp.asarray(X), COLUMNWISE))
        got = multihost_utils.process_allgather(
            T.apply(Xs, COLUMNWISE), tiled=True)
        np.testing.assert_allclose(np.asarray(got), want,
                                   atol=1e-4, rtol=1e-4)
        print(f"proc {pid}: {name} cross-host oracle ok", flush=True)

    # the ml/ layer across hosts: Block-ADMM training on host-spanning
    # data must match the local same-seed oracle (P7 at process level;
    # regression guard for the jitted step closing over global arrays —
    # multi-process jax forbids that, so X/Y/factorizations are jit
    # arguments)
    from libskylark_tpu.algorithms.prox import L2Regularizer, SquaredLoss
    from libskylark_tpu.ml.admm import BlockADMMSolver

    def make_solver():
        sol = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01,
                              num_features=d, num_partitions=2)
        sol.maxiter = 6
        sol.tol = 0.0
        return sol

    # classification labels: the 0..k-1 validation and k inference run
    # as device reductions (np.asarray of a host-spanning Y is
    # impossible), so this also guards the label path cross-host
    Yv = (X[:, 0] > 0).astype(np.int32)
    Ys = jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P()), lambda idx: Yv[idx])
    model = make_solver().train(Xs, Ys, regression=False)
    assert model.coef.is_fully_replicated
    local = make_solver().train(jnp.asarray(X), jnp.asarray(Yv),
                                regression=False)
    np.testing.assert_allclose(np.asarray(model.coef),
                               np.asarray(local.coef),
                               atol=1e-3, rtol=1e-3)
    print(f"proc {pid}: ADMM cross-host oracle ok", flush=True)

    # checkpoint/resume ACROSS HOSTS: a partial run checkpoints
    # host-spanning state (orbax multiprocess save under
    # jax.distributed), the rerun validates the resume identity — whose
    # data fingerprint takes the jitted spanning-stat path, since X/Y
    # span non-addressable devices here — and must finish bit-identical
    # to the uninterrupted run in EVERY process
    ck_root = os.environ.get("SKYLARK_MH_TMP")
    if ck_root:
        ckdir = os.path.join(ck_root, "admm_ck")
        part = make_solver()
        part.maxiter = 3
        part.train(Xs, Ys, regression=False, checkpoint=ckdir)
        full = make_solver()
        full.maxiter = 6
        resumed = full.train(Xs, Ys, regression=False, checkpoint=ckdir)
        np.testing.assert_array_equal(np.asarray(resumed.coef),
                                      np.asarray(model.coef))
        print(f"proc {pid}: ADMM cross-host checkpoint resume ok",
              flush=True)

    # the nla/algorithms layers across hosts: Krylov LSQR and randomized
    # SVD on host-spanning operands vs the local same-seed oracles
    # (eager ops and lax.while_loop take spanning operands as arguments
    # naturally — unlike a jitted closure — but only a process-level run
    # proves it)
    from libskylark_tpu.algorithms.krylov import KrylovParams, lsqr
    from libskylark_tpu.nla.svd import approximate_svd

    bvec = (X @ np.arange(d, dtype=np.float32))
    bs = jax.make_array_from_callback(
        (n,), sharding, lambda idx: bvec[idx])
    xg, _ = lsqr(Xs, bs, KrylovParams(iter_lim=30))
    xl, _ = lsqr(jnp.asarray(X), jnp.asarray(bvec),
                 KrylovParams(iter_lim=30))
    np.testing.assert_allclose(np.asarray(xg), np.asarray(xl),
                               atol=1e-3, rtol=1e-3)
    print(f"proc {pid}: LSQR cross-host oracle ok", flush=True)

    _, S_g, _ = approximate_svd(Xs, 4, Context(seed=7))
    _, S_l, _ = approximate_svd(jnp.asarray(X), 4, Context(seed=7))
    np.testing.assert_allclose(np.asarray(S_g), np.asarray(S_l),
                               atol=1e-3, rtol=1e-3)
    print(f"proc {pid}: randSVD cross-host oracle ok", flush=True)

    # raw cross-host collective sanity: psum over the host-spanning axis
    from libskylark_tpu.base.compat import shard_map

    gx = jax.make_array_from_callback(
        (n_dev,), sharding,
        lambda idx: np.full(1, float(pid + 1), np.float32))
    out = jax.jit(shard_map(lambda v: jax.lax.psum(v, "d"), mesh=mesh,
                            in_specs=P("d"), out_specs=P("d")))(gx)
    # each process holds DPP shards of value pid+1 → psum = DPP·Σ(i+1)
    expect = float(DPP) * sum(range(1, nprocs + 1))
    got = float(np.asarray(out.addressable_shards[0].data)[0])
    assert got == expect, (got, expect)
    print(f"proc {pid}: psum across hosts = {got} MULTIHOST_OK",
          flush=True)


if __name__ == "__main__":
    main()
