"""Tests for the algorithms layer: Krylov, prox, regression solvers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import Context
from libskylark_tpu import algorithms as alg
from libskylark_tpu.algorithms import prox


def _lstsq_problem(m, n, k=1, seed=0, cond=10.0):
    rng = np.random.default_rng(seed)
    U, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -np.log10(cond), n)
    A = (U * s) @ V.T
    X = rng.standard_normal((n, k))
    B = A @ X + 0.01 * rng.standard_normal((m, k))
    return (A.astype(np.float32), B.astype(np.float32))


class TestLSQR:
    def test_matches_lstsq(self):
        A, B = _lstsq_problem(120, 20)
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        x, it = alg.lsqr(jnp.asarray(A), jnp.asarray(B),
                         alg.KrylovParams(tolerance=1e-7, iter_lim=500))
        assert int(it) > 0
        np.testing.assert_allclose(np.asarray(x), x_np, atol=2e-3)

    def test_multiple_rhs(self):
        A, B = _lstsq_problem(100, 15, k=4, seed=1)
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        x, _ = alg.lsqr(jnp.asarray(A), jnp.asarray(B),
                        alg.KrylovParams(tolerance=1e-7, iter_lim=500))
        np.testing.assert_allclose(np.asarray(x), x_np, atol=2e-3)

    def test_preconditioned_converges_fast(self):
        """With R from QR(A) as right precond, LSQR must converge in a
        handful of iterations — the Blendenpik principle."""
        A, B = _lstsq_problem(200, 30, seed=2, cond=1e4)
        R = np.linalg.qr(A, mode="r")
        x_pre, it_pre = alg.lsqr(
            jnp.asarray(A), jnp.asarray(B),
            alg.KrylovParams(tolerance=1e-9, iter_lim=200),
            precond=alg.TriInversePrecond(jnp.asarray(R)),
        )
        _, it_plain = alg.lsqr(jnp.asarray(A), jnp.asarray(B),
                               alg.KrylovParams(tolerance=1e-9, iter_lim=200))
        assert int(it_pre) <= 5
        assert int(it_pre) < int(it_plain)
        # At cond=1e4 in f32, coefficients are ill-determined; judge by
        # residual optimality instead.
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        res_opt = np.linalg.norm(A @ x_np - B)
        res_pre = np.linalg.norm(A @ np.asarray(x_pre) - B)
        assert res_pre <= res_opt * 1.001 + 1e-6

    def test_operator_pair(self):
        A, B = _lstsq_problem(80, 10, seed=3)
        Aj = jnp.asarray(A)
        ops = ((lambda x: Aj @ x), (lambda x: Aj.T @ x))
        x, _ = alg.lsqr(ops, jnp.asarray(B),
                        alg.KrylovParams(tolerance=1e-7, iter_lim=300),
                        shape=A.shape)
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_np, atol=2e-3)

    def test_jittable(self):
        A, B = _lstsq_problem(60, 8, seed=4)

        @jax.jit
        def solve(Aj, Bj):
            x, it = alg.lsqr(Aj, Bj, alg.KrylovParams(tolerance=1e-6, iter_lim=100))
            return x

        x = solve(jnp.asarray(A), jnp.asarray(B))
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_np, atol=2e-3)


def _spd_problem(n, k=1, seed=0, cond=100.0):
    rng = np.random.default_rng(seed)
    Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -np.log10(cond), n)
    A = (Q * s) @ Q.T
    B = rng.standard_normal((n, k))
    return A.astype(np.float32), B.astype(np.float32)


class TestCG:
    def test_spd_solve(self):
        A, B = _spd_problem(50, k=2)
        x, it = alg.cg(jnp.asarray(A), jnp.asarray(B),
                       alg.KrylovParams(tolerance=1e-8, iter_lim=500))
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, B),
                                   atol=2e-3)

    def test_preconditioned(self):
        A, B = _spd_problem(60, seed=1, cond=1e4)
        Minv = np.linalg.inv(A + 0.01 * np.eye(60)).astype(np.float32)
        x_pre, it_pre = alg.cg(jnp.asarray(A), jnp.asarray(B),
                               alg.KrylovParams(tolerance=1e-8, iter_lim=300),
                               precond=alg.MatPrecond(jnp.asarray(Minv)))
        _, it_plain = alg.cg(jnp.asarray(A), jnp.asarray(B),
                             alg.KrylovParams(tolerance=1e-8, iter_lim=300))
        assert int(it_pre) < int(it_plain)

    def test_flexible_cg(self):
        A, B = _spd_problem(40, seed=2)
        x, _ = alg.flexible_cg(jnp.asarray(A), jnp.asarray(B),
                               alg.KrylovParams(tolerance=1e-8, iter_lim=300))
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, B),
                                   atol=2e-3)

    def test_chebyshev(self):
        A, B = _spd_problem(40, seed=3, cond=50.0)
        ev = np.linalg.eigvalsh(A)
        x, _ = alg.chebyshev(jnp.asarray(A), jnp.asarray(B),
                             float(ev[0] * 0.9), float(ev[-1] * 1.1),
                             alg.KrylovParams(iter_lim=120))
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, B),
                                   atol=5e-3)


class TestRandBlock:
    @pytest.mark.slow
    def test_gauss_seidel_converges(self):
        A, B = _spd_problem(100, seed=5, cond=20.0)
        x, sweeps = alg.asynch.rand_block_gauss_seidel(
            jnp.asarray(A), jnp.asarray(B), Context(seed=7),
            alg.asynch.RandBlockParams(block_size=32, sweeps=3, tolerance=1e-6,
                                       max_outer=40),
        )
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, B),
                                   atol=5e-3)

    @pytest.mark.slow
    def test_fcg_with_gs_preconditioner(self):
        A, B = _spd_problem(64, seed=6, cond=200.0)
        x, it = alg.asynch.rand_block_fcg(
            jnp.asarray(A), jnp.asarray(B), Context(seed=11),
            alg.asynch.RandBlockParams(block_size=16),
            alg.KrylovParams(tolerance=1e-8, iter_lim=200),
        )
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, B),
                                   atol=5e-3)


class TestExactSolvers:
    @pytest.mark.parametrize("method", ["qr", "sne", "ne", "svd"])
    def test_all_methods_agree(self, method):
        A, B = _lstsq_problem(100, 12, k=3, seed=7)
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        x = alg.solve_l2_exact(jnp.asarray(A), jnp.asarray(B), method=method)
        np.testing.assert_allclose(np.asarray(x), x_np, atol=2e-3)

    def test_unknown_method_raises(self):
        with pytest.raises(Exception, match="unknown exact l2"):
            alg.solve_l2_exact(jnp.eye(3), jnp.ones(3), method="nope")


class TestSketchedSolver:
    def test_residual_near_optimal(self):
        """Sketch-and-solve residual ≤ (1+ε)·optimal (Drineas et al.; the
        reference's ApproximateLeastSquares contract)."""
        from libskylark_tpu import sketch as sk

        A, B = _lstsq_problem(2000, 10, seed=8)
        T = sk.CWT(2000, 400, Context(seed=13))
        x = alg.solve_l2_sketched(jnp.asarray(A), jnp.asarray(B), T)
        res_opt = np.linalg.norm(A @ np.linalg.lstsq(A, B, rcond=None)[0] - B)
        res_sk = np.linalg.norm(A @ np.asarray(x) - B)
        assert res_sk <= 1.5 * res_opt + 1e-6


class TestAccelerated:
    @pytest.mark.parametrize("method", ["blendenpik", "lsrn", "simplified_blendenpik"])
    def test_solves_to_high_accuracy(self, method):
        A, B = _lstsq_problem(1500, 25, seed=9, cond=1e3)
        x, it = alg.solve_l2_accelerated(
            jnp.asarray(A), jnp.asarray(B), Context(seed=17), method=method,
        )
        assert int(it) > 0, "should use LSQR path, not fallback"
        x_np = np.linalg.lstsq(A, B, rcond=None)[0]
        # f32 accuracy floor, not solver quality: at cond=1e3 the
        # attainable error is ~cond·eps_f32·‖x‖ ≈ 4e-3 and the exact
        # placement wobbles with the toolchain's gemm rounding; 1e-2
        # stays a "high accuracy" bound (~3e-4 relative) while clearing
        # the floor on every jax line
        np.testing.assert_allclose(np.asarray(x), x_np, atol=1e-2)
        # sketch-preconditioned LSQR should converge quickly
        assert int(it) <= 60

    def test_fallback_on_rank_deficiency(self):
        rng = np.random.default_rng(10)
        A = rng.standard_normal((300, 10)).astype(np.float32)
        A[:, -1] = A[:, 0]  # exactly rank-deficient
        B = rng.standard_normal((300, 1)).astype(np.float32)
        x, it = alg.solve_l2_accelerated(
            jnp.asarray(A), jnp.asarray(B), Context(seed=19), method="blendenpik",
        )
        assert int(it) == 0, "should fall back to exact SVD solver"
        assert np.isfinite(np.asarray(x)).all()


class TestProx:
    def test_squared_loss(self):
        O = jnp.asarray([[1.0, 2.0, -1.0]])
        T = jnp.asarray([1.0, 0.0, 1.0])
        assert float(prox.SquaredLoss().evaluate(O, T)) == pytest.approx(
            0.5 * (0 + 4 + 4)
        )
        Y = prox.SquaredLoss().prox(O, 1.0, T)
        np.testing.assert_allclose(np.asarray(Y), [[1.0, 1.0, 0.0]])

    def test_lad_prox_properties(self):
        X = jnp.asarray([[3.0, 0.5, -2.0]])
        T = jnp.asarray([0.0, 0.0, 0.0])
        Y = np.asarray(prox.LADLoss().prox(X, 1.0, T))
        np.testing.assert_allclose(Y, [[2.0, 0.0, -1.0]])

    def test_hinge_loss(self):
        O = jnp.asarray([[0.5, 2.0, -1.0]])
        T = jnp.asarray([1.0, 1.0, -1.0])
        # losses: max(1-0.5,0)+max(1-2,0)+max(1-1,0) = 0.5
        assert float(prox.HingeLoss().evaluate(O, T)) == pytest.approx(0.5)

    def test_hinge_prox_piecewise(self):
        lam = 0.5
        X = jnp.asarray([[2.0, 0.9, -1.0]])
        T = jnp.asarray([1.0, 1.0, 1.0])
        Y = np.asarray(prox.HingeLoss().prox(X, lam, T))
        # yv=2>1 -> keep; yv=0.9 in [1-lam,1] -> set to t=1; yv=-1<1-lam -> x+lam*t
        np.testing.assert_allclose(Y, [[2.0, 1.0, -0.5]])

    def test_logistic_prox_reduces_objective(self):
        rng = np.random.default_rng(11)
        k, n = 5, 12
        X = jnp.asarray(rng.standard_normal((k, n)).astype(np.float32))
        T = jnp.asarray(rng.integers(0, k, n))
        lam = 0.7
        L = prox.LogisticLoss()
        Y = L.prox(X, lam, T)

        def objective(Z):
            return float(L.evaluate(Z, T)) + float(
                jnp.sum((Z - X) ** 2)
            ) / (2 * lam)

        assert objective(Y) < objective(X) - 1e-3
        # near-stationarity: gradient norm small
        labels = np.asarray(T)
        E = (np.arange(k)[:, None] == labels[None, :]).astype(np.float32)
        P = np.asarray(jax.nn.softmax(Y, axis=0))
        grad = P - E + (np.asarray(Y) - np.asarray(X)) / lam
        assert np.abs(grad).max() < 0.05

    def test_multiclass_expansion(self):
        O = jnp.zeros((3, 2))
        T = jnp.asarray([0, 2])
        # squared loss vs one-vs-all ±1: each column has one (0-1)^2 and two (0+1)^2
        assert float(prox.SquaredLoss().evaluate(O, T)) == pytest.approx(3.0)

    def test_regularizers(self):
        W = jnp.asarray([[2.0, -0.5], [0.1, -3.0]])
        mu = jnp.zeros_like(W)
        np.testing.assert_allclose(
            np.asarray(prox.L2Regularizer().prox(W, 1.0, mu)), np.asarray(W) / 2
        )
        Y = np.asarray(prox.L1Regularizer().prox(W, 1.0, mu))
        np.testing.assert_allclose(Y, [[1.0, 0.0], [0.0, -2.0]])
        np.testing.assert_allclose(
            np.asarray(prox.EmptyRegularizer().prox(W, 1.0, mu)), np.asarray(W)
        )
        assert float(prox.L1Regularizer().evaluate(W)) == pytest.approx(5.6)
