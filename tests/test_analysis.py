"""skylark-lint: fixture-corpus rule tests, baseline/suppression
mechanics, the runtime lock-order witness, and the static/runtime
lock-graph agreement (docs/analysis.rst).

The fixture corpus lives in ``tests/lint_fixtures/``: ``*_flag.py``
files must produce their rule's finding, ``*_pass.py`` files must
produce none. ``lock_inversion_flag.py`` doubles as the runtime
witness's deliberate two-lock inversion — the same file both halves of
the lock-discipline story must catch.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from libskylark_tpu.analysis import (
    Finding, Project, compare_to_baseline, registered_rules, run_rules,
)
from libskylark_tpu.analysis.rules.lock_discipline import (
    static_lock_graph, _find_cycles,
)
from libskylark_tpu.base import locks as _locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def fixture_project(*names, root=FIXTURES):
    proj = Project(root)
    for n in names:
        proj.add_file(os.path.join(root, n))
    return proj


def findings_for(*names, rule, root=FIXTURES):
    proj = fixture_project(*names, root=root)
    return [f for f in run_rules(proj, only=[rule]) if f.rule == rule]


# ---------------------------------------------------------------------------
# rule family: env-registry
# ---------------------------------------------------------------------------


def test_env_rule_flags_raw_reads():
    got = findings_for("env_raw_read_flag.py", rule="env-registry")
    symbols = {f.symbol for f in got}
    assert "SKYLARK_BOGUS_FLAG" in symbols          # .get()
    assert "SKYLARK_BOGUS_SUBSCRIPT" in symbols     # [...]
    assert "SKYLARK_BOGUS_GETENV" in symbols        # os.getenv
    assert "SKYLARK_BOGUS_MEMBER" in symbols        # in os.environ
    assert "<dynamic>" in symbols                   # non-literal key


def test_env_rule_passes_registry_and_writes():
    assert findings_for("env_ok_pass.py", rule="env-registry") == []


def test_env_rule_suppressions():
    # both suppression forms (same-line, comment-line-above) hold
    assert findings_for("suppressed_pass.py", rule="env-registry") == []


def test_repo_has_no_raw_skylark_reads():
    """The acceptance invariant: a raw os.environ SKYLARK_* read
    anywhere in the package is a finding (everything live today is
    migrated; nothing outside the baseline)."""
    proj = Project.load(REPO)
    raw = [f for f in run_rules(proj, only=["env-registry"])
           if f.symbol.startswith("SKYLARK_")
           and "raw" in f.message]
    assert raw == [], [f.render() for f in raw]


def test_injected_raw_read_fails_gate(tmp_path):
    """A new raw read added to the package is caught as a NEW finding
    vs the committed baseline — what the CI lint gate enforces."""
    proj = Project.load(REPO)
    bad = tmp_path / "bad_module.py"
    bad.write_text(
        "import os\n\n\n"
        "def leak():\n"
        "    return os.environ.get('SKYLARK_TELEMETRY')\n")
    # place it logically inside the package tree
    mod = proj.add_file(str(bad))
    mod.relpath = "libskylark_tpu/bad_module.py"
    findings = run_rules(proj, only=["env-registry"])
    new, _stale = compare_to_baseline(findings)
    assert any(f.symbol == "SKYLARK_TELEMETRY" for f in new)


# ---------------------------------------------------------------------------
# rule family: jit-purity
# ---------------------------------------------------------------------------


def test_jit_rule_flags_impure_closure():
    got = findings_for("jit_impure_flag.py", rule="jit-purity")
    by_root = {}
    for f in got:
        kind = f.message.split("reaches ")[1].split(" impurity")[0]
        by_root.setdefault(f.symbol.split(":")[1], set()).add(kind)
    assert by_root.get("impure_root") == {
        "env", "clock", "host-rng", "mutable-global"}
    # the nested closure passed to jax.jit(...) is a root too, and
    # reaches the env helper transitively
    assert "env" in by_root.get("build.<locals>.inner", set())


def test_jit_rule_passes_pure():
    assert findings_for("jit_pure_pass.py", rule="jit-purity") == []


# ---------------------------------------------------------------------------
# rule family: lock-discipline (static)
# ---------------------------------------------------------------------------


def test_lock_rule_flags_inversion():
    got = findings_for("lock_inversion_flag.py", rule="lock-discipline")
    cycles = [f for f in got if f.symbol.startswith("cycle:")]
    assert cycles, [f.render() for f in got]
    assert any("fixture.alpha" in f.symbol and "fixture.beta" in f.symbol
               for f in cycles)


def test_lock_rule_flags_blocking_and_bare_locks():
    got = findings_for("lock_blocking_flag.py", rule="lock-discipline")
    msgs = "\n".join(f.message for f in got)
    assert "Future.result()" in msgs
    assert "time.sleep()" in msgs
    assert "callback fan-out" in msgs
    assert "direct threading.Lock()" in msgs


def test_lock_rule_passes_consistent_order():
    assert findings_for("lock_ok_pass.py", rule="lock-discipline") == []


def test_repo_static_lock_graph_acyclic():
    """Half of the agreement check: the package's static lock graph
    has no cycle (the runtime witness asserts the other half in
    test_witness_serve_leg_clean and the CI chaos battery)."""
    g = static_lock_graph(Project.load(REPO))
    assert _find_cycles({a: list(b) for a, b in g["edges"].items()}) == []
    # sanity: the graph actually sees the serving surface
    assert "serve.state" in g["sites"]


# ---------------------------------------------------------------------------
# rule family: metric-names
# ---------------------------------------------------------------------------


def _metrics_findings():
    root = os.path.join(FIXTURES, "metrics_proj")
    proj = Project(root)
    for rel in ("libskylark_tpu/telemetry/names.py", "app_ok.py",
                "app_flag.py"):
        proj.add_file(os.path.join(root, rel))
    return run_rules(proj, only=["metric-names"])


def test_metric_rule_flags():
    got = _metrics_findings()
    by_symbol = {}
    for f in got:
        by_symbol.setdefault(f.symbol, []).append(f.message)
    assert "demo.bogus" in by_symbol                       # undeclared
    assert any("declared as counter" in m
               for m in by_symbol.get("demo.requests", []))  # kind clash
    assert any("2 sites" in m
               for m in by_symbol.get("demo.requests", []))  # duplicate
    assert "Demo-Bad.Name" in by_symbol                    # prom chars
    assert "<dynamic>" in by_symbol                        # non-literal
    assert any("stale" in m
               for m in by_symbol.get("demo.never_created", []))


def test_metric_rule_passes_clean_creations():
    got = _metrics_findings()
    # the two clean creations in app_ok.py produce nothing anchored on
    # themselves (the demo.requests duplicate is charged to the second
    # site, which is a deliberate flag-file collision)
    assert not any(f.symbol == "demo.depth" for f in got)


def test_repo_metric_names_clean():
    proj = Project.load(REPO)
    assert run_rules(proj, only=["metric-names"]) == []


# ---------------------------------------------------------------------------
# framework: baseline + gate + CLI
# ---------------------------------------------------------------------------


def test_all_rule_families_registered():
    assert set(registered_rules()) >= {
        "jit-purity", "lock-discipline", "env-registry", "metric-names"}


def test_repo_gate_is_clean_via_cli():
    """script/lint (gate mode) exits 0 on the committed tree +
    baseline — what script/ci runs on every commit."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "script", "lint")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr


def test_stale_baseline_entry_fails_gate():
    proj = Project.load(REPO)
    findings = run_rules(proj)
    fake = Finding("env-registry", "libskylark_tpu/gone.py", 1,
                   "SKYLARK_GONE", "was fixed; entry not removed")
    import libskylark_tpu.analysis.core as core
    base = core.baseline_load()
    base.append({"rule": fake.rule, "path": fake.path,
                 "symbol": fake.symbol, "message": fake.message})
    import json as _json
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fh:
        _json.dump({"findings": base}, fh)
        tmp = fh.name
    try:
        new, stale = compare_to_baseline(findings, path=tmp)
        assert new == []
        assert len(stale) == 1 and stale[0]["symbol"] == "SKYLARK_GONE"
    finally:
        os.unlink(tmp)


def test_env_table_matches_committed(tmp_path):
    """docs/env_vars.rst is generated from the registry; drift fails
    (the CI lint gate re-emits and diffs)."""
    committed = os.path.join(REPO, "docs", "env_vars.rst")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "script", "lint"),
         "--env-table", str(tmp_path / "env_vars.rst")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    with open(committed) as fh:
        want = fh.read()
    with open(tmp_path / "env_vars.rst") as fh:
        got = fh.read()
    assert got == want, "docs/env_vars.rst drifted — regenerate with " \
                        "script/lint --env-table"


# ---------------------------------------------------------------------------
# runtime lock-order witness
# ---------------------------------------------------------------------------


@pytest.fixture()
def witness():
    _locks.enable_witness(True)
    _locks.reset_witness()
    yield
    _locks.enable_witness(False)
    _locks.reset_witness()


def _load_fixture_module(name):
    path = os.path.join(FIXTURES, f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"lintfix_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_make_lock_plain_when_disabled():
    _locks.enable_witness(False)
    import threading
    lk = _locks.make_lock("test.plain")
    assert isinstance(lk, type(threading.Lock()))


def test_witness_detects_deliberate_inversion(witness):
    """The satellite contract: the deliberate two-lock inversion in
    the test-only module is detected at runtime — by the SAME file the
    static rule must flag (test_lock_rule_flags_inversion)."""
    mod = _load_fixture_module("lock_inversion_flag")
    assert mod.run_inversion() == 3
    rep = _locks.witness_report()
    assert rep["violations"], rep
    edge = rep["violations"][0]["edge"]
    assert set(edge) == {"fixture.alpha", "fixture.beta"}
    with pytest.raises(_locks.LockOrderError):
        _locks.check_witness()


def test_witness_clean_on_consistent_order(witness):
    a = _locks.make_lock("w.a")
    b = _locks.make_lock("w.b")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = _locks.witness_report()
    assert rep["violations"] == []
    assert rep["edges"] == {"w.a": ["w.b"]}
    _locks.check_witness()  # no raise


def test_witness_condition_wait_tracks(witness):
    import threading
    lk = _locks.make_lock("w.cv_lock")
    cv = threading.Condition(lk)
    with cv:
        cv.wait(timeout=0.01)   # releases + reacquires through the
        #                         wrapper without corrupting the stack
    rep = _locks.witness_report()
    assert rep["violations"] == []
    _locks.check_witness()


def test_witness_serve_leg_clean(witness):
    """One full mini chaos leg under instrumented locks (the runtime
    half of the static/runtime agreement): a serve storm with an
    injected poison fault, forced flushes, and a drain — every lock
    the executor takes is witnessed, and no acquisition closes a
    cycle."""
    import numpy as np

    from libskylark_tpu import Context, engine
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.resilience import faults

    rng = np.random.default_rng(0)
    ctx = Context(seed=0)
    T = sk.CWT(24, 8, ctx)
    ops = [rng.standard_normal((24, 3)).astype(np.float32)
           for _ in range(8)]
    plan = faults.FaultPlan({
        "seed": 3,
        "faults": [{"site": "serve.flush", "error": "SketchError",
                    "tag": "poison"}]})
    with faults.fault_plan(plan):
        ex = engine.MicrobatchExecutor(max_batch=4,
                                       linger_us=10_000_000)
        futs = []
        for i, A in enumerate(ops):
            if i == 2:
                with faults.tag("poison"):
                    futs.append(ex.submit_sketch(T, A))
            else:
                futs.append(ex.submit_sketch(T, A))
            if (i + 1) % 4 == 0:
                ex.flush()
        ex.flush()
        assert ex.drain(timeout=60.0)
        done = [f for f in futs if f.done()]
        assert len(done) == len(futs)       # zero orphans under chaos
    rep = _locks.witness_report()
    assert rep["acquisitions"] > 0          # the leg was instrumented
    assert rep["violations"] == [], rep["violations"]
    _locks.check_witness()
    # agreement: every witnessed edge between named sites is between
    # sites the static graph also knows (the static graph may know
    # MORE — it sees paths the storm didn't take)
    static = static_lock_graph(Project.load(REPO))
    static_sites = set(static["sites"]) | {
        "telemetry.metric", "telemetry.registry", "engine.cache",
        "engine.fn_stats", "serve.state", "serve.stats", "serve.pub",
        "serve.compiled", "resilience.health", "resilience.fault_plan",
        "resilience.fault_stack", "resilience.preemption",
        "tune.plan_cache", "tune.global_cache", "telemetry.sink"}
    for a, bs in rep["edges"].items():
        assert a in static_sites, a
        for b in bs:
            assert b in static_sites, b


def test_witness_report_shape():
    rep = _locks.witness_report()
    assert set(rep) == {"acquisitions", "edges", "violations"}
    json.dumps(rep)   # JSON-able (the chaos battery embeds it)
