"""Persistent AOT executable artifacts, warmup packs, and cross-process
single-flight (libskylark_tpu/engine/aot.py + engine/warmup.py).

Oracles:

- *load-instead-of-compile*: a key compiled once under
  ``SKYLARK_AOT_DIR`` resolves in a later "process" (simulated by
  ``engine.reset()`` in-process, and by real subprocesses in the race
  test) as an ``aot_load`` with ZERO backend compiles, bit-equal.
- *fail-open*: a corrupted / compat-mismatched / foreign artifact is
  counted (``aot_load_failures``), warned once, and falls back to a
  fresh compile — never an exception on the serve path.
- *cross-process single-flight*: N racing cold processes on one key
  perform exactly ONE backend compile fleet-wide (file lock, with
  stale-lock takeover when the holder died).
- *warmup packs*: a pack built in one engine era boots a fresh era
  serving every packed bucket with zero compiles, zero misses, results
  bit-equal to the builder's; plan-fingerprint drift and compat
  mismatches skip the pack instead of mis-serving it.
"""

from __future__ import annotations

import json
import os
import re
import struct
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from libskylark_tpu import engine
from libskylark_tpu.engine import aot
from libskylark_tpu.engine import serve as serve_mod
from libskylark_tpu.engine import warmup


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


@pytest.fixture()
def aot_store(tmp_path, monkeypatch):
    d = str(tmp_path / "store")
    monkeypatch.setenv("SKYLARK_AOT_DIR", d)
    return d


def _double(x):
    return x * 2.0 + 1.0


def _wrapped(tag: str):
    return engine.compiled(_double, name=f"aot.test.{tag}",
                           key_fn=lambda *a: (tag,))


def _artifacts(store):
    if not os.path.isdir(store):
        return []
    return sorted(f for f in os.listdir(store) if f.endswith(".skyaot"))


class TestArtifactStore:
    def test_load_instead_of_compile_bit_equal(self, fresh_engine,
                                               aot_store):
        cf = _wrapped("roundtrip")
        x = jnp.arange(12, dtype=jnp.float32)
        r1 = np.asarray(cf(x))
        s = engine.stats()
        assert (s.misses, s.compiles, s.aot_loads) == (1, 1, 0)
        assert len(_artifacts(aot_store)) == 1
        engine.reset()                      # "a fresh process"
        r2 = np.asarray(cf(x))
        s = engine.stats()
        assert (s.misses, s.compiles, s.aot_loads) == (1, 0, 1)
        assert s.load_seconds > 0.0 and s.compile_seconds == 0.0
        assert np.array_equal(r1, r2)

    def test_disabled_without_env(self, fresh_engine, tmp_path,
                                  monkeypatch):
        monkeypatch.delenv("SKYLARK_AOT_DIR", raising=False)
        monkeypatch.delenv("SKYLARK_EXEC_CACHE_DIR", raising=False)
        assert not aot.enabled()
        _wrapped("disabled")(jnp.ones(4))
        assert engine.stats().compiles == 1

    def test_off_value_disables_even_with_alias(self, monkeypatch,
                                                tmp_path):
        monkeypatch.setenv("SKYLARK_AOT_DIR", "0")
        monkeypatch.setenv("SKYLARK_EXEC_CACHE_DIR", str(tmp_path))
        assert aot.aot_dir() is None

    def test_legacy_alias_warns_once_and_subdirs(self, monkeypatch,
                                                 tmp_path):
        monkeypatch.delenv("SKYLARK_AOT_DIR", raising=False)
        monkeypatch.setenv("SKYLARK_EXEC_CACHE_DIR", str(tmp_path))
        monkeypatch.setattr(aot, "_alias_warned", False)
        with pytest.warns(DeprecationWarning, match="SKYLARK_AOT_DIR"):
            assert aot.aot_dir() == os.path.join(str(tmp_path), "aot")
        # second resolution is silent (one deprecation note per process)
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")
            assert aot.aot_dir() == os.path.join(str(tmp_path), "aot")

    def test_corrupted_artifact_falls_back_and_quarantines(
            self, fresh_engine, aot_store):
        cf = _wrapped("corrupt")
        x = jnp.ones(8, dtype=jnp.float32)
        r1 = np.asarray(cf(x))
        (name,) = _artifacts(aot_store)
        with open(os.path.join(aot_store, name), "wb") as fh:
            fh.write(b"not an artifact")
        engine.reset()
        with pytest.warns(RuntimeWarning, match="unusable"):
            r2 = np.asarray(cf(x))
        s = engine.stats()
        assert s.compiles == 1 and s.aot_loads == 0
        assert s.aot_load_failures == 1
        assert np.array_equal(r1, r2)
        # the broken file was quarantined and the fresh compile
        # re-persisted a good artifact under the canonical name
        assert _artifacts(aot_store) == [name]
        assert os.path.exists(os.path.join(aot_store, name + ".bad"))

    def test_compat_mismatch_falls_back_keeps_artifact(
            self, fresh_engine, aot_store):
        cf = _wrapped("compat")
        x = jnp.ones(6, dtype=jnp.float32)
        r1 = np.asarray(cf(x))
        (name,) = _artifacts(aot_store)
        path = os.path.join(aot_store, name)
        # rewrite the header with a foreign jax version, keeping the
        # pickle payload byte-identical
        with open(path, "rb") as fh:
            raw = fh.read()
        hlen = struct.unpack(">Q", raw[8:16])[0]
        header = json.loads(raw[16:16 + hlen])
        header["compat"]["jax"] = "0.0.0"
        hdr = json.dumps(header, sort_keys=True).encode()
        with open(path, "wb") as fh:
            fh.write(raw[:8] + struct.pack(">Q", len(hdr)) + hdr
                     + raw[16 + hlen:])
        engine.reset()
        r2 = np.asarray(cf(x))
        s = engine.stats()
        assert s.compiles == 1 and s.aot_load_failures == 1
        assert np.array_equal(r1, r2)
        # compat-mismatched artifacts are NOT quarantined: they are
        # valid for the runtime that wrote them... until the fresh
        # compile re-persists over the same digest (same runtime key)
        assert not os.path.exists(path + ".bad")

    def test_key_digest_and_compat_probe(self):
        k1 = ("a", ("b", 1), (2, "c"))
        assert aot.key_digest(k1) == aot.key_digest(("a", ("b", 1),
                                                     (2, "c")))
        assert aot.key_digest(k1) != aot.key_digest(("a", ("b", 2),
                                                     (2, "c")))
        ok, why = aot.compat_probe(aot.compat_stamp())
        assert ok and why is None
        bad = dict(aot.compat_stamp(), backend="tpu-imaginary")
        ok, why = aot.compat_probe(bad)
        assert not ok and "backend" in why
        assert aot.compat_probe(None) == (False, "no-compat-stamp")

    def test_persistent_cache_failure_observable(self, monkeypatch):
        import jax as _jax

        # the package re-exports the same-named decorator, shadowing
        # the submodule attribute even for `import a.b.c as x`
        _c = sys.modules["libskylark_tpu.engine.compiled"]
        from libskylark_tpu import telemetry

        calls = telemetry.counter("engine.persistent_cache_failures")
        before = calls.value(reason="RuntimeError")

        def boom(*a, **kw):
            raise RuntimeError("no config for you")

        monkeypatch.setattr(_jax.config, "update", boom)
        with pytest.warns(RuntimeWarning, match="persistent compilation"):
            assert _c.enable_persistent_cache("/tmp/nowhere") is False
        assert calls.value(reason="RuntimeError") == before + 1


class TestFileLock:
    def test_exclusive_then_release(self, tmp_path):
        path = str(tmp_path / "k.lock")
        a = aot.FileLock(path)
        b = aot.FileLock(path, poll=0.01)
        assert a.acquire(timeout=1.0)
        assert not b.acquire(timeout=0.2)
        a.release()
        assert b.acquire(timeout=1.0)
        b.release()
        assert not os.path.exists(path)

    def test_dead_holder_takeover(self, tmp_path):
        path = str(tmp_path / "k.lock")
        import socket

        # a pid that is certainly not alive: a just-reaped child's
        child = subprocess.Popen(["sleep", "0"])  # noqa: S603,S607
        child.wait()
        with open(path, "w") as fh:
            json.dump({"pid": child.pid, "host": socket.gethostname(),
                       "t": time.time()}, fh)
        lk = aot.FileLock(path, stale_seconds=600.0, poll=0.01)
        t0 = time.monotonic()
        assert lk.acquire(timeout=5.0)
        assert time.monotonic() - t0 < 2.0   # takeover, not timeout
        lk.release()

    def test_age_takeover(self, tmp_path):
        path = str(tmp_path / "k.lock")
        import socket

        with open(path, "w") as fh:
            json.dump({"pid": os.getpid(),       # alive holder...
                       "host": socket.gethostname(),
                       "t": time.time()}, fh)
        old = time.time() - 60.0
        os.utime(path, (old, old))               # ...but long past stale
        lk = aot.FileLock(path, stale_seconds=5.0, poll=0.01)
        assert lk.acquire(timeout=5.0)
        lk.release()

    def test_thread_mutual_exclusion(self, tmp_path):
        path = str(tmp_path / "k.lock")
        inside = []
        overlaps = []

        def worker():
            lk = aot.FileLock(path, poll=0.005)
            for _ in range(5):
                assert lk.acquire(timeout=10.0)
                inside.append(1)
                if len(inside) > 1:
                    overlaps.append(True)
                time.sleep(0.002)
                inside.pop()
                lk.release()

        ts = [threading.Thread(target=worker) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not overlaps


_RACE_CHILD = textwrap.dedent("""
    import json, os, sys, time
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {moddir!r})
    go = sys.argv[1]
    while not os.path.exists(go):
        time.sleep(0.005)
    from libskylark_tpu import engine
    import aot_race_fn, jax.numpy as jnp, numpy as np
    cf = engine.compiled(aot_race_fn.fn, name="aot.race",
                         key_fn=lambda *a: ("race",))
    out = np.asarray(cf(jnp.ones((32, 32), jnp.float32)))
    s = engine.stats()
    print(json.dumps({{"compiles": s.compiles, "aot_loads": s.aot_loads,
                       "failures": s.aot_load_failures,
                       "sum": float(out.sum())}}))
""")


class TestCrossProcessSingleFlight:
    def test_racing_cold_processes_compile_exactly_once(self, tmp_path):
        """The acceptance criterion: N cold replicas racing on one key
        perform exactly one backend compile fleet-wide — the winner
        compiles under the file lock and serializes; the waiters block
        on the lock, then LOAD the winner's artifact."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        (tmp_path / "aot_race_fn.py").write_text(
            "import jax.numpy as jnp\n"
            "def fn(x):\n"
            "    return (x @ x.T).sum(axis=0) * 3.0\n")
        child_py = tmp_path / "child.py"
        child_py.write_text(_RACE_CHILD.format(repo=repo,
                                               moddir=str(tmp_path)))
        store = tmp_path / "store"
        go = tmp_path / "go.flag"
        env = dict(os.environ, SKYLARK_AOT_DIR=str(store),
                   JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen(
            [sys.executable, str(child_py), str(go)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for _ in range(3)]
        time.sleep(0.5)       # let all three reach the barrier
        go.touch()
        outs = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=240)
            assert p.returncode == 0, stderr[-800:]
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
        assert sum(o["compiles"] for o in outs) == 1
        assert sum(o["aot_loads"] for o in outs) == 2
        assert all(o["failures"] == 0 for o in outs)
        assert len({o["sum"] for o in outs}) == 1
        # the lock is gone, the artifact remains
        files = os.listdir(store)
        assert [f for f in files if f.endswith(".skyaot")]
        assert not [f for f in files if f.endswith(".lock")]


def _pack_specs():
    return [
        warmup.BucketSpec(endpoint="sketch_apply", family="JLT",
                          n=120, m=28, s_dim=32, rowwise=True,
                          capacities=(1, 2)),
        warmup.BucketSpec(endpoint="sketch_apply", family="CWT",
                          n=48, m=6, s_dim=16, rowwise=False,
                          capacities=(2,)),
    ]


class TestWarmupPack:
    def test_build_then_boot_zero_compiles_bit_equal(self, fresh_engine,
                                                     tmp_path):
        pack = str(tmp_path / "pack")
        manifest = warmup.build_pack(pack, _pack_specs())
        assert len(manifest["entries"]) == 3
        assert all(e["kernel"] for e in manifest["entries"])
        assert all(e.get("results_digest") for e in manifest["entries"])
        assert not any(e.get("artifact_missing")
                       for e in manifest["entries"])
        # cold control first: same cohorts, no pack -> compiles
        engine.reset()
        cold = warmup.serve_probe(pack, load=False)
        assert cold["engine"]["compiles"] == 3
        assert cold["bit_equal"], cold["mismatches"]
        # the boot under test: fresh era + pack -> zero compiles,
        # zero misses (every first request a HIT), all loads
        engine.reset()
        warm = warmup.serve_probe(pack, load=True)
        assert warm["warmup"]["loaded"] == 3
        assert warm["warmup"]["kernel_restored"] == 3
        assert warm["engine"]["compiles"] == 0
        assert warm["engine"]["misses"] == 0
        assert warm["engine"]["aot_loads"] == 3
        assert warm["bit_equal"], warm["mismatches"]

    def test_plan_fingerprint_drift_skips_pack(self, fresh_engine,
                                               tmp_path):
        pack = str(tmp_path / "pack")
        warmup.build_pack(pack, _pack_specs()[:1])
        manifest = warmup.read_manifest(pack)
        manifest["plan_fingerprint"] = "deadbeefdeadbeef"
        with open(os.path.join(pack, warmup.MANIFEST), "w") as fh:
            json.dump(manifest, fh)
        engine.reset()
        report = warmup.load_pack(pack)
        assert report["loaded"] == 0
        assert report["plan_fingerprint_match"] is False
        assert "drift" in report["skipped"]
        with pytest.raises(RuntimeError, match="drift"):
            warmup.load_pack(pack, strict=True)

    def test_compat_mismatch_skips_pack(self, fresh_engine, tmp_path):
        pack = str(tmp_path / "pack")
        warmup.build_pack(pack, _pack_specs()[:1])
        manifest = warmup.read_manifest(pack)
        manifest["compat"]["device_count"] = 4096
        with open(os.path.join(pack, warmup.MANIFEST), "w") as fh:
            json.dump(manifest, fh)
        engine.reset()
        report = warmup.load_pack(pack)
        assert report["loaded"] == 0
        assert report["skipped"].startswith("compat:")

    def test_missing_pack_degrades(self, tmp_path):
        report = warmup.load_pack(str(tmp_path / "nope"))
        assert report["loaded"] == 0 and report["skipped"]

    def test_kernel_token_parse_and_restore(self, fresh_engine):
        from libskylark_tpu.tune import Plan

        p = serve_mod._parse_plan_token("pallas/mt128/pipe")
        assert p == Plan(backend="pallas", m_tile=128, pipeline=True)
        assert serve_mod._parse_plan_token("mosaic-nonsense") is None
        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500)
        try:
            statics = ("sketch_apply", "CWT", "None", 16, False,
                       "float32", (64, 8))
            assert ex.restore_kernel_choice(statics, 2, "xla")
            fp = engine.plan_fingerprint()
            assert ex._kernel_memo[(statics, 2, fp)] == \
                ("xla", None, "pack", None)
            assert not ex.restore_kernel_choice(statics, 2, "garbage!")
        finally:
            ex.shutdown()

    def test_explicit_kernel_pin_outranks_pack(self, fresh_engine,
                                               monkeypatch):
        """An operator pin (executor ``kernel=`` arg or
        SKYLARK_SERVE_KERNEL) must not be overridden by a pack's
        recorded decision — restore declines, live resolution rules."""
        statics = ("sketch_apply", "CWT", "None", 16, False,
                   "float32", (64, 8))
        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500,
                                       kernel="xla")
        try:
            assert not ex.restore_kernel_choice(statics, 2,
                                                "pallas/mt128")
            assert not ex._kernel_memo
        finally:
            ex.shutdown()
        monkeypatch.setenv("SKYLARK_SERVE_KERNEL", "xla")
        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500)
        try:
            assert not ex.restore_kernel_choice(statics, 2, "xla")
            assert not ex._kernel_memo
        finally:
            ex.shutdown()
        # disabling plan consultation also disables pack restoration —
        # the pack's decisions ARE plan-cache decisions
        monkeypatch.delenv("SKYLARK_SERVE_KERNEL")
        from libskylark_tpu.sketch import params as sketch_params

        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500)
        try:
            sketch_params.set_use_plan_cache(False)
            assert not ex.restore_kernel_choice(statics, 2, "xla")
            assert not ex._kernel_memo
        finally:
            sketch_params.set_use_plan_cache(True)
            ex.shutdown()

    def test_second_load_skips_resident_keys(self, fresh_engine,
                                             tmp_path):
        """A second thread replica booting from the same pack finds
        every key resident: no second deserialize, no aot_loads
        inflation — only its own kernel memo gets seeded."""
        pack = str(tmp_path / "pack")
        warmup.build_pack(pack, _pack_specs()[:1])
        engine.reset()
        r1 = warmup.load_pack(pack)
        assert r1["loaded"] >= 1 and r1["resident"] == 0
        loads_after_first = engine.stats().aot_loads
        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500)
        try:
            r2 = warmup.load_pack(pack, executors=(ex,))
            assert r2["loaded"] == 0
            assert r2["resident"] == r1["loaded"]
            assert r2["failed"] == 0
            assert r2["kernel_restored"] >= 1
            assert engine.stats().aot_loads == loads_after_first
        finally:
            ex.shutdown()

    def test_select_top_buckets_from_plan_cache(self, tmp_path):
        from libskylark_tpu import tune

        cache = tune.PlanCache(path=None)
        w1 = tune.serve_workload("sketch_apply", "JLT", "float32",
                                 (64, 128), 32, 8, rowwise=True)
        w2 = tune.serve_workload("sketch_apply", "CWT", "float32",
                                 (64, 8), 16, 2, rowwise=False)
        cache.put(w1, tune.Plan(backend="xla"), source="measured")
        cache.put(w2, tune.Plan(backend="xla"), source="ranked")
        prev = tune.set_cache(cache)
        try:
            specs = warmup.select_top_buckets(8)
        finally:
            tune.set_cache(prev)
        assert len(specs) == 2
        # measured entries rank ahead of ranked ones
        assert specs[0].family == "JLT" and specs[0].capacities == (8,)
        assert specs[0].rowwise and specs[0].s_dim == 32
        assert specs[1].family == "CWT" and not specs[1].rowwise

    def test_artifact_headers_readable_without_unpickle(
            self, fresh_engine, tmp_path):
        pack = str(tmp_path / "pack")
        warmup.build_pack(pack, _pack_specs()[:1])
        arts = aot.list_artifacts(os.path.join(pack, "artifacts"))
        assert len(arts) == 2
        for h in arts:
            assert h["name"] == "serve.sketch_apply"
            assert h["compat"]["backend"] == "cpu"
            # the pickled key never executed: list_artifacts reads
            # headers only (pickle.loads would need jax state)
            assert "key_repr" in h


class TestEnvPropagation:
    def test_snapshot_and_apply(self, monkeypatch):
        from libskylark_tpu.fleet import replica as replica_mod

        monkeypatch.setenv("SKYLARK_AOT_DIR", "/tmp/a")
        monkeypatch.setenv("SKYLARK_PLAN_CACHE", "/tmp/p.json")
        monkeypatch.delenv("SKYLARK_TELEMETRY_DIR", raising=False)
        snap = replica_mod.propagated_env()
        assert snap["SKYLARK_AOT_DIR"] == "/tmp/a"
        assert snap["SKYLARK_TELEMETRY_DIR"] is None
        # the parent moves on; the child still applies the snapshot
        monkeypatch.setenv("SKYLARK_AOT_DIR", "/tmp/CHANGED")
        monkeypatch.setenv("SKYLARK_TELEMETRY_DIR", "/tmp/t")
        replica_mod._apply_env(snap)
        assert os.environ["SKYLARK_AOT_DIR"] == "/tmp/a"
        assert "SKYLARK_TELEMETRY_DIR" not in os.environ

    def test_apply_none_is_noop(self):
        from libskylark_tpu.fleet import replica as replica_mod

        replica_mod._apply_env(None)


class TestTelemetryRendering:
    def test_aot_counters_prometheus_rendered(self, fresh_engine,
                                              aot_store):
        """Satellite: the ``aot_loads`` / ``aot_load_failures`` /
        ``load_seconds`` split shows up on the unified Prometheus
        surface (engine collector block flattened to gauges)."""
        from libskylark_tpu import telemetry

        @engine.compiled(name="aot.test.prom")
        def f(x):
            return x * 3.0

        x = jnp.arange(6.0, dtype=jnp.float32)
        f(x)                      # compile + persist
        engine.reset()
        f(x)                      # fresh era: artifact load
        s = engine.stats()
        assert s.aot_loads == 1 and s.compiles == 0
        text = telemetry.prometheus_text()
        assert "skylark_engine_stats_aot_loads 1" in text
        assert "skylark_engine_stats_aot_load_failures 0" in text
        assert "skylark_engine_stats_load_seconds" in text
        assert "skylark_engine_stats_compiles 0" in text
        # lifetime rollup carries the pre-reset compile (>= because
        # the rollup is reset-proof across the whole test session)
        m = re.search(r"skylark_engine_lifetime_compiles (\d+)", text)
        assert m and int(m.group(1)) >= 1


@pytest.mark.slow
class TestProcessReplicaPackBoot:
    def test_child_env_explicit_and_zero_compile_boot(
            self, fresh_engine, tmp_path, monkeypatch):
        """Satellite regression: a spawn child applies the parent's
        EXPLICIT engine-environment snapshot (not whatever os.environ
        held at Process.start), loads the warmup pack before accepting
        traffic, and serves the packed bucket bit-equal with ZERO
        backend compiles — the acceptance criterion's ProcessReplica
        leg."""
        from libskylark_tpu import fleet
        from libskylark_tpu import sketch as sk

        spec = warmup.BucketSpec(endpoint="sketch_apply", family="CWT",
                                 n=48, m=6, s_dim=16, rowwise=False,
                                 capacities=(1,))
        pack = str(tmp_path / "pack")
        manifest = warmup.build_pack(pack, [spec])
        assert manifest["entries"]

        store_a = str(tmp_path / "store_a")
        monkeypatch.setenv("SKYLARK_AOT_DIR", store_a)
        env = fleet.propagated_env()
        assert env["SKYLARK_AOT_DIR"] == store_a
        # poison os.environ AFTER the snapshot: without explicit
        # propagation the child would inherit this by spawn accident
        monkeypatch.setenv("SKYLARK_AOT_DIR", str(tmp_path / "WRONG"))

        r = fleet.ProcessReplica(
            "packed", warmup_pack=pack, env=env,
            max_batch=int(manifest["max_batch"]), linger_us=1000)
        try:
            info = r.boot_info()
            assert info["env"]["SKYLARK_AOT_DIR"] == store_a
            wrep = info["warmup"]
            assert wrep["skipped"] is None and wrep["failed"] == 0
            assert wrep["loaded"] == len(manifest["entries"])
            eng0 = info["engine"]
            assert eng0["compiles"] == 0
            assert eng0["aot_loads"] == len(manifest["entries"])

            # the canonical cohort, through the pipe: bit-equal to the
            # parent's sequential reference, still zero compiles
            (T, A) = warmup._spec_requests(spec, 1)[0]
            ref = np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            fut = r.submit("sketch_apply", transform=T, A=A,
                           dimension=sk.COLUMNWISE)
            r.flush()
            got = np.asarray(fut.result(timeout=120))
            assert np.array_equal(got, ref)
            eng1 = r.boot_info()["engine"]
            assert eng1["compiles"] == 0 and eng1["misses"] == 0
            assert eng1["hits"] >= 1
        finally:
            r.shutdown()
