"""Automatic materialize-and-reuse dispatch (OperatorCache +
sketch/params auto_materialize knobs).

The virtual-operator default pays generation per apply — right for
one-shot sketches; steady-state reuse (serving predict paths, eager
solver loops) should amortize it to zero WITHOUT a manual
``materialize()`` call. The dispatch must never fire under a jit trace
(it would pin a tracer), never exceed its byte budget, and — on the XLA
path — change nothing numerically (the materialized apply is the same
contraction as the unblocked virtual one)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu.base.context import Context
from libskylark_tpu.sketch import JLT, ROWWISE
from libskylark_tpu.sketch import params as sketch_params
from libskylark_tpu.sketch.qrft import GaussianQRFT
from libskylark_tpu.sketch.rft import GaussianRFT


@pytest.fixture(autouse=True)
def _restore_params():
    prev = (sketch_params.get_auto_materialize(),
            sketch_params.get_auto_materialize_after(),
            sketch_params.get_auto_materialize_bytes())
    yield
    sketch_params.set_auto_materialize(prev[0])
    sketch_params.set_auto_materialize_after(prev[1])
    sketch_params.set_auto_materialize_bytes(prev[2])


@pytest.fixture
def A():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.standard_normal((32, 256)), jnp.float32)


def test_nth_eager_apply_pins_and_preserves_results(A):
    sketch_params.set_auto_materialize_after(3)
    T = JLT(256, 16, Context(seed=1))
    fresh = np.asarray(JLT(256, 16, Context(seed=1)).apply(A, ROWWISE))
    outs = [np.asarray(T.apply(A, ROWWISE)) for _ in range(4)]
    assert T._op_cache is not None          # pinned on the 3rd apply
    for o in outs:
        # XLA path: materialized apply is the SAME contraction — exact
        np.testing.assert_array_equal(o, fresh)


def test_jit_traced_applies_never_count(A):
    sketch_params.set_auto_materialize_after(1)
    T = JLT(256, 16, Context(seed=1))
    f = jax.jit(lambda X: T.apply(X, ROWWISE))
    for _ in range(4):
        f(A).block_until_ready()
    assert T._op_cache is None


def test_budget_respected(A):
    sketch_params.set_auto_materialize_after(1)
    sketch_params.set_auto_materialize_bytes(16 * 256 * 4 - 1)  # 1 short
    T = JLT(256, 16, Context(seed=1))
    T.apply(A, ROWWISE)
    T.apply(A, ROWWISE)
    assert T._op_cache is None


def test_disable_flag(A):
    sketch_params.set_auto_materialize(False)
    sketch_params.set_auto_materialize_after(1)
    T = JLT(256, 16, Context(seed=1))
    for _ in range(3):
        T.apply(A, ROWWISE)
    assert T._op_cache is None


def test_dematerialize_resets_dispatch(A):
    sketch_params.set_auto_materialize_after(2)
    T = JLT(256, 16, Context(seed=1))
    T.apply(A, ROWWISE)
    T.apply(A, ROWWISE)
    assert T._op_cache is not None
    T.dematerialize()
    assert T._op_cache is None
    T.apply(A, ROWWISE)                      # count restarted: 1 < 2
    assert T._op_cache is None


@pytest.mark.parametrize("make", [
    lambda: GaussianRFT(256, 24, Context(seed=2), sigma=2.0),
    lambda: GaussianQRFT(256, 24, Context(seed=2), sigma=2.0),
])
def test_feature_maps_auto_pin_within_oracle(A, make):
    sketch_params.set_auto_materialize_after(2)
    T = make()
    fresh = np.asarray(make().apply(A, ROWWISE))
    for _ in range(3):
        out = np.asarray(T.apply(A, ROWWISE))
    assert T._op_cache is not None
    np.testing.assert_allclose(out, fresh, atol=1e-4, rtol=1e-4)


def test_no_auto_pin_on_fused_kernel_path(A, monkeypatch):
    """When the eager apply routes through the fused Pallas kernel,
    auto-materialize must NOT fire: pinning would silently switch the
    Nth apply from bf16x3 kernel numerics to a full-precision cached
    gemm — a cross-call reproducibility break (r3 advisor, medium).
    Simulated off-chip by forcing the veto predicate on (the real
    kernel cannot compile on CPU); what's under test is the dispatch
    wiring: would-serve -> never auto-pin."""
    from libskylark_tpu.sketch import dense as dense_mod

    monkeypatch.setattr(dense_mod, "pallas_serves_eager",
                        lambda *a: True)
    sketch_params.set_auto_materialize_after(1)
    T = JLT(256, 16, Context(seed=1))
    for _ in range(3):
        T.apply(A, ROWWISE)
    assert T._op_cache is None  # veto: no silent regime switch
    # explicit materialize() remains the visible opt-in
    T.materialize()
    assert T._op_cache is not None

    # RFT shares the veto through the same dispatch
    R = GaussianRFT(256, 24, Context(seed=2), sigma=2.0)
    for _ in range(3):
        R.apply(A, ROWWISE)
    assert R._op_cache is None


def test_unsupported_kernel_inputs_still_auto_pin(A, monkeypatch):
    """pallas_serves_eager mirrors the kernel's own qualification: an
    apply the kernel would DECLINE (f64 input — supported() is
    f32-only) runs the plain XLA contraction, so auto-materialize must
    keep amortizing it even in a pallas-ambient context (review
    finding: the veto must not permanently disable amortization for
    XLA-path applies on TPU)."""
    from libskylark_tpu.sketch import dense as dense_mod
    from libskylark_tpu.sketch import pallas_dense

    monkeypatch.setattr(pallas_dense, "available", lambda: True)
    monkeypatch.setattr(dense_mod, "pallas_ambient_ok", lambda A: True)
    sketch_params.set_auto_materialize_after(2)
    T = JLT(256, 16, Context(seed=1))
    Ab = A.astype(jnp.bfloat16)  # supported() is f32-only -> XLA path
    assert not dense_mod.pallas_serves_eager(Ab, T.dist, 16, 1)
    T.apply(Ab, ROWWISE)
    T.apply(Ab, ROWWISE)
    assert T._op_cache is not None  # amortization kept

    # VMEM/tile decline (review finding): an f32 apply whose s_dim
    # exceeds every valid tile's VMEM budget falls back to XLA too —
    # the veto must mirror that via effective_plan, not just supported()
    assert not dense_mod.pallas_serves_eager(A, T.dist, 1 << 16, 1)
    # while a plannable config (small s_dim) IS vetoed
    assert dense_mod.pallas_serves_eager(A, T.dist, 16, 1)


def test_wider_dtype_request_repins(A):
    """A narrow pin must not permanently block amortization for wider
    dtypes: _cached_op refuses to upcast, so wide applies keep counting
    and re-pin at the wider dtype."""
    sketch_params.set_auto_materialize_after(2)
    T = JLT(256, 16, Context(seed=1))
    Ab = A.astype(jnp.bfloat16)
    T.apply(Ab, ROWWISE)
    T.apply(Ab, ROWWISE)
    assert T._op_cache is not None and T._op_cache.dtype == jnp.bfloat16
    T.apply(A, ROWWISE)                      # f32: wider, counts anew
    assert T._op_cache.dtype == jnp.float32  # re-pinned wider


def test_expsemigroup_qrlt_auto_pins(A):
    from libskylark_tpu.sketch.qrft import ExpSemigroupQRLT

    sketch_params.set_auto_materialize_after(2)
    Apos = jnp.abs(A)  # semigroup kernels take nonnegative inputs
    T = ExpSemigroupQRLT(256, 24, Context(seed=2), beta=0.5)
    fresh = np.asarray(
        ExpSemigroupQRLT(256, 24, Context(seed=2), beta=0.5).apply(
            Apos, ROWWISE))
    for _ in range(3):
        out = np.asarray(T.apply(Apos, ROWWISE))
    assert T._op_cache is not None
    np.testing.assert_allclose(out, fresh, atol=1e-4, rtol=1e-4)
