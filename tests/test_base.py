"""Tests for base layer: context, counter-based streams, quasirand.

The stream-determinism tests are the TPU analog of the reference's core
oracle: values are a pure function of (seed, counter/index), independent of
how/where slices are materialized (ref: base/randgen.hpp:98-115,
tests/unit/DenseSketchApplyElementalTest.cpp:44-101).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu.base import Context, randgen
from libskylark_tpu.base.context import Allocation
from libskylark_tpu.base.quasirand import LeapedHaltonSequence, QMCSequence, radical_inverse


class TestContext:
    def test_allocation_advances_counter(self):
        ctx = Context(seed=42)
        a0 = ctx.allocate()
        a1 = ctx.allocate()
        assert (a0.seed, a0.counter) == (42, 0)
        assert (a1.seed, a1.counter) == (42, 1)
        assert ctx.counter == 2

    def test_json_roundtrip(self):
        ctx = Context(seed=7, counter=13)
        ctx2 = Context.from_json(ctx.to_json())
        assert (ctx2.seed, ctx2.counter) == (7, 13)
        d = ctx.to_dict()
        assert d["skylark_object_type"] == "context"

    def test_allocation_reconstructible(self):
        ctx = Context(seed=5)
        a = ctx.allocate()
        b = Allocation.from_dict(json.loads(json.dumps(a.to_dict())))
        assert jnp.array_equal(
            jax.random.key_data(a.key), jax.random.key_data(b.key)
        )

    def test_different_seeds_different_keys(self):
        k1 = Context(seed=1).allocate().key
        k2 = Context(seed=2).allocate().key
        assert not jnp.array_equal(
            jax.random.key_data(k1), jax.random.key_data(k2)
        )


class TestStream:
    def setup_method(self):
        self.key = Context(seed=123).allocate().key

    def test_slice_consistency(self):
        """Any sub-slice equals the corresponding piece of a larger slice —
        the layout-independence property everything depends on."""
        dist = randgen.Normal()
        full = randgen.stream_slice(self.key, dist, 0, 10000)
        for lo, hi in [(0, 100), (37, 4096), (4000, 4200), (8191, 10000)]:
            part = randgen.stream_slice(self.key, dist, lo, hi)
            np.testing.assert_array_equal(np.asarray(full[lo:hi]), np.asarray(part))

    def test_chunks_match_slice(self):
        dist = randgen.Uniform(0.0, 1.0)
        via_chunks = randgen.stream_chunks(self.key, dist, 2, 3)
        via_slice = randgen.stream_slice(
            self.key, dist, 2 * randgen.CHUNK, 5 * randgen.CHUNK
        )
        np.testing.assert_array_equal(np.asarray(via_chunks), np.asarray(via_slice))

    def test_traced_chunk_ids(self):
        """Chunk generation works with traced ids (needed inside lax loops)."""
        dist = randgen.Normal()

        @jax.jit
        def gen(cid):
            return randgen.stream_chunks(self.key, dist, cid, 1)

        np.testing.assert_array_equal(
            np.asarray(gen(jnp.int32(3))),
            np.asarray(randgen.stream_chunks(self.key, dist, 3, 1)),
        )

    def test_dense_panel_consistency(self):
        dist = randgen.Normal()
        rows, bc = 16, 8
        full = randgen.dense_panel(self.key, dist, rows, 0, 64, bc)
        assert full.shape == (rows, 64)
        for lo, hi in [(0, 8), (3, 19), (40, 64)]:
            part = randgen.dense_panel(self.key, dist, rows, lo, hi, bc)
            np.testing.assert_array_equal(np.asarray(full[:, lo:hi]), np.asarray(part))

    @pytest.mark.slow
    def test_distribution_statistics(self):
        n = 1 << 16
        normal = np.asarray(randgen.stream_slice(self.key, randgen.Normal(), 0, n))
        assert abs(normal.mean()) < 0.02 and abs(normal.std() - 1.0) < 0.02
        rad = np.asarray(randgen.stream_slice(self.key, randgen.Rademacher(), 0, n))
        assert set(np.unique(rad)) == {-1.0, 1.0}
        assert abs(rad.mean()) < 0.02
        ui = np.asarray(
            randgen.stream_slice(
                self.key, randgen.UniformInt(0, 9), 0, n, dtype=jnp.int32
            )
        )
        assert ui.min() == 0 and ui.max() == 9
        levy = np.asarray(randgen.stream_slice(self.key, randgen.StandardLevy(), 0, n))
        assert (levy > 0).all()
        # Standard Levy median is 1/(2*erfinv(1/2)^2) ~ 2.198
        assert 1.8 < np.median(levy) < 2.6

    def test_distribution_serialization(self):
        for dist in [
            randgen.Normal(1.0, 2.0),
            randgen.Cauchy(0.0, 3.0),
            randgen.UniformInt(0, 5),
            randgen.Rademacher(),
            randgen.StandardLevy(),
        ]:
            d2 = randgen.Distribution.from_dict(json.loads(json.dumps(dist.to_dict())))
            assert d2 == dist


class TestQuasirand:
    def test_radical_inverse_base2(self):
        # van der Corput base 2 of idx+1: 1->0.5, 2->0.25, 3->0.75, 4->0.125
        got = radical_inverse(np.int64(2), np.arange(4))
        np.testing.assert_allclose(got, [0.5, 0.25, 0.75, 0.125])

    def test_panel_matches_coordinate(self):
        seq = LeapedHaltonSequence(d=5)
        panel = seq.panel(10, 20, 5)
        for r, idx in enumerate(range(10, 20)):
            for i in range(5):
                assert panel[r, i] == pytest.approx(seq.coordinate(idx, i), abs=1e-12)

    def test_low_discrepancy(self):
        seq = LeapedHaltonSequence(d=2)
        panel = seq.panel(0, 512, 2)
        assert ((panel >= 0) & (panel < 1)).all()
        # QMC means converge to 0.5 much faster than sqrt(n)
        np.testing.assert_allclose(panel.mean(axis=0), [0.5, 0.5], atol=0.01)

    def test_serialization_roundtrip(self):
        seq = LeapedHaltonSequence(d=7)
        seq2 = QMCSequence.from_dict(json.loads(json.dumps(seq.to_dict())))
        assert seq2.d == 7 and seq2.leap == seq.leap
        assert seq2.coordinate(100, 3) == seq.coordinate(100, 3)


class TestMesh:
    def test_make_mesh_shapes(self, devices):
        from libskylark_tpu import parallel as par

        m1 = par.make_mesh()
        assert m1.devices.shape == (8,)
        m2 = par.make_mesh((2, 4))
        assert m2.devices.shape == (2, 4)
        sq = par.square_mesh()
        assert sq.devices.shape == (2, 4)

    def test_distribute_and_gather(self, mesh2d):
        from libskylark_tpu import parallel as par

        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        xs = par.distribute(x, par.grid2d(mesh2d))
        assert xs.sharding.is_fully_replicated is False
        np.testing.assert_array_equal(par.to_host(xs), x)
        xr = par.distribute(x, par.replicated(mesh2d))
        assert xr.sharding.is_fully_replicated

    def test_sharded_matmul_matches_local(self, mesh2d):
        """XLA-inserted collectives produce the same product as local compute
        — the 'unified Gemm' guarantee (ref: base/Gemm.hpp)."""
        from libskylark_tpu import parallel as par

        rng = np.random.default_rng(0)
        a = rng.standard_normal((32, 16)).astype(np.float32)
        b = rng.standard_normal((16, 24)).astype(np.float32)
        a_s = par.distribute(a, par.row_sharded(mesh2d))
        b_s = par.distribute(b, par.replicated(mesh2d))
        out = jax.jit(jnp.matmul)(a_s, b_s)
        np.testing.assert_allclose(par.to_host(out), a @ b, rtol=1e-5)


class TestSequenceParallelApply:
    """Explicit shard_map panel pipeline == local apply (the long-context
    analog; SURVEY.md §5)."""

    def test_columnwise_matches_local(self, mesh1d):
        import jax.numpy as jnp
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        N, S, m = 2048, 64, 16
        rng = np.random.default_rng(5)
        A = jnp.asarray(rng.standard_normal((N, m)).astype(np.float32))
        T = sk.JLT(N, S, Context(seed=17))
        local = np.asarray(T.apply(A, sk.COLUMNWISE))
        seq = np.asarray(shard_apply.columnwise(T, A, mesh1d))
        np.testing.assert_allclose(seq, local, atol=1e-4, rtol=1e-4)

    def test_rowwise_matches_local(self, mesh1d):
        import jax.numpy as jnp
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        N, S, m = 2048, 64, 16
        rng = np.random.default_rng(6)
        A = jnp.asarray(rng.standard_normal((m, N)).astype(np.float32))
        T = sk.CT(N, S, Context(seed=18), C=1.0)
        local = np.asarray(T.apply(A, sk.ROWWISE))
        seq = np.asarray(shard_apply.rowwise(T, A, mesh1d))
        np.testing.assert_allclose(seq, local, atol=1e-3, rtol=1e-3)

    @pytest.mark.slow
    def test_ragged_n_matches_local(self, mesh1d, devices):
        """Non-dividing N zero-pads exactly — the np∈{5,7} ragged-layout
        discipline (ref: tests/unit/CMakeLists.txt:31-33), including on a
        5-device submesh."""
        import jax.numpy as jnp
        from libskylark_tpu import parallel as par
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        mesh5 = par.make_mesh(devices=devices[:5])
        N, S, m = 1000, 16, 4
        rng = np.random.default_rng(7)
        A = jnp.asarray(rng.standard_normal((N, m)).astype(np.float32))
        T = sk.JLT(N, S, Context(seed=1))
        local = np.asarray(T.apply(A, sk.COLUMNWISE))
        for mesh in (mesh1d, mesh5):
            seq = np.asarray(shard_apply.columnwise(T, A, mesh))
            np.testing.assert_allclose(seq, local, atol=1e-4, rtol=1e-4)
        Ar = jnp.asarray(rng.standard_normal((m, N)).astype(np.float32))
        localr = np.asarray(T.apply(Ar, sk.ROWWISE))
        seqr = np.asarray(shard_apply.rowwise(T, Ar, mesh5))
        np.testing.assert_allclose(seqr, localr, atol=1e-4, rtol=1e-4)

    def test_rejects_non_dense_transform(self, mesh1d):
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base import errors
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        cwt = sk.CWT(2048, 16, Context(seed=1))
        with pytest.raises(errors.UnsupportedError):
            shard_apply.columnwise(cwt, np.zeros((2048, 4), np.float32),
                                   mesh1d)

    @pytest.mark.slow
    def test_pallas_fused_pipeline_interpret(self, mesh1d):
        """The fused kernel runs per-device inside the shard_map pipeline
        (interpret mode on the CPU mesh) and matches the local apply —
        VERDICT weak #5: the fast kernel must serve the distributed path."""
        import jax.numpy as jnp
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        N, S, m = 2048, 32, 16
        rng = np.random.default_rng(8)
        T = sk.JLT(N, S, Context(seed=21))
        Ar = jnp.asarray(rng.standard_normal((m, N)).astype(np.float32))
        localr = np.asarray(T.apply(Ar, sk.ROWWISE))
        seqr = np.asarray(shard_apply.rowwise(
            T, Ar, mesh1d, use_pallas=True, interpret=True))
        np.testing.assert_allclose(seqr, localr, atol=1e-4, rtol=1e-4)
        Ac = jnp.asarray(rng.standard_normal((N, m)).astype(np.float32))
        localc = np.asarray(T.apply(Ac, sk.COLUMNWISE))
        seqc = np.asarray(shard_apply.columnwise(
            T, Ac, mesh1d, use_pallas=True, interpret=True))
        np.testing.assert_allclose(seqc, localc, atol=1e-4, rtol=1e-4)

    def test_rejects_wrong_sequence_length(self, mesh1d):
        from libskylark_tpu import sketch as sk
        from libskylark_tpu.base import errors
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.parallel import shard_apply

        T = sk.JLT(4096, 16, Context(seed=2))
        with pytest.raises(errors.SketchError):
            shard_apply.columnwise(T, np.zeros((2048, 4), np.float32),
                                   mesh1d)
        with pytest.raises(errors.SketchError):
            shard_apply.rowwise(T, np.zeros((4, 2048), np.float32), mesh1d)


class TestPrecisionPolicy:
    def test_ambient_pin_detection_and_frft_yield(self):
        """r4 advisor: an explicit jax.default_matmul_precision(...)
        context must govern the FRFT WHT path (which otherwise opts into
        Precision.HIGH); the library's own installed default must NOT
        count as a user pin."""
        import jax

        from libskylark_tpu.base import precision as bprec
        from libskylark_tpu.sketch.frft import FastGaussianRFT
        from libskylark_tpu.base.context import Context

        assert not bprec.ambient_precision_pinned_by_user()
        with jax.default_matmul_precision("tensorfloat32"):
            assert bprec.ambient_precision_pinned_by_user()
        assert not bprec.ambient_precision_pinned_by_user()

        T = FastGaussianRFT(64, 128, Context(seed=5), sigma=2.0)
        seen = []
        fut = T._fut
        orig = fut.apply

        def spy(W, axis=-1, precision="MISSING"):
            seen.append(precision)
            return orig(W, axis=axis)

        T._fut = type("Spy", (), {"apply": staticmethod(spy),
                                  "scale": staticmethod(fut.scale)})()
        import jax.numpy as jnp
        import numpy as np
        X = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 64)), jnp.float32)
        from libskylark_tpu.sketch import ROWWISE
        T.apply(X, ROWWISE)                      # library default ambient
        with jax.default_matmul_precision("tensorfloat32"):
            T.apply(X, ROWWISE)                  # user-pinned ambient
        assert seen[0] is jax.lax.Precision.HIGH  # opt-in active
        assert seen[2] is None                    # user pin honored
