"""End-to-end tests of bench.py's wedged-tunnel fallback: the
verified-committed block, content-hash oracle freshness, and the r5
promotion rule (a committed capture becomes the headline value ONLY
when its oracle stamp certifies the working tree — since the closure
extension, the stamp's closure_sha256 must match the kernel-relevant
closure: pallas_dense.py + sketch/params.py + base/randgen.py).

Runs bench.py as a subprocess from a fixture tree with
SKYLARK_BENCH_DEADLINE below the probe threshold, so main() goes
straight to the fallback path — no backend is ever touched (these are
orchestration tests, deliberately hardware-free)."""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tree(tmp_path):
    """Minimal working tree: bench.py + the kernel-closure files + a
    committed r99 headline record; returns (dir, write_stamp, run)."""
    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path / "bench.py")
    kdir = tmp_path / "libskylark_tpu" / "sketch"
    kdir.mkdir(parents=True)
    (kdir / "pallas_dense.py").write_text("# kernel source v1\n")
    (kdir / "params.py").write_text("# knobs v1\n")
    bdir2 = tmp_path / "libskylark_tpu" / "base"
    bdir2.mkdir(parents=True)
    (bdir2 / "randgen.py").write_text("# streams v1\n")
    bdir = tmp_path / "benchmarks"
    bdir.mkdir()
    rec = {"metric": "jlt_sketch_apply_GBps_per_chip", "value": 123.4,
           "provenance": {"captured": "2026-07-31T00:00:00+00:00"},
           "cold_start_wall_s": 61}
    (bdir / "results_tpu_r99_headline.json").write_text(json.dumps(rec))

    def write_stamp(content: str | None):
        p = bdir / ".tpu_oracle_recert_r99"
        if content is None:
            # the REAL stamp writer — the steps scripts call this same
            # entry point, so the test certifies the actual format
            out = subprocess.run(
                [sys.executable, str(tmp_path / "bench.py"), "--stamp"],
                capture_output=True, text=True, timeout=60,
                cwd=str(tmp_path))
            assert out.returncode == 0, out.stderr[-500:]
            content = f"2026-07-31T00:00:00Z {out.stdout.strip()}"
        p.write_text(content)

    def run(extra_env=None, timeout=60):
        env = dict(os.environ)
        env["SKYLARK_BENCH_DEADLINE"] = "25"  # below the 30s loop gate
        env.update(extra_env or {})
        out = subprocess.run(
            [sys.executable, str(tmp_path / "bench.py")],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=str(tmp_path))
        assert out.returncode == 0, out.stderr[-500:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    return tmp_path, write_stamp, run


def test_no_stamp_reports_null_with_verified_block(tree):
    _, _, run = tree
    rec = run()
    assert rec["value"] is None
    vc = rec["verified_committed"]
    assert vc["value"] == 123.4
    assert vc["oracle_fresh"] is False and vc["oracle_stamp"] is None


def test_fresh_stamp_promotes_committed_value(tree):
    _, write_stamp, run = tree
    write_stamp(None)  # matching kernel sha
    rec = run()
    assert rec["value"] == 123.4
    assert rec["measured_live"] is False
    assert rec["promoted_from_committed"].endswith(
        "results_tpu_r99_headline.json")
    assert rec["verified_committed"]["oracle_fresh"] is True


def test_stale_kernel_hash_blocks_promotion(tree):
    tmp, write_stamp, run = tree
    write_stamp(None)
    # the kernel changes AFTER certification: the number no longer
    # describes certified numerics — must NOT be promoted
    (tmp / "libskylark_tpu" / "sketch" / "pallas_dense.py").write_text(
        "# kernel source v2 (uncertified)\n")
    rec = run()
    assert rec["value"] is None
    assert rec["verified_committed"]["oracle_fresh"] is False


@pytest.mark.parametrize("rel", [
    os.path.join("libskylark_tpu", "sketch", "params.py"),
    os.path.join("libskylark_tpu", "base", "randgen.py"),
])
def test_stale_closure_blocks_promotion(tree, rel):
    """The ADVICE r5 stamp-closure extension: a post-certification
    change to the tuning knobs or the generation streams — not just the
    kernel file — makes the stamp stale."""
    tmp, write_stamp, run = tree
    write_stamp(None)
    (tmp / rel).write_text("# changed after certification\n")
    rec = run()
    assert rec["value"] is None
    assert rec["verified_committed"]["oracle_fresh"] is False


def test_dead_backend_fails_fast_to_fallback(tree):
    """A FIRST probe that exits with a hard error (backend init raised
    — dead tunnel / absent hardware) must skip the escalating-retry
    ladder entirely and emit the committed-capture record immediately
    (r4/r5 burned ~450s of probe timeouts learning nothing)."""
    import time

    _, _, run = tree
    t0 = time.monotonic()
    rec = run(extra_env={"SKYLARK_BENCH_DEADLINE": "600",
                         "JAX_PLATFORMS": "not_a_backend"},
              timeout=120)
    wall = time.monotonic() - t0
    assert rec["value"] is None            # no oracle stamp: no promote
    assert "fail-fast" in rec["error"]
    assert rec["verified_committed"]["value"] == 123.4
    # one probe's worth of wall, not the 600s deadline or a 75s+ ladder
    assert wall < 60


def test_max_wall_budget_caps_orchestration(tree):
    """SKYLARK_BENCH_MAX_WALL bounds the whole orchestration below the
    retry deadline: a 5s budget goes straight to the fallback."""
    _, _, run = tree
    rec = run(extra_env={"SKYLARK_BENCH_DEADLINE": "600",
                         "SKYLARK_BENCH_MAX_WALL": "5"})
    assert rec["value"] is None
    assert "deadline exhausted" in rec["error"]
    assert rec["verified_committed"]["value"] == 123.4


def test_pre_closure_stamp_does_not_promote(tree):
    """Legacy stamps (kernel_sha256 only, or bare timestamps) certify at
    most one file of the three-file closure: definitively stale."""
    _, write_stamp, run = tree
    write_stamp("2026-07-31T00:00:00Z")  # old format: timestamp only
    rec = run()
    assert rec["value"] is None
    vc = rec["verified_committed"]
    assert vc["oracle_fresh"] is False
    assert "pre-closure" in vc.get("oracle_stale_reason", "")
