"""Training-state checkpoint/resume (utility/checkpoint.py + the ADMM
integration). The reference has no counterpart (SURVEY.md §5: its
checkpoint row is empty — models/sketches serialize but a killed solver
restarts from zero); the contract here is the strong one TPU preemption
demands: resume == uninterrupted, bit-identical."""

import numpy as np
import pytest
import jax.numpy as jnp

pytest.importorskip("orbax.checkpoint")

from libskylark_tpu.algorithms.prox import L2Regularizer, SquaredLoss
from libskylark_tpu.base import errors
from libskylark_tpu.ml.admm import BlockADMMSolver
from libskylark_tpu.utility.checkpoint import (
    TrainCheckpointer,
    device_state,
)


@pytest.fixture
def data():
    rng = np.random.default_rng(4)
    X = rng.standard_normal((96, 12)).astype(np.float32)
    Y = np.sin(X[:, 0]).astype(np.float32)
    return X, Y


class TestTrainCheckpointer:
    def test_roundtrip_pytree_and_metadata(self, tmp_path):
        state = {
            "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "step_scale": jnp.float32(0.5),
            "nested": [jnp.ones((4,), jnp.int32)],
        }
        with TrainCheckpointer(tmp_path / "ck") as ck:
            ck.save(3, state, {"phase": "warmup"})
            step, got, meta = ck.restore()
        assert step == 3 and meta["phase"] == "warmup"
        got = device_state(got)
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(state["w"]))
        assert got["w"].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(got["nested"][0]),
                                      np.ones(4))

    def test_keep_bounds_retention(self, tmp_path):
        with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
            for s in (1, 2, 3, 4):
                ck.save(s, {"x": jnp.full((2,), s, jnp.float32)})
            assert ck.latest_step() == 4
            assert ck.all_steps() == [3, 4]
            _, got, _ = ck.restore(3)
            np.testing.assert_array_equal(np.asarray(got["x"]), [3.0, 3.0])

    def test_restore_empty_raises(self, tmp_path):
        with TrainCheckpointer(tmp_path / "ck") as ck:
            with pytest.raises(errors.InvalidParametersError):
                ck.restore()


def _solver(maxiter):
    s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                        num_partitions=2)
    s.maxiter = maxiter
    s.tol = 0.0
    return s


class TestADMMResume:
    def test_resume_bit_identical_to_uninterrupted(self, data, tmp_path):
        X, Y = data
        ref = _solver(6).train(X, Y, regression=True)

        # "preempted" run: dies after 4 iterations, checkpoints every 2
        ckdir = tmp_path / "admm"
        _solver(4).train(X, Y, regression=True,
                         checkpoint=ckdir, checkpoint_every=2)
        # resumed run over the same directory finishes 5..6
        resumed = _solver(6).train(X, Y, regression=True,
                                   checkpoint=ckdir, checkpoint_every=2)
        np.testing.assert_array_equal(np.asarray(resumed.coef),
                                      np.asarray(ref.coef))

    def test_resume_skips_completed_iterations(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(4).train(X, Y, regression=True, checkpoint=ckdir)
        with TrainCheckpointer(ckdir) as ck:
            assert ck.latest_step() == 4  # final state always saved
        # a resume at maxiter == latest step runs zero new iterations and
        # returns the checkpointed model
        m = _solver(4).train(X, Y, regression=True, checkpoint=ckdir)
        with TrainCheckpointer(ckdir) as ck:
            step, state, meta = ck.restore()
        np.testing.assert_array_equal(np.asarray(m.coef),
                                      np.asarray(state[0]))

    def test_mismatched_problem_refuses(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        other = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 8,
                                num_partitions=2)
        other.maxiter = 2
        other.tol = 0.0
        with pytest.raises(errors.InvalidParametersError):
            other.train(X[:, :8], Y, regression=True, checkpoint=ckdir)

    def test_mismatched_hyperparameters_refuse(self, data, tmp_path):
        """Same shapes, different lambda: the carry belongs to a
        different objective — resuming must refuse, not silently train
        against the new objective from the old state."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        other = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 1.0, 12,
                                num_partitions=2)
        other.maxiter = 4
        other.tol = 0.0
        with pytest.raises(errors.InvalidParametersError):
            other.train(X, Y, regression=True, checkpoint=ckdir)

    def test_mismatched_data_refuses(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        with pytest.raises(errors.InvalidParametersError):
            _solver(4).train(X + 1.0, Y, regression=True,
                             checkpoint=ckdir)

    def test_maxiter_below_checkpoint_refuses(self, data, tmp_path):
        """maxiter=5 against a step-8 checkpoint: returning the step-8
        model would silently over-train relative to the request."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(8).train(X, Y, regression=True, checkpoint=ckdir)
        with pytest.raises(errors.InvalidParametersError):
            _solver(5).train(X, Y, regression=True, checkpoint=ckdir)

    def test_resume_with_sharded_data(self, data, tmp_path, mesh1d):
        """The preemption scenario the feature exists for: training on a
        mesh, killed, resumed — the restored carry re-shards through jit
        and the result matches the uninterrupted sharded run exactly."""
        import libskylark_tpu.parallel as par

        X, Y = data
        Xs = par.distribute(X, par.row_sharded(mesh1d))
        ref = _solver(6).train(Xs, Y, regression=True)
        ckdir = tmp_path / "admm_sharded"
        _solver(3).train(Xs, Y, regression=True, checkpoint=ckdir,
                         checkpoint_every=1)
        resumed = _solver(6).train(Xs, Y, regression=True,
                                   checkpoint=ckdir, checkpoint_every=1)
        np.testing.assert_array_equal(np.asarray(resumed.coef),
                                      np.asarray(ref.coef))

    def test_converged_run_rerun_is_stable(self, data, tmp_path):
        """A run that stopped on tol convergence is DONE: rerunning the
        identical command must return the same model, not advance one
        extra iteration per rerun (drift)."""
        X, Y = data
        ckdir = tmp_path / "admm"

        def run():
            s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                                num_partitions=2)
            s.maxiter = 200
            s.tol = 1e-3  # converges well before maxiter
            return s.train(X, Y, regression=True, checkpoint=ckdir)

        first = run()
        with TrainCheckpointer(ckdir) as ck:
            step1 = ck.latest_step()
        second = run()
        with TrainCheckpointer(ckdir) as ck:
            assert ck.latest_step() == step1  # no extra iteration saved
        np.testing.assert_array_equal(np.asarray(second.coef),
                                      np.asarray(first.coef))

    def test_permuted_rows_refuse(self, data, tmp_path):
        """Row-permuted data has the same global sum but misaligns the
        per-example duals — the position-weighted fingerprint must
        refuse the resume."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        perm = np.random.default_rng(0).permutation(len(Y))
        with pytest.raises(errors.InvalidParametersError):
            _solver(4).train(X[perm], Y[perm], regression=True,
                             checkpoint=ckdir)
