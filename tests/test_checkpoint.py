"""Training-state checkpoint/resume (utility/checkpoint.py + the ADMM
integration). The reference has no counterpart (SURVEY.md §5: its
checkpoint row is empty — models/sketches serialize but a killed solver
restarts from zero); the contract here is the strong one TPU preemption
demands: resume == uninterrupted, bit-identical."""

import numpy as np
import pytest
import jax.numpy as jnp

pytest.importorskip("orbax.checkpoint")

from libskylark_tpu.algorithms.prox import L2Regularizer, SquaredLoss
from libskylark_tpu.base import errors
from libskylark_tpu.ml.admm import BlockADMMSolver
from libskylark_tpu.utility.checkpoint import (
    TrainCheckpointer,
    device_state,
)


@pytest.fixture
def data():
    rng = np.random.default_rng(4)
    X = rng.standard_normal((96, 12)).astype(np.float32)
    Y = np.sin(X[:, 0]).astype(np.float32)
    return X, Y


class TestTrainCheckpointer:
    def test_roundtrip_pytree_and_metadata(self, tmp_path):
        state = {
            "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "step_scale": jnp.float32(0.5),
            "nested": [jnp.ones((4,), jnp.int32)],
        }
        with TrainCheckpointer(tmp_path / "ck") as ck:
            ck.save(3, state, {"phase": "warmup"})
            step, got, meta = ck.restore()
        assert step == 3 and meta["phase"] == "warmup"
        got = device_state(got)
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(state["w"]))
        assert got["w"].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(got["nested"][0]),
                                      np.ones(4))

    def test_keep_bounds_retention(self, tmp_path):
        with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
            for s in (1, 2, 3, 4):
                ck.save(s, {"x": jnp.full((2,), s, jnp.float32)})
            assert ck.latest_step() == 4
            assert ck.all_steps() == [3, 4]
            _, got, _ = ck.restore(3)
            np.testing.assert_array_equal(np.asarray(got["x"]), [3.0, 3.0])

    def test_restore_empty_raises(self, tmp_path):
        with TrainCheckpointer(tmp_path / "ck") as ck:
            with pytest.raises(errors.InvalidParametersError):
                ck.restore()


def _solver(maxiter):
    s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                        num_partitions=2)
    s.maxiter = maxiter
    s.tol = 0.0
    return s


class TestADMMResume:
    def test_resume_bit_identical_to_uninterrupted(self, data, tmp_path):
        X, Y = data
        ref = _solver(6).train(X, Y, regression=True)

        # "preempted" run: dies after 4 iterations, checkpoints every 2
        ckdir = tmp_path / "admm"
        _solver(4).train(X, Y, regression=True,
                         checkpoint=ckdir, checkpoint_every=2)
        # resumed run over the same directory finishes 5..6
        resumed = _solver(6).train(X, Y, regression=True,
                                   checkpoint=ckdir, checkpoint_every=2)
        np.testing.assert_array_equal(np.asarray(resumed.coef),
                                      np.asarray(ref.coef))

    def test_resume_skips_completed_iterations(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(4).train(X, Y, regression=True, checkpoint=ckdir)
        with TrainCheckpointer(ckdir) as ck:
            assert ck.latest_step() == 4  # final state always saved
        # a resume at maxiter == latest step runs zero new iterations and
        # returns the checkpointed model
        m = _solver(4).train(X, Y, regression=True, checkpoint=ckdir)
        with TrainCheckpointer(ckdir) as ck:
            step, state, meta = ck.restore()
        np.testing.assert_array_equal(np.asarray(m.coef),
                                      np.asarray(state[0]))

    def test_mismatched_problem_refuses(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        other = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 8,
                                num_partitions=2)
        other.maxiter = 2
        other.tol = 0.0
        with pytest.raises(errors.InvalidParametersError):
            other.train(X[:, :8], Y, regression=True, checkpoint=ckdir)

    def test_mismatched_hyperparameters_refuse(self, data, tmp_path):
        """Same shapes, different lambda: the carry belongs to a
        different objective — resuming must refuse, not silently train
        against the new objective from the old state."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        other = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 1.0, 12,
                                num_partitions=2)
        other.maxiter = 4
        other.tol = 0.0
        with pytest.raises(errors.InvalidParametersError):
            other.train(X, Y, regression=True, checkpoint=ckdir)

    def test_mismatched_data_refuses(self, data, tmp_path):
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        with pytest.raises(errors.InvalidParametersError):
            _solver(4).train(X + 1.0, Y, regression=True,
                             checkpoint=ckdir)

    def test_maxiter_below_checkpoint_refuses(self, data, tmp_path):
        """maxiter=5 against a step-8 checkpoint: returning the step-8
        model would silently over-train relative to the request."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(8).train(X, Y, regression=True, checkpoint=ckdir)
        with pytest.raises(errors.InvalidParametersError):
            _solver(5).train(X, Y, regression=True, checkpoint=ckdir)

    def test_converged_resume_with_different_tol_refuses(self, data,
                                                         tmp_path):
        """tol=0 is the documented force-maxiter knob; a converged
        checkpoint must not silently satisfy a rerun that asks for
        different stopping behavior."""
        X, Y = data
        ckdir = tmp_path / "admm"
        s1 = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                             num_partitions=2)
        s1.maxiter = 200
        s1.tol = 1e-3
        s1.train(X, Y, regression=True, checkpoint=ckdir)
        s2 = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                             num_partitions=2)
        s2.maxiter = 200
        s2.tol = 0.0
        with pytest.raises(errors.InvalidParametersError, match="tol"):
            s2.train(X, Y, regression=True, checkpoint=ckdir)

    def test_resume_with_sharded_data(self, data, tmp_path, mesh1d):
        """The preemption scenario the feature exists for: training on a
        mesh, killed, resumed — the restored carry re-shards through jit
        and the result matches the uninterrupted sharded run exactly."""
        import libskylark_tpu.parallel as par

        X, Y = data
        Xs = par.distribute(X, par.row_sharded(mesh1d))
        ref = _solver(6).train(Xs, Y, regression=True)
        ckdir = tmp_path / "admm_sharded"
        _solver(3).train(Xs, Y, regression=True, checkpoint=ckdir,
                         checkpoint_every=1)
        resumed = _solver(6).train(Xs, Y, regression=True,
                                   checkpoint=ckdir, checkpoint_every=1)
        np.testing.assert_array_equal(np.asarray(resumed.coef),
                                      np.asarray(ref.coef))

    def test_converged_run_rerun_is_stable(self, data, tmp_path):
        """A run that stopped on tol convergence is DONE: rerunning the
        identical command must return the same model, not advance one
        extra iteration per rerun (drift)."""
        X, Y = data
        ckdir = tmp_path / "admm"

        def run():
            s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 12,
                                num_partitions=2)
            s.maxiter = 200
            s.tol = 1e-3  # converges well before maxiter
            return s.train(X, Y, regression=True, checkpoint=ckdir)

        first = run()
        with TrainCheckpointer(ckdir) as ck:
            step1 = ck.latest_step()
        second = run()
        with TrainCheckpointer(ckdir) as ck:
            assert ck.latest_step() == step1  # no extra iteration saved
        np.testing.assert_array_equal(np.asarray(second.coef),
                                      np.asarray(first.coef))

    def test_legacy_identity_scheme_diagnosed_as_format(self, data,
                                                        tmp_path):
        """A checkpoint written under a different resume-identity
        scheme (e.g. the pre-digest float-statistic hash) must refuse
        with a format diagnosis, not 'different training run' (review
        finding)."""
        from libskylark_tpu.utility.checkpoint import TrainCheckpointer

        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        with TrainCheckpointer(str(ckdir)) as ck:
            step, meta = ck.metadata()
            _, state, _ = ck.restore(step)
            meta = dict(meta)
            meta.pop("identity_scheme")  # simulate an older build
            ck.save(step + 1, state, meta)
        with pytest.raises(errors.InvalidParametersError,
                           match="older build"):
            _solver(4).train(X, Y, regression=True, checkpoint=ckdir)

    def test_permuted_rows_refuse(self, data, tmp_path):
        """Row-permuted data has the same global sum but misaligns the
        per-example duals — the position-weighted fingerprint must
        refuse the resume."""
        X, Y = data
        ckdir = tmp_path / "admm"
        _solver(2).train(X, Y, regression=True, checkpoint=ckdir)
        perm = np.random.default_rng(0).permutation(len(Y))
        with pytest.raises(errors.InvalidParametersError):
            _solver(4).train(X[perm], Y[perm], regression=True,
                             checkpoint=ckdir)


class TestStreamingResume:
    """Checkpointable streaming sketch (io/streaming.py): a killed
    ingestion job resumes past the rows already folded in."""

    def _batches(self, X, Y, bs):
        for i in range(0, len(Y), bs):
            yield X[i:i + bs], Y[i:i + bs]

    @pytest.fixture
    def stream_data(self):
        rng = np.random.default_rng(7)
        X = rng.standard_normal((64, 5)).astype(np.float32)
        Y = rng.standard_normal(64).astype(np.float32)
        return X, Y

    def test_resume_equals_one_shot(self, stream_data, tmp_path):
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ref_SX, ref_SY = StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X, Y, 8))

        ckdir = tmp_path / "stream"
        # partial pass: only the first 3 batches (24 rows), then "dies"
        part = StreamingCWT(64, 16, Context(seed=5))
        part.sketch(self._batches(X[:24], Y[:24], 8),
                    checkpoint=ckdir, checkpoint_every=1)
        # the partial pass declared n=64 but the stream ended at 24 —
        # its accumulators for rows 0..23 are checkpointed
        full = StreamingCWT(64, 16, Context(seed=5))
        SX, SY = full.sketch(self._batches(X, Y, 8), checkpoint=ckdir,
                             checkpoint_every=1)
        np.testing.assert_array_equal(np.asarray(SX), np.asarray(ref_SX))
        np.testing.assert_array_equal(np.asarray(SY), np.asarray(ref_SY))

    def test_different_stream_refuses(self, stream_data, tmp_path):
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
            checkpoint_every=1)
        other = X.copy()
        other[0, 0] += 1.0  # different first batch, same config
        with pytest.raises(errors.InvalidParametersError):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                self._batches(other, Y, 8), checkpoint=ckdir)

    def test_changed_batching_refuses(self, stream_data, tmp_path):
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
            checkpoint_every=1)

        def odd_batches():
            # batch 0 identical (passes the content check), later
            # batching shifted so one batch straddles the saved offset 24
            yield X[:8], Y[:8]
            yield X[8:18], Y[8:18]
            yield X[18:28], Y[18:28]   # straddles 24

        with pytest.raises(errors.InvalidParametersError,
                           match="straddles"):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                odd_batches(), checkpoint=ckdir)

    def test_truncated_resume_stream_refuses(self, stream_data, tmp_path):
        """A re-supplied stream that ends DURING fast-forward (shorter
        than the checkpointed offset, or empty) must refuse instead of
        returning the restored partial accumulators as final (r3
        advisor)."""
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
            checkpoint_every=1)
        with pytest.raises(errors.InvalidParametersError,
                           match="ended at 16 rows"):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                self._batches(X[:16], Y[:16], 8), checkpoint=ckdir)
        with pytest.raises(errors.InvalidParametersError,
                           match="ended at 0 rows"):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                iter(()), checkpoint=ckdir)

    def test_cross_dtype_leaves_preserved(self, tmp_path):
        """device_state casts only floating leaves: an int step counter
        or index array must keep its dtype (r3 advisor)."""
        from libskylark_tpu.utility.checkpoint import device_state

        state = {"w": np.ones(3, np.float64),
                 "step": np.asarray(7, np.int64),
                 "idx": np.arange(4, dtype=np.int32),
                 "flag": np.asarray(True)}
        out = device_state(state, dtype=jnp.float32)
        assert out["w"].dtype == jnp.float32
        assert jnp.issubdtype(out["step"].dtype, jnp.integer)
        assert jnp.issubdtype(out["idx"].dtype, jnp.integer)
        assert out["flag"].dtype == jnp.bool_
        assert int(out["step"]) == 7

    def test_sample_digest_platform_independent_identity(self):
        """sample_digest: exact on content, shape-sensitive, bounded,
        identical for host and device arrays of the same bytes."""
        from libskylark_tpu.utility.checkpoint import sample_digest

        rng = np.random.default_rng(0)
        A = rng.standard_normal((1000, 4)).astype(np.float32)
        assert sample_digest(A) == sample_digest(jnp.asarray(A))
        B = A.copy()
        B[0, 0] += 1.0                      # sampled row change: caught
        assert sample_digest(B) != sample_digest(A)
        assert sample_digest(A[:999]) != sample_digest(A)  # shape change
        nanA = A.copy()
        nanA[0, 1] = np.nan                 # NaN round-trips exactly
        assert sample_digest(nanA) == sample_digest(nanA.copy())
        # empty leading axis: valid digest, not an IndexError (review
        # finding — positional_fingerprint handled empties)
        assert isinstance(sample_digest(np.zeros((0, 4), np.float32)),
                          str)
        assert (sample_digest(np.zeros((0, 4), np.float32))
                != sample_digest(np.zeros((0, 5), np.float32)))

    def test_sample_digest_full_coverage_under_byte_budget(self):
        """r4 advisor (medium): a one-row edit in a large-n operand must
        change the digest whenever the f32 view fits the byte budget —
        the old fixed 16-row sample missed it ~(1 - 16/n) of the time."""
        from libskylark_tpu.utility.checkpoint import sample_digest

        rng = np.random.default_rng(1)
        A = rng.standard_normal((100_000, 8)).astype(np.float32)  # 3.2 MB
        B = A.copy()
        B[54_321, 3] += 1.0                 # arbitrary interior row
        assert sample_digest(B) != sample_digest(A)
        # above the budget, sampling kicks in but stays >= 1024 rows and
        # still covers far more than the old 16 (deterministic + bounded)
        d1 = sample_digest(A, byte_budget=1 << 16)
        assert d1 == sample_digest(A, byte_budget=1 << 16)
        assert d1 != sample_digest(A)  # different idx set → different tag
        # explicit rows= override keeps the bounded-caller contract
        assert (sample_digest(A, rows=16)
                == sample_digest(A.copy(), rows=16))

    def test_sample_digest_nonaddressable_fallback(self, monkeypatch):
        """Multi-host-sharded operands (not host-readable) fall back to
        a device-side position-weighted statistic instead of crashing
        on the host gather (review finding). Row AND column
        permutations must change it."""
        import libskylark_tpu.utility.checkpoint as ckpt_mod
        from libskylark_tpu.utility.checkpoint import sample_digest

        monkeypatch.setattr(ckpt_mod, "_fully_addressable",
                            lambda a: False)
        A = jnp.asarray(
            np.random.default_rng(3).standard_normal((32, 6)),
            jnp.float32)
        d = sample_digest(A)
        assert isinstance(d, str) and d == sample_digest(A)
        assert sample_digest(A[::-1]) != d          # row permutation
        assert sample_digest(A[:, ::-1]) != d       # column permutation

    def test_legacy_float_batch0_hash_diagnosed_as_format(
            self, stream_data, tmp_path):
        """A checkpoint whose batch0_hash is the pre-digest float must
        refuse with a format-incompatibility message, not the
        misleading 'first batch differs' (review finding)."""
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT
        from libskylark_tpu.utility.checkpoint import TrainCheckpointer

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        s = StreamingCWT(64, 16, Context(seed=5))
        s.sketch(self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
                 checkpoint_every=1)
        with TrainCheckpointer(str(ckdir)) as ck:
            step, meta = ck.metadata()
            _, state, _ = ck.restore(step)
            meta = dict(meta)
            meta["batch0_hash"] = 1.2345  # simulate the old format
            ck.save(step + 1, state, meta)
        with pytest.raises(errors.InvalidParametersError,
                           match="older build"):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                self._batches(X, Y, 8), checkpoint=ckdir)

    def test_foreign_digest_scheme_diagnosed_as_format(
            self, stream_data, tmp_path):
        """A checkpoint tagged with a DIFFERENT digest scheme must
        refuse with a format diagnosis (the ml/admm.py _IDENTITY_SCHEME
        discipline applied to streaming, ADVICE r5) — not fall through
        to a digest comparison that misdiagnoses it as a different
        stream."""
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT
        from libskylark_tpu.utility.checkpoint import TrainCheckpointer

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        s = StreamingCWT(64, 16, Context(seed=5))
        s.sketch(self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
                 checkpoint_every=1)
        with TrainCheckpointer(str(ckdir)) as ck:
            step, meta = ck.metadata()
            assert meta["digest_scheme"] == 2  # current scheme tagged
            _, state, _ = ck.restore(step)
            meta = dict(meta)
            meta["digest_scheme"] = 99  # a future/foreign scheme
            ck.save(step + 1, state, meta)
        with pytest.raises(errors.InvalidParametersError,
                           match="digest scheme"):
            StreamingCWT(64, 16, Context(seed=5)).sketch(
                self._batches(X, Y, 8), checkpoint=ckdir)

    def test_exact_offset_rerun_is_consistent_noop(self, stream_data,
                                                   tmp_path):
        """A re-supplied stream ending EXACTLY at the checkpointed
        offset re-verifies batch 0, folds nothing new, and returns the
        same partial state as the pass that wrote the checkpoint — the
        partial-pass contract, not a truncation refusal (boundary
        documented at the guard)."""
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        SX1, SY1 = StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X[:24], Y[:24], 8), checkpoint=ckdir,
            checkpoint_every=1)
        SX2, SY2 = StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X[:24], Y[:24], 8), checkpoint=ckdir)
        np.testing.assert_array_equal(np.asarray(SX2), np.asarray(SX1))
        np.testing.assert_array_equal(np.asarray(SY2), np.asarray(SY1))

    def test_finished_stream_rerun_skips_read(self, stream_data, tmp_path):
        from libskylark_tpu.base.context import Context
        from libskylark_tpu.io.streaming import StreamingCWT

        X, Y = stream_data
        ckdir = tmp_path / "stream"
        SX1, SY1 = StreamingCWT(64, 16, Context(seed=5)).sketch(
            self._batches(X, Y, 8), checkpoint=ckdir, checkpoint_every=2)

        def exploding():
            raise AssertionError("finished rerun must not read stream")
            yield  # pragma: no cover

        SX2, SY2 = StreamingCWT(64, 16, Context(seed=5)).sketch(
            exploding(), checkpoint=ckdir)
        np.testing.assert_array_equal(np.asarray(SX2), np.asarray(SX1))
        np.testing.assert_array_equal(np.asarray(SY2), np.asarray(SY1))

