"""CLI driver tests: each executable run end-to-end on tiny data
(the reference wires its CLIs into CTest the same way —
ref: ml/CMakeLists.txt, nla/CMakeLists.txt)."""

import numpy as np
import pytest

import libskylark_tpu.io as skio
from libskylark_tpu.cli import (
    skylark_community,
    skylark_convert2hdf5,
    skylark_graph_se,
    skylark_linear,
    skylark_ml,
    skylark_svd,
)


@pytest.fixture()
def regression_file(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((200, 10)).astype(np.float32)
    w = rng.standard_normal(10).astype(np.float32)
    y = X @ w + 0.01 * rng.standard_normal(200).astype(np.float32)
    p = tmp_path / "reg.libsvm"
    skio.write_libsvm(p, X, y)
    return str(p), X, w


@pytest.fixture()
def classification_file(tmp_path):
    rng = np.random.default_rng(1)
    n = 120
    X = rng.standard_normal((n, 6)).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)
    p = tmp_path / "cls.libsvm"
    skio.write_libsvm(p, X, y)
    return str(p)


@pytest.fixture()
def graph_file(tmp_path):
    # two 5-cliques joined by one edge
    lines = []
    for block, off in ((0, 0), (1, 5)):
        for i in range(5):
            for j in range(i + 1, 5):
                lines.append(f"{off + i} {off + j}")
    lines.append("0 5")
    p = tmp_path / "graph.txt"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestSVDCli:
    def test_libsvm_input(self, regression_file, tmp_path):
        path, X, _ = regression_file
        prefix = str(tmp_path / "svd")
        rc = skylark_svd.main([path, "-k", "4", "--prefix", prefix])
        assert rc == 0
        U = np.loadtxt(prefix + ".U.txt")
        S = np.loadtxt(prefix + ".S.txt")
        V = np.loadtxt(prefix + ".V.txt")
        R = (U * S) @ V.T
        # rank-4 truncation of a full-rank matrix: check projection quality
        # against numpy's optimal rank-4 approximation
        u, s, vt = np.linalg.svd(X, full_matrices=False)
        opt = (u[:, :4] * s[:4]) @ vt[:4]
        assert np.linalg.norm(R - X) <= 1.25 * np.linalg.norm(opt - X) + 1e-5

    def test_streaming_matches_oneshot(self, regression_file, tmp_path):
        """--streaming (chunked read into sharded HBM) must produce the
        same factorization as the whole-file read at the same seed."""
        path, X, _ = regression_file
        p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
        assert skylark_svd.main([path, "-k", "4", "--prefix", p1]) == 0
        assert skylark_svd.main(
            [path, "-k", "4", "--prefix", p2,
             "--streaming", "--batch-rows", "7"]) == 0
        for suffix in (".U.txt", ".S.txt", ".V.txt"):
            np.testing.assert_allclose(
                np.loadtxt(p2 + suffix), np.loadtxt(p1 + suffix),
                atol=1e-3, rtol=1e-3)

    def test_profile_mode(self, tmp_path):
        prefix = str(tmp_path / "prof")
        rc = skylark_svd.main(
            ["--profile", "64", "32", "-k", "3", "--prefix", prefix])
        assert rc == 0
        assert np.loadtxt(prefix + ".S.txt").shape == (3,)

    @pytest.mark.slow
    def test_arclist_symmetric(self, graph_file, tmp_path):
        prefix = str(tmp_path / "g")
        rc = skylark_svd.main([graph_file, "--filetype", "ARC_LIST",
                               "-k", "2", "--prefix", prefix])
        assert rc == 0
        assert np.loadtxt(prefix + ".S.txt").shape == (2,)


class TestLinearCli:
    def test_sketch_and_solve(self, regression_file, tmp_path):
        path, X, w = regression_file
        prefix = str(tmp_path / "lin")
        rc = skylark_linear.main([path, "--prefix", prefix])
        assert rc == 0
        x = np.loadtxt(prefix + ".x.txt")
        assert np.linalg.norm(x - w) / np.linalg.norm(w) < 0.2

    @pytest.mark.slow
    def test_streaming_matches_whole_file(self, regression_file, tmp_path):
        path, X, y = regression_file
        p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
        assert skylark_linear.main([path, "-p", "--prefix", p1]) == 0
        assert skylark_linear.main(
            [path, "-p", "--prefix", p2,
             "--streaming", "--batch-rows", "9"]) == 0
        np.testing.assert_allclose(
            np.loadtxt(p2 + ".x.txt"), np.loadtxt(p1 + ".x.txt"),
            atol=1e-3, rtol=1e-3)

    def test_highprecision(self, regression_file, tmp_path):
        path, X, w = regression_file
        prefix = str(tmp_path / "linhp")
        rc = skylark_linear.main([path, "-p", "--prefix", prefix])
        assert rc == 0
        x = np.loadtxt(prefix + ".x.txt")
        assert np.linalg.norm(x - w) / np.linalg.norm(w) < 0.05


class TestMLCli:
    @pytest.mark.slow
    def test_train_and_test_classification(self, classification_file,
                                           tmp_path):
        model = str(tmp_path / "model.json")
        rc = skylark_ml.main([
            classification_file, model, "-l", "2", "-r", "1", "-k", "1",
            "-g", "1.0", "-c", "0.01", "-f", "64", "-i", "8",
        ])
        assert rc == 0
        rc = skylark_ml.main(["--testfile", classification_file,
                              "--modelfile", model])
        assert rc == 0

    @pytest.mark.slow
    def test_train_streaming_matches_whole_file(self, classification_file,
                                                tmp_path):
        """--streaming ingestion trains to the same model as the
        whole-file read (same seed, same streams)."""
        path = classification_file
        m1 = str(tmp_path / "m1.json")
        m2 = str(tmp_path / "m2.json")
        common = [path, "-k", "1", "-g", "3.0", "-f", "64", "-i", "4",
                  "-c", "0.01", "-l", "2", "-r", "1"]
        assert skylark_ml.main(common + [m1]) == 0
        assert skylark_ml.main(
            common + [m2, "--streaming", "--batch-rows", "13"]) == 0
        from libskylark_tpu.ml.model import HilbertModel

        c1 = np.asarray(HilbertModel.load(m1).coef)
        c2 = np.asarray(HilbertModel.load(m2).coef)
        np.testing.assert_allclose(c2, c1, atol=1e-3, rtol=1e-3)

    def test_train_regression_linear(self, regression_file, tmp_path):
        path, _, _ = regression_file
        model = str(tmp_path / "reg_model.json")
        rc = skylark_ml.main([
            path, model, "--regression", "-c", "0.001", "-i", "15",
        ])
        assert rc == 0
        rc = skylark_ml.main(["--testfile", path, "--modelfile", model,
                              "--regression"])
        assert rc == 0


class TestGraphCli:
    def test_graph_se(self, graph_file, tmp_path):
        prefix = str(tmp_path / "se")
        rc = skylark_graph_se.main(
            [graph_file, "-k", "2", "-n", "--prefix", prefix])
        assert rc == 0
        V = np.loadtxt(prefix + ".V.txt")
        assert V.shape == (10, 2)
        idx = [int(v) for v in
               (tmp_path / "se.index.txt").read_text().split()]
        assert sorted(idx) == list(range(10))

    def test_community_batch(self, graph_file, capsys):
        rc = skylark_community.main([graph_file, "0", "-n", "-q"])
        assert rc == 0
        out = capsys.readouterr().out.split()
        members = {int(v) for v in out}
        # seed block (vertices 0-4) should dominate the cluster
        assert 0 in members
        assert len(members & {0, 1, 2, 3, 4}) >= 3

    def test_community_missing_seed(self, graph_file):
        rc = skylark_community.main([graph_file, "99", "-n"])
        assert rc == 2


@pytest.mark.skipif(not skio.have_hdf5(), reason="h5py unavailable")
class TestConvertCli:
    def test_roundtrip_dense(self, regression_file, tmp_path):
        path, X, _ = regression_file
        h5 = str(tmp_path / "data.h5")
        rc = skylark_convert2hdf5.main([path, h5])
        assert rc == 0
        X2, _ = skio.read_hdf5(h5)
        np.testing.assert_allclose(X2, X, rtol=1e-6)

    def test_roundtrip_sparse(self, classification_file, tmp_path):
        h5 = str(tmp_path / "datas.h5")
        rc = skylark_convert2hdf5.main([classification_file, h5,
                                        "--mode", "1"])
        assert rc == 0
        X2, _ = skio.read_hdf5(h5, sparse=True)
        X1, _ = skio.read_libsvm(classification_file)
        np.testing.assert_allclose(np.asarray(X2.todense()), X1, rtol=1e-5)


class TestLabelCoding:
    def test_noncontiguous_labels_roundtrip(self, tmp_path):
        """Labels {3,7,9}: accuracy must be computed against the original
        label values via the stored coding (review regression)."""
        rng = np.random.default_rng(5)
        n = 90
        X = rng.standard_normal((n, 4)).astype(np.float32)
        raw = np.where(X[:, 0] > 0.5, 9, np.where(X[:, 0] > -0.5, 7, 3))
        p = tmp_path / "odd.libsvm"
        skio.write_libsvm(p, X, raw.astype(np.float32))
        model = str(tmp_path / "odd.json")
        rc = skylark_ml.main([str(p), model, "-c", "0.001", "-i", "30"])
        assert rc == 0
        # model stores the coding
        from libskylark_tpu.ml.model import HilbertModel

        m = HilbertModel.load(model)
        assert m.label_coding == [3, 7, 9]
        # subset-of-labels test file must still score against raw values
        mask = raw != 3
        p2 = tmp_path / "subset.libsvm"
        skio.write_libsvm(p2, X[mask], raw[mask].astype(np.float32))
        out = str(tmp_path / "pred")
        rc = skylark_ml.main(["--testfile", str(p2), "--modelfile", model,
                              "--outputfile", out])
        assert rc == 0
        preds = np.loadtxt(out + ".txt")
        assert set(np.unique(preds)) <= {3.0, 7.0, 9.0}

    def test_modelfile_checked_before_training(self, tmp_path):
        rng = np.random.default_rng(6)
        X = rng.standard_normal((20, 3)).astype(np.float32)
        p = tmp_path / "t.libsvm"
        skio.write_libsvm(p, X, (X[:, 0] > 0).astype(np.float32))
        rc = skylark_ml.main([str(p)])
        assert rc == 2


class TestMLCheckpointResume:
    def test_train_resume_matches_uninterrupted(self, tmp_path):
        """--checkpoint-dir: a killed training run rerun with the same
        directory must produce the same model as one uninterrupted run
        (the ADMM carry is persisted and resumed)."""
        pytest.importorskip("orbax.checkpoint")
        rng = np.random.default_rng(11)
        X = rng.standard_normal((80, 6)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        p = tmp_path / "reg.libsvm"
        skio.write_libsvm(p, X, y)

        from libskylark_tpu.ml.model import HilbertModel

        ref_model = str(tmp_path / "ref.json")
        common = ["-c", "0.001", "-e", "0", "--regression"]
        assert skylark_ml.main([str(p), ref_model, "-i", "8"] + common) == 0

        ck = str(tmp_path / "ck")
        part = str(tmp_path / "part.json")
        assert skylark_ml.main(
            [str(p), part, "-i", "5", "--checkpoint-dir", ck,
             "--checkpoint-every", "2"] + common) == 0
        resumed = str(tmp_path / "resumed.json")
        assert skylark_ml.main(
            [str(p), resumed, "-i", "8", "--checkpoint-dir", ck,
             "--checkpoint-every", "2"] + common) == 0

        np.testing.assert_array_equal(
            np.asarray(HilbertModel.load(resumed).coef),
            np.asarray(HilbertModel.load(ref_model).coef))
