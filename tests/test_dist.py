"""Fault-tolerant distributed sketching (libskylark_tpu/dist,
docs/distributed).

The contract under test: a row shard is a recomputable, idempotent
unit of work — re-execution anywhere is bit-equal, merge order is
invariant (canonical tree), lost shards degrade with EXACT coverage
accounting gated by ``min_coverage``, and the coordinator absorbs
injected shard faults by retry + ring reassignment with the final
merge bit-equal to the one-shot ``sketch_local`` reference.
"""

from __future__ import annotations

import itertools
import random

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import dist
from libskylark_tpu import sketch as sk
from libskylark_tpu.base import errors as sk_errors
from libskylark_tpu.dist import plan as dp
from libskylark_tpu.resilience import faults

KINDS = ("cwt", "jlt", "srht", "ust")
N, D, S_DIM, TARGETS = 64, 8, 16, 2


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    return (rng.standard_normal((N, D)).astype(np.float32),
            rng.standard_normal((N, TARGETS)).astype(np.float32))


def _plan(kind, **kw):
    base = dict(kind=kind, n=N, s_dim=S_DIM, d=D, seed=5,
                targets=TARGETS, shard_rows=10)
    base.update(kw)
    return dp.ShardPlan(**base).validate()


def _partials(plan, src):
    return {i: dp.compute_shard(plan, i, src)
            for i, _, _ in plan.shards()}


# ---------------------------------------------------------------------------
# plan geometry + identity
# ---------------------------------------------------------------------------


class TestShardPlan:
    def test_geometry_ragged_tail(self):
        p = _plan("cwt")
        assert p.num_shards == 7
        assert p.shard_range(0) == (0, 10)
        assert p.shard_range(6) == (60, 64)
        assert sum(hi - lo for _, lo, hi in p.shards()) == N

    def test_env_default_shard_rows(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_DIST_SHARD_ROWS", "16")
        p = dp.ShardPlan(kind="cwt", n=N, s_dim=S_DIM, d=D)
        assert p.rows_per_shard == 16 and p.num_shards == 4
        # serialization pins the effective grid at dispatch time: a
        # replica under a different env computes the same ranges
        doc = p.to_dict()
        monkeypatch.setenv("SKYLARK_DIST_SHARD_ROWS", "7")
        assert dp.ShardPlan.from_dict(doc).rows_per_shard == 16

    def test_roundtrip_and_fingerprint(self):
        p = _plan("jlt")
        q = dp.ShardPlan.from_dict(p.to_dict())
        assert q.fingerprint() == p.fingerprint()
        assert _plan("jlt", seed=6).fingerprint() != p.fingerprint()

    @pytest.mark.parametrize("kw", [
        dict(kind="nope"), dict(n=0), dict(s_dim=0),
        dict(kind="srht", n=60), dict(shard_rows=-1),
    ])
    def test_validation(self, kw):
        with pytest.raises(sk_errors.InvalidParametersError):
            _plan(kw.pop("kind", "cwt"), **kw)


# ---------------------------------------------------------------------------
# partials: correctness vs the one-shot apply + re-execution identity
# ---------------------------------------------------------------------------


class TestPartials:
    @pytest.mark.parametrize("kind", KINDS)
    def test_full_merge_matches_oneshot_apply(self, data, kind):
        A, Y = data
        plan = _plan(kind)
        res = dp.sketch_local(plan, dp.ArraySource(A, Y))
        t = plan._transform()
        ref = np.asarray(t.apply(jnp.asarray(A), sk.COLUMNWISE))
        refy = np.asarray(t.apply(jnp.asarray(Y), sk.COLUMNWISE))
        assert res.coverage == 1.0 and not res.degraded
        if kind == "ust":
            # sampler merges are placement, not addition: exact
            assert np.array_equal(res.SX, ref)
            assert np.array_equal(res.SY, refy)
        else:
            np.testing.assert_allclose(res.SX, ref, atol=1e-4)
            np.testing.assert_allclose(res.SY, refy, atol=1e-4)

    @pytest.mark.parametrize("kind", KINDS)
    def test_reexecution_bit_equal(self, data, kind):
        """Same shard, fresh transform state, different batching-free
        source object: bit-identical partial (the idempotent-unit
        contract)."""
        A, Y = data
        plan = _plan(kind)
        p1 = dp.compute_shard(plan, 3, dp.ArraySource(A, Y))
        p2 = dp.compute_shard(plan, 3, dp.ArraySource(A.copy(),
                                                      Y.copy()))
        assert set(p1) == set(p2)
        for k in p1:
            assert np.array_equal(p1[k], p2[k]), k

    @pytest.mark.parametrize("kind", KINDS)
    def test_reexecution_on_replica_bit_equal(self, data, kind):
        """A shard re-executed through the fleet ``shard`` verb (a
        different 'replica') reproduces the local partial bit-exactly
        — the dispatch payload is the serialized plan."""
        from libskylark_tpu.fleet import ThreadReplica

        A, Y = data
        plan = _plan(kind)
        local = dp.compute_shard(plan, 2, dp.ArraySource(A, Y))
        r = ThreadReplica("rx", max_batch=2)
        try:
            lo, hi = plan.shard_range(2)
            out = r.shard({"plan": plan.to_dict(), "index": 2,
                           "source": dp.ArraySource(A, Y).subrange(
                               lo, hi)}).result(timeout=60.0)
        finally:
            r.shutdown()
        assert out["index"] == 2 and out["rows"] == hi - lo
        for k in local:
            assert np.array_equal(local[k], out["partial"][k]), k

    def test_subrange_ships_only_shard_rows(self, data):
        A, Y = data
        src = dp.ArraySource(A, Y)
        sub = src.subrange(10, 20)
        assert sub._X.shape == (10, D)
        got = list(sub.read(10, 20))
        assert len(got) == 1 and got[0][0] == 10
        assert np.array_equal(got[0][1], A[10:20])
        with pytest.raises(sk_errors.InvalidParametersError):
            list(sub.read(0, 10))

    def test_operator_panel_diagonal_amortization_bit_equal(self):
        """The sessions appender's pre-generated full diagonal and the
        shard tasks' per-slice stream must produce identical panel
        bits (positional-stream invariance)."""
        from libskylark_tpu import Context
        from libskylark_tpu.sketch.fjlt import FJLT

        t = FJLT(64, 16, Context(seed=2), fut="wht")
        diag = np.asarray(t.diagonal(jnp.float32))
        sliced = t.operator_panel(10, 30, np.float32)
        amortized = t.operator_panel(10, 30, np.float32, diagonal=diag)
        assert np.array_equal(sliced, amortized)

    def test_batching_invariant_partial_cwt(self, data):
        """CWT folds scatter in row order into the carried
        accumulator: the partial is bit-identical across source batch
        grids (the io/streaming invariant at shard scope)."""
        A, _ = data
        plan = _plan("cwt", targets=0)
        outs = [dp.compute_shard(plan, 1,
                                 dp.ArraySource(A, batch_rows=b))
                for b in (0, 3, 4, 10)]
        for o in outs[1:]:
            assert np.array_equal(outs[0]["SX"], o["SX"])


# ---------------------------------------------------------------------------
# merge: order invariance + degraded accounting
# ---------------------------------------------------------------------------


class TestMerge:
    @pytest.mark.parametrize("kind", KINDS)
    def test_merge_order_invariance_property(self, data, kind):
        """Any arrival permutation (and any grouping a coordinator
        could have buffered them in) merges bit-equal: the merge
        canonicalizes to ascending shard index and reduces through a
        fixed pairwise tree, so the bits depend only on the present
        SET of shards."""
        A, Y = data
        plan = _plan(kind)
        parts = _partials(plan, dp.ArraySource(A, Y))
        ref = dp.merge_partials(plan, parts)
        rng = random.Random(0)
        keys = list(parts)
        for _ in range(6):
            rng.shuffle(keys)
            perm = {k: parts[k] for k in keys}
            got = dp.merge_partials(plan, perm)
            for name in ref:
                assert np.array_equal(ref[name], got[name]), name
        # subsets are deterministic too (the degraded-merge path):
        # same present-set, any order => same bits
        for drop in range(plan.num_shards):
            sub = [k for k in parts if k != drop]
            m1 = dp.merge_partials(plan, {k: parts[k] for k in sub})
            m2 = dp.merge_partials(
                plan, {k: parts[k] for k in reversed(sub)})
            for name in m1:
                assert np.array_equal(m1[name], m2[name]), name

    def test_missing_ranges_coalesce(self):
        plan = _plan("cwt")
        assert dp.missing_ranges(plan, [0, 3, 6]) == \
            ((10, 30), (40, 60))
        assert dp.missing_ranges(plan, range(7)) == ()
        assert dp.missing_ranges(plan, []) == ((0, 64),)

    def test_degraded_result_accounting(self, data):
        A, Y = data
        plan = _plan("cwt")
        parts = _partials(plan, dp.ArraySource(A, Y))
        del parts[2], parts[3], parts[6]
        res = dp.build_result(plan, parts)
        assert isinstance(res, dp.DegradedSketchResult)
        assert res.degraded
        assert res.rows_merged == 40 and res.coverage == 40 / 64
        assert res.missing == ((20, 40), (60, 64))
        assert res.shards == 7 and res.shards_merged == 4
        # the surviving-rows sketch is still a valid sketch: equal to
        # the one-shot apply of the surviving rows zeroed-out data
        mask = np.ones(N, bool)
        mask[20:40] = mask[60:64] = False
        ref = np.asarray(_plan("cwt")._transform().apply(
            jnp.asarray(np.where(mask[:, None], A, 0.0)),
            sk.COLUMNWISE))
        np.testing.assert_allclose(res.SX, ref, atol=1e-4)

    def test_min_coverage_gate(self, data):
        A, Y = data
        plan = _plan("cwt")
        parts = _partials(plan, dp.ArraySource(A, Y))
        del parts[5]
        res = dp.build_result(plan, parts)
        assert res.require(0.8) is res
        with pytest.raises(sk_errors.SketchCoverageError) as ei:
            res.require(1.0)
        assert ei.value.coverage == res.coverage
        assert ei.value.missing == ((50, 60),)

    def test_merge_fault_site(self, data):
        A, Y = data
        plan = _plan("cwt")
        parts = _partials(plan, dp.ArraySource(A, Y))
        with faults.fault_plan({"seed": 1, "faults": [
                {"site": "dist.merge", "error": "SketchError"}]}):
            with pytest.raises(sk_errors.SketchError):
                dp.merge_partials(plan, parts)


# ---------------------------------------------------------------------------
# ingest: grid alignment + resume-at-consumed-offset
# ---------------------------------------------------------------------------


class _FlakyOnce(dp.ShardSource):
    """Wraps a source; the first ``read`` raises after ``ok_batches``
    yields — the transient-mid-shard transport failure. Records every
    read's start offset so the test can assert the resume point."""

    def __init__(self, inner, ok_batches):
        self.inner = inner
        self.n, self.d, self.targets = (inner.n, inner.d,
                                        inner.targets)
        self.ok_batches = ok_batches
        self.read_offsets = []
        self._tripped = False

    def read(self, lo, hi):
        self.read_offsets.append(lo)
        it = self.inner.read(lo, hi)
        for k, item in enumerate(it):
            if not self._tripped and k == self.ok_batches:
                self._tripped = True
                raise sk_errors.IOError_("injected transport loss")
            yield item


class TestIngest:
    def test_resume_at_consumed_offset(self, data):
        A, _ = data
        plan = _plan("cwt", targets=0, shard_rows=20)
        flaky = _FlakyOnce(dp.ArraySource(A, batch_rows=4), 2)
        out = dp.compute_shard(plan, 1, flaky)
        ref = dp.compute_shard(plan, 1, dp.ArraySource(A,
                                                       batch_rows=4))
        assert np.array_equal(out["SX"], ref["SX"])
        # first read started at the shard base; the retry re-entered
        # at the consumed offset (2 batches in), not from scratch
        assert flaky.read_offsets == [20, 28]

    def test_ingest_fault_site_resumes(self, data):
        A, _ = data
        plan = _plan("cwt", targets=0, shard_rows=20)
        src = dp.ArraySource(A, batch_rows=4)
        with faults.fault_plan({"seed": 1, "faults": [
                {"site": "dist.ingest", "error": "IOError_",
                 "on_hit": 3}]}) as p:
            out = dp.compute_shard(plan, 0, src)
        assert [f[0] for f in p.fired] == ["dist.ingest"]
        ref = dp.compute_shard(plan, 0, src)
        assert np.array_equal(out["SX"], ref["SX"])

    def test_short_source_raises_after_retries(self, data):
        """A stream that ends before the shard bound must surface (no
        fabricated rows) — after the retry ladder gave a reconnect its
        shot."""
        A, _ = data
        plan = _plan("cwt", targets=0)
        reads = []

        class Short(dp.ShardSource):
            n, d, targets = N, D, 0

            def read(self, lo, hi):
                reads.append(lo)
                yield lo, A[lo:hi - 2], None

        from libskylark_tpu.resilience.policy import RetryPolicy

        with pytest.raises(sk_errors.IOError_):
            dp.compute_shard(plan, 0, Short(), retry=RetryPolicy(
                max_attempts=3, base_delay=0.0, max_delay=0.0,
                jitter="none", sleep=lambda s: None))
        assert len(reads) == 3      # the ladder re-entered, then gave up

    def test_grid_spans_absolute(self):
        assert list(dp._grid_spans(0, 10, 4)) == [(0, 4), (4, 8),
                                                  (8, 10)]
        # a resumed read (lo = prior batch end) keeps the boundaries
        assert list(dp._grid_spans(4, 10, 4)) == [(4, 8), (8, 10)]
        assert list(dp._grid_spans(3, 10, 4)) == [(3, 4), (4, 8),
                                                  (8, 10)]
        assert list(dp._grid_spans(3, 10, 0)) == [(3, 10)]


class TestFileSources:
    def test_hdf5_source_matches_array(self, data, tmp_path):
        h5py = pytest.importorskip("h5py")
        A, Y = data
        path = str(tmp_path / "rows.h5")
        with h5py.File(path, "w") as f:
            f["X"] = A
            f["Y"] = Y
        src = dp.HDF5Source.probe(path, batch_rows=10)
        assert (src.n, src.d, src.targets) == (N, D, TARGETS)
        plan = _plan("cwt")
        res = dp.sketch_local(plan, src)
        ref = dp.sketch_local(plan, dp.ArraySource(A, Y,
                                                   batch_rows=10))
        assert np.array_equal(res.SX, ref.SX)
        assert np.array_equal(res.SY, ref.SY)

    def test_libsvm_source_range_reads(self, tmp_path):
        rng = np.random.default_rng(3)
        Araw = rng.integers(1, 5, size=(N, D)).astype(np.float32)
        y = rng.integers(0, 2, size=N)
        path = tmp_path / "rows.svm"
        with open(path, "w") as f:
            for i in range(N):
                feats = " ".join(f"{j + 1}:{Araw[i, j]:.1f}"
                                 for j in range(D))
                f.write(f"{y[i]} {feats}\n")
        src = dp.LibsvmSource(path=str(path), n=N, d=D, targets=1,
                              batch_rows=10)
        got = np.concatenate([X for _, X, _ in src.read(15, 40)])
        assert np.array_equal(got, Araw[15:40])
        plan = dp.ShardPlan(kind="cwt", n=N, s_dim=S_DIM, d=D, seed=5,
                            targets=1, shard_rows=10)
        res = dp.sketch_local(plan, src)
        ref = dp.sketch_local(
            plan, dp.ArraySource(Araw, y.astype(np.float32),
                                 batch_rows=10))
        assert np.array_equal(res.SX, ref.SX)
        assert np.array_equal(res.SY, ref.SY)


# ---------------------------------------------------------------------------
# coordinator: fleet dispatch, retries, reassignment, hedging
# ---------------------------------------------------------------------------


@pytest.fixture()
def thread_pool():
    from libskylark_tpu import fleet

    pool = fleet.ReplicaPool(2, max_batch=4)
    yield pool
    pool.shutdown()


class TestCoordinator:
    def test_fleet_bit_equal_to_local(self, data, thread_pool):
        A, Y = data
        plan = _plan("jlt")
        src = dp.ArraySource(A, Y)
        ref = dp.sketch_local(plan, src)
        co = dist.DistSketchCoordinator(thread_pool)
        res = co.sketch(plan, src)
        assert np.array_equal(res.SX, ref.SX)
        assert np.array_equal(res.SY, ref.SY)
        st = co.stats()
        assert st["dispatched"] == plan.num_shards
        assert sum(st["by_replica"].values()) == plan.num_shards
        assert len(st["by_replica"]) == 2   # both replicas drew work

    def test_local_mode_no_fleet(self, data):
        A, Y = data
        plan = _plan("srht")
        src = dp.ArraySource(A, Y)
        co = dist.DistSketchCoordinator()
        res = co.sketch(plan, src)
        ref = dp.sketch_local(plan, src)
        assert np.array_equal(res.SX, ref.SX)

    def test_injected_faults_retry_and_reassign(self, data,
                                                thread_pool):
        A, Y = data
        plan = _plan("cwt")
        src = dp.ArraySource(A, Y)
        ref = dp.sketch_local(plan, src)
        co = dist.DistSketchCoordinator(thread_pool, retries=3,
                                        max_inflight=1)
        with faults.fault_plan({"seed": 7, "faults": [
                {"site": "dist.shard", "error": "IOError_",
                 "every": 3}]}) as p:
            res = co.sketch(plan, src)
        assert p.fired and res.coverage == 1.0
        assert np.array_equal(res.SX, ref.SX)
        st = co.stats()
        assert st["retried"] == len(p.fired)
        assert st["reassigned"] >= 1 and st["abandoned"] == 0

    def test_exhausted_budget_gates_and_degrades(self, data,
                                                 thread_pool):
        A, Y = data
        plan = _plan("cwt")
        src = dp.ArraySource(A, Y)
        kill = {"seed": 7, "faults": [
            {"site": "dist.shard", "error": "IOError_", "after": 2}]}
        co = dist.DistSketchCoordinator(thread_pool, retries=1,
                                        max_inflight=1)
        with faults.fault_plan(kill):
            with pytest.raises(sk_errors.SketchCoverageError):
                co.sketch(plan, src)           # default gate 1.0
        co2 = dist.DistSketchCoordinator(thread_pool, retries=1,
                                         max_inflight=1)
        with faults.fault_plan(kill):
            res = co2.sketch(plan, src, min_coverage=0.2)
        assert isinstance(res, dp.DegradedSketchResult)
        assert res.rows_merged == 20 and res.missing == ((20, 64),)
        assert co2.stats()["abandoned"] == 5

    def test_logic_errors_propagate_immediately(self, data,
                                                thread_pool):
        A, Y = data
        plan = _plan("cwt", n=N * 2)        # source too small
        co = dist.DistSketchCoordinator(thread_pool)
        with pytest.raises(sk_errors.InvalidParametersError):
            co.sketch(plan, dp.ArraySource(A, Y))

    def test_hedge_rescues_straggler(self, data, thread_pool):
        import time as _time

        A, Y = data
        plan = _plan("cwt")
        src = dp.ArraySource(A, Y)
        ref = dp.sketch_local(plan, src)
        co = dist.DistSketchCoordinator(thread_pool, retries=2,
                                        hedge=True,
                                        hedge_delay_s=0.25)
        t0 = _time.monotonic()
        with faults.fault_plan({"seed": 7, "faults": [
                {"site": "dist.shard", "stall_s": 20.0,
                 "on_hit": 1}]}):
            res = co.sketch(plan, src)
        assert _time.monotonic() - t0 < 15.0
        assert co.stats()["hedged"] == 1
        assert np.array_equal(res.SX, ref.SX)

    def test_hedge_twins_completing_together(self, data):
        """Primary and mirror resolving within one wait window must
        not crash the loop (regression: the winner purges its twin
        from the tracking map while the twin still sits in the done
        set)."""
        from concurrent.futures import Future

        A, Y = data
        plan = _plan("cwt", shard_rows=64)      # one shard
        src = dp.ArraySource(A, Y)
        ref = dp.sketch_local(plan, src)
        pending = []

        class FakeReplica:
            def __init__(self, name):
                self.name = name

            def state(self):
                return "SERVING"

            def shard(self, task):
                fut = Future()
                if not pending:
                    pending.append((fut, task))     # primary: stall
                else:
                    # the mirror: resolve BOTH twins at once, so both
                    # land in the same wait round's done set
                    out = dp.execute_task(task)
                    pfut, _ = pending[0]
                    pfut.set_result(out)
                    fut.set_result(out)
                return fut

        co = dist.DistSketchCoordinator(
            replicas=[FakeReplica("a"), FakeReplica("b")],
            retries=1, hedge=True, hedge_delay_s=0.05)
        res = co.sketch(plan, src)
        assert np.array_equal(res.SX, ref.SX)
        assert co.stats()["hedged"] == 1

    def test_env_knob_defaults(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_DIST_RETRIES", "9")
        monkeypatch.setenv("SKYLARK_DIST_MIN_COVERAGE", "0.5")
        monkeypatch.setenv("SKYLARK_DIST_HEDGE", "1")
        monkeypatch.setenv("SKYLARK_DIST_HEDGE_DELAY_MS", "250")
        co = dist.DistSketchCoordinator()
        assert co.retries == 9
        assert co.min_coverage == 0.5
        assert co.hedge is True and co.hedge_delay_s == 0.25

    def test_lifetime_collector(self, data):
        A, Y = data
        before = dist.dist_stats()
        co = dist.DistSketchCoordinator()
        co.sketch(_plan("cwt"), dp.ArraySource(A, Y))
        after = dist.dist_stats()
        assert after["dispatched"] >= before["dispatched"] + 7
        assert after["merges"] == before["merges"] + 1
        assert after["last_coverage"] == 1.0


# ---------------------------------------------------------------------------
# sketch-size-communication algorithms
# ---------------------------------------------------------------------------


class TestAlgorithms:
    def test_randomized_svd_recovers_spectrum(self):
        rng = np.random.default_rng(4)
        U = np.linalg.qr(rng.standard_normal((256, 4)))[0]
        V = np.linalg.qr(rng.standard_normal((12, 4)))[0]
        svals = np.array([10.0, 6.0, 3.0, 1.0])
        A = (U * svals) @ V.T
        out = dist.randomized_svd(
            dp.ArraySource(A.astype(np.float32)), rank=4, s_dim=64,
            seed=3, shard_rows=64)
        assert out["coverage"] == 1.0 and not out["degraded"]
        np.testing.assert_allclose(out["singular_values"], svals,
                                   rtol=0.2)

    def test_sketched_lstsq_recovers_coef(self):
        rng = np.random.default_rng(5)
        X = rng.standard_normal((512, 6)).astype(np.float32)
        w = rng.standard_normal((6, 1)).astype(np.float32)
        src = dp.ArraySource(X, X @ w)
        out = dist.sketched_lstsq(src, s_dim=128, seed=3,
                                  shard_rows=128)
        assert out["coverage"] == 1.0
        np.testing.assert_allclose(out["coef"], w, atol=5e-2)

    def test_degraded_svd_reports_coverage(self, thread_pool):
        rng = np.random.default_rng(6)
        A = rng.standard_normal((64, D)).astype(np.float32)
        co = dist.DistSketchCoordinator(thread_pool, retries=0,
                                        max_inflight=1)
        with faults.fault_plan({"seed": 7, "faults": [
                {"site": "dist.shard", "error": "IOError_",
                 "on_hit": 3}]}):
            out = dist.randomized_svd(
                dp.ArraySource(A), rank=2, s_dim=8, seed=3,
                shard_rows=16, coordinator=co, min_coverage=0.5)
        assert out["degraded"] and out["coverage"] == 48 / 64
        assert out["missing"] == [(32, 48)]

    def test_lstsq_requires_targets(self):
        with pytest.raises(sk_errors.InvalidParametersError):
            dist.sketched_lstsq(
                dp.ArraySource(np.zeros((8, 2), np.float32)), s_dim=4)


# ---------------------------------------------------------------------------
# process replicas: the real preemption domain (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestProcessReplicaE2E:
    def test_crash_mid_storm_reassigns_bit_equal(self, data):
        import json as _json

        from libskylark_tpu import fleet

        A, Y = data
        plan = _plan("cwt")
        src = dp.ArraySource(A, Y)
        ref = dp.sketch_local(plan, src)
        crash = _json.dumps({"seed": 7, "faults": [
            {"site": "dist.shard", "crash": True, "on_hit": 2}]})

        def victim_env(name):
            return ({"SKYLARK_FAULT_PLAN": crash}
                    if name == "r0" else None)

        pool = fleet.ReplicaPool(2, backend="process", max_batch=4,
                                 replica_env=victim_env)
        try:
            co = dist.DistSketchCoordinator(pool, retries=3)
            res = co.sketch(plan, src)
            assert np.array_equal(res.SX, ref.SX)
            assert np.array_equal(res.SY, ref.SY)
            assert res.coverage == 1.0
            assert pool.crashed_names() == ["r0"]
            st = co.stats()
            assert st["reassigned"] >= 1 and st["abandoned"] == 0
        finally:
            pool.shutdown()


# ---------------------------------------------------------------------------
# merge-order invariance across execution paths (the property the
# chaos gates lean on): local, fleet, permuted — one bit pattern
# ---------------------------------------------------------------------------


def test_all_paths_one_bit_pattern(data):
    A, Y = data
    plan = _plan("cwt")
    src = dp.ArraySource(A, Y)
    ref = dp.sketch_local(plan, src)
    parts = _partials(plan, src)
    for perm in itertools.islice(
            itertools.permutations(list(parts)), 0, 24, 7):
        got = dp.merge_partials(plan, {k: parts[k] for k in perm})
        assert np.array_equal(got["SX"], ref.SX)
        assert np.array_equal(got["SY"], ref.SY)
