"""Pipelined dist-serve endpoints (libskylark_tpu/dist/serve,
docs/distributed).

The contract under test: ``submit_dist_sketch`` / ``submit_dist_lstsq``
/ ``submit_dist_svd`` fan shard tasks through the fleet (or a private
local pool) and merge partials incrementally AS THEY LAND, with

- full-coverage bits equal to the one-shot ``sketch_local`` reference,
  across every arrival order and merge fan-in (the eager tree IS the
  canonical ``merge_partials`` tree);
- per-class ``min_coverage`` SLOs: an interactive request may resolve
  early with a quantified ``DegradedSketchResult`` (exact missing
  ranges), its standard-class twin blocks for 1.0 and raises
  ``SketchCoverageError`` when a shard is lost for good;
- retries/hedges billed to the owning tenant's token bucket (first
  attempts free; quota exhaustion degrades the job, never crashes it);
- degraded results staying OUT of the content-addressed result cache,
  and gates riding the request digest (a 0.9-gated and a 1.0-gated twin
  never share a flight or cache entry);
- ``dist.shard_task`` spans parented under the originating
  ``serve.submit`` request id, and the stats/metrics rollups
  (``dist.shard_tasks`` by_replica, ``dist_serve_stats``,
  ``engine.serve_stats()["dist"]``).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from libskylark_tpu import engine, fleet, telemetry
from libskylark_tpu.base import env as sk_env
from libskylark_tpu.base import errors as sk_errors
from libskylark_tpu.dist import plan as dp
from libskylark_tpu.dist import serve as dserve
from libskylark_tpu.dist.coordinator import DistSketchCoordinator
from libskylark_tpu.qos import tenants as qtenants
from libskylark_tpu.telemetry import metrics as tmetrics
from libskylark_tpu.telemetry import trace as ttrace

N, D, S_DIM, TARGETS = 120, 8, 16, 2
SHARD_ROWS = 12          # 10 shards of 12 rows
POISON = (108, 120)      # the last shard — see _PoisonSource


@pytest.fixture(scope="module")
def data():
    # integer-valued float32: every partial sum is exact, so merged
    # bits never depend on association even across DIFFERENT tree
    # shapes (degraded-vs-zeroed-oracle comparisons below)
    rng = np.random.default_rng(23)
    X = rng.integers(-8, 9, size=(N, D)).astype(np.float32)
    Y = rng.integers(-8, 9, size=(N, TARGETS)).astype(np.float32)
    return X, Y


def _plan(kind, **kw):
    base = dict(kind=kind, n=N, s_dim=S_DIM, d=D, seed=5,
                shard_rows=SHARD_ROWS)
    base.update(kw)
    return dp.ShardPlan(**base).validate()


def _partials(plan, src):
    return {i: dp.compute_shard(plan, i, src)
            for i, _, _ in plan.shards()}


class _PoisonSource(dp.ArraySource):
    """In-memory rows whose ``[fail_lo, fail_hi)`` range permanently
    fails to read with a retryable error — the shard that covers it can
    never settle, on any replica, ever. Overrides ``subrange`` so the
    poison survives the per-task slicing of the dispatch path."""

    def __init__(self, X, Y=None, batch_rows=0, offset=0,
                 fail=(0, 0)):
        super().__init__(X, Y, batch_rows=batch_rows, offset=offset)
        self._fail = tuple(fail)

    def subrange(self, lo, hi):
        base = super().subrange(lo, hi)
        return _PoisonSource(base._X, base._Y,
                             batch_rows=base.batch_rows, offset=lo,
                             fail=self._fail)

    def read(self, lo, hi):
        flo, fhi = self._fail
        if lo < fhi and hi > flo:
            raise OSError(f"poisoned rows [{flo}, {fhi})")
        return super().read(lo, hi)


@pytest.fixture
def executor():
    engine.reset()
    ex = engine.MicrobatchExecutor(max_batch=4, cache=True)
    yield ex
    ex.shutdown()


# ---------------------------------------------------------------------------
# the incremental merger: eager canonical tree
# ---------------------------------------------------------------------------


class TestIncrementalMerger:
    @pytest.mark.parametrize("kind", dp.KINDS)
    def test_full_coverage_bit_equal_any_order(self, kind, data):
        X, Y = data
        if kind == "srht":                 # WHT needs a pow2 extent
            rng = np.random.default_rng(29)
            X = rng.integers(-8, 9, size=(128, D)).astype(np.float32)
            Y = rng.integers(-8, 9,
                             size=(128, TARGETS)).astype(np.float32)
            plan = _plan(kind, n=128, shard_rows=16, targets=TARGETS)
        else:
            plan = _plan(kind, targets=TARGETS)
        src = dp.ArraySource(X, Y)
        ref = dp.sketch_local(plan, src)
        parts = _partials(plan, src)
        orders = [sorted(parts), sorted(parts, reverse=True),
                  random.Random(3).sample(sorted(parts), len(parts))]
        for order in orders:
            m = dserve.IncrementalMerger(plan)
            for i in order:
                m.add(i, parts[i])
            res = m.result()
            assert res.coverage == 1.0 and not res.degraded
            assert np.array_equal(res.SX, ref.SX)
            assert np.array_equal(res.SY, ref.SY)

    def test_tree_shape_and_fanin_neutrality(self, data):
        X, _ = data
        plan = _plan("cwt")
        parts = _partials(plan, dp.ArraySource(X))
        results = []
        for fanin in (1, 64):
            m = dserve.IncrementalMerger(plan, fanin=fanin)
            for i in parts:
                m.add(i, parts[i])
            results.append(m.result())
            # 10 leaves: 9 pairwise combines, ceil(log2(10)) levels
            assert m.merge_ops == plan.num_shards - 1
            assert m.depth == 4
        assert np.array_equal(results[0].SX, results[1].SX)

    def test_duplicate_add_is_idempotent(self, data):
        X, _ = data
        plan = _plan("cwt")
        src = dp.ArraySource(X)
        parts = _partials(plan, src)
        m = dserve.IncrementalMerger(plan)
        for i in parts:
            m.add(i, parts[i])
            m.add(i, parts[i])         # a settled hedge twin
        res = m.result()
        assert res.rows_merged == N and res.coverage == 1.0
        assert np.array_equal(res.SX, dp.sketch_local(plan, src).SX)

    @pytest.mark.parametrize("kind", ["cwt", "ust"])
    def test_degraded_merge_is_canonical_over_survivors(self, kind,
                                                        data):
        X, _ = data
        plan = _plan(kind)
        parts = _partials(plan, dp.ArraySource(X))
        kept = {i: p for i, p in parts.items() if i != 4}
        m = dserve.IncrementalMerger(plan)
        for i in kept:
            m.add(i, kept[i])
        res = m.result()
        assert isinstance(res, dp.DegradedSketchResult)
        assert res.coverage == (N - SHARD_ROWS) / N
        assert res.missing == ((48, 60),)
        assert np.array_equal(res.SX,
                              dp.merge_partials(plan, kept)["SX"])

    def test_degraded_equals_zeroed_source_oracle(self, data):
        # the satellite-3 identity: a merge missing the TAIL shard is
        # bit-equal to the one-shot sketch of the same rows with the
        # missing range zeroed (the zero partial adds exactly and the
        # canonical trees coincide)
        X, _ = data
        plan = _plan("cwt")
        parts = _partials(plan, dp.ArraySource(X))
        m = dserve.IncrementalMerger(plan)
        for i in parts:
            if i != plan.num_shards - 1:
                m.add(i, parts[i])
        res = m.result()
        Xz = X.copy()
        Xz[POISON[0]:POISON[1]] = 0
        oracle = dp.sketch_local(plan, dp.ArraySource(Xz))
        assert res.degraded and res.missing == (POISON,)
        assert np.array_equal(res.SX, oracle.SX)


# ---------------------------------------------------------------------------
# executor endpoints
# ---------------------------------------------------------------------------


class TestExecutorEndpoints:
    def test_sketch_bit_equal_and_accounted(self, executor, data):
        X, _ = data
        plan = _plan("jlt")
        src = dp.ArraySource(X)
        ref = dp.sketch_local(plan, src)
        c0 = engine.stats().compiles
        res = executor.submit_dist_sketch(plan, src).result(timeout=120)
        assert res.coverage == 1.0 and not res.degraded
        assert np.array_equal(res.SX, ref.SX)
        # shard tasks never touch the solver's executable cache
        assert engine.stats().compiles == c0
        d = executor.stats()["dist"]
        assert d["jobs"] == 1 and d["completed"] == 1
        assert d["by_replica"]["<local>"]["shard_tasks"] \
            == plan.num_shards

    def test_identical_resubmit_hits_result_cache(self, executor,
                                                  data):
        X, _ = data
        plan = _plan("cwt")
        src = dp.ArraySource(X)
        r1 = executor.submit_dist_sketch(plan, src).result(timeout=120)
        r2 = executor.submit_dist_sketch(plan, src).result(timeout=120)
        assert np.array_equal(r1.SX, r2.SX)
        d = executor.stats()["dist"]
        assert d["jobs"] == 2 and d["completed"] == 1   # one ran
        assert not r2.SX.flags.writeable      # shared, so frozen

    def test_lstsq_endpoint_matches_local_factor(self, executor,
                                                 data):
        X, Y = data
        from libskylark_tpu.dist.algorithms import lstsq_plan

        src = dp.ArraySource(X, Y)
        out = executor.submit_dist_lstsq(
            src, s_dim=S_DIM, seed=5, kind="cwt",
            shard_rows=SHARD_ROWS).result(timeout=120)
        plan = lstsq_plan(src, s_dim=S_DIM, seed=5, kind="cwt",
                          shard_rows=SHARD_ROWS)
        ref = dserve.solve_lstsq(dp.sketch_local(plan, src))
        assert out["coverage"] == 1.0 and not out["degraded"]
        assert out["missing"] == []
        np.testing.assert_allclose(out["coef"], ref["coef"],
                                   rtol=1e-5, atol=1e-5)

    def test_svd_endpoint_matches_local_factor(self, executor, data):
        X, _ = data
        from libskylark_tpu.dist.algorithms import svd_plan

        src = dp.ArraySource(X)
        rank = 3
        out = executor.submit_dist_svd(
            src, rank, seed=5, shard_rows=SHARD_ROWS).result(
                timeout=120)
        plan = svd_plan(src, rank, seed=5, shard_rows=SHARD_ROWS)
        ref = dserve.solve_svd(dp.sketch_local(plan, src), rank)
        assert out["singular_values"].shape == (rank,)
        assert out["Vt"].shape == (rank, D)
        np.testing.assert_allclose(out["singular_values"],
                                   ref["singular_values"],
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# router endpoints over a live thread fleet
# ---------------------------------------------------------------------------


class TestRouterFleet:
    @pytest.fixture
    def router(self):
        engine.reset()
        pool = fleet.ReplicaPool(2, backend="thread")
        router = fleet.Router(pool)
        yield router
        router.close()
        pool.shutdown()

    def test_fleet_fanout_bit_equal(self, router, data):
        X, _ = data
        plan = _plan("cwt")
        src = dp.ArraySource(X)
        ref = dp.sketch_local(plan, src)
        res = router.submit_dist_sketch(plan, src).result(timeout=120)
        assert res.coverage == 1.0
        assert np.array_equal(res.SX, ref.SX)
        rs = router.stats()
        assert rs["dist_jobs"] == 1
        co = rs["dist_coordinator"]
        assert co["dispatched"] == plan.num_shards
        # every shard landed on a fleet member, none fell back local
        assert set(co["by_replica"]) <= {"r0", "r1"}
        assert sum(co["by_replica"].values()) == plan.num_shards


# ---------------------------------------------------------------------------
# per-class coverage SLOs + tenant billing (docs/qos)
# ---------------------------------------------------------------------------


class TestDegradedQoS:
    def test_interactive_resolves_degraded_with_exact_missing(
            self, executor, data):
        X, _ = data
        plan = _plan("cwt")
        src = _PoisonSource(X, fail=POISON)
        res = executor.submit_dist_sketch(
            plan, src, qos_class="interactive", min_coverage=0.9,
            coordinator=DistSketchCoordinator(retries=1)).result(
                timeout=120)
        assert isinstance(res, dp.DegradedSketchResult)
        assert res.coverage == 0.9
        assert res.missing == (POISON,)
        # quantified AND exact: the surviving rows' sketch is bit-equal
        # to the one-shot sketch with the lost range zeroed
        Xz = X.copy()
        Xz[POISON[0]:POISON[1]] = 0
        oracle = dp.sketch_local(plan, dp.ArraySource(Xz))
        assert np.array_equal(res.SX, oracle.SX)

    def test_standard_twin_blocks_for_full_coverage(self, executor,
                                                    data):
        X, _ = data
        plan = _plan("cwt")
        src = _PoisonSource(X, fail=POISON)
        fut = executor.submit_dist_sketch(
            plan, src, qos_class="standard",
            coordinator=DistSketchCoordinator(retries=1))
        with pytest.raises(sk_errors.SketchCoverageError):
            fut.result(timeout=120)
        assert executor.stats()["dist"]["failed"] == 1

    def test_degraded_result_never_enters_the_cache(self, executor,
                                                    data):
        X, _ = data
        plan = _plan("cwt")
        src = _PoisonSource(X, fail=POISON)
        kw = dict(qos_class="interactive", min_coverage=0.9)
        r1 = executor.submit_dist_sketch(
            plan, src,
            coordinator=DistSketchCoordinator(retries=1),
            **kw).result(timeout=120)
        assert r1.degraded
        r2 = executor.submit_dist_sketch(
            plan, src,
            coordinator=DistSketchCoordinator(retries=1),
            **kw).result(timeout=120)
        assert r2.degraded
        # both jobs RAN (no cached degraded bits were replayed)
        d = executor.stats()["dist"]
        assert d["jobs"] == 2 and d["completed"] == 2

    def test_class_gate_env_knob(self, executor, data, monkeypatch):
        monkeypatch.setenv(
            "SKYLARK_DIST_SERVE_MIN_COVERAGE_INTERACTIVE", "0.9")
        assert dserve.class_min_coverage("interactive") == 0.9
        assert dserve.class_min_coverage("standard") == 1.0
        assert dserve.class_min_coverage("no-such-class") == 1.0
        X, _ = data
        plan = _plan("cwt")
        src = _PoisonSource(X, fail=POISON)
        res = executor.submit_dist_sketch(
            plan, src, qos_class="interactive",
            coordinator=DistSketchCoordinator(retries=1)).result(
                timeout=120)
        assert res.degraded and res.coverage == 0.9

    def test_retries_billed_quota_degrades_not_crashes(self, data):
        X, _ = data
        reg = qtenants.TenantRegistry()
        # bucket of 2: the front-door admission takes one, the first
        # re-execution of the poisoned shard takes the other; the
        # second re-execution is refused and the shard abandons.
        # standard class (not interactive) so no early resolve races
        # the retry ladder — the billing sequence is deterministic
        reg.register("acme", "standard", rate=1e-9, burst=2.0)
        engine.reset()
        ex = engine.MicrobatchExecutor(max_batch=4, cache=False,
                                       tenants=reg)
        try:
            plan = _plan("cwt")
            src = _PoisonSource(X, fail=POISON)
            ss0 = dserve.dist_serve_stats()
            res = ex.submit_dist_sketch(
                plan, src, tenant="acme", min_coverage=0.9,
                coordinator=DistSketchCoordinator(retries=4)).result(
                    timeout=120)
            assert res.degraded and res.coverage == 0.9
            ss1 = dserve.dist_serve_stats()
            assert ss1["retries_billed"] - ss0["retries_billed"] == 1
            assert ss1["quota_stopped"] - ss0["quota_stopped"] == 1
            # the bucket is empty: the NEXT request is refused at the
            # front door, before any shard work
            with pytest.raises(sk_errors.TenantQuotaError):
                ex.submit_dist_sketch(plan, dp.ArraySource(X),
                                      tenant="acme")
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# observability: spans, metrics, rollups (docs/observability)
# ---------------------------------------------------------------------------


class TestObservability:
    @pytest.fixture
    def tracing(self):
        was = telemetry.enabled()
        telemetry.set_enabled(True)
        ttrace.clear_finished()
        yield
        telemetry.set_enabled(was)

    def test_shard_spans_parented_under_submit(self, tracing,
                                               executor, data):
        X, _ = data
        plan = _plan("cwt")
        executor.submit_dist_sketch(
            plan, dp.ArraySource(X)).result(timeout=120)
        spans = ttrace.finished_spans()
        submits = [s for s in spans if s.name == "serve.submit"
                   and s.attrs.get("endpoint") == "dist_sketch"]
        assert len(submits) == 1
        root = submits[0]
        assert root.request_id and root.request_id.startswith("req-")
        shard = [s for s in spans if s.name == "dist.shard_task"]
        assert len(shard) == plan.num_shards
        for s in shard:
            assert s.trace_id == root.trace_id
            assert s.parent_id == root.span_id
            assert s.request_id == root.request_id
            assert s.attrs["replica"] == "<local>"
            assert s.attrs["outcome"] == "settled"

    def test_metrics_and_lifetime_rollups(self, executor, data):
        X, _ = data
        plan = _plan("cwt")
        executor.submit_dist_sketch(
            plan, dp.ArraySource(X)).result(timeout=120)
        snap = tmetrics.snapshot()
        for name in ("dist.shard_tasks", "dist.merge_depth",
                     "dist.jobs", "dist.early_resolves"):
            assert name in snap["metrics"]
        ss = snap["collectors"]["dist_serve"]
        assert ss["jobs"] >= 1 and ss["shard_tasks"] >= plan.num_shards
        assert ss["by_replica"].get("<local>", 0) >= plan.num_shards
        assert ss["merge_depth_peak"] >= 1
        assert ss["last_coverage"] == 1.0
        agg = engine.serve_stats()
        assert agg["dist"]["jobs"] >= 1
        assert agg["dist"]["by_replica"]["<local>"]["shard_tasks"] \
            >= plan.num_shards
        life = agg["dist"]["lifetime"]
        assert life["serve"]["jobs"] >= 1
        assert "coordinator" in life


# ---------------------------------------------------------------------------
# env knobs (docs/env_vars table)
# ---------------------------------------------------------------------------


class TestEnvKnobs:
    def test_defaults_and_propagation(self, monkeypatch):
        for var in ("SKYLARK_DIST_SERVE_PIPELINE",
                    "SKYLARK_DIST_SERVE_MERGE_FANIN",
                    "SKYLARK_DIST_SERVE_MIN_COVERAGE_INTERACTIVE",
                    "SKYLARK_DIST_SERVE_MIN_COVERAGE_STANDARD",
                    "SKYLARK_DIST_SERVE_MIN_COVERAGE_BEST_EFFORT"):
            monkeypatch.delenv(var, raising=False)
            # replica children must see the same gates as the parent
            assert sk_env.REGISTRY[var].propagate
        assert sk_env.DIST_SERVE_PIPELINE.get() == 0
        assert sk_env.DIST_SERVE_MERGE_FANIN.get() == 8
        for cls in qtenants.CLASSES:
            assert dserve.class_min_coverage(cls) == 1.0

    def test_pipeline_depth_bounds_inflight(self, executor, data):
        X, _ = data
        plan = _plan("cwt")
        res = executor.submit_dist_sketch(
            plan, dp.ArraySource(X), pipeline=1).result(timeout=120)
        assert res.coverage == 1.0
        assert np.array_equal(
            res.SX, dp.sketch_local(plan, dp.ArraySource(X)).SX)
