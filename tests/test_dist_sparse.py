"""Oracle tests for the mesh-distributed sparse layer (P4/P5).

The reference runs its distributed-sparse tests at np∈{1,4,5,7} to force
ragged (non-dividing) layouts (ref: tests/unit/CMakeLists.txt:31-33,
DistSparseTest.cpp, SparseSketchApplyCombBLASTest.cpp). Here the analog:
the same matrix distributed on a 1D 8-device mesh, a 2D (2,4) grid, and a
ragged 5-device submesh must produce products and sketch applies that
match the local computation elementwise (≤1e-4, the determinism oracle —
ref: tests/unit/test_utils.hpp:48)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from libskylark_tpu import parallel as par
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.dist_sparse import distribute_sparse
from libskylark_tpu.base.sparse import SparseMatrix, spmm, spmm_t
from libskylark_tpu.sketch import CWT, MMT, WZT, JLT, CT, ROWWISE, COLUMNWISE

ATOL = 1e-4


def _rand_sparse(h, w, density=0.08, seed=0) -> SparseMatrix:
    rng = np.random.default_rng(seed)
    A = sp.random(h, w, density=density, random_state=rng, format="csc",
                  dtype=np.float32)
    return SparseMatrix.from_scipy(A)


@pytest.fixture()
def mesh5(devices):
    """Ragged 5-device submesh — the np=5 discipline."""
    return par.make_mesh(devices=devices[:5])


def _grids(mesh1d, mesh2d, mesh5):
    return [
        (mesh1d, dict(row_axis="rows")),
        (mesh1d, dict(col_axis="rows")),
        (mesh2d, dict(row_axis="rows", col_axis="cols")),
        (mesh5, dict(row_axis="rows")),
    ]


# ---------------------------------------------------------------------------
# container + products
# ---------------------------------------------------------------------------


def test_roundtrip_to_local(mesh1d, mesh2d, devices):
    A = _rand_sparse(53, 37, seed=1)
    for mesh, axes in [(mesh1d, dict(row_axis="rows")),
                       (mesh2d, dict(row_axis="rows", col_axis="cols"))]:
        D = distribute_sparse(A, mesh, **axes)
        B = D.to_local()
        np.testing.assert_allclose(
            B.to_scipy().toarray(), A.to_scipy().toarray(), atol=0
        )


def test_todense_matches(mesh2d):
    A = _rand_sparse(45, 30, seed=2)
    D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
    np.testing.assert_allclose(
        np.asarray(D.todense()), A.to_scipy().toarray(), atol=0
    )


@pytest.mark.parametrize("hw", [(64, 48), (53, 41)])
@pytest.mark.slow
def test_spmm_oracle(hw, mesh1d, mesh2d, devices):
    h, w = hw
    A = _rand_sparse(h, w, seed=3)
    B = jnp.asarray(
        np.random.default_rng(4).standard_normal((w, 7)), jnp.float32
    )
    want = np.asarray(spmm(A, B))
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(D.spmm(B))
        np.testing.assert_allclose(got, want, atol=ATOL, err_msg=str(axes))


@pytest.mark.parametrize("hw", [(64, 48), (53, 41)])
@pytest.mark.slow
def test_spmm_t_oracle(hw, mesh1d, mesh2d, devices):
    h, w = hw
    A = _rand_sparse(h, w, seed=5)
    B = jnp.asarray(
        np.random.default_rng(6).standard_normal((h, 5)), jnp.float32
    )
    want = np.asarray(spmm_t(A, B))
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(D.spmm_t(B))
        np.testing.assert_allclose(got, want, atol=ATOL, err_msg=str(axes))


def test_spmm_vector(mesh2d):
    A = _rand_sparse(40, 33, seed=7)
    x = jnp.asarray(
        np.random.default_rng(8).standard_normal(33), jnp.float32
    )
    D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
    np.testing.assert_allclose(
        np.asarray(D.spmm(x)), np.asarray(spmm(A, x)), atol=ATOL
    )


# ---------------------------------------------------------------------------
# sketch applies: sharded-sparse vs local oracle (BASELINE config 2 shape)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("Tcls", [CWT, MMT, WZT], ids=lambda c: c.__name__)
@pytest.mark.slow
def test_hash_columnwise_dist_oracle(Tcls, mesh1d, mesh2d, devices):
    n, w, s = 100, 37, 24
    A = _rand_sparse(n, w, seed=9)
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        T = Tcls(n, s, Context(seed=17))
        want = np.asarray(T.apply(A, COLUMNWISE))
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(T.apply(D, COLUMNWISE))
        assert got.shape == want.shape
        tol = ATOL * max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=tol, err_msg=str(axes))


@pytest.mark.parametrize("Tcls", [CWT, MMT], ids=lambda c: c.__name__)
@pytest.mark.slow
def test_hash_rowwise_dist_oracle(Tcls, mesh1d, mesh2d, devices):
    m, n, s = 37, 100, 24
    A = _rand_sparse(m, n, seed=10)
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        T = Tcls(n, s, Context(seed=18))
        want = np.asarray(T.apply(A, ROWWISE))
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(T.apply(D, ROWWISE))
        assert got.shape == want.shape
        tol = ATOL * max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=tol, err_msg=str(axes))


@pytest.mark.parametrize("Tcls", [JLT, CT], ids=lambda c: c.__name__)
@pytest.mark.slow
def test_dense_rowwise_dist_oracle(Tcls, mesh1d, mesh2d, devices):
    m, n, s = 29, 300, 16
    A = _rand_sparse(m, n, seed=11)
    mesh5 = par.make_mesh(devices=devices[:5])
    # 2D grid + ragged 5-device mesh only: the per-cell virtual-panel
    # compile dominates runtime, and these two cover both code paths
    # (psum over cols / ragged 1D)
    for mesh, axes in [(mesh2d, dict(row_axis="rows", col_axis="cols")),
                       (mesh5, dict(row_axis="rows"))]:
        T = Tcls(n, s, Context(seed=19))
        want = np.asarray(T.apply(A, ROWWISE))
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(T.apply(D, ROWWISE))
        assert got.shape == want.shape
        tol = ATOL * max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=tol, err_msg=str(axes))


@pytest.mark.parametrize("Tcls", [JLT], ids=lambda c: c.__name__)
@pytest.mark.slow
def test_dense_columnwise_dist_oracle(Tcls, mesh1d, mesh2d, devices):
    n, w, s = 300, 29, 16
    A = _rand_sparse(n, w, seed=12)
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in [(mesh2d, dict(row_axis="rows", col_axis="cols")),
                       (mesh5, dict(row_axis="rows"))]:
        T = Tcls(n, s, Context(seed=20))
        want = np.asarray(T.apply(A, COLUMNWISE))
        D = distribute_sparse(A, mesh, **axes)
        got = np.asarray(T.apply(D, COLUMNWISE))
        assert got.shape == want.shape
        tol = ATOL * max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=tol, err_msg=str(axes))


@pytest.mark.parametrize("cw", [True, False], ids=["columnwise", "rowwise"])
@pytest.mark.slow
def test_hash_sparse_to_sparse_dist(cw, mesh1d, mesh2d, devices):
    """Sparse→sparse distributed hash apply (SpParMat→SpParMat analog):
    the distributed sparse result must densify to the local sparse→sparse
    apply's result."""
    from libskylark_tpu.sketch.transform import COLUMNWISE, ROWWISE as RW

    n, w, s = 100, 37, 24
    mesh5 = par.make_mesh(devices=devices[:5])
    shape = (n, w) if cw else (w, n)
    A = _rand_sparse(*shape, seed=14)
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        T = CWT(n, s, Context(seed=23))
        want = T.apply_sparse(A, COLUMNWISE if cw else RW)
        D = distribute_sparse(A, mesh, **axes)
        got = T.apply_sparse(D, COLUMNWISE if cw else RW)
        assert got.shape == want.shape
        np.testing.assert_allclose(
            np.asarray(got.todense()), want.to_scipy().toarray(),
            atol=ATOL, err_msg=str(axes),
        )


@pytest.mark.slow
def test_hash_sparse_chained_pad_bounded(mesh2d, devices):
    """Chained sparse→sparse applies must not compound padded slots by the
    merged-axis factor each round (advisor r2: re-bucket/compact after the
    cell merge). Each apply's output pad stays within ~2× the true max
    cell nnz, and the chained result still matches the local oracle."""
    from libskylark_tpu.sketch.transform import COLUMNWISE

    n, w = 120, 33
    s1, s2 = 64, 24
    A = _rand_sparse(n, w, seed=31)
    T1 = CWT(n, s1, Context(seed=41))
    T2 = CWT(s1, s2, Context(seed=42))
    want = T2.apply_sparse(T1.apply_sparse(A, COLUMNWISE), COLUMNWISE)

    D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
    mid = T1.apply_sparse(D, COLUMNWISE)
    got = T2.apply_sparse(mid, COLUMNWISE)
    for step in (mid, got):
        pad = step.v.shape[-1]
        true = max(int(jnp.max(jnp.count_nonzero(step.v, axis=-1))), 1)
        assert pad <= 2 * true, f"pad {pad} vs true max cell nnz {true}"
    np.testing.assert_allclose(
        np.asarray(got.todense()), want.to_scipy().toarray(), atol=ATOL
    )


@pytest.mark.parametrize("replace", [True, False], ids=["with", "without"])
@pytest.mark.slow
def test_ust_dist_oracle(replace, mesh1d, mesh2d, devices):
    """Row/col sampling of a distributed sparse matrix == local gather
    (incl. with-replacement duplicate slots)."""
    from libskylark_tpu.sketch import UST

    n, w, s = 100, 37, 24
    A = _rand_sparse(n, w, seed=21)
    Ar = _rand_sparse(w, n, seed=22)
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in _grids(mesh1d, mesh2d, mesh5):
        T = UST(n, s, Context(seed=31), replace=replace)
        want = np.asarray(T.apply(A, COLUMNWISE))
        got = np.asarray(T.apply(
            distribute_sparse(A, mesh, **axes), COLUMNWISE))
        np.testing.assert_allclose(got, want, atol=ATOL, err_msg=str(axes))
        wantr = np.asarray(T.apply(Ar, ROWWISE))
        gotr = np.asarray(T.apply(
            distribute_sparse(Ar, mesh, **axes), ROWWISE))
        np.testing.assert_allclose(gotr, wantr, atol=ATOL,
                                   err_msg=str(axes))


@pytest.mark.slow
def test_rft_dist_sparse_oracle(mesh2d, devices):
    """Random-feature maps on a distributed sparse input == local sparse
    apply (kernel features from sparse libsvm-style data at scale)."""
    from libskylark_tpu.sketch.rft import GaussianRFT

    m, n, s = 29, 300, 16
    A = _rand_sparse(m, n, seed=23)
    mesh5 = par.make_mesh(devices=devices[:5])
    for mesh, axes in [(mesh2d, dict(row_axis="rows", col_axis="cols")),
                       (mesh5, dict(row_axis="rows"))]:
        T = GaussianRFT(n, s, Context(seed=33), sigma=1.5)
        want = np.asarray(T.apply(A, ROWWISE))
        got = np.asarray(T.apply(
            distribute_sparse(A, mesh, **axes), ROWWISE))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=ATOL, err_msg=str(axes))
        # columnwise direction too (input transposed: sketched dim = rows)
        Ac = A.T
        wantc = np.asarray(T.apply(Ac, COLUMNWISE))
        gotc = np.asarray(T.apply(
            distribute_sparse(Ac, mesh, **axes), COLUMNWISE))
        assert gotc.shape == wantc.shape
        np.testing.assert_allclose(gotc, wantc, atol=ATOL,
                                   err_msg=str(axes))


def test_transpose(mesh2d):
    A = _rand_sparse(37, 53, seed=15)
    D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
    np.testing.assert_allclose(
        np.asarray(D.T.todense()), A.to_scipy().toarray().T, atol=0
    )


@pytest.mark.slow
def test_approximate_svd_on_dist_sparse(mesh2d):
    """Randomized SVD on sparse operands without densifying (the
    reference's sparse branch, ref: nla/skylark_svd.cpp:129-215) — local
    SparseMatrix and DistSparseMatrix must both track the dense result."""
    from libskylark_tpu.nla.svd import ApproximateSVDParams, approximate_svd

    rng = np.random.default_rng(16)
    U0 = rng.standard_normal((120, 5)).astype(np.float32)
    V0 = rng.standard_normal((5, 60)).astype(np.float32)
    mask = rng.uniform(size=(120, 60)) < 0.3
    dense = (U0 @ V0) * mask
    A = SparseMatrix.from_scipy(sp.csc_matrix(dense))
    k = 4
    p = ApproximateSVDParams(num_iterations=2)
    Ud, Sd, Vd = approximate_svd(jnp.asarray(dense), k, Context(seed=30), p)
    for operand in (A, distribute_sparse(A, mesh2d, row_axis="rows",
                                         col_axis="cols")):
        U, S, V = approximate_svd(operand, k, Context(seed=30), p)
        np.testing.assert_allclose(np.asarray(S), np.asarray(Sd),
                                   rtol=1e-3, atol=1e-3)
        rec = np.asarray(U * S[None]) @ np.asarray(V).T
        recd = np.asarray(Ud * Sd[None]) @ np.asarray(Vd).T
        np.testing.assert_allclose(rec, recd, atol=1e-2)
    # wide branch (m < n) through the transposed operand
    Uw, Sw, Vw = approximate_svd(A.T, k, Context(seed=30), p)
    np.testing.assert_allclose(np.asarray(Sw), np.asarray(Sd),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_empty_cells_ok(mesh2d):
    """A matrix whose nonzeros all land in one grid cell — the other cells
    are pure padding."""
    rows = np.array([0, 1, 2])
    cols = np.array([0, 1, 2])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    A = SparseMatrix.from_coo(rows, cols, vals, (40, 40))
    D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
    B = jnp.asarray(
        np.random.default_rng(13).standard_normal((40, 3)), jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(D.spmm(B)), np.asarray(spmm(A, B)), atol=ATOL
    )
