"""Engine tests: the donation-aware executable cache under the solver
pipelines (libskylark_tpu/engine).

Oracles: (a) the cache's own counters — the AOT discipline makes the
miss counter exactly the solver-compile counter; (b) jax's lowering
counter (jax._src.test_util.count_jit_and_pmap_lowerings) as the
framework-level witness that a cache hit really compiles nothing; (c)
donation observable through jax's deleted-buffer error.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import jax._src.test_util as jtu

from libskylark_tpu import Context, engine, nla, tune
from libskylark_tpu.engine.cache import CacheEntry, ExecutableCache


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


@pytest.fixture()
def scratch_plan_cache():
    """Swap in an empty in-memory plan cache so plan-fingerprint tests
    neither see nor touch the repo's certified benchmarks/plan_cache.json."""
    prev = tune.set_cache(tune.PlanCache(path=None))
    yield tune.get_cache()
    tune.set_cache(prev)


class TestCompiledWrapper:
    def test_hit_miss_counters(self, fresh_engine):
        calls = []

        @engine.compiled(static_argnames=("k",))
        def f(A, *, k):
            calls.append(1)
            return jnp.sum(A) * k

        A = jnp.ones((8, 8))
        assert float(f(A, k=3)) == 192.0
        assert float(f(A, k=3)) == 192.0
        s = engine.stats()
        assert (s.misses, s.hits, s.recompiles) == (1, 1, 0)
        # tracing happened exactly once — the hit served the executable
        assert len(calls) == 1

    def test_static_and_shape_changes_key_separately(self, fresh_engine):
        @engine.compiled(static_argnames=("k",))
        def f(A, *, k):
            return A * k

        f(jnp.ones((4,)), k=1)
        f(jnp.ones((4,)), k=2)       # static change: new executable
        f(jnp.ones((8,)), k=1)       # shape change: new executable
        f(jnp.ones((4,), jnp.bfloat16), k=1)  # dtype change too
        s = engine.stats()
        assert s.misses == 4 and s.hits == 0 and s.recompiles == 0

    def test_dynamic_kwargs_rejected(self, fresh_engine):
        @engine.compiled(static_argnames=("k",))
        def f(A, *, k):
            return A * k

        with pytest.raises(TypeError, match="positional"):
            f(A=jnp.ones((4,)), k=1)

    def test_identical_second_call_compiles_nothing(self, fresh_engine):
        """Framework-level recompile guard: the cache hit must not
        lower/compile anything in jax either."""

        @engine.compiled
        def f(A):
            return A @ A.T

        A = jnp.ones((16, 16))
        f(A)
        with jtu.count_jit_and_pmap_lowerings() as lowerings:
            f(A)
        assert lowerings[0] == 0   # the counter is a single-cell list
        assert engine.stats().hits == 1

    def test_key_fn_extras_distinguish_closures(self, fresh_engine):
        """Two closures with the same code but different collaborators
        must key separately via key_fn — and identical collaborators
        must share one executable even across wrapper objects."""

        def make(scale):
            def f(A):
                return A * scale

            return engine.compiled(f, name="scaled",
                                   key_fn=lambda *a: (scale,))

        A = jnp.ones((4,))
        assert float(make(2.0)(A)[0]) == 2.0
        assert float(make(3.0)(A)[0]) == 3.0   # different extra: miss
        assert float(make(2.0)(A)[0]) == 2.0   # same extra, new wrapper: hit
        s = engine.stats()
        assert s.misses == 2 and s.hits == 1

    def test_donation_explicit_consumes_operand(self, fresh_engine):
        @engine.compiled(donate_argnums=(0,))
        def f(A):
            return A + 1

        A = jnp.ones((32,))
        f(A)
        with pytest.raises(RuntimeError, match="deleted"):
            _ = A + 1

    def test_auto_donation_off_by_default(self, fresh_engine, monkeypatch):
        monkeypatch.delenv("SKYLARK_ENGINE_DONATE", raising=False)

        @engine.compiled(donate_argnums=(0,), donate="auto")
        def f(A):
            return A + 1

        A = jnp.ones((32,))
        f(A)
        _ = A + 1  # still alive: auto-donation requires the opt-in

    def test_auto_donation_opt_in(self, fresh_engine, monkeypatch):
        @engine.compiled(donate_argnums=(0,), donate="auto")
        def f(A):
            return A + 1

        f(jnp.ones((32,)))  # compiled without donation
        monkeypatch.setenv("SKYLARK_ENGINE_DONATE", "1")
        A = jnp.ones((32,))
        f(A)  # donation flag is part of the key: fresh executable, no thrash
        with pytest.raises(RuntimeError, match="deleted"):
            _ = A + 1
        s = engine.stats()
        assert s.misses == 2 and s.recompiles == 0

    def test_digest_tracks_serialization(self):
        ctx = Context(seed=9)
        from libskylark_tpu import sketch as sk

        t1 = sk.JLT(64, 8, Context(seed=9))
        t2 = sk.JLT(64, 8, Context(seed=9))   # same (seed, counter=0)
        t3 = sk.JLT(64, 8, ctx)
        t4 = sk.JLT(64, 8, ctx)               # counter advanced: differs
        assert engine.digest(t1) == engine.digest(t2)
        assert engine.digest(t3) != engine.digest(t4)

    def test_stats_dump(self, fresh_engine, tmp_path):
        @engine.compiled
        def f(A):
            return A + 1

        f(jnp.ones((4,)))
        path = tmp_path / "engine_stats.json"
        engine.dump_stats(str(path))
        import json

        doc = json.loads(path.read_text())
        assert doc["stats"]["misses"] == 1
        assert doc["cache_size"] == 1
        assert doc["entries"][0]["calls"] == 1


class TestPlanFingerprintKey:
    def test_plan_edit_recompiles_exactly_once(self, fresh_engine,
                                               scratch_plan_cache):
        """Tentpole acceptance: a cached-plan change triggers exactly
        one recompile of an engine-served solver; a no-op write (same
        plan re-recorded with a better measurement) triggers none."""
        A = jnp.asarray(
            np.random.default_rng(0).standard_normal((96, 48)),
            jnp.float32)
        p = nla.ApproximateSVDParams(num_iterations=1)

        def solve():
            return nla.approximate_svd(A, 4, Context(seed=7), p)

        solve()
        solve()
        s = engine.stats()
        assert (s.misses, s.hits) == (1, 1)

        w = tune.dense_workload("normal", (96, 48), "float32", 8,
                                seq_axis=1)
        scratch_plan_cache.put(w, tune.Plan("pallas", m_tile=128,
                                            precision="f32"))
        solve()                       # plan changed: exactly one compile
        solve()                       # and it sticks
        s = engine.stats()
        assert (s.misses, s.hits) == (2, 2)

        # re-recording the SAME plan with a measurement value is not a
        # plan change — the fingerprint hashes plans, not metadata
        scratch_plan_cache.record_measurement(
            w, tune.Plan("pallas", m_tile=128, precision="f32"), 42.0)
        solve()
        s = engine.stats()
        assert (s.misses, s.hits) == (2, 3)
        assert s.recompiles == 0

    def test_fingerprint_stable_and_content_keyed(self, scratch_plan_cache):
        fp0 = scratch_plan_cache.fingerprint()
        assert fp0 == scratch_plan_cache.fingerprint()
        w = tune.dense_workload("normal", (64, 64), "float32", 16,
                                seq_axis=1)
        scratch_plan_cache.put(w, tune.Plan("xla"))
        assert scratch_plan_cache.fingerprint() != fp0


class TestExecutableCacheLRU:
    def _entry(self, name="e"):
        return CacheEntry(executable=None, name=name, compile_seconds=0.0)

    def test_eviction_and_thrash_counter(self):
        c = ExecutableCache(maxsize=2)
        for k in ("a", "b"):
            assert c.lookup(k) is None
            c.insert(k, self._entry(k))
        assert c.lookup("a") is not None        # refresh a; b is now LRU
        assert c.lookup("c") is None
        c.insert("c", self._entry("c"))         # evicts b
        assert c.stats.evictions == 1
        assert c.lookup("b") is None            # thrash: seen before
        assert c.stats.recompiles == 1
        assert len(c) == 2

    def test_reset_clears_seen(self):
        c = ExecutableCache(maxsize=4)
        c.lookup("a")
        c.insert("a", self._entry())
        c.reset()
        assert c.lookup("a") is None
        assert c.stats.recompiles == 0          # fresh slate, not thrash


class TestCacheThreadSafety:
    """The serve executor made the cache multi-threaded for the first
    time: misses must be single-flight (N racing threads on one cold
    key = ONE compile), counter increments must never be lost, and the
    LRU order must survive concurrent mutation."""

    def test_concurrent_calls_single_flight(self, fresh_engine):
        @engine.compiled
        def f(A):
            return A * 2.0 + 1.0

        A = jnp.ones((32, 32))
        n_threads, per = 8, 25
        barrier = threading.Barrier(n_threads)
        errs = []

        def worker():
            try:
                barrier.wait()
                for _ in range(per):
                    f(A)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs and not any(t.is_alive() for t in threads)
        s = engine.stats()
        total = n_threads * per
        # single-flight: exactly one compile; no increment was lost
        assert s.misses == 1
        assert s.hits == total - 1
        assert s.executions == total
        assert s.recompiles == 0
        assert len(engine.cache()) == 1

    def test_concurrent_distinct_keys_lru_integrity(self):
        c = ExecutableCache(maxsize=4)
        n_threads, per, n_keys = 8, 200, 16
        barrier = threading.Barrier(n_threads)

        def worker(tid):
            barrier.wait()
            for i in range(per):
                k = (tid * per + i) % n_keys
                entry = c.acquire(k)
                if entry is None:
                    c.insert(k, CacheEntry(executable=None, name=str(k),
                                           compile_seconds=0.0))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not any(t.is_alive() for t in threads)
        assert len(c) <= 4
        s = c.stats
        # every lookup resolved to a hit or an owned miss, none dropped
        assert s.hits + s.misses == n_threads * per
        # every miss became exactly one insert; evictions account for
        # all inserts beyond capacity — a corrupted OrderedDict would
        # break this identity
        assert s.evictions == s.misses - len(c)

    def test_compile_failure_releases_waiters(self, fresh_engine):
        @engine.compiled
        def bad(A):
            raise ValueError("boom at trace time")

        A = jnp.ones((8,))
        n_threads = 6
        barrier = threading.Barrier(n_threads)
        outcomes = []

        def worker():
            barrier.wait()
            try:
                bad(A)
                outcomes.append("ok")
            except ValueError:
                outcomes.append("raised")

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        # an aborted compile must release its waiters (no deadlock) and
        # every caller sees the failure
        assert not any(t.is_alive() for t in threads)
        assert outcomes == ["raised"] * n_threads
        # a failed compile never enters `seen`: retries are plain
        # misses, not thrash
        assert engine.stats().recompiles == 0

        @engine.compiled
        def good(A):
            return A + 1

        assert float(good(A)[0]) == 2.0   # cache still serviceable


class TestPersistentCacheWiring:
    def test_enable_persistent_cache(self, tmp_path):
        prev = jax.config.jax_compilation_cache_dir
        try:
            assert engine.enable_persistent_cache(str(tmp_path))
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_disabled_values(self):
        assert not engine.enable_persistent_cache("0")
        assert not engine.enable_persistent_cache("")
