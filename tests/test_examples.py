"""Smoke-run every example script (the reference treats examples/ as
executable documentation wired into the build — ref: examples/CMakeLists.txt)."""

import importlib
import pathlib
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parent.parent / "examples")
)


@pytest.mark.parametrize("name", [
    "sketching",
    "least_squares",
    "random_features",
    "kernel_regression",
    "condest_asynch",
    "streaming_ingest",
    "preemptible_training",
])
def test_example_runs(name, capsys):
    mod = importlib.import_module(name)
    mod.main()
    out = capsys.readouterr().out
    assert out.strip(), f"{name} printed nothing"
