"""Fleet subsystem: replicated serving behind a warm-cache-aware
router (libskylark_tpu/fleet/).

Oracles:

- *correctness through the router*: every routed result is bit-equal
  to the sequential ``transform.apply`` oracle (CWT's stream
  exactness) — routing must never change a request's bits, whichever
  replica serves it;
- *affinity*: one bucket class pins to one ring owner, so a warmed
  fleet serves with zero additional compiles and a hit-rate of 1.0;
- *health routing*: DRAINING replicas leave the ring (push, via the
  resilience health hub — no polling), DEGRADED ones are deprioritized;
- *failover*: a draining/refusing replica or an injected
  ``fleet.route`` fault moves requests to the next deterministic
  candidate with zero client-visible failures and zero orphaned
  futures;
- *preemption composition*: SIGTERM (process-wide, and per-replica via
  ``preempt_replica``) drains mid-traffic with every future resolved
  and the drained replica's final drain hook fired exactly once.

Satellites covered here: the multi-executor ``serve_stats()``
aggregation fix, the per-replica telemetry labels end to end
(snapshot + Prometheus), and ``request_statics`` ==
executor-derived statics (the affinity key can never drift from the
executable key).
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from libskylark_tpu import Context, engine, fleet, resilience, telemetry
from libskylark_tpu import sketch as sk
from libskylark_tpu.fleet.ring import HashRing
from libskylark_tpu.resilience import faults


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _fleet(n=3, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("linger_us", 1000)
    pool = fleet.ReplicaPool(n, **kw)
    return pool, fleet.Router(pool)


def _classed_reqs(n_reqs=12, classes=(40, 70, 130), s_dim=16, seed=0):
    """Requests spread over len(classes) distinct pow2 bucket classes."""
    rng = np.random.default_rng(seed)
    ctx = Context(seed=seed)
    transforms = {n: sk.CWT(n, s_dim, ctx) for n in classes}
    reqs = []
    for i in range(n_reqs):
        n = classes[i % len(classes)]
        A = rng.standard_normal((n, 3 + i % 3)).astype(np.float32)
        reqs.append((transforms[n], A))
    return reqs


def _refs(reqs):
    return [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for (T, A) in reqs]


class TestHashRing:
    def test_owner_deterministic_and_stable(self):
        r1 = HashRing(["a", "b", "c"])
        r2 = HashRing(["c", "a", "b"])      # insertion order irrelevant
        keys = [("sketch_apply", "CWT", i) for i in range(50)]
        assert [r1.owner(k) for k in keys] == [r2.owner(k) for k in keys]

    def test_removal_only_moves_removed_members_keys(self):
        ring = HashRing(["a", "b", "c"], vnodes=64)
        keys = [("bucket", i) for i in range(200)]
        before = {k: ring.owner(k) for k in keys}
        ring.remove("b")
        for k, owner in before.items():
            if owner != "b":
                assert ring.owner(k) == owner   # minimal disruption
            else:
                assert ring.owner(k) in ("a", "c")

    def test_preference_covers_all_members_once(self):
        ring = HashRing(["a", "b", "c", "d"])
        pref = list(ring.preference(("k",)))
        assert sorted(pref) == ["a", "b", "c", "d"]

    def test_spread(self):
        ring = HashRing([f"r{i}" for i in range(4)], vnodes=64)
        owners = [ring.owner(("bucket", i)) for i in range(400)]
        counts = {m: owners.count(m) for m in set(owners)}
        assert len(counts) == 4
        assert min(counts.values()) > 400 // 16   # no starved member

    def test_empty_ring(self):
        ring = HashRing()
        with pytest.raises(LookupError):
            ring.owner("k")


class TestAffinityKey:
    def test_request_statics_matches_executor_statics(self, fresh_engine):
        """The router's affinity key and the executor's executable key
        must be the SAME tuple — drift would send requests to cold
        replicas forever."""
        ctx = Context(seed=0)
        rng = np.random.default_rng(0)
        ex = engine.MicrobatchExecutor(max_batch=2, linger_us=500)
        try:
            T = sk.JLT(40, 16, ctx)
            A = rng.standard_normal((40, 3)).astype(np.float32)
            assert engine.request_statics(
                "sketch_apply", transform=T, A=A, dimension=None
            ) == ex._prep_sketch(T, A)[1]

            Tc = sk.CWT(40, 12, ctx)
            B = rng.standard_normal((40, 2)).astype(np.float32)
            assert engine.request_statics(
                "solve_l2_sketched", A=A, B=B, transform=Tc, method="qr"
            ) == ex._prep_solve(A, B, Tc)[1]

            from libskylark_tpu import ml

            X = rng.standard_normal((20, 3)).astype(np.float32)
            coef = rng.standard_normal((20,)).astype(np.float32)
            q = rng.standard_normal((4, 3)).astype(np.float32)
            k = ml.Gaussian(3, sigma=1.0)
            assert engine.request_statics(
                "krr_predict", kernel=k, X_new=q, X_train=X, coef=coef
            ) == ex._prep_krr(k, q, X, coef)[1]
        finally:
            ex.shutdown()

    def test_transport_kwargs_ignored(self, fresh_engine):
        ctx = Context(seed=1)
        T = sk.CWT(40, 16, ctx)
        A = np.ones((40, 3), np.float32)
        base = engine.request_statics("sketch_apply", transform=T, A=A)
        assert base == engine.request_statics(
            "sketch_apply", transform=T, A=A, timeout=5.0, deadline=1.0,
            request_id="req-x")


class TestRouterAffinity:
    def test_results_bit_equal_and_sticky(self, fresh_engine):
        reqs = _classed_reqs(24)
        refs = _refs(reqs)
        pool, router = _fleet(3)
        try:
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=60)),
                                      ref)
            st = router.stats()
            assert st["routed"] == 24
            assert st["affinity_hit_rate"] == 1.0
            assert st["failover"] == 0
            # stickiness: each bucket class routed to exactly one
            # replica — the fleet compiled each class once total
            owners = {router.owner_of("sketch_apply", transform=T, A=A,
                                      dimension=None)
                      for (T, A) in reqs}
            assert set(st["by_replica"]) == owners
        finally:
            router.close()
            pool.shutdown()

    def test_zero_extra_compiles_after_warmup(self, fresh_engine):
        """A warmed fleet serves a repeat storm with zero engine misses
        — the warm-cache-aware routing claim, measured."""
        reqs = _classed_reqs(24)
        pool, router = _fleet(3, linger_us=10_000_000)
        try:
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            pool.flush()
            [f.result(timeout=60) for f in futs]
            m0 = engine.stats().misses
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            pool.flush()
            [f.result(timeout=60) for f in futs]
            assert engine.stats().misses == m0
            assert engine.stats().recompiles == 0
            assert router.stats()["affinity_hit_rate"] == 1.0
        finally:
            router.close()
            pool.shutdown()

    def test_owner_of_is_read_only(self, fresh_engine):
        """Probing owner_of must never perturb routing: a
        capacity-planning query for classes that never arrive cannot
        charge phantom ownership and shift real placement."""
        pool, router = _fleet(2)
        try:
            ctx = Context(seed=0)
            # probe several hypothetical classes before any traffic
            probed = [router.owner_of(
                "sketch_apply", transform=sk.CWT(n, 16, ctx),
                A=np.ones((n, 2), np.float32), dimension=None)
                for n in (40, 70, 130, 200)]
            assert all(p is not None for p in probed)
            assert router._assign == {}      # nothing cached
            assert not router._owned         # nothing charged
            # a probe agrees with where the first real request lands
            T = sk.CWT(40, 16, ctx)
            A = np.ones((40, 2), np.float32)
            peek = router.owner_of("sketch_apply", transform=T, A=A,
                                   dimension=None)
            router.submit_sketch(T, A).result(timeout=60)
            assert router.stats()["by_replica"] == {peek: 1}
        finally:
            router.close()
            pool.shutdown()

    def test_dropped_router_is_collectible(self, fresh_engine):
        """A router dropped without close() must not be pinned by its
        health-hub subscription (it would aggregate into fleet_stats
        forever); the weak subscription shim lets it collect."""
        import gc
        import weakref

        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=500)
        try:
            router = fleet.Router(pool)
            wr = weakref.ref(router)
            del router
            gc.collect()
            assert wr() is None
            # the next publish sweeps the dead shim without warning
            pool.get(pool.names()[0]).drain(timeout=10)
        finally:
            pool.shutdown()

    def test_load_spill_past_threshold(self, fresh_engine):
        """A saturated owner spills to the least-loaded peer: affinity
        trades off against live queue depth."""
        reqs = _classed_reqs(8, classes=(40,))   # ONE bucket class
        pool, router = _fleet(2, max_batch=4, linger_us=10_000_000)
        router.spill_threshold = 4
        try:
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            st = router.stats()
            assert st["spilled"] > 0
            assert len(st["by_replica"]) == 2   # both replicas loaded
            pool.flush()
            refs = _refs(reqs)
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=60)),
                                      ref)
        finally:
            router.close()
            pool.shutdown()


class TestHealthRouting:
    def test_draining_replica_leaves_ring_push_not_poll(
            self, fresh_engine):
        pool, router = _fleet(3)
        try:
            victim = pool.names()[0]
            assert victim in router.routable()
            pool.get(victim).drain(timeout=30)
            # the DRAINING announcement is push: no request needed to
            # notice
            assert victim not in router.routable()
            assert victim in router.stats()["removed"]
        finally:
            router.close()
            pool.shutdown()

    def test_degraded_replica_deprioritized(self, fresh_engine):
        reqs = _classed_reqs(6, classes=(40,))
        pool, router = _fleet(2, linger_us=500)
        try:
            owner = router.owner_of("sketch_apply",
                                    transform=reqs[0][0], A=reqs[0][1],
                                    dimension=None)
            ex = pool.get(owner).executor
            # force the DEGRADED detector: a window of failed flushes,
            # then publish (what the flush worker does per root flush)
            for _ in range(8):
                ex._health.append(1.0)
            ex._maybe_publish_state()
            assert owner in router.stats()["degraded"]
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            [f.result(timeout=60) for f in futs]
            st = router.stats()
            # traffic avoided the degraded owner entirely
            assert st["by_replica"].get(owner, 0) == 0
            assert st["affinity_hit_rate"] == 0.0
            # recovery: successful flushes heal the window, the router
            # re-prioritizes the owner
            for _ in range(32):
                ex._health.append(0.0)
            ex._maybe_publish_state()
            assert owner not in router.stats()["degraded"]
        finally:
            router.close()
            pool.shutdown()

    def test_router_seeded_from_current_states(self, fresh_engine):
        """A router built AFTER a replica started draining must not
        route to it (the subscription starts late; the constructor
        seeds from live states)."""
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=500)
        try:
            pool.get("r0").drain(timeout=30)
            router = fleet.Router(pool)
            assert router.routable() == ["r1"]
            router.close()
        finally:
            pool.shutdown()


class TestFailover:
    def test_drain_one_replica_mid_traffic(self, fresh_engine):
        """The tentpole drain story: preempt one replica while traffic
        flows — peers absorb the load, zero futures orphaned, zero
        client-visible failures, and the drained replica's final
        drain hook (its checkpoint) fires exactly once."""
        reqs = _classed_reqs(48, classes=(40, 70, 130), seed=3)
        refs = _refs(reqs)
        pool, router = _fleet(3, linger_us=2000)
        fired = []
        try:
            victim = router.owner_of("sketch_apply",
                                     transform=reqs[0][0], A=reqs[0][1],
                                     dimension=None)
            pool.on_replica_drain(victim, lambda: fired.append(victim))
            futs = []
            stop = threading.Event()

            def preempt_mid_traffic():
                stop.wait(0.05)
                pool.preempt_replica(victim, timeout=60)

            t = threading.Thread(target=preempt_mid_traffic)
            t.start()
            for i, (T, A) in enumerate(reqs):
                futs.append(router.submit_sketch(T, A))
                if i == 8:
                    stop.set()
                    time.sleep(0.01)
            t.join()
            outs = [np.asarray(f.result(timeout=120)) for f in futs]
            for o, ref in zip(outs, refs):
                assert np.array_equal(o, ref)
            assert fired == [victim]               # checkpoint fired once
            assert victim not in router.routable()
            st = router.stats()
            # peers absorbed everything submitted after the drain
            assert sum(st["by_replica"].get(n, 0)
                       for n in pool.names() if n != victim) > 0
            # double-preempt must not re-fire the hook
            pool.preempt_replica(victim, timeout=5)
            assert fired == [victim]
        finally:
            router.close()
            pool.shutdown()

    def test_injected_route_fault_fails_over(self, fresh_engine):
        """The fleet.route chaos site: an injected fault on the first
        candidate moves the request to the next replica — the client
        sees a result, not the fault."""
        reqs = _classed_reqs(6, classes=(40,), seed=4)
        refs = _refs(reqs)
        plan = {"seed": 3, "faults": [
            {"site": "fleet.route", "error": "IOError_", "every": 2}]}
        pool, router = _fleet(2, linger_us=500)
        try:
            with faults.fault_plan(plan):
                futs = [router.submit_sketch(T, A) for (T, A) in reqs]
                outs = [np.asarray(f.result(timeout=60)) for f in futs]
                fired = faults.fired()
            for o, ref in zip(outs, refs):
                assert np.array_equal(o, ref)
            st = router.stats()
            # every 2nd route ATTEMPT fires; each fire costs one extra
            # attempt, so hits 2,4,6,8,10 fail over and 1,3,5,7,9,11
            # land — 5 deterministic failovers for 6 submits
            assert st["failover"] == 5
            assert st["routed"] == 6        # all requests still landed
            assert [f[0] for f in fired] == ["fleet.route"] * 5
        finally:
            router.close()
            pool.shutdown()

    def test_all_replicas_down_raises_no_healthy(self, fresh_engine):
        pool, router = _fleet(2)
        try:
            for name in pool.names():
                pool.get(name).drain(timeout=30)
            T = sk.CWT(40, 16, Context(seed=0))
            with pytest.raises(fleet.NoHealthyReplicaError):
                router.submit_sketch(T, np.ones((40, 2), np.float32))
            # a fleet refusal is still a ServeOverloadedError: existing
            # single-executor retry handling keeps working
            with pytest.raises(engine.ServeOverloadedError):
                router.submit_sketch(T, np.ones((40, 2), np.float32))
        finally:
            router.close()
            pool.shutdown()


class TestSharedDispatchPool:
    def test_shared_workers_serve_and_drain(self, fresh_engine):
        """A host-sized shared flush pool: replicas spawn no private
        workers, cohorts from every replica drain through the pool's
        dispatchers, results stay bit-equal, and a one-replica drain
        still reaches quiescence (its in-flight cohorts run on pool
        threads that outlive the replica)."""
        reqs = _classed_reqs(18, seed=13)
        refs = _refs(reqs)
        pool = fleet.ReplicaPool(3, max_batch=4, linger_us=1000,
                                 shared_workers=2)
        router = fleet.Router(pool)
        try:
            for r in pool.replicas():
                assert r.executor._workers == []   # no private workers
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=60)),
                                      ref)
            victim = pool.names()[0]
            assert pool.preempt_replica(victim, timeout=30)
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=60)),
                                      ref)
        finally:
            router.close()
            pool.shutdown()

    def test_shared_workers_rejects_process_backend(self):
        with pytest.raises(ValueError, match="thread replicas only"):
            fleet.ReplicaPool(2, backend="process", shared_workers=2)


class TestPreemptionComposition:
    @pytest.fixture(autouse=True)
    def _clean_handler(self):
        yield
        resilience.uninstall_preemption_handler()
        resilience.reset_preemption()

    def test_sigterm_drains_fleet_and_fires_replica_hooks(
            self, fresh_engine):
        """Process-wide SIGTERM composes: the r9 handler drains every
        replica executor (futures resolve), the pool's hook then runs
        every replica's final drain hook, and the router ends with an
        empty ring."""
        reqs = _classed_reqs(9, seed=5)
        refs = _refs(reqs)
        pool, router = _fleet(3, linger_us=10_000_000)
        fired = []
        try:
            for name in pool.names():
                pool.on_replica_drain(
                    name, lambda n=name: fired.append(n))
            futs = [router.submit_sketch(T, A) for (T, A) in reqs]
            resilience.install_preemption_handler(drain_timeout=60.0)
            os.kill(os.getpid(), signal.SIGTERM)
            assert resilience.wait_for_preemption_teardown(timeout=60.0)
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=5)),
                                      ref)
            assert sorted(fired) == pool.names()   # each exactly once
            assert router.routable() == []
            with pytest.raises(fleet.NoHealthyReplicaError):
                router.submit_sketch(reqs[0][0], reqs[0][1])
        finally:
            router.close()
            pool.shutdown()


@pytest.mark.slow
class TestProcessReplica:
    def test_process_fleet_serves_and_sigterm_drains_one(
            self, fresh_engine):
        """A 2-process fleet: results bit-equal through the pipe, then
        a REAL SIGTERM to one child — the child's preemption handler
        drains (its queued work resolves), the parent's router sheds
        to the peer, zero client-visible failures."""
        ctx = Context(seed=0)
        rng = np.random.default_rng(0)
        T = sk.CWT(40, 16, ctx)
        ops = [rng.standard_normal((40, 3)).astype(np.float32)
               for _ in range(8)]
        refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
                for A in ops]
        pool = fleet.ReplicaPool(2, backend="process", max_batch=8,
                                 linger_us=1000)
        router = fleet.Router(pool)
        try:
            futs = [router.submit_sketch(T, A) for A in ops]
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=120)),
                                      ref)
            victim = router.owner_of("sketch_apply", transform=T,
                                     A=ops[0], dimension=None)
            fired = []
            pool.on_replica_drain(victim, lambda: fired.append(victim))
            assert pool.preempt_replica(victim, timeout=90)
            assert fired == [victim]
            assert router.routable() == [n for n in pool.names()
                                         if n != victim]
            # the surviving replica takes the traffic
            futs = [router.submit_sketch(T, A) for A in ops]
            for f, ref in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result(timeout=120)),
                                      ref)
            assert router.stats()["failover"] == 0   # ring had updated
        finally:
            router.close()
            pool.shutdown()


class TestServeStatsMultiExecutor:
    def test_aggregation_over_two_executors(self, fresh_engine):
        """Satellite regression: serve_stats() over two live executors
        — counters sum, peaks take max (not sum), histograms merge,
        and by_replica disaggregates under each executor's name."""
        reqs = _classed_reqs(8, classes=(40,), seed=6)
        ex1 = engine.MicrobatchExecutor(max_batch=4, linger_us=500,
                                        name="agg-a")
        ex2 = engine.MicrobatchExecutor(max_batch=4, linger_us=500,
                                        name="agg-b")
        try:
            futs = ([ex1.submit_sketch(T, A) for (T, A) in reqs[:5]]
                    + [ex2.submit_sketch(T, A) for (T, A) in reqs[5:]])
            [f.result(timeout=60) for f in futs]
            agg = engine.serve_stats()
            s1, s2 = ex1.stats(), ex2.stats()
            assert agg["executors"] >= 2
            assert agg["submitted"] >= 8
            assert agg["by_replica"]["agg-a"]["submitted"] == 5
            assert agg["by_replica"]["agg-b"]["submitted"] == 3
            # peaks: max across executors, never the sum
            assert agg["queued_peak"] == max(
                b["queued_peak"] for b in agg["by_replica"].values())
            assert agg["isolation_depth_peak"] == max(
                b["isolation_depth_peak"]
                for b in agg["by_replica"].values())
            # histogram merge: bin-wise sum of the per-replica hists
            merged = {}
            for b in agg["by_replica"].values():
                for cap, n in b["batch_capacity_hist"].items():
                    merged[cap] = merged.get(cap, 0) + n
            for cap, n in merged.items():
                assert agg["batch_capacity_hist"][cap] >= n
            assert agg["states"].get("SERVING", 0) >= 2
            assert s1["submitted"] + s2["submitted"] == 8
        finally:
            ex1.shutdown()
            ex2.shutdown()

    def test_prometheus_disaggregates_per_replica(self, fresh_engine):
        """Satellite: the replica label reaches the Prometheus surface
        as a label set, not a summed scalar."""
        reqs = _classed_reqs(4, classes=(40,), seed=7)
        ex1 = engine.MicrobatchExecutor(max_batch=4, linger_us=500,
                                        name="prom-a")
        ex2 = engine.MicrobatchExecutor(max_batch=4, linger_us=500,
                                        name="prom-b")
        try:
            futs = ([ex1.submit_sketch(T, A) for (T, A) in reqs[:3]]
                    + [ex2.submit_sketch(T, A) for (T, A) in reqs[3:]])
            [f.result(timeout=60) for f in futs]
            snap = telemetry.snapshot()
            by = snap["collectors"]["serve"]["by_replica"]
            assert by["prom-a"]["submitted"] == 3
            assert by["prom-b"]["submitted"] == 1
            text = telemetry.prometheus_text()
            assert 'skylark_serve_submitted{replica="prom-a"} 3' in text
            assert 'skylark_serve_submitted{replica="prom-b"} 1' in text
            # exactly one TYPE declaration per metric family
            type_lines = [ln for ln in text.splitlines()
                          if ln == "# TYPE skylark_serve_submitted gauge"]
            assert len(type_lines) == 1
        finally:
            ex1.shutdown()
            ex2.shutdown()


class TestFleetTelemetry:
    def test_fleet_collector_and_route_spans(self, fresh_engine):
        """fleet.routed/affinity counters in the snapshot, and the
        fleet.route span parenting the serve.submit span with one
        request id end to end."""
        reqs = _classed_reqs(6, seed=8)
        telemetry.set_enabled(True)
        try:
            import libskylark_tpu.telemetry.trace as trace_mod

            trace_mod.clear_finished()
            pool, router = _fleet(2, linger_us=500)
            try:
                futs = [router.submit_sketch(T, A) for (T, A) in reqs]
                [f.result(timeout=60) for f in futs]
            finally:
                router.close()
                pool.shutdown()
            snap = telemetry.snapshot()
            fl = snap["collectors"]["fleet"]
            assert fl["routed"] >= 6
            assert fl["affinity_hit_rate"] is not None
            assert fl["by_replica"]
            routed = snap["metrics"]["fleet.routed"]["values"]
            assert sum(v["value"] for v in routed) >= 6
            spans = trace_mod.finished_spans()
            routes = {s.span_id: s for s in spans
                      if s.name == "fleet.route"}
            submits = [s for s in spans if s.name == "serve.submit"]
            assert routes and submits
            parented = [s for s in submits if s.parent_id in routes]
            assert parented, "serve.submit must nest under fleet.route"
            for s in parented:
                assert s.request_id == routes[s.parent_id].request_id
        finally:
            telemetry.set_enabled(False)
