"""Queue-depth autoscaler (libskylark_tpu/fleet/autoscale.py) and the
elastic ReplicaPool membership underneath it.

Oracles:

- *scale-up under load*: a sustained queue storm grows the pool (and
  the subscribed router's ring — push, via the health hub's SERVING
  publish) without a single client-visible failure or extra compile;
- *scale-down at idle*: sustained idleness drains a replica away via
  the r11 preemption path (DRAINING published before the queue
  empties, final drain hooks fired, futures resolved) back to the
  floor;
- *hysteresis*: bounds are hard (never below ``min_replicas``, never
  above ``max_replicas``) and the cooldown forbids back-to-back
  events no matter how loud the signal.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import jax.numpy as jnp

from libskylark_tpu import Context, engine, fleet
from libskylark_tpu import sketch as sk


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _workload(n_reqs=32, n=40, s_dim=16, seed=0):
    rng = np.random.default_rng(seed)
    ctx = Context(seed=seed)
    T = sk.CWT(n, s_dim, ctx)
    ops = [rng.standard_normal((n, 3 + i % 4)).astype(np.float32)
           for i in range(n_reqs)]
    refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for A in ops]
    return T, ops, refs


def _wait(pred, timeout=15.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


class TestPoolMembership:
    def test_add_replica_joins_router_ring(self, fresh_engine):
        pool = fleet.ReplicaPool(1, max_batch=4, linger_us=1000)
        router = fleet.Router(pool)
        try:
            assert router.routable() == ["r0"]
            name = pool.add_replica()
            assert name == "r1"
            assert sorted(pool.names()) == ["r0", "r1"]
            # the SERVING publish reached the subscribed router
            assert _wait(lambda: name in router.routable(), 5.0)
            # the grown fleet serves
            T, ops, refs = _workload(4)
            outs = [router.submit_sketch(T, A).result(timeout=60)
                    for A in ops]
            for got, want in zip(outs, refs):
                assert np.array_equal(np.asarray(got), want)
        finally:
            router.close()
            pool.shutdown()

    def test_remove_replica_drains_and_fires_hooks(self, fresh_engine):
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=1000)
        router = fleet.Router(pool)
        hooks = []
        pool.on_replica_drain("r1", lambda: hooks.append("r1"))
        try:
            drained = pool.remove_replica("r1")
            assert drained
            assert hooks == ["r1"]
            assert pool.names() == ["r0"]
            assert "r1" not in router.routable()
            with pytest.raises(KeyError):
                pool.remove_replica("r1")
            # the survivor still serves
            T, ops, refs = _workload(2)
            out = router.submit_sketch(T, ops[0]).result(timeout=60)
            assert np.array_equal(np.asarray(out), refs[0])
        finally:
            router.close()
            pool.shutdown()

    def test_duplicate_add_rejected(self, fresh_engine):
        pool = fleet.ReplicaPool(1, max_batch=4, linger_us=1000)
        try:
            with pytest.raises(ValueError):
                pool.add_replica("r0")
        finally:
            pool.shutdown()

    def test_backend_auto_resolves_by_core_count(self, monkeypatch):
        monkeypatch.setattr("os.cpu_count", lambda: 2)
        assert fleet.resolve_backend("auto") == "thread"
        monkeypatch.setattr("os.cpu_count", lambda: 8)
        assert fleet.resolve_backend("auto") == "process"
        monkeypatch.setenv("SKYLARK_FLEET_BACKEND", "thread")
        assert fleet.resolve_backend(None) == "thread"


class TestAutoscaler:
    def test_storm_scales_up_idle_scales_down(self, fresh_engine):
        from libskylark_tpu.resilience import faults

        T, ops, refs = _workload(24)
        pool = fleet.ReplicaPool(1, max_batch=8, linger_us=2000)
        router = fleet.Router(pool)
        scaler = fleet.Autoscaler(
            pool, router, min_replicas=1, max_replicas=2, up_depth=2,
            down_depth=1, up_ticks=1, down_ticks=3, cooldown_s=0.2,
            interval_s=0.05)
        try:
            # warm the class's whole capacity ladder so the storm
            # (and the grown replica) is provably compile-free
            for cap in (1, 2, 4, 8):
                futs = [router.submit_sketch(T, ops[i])
                        for i in range(cap)]
                [f.result(timeout=60) for f in futs]
            misses0 = engine.stats().misses
            # throttle every flush by 10 ms so the controller's ticks
            # deterministically observe the storm's queue depth (a
            # warm 1-core box otherwise drains it between two ticks)
            plan = {"seed": 1, "faults": [
                {"site": "serve.flush", "stall_s": 0.01, "every": 1}]}
            with faults.fault_plan(plan):
                futs = [router.submit_sketch(T, A)
                        for A in ops for _ in range(4)]
                assert _wait(lambda: len(pool.names()) == 2), \
                    "queue storm never triggered a scale-up"
                outs = [f.result(timeout=120) for f in futs]
            for i, got in enumerate(outs):
                assert np.array_equal(np.asarray(got), refs[i // 4])
            # zero compiles: the grown replica shares the warm class
            assert engine.stats().misses == misses0
            # idle: back down to the floor via the drain path
            assert _wait(lambda: len(pool.names()) == 1, 20.0), \
                "idle fleet never scaled down"
            st = scaler.stats()
            assert st["scale_ups"] >= 1 and st["scale_downs"] >= 1
            assert st["replicas"] == 1
            # post-shrink traffic still lands
            out = router.submit_sketch(T, ops[0]).result(timeout=60)
            assert np.array_equal(np.asarray(out), refs[0])
        finally:
            scaler.close()
            router.close()
            pool.shutdown()

    def test_bounds_and_cooldown(self, fresh_engine):
        T, ops, _ = _workload(16)
        pool = fleet.ReplicaPool(1, max_batch=4, linger_us=2000)
        router = fleet.Router(pool)
        # cooldown far longer than the test: at most ONE event may
        # fire no matter how loud and sustained the signal is
        scaler = fleet.Autoscaler(
            pool, router, min_replicas=1, max_replicas=2, up_depth=1,
            down_depth=0, up_ticks=1, down_ticks=1, cooldown_s=60.0,
            interval_s=0.05)
        try:
            futs = [router.submit_sketch(T, A)
                    for A in ops for _ in range(4)]
            assert _wait(lambda: scaler.stats()["scale_ups"] == 1)
            time.sleep(0.5)
            st = scaler.stats()
            assert st["scale_ups"] == 1, "cooldown was ignored"
            assert len(pool.names()) <= 2
            [f.result(timeout=120) for f in futs]
        finally:
            scaler.close()
            router.close()
            pool.shutdown()

    def test_never_below_min(self, fresh_engine):
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=1000)
        scaler = fleet.Autoscaler(
            pool, None, min_replicas=2, max_replicas=3, up_depth=100,
            down_depth=5, up_ticks=1, down_ticks=1, cooldown_s=0.0,
            interval_s=0.02)
        try:
            time.sleep(0.5)               # many idle ticks
            assert len(pool.names()) == 2
            assert scaler.stats()["scale_downs"] == 0
        finally:
            scaler.close()
            pool.shutdown()

    def test_invalid_bounds_rejected(self, fresh_engine):
        pool = fleet.ReplicaPool(1, max_batch=4)
        try:
            with pytest.raises(ValueError):
                fleet.Autoscaler(pool, min_replicas=3, max_replicas=2,
                                 start=False)
        finally:
            pool.shutdown()

    def test_env_defaults(self, fresh_engine, monkeypatch):
        monkeypatch.setenv("SKYLARK_FLEET_AUTOSCALE_MIN", "2")
        monkeypatch.setenv("SKYLARK_FLEET_AUTOSCALE_MAX", "5")
        monkeypatch.setenv("SKYLARK_FLEET_AUTOSCALE_UP_DEPTH", "17")
        monkeypatch.setenv("SKYLARK_FLEET_AUTOSCALE_COOLDOWN", "9.5")
        pool = fleet.ReplicaPool(2, max_batch=4)
        scaler = fleet.Autoscaler(pool, start=False)
        try:
            assert scaler.min_replicas == 2
            assert scaler.max_replicas == 5
            assert scaler.up_depth == 17
            assert scaler.cooldown_s == 9.5
        finally:
            scaler.close()
            pool.shutdown()

    def test_collector_rollup(self, fresh_engine):
        pool = fleet.ReplicaPool(1, max_batch=4)
        scaler = fleet.Autoscaler(pool, start=False, min_replicas=1,
                                  max_replicas=2)
        try:
            agg = fleet.fleet_stats()["autoscale"]
            assert agg["scalers"] >= 1
            assert "scale_ups" in agg and "scale_downs" in agg
        finally:
            scaler.close()
            pool.shutdown()
