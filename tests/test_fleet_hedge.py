"""Hedged requests (fleet/router.py) and the stall fault that drives
their chaos leg (resilience/faults.py ``stall_s``).

Oracles:

- *straggler rescue*: a primary stalled by an injected fault resolves
  through the mirror in ~hedge-delay time, bit-equal to the oracle,
  with ``hedged``/``hedge_wins`` counted;
- *determinism guard*: in verify mode BOTH attempts complete and must
  compare bit-equal (``hedge_mismatches`` stays 0 — the endpoints are
  pure functions of their operands);
- *no false hedges*: a healthy fleet under a delay far above its p99
  never mirrors anything;
- *stall faults*: fire deterministically (same seed, same sequence),
  sleep instead of raising, and reject nonsensical specs.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from concurrent.futures import wait as cf_wait

import numpy as np
import pytest

import jax.numpy as jnp

from libskylark_tpu import Context, engine, fleet
from libskylark_tpu import sketch as sk
from libskylark_tpu.base.errors import InvalidParametersError
from libskylark_tpu.fleet.replica import _resolve
from libskylark_tpu.resilience import faults


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _workload(n_reqs=8, n=40, s_dim=16, seed=0):
    rng = np.random.default_rng(seed)
    ctx = Context(seed=seed)
    T = sk.CWT(n, s_dim, ctx)
    ops = [rng.standard_normal((n, 3 + i % 4)).astype(np.float32)
           for i in range(n_reqs)]
    refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            for A in ops]
    return T, ops, refs


def _warm_all(pool, T, A):
    """Warm EVERY replica for the class — a hedge target must answer
    from a warm cache for the race to be about queueing, not
    compiles (thread replicas share one cache; this is one submit
    each to also warm each executor's flush path)."""
    for name in pool.names():
        pool.get(name).submit("sketch_apply", transform=T, A=A,
                              dimension=None).result(timeout=60)


STALL_PLAN = {"seed": 3, "faults": [
    {"site": "serve.flush", "stall_s": 0.5, "tag": "hedge-stall"}]}


class TestStallFault:
    def test_stall_sleeps_instead_of_raising(self):
        plan = {"seed": 1, "faults": [
            {"site": "serve.flush", "stall_s": 0.15, "times": 1}]}
        with faults.fault_plan(plan):
            t0 = time.monotonic()
            faults.check("serve.flush")        # fires: sleeps, no raise
            took = time.monotonic() - t0
            assert took >= 0.14
            t1 = time.monotonic()
            faults.check("serve.flush")        # exhausted: no-op
            assert time.monotonic() - t1 < 0.1
            assert faults.fired() == [("serve.flush", 1, "stall")]

    def test_stall_replays_deterministically(self):
        plan = {"seed": 5, "faults": [
            {"site": "fleet.route", "stall_s": 0.0, "every": 3}]}
        seqs = []
        for _ in range(2):
            with faults.fault_plan(plan):
                for _ in range(9):
                    faults.check("fleet.route")
                seqs.append(faults.fired())
        assert seqs[0] == seqs[1]
        assert len(seqs[0]) == 3

    def test_stall_and_error_mutually_exclusive(self):
        with pytest.raises(InvalidParametersError):
            faults.FaultPlan({"faults": [
                {"site": "x", "stall_s": 0.1, "error": "IOError_"}]})

    def test_negative_stall_rejected(self):
        with pytest.raises(InvalidParametersError):
            faults.FaultPlan({"faults": [{"site": "x", "stall_s": -1}]})


class TestHedging:
    def test_stalled_primary_rescued_by_mirror(self, fresh_engine):
        T, ops, refs = _workload()
        pool = fleet.ReplicaPool(2, max_batch=8, linger_us=1000)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=60,
                              hedge_verify=True)
        try:
            _warm_all(pool, T, ops[0])
            with faults.fault_plan(STALL_PLAN):
                with faults.tag("hedge-stall"):
                    t0 = time.monotonic()
                    fut = router.submit_sketch(T, ops[0])
                out = fut.result(timeout=60)
                took = time.monotonic() - t0
                assert faults.fired() == [("serve.flush", 1, "stall")]
            assert np.array_equal(np.asarray(out), refs[0])
            # the mirror answered while the primary slept
            assert took < 0.45
            # verify mode lets the loser finish; wait for it, then
            # check the determinism guard saw two equal results
            time.sleep(0.8)
            st = router.stats()
            assert st["hedged"] == 1
            assert st["hedge_wins"] == 1
            assert st["hedge_mismatches"] == 0
        finally:
            router.close()
            pool.shutdown()

    def test_healthy_fleet_never_hedges(self, fresh_engine):
        T, ops, refs = _workload()
        pool = fleet.ReplicaPool(2, max_batch=8, linger_us=1000)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=2000)
        try:
            _warm_all(pool, T, ops[0])
            futs = [router.submit_sketch(T, A) for A in ops]
            outs = [f.result(timeout=60) for f in futs]
            for got, want in zip(outs, refs):
                assert np.array_equal(np.asarray(got), want)
            st = router.stats()
            assert st["hedged"] == 0
            assert st["hedge_wins"] == 0
        finally:
            router.close()
            pool.shutdown()

    def test_hedge_futures_never_orphan(self, fresh_engine):
        """Both attempts resolve (winner settles the client; the loser
        is cancelled or completes) — nothing dangles."""
        T, ops, refs = _workload()
        pool = fleet.ReplicaPool(2, max_batch=8, linger_us=1000)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=40)
        try:
            _warm_all(pool, T, ops[0])
            with faults.fault_plan(STALL_PLAN):
                with faults.tag("hedge-stall"):
                    fut = router.submit_sketch(T, ops[0])
                assert np.array_equal(
                    np.asarray(fut.result(timeout=60)), refs[0])
            time.sleep(0.8)               # loser's stall elapses
            # every executor quiesces: no stuck cohort, no orphan
            for name in pool.names():
                assert pool.get(name).queue_depth() == 0
            st = router.stats()
            assert st["hedged"] == 1
        finally:
            router.close()
            pool.shutdown()

    def test_single_replica_hedge_is_noop(self, fresh_engine):
        """No second preference member: the watchdog finds no target
        and the primary simply wins late."""
        T, ops, refs = _workload()
        pool = fleet.ReplicaPool(1, max_batch=8, linger_us=1000)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=20)
        try:
            _warm_all(pool, T, ops[0])
            with faults.fault_plan(STALL_PLAN):
                with faults.tag("hedge-stall"):
                    fut = router.submit_sketch(T, ops[0])
                out = fut.result(timeout=60)
            assert np.array_equal(np.asarray(out), refs[0])
            assert router.stats()["hedged"] == 0
        finally:
            router.close()
            pool.shutdown()

    def test_delay_fixed_and_p99_derived(self, fresh_engine):
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=1000)
        fixed = fleet.Router(pool, hedge=True, hedge_delay_ms=123.0)
        derived = fleet.Router(pool, hedge=True)
        try:
            assert fixed._hedge_delay_s() == pytest.approx(0.123)
            # p99-derived from the router's own observed latencies
            # (the r10 histogram quantity)
            derived._latency.extend([0.010] * 50 + [0.200] * 50)
            derived._hedge_delay_cache = (0.0, 0.0)   # force refresh
            d = derived._hedge_delay_s()
            assert d == pytest.approx(0.200, rel=0.05)
            # cold router: seeded from replica latency histograms
            cold = fleet.Router(pool, hedge=True)
            cold._hedge_delay_cache = (0.0, 0.0)
            assert cold._hedge_delay_s() > 0.0
            cold.close()
        finally:
            fixed.close()
            derived.close()
            pool.shutdown()

    def test_env_flag_enables_hedging(self, fresh_engine, monkeypatch):
        monkeypatch.setenv("SKYLARK_FLEET_HEDGE", "1")
        monkeypatch.setenv("SKYLARK_FLEET_HEDGE_DELAY_MS", "77")
        pool = fleet.ReplicaPool(2, max_batch=4)
        router = fleet.Router(pool)
        try:
            assert router._hedge_on
            assert router._hedge_delay_s() == pytest.approx(0.077)
        finally:
            router.close()
            pool.shutdown()

    def test_resolve_tolerates_cancelled_future(self):
        fut = Future()
        fut.cancel()
        _resolve(fut, result=1)           # must not raise
        _resolve(fut, exception=RuntimeError("x"))
        fut2 = Future()
        _resolve(fut2, result=41)
        _resolve(fut2, result=42)         # second settle ignored
        assert fut2.result(timeout=1) == 41

    def test_hedged_storm_all_resolve(self, fresh_engine):
        """A storm where several primaries stall: every client future
        resolves bit-equal (cf_wait guards against orphans)."""
        T, ops, refs = _workload(8)
        pool = fleet.ReplicaPool(2, max_batch=8, linger_us=1000)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=50)
        plan = {"seed": 9, "faults": [
            {"site": "serve.flush", "stall_s": 0.4, "tag": "h",
             "times": 2}]}
        try:
            _warm_all(pool, T, ops[0])
            with faults.fault_plan(plan):
                futs = []
                for i, A in enumerate(ops):
                    if i % 3 == 0:
                        with faults.tag("h"):
                            futs.append(router.submit_sketch(T, A))
                    else:
                        futs.append(router.submit_sketch(T, A))
                done, pending = cf_wait(futs, timeout=120)
                assert not pending, "orphaned client futures"
            for f, want in zip(futs, refs):
                assert np.array_equal(np.asarray(f.result()), want)
            assert router.stats()["hedge_mismatches"] == 0
        finally:
            router.close()
            pool.shutdown()
