"""Shared-memory replica transport (libskylark_tpu/fleet/shm.py).

Oracles:

- *codec exactness*: whatever rides a ring slot decodes bit-equal,
  zero-copy (the decoded view maps the segment, not a copy), and the
  pickle fallback (small/oversize/exhausted-ring payloads) carries
  the identical object — transport choice can never change a result;
- *slot lifecycle*: a decoded view's garbage collection releases its
  slot back to the writer (the ack turnaround), exhaustion degrades
  to the pipe instead of blocking, and the fallback counters tell
  the truth;
- *segment lifecycle* (the no-leak contract): ``/dev/shm`` names
  exist only during replica boot — the owner unlinks as soon as the
  peer's attach is proven — so a clean drain, a mid-flight SIGTERM,
  and a ``kill -9``'d child all end with zero leaked entries. The
  module-scoped autouse fixture enforces it after every test.

The slow tier runs the whole path through a real jax-hosting
``ProcessReplica``: SHM results bit-equal the pickle-transport and
in-process oracles, and SIGTERM / ``kill -9`` mid-flight leak
nothing.
"""

from __future__ import annotations

import gc
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from libskylark_tpu.fleet.shm import (SHM_PREFIX, ShmRef, ShmTransport,
                                      shm_entries)

pytestmark = pytest.mark.skipif(
    not os.path.isdir("/dev/shm"),
    reason="POSIX shared memory filesystem not available")


@pytest.fixture(autouse=True)
def no_leaked_segments():
    """Every test must end with zero live skylark segments — the
    satellite acceptance criterion, enforced at the finest grain."""
    yield
    gc.collect()
    assert shm_entries() == [], (
        f"leaked /dev/shm entries: {shm_entries()}")


def _transport(**kw):
    kw.setdefault("slots", 4)
    kw.setdefault("slot_bytes", 1 << 16)
    kw.setdefault("min_bytes", 64)
    return ShmTransport.create("t", **kw)


def _pair(**kw):
    t = _transport(**kw)
    peer = ShmTransport.attach(t.child_spec())
    return t, peer


class TestRingCodec:
    def test_roundtrip_bit_equal_and_zero_copy(self):
        t, peer = _pair()
        try:
            A = np.arange(6000, dtype=np.float32).reshape(60, 100)
            enc, claimed = t.encode({"A": A, "x": 7})
            assert isinstance(enc["A"], ShmRef)
            assert enc["x"] == 7 and len(claimed) == 1
            dec = peer.decode(enc)
            assert np.array_equal(dec["A"], A)
            assert str(dec["A"].dtype) == "float32"
            # zero-copy: the view's buffer is the mapped segment, and
            # it is read-only (a receiver must not scribble on a slot
            # the writer still owns)
            assert not dec["A"].flags.owndata
            assert not dec["A"].flags.writeable
        finally:
            t.destroy()
            peer.destroy()

    def test_small_arrays_stay_inline(self):
        t, peer = _pair(min_bytes=1 << 20)
        try:
            A = np.ones((8, 8), np.float32)
            enc, claimed = t.encode({"A": A})
            assert isinstance(enc["A"], np.ndarray)
            assert claimed == []
            assert np.array_equal(peer.decode(enc)["A"], A)
        finally:
            t.destroy()
            peer.destroy()

    def test_oversize_object_structured_fall_back_by_reason(self):
        t, peer = _pair(slot_bytes=1 << 10, min_bytes=8)
        try:
            big = np.zeros(4096, np.float64)       # > slot_bytes
            obj = np.array([object()] * 8, dtype=object)
            rec = np.zeros(64, dtype=[("a", "<f4"), ("b", "<i4")])
            enc, claimed = t.encode({"big": big, "obj": obj,
                                     "rec": rec})
            assert isinstance(enc["big"], np.ndarray)
            assert isinstance(enc["obj"], np.ndarray)
            # structured dtypes must NOT ride: their str() headers do
            # not round-trip through np.dtype on the receiver — the
            # pickle path serves them instead (transport choice never
            # changes a result)
            assert isinstance(enc["rec"], np.ndarray)
            assert claimed == []
            assert t.tx.fallback_reasons["oversize"] == 1
            assert t.tx.fallback_reasons["dtype"] == 2
            dec = peer.decode(enc)
            assert np.array_equal(dec["rec"], rec)
        finally:
            t.destroy()
            peer.destroy()

    def test_torn_header_rejected_and_slots_recovered(self):
        t, peer = _pair()
        try:
            A = np.arange(1024, dtype=np.float32)
            enc, claimed = t.encode({"A": A})
            assert len(claimed) == 1
            # corrupt the header: decode must fail BEFORE any view
            # exists, and recover() must return the slot
            enc["A"].dtype = "not-a-dtype"
            with pytest.raises(Exception):
                peer.decode(enc)
            peer.recover(enc)
            t.release(peer.drain_acks())
            assert t.tx.free_slots() == t.tx.slots
        finally:
            t.destroy()
            peer.destroy()

    def test_decoded_arrays_uniformly_read_only(self):
        """SHM views AND pickle-fallback arrays decode read-only — a
        load-dependent writable/read-only flip would be a
        client-visible heisenbug."""
        t, peer = _pair()
        try:
            big = np.arange(1024, dtype=np.float32)   # rides the ring
            small = np.arange(4, dtype=np.float32)    # stays inline
            enc, _ = t.encode({"big": big, "small": small})
            dec = peer.decode(enc)
            assert not dec["big"].flags.writeable
            assert not dec["small"].flags.writeable
        finally:
            t.destroy()
            peer.destroy()

    def test_exhaustion_degrades_then_ack_recovers(self):
        t, peer = _pair(slots=2)
        try:
            arrs = [np.full(128, i, np.float32) for i in range(4)]
            enc, claimed = t.encode({"a": arrs})
            kinds = [type(v).__name__ for v in enc["a"]]
            assert kinds.count("ShmRef") == 2      # ring capacity
            assert kinds.count("ndarray") == 2     # degraded, not lost
            assert t.tx.fallbacks == 2
            dec = peer.decode(enc)
            for got, want in zip(dec["a"], arrs):
                assert np.array_equal(got, want)
            # releasing the views frees the slots for the next send
            del dec
            gc.collect()
            t.release(peer.drain_acks())
            assert t.tx.free_slots() == 2
            enc2, claimed2 = t.encode({"b": arrs[0]})
            assert isinstance(enc2["b"], ShmRef)
        finally:
            t.destroy()
            peer.destroy()

    def test_noncontiguous_source(self):
        t, peer = _pair()
        try:
            base = np.arange(400, dtype=np.float32).reshape(20, 20)
            view = base[::2, 1::3]                 # strided, non-C
            enc, _ = t.encode({"v": view})
            assert isinstance(enc["v"], ShmRef)
            assert np.array_equal(peer.decode(enc)["v"], view)
        finally:
            t.destroy()
            peer.destroy()

    def test_shm_vs_inline_identical(self):
        """Transport-choice bit-equality at the codec level: the same
        payload through the ring and through the inline (pickle-path)
        representation decodes identically."""
        t, peer = _pair()
        try:
            rng = np.random.default_rng(0)
            A = rng.standard_normal((50, 70)).astype(np.float32)
            via_ring, _ = t.encode({"A": A})
            assert isinstance(via_ring["A"], ShmRef)
            inline = {"A": A}                      # what pickle carries
            dec_ring = peer.decode(via_ring)
            dec_inline = peer.decode(inline)
            assert np.array_equal(dec_ring["A"], dec_inline["A"])
            assert dec_ring["A"].tobytes() == dec_inline["A"].tobytes()
        finally:
            t.destroy()
            peer.destroy()


class TestSegmentLifecycle:
    def test_unlink_removes_names_views_stay_valid(self):
        t, peer = _pair()
        A = np.arange(256, dtype=np.float32)
        enc, _ = t.encode({"A": A})
        dec = peer.decode(enc)
        assert len(shm_entries()) == 2
        t.unlink()
        assert shm_entries() == []
        # POSIX semantics: the mapping outlives the name
        assert np.array_equal(dec["A"], A)
        del dec
        t.destroy()
        peer.destroy()

    def test_destroy_idempotent(self):
        t = _transport()
        t.destroy()
        t.destroy()
        assert shm_entries() == []

    def _attacher(self, spec):
        """A child process that attaches the segments and sleeps —
        the boot-window peer for the kill tests (no jax import: the
        lifecycle is transport-level, not executor-level)."""
        code = (
            "import sys, time, json\n"
            "from libskylark_tpu.fleet.shm import ShmTransport\n"
            "t = ShmTransport.attach(json.loads(sys.argv[1]))\n"
            "t.untrack_local()    # standalone process, own tracker\n"
            "print('attached', flush=True)\n"
            "time.sleep(60)\n")
        import json

        env = dict(os.environ)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-c", code, json.dumps(spec)],
            stdout=subprocess.PIPE, env=env, text=True)
        assert proc.stdout.readline().strip() == "attached"
        return proc

    @pytest.mark.parametrize("sig", [signal.SIGTERM, signal.SIGKILL])
    def test_killed_attached_child_leaks_nothing(self, sig):
        """The steady-state story: parent unlinks after attach, so a
        SIGTERM'd or ``kill -9``'d peer cannot leak a name."""
        t = _transport()
        proc = self._attacher(t.child_spec())
        try:
            t.unlink()                    # the boot handshake's end
            assert shm_entries() == []
            proc.send_signal(sig)
            proc.wait(timeout=30)
            assert shm_entries() == []
        finally:
            if proc.poll() is None:
                proc.kill()
            t.destroy()

    def test_child_dead_before_unlink_parent_destroy_cleans(self):
        """The boot-window story: the peer dies before the unlink
        handshake — the owner's destroy (shutdown path / dead-child
        reader path / atexit sweep) removes the names."""
        t = _transport()
        proc = self._attacher(t.child_spec())
        try:
            proc.kill()
            proc.wait(timeout=30)
            assert len(shm_entries()) == 2    # still in boot window
            t.destroy()
            assert shm_entries() == []
        finally:
            if proc.poll() is None:
                proc.kill()


@pytest.mark.slow
class TestProcessReplicaShm:
    """End to end through a real spawned jax-hosting replica."""

    def _reqs(self, n=6, cols=3000):
        from libskylark_tpu import Context
        from libskylark_tpu import sketch as sk

        ctx = Context(seed=0)
        T = sk.CWT(40, 16, ctx)
        rng = np.random.default_rng(0)
        ops = [rng.standard_normal((40, cols - i)).astype(np.float32)
               for i in range(n)]
        return T, ops

    def test_shm_bit_equal_to_pickle_and_oracle(self):
        import jax.numpy as jnp

        from libskylark_tpu import fleet
        from libskylark_tpu import sketch as sk

        T, ops = self._reqs()
        refs = [np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
                for A in ops]
        r_shm = fleet.ProcessReplica("shm0", max_batch=4,
                                     linger_us=1000, shm=True)
        try:
            outs = [r_shm.submit("sketch_apply", transform=T, A=A,
                                 dimension=None).result(timeout=120)
                    for A in ops]
            for got, want in zip(outs, refs):
                assert np.array_equal(np.asarray(got), want)
            # the operands are ~470 KB: they must actually have ridden
            # the ring, both directions
            assert r_shm.transport_stats()["sends"] >= len(ops)
            assert (r_shm.boot_info()["shm"] or {}).get("sends", 0) > 0
        finally:
            r_shm.shutdown()
        assert shm_entries() == []
        r_pkl = fleet.ProcessReplica("pkl0", max_batch=4,
                                     linger_us=1000, shm=False)
        try:
            outs_pkl = [r_pkl.submit("sketch_apply", transform=T, A=A,
                                     dimension=None).result(timeout=120)
                        for A in ops]
            for got, want in zip(outs_pkl, refs):
                assert np.array_equal(np.asarray(got), want)
        finally:
            r_pkl.shutdown()

    def test_sigterm_mid_flight_no_leak(self):
        from libskylark_tpu import fleet

        T, ops = self._reqs(n=4)
        r = fleet.ProcessReplica("shmterm", max_batch=8,
                                 linger_us=200_000, shm=True)
        try:
            futs = [r.submit("sketch_apply", transform=T, A=A,
                             dimension=None) for A in ops]
            r.preempt()                  # real SIGTERM, queue nonempty
            deadline = time.monotonic() + 60
            while r.state() != "STOPPED" and time.monotonic() < deadline:
                time.sleep(0.05)
            assert r.state() == "STOPPED"
            # the drain resolves in-flight futures; none may orphan
            for f in futs:
                try:
                    f.result(timeout=60)
                except Exception:  # noqa: BLE001 — refused is fine
                    pass
        finally:
            r.shutdown()
        assert shm_entries() == []

    def test_kill_9_child_fails_futures_no_leak(self):
        from libskylark_tpu import fleet
        from libskylark_tpu.engine.serve import ServeOverloadedError

        T, ops = self._reqs(n=2)
        r = fleet.ProcessReplica("shmkill", max_batch=8,
                                 linger_us=500_000, shm=True)
        try:
            futs = [r.submit("sketch_apply", transform=T, A=A,
                             dimension=None) for A in ops]
            os.kill(r._proc.pid, signal.SIGKILL)
            for f in futs:
                with pytest.raises(ServeOverloadedError):
                    f.result(timeout=60)
        finally:
            r.shutdown()
        assert shm_entries() == []
