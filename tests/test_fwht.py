"""FWHT-native tier (docs/performance, "In-kernel FWHT and compressed
matmul"): the panel-free SRHT lowering, the in-kernel Pallas butterfly,
and the compressed approximate-matmul endpoint.

Oracles:

- *Sylvester reference*: ``fut.fwht`` equals the dense
  ``_hadamard_np`` matmul bit for bit on integer-valued f32 lattices
  (exact adds both ways), allclose on general floats.
- *dyadic bit-equality*: the fused ``fwht_sketch`` / serve /
  ``fold_rows`` / Pallas programs are bit-equal to the
  ``operator_panel`` matmul whenever every intermediate is exactly
  representable — integer-valued operands with ``n`` and ``s`` EVEN
  powers of two (``1/sqrt(n)`` dyadic). Odd powers (n = 2^13, ...)
  are allclose only: the scales are irrational and summation orders
  legitimately differ in the last ulp.
- *stream bit-identity*: the in-kernel Threefry regeneration draws the
  SAME sign diagonal and sample coordinates as the transform's own
  ``diagonal()`` / ``sample_indices()`` — pinned end-to-end by
  requiring the Pallas path bit-equal to the XLA twin on dyadic input
  (one flipped sign or swapped sample would break equality).
- *selection precedence* for the SRHT family: executor ``kernel=``
  argument > ``SKYLARK_FWHT_KERNEL`` > ``SKYLARK_SERVE_KERNEL`` >
  plan cache > xla default, with the FWHT pin invisible to non-SRHT
  buckets and outranking warmup-pack restoration.
- *compressed matmul*: ``(A Sᵀ)(S B)`` is within the returned
  ``‖A‖_F·‖B‖_F·√(2/s)`` scale on well-conditioned data; the sparse-A
  CWT lane is bit-equal to its densified twin.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr
import scipy.sparse as sp

from libskylark_tpu import Context, engine, tune
from libskylark_tpu import sketch as sk
from libskylark_tpu.base.context import Allocation
from libskylark_tpu.base.errors import UnsupportedError
from libskylark_tpu.sketch import fjlt as _fjlt
from libskylark_tpu.sketch import fut as _fut
from libskylark_tpu.sketch import pallas_fwht
from libskylark_tpu.sketch.fjlt import FJLT
from libskylark_tpu.sketch.hash import CWT


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _executor(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("linger_us", 1000)
    return engine.MicrobatchExecutor(**kw)


def _kd(transform):
    return engine.serve.MicrobatchExecutor._key_data(transform)


def _lattice(rng, shape):
    """Integer-valued f32: every butterfly intermediate is exact."""
    return rng.integers(-4, 5, size=shape).astype(np.float32)


# ---------------------------------------------------------------------------
# fut.fwht vs the dense Sylvester reference
# ---------------------------------------------------------------------------


class TestFWHT:
    @pytest.mark.parametrize("n", [2, 8, 64, 256, 1024])
    def test_matches_hadamard_matmul(self, n):
        rng = np.random.default_rng(n)
        A = _lattice(rng, (n, 5))
        H = _fut._hadamard_np(n).astype(np.float32)
        out = np.asarray(_fut.fwht(jnp.asarray(A), axis=0))
        assert np.array_equal(out, H @ A)

    def test_general_floats_allclose(self):
        rng = np.random.default_rng(0)
        A = rng.standard_normal((512, 7)).astype(np.float32)
        H = _fut._hadamard_np(512).astype(np.float32)
        out = np.asarray(_fut.fwht(jnp.asarray(A), axis=0))
        np.testing.assert_allclose(out, H @ A, rtol=2e-4, atol=2e-3)

    def test_axis1(self):
        rng = np.random.default_rng(1)
        A = _lattice(rng, (3, 128))
        H = _fut._hadamard_np(128).astype(np.float32)
        out = np.asarray(_fut.fwht(jnp.asarray(A), axis=1))
        assert np.array_equal(out, A @ H)

    def test_nonpow2_rejected(self):
        with pytest.raises(ValueError, match="power-of-2"):
            _fut.fwht(jnp.zeros((12, 3)), axis=0)

    def test_fused_sketch_equals_composed(self):
        """fwht_sketch is the literal diag→FWHT→gather composition."""
        rng = np.random.default_rng(2)
        n, s, m = 1024, 64, 9
        A = rng.standard_normal((n, m)).astype(np.float32)
        D = (1.0 - 2.0 * rng.integers(0, 2, n)).astype(np.float32)
        idx = rng.integers(0, n, s).astype(np.int32)
        fs, ss = 1.0 / math.sqrt(n), math.sqrt(n / s)
        fused = np.asarray(_fut.fwht_sketch(
            jnp.asarray(A), jnp.asarray(D), jnp.asarray(idx), fs, ss,
            axis=0))
        mixed = _fut.fwht(fs * jnp.asarray(D)[:, None] * A, axis=0)
        composed = np.asarray(ss * mixed[jnp.asarray(idx), :])
        assert np.array_equal(fused, composed)


# ---------------------------------------------------------------------------
# the panel-free SRHT programs vs the operator-panel oracle
# ---------------------------------------------------------------------------


class TestPanelFree:
    @pytest.mark.parametrize("n,s", [(256, 16), (4096, 64)])
    def test_serve_apply_bit_equal_dyadic(self, n, s):
        """n, s even powers of two + lattice data: bit-equal to both
        the transform's own apply and the materialized panel."""
        rng = np.random.default_rng(s)
        t = FJLT(n, s, Context(seed=5), fut="wht")
        A = _lattice(rng, (7, n))
        out = np.asarray(_fjlt.srht_serve_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=True))
        ref = np.asarray(t.apply(A, sk.ROWWISE))
        assert np.array_equal(out, ref)
        panel = t.operator_panel(0, n)
        assert np.array_equal(out, A @ np.asarray(panel).T)

    def test_serve_apply_columnwise(self):
        n, s = 1024, 64
        rng = np.random.default_rng(3)
        t = FJLT(n, s, Context(seed=9), fut="wht")
        A = _lattice(rng, (n, 5))
        out = np.asarray(_fjlt.srht_serve_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=False))
        assert np.array_equal(out, np.asarray(t.apply(A, sk.COLUMNWISE)))

    def test_serve_apply_floats_allclose(self):
        n, s = 2048, 128
        rng = np.random.default_rng(4)
        t = FJLT(n, s, Context(seed=2), fut="wht")
        A = rng.standard_normal((6, n)).astype(np.float32)
        out = np.asarray(_fjlt.srht_serve_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=True))
        np.testing.assert_allclose(
            out, np.asarray(t.apply(A, sk.ROWWISE)), rtol=1e-4,
            atol=1e-4)

    @pytest.mark.parametrize("lo,hi", [(0, 256), (0, 1), (17, 18),
                                       (13, 200), (128, 256)])
    def test_fold_rows_vs_panel(self, lo, hi):
        """Partial folds over aligned-block decompositions equal the
        panel contraction (dyadic regime: bitwise)."""
        n, s, m = 256, 16, 6
        rng = np.random.default_rng(hi)
        t = FJLT(n, s, Context(seed=13), fut="wht")
        X = _lattice(rng, (hi - lo, m))
        out = np.asarray(t.fold_rows(X, lo, hi))
        panel = np.asarray(t.operator_panel(lo, hi))
        assert np.array_equal(out, panel @ X)

    def test_fold_rows_split_sums_to_full(self):
        n, s, m = 1024, 64, 4
        rng = np.random.default_rng(8)
        t = FJLT(n, s, Context(seed=21), fut="wht")
        X = _lattice(rng, (n, m))
        full = np.asarray(t.fold_rows(X, 0, n))
        split = (np.asarray(t.fold_rows(X[:300], 0, 300))
                 + np.asarray(t.fold_rows(X[300:], 300, n)))
        np.testing.assert_allclose(full, split, rtol=1e-5, atol=1e-5)
        assert np.array_equal(
            full, np.asarray(t.apply(X, sk.COLUMNWISE)))

    def test_fold_rows_non_wht_rejected(self):
        t = FJLT(256, 16, Context(seed=1), fut="dct")
        with pytest.raises(UnsupportedError):
            t.fold_rows(np.zeros((4, 2), np.float32), 0, 4)


# ---------------------------------------------------------------------------
# the Pallas in-kernel butterfly (interpret mode on the CPU mesh)
# ---------------------------------------------------------------------------


class TestPallasKernel:
    @pytest.mark.parametrize("n,s,m", [(256, 16, 3), (4096, 64, 37)])
    def test_kernel_bit_equal_to_xla_twin_dyadic(self, n, s, m):
        """Bit-equality pins BOTH the butterfly arithmetic and the
        in-kernel Threefry streams: one flipped Rademacher sign or one
        swapped sample index would break it."""
        rng = np.random.default_rng(n + s)
        t = FJLT(n, s, Context(seed=31), fut="wht")
        A = _lattice(rng, (m, n))
        ker = np.asarray(pallas_fwht.srht_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=True,
            interpret=True))
        twin = np.asarray(_fjlt.srht_serve_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=True))
        assert np.array_equal(ker, twin)

    def test_kernel_columnwise_and_floats(self):
        n, s, m = 1024, 128, 11
        rng = np.random.default_rng(6)
        t = FJLT(n, s, Context(seed=17), fut="wht")
        A = rng.standard_normal((n, m)).astype(np.float32)
        ker = np.asarray(pallas_fwht.srht_apply(
            _kd(t), jnp.asarray(A), s_dim=s, rowwise=False,
            interpret=True))
        ref = np.asarray(t.apply(A, sk.COLUMNWISE))
        np.testing.assert_allclose(ker, ref, rtol=1e-4, atol=1e-4)

    def test_batched_lane_invariance(self):
        """A lane out of a B=3 cohort is bit-equal to its own B=1
        run — capacity never reaches per-lane arithmetic."""
        n, s, m = 512, 32, 5
        rng = np.random.default_rng(7)
        kds = np.stack([_kd(FJLT(n, s, Context(seed=40 + i),
                                 fut="wht")) for i in range(3)])
        A = np.stack([_lattice(rng, (m, n)) for _ in range(3)])
        out = np.asarray(pallas_fwht.srht_apply_batched(
            kds, jnp.asarray(A), s_dim=s, rowwise=True,
            interpret=True))
        for i in range(3):
            solo = np.asarray(pallas_fwht.srht_apply(
                kds[i], jnp.asarray(A[i]), s_dim=s, rowwise=True,
                interpret=True))
            assert np.array_equal(out[i], solo)

    def test_qualify_declines(self):
        ok, why = pallas_fwht.qualify(16, 1000, 4, jnp.float32,
                                      interpret=True)
        assert not ok and "power of two" in why
        ok, why = pallas_fwht.qualify(16, 64, 4, jnp.float32,
                                      interpret=True)
        assert not ok     # below one lane block
        ok, why = pallas_fwht.qualify(4096, 8192, 4, jnp.float32,
                                      interpret=True)
        assert not ok and "cipher sweep" in why
        ok, why = pallas_fwht.qualify(16, 1024, 4, jnp.bfloat16,
                                      interpret=True)
        assert not ok and "float32" in why
        ok, why = pallas_fwht.qualify(16, 1024, 4, jnp.float32,
                                      interpret=True)
        assert ok


# ---------------------------------------------------------------------------
# serve integration: the SRHT sketch_apply family
# ---------------------------------------------------------------------------


class TestServeSRHT:
    def test_capacity1_bit_equality_both_orientations(
            self, fresh_engine):
        rng = np.random.default_rng(11)
        n, s = 1024, 256
        t = FJLT(n, s, Context(seed=7), fut="wht")
        with _executor() as ex:
            A = _lattice(rng, (37, n))
            out = np.asarray(ex.submit_sketch(
                t, A, dimension=sk.ROWWISE).result(timeout=60))
            assert np.array_equal(
                out, np.asarray(t.apply(A, sk.ROWWISE)))
            Ac = _lattice(rng, (n, 9))
            outc = np.asarray(ex.submit_sketch(
                t, Ac, dimension=sk.COLUMNWISE).result(timeout=60))
            assert np.array_equal(
                outc, np.asarray(t.apply(Ac, sk.COLUMNWISE)))
            st = ex.stats()["fwht"]
            assert st["by_backend"]["xla"]["flushes"] == 2

    def test_cohort_lane_matches_capacity1(self, fresh_engine):
        rng = np.random.default_rng(12)
        n, s = 512, 64
        ts = [FJLT(n, s, Context(seed=50 + i), fut="wht")
              for i in range(4)]
        ops = [_lattice(rng, (6, n)) for _ in range(4)]
        with _executor(max_batch=4, linger_us=50000) as ex:
            futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                    for t, A in zip(ts, ops)]
            ex.flush()
            batched = [np.asarray(f.result(timeout=60)) for f in futs]
        with _executor(max_batch=1, linger_us=100) as ex1:
            for t, A, got in zip(ts, ops, batched):
                solo = np.asarray(ex1.submit_sketch(
                    t, A, dimension=sk.ROWWISE).result(timeout=60))
                assert np.array_equal(got, solo)

    def test_nonpow2_rejected(self, fresh_engine):
        t = FJLT(1000, 64, Context(seed=3), fut="wht")
        with _executor() as ex:
            with pytest.raises(ValueError, match="power-of-2"):
                ex.submit_sketch(
                    t, np.zeros((4, 1000), np.float32),
                    dimension=sk.ROWWISE)

    def test_non_wht_mixer_rejected(self, fresh_engine):
        t = FJLT(1024, 64, Context(seed=3), fut="dct")
        with _executor() as ex:
            with pytest.raises(UnsupportedError):
                ex.submit_sketch(t, np.zeros((4, 1024), np.float32),
                                 dimension=sk.ROWWISE)

    def test_zero_recompiles_after_warmup(self, fresh_engine):
        rng = np.random.default_rng(13)
        n, s = 512, 64
        t = FJLT(n, s, Context(seed=19), fut="wht")
        reqs = [_lattice(rng, (5, n)) for _ in range(8)]
        with _executor(max_batch=8, linger_us=4000) as ex:
            for cap in (1, 2, 4, 8):
                futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                        for A in reqs[:cap]]
                ex.flush()
                [f.result(timeout=60) for f in futs]
            m0, r0 = engine.stats().misses, engine.stats().recompiles
            for _ in range(2):
                futs = [ex.submit_sketch(t, A, dimension=sk.ROWWISE)
                        for A in reqs]
                ex.flush()
                [f.result(timeout=60) for f in futs]
            assert engine.stats().misses - m0 == 0
            assert engine.stats().recompiles - r0 == 0

    def test_pallas_pin_bit_equal_and_counted(self, fresh_engine,
                                              monkeypatch):
        """SKYLARK_FWHT_KERNEL=pallas routes the flush through the
        interpret-mode kernel; dyadic input stays bit-equal and the
        flush is attributed to the pallas backend."""
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "pallas")
        rng = np.random.default_rng(14)
        n, s = 4096, 256
        t = FJLT(n, s, Context(seed=23), fut="wht")
        A = _lattice(rng, (16, n))
        with _executor() as ex:
            out = np.asarray(ex.submit_sketch(
                t, A, dimension=sk.ROWWISE).result(timeout=120))
            st = ex.stats()["fwht"]
        assert np.array_equal(out, np.asarray(t.apply(A, sk.ROWWISE)))
        assert st["by_backend"]["pallas"]["flushes"] == 1

    def test_min_n_decline(self, fresh_engine, monkeypatch):
        """Below SKYLARK_FWHT_MIN_N a pallas intent declines (counted
        reason) back to the XLA program."""
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "pallas")
        rng = np.random.default_rng(15)
        t = FJLT(1024, 64, Context(seed=29), fut="wht")
        A = _lattice(rng, (4, 1024))
        with _executor() as ex:
            out = np.asarray(ex.submit_sketch(
                t, A, dimension=sk.ROWWISE).result(timeout=60))
            st = ex.stats()
        assert np.array_equal(out, np.asarray(t.apply(A, sk.ROWWISE)))
        assert st["fwht"]["by_backend"] == {"xla": {"flushes": 1}}
        assert any("fwht-min-n" in k.replace("_", "-")
                   for k in st["kernel"]["by_reason"])


# ---------------------------------------------------------------------------
# selection precedence for the SRHT family
# ---------------------------------------------------------------------------


class TestFWHTPrecedence:
    def _flush_one(self, ex):
        rng = np.random.default_rng(16)
        t = FJLT(4096, 64, Context(seed=7), fut="wht")
        A = rng.standard_normal((4, 4096)).astype(np.float32)
        fut = ex.submit_sketch(t, A, dimension=sk.ROWWISE)
        ex.flush()
        fut.result(timeout=120)
        (choice,) = ex._kernel_memo.values()
        return choice

    def test_arg_beats_env(self, fresh_engine, monkeypatch):
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "pallas")
        with _executor(kernel="xla") as ex:
            backend, _plan, source, declined = self._flush_one(ex)
        assert (backend, source, declined) == ("xla", "arg", None)

    def test_env_pin_resolves(self, fresh_engine, monkeypatch):
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "pallas")
        prev = tune.set_cache(tune.PlanCache(path=None))
        try:
            with _executor() as ex:
                backend, _plan, source, _d = self._flush_one(ex)
        finally:
            tune.set_cache(prev)
        # interpret-mode pallas qualifies on the CPU mesh (the CI
        # bit-equality leg); the pin is attributed to the env
        assert source == "env"
        assert backend == "pallas"

    def test_fwht_pin_beats_general_serve_env(self, fresh_engine,
                                              monkeypatch):
        monkeypatch.setenv("SKYLARK_SERVE_KERNEL", "pallas")
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "xla")
        with _executor() as ex:
            backend, _plan, source, declined = self._flush_one(ex)
        assert (backend, source, declined) == ("xla", "env", None)

    def test_pin_invisible_to_cwt_buckets(self, fresh_engine,
                                          monkeypatch):
        monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "pallas")
        rng = np.random.default_rng(17)
        t = CWT(512, 32, Context(seed=9))
        A = rng.standard_normal((512, 4)).astype(np.float32)
        prev = tune.set_cache(tune.PlanCache(path=None))
        try:
            with _executor() as ex:
                fut = ex.submit_sketch(t, A, dimension=sk.COLUMNWISE)
                ex.flush()
                fut.result(timeout=60)
                (choice,) = ex._kernel_memo.values()
        finally:
            tune.set_cache(prev)
        assert choice[2] == "default"

    def test_pin_outranks_pack_restore(self, fresh_engine,
                                       monkeypatch):
        statics = ("sketch_apply", "SRHT", "None", 64, True,
                   "float32", (8, 4096))
        with _executor() as ex:
            monkeypatch.setenv("SKYLARK_FWHT_KERNEL", "xla")
            assert not ex.restore_kernel_choice(statics, 4, "pallas")
            monkeypatch.delenv("SKYLARK_FWHT_KERNEL")
            assert ex.restore_kernel_choice(statics, 4, "pallas")

    def test_ladder_has_mtile_candidates(self):
        w = tune.serve_workload("sketch_apply", "SRHT", "float32",
                                (512, 4096), 256, 4, rowwise=True)
        cands = tune.enumerate_candidates(w)
        mtiles = sorted(p.m_tile for p in cands
                        if p.backend == "pallas")
        assert mtiles == [128, 256, 512]
        ranked = tune.rank_candidates(w)
        assert ranked[0][0].backend == "xla"   # CPU host certifies xla
        pallas_rec = next(c for p, c in ranked
                          if p.backend == "pallas")
        assert pallas_rec.get("interpret")


# ---------------------------------------------------------------------------
# compressed approximate matmul
# ---------------------------------------------------------------------------


class TestCompressedMatmul:
    def test_srht_dense_within_bound(self, fresh_engine):
        rng = np.random.default_rng(18)
        n, m, p = 2048, 40, 17
        t = FJLT(n, 512, Context(seed=11), fut="wht")
        A = rng.standard_normal((m, n)).astype(np.float32)
        B = rng.standard_normal((n, p)).astype(np.float32)
        with _executor() as ex:
            est, bound = ex.submit_compressed_matmul(
                A, B, t).result(timeout=120)
        est = np.asarray(est)
        assert est.shape == (m, p)
        err = np.linalg.norm(est - A @ B)
        assert err <= bound
        assert bound == pytest.approx(
            np.linalg.norm(A) * np.linalg.norm(B)
            * math.sqrt(2.0 / 512))

    def test_cwt_dense_within_bound(self, fresh_engine):
        rng = np.random.default_rng(19)
        n, m, p = 1500, 30, 9            # non-pow2 contraction
        t = CWT(n, 512, Context(seed=13))
        A = rng.standard_normal((m, n)).astype(np.float32)
        B = rng.standard_normal((n, p)).astype(np.float32)
        with _executor() as ex:
            est, bound = ex.submit_compressed_matmul(
                A, B, t).result(timeout=120)
        assert np.linalg.norm(np.asarray(est) - A @ B) <= bound

    def test_sparse_cwt_bit_equal_to_densified(self, fresh_engine):
        rng = np.random.default_rng(20)
        n, m, p = 1500, 30, 9
        t = CWT(n, 256, Context(seed=17))
        A = sp.random(m, n, density=0.05, random_state=5,
                      dtype=np.float32, format="csr")
        B = rng.standard_normal((n, p)).astype(np.float32)
        with _executor() as ex:
            es, bs = ex.submit_compressed_matmul(
                A, B, t).result(timeout=120)
            ed, bd = ex.submit_compressed_matmul(
                A.toarray(), B, t).result(timeout=120)
        assert np.array_equal(np.asarray(es), np.asarray(ed))
        assert bs == pytest.approx(bd)

    def test_sparse_srht_matches_densified(self, fresh_engine):
        rng = np.random.default_rng(21)
        n, m, p = 2048, 30, 9
        t = FJLT(n, 256, Context(seed=19), fut="wht")
        A = sp.random(m, n, density=0.05, random_state=6,
                      dtype=np.float32, format="csr")
        B = rng.standard_normal((n, p)).astype(np.float32)
        with _executor() as ex:
            es, _ = ex.submit_compressed_matmul(
                A, B, t).result(timeout=120)
            ed, _ = ex.submit_compressed_matmul(
                A.toarray(), B, t).result(timeout=120)
        np.testing.assert_allclose(np.asarray(es), np.asarray(ed),
                                   rtol=1e-4, atol=1e-4)

    def test_default_transform_family_split(self, fresh_engine):
        """No caller transform: SRHT on pow2 contraction, CWT
        otherwise; the two front doors build bit-identical defaults."""
        rng = np.random.default_rng(22)
        with _executor() as ex:
            A = rng.standard_normal((8, 1024)).astype(np.float32)
            B = rng.standard_normal((1024, 3)).astype(np.float32)
            est, _ = ex.submit_compressed_matmul(
                A, B, s_dim=256, seed=4).result(timeout=120)
            t = engine.serve.default_cmm_transform(A, s_dim=256,
                                                   seed=4)
            assert isinstance(t, FJLT)
            est2, _ = ex.submit_compressed_matmul(
                A, B, t).result(timeout=120)
            assert np.array_equal(np.asarray(est), np.asarray(est2))
            A2 = rng.standard_normal((8, 1000)).astype(np.float32)
            assert isinstance(
                engine.serve.default_cmm_transform(A2), CWT)

    def test_unsupported_family_rejected(self, fresh_engine):
        rng = np.random.default_rng(23)
        t = sk.JLT(256, 32, Context(seed=3))
        A = rng.standard_normal((4, 256)).astype(np.float32)
        B = rng.standard_normal((256, 3)).astype(np.float32)
        with _executor() as ex:
            with pytest.raises(TypeError):
                ex.submit_compressed_matmul(A, B, t)

    def test_contraction_mismatch_rejected(self, fresh_engine):
        t = CWT(256, 32, Context(seed=3))
        with _executor() as ex:
            with pytest.raises(ValueError):
                ex.submit_compressed_matmul(
                    np.zeros((4, 256), np.float32),
                    np.zeros((128, 3), np.float32), t)

    def test_submits_counted(self, fresh_engine):
        rng = np.random.default_rng(24)
        t = CWT(512, 64, Context(seed=31))
        A = rng.standard_normal((4, 512)).astype(np.float32)
        B = rng.standard_normal((512, 3)).astype(np.float32)
        with _executor() as ex:
            ex.submit_compressed_matmul(A, B, t).result(timeout=60)
            st = ex.stats()["fwht"]
        assert st["cm_submits"] == 1
        assert engine.serve_stats()["fwht"]["cm_submits"] >= 1

    def test_tune_workload_is_xla_only(self):
        w = tune.serve_workload("compressed_matmul", "SRHT",
                                "float32", (64, 2048), 512, 2,
                                nnz=64)
        cands = tune.enumerate_candidates(w)
        assert [p.backend for p in cands] == ["xla"]
        ranked = tune.rank_candidates(w)
        assert ranked[0][1]["modeled_s"] > 0


# ---------------------------------------------------------------------------
# cross-subsystem dyadic regression: the dist shard fold and the
# session appender ride the SAME panel-free fold_rows — both must stay
# on the operator-panel oracle's bit pattern in the dyadic regime
# ---------------------------------------------------------------------------


class TestPanelFreeDistSessions:
    def test_dist_srht_shards_bit_equal_dyadic(self):
        """Ragged shard folds summed across shards equal the one-shot
        apply bit for bit (n, s even powers of two + lattice data:
        every partial is an exact dyadic rational, so the shard-order
        summation is exact)."""
        from libskylark_tpu.dist import plan as dp

        n, s, d = 256, 16, 6
        rng = np.random.default_rng(26)
        A = _lattice(rng, (n, d))
        plan = dp.ShardPlan(kind="srht", n=n, s_dim=s, d=d, seed=5,
                            targets=0, shard_rows=48).validate()
        sx = np.zeros((s, d), np.float32)
        for i, _, _ in plan.shards():
            sx = sx + dp.compute_shard(
                plan, i, dp.ArraySource(A))["SX"]
        t = FJLT(n, s, Context(seed=5), fut="wht")
        assert np.array_equal(
            sx, np.asarray(t.apply(jnp.asarray(A), sk.COLUMNWISE)))

    def test_session_fold_bit_equal_to_dist_fold_dyadic(self, tmp_path):
        """The sessions appender (cached full diagonal) and the dist
        folder (per-slice streams) are twins — same bits at the same
        offsets (the both-or-neither rule in sessions/state.py)."""
        from libskylark_tpu import sessions
        from libskylark_tpu.io.chunked import iter_array_batches

        n, s, d = 256, 16, 6
        rng = np.random.default_rng(27)
        A = _lattice(rng, (n, d))
        reg = sessions.SessionRegistry(directory=str(tmp_path))
        sid = reg.open(sessions.SessionSpec(
            kind="srht", n=n, s_dim=s, d=d, seed=5))
        seq = 0
        for Xb, _ in iter_array_batches(A, 40):
            seq += 1
            reg.append(sid, Xb, seq=seq)
        out = reg.finalize(sid)
        t = FJLT(n, s, Context(seed=5), fut="wht")
        assert np.array_equal(
            out["SX"],
            np.asarray(t.apply(jnp.asarray(A), sk.COLUMNWISE)))


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------


class TestTelemetry:
    def test_prometheus_names(self, fresh_engine):
        from libskylark_tpu import telemetry

        rng = np.random.default_rng(25)
        t = FJLT(1024, 64, Context(seed=3), fut="wht")
        with _executor() as ex:
            ex.submit_sketch(t, _lattice(rng, (4, 1024)),
                             dimension=sk.ROWWISE).result(timeout=60)
            tc = CWT(512, 64, Context(seed=5))
            ex.submit_compressed_matmul(
                rng.standard_normal((4, 512)).astype(np.float32),
                rng.standard_normal((512, 3)).astype(np.float32),
                tc).result(timeout=60)
        text = telemetry.prometheus_text()
        assert "skylark_serve_fwht_flushes_total" in text
        assert "skylark_serve_compressed_matmul_submits_total" in text
