"""Gate mechanics of benchmarks/hlo_cost.py (stubbed configs — the
real lowering runs in script/ci; these tests exercise the ratchet
logic: growth fails, shrink/equal passes, vanished config fails,
jax-version mismatch demotes failures to informational)."""

from __future__ import annotations

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import hlo_cost  # noqa: E402


def _cfg(name, flops, bytes_accessed, temp=100):
    def fn():
        return {"config": name, "flops": float(flops),
                "bytes_accessed": float(bytes_accessed),
                "argument_bytes": 1, "output_bytes": 1,
                "temp_bytes": temp}
    fn.__name__ = f"cfg_{name}"
    return fn


@pytest.fixture
def harness(monkeypatch, tmp_path):
    import jax

    monkeypatch.setattr(hlo_cost, "HERE", str(tmp_path))

    def write_prior(rnd, rows, jax_version=None):
        (tmp_path / f"hlo_cost_r{rnd:02d}.json").write_text(json.dumps(
            {"backend": "cpu",
             "jax_version": jax_version or jax.__version__,
             "results": rows}))

    def run(argv, configs):
        monkeypatch.setattr(hlo_cost, "CONFIGS", tuple(configs))
        monkeypatch.setattr(sys, "argv", ["hlo_cost.py"] + argv)
        try:
            hlo_cost.main()
        except SystemExit as e:
            return e.code if isinstance(e.code, int) else 1
        return 0

    return write_prior, run, tmp_path


def test_gate_passes_at_parity_and_fails_on_growth(harness):
    write_prior, run, _ = harness
    write_prior(4, [_cfg("a", 1000, 5000)()])
    assert run(["--gate"], [_cfg("a", 1000, 5000)]) == 0
    assert run(["--gate"], [_cfg("a", 1000, 4000)]) == 0   # shrink ok
    assert run(["--gate"], [_cfg("a", 1200, 5000)]) == 1   # flops +20%
    assert run(["--gate"], [_cfg("a", 1000, 6000)]) == 1   # bytes +20%
    assert run(["--gate"], [_cfg("a", 1000, 5000, temp=200)]) == 1


def test_gate_fails_on_vanished_config_and_frees_new(harness):
    write_prior, run, _ = harness
    write_prior(4, [_cfg("a", 1000, 5000)()])
    # a new config is free; the vanished one fails
    assert run(["--gate"], [_cfg("b", 9e9, 9e9)]) == 1
    assert run(["--gate"], [_cfg("a", 1000, 5000),
                            _cfg("b", 9e9, 9e9)]) == 0


def test_only_scopes_the_gate(harness):
    write_prior, run, _ = harness
    write_prior(4, [_cfg("a", 1000, 5000)(), _cfg("b", 1000, 5000)()])
    # scoped run must not judge the unran config as vanished
    assert run(["--gate", "--only", "a"], [_cfg("a", 1000, 5000),
                                           _cfg("b", 1000, 5000)]) == 0


def test_jax_version_mismatch_is_informational(harness):
    write_prior, run, _ = harness
    write_prior(4, [_cfg("a", 1000, 5000)()], jax_version="0.0.1")
    assert run(["--gate"], [_cfg("a", 5000, 5000)]) == 0


def test_save_writes_artifact_and_excludes_self_from_prior(harness):
    write_prior, run, tmp = harness
    write_prior(4, [_cfg("a", 1000, 5000)()])
    assert run(["--save", "90", "--gate"], [_cfg("a", 1000, 5000)]) == 0
    doc = json.loads((tmp / "hlo_cost_r90.json").read_text())
    assert doc["results"][0]["flops"] == 1000.0
    # now regress: the prior must be r4 (not the just-saved r90 clone)
    assert run(["--save", "91", "--gate"], [_cfg("a", 2000, 5000)]) == 1
