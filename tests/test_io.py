"""IO layer tests: libsvm/arc-list/HDF5 round trips, streaming sketch,
native-vs-Python parser agreement.

Mirrors the reference's IO test strategy (ref: tests/unit/io_test.py —
write/read round trip compared by norm; tests/unit/ReadArcList.cpp)."""

import io as pyio

import jax.numpy as jnp
import numpy as np
import pytest

import libskylark_tpu.io as skio
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.sparse import SparseMatrix


LIBSVM_TEXT = """\
1 2:0.5 4:1.25
-1 1:3 3:-0.75
1 4:2
-1 2:-1.5 3:0.25 4:0.125
"""


def _dense_ref():
    X = np.zeros((4, 4), dtype=np.float32)
    X[0, 1] = 0.5
    X[0, 3] = 1.25
    X[1, 0] = 3
    X[1, 2] = -0.75
    X[2, 3] = 2
    X[3, 1] = -1.5
    X[3, 2] = 0.25
    X[3, 3] = 0.125
    Y = np.array([1, -1, 1, -1], dtype=np.float32)
    return X, Y


class TestLibsvm:
    def test_read_dense_rows(self):
        X, Y = skio.read_libsvm(pyio.StringIO(LIBSVM_TEXT))
        Xr, Yr = _dense_ref()
        np.testing.assert_allclose(X, Xr)
        np.testing.assert_allclose(Y, Yr)

    def test_read_dense_columns(self):
        X, Y = skio.read_libsvm(pyio.StringIO(LIBSVM_TEXT),
                                direction=skio.libsvm.COLUMNS)
        Xr, Yr = _dense_ref()
        np.testing.assert_allclose(X, Xr.T)
        np.testing.assert_allclose(Y, Yr)

    def test_read_sparse(self):
        X, Y = skio.read_libsvm(pyio.StringIO(LIBSVM_TEXT), sparse=True)
        Xr, _ = _dense_ref()
        assert isinstance(X, SparseMatrix)
        np.testing.assert_allclose(np.asarray(X.todense()), Xr)

    def test_min_d_max_n(self):
        X, Y = skio.read_libsvm(pyio.StringIO(LIBSVM_TEXT), min_d=7, max_n=2)
        assert X.shape == (2, 7)
        assert Y.shape == (2,)

    def test_multitarget(self):
        text = "1 2 1:0.5\n3 4 2:1.5\n"
        X, Y = skio.read_libsvm(pyio.StringIO(text))
        assert Y.shape == (2, 2)
        np.testing.assert_allclose(Y, [[1, 2], [3, 4]])
        assert X.shape == (2, 2)

    def test_comment_terminates(self):
        text = "1 1:2\n# done\n1 1:3\n"
        X, Y = skio.read_libsvm(pyio.StringIO(text))
        assert X.shape[0] == 1

    def test_write_read_roundtrip(self, tmp_path):
        Xr, Yr = _dense_ref()
        p = tmp_path / "data.libsvm"
        skio.write_libsvm(p, Xr, Yr)
        X, Y = skio.read_libsvm(p)
        np.testing.assert_allclose(X, Xr, rtol=1e-6)
        np.testing.assert_allclose(Y, Yr)

    def test_write_sparse_roundtrip(self, tmp_path):
        Xr, Yr = _dense_ref()
        p = tmp_path / "data.libsvm"
        skio.write_libsvm(p, SparseMatrix.from_dense(Xr), Yr)
        X, Y = skio.read_libsvm(p, sparse=True)
        np.testing.assert_allclose(np.asarray(X.todense()), Xr, rtol=1e-6)

    def test_read_dir(self, tmp_path):
        Xr, Yr = _dense_ref()
        (tmp_path / "part0").write_text("1 2:0.5 4:1.25\n-1 1:3 3:-0.75\n")
        (tmp_path / "part1").write_text("1 4:2\n-1 2:-1.5 3:0.25 4:0.125\n")
        X, Y = skio.read_dir_libsvm(str(tmp_path))
        np.testing.assert_allclose(X, Xr)
        np.testing.assert_allclose(Y, Yr)

    def test_native_matches_python(self, tmp_path):
        from libskylark_tpu.io import native
        from libskylark_tpu.io.libsvm import _open_lines, _parse_lines

        parsed = native.parse_libsvm(pyio.StringIO(LIBSVM_TEXT))
        if parsed is None:
            pytest.skip("native library unavailable")
        t_n, i_n, v_n, d_n, nt_n = parsed
        t_p, i_p, v_p, d_p, nt_p = _parse_lines(
            LIBSVM_TEXT.splitlines(), -1)
        assert (d_n, nt_n) == (d_p, nt_p)
        assert len(t_n) == len(t_p)
        for a, b in zip(t_n, t_p):
            np.testing.assert_allclose(a, b)
        for a, b in zip(i_n, i_p):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(v_n, v_p):
            np.testing.assert_allclose(a, b)


class TestArcList:
    TEXT = "# a comment\n0 1\n1 2 2.5\n2 0\n"

    def test_read(self):
        A = skio.read_arc_list(pyio.StringIO(self.TEXT))
        D = np.asarray(A.todense())
        assert A.shape == (3, 3)
        assert D[0, 1] == 1 and D[1, 2] == 2.5 and D[2, 0] == 1

    def test_symmetrize(self):
        A = skio.read_arc_list(pyio.StringIO(self.TEXT), symmetrize=True)
        D = np.asarray(A.todense())
        np.testing.assert_allclose(D, D.T)
        assert D[2, 1] == 2.5

    def test_roundtrip(self, tmp_path):
        A = skio.read_arc_list(pyio.StringIO(self.TEXT))
        p = tmp_path / "graph.txt"
        skio.write_arc_list(p, A)
        B = skio.read_arc_list(p)
        np.testing.assert_allclose(
            np.asarray(A.todense()), np.asarray(B.todense()))

    def test_native_matches_python(self):
        from libskylark_tpu.io import native

        parsed = native.parse_arc_list(pyio.StringIO(self.TEXT))
        if parsed is None:
            pytest.skip("native library unavailable")
        src, dst, w = parsed
        np.testing.assert_array_equal(src, [0, 1, 2])
        np.testing.assert_array_equal(dst, [1, 2, 0])
        np.testing.assert_allclose(w, [1.0, 2.5, 1.0])


@pytest.mark.skipif(not skio.have_hdf5(), reason="h5py unavailable")
class TestHDF5:
    def test_dense_roundtrip(self, tmp_path):
        Xr, Yr = _dense_ref()
        p = tmp_path / "data.h5"
        skio.write_hdf5(p, Xr, Yr)
        X, Y = skio.read_hdf5(p)
        np.testing.assert_allclose(X, Xr)
        np.testing.assert_allclose(Y, Yr)

    def test_sparse_roundtrip(self, tmp_path):
        Xr, Yr = _dense_ref()
        p = tmp_path / "data.h5"
        skio.write_hdf5(p, SparseMatrix.from_dense(Xr), Yr)
        X, Y = skio.read_hdf5(p, sparse=True)
        assert isinstance(X, SparseMatrix)
        np.testing.assert_allclose(np.asarray(X.todense()), Xr)
        # reference layout datasets present (ref: ml/io.hpp:124-205)
        import h5py

        with h5py.File(p, "r") as f:
            assert {"dimensions", "indptr", "indices", "values", "Y"} <= set(f)


class TestStreaming:
    def test_matches_one_shot_cwt(self):
        """Streaming sketch == one-shot CWT on concatenated data — the
        layout/arrival-order independence invariant."""
        from libskylark_tpu.sketch import COLUMNWISE
        from libskylark_tpu.sketch.hash import CWT

        rng = np.random.default_rng(0)
        n, d, s = 48, 6, 8
        X = rng.standard_normal((n, d)).astype(np.float32)
        Y = rng.integers(0, 2, n).astype(np.float32) * 2 - 1

        ctx = Context(seed=7)
        stream = skio.StreamingCWT(n, s, ctx)
        batches = [(X[i:i + 16], Y[i:i + 16]) for i in range(0, n, 16)]
        SX, SY = stream.sketch(iter(batches))

        cwt = CWT(n, s, Context(seed=7))
        SX_ref = cwt.apply(X, COLUMNWISE)
        np.testing.assert_allclose(np.asarray(SX), np.asarray(SX_ref),
                                   rtol=1e-5, atol=1e-5)
        SY_ref = cwt.apply(Y[:, None], COLUMNWISE)[:, 0]
        np.testing.assert_allclose(np.asarray(SY), np.asarray(SY_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_multiclass_stream(self):
        n, d, s, c = 30, 5, 6, 4
        rng = np.random.default_rng(1)
        X = rng.standard_normal((n, d)).astype(np.float32)
        Y = rng.integers(0, c, n)
        stream = skio.StreamingCWT(n, s, Context(seed=3))
        SX, SY = stream.sketch(
            [(X[:15], Y[:15]), (X[15:], Y[15:])], num_classes=c)
        assert SX.shape == (s, d)
        assert SY.shape == (s, c)


class TestStreamingOverlap:
    """Double-buffered prefetch (io/chunked.prefetch_batches wired into
    StreamingCWT.sketch): overlap must move bytes EARLIER without
    changing a single bit of the result."""

    def _data(self, n=192, d=6):
        rng = np.random.default_rng(0)
        X = rng.standard_normal((n, d)).astype(np.float32)
        Y = rng.integers(0, 2, n).astype(np.float32) * 2 - 1
        return X, Y

    def test_double_buffered_bit_equal_to_one_shot(self):
        """The acceptance oracle: the streaming double-buffered path is
        BIT-equal to the one-shot CWT.apply on the concatenated data
        (carried-accumulator scatter + value-preserving prefetch)."""
        from libskylark_tpu.sketch import COLUMNWISE
        from libskylark_tpu.sketch.hash import CWT

        n, d, s = 192, 6, 8
        X, Y = self._data(n, d)
        batches = [(X[i:i + 32], Y[i:i + 32]) for i in range(0, n, 32)]

        SX, SY = skio.StreamingCWT(n, s, Context(seed=7)).sketch(
            iter(batches), prefetch_depth=2)
        cwt = CWT(n, s, Context(seed=7))
        SX_ref = cwt.apply(jnp.asarray(X), COLUMNWISE)
        SY_ref = cwt.apply(jnp.asarray(Y[:, None]), COLUMNWISE)[:, 0]
        np.testing.assert_array_equal(np.asarray(SX), np.asarray(SX_ref))
        np.testing.assert_array_equal(np.asarray(SY), np.asarray(SY_ref))

    def test_prefetch_bit_equal_to_synchronous(self):
        n, s = 192, 8
        X, Y = self._data(n)
        batches = [(X[i:i + 32], Y[i:i + 32]) for i in range(0, n, 32)]
        SX_pf, SY_pf = skio.StreamingCWT(n, s, Context(seed=7)).sketch(
            iter(batches), prefetch_depth=3)
        SX_sy, SY_sy = skio.StreamingCWT(n, s, Context(seed=7)).sketch(
            iter(batches), prefetch_depth=0)
        np.testing.assert_array_equal(np.asarray(SX_pf),
                                      np.asarray(SX_sy))
        np.testing.assert_array_equal(np.asarray(SY_pf),
                                      np.asarray(SY_sy))

    def test_prefetch_preserves_order_and_devices_leading_array(self):
        import jax as _jax

        items = [(np.full((2, 2), i, np.float32), i) for i in range(7)]
        out = list(skio.prefetch_batches(iter(items), depth=2))
        assert [y for _, y in out] == list(range(7))
        for X, i in out:
            assert isinstance(X, _jax.Array)  # moved to device
            np.testing.assert_array_equal(np.asarray(X),
                                          np.full((2, 2), i, np.float32))

    def test_prefetch_depth_zero_is_synchronous_passthrough(self):
        items = [(np.zeros((1, 1), np.float32), k) for k in range(3)]
        out = list(skio.prefetch_batches(iter(items), depth=0))
        assert [k for _, k in out] == [0, 1, 2]

    def test_prefetch_propagates_producer_exception_in_position(self):
        def gen():
            yield (np.zeros((1, 1), np.float32), 0)
            yield (np.zeros((1, 1), np.float32), 1)
            raise RuntimeError("stream broke")

        it = skio.prefetch_batches(gen(), depth=2)
        assert next(it)[1] == 0
        assert next(it)[1] == 1
        with pytest.raises(RuntimeError, match="stream broke"):
            next(it)

    def test_prefetch_consumer_abandon_does_not_hang(self):
        produced = []

        def gen():
            for i in range(1000):
                produced.append(i)
                yield (np.zeros((1, 1), np.float32), i)

        it = skio.prefetch_batches(gen(), depth=2)
        next(it)
        it.close()  # abandon early: worker must stop, not deadlock
        assert len(produced) < 1000

    def test_stream_sketch_libsvm_prefetch_matches_sync(self, tmp_path):
        rng = np.random.default_rng(5)
        lines = []
        for i in range(40):
            feats = " ".join(f"{j + 1}:{rng.standard_normal():.5f}"
                             for j in range(6))
            lines.append(f"{1 if i % 2 else -1} {feats}\n")
        p = tmp_path / "data.libsvm"
        p.write_text("".join(lines))
        a = skio.stream_sketch_libsvm(str(p), 8, Context(seed=2),
                                      batch_rows=16, prefetch_depth=2)
        b = skio.stream_sketch_libsvm(str(p), 8, Context(seed=2),
                                      batch_rows=16, prefetch_depth=0)
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


class TestReviewRegressions:
    def test_dir_shard_trailing_blank_line(self, tmp_path):
        """A trailing blank line in one shard must not swallow later shards."""
        (tmp_path / "part0").write_text("1 1:2\n\n")
        (tmp_path / "part1").write_text("1 1:3\n")
        X, Y = skio.read_dir_libsvm(str(tmp_path))
        assert X.shape == (2, 1)
        np.testing.assert_allclose(X[:, 0], [2, 3])

    def test_native_rejects_short_label_row(self):
        """'3 2:1.5' under nt=2 must error in BOTH parsers (native parity)."""
        from libskylark_tpu.base import errors
        from libskylark_tpu.io import native
        from libskylark_tpu.io.libsvm import _parse_lines

        text = "1 2 1:0.5\n3 2:1.5\n"
        with pytest.raises(errors.IOError_):
            _parse_lines(text.splitlines(), -1)
        if native._load() is None:
            pytest.skip("native library unavailable")
        with pytest.raises(errors.IOError_):
            native.parse_libsvm(pyio.StringIO(text))
