"""Streaming/oversized ingestion: bounded-memory readers equal the
one-shot readers (ref: utility/io/libsvm_io.hpp:812-1371 chunked readers,
utility/hdfs.hpp line streamer; the oracle is the whole-file path)."""

import io as _io

import numpy as np
import pytest

from libskylark_tpu import io as skio
from libskylark_tpu.base.context import Context
from libskylark_tpu.sketch import CWT, COLUMNWISE


def _write_libsvm(tmp_path, n=57, d=12, nt=1, seed=0):
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((n, d)) *
         (rng.uniform(size=(n, d)) < 0.4)).astype(np.float32)
    Y = rng.integers(0, 3, size=(n,)).astype(np.float32)
    p = tmp_path / "data.libsvm"
    skio.write_libsvm(str(p), X, Y)
    return str(p), X, Y


def test_scan_dims(tmp_path):
    p, X, Y = _write_libsvm(tmp_path)
    n, d, nt = skio.scan_libsvm_dims(p)
    assert n == X.shape[0]
    assert nt == 1
    # d is the max feature index seen — zero trailing columns collapse,
    # same as the one-shot reader
    X1, _ = skio.read_libsvm(p)
    assert d == X1.shape[1]


@pytest.mark.parametrize("batch_rows", [7, 64])
def test_iter_batches_equals_one_shot(tmp_path, batch_rows):
    p, _, _ = _write_libsvm(tmp_path)
    X1, Y1 = skio.read_libsvm(p)
    xs, ys = zip(*skio.iter_libsvm_batches(p, batch_rows, d=X1.shape[1]))
    np.testing.assert_allclose(np.concatenate(xs), X1, atol=1e-6)
    np.testing.assert_allclose(np.concatenate(ys), Y1, atol=1e-6)


def test_iter_batches_sparse(tmp_path):
    p, _, _ = _write_libsvm(tmp_path)
    X1, _ = skio.read_libsvm(p)
    batches = list(skio.iter_libsvm_batches(
        p, 10, d=X1.shape[1], sparse=True))
    dense = np.concatenate(
        [b.to_scipy().toarray() for b, _ in batches])
    np.testing.assert_allclose(dense, X1, atol=1e-6)


def test_iter_batches_from_stream_needs_d(tmp_path):
    p, _, _ = _write_libsvm(tmp_path)
    text = open(p).read()
    from libskylark_tpu.base import errors

    with pytest.raises(errors.InvalidParametersError):
        next(skio.iter_libsvm_batches(_io.StringIO(text), 8))
    # with d supplied, a one-shot stream works (the HDFS seam)
    X1, _ = skio.read_libsvm(p)
    xs = [x for x, _ in skio.iter_libsvm_batches(
        _io.StringIO(text), 8, d=X1.shape[1])]
    np.testing.assert_allclose(np.concatenate(xs), X1, atol=1e-6)


@pytest.mark.parametrize("n", [64, 53])
def test_read_sharded_equals_one_shot(tmp_path, mesh1d, n):
    """Batches land sharded over the mesh; ragged n zero-pads the tail
    shard and slices back."""
    p, _, _ = _write_libsvm(tmp_path, n=n, seed=3)
    X1, Y1 = skio.read_libsvm(p)
    X, Y = skio.read_libsvm_sharded(p, mesh1d, batch_rows=9)
    assert X.shape == X1.shape
    np.testing.assert_allclose(np.asarray(X), X1, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Y), Y1, atol=1e-6)


def test_read_sharded_2d_mesh(tmp_path, mesh2d):
    """On a 2D mesh, P('rows', None) replicates each shard across the
    column axis — every replica device must receive the shard's data
    (regression test for mesh-order device placement)."""
    p, _, _ = _write_libsvm(tmp_path, n=48, seed=6)
    X1, Y1 = skio.read_libsvm(p)
    X, Y = skio.read_libsvm_sharded(p, mesh2d, batch_rows=11)
    np.testing.assert_allclose(np.asarray(X), X1, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Y), Y1, atol=1e-6)


def test_stream_sketch_equals_one_shot(tmp_path):
    """Chunked streaming sketch == one-shot CWT of the whole file
    (counter-stream order independence)."""
    p, _, _ = _write_libsvm(tmp_path, n=40, seed=4)
    X1, Y1 = skio.read_libsvm(p)
    s = 16
    SX, SY = skio.stream_sketch_libsvm(p, s, Context(seed=9), batch_rows=7)
    T = CWT(X1.shape[0], s, Context(seed=9))
    want = np.asarray(T.apply(X1, COLUMNWISE))
    np.testing.assert_allclose(np.asarray(SX), want, atol=1e-4)


def test_hdf5_batches(tmp_path):
    pytest.importorskip("h5py")
    rng = np.random.default_rng(5)
    X = rng.standard_normal((33, 6)).astype(np.float32)
    Y = rng.standard_normal(33).astype(np.float32)
    p = str(tmp_path / "d.h5")
    skio.write_hdf5(p, X, Y)
    xs, ys = zip(*skio.iter_hdf5_batches(p, 8))
    np.testing.assert_allclose(np.concatenate(xs), X, atol=1e-6)
    np.testing.assert_allclose(np.concatenate(ys), Y, atol=1e-6)


class _WebHDFSStub:
    """Minimal in-process WebHDFS REST endpoint: the namenode answers OPEN
    with a 307 redirect to a /data URL on the same server (the
    namenode→datanode hop of the real protocol), which then streams the
    file bytes. Runs on 127.0.0.1 — exercises the full urllib path of
    io/webhdfs.py without any external service."""

    def __init__(self, files: dict):
        import http.server
        import threading

        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                u = urlparse(self.path)
                if u.path.startswith("/webhdfs/v1"):
                    q = parse_qs(u.query)
                    assert q.get("op") == ["OPEN"], q
                    hdfs_path = u.path[len("/webhdfs/v1"):]
                    self.send_response(307)
                    self.send_header(
                        "Location",
                        f"http://127.0.0.1:{stub.port}/data{hdfs_path}")
                    self.end_headers()
                elif u.path.startswith("/data"):
                    body = stub.files.get(u.path[len("/data"):])
                    if body is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

        self.files = files
        self.httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_webhdfs_transport_lines(tmp_path):
    """webhdfs_lines streams a file through the REST protocol (with the
    namenode→datanode redirect) and yields the same lines as local open —
    including a file without a trailing newline and multi-chunk reads."""
    content = "".join(f"line {i} αβ\n" for i in range(500)) + "tail-no-nl"
    stub = _WebHDFSStub({"/user/x/data.txt": content.encode()})
    try:
        got = list(skio.webhdfs_lines(
            stub.url, "/user/x/data.txt", buffer_bytes=256))
    finally:
        stub.close()
    assert got == content.splitlines(keepends=True)


def test_webhdfs_feeds_the_reader_seam(tmp_path, mesh1d):
    """The transport plugs into the chunked readers: read_libsvm_sharded
    off a WebHDFS stream == local file read (ref: the reference's HDFS
    libsvm variants, utility/io/libsvm_io.hpp:1395-1876)."""
    p, _, _ = _write_libsvm(tmp_path, n=24, seed=11)
    with open(p) as fh:
        body = fh.read().encode()
    stub = _WebHDFSStub({"/ds/train.libsvm": body})
    try:
        X1, Y1 = skio.read_libsvm(p)
        # dims scan + data pass are two separate streams over the seam
        n, d, _ = skio.scan_libsvm_dims(
            skio.webhdfs_lines(stub.url, "/ds/train.libsvm"))
        X, Y = skio.read_libsvm_sharded(
            skio.webhdfs_lines(stub.url, "/ds/train.libsvm"), mesh1d,
            batch_rows=7, dims=(n, d))
    finally:
        stub.close()
    np.testing.assert_allclose(np.asarray(X), X1, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Y), Y1, atol=1e-6)


def test_sharded_read_dims_with_max_n(tmp_path, mesh1d):
    """dims + an explicit smaller max_n truncates the shard plan itself
    instead of raising a spurious stream-shrunk error."""
    p, _, _ = _write_libsvm(tmp_path, n=30, seed=12)
    with open(p) as fh:
        lines = fh.readlines()
    n, d, nt = skio.scan_libsvm_dims(iter(lines))
    X, Y = skio.read_libsvm_sharded(iter(lines), mesh1d, max_n=10,
                                    dims=(n, d, nt))
    X_full, _ = skio.read_libsvm(p)
    assert X.shape[0] == 10
    np.testing.assert_allclose(np.asarray(X), X_full[:10], atol=1e-6)
