"""Krylov solvers on mesh-sharded operands == local (the solver-level
analog of the reference's multi-rank unit tests — LSQR/CG are templated
over distributed matrix types and run under mpirun there; here the same
solver code takes sharded arrays and XLA inserts the collectives,
ref: algorithms/Krylov/LSQR.hpp:21, CG.hpp:23, internal.hpp replicated
scalars)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from libskylark_tpu import parallel as par
from libskylark_tpu.algorithms.krylov import (
    KrylovParams,
    cg,
    chebyshev,
    flexible_cg,
    lsqr,
)


@pytest.fixture()
def problem():
    rng = np.random.default_rng(0)
    m, n, k = 96, 24, 3
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    B = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    return A, B


@pytest.mark.slow
def test_lsqr_sharded_matches_local(problem, mesh1d):
    A, B = problem
    X0, it0 = lsqr(A, B, KrylovParams(tolerance=1e-8, iter_lim=200))
    Ad = jax.device_put(A, NamedSharding(mesh1d, P("rows", None)))
    Bd = jax.device_put(B, NamedSharding(mesh1d, P("rows", None)))
    X1, it1 = lsqr(Ad, Bd, KrylovParams(tolerance=1e-8, iter_lim=200))
    np.testing.assert_allclose(
        np.asarray(X1), np.asarray(X0), atol=1e-4, rtol=1e-4
    )


def test_lsqr_sharded_5_device_submesh(devices):
    """np=5-style mesh diversity (jax NamedShardings need divisible dims,
    so the rows are a multiple of 5 — true ragged layouts live in the
    explicit-padding layers: shard_apply, dist_sparse, pallas_dense)."""
    rng = np.random.default_rng(2)
    m, n, k = 90, 24, 3
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    B = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    mesh5 = par.make_mesh(devices=devices[:5])
    X0, _ = lsqr(A, B, KrylovParams(tolerance=1e-8, iter_lim=200))
    Ad = jax.device_put(A, NamedSharding(mesh5, P("rows", None)))
    Bd = jax.device_put(B, NamedSharding(mesh5, P("rows", None)))
    X1, _ = lsqr(Ad, Bd, KrylovParams(tolerance=1e-8, iter_lim=200))
    np.testing.assert_allclose(
        np.asarray(X1), np.asarray(X0), atol=1e-4, rtol=1e-4
    )


def _spd(n=48, seed=1):
    rng = np.random.default_rng(seed)
    M = rng.standard_normal((n, n)).astype(np.float32)
    A = jnp.asarray(M @ M.T + n * np.eye(n, dtype=np.float32))
    B = jnp.asarray(rng.standard_normal((n, 2)), jnp.float32)
    return A, B


def _sharded(mesh, *arrays):
    sh = NamedSharding(mesh, P("rows", None))
    return tuple(jax.device_put(a, sh) for a in arrays)


def test_cg_sharded_matches_local(mesh1d):
    A, B = _spd()
    X0, _ = cg(A, B, KrylovParams(tolerance=1e-10, iter_lim=300))
    Ad, Bd = _sharded(mesh1d, A, B)
    X1, _ = cg(Ad, Bd, KrylovParams(tolerance=1e-10, iter_lim=300))
    np.testing.assert_allclose(
        np.asarray(X1), np.asarray(X0), atol=1e-4, rtol=1e-4
    )


def test_flexible_cg_sharded_matches_local(mesh1d):
    A, B = _spd(seed=4)
    X0, _ = flexible_cg(A, B, KrylovParams(tolerance=1e-10, iter_lim=300))
    Ad, Bd = _sharded(mesh1d, A, B)
    X1, _ = flexible_cg(Ad, Bd, KrylovParams(tolerance=1e-10, iter_lim=300))
    np.testing.assert_allclose(
        np.asarray(X1), np.asarray(X0), atol=1e-4, rtol=1e-4
    )


def test_chebyshev_sharded_matches_local(mesh1d):
    A, B = _spd(seed=5)
    w = np.linalg.eigvalsh(np.asarray(A))
    lo, hi = float(w[0]) * 0.9, float(w[-1]) * 1.1
    X0, _ = chebyshev(A, B, lo, hi, KrylovParams(iter_lim=80))
    Ad, Bd = _sharded(mesh1d, A, B)
    X1, _ = chebyshev(Ad, Bd, lo, hi, KrylovParams(iter_lim=80))
    np.testing.assert_allclose(
        np.asarray(X1), np.asarray(X0), atol=1e-4, rtol=1e-4
    )
