"""Memory proof for the panel-blocked apply paths.

The lazy-operator design exists to bound memory: blocked apply at panel
size b must allocate O(S_dim·b), never the full (S_dim × N) operator
(ref: sketch/dense_transform_data.hpp:79-152 realize_matrix_view;
sketch/sketch_params.hpp:15-19 "better performance, much more memory").
The reference checks memory with a valgrind ctest target
(ref: tests/CMakeLists.txt:2-10); the XLA-native analog here inspects the
traced computation: the largest intermediate array in the jaxpr of a
blocked apply must be panel-sized, and the test FAILS if anyone
materializes the full operator.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu.base.context import Context
from libskylark_tpu.sketch import JLT, ROWWISE, COLUMNWISE
from libskylark_tpu.sketch import params as sketch_params


def _max_intermediate_elems(jaxpr) -> int:
    """Largest output aval (in elements) over all eqns, recursing into
    nested jaxprs (scan/while/cond bodies, pjit calls)."""
    biggest = 0
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            aval = var.aval
            if hasattr(aval, "shape") and aval.shape:
                biggest = max(biggest, int(np.prod(aval.shape)))
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):  # ClosedJaxpr
                biggest = max(biggest, _max_intermediate_elems(v.jaxpr))
            elif hasattr(v, "eqns"):  # raw Jaxpr
                biggest = max(biggest, _max_intermediate_elems(v))
    return biggest


@pytest.fixture(autouse=True)
def _no_pallas():
    sketch_params.set_use_pallas(False)
    yield
    sketch_params.set_use_pallas(True)


@pytest.mark.parametrize("dimension", [ROWWISE, COLUMNWISE])
def test_blocked_apply_is_panel_bounded(dimension):
    """At blocksize b, no intermediate exceeds O(S·b + output)."""
    N, S, m, bs = 16384, 64, 8, 1024
    T = JLT(N, S, Context(seed=1))
    shape = (m, N) if dimension == ROWWISE else (N, m)
    A = jnp.zeros(shape, jnp.float32)

    sketch_params.set_blocksize(bs)
    try:
        jaxpr = jax.make_jaxpr(lambda X: T.apply(X, dimension))(A)
    finally:
        sketch_params.set_blocksize(0)

    biggest = _max_intermediate_elems(jaxpr.jaxpr)
    full_S = S * N                       # 1,048,576 elements
    panel_budget = S * bs + N * m + 4096  # panel + input + slack
    assert biggest < full_S, (
        f"blocked apply materialized a {biggest}-element intermediate "
        f"(full operator is {full_S}) — the memory bound is broken"
    )
    assert biggest <= panel_budget, (
        f"largest intermediate {biggest} exceeds the panel budget "
        f"{panel_budget}"
    )


@pytest.mark.slow
def test_auto_blocking_guards_huge_operators():
    """With blocksize unset, an apply whose operator exceeds the
    auto-block threshold takes the panel path anyway — the memory-safety
    default the reference gets from blocksize=1000."""
    N, S, m = 16384, 64, 8
    T = JLT(N, S, Context(seed=2))
    A = jnp.zeros((m, N), jnp.float32)
    old = sketch_params.get_auto_block_bytes()
    sketch_params.set_auto_block_bytes(1 << 20)  # 1 MiB: S (4 MiB) exceeds
    try:
        jaxpr = jax.make_jaxpr(lambda X: T.apply(X, ROWWISE))(A)
    finally:
        sketch_params.set_auto_block_bytes(old)
    assert _max_intermediate_elems(jaxpr.jaxpr) < S * N
    # correctness at the auto-chosen panel size
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, N)), jnp.float32)
    want = np.asarray(T.apply(A, ROWWISE))
    sketch_params.set_auto_block_bytes(1 << 20)
    try:
        got = np.asarray(T.apply(A, ROWWISE))
    finally:
        sketch_params.set_auto_block_bytes(old)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_unblocked_apply_does_materialize():
    """Sanity check on the measuring stick: with blocking off, the full
    operator IS an intermediate — so the blocked assertion above is
    actually measuring the thing it claims to measure."""
    N, S, m = 16384, 64, 8
    T = JLT(N, S, Context(seed=1))
    A = jnp.zeros((m, N), jnp.float32)
    jaxpr = jax.make_jaxpr(lambda X: T.apply(X, ROWWISE))(A)
    assert _max_intermediate_elems(jaxpr.jaxpr) >= S * N


def test_shard_apply_pipeline_is_panel_bounded(mesh1d):
    """The explicit shard_map pipeline holds one BLOCK_COLS panel per
    device: largest per-device intermediate must be panel-sized, not the
    (S × N/p) operator shard."""
    from libskylark_tpu.parallel import shard_apply
    from libskylark_tpu.sketch.dense import BLOCK_COLS

    N, S, m = 16384, 64, 8
    T = JLT(N, S, Context(seed=2))
    A = jnp.zeros((m, N), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda X: shard_apply.rowwise(T, X, mesh1d, use_pallas=False)
    )(A)
    biggest = _max_intermediate_elems(jaxpr.jaxpr)
    shard_S = S * (N // 8)               # the lazy win: never materialized
    panel_budget = S * BLOCK_COLS + N * m + 4096
    assert biggest < shard_S
    assert biggest <= panel_budget
