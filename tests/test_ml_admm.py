"""BlockADMM + HilbertModel tests.

Oracles: objective decrease over iterations, end-to-end fit quality on
synthetic data (linear regression and kernel classification), and model
save/load round trip reproducing predictions exactly (the counter-based
serialization guarantee, ref: ml/model.hpp:103-137)."""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from libskylark_tpu import Context, ml
from libskylark_tpu.algorithms import prox


def _linear_data(n=80, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32)
    y = X @ w + 0.05 * rng.standard_normal(n).astype(np.float32)
    return X, y.astype(np.float32)


def _blobs(n_per=50, d=4, seed=1):
    rng = np.random.default_rng(seed)
    X0 = rng.standard_normal((n_per, d)) - 2.0
    X1 = rng.standard_normal((n_per, d)) + 2.0
    X = np.vstack([X0, X1]).astype(np.float32)
    y = np.array([0] * n_per + [1] * n_per)
    perm = rng.permutation(2 * n_per)
    return X[perm], y[perm]


class TestHilbertModel:
    def _make(self):
        ctx = Context(seed=21)
        k = ml.Gaussian(5, sigma=2.0)
        maps = [k.create_rft(8, ctx), k.create_rft(8, ctx)]
        m = ml.HilbertModel(maps, True, 16, 3, regression=False, input_size=5)
        rng = np.random.default_rng(2)
        m.coef = jnp.asarray(rng.standard_normal((16, 3)).astype(np.float32))
        return m

    def test_save_load_round_trip(self, tmp_path):
        m = self._make()
        X = np.random.default_rng(3).standard_normal((10, 5)).astype(np.float32)
        labels, DV = m.predict(X)
        f = tmp_path / "model.json"
        m.save(str(f), header="test model\nsecond line")
        m2 = ml.HilbertModel.load(str(f))
        labels2, DV2 = m2.predict(X)
        np.testing.assert_array_equal(np.asarray(labels), np.asarray(labels2))
        np.testing.assert_allclose(np.asarray(DV), np.asarray(DV2), rtol=1e-6)

    def test_json_fields(self):
        d = self._make().to_dict()
        assert d["skylark_object_type"] == "model:linear-on-features"
        assert d["feature_mapping"]["number_maps"] == 2
        json.dumps(d)  # fully JSON-serializable

    def test_linear_model_no_maps(self):
        m = ml.HilbertModel([], False, 4, 1, regression=True)
        m.coef = jnp.ones((4, 1), jnp.float32)
        X = np.eye(4, dtype=np.float32)
        _, DV = m.predict(X)
        np.testing.assert_allclose(np.asarray(DV).ravel(), 1.0)

    def test_sign_decode_single_output(self):
        m = ml.HilbertModel([], False, 2, 1, regression=False)
        m.coef = jnp.asarray([[1.0], [0.0]], jnp.float32)
        labels, _ = m.predict(np.array([[3.0, 0.0], [-2.0, 0.0]], np.float32))
        np.testing.assert_array_equal(np.asarray(labels), [1, -1])


class TestBlockADMMLinear:
    def test_linear_regression_fits(self):
        X, y = _linear_data()
        solver = ml.BlockADMMSolver(
            prox.SquaredLoss(), prox.L2Regularizer(), 1e-4,
            num_features=X.shape[1], num_partitions=2,
        )
        solver.rho = 1.0
        solver.maxiter = 150
        model = solver.train(X, y, regression=True)
        _, DV = model.predict(X)
        rel = np.linalg.norm(np.asarray(DV).ravel() - y) / np.linalg.norm(y)
        assert rel < 0.15, rel

    def test_partition_sizes(self):
        s = ml.admm._partition(10, 3)
        assert s == [3, 3, 4] and sum(s) == 10


class TestBlockADMMKernel:
    @pytest.mark.parametrize("loss", [prox.HingeLoss(), prox.LogisticLoss()])
    @pytest.mark.slow
    def test_classification(self, loss):
        X, y = _blobs()
        solver = ml.BlockADMMSolver.from_kernel(
            Context(seed=30), loss, prox.L2Regularizer(), 1e-3,
            num_features=96, kernel=ml.Gaussian(4, sigma=3.0),
            num_partitions=3,
        )
        solver.maxiter = 60
        model = solver.train(X, y, regression=False)
        labels, _ = model.predict(X)
        assert (np.asarray(labels) == y).mean() > 0.9

    @pytest.mark.slow
    def test_model_round_trip_after_training(self, tmp_path):
        X, y = _blobs(seed=5)
        solver = ml.BlockADMMSolver.from_kernel(
            Context(seed=31), prox.HingeLoss(), prox.L2Regularizer(), 1e-3,
            num_features=32, kernel=ml.Gaussian(4, sigma=3.0),
            num_partitions=2,
        )
        solver.maxiter = 30
        model = solver.train(X, y)
        f = tmp_path / "m.json"
        model.save(str(f))
        m2 = ml.HilbertModel.load(str(f))
        l1, _ = model.predict(X)
        l2, _ = m2.predict(X)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))

    @pytest.mark.slow
    def test_cache_transforms_same_result(self):
        X, y = _linear_data(n=40, d=4, seed=7)
        def run(cache):
            solver = ml.BlockADMMSolver.from_kernel(
                Context(seed=32), prox.SquaredLoss(), prox.L2Regularizer(),
                1e-3, num_features=24, kernel=ml.Gaussian(4, sigma=2.0),
                num_partitions=2,
            )
            solver.maxiter = 20
            solver.cache_transforms = cache
            return solver.train(X, y, regression=True)
        m1, m2 = run(False), run(True)
        np.testing.assert_allclose(
            np.asarray(m1.coef), np.asarray(m2.coef), rtol=1e-4, atol=1e-5
        )
