"""Graph-algorithm tests: spectral embedding separates a planted partition;
TD-PPR diffusion is localized and seeded; sweep cut recovers a planted
community. Mirrors the reference's graph drivers (skylark_graph_se,
skylark_community) as library-level checks."""

import numpy as np
import pytest

from libskylark_tpu import Context, ml
from libskylark_tpu.nla.svd import ApproximateSVDParams


def _two_blocks(n_per=20, p_in=0.9, p_out=0.05, seed=0):
    """Planted 2-community graph."""
    rng = np.random.default_rng(seed)
    G = ml.Graph()
    n = 2 * n_per
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n_per) == (j < n_per)
            p = p_in if same else p_out
            if rng.random() < p:
                G.add_edge(i, j)
    return G


class TestGraph:
    def test_basic_counts(self):
        G = ml.Graph([(0, 1), (1, 2), (2, 0)])
        assert G.num_vertices() == 3
        assert G.num_edges() == 6  # both directions, ref convention
        assert G.degree(1) == 2

    def test_no_self_loops_no_dups(self):
        G = ml.Graph([(0, 0), (0, 1), (1, 0)])
        assert G.num_edges() == 2

    def test_adjacency_matrix(self):
        G = ml.Graph([(0, 1), (1, 2)])
        A, idx = G.adjacency_matrix()
        assert A.sum() == 4
        np.testing.assert_array_equal(A, A.T)


class TestApproximateASE:
    def test_separates_blocks(self):
        G = _two_blocks()
        X, idx = ml.approximate_ase(
            G, 2, Context(seed=5), ApproximateSVDParams(num_iterations=3)
        )
        X = np.asarray(X)
        # 2nd embedding coordinate splits the two blocks (1st is the
        # Perron direction).
        side = X[:, 1] > 0
        labels = np.array([v < 20 for v in idx])
        agree = (side == labels).mean()
        assert agree > 0.9 or agree < 0.1

    def test_sparse_operand_matches_dense(self):
        """The sparse-adjacency path (no densification) equals the dense
        path at the same seed — same randomized algorithm, same streams."""
        G = _two_blocks()
        p = ApproximateSVDParams(num_iterations=3)
        Xd, idxd = ml.approximate_ase(G, 2, Context(seed=5), p,
                                      sparse=False)
        Xs, idxs = ml.approximate_ase(G, 2, Context(seed=5), p,
                                      sparse=True)
        assert idxd == idxs
        np.testing.assert_allclose(
            np.asarray(Xs), np.asarray(Xd), atol=1e-3, rtol=1e-3
        )


class TestTimeDependentPPR:
    def test_localized_and_seeded(self):
        G = _two_blocks(seed=3)
        y, x = ml.time_dependent_ppr(G, {0: 1.0})
        assert len(x) == 4
        assert all(xi >= 0 and xi <= 5.0 for xi in x)
        assert 0 in y
        # Mass concentrates on the seed's community.
        in_mass = sum(v[0] for n, v in y.items() if n < 20)
        out_mass = sum(v[0] for n, v in y.items() if n >= 20)
        assert in_mass > out_mass

    def test_seed_not_in_graph_raises(self):
        G = ml.Graph([(0, 1)])
        with pytest.raises(Exception):
            ml.time_dependent_ppr(G, {99: 1.0})


class TestFindLocalCluster:
    def test_recovers_planted_community(self):
        G = _two_blocks(seed=7)
        cluster, cond = ml.find_local_cluster(G, {0, 1, 2})
        inside = sum(1 for v in cluster if v < 20)
        assert len(cluster) > 0
        assert inside / len(cluster) > 0.8
        assert 0 <= cond <= 1

    def test_recursive_does_not_worsen(self):
        G = _two_blocks(seed=9)
        _, cond1 = ml.find_local_cluster(G, {0})
        _, cond2 = ml.find_local_cluster(G, {0}, recursive=True)
        assert cond2 <= cond1 + 1e-12
