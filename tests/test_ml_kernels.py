"""ML kernel layer tests: distance matrices, label coding, Gram matrices,
random-feature-map consistency (E[z(x)·z(y)] ≈ k(x,y)), serialization.

The feature-map consistency checks are the statistical analog of the
reference's regression tests (ref: tests/regression/svd_test.py) — loose
tolerances, fixed seeds.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from libskylark_tpu import Context
from libskylark_tpu import ml
from libskylark_tpu import sketch as sk
from libskylark_tpu.base.distance import (
    euclidean_distance_matrix,
    l1_distance_matrix,
)


def _data(n, d, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return (scale * rng.standard_normal((n, d))).astype(np.float32)


class TestDistance:
    def test_euclidean_squared(self):
        X = _data(7, 4, 1)
        Y = _data(5, 4, 2)
        D = np.asarray(euclidean_distance_matrix(X, Y))
        brute = ((X[:, None, :] - Y[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(D, brute, rtol=1e-4, atol=1e-5)

    def test_l1(self):
        X = _data(6, 3, 3)
        Y = _data(4, 3, 4)
        D = np.asarray(l1_distance_matrix(X, Y))
        brute = np.abs(X[:, None, :] - Y[None, :, :]).sum(-1)
        np.testing.assert_allclose(D, brute, rtol=1e-5, atol=1e-6)


class TestCoding:
    def test_round_trip(self):
        labels = np.array([3, 1, 2, 1, 3, 2, 2])
        Y, coding = ml.dummy_coding(labels)
        assert Y.shape == (7, 3)
        assert np.all(np.asarray(Y).sum(axis=1) == -(len(coding) - 2))
        back = ml.dummy_decode(Y, coding)
        np.testing.assert_array_equal(back, labels)

    def test_reuse_coding(self):
        Y, coding = ml.dummy_coding([5, 7], coding=[5, 6, 7])
        assert Y.shape == (2, 3)
        assert np.asarray(Y)[0, 0] == 1 and np.asarray(Y)[1, 2] == 1


class TestGram:
    def test_gaussian_entries(self):
        X = _data(6, 3, 5)
        k = ml.Gaussian(3, sigma=1.7)
        K = np.asarray(k.symmetric_gram(X))
        i, j = 2, 4
        expect = np.exp(-np.sum((X[i] - X[j]) ** 2) / (2 * 1.7**2))
        assert abs(K[i, j] - expect) < 1e-5
        np.testing.assert_allclose(np.diag(K), 1.0, atol=1e-5)

    def test_polynomial(self):
        X = _data(5, 3, 6)
        k = ml.Polynomial(3, q=3, c=0.5, gamma=2.0)
        K = np.asarray(k.gram(X, X))
        expect = (2.0 * X @ X.T + 0.5) ** 3
        np.testing.assert_allclose(K, expect, rtol=1e-4)

    def test_laplacian(self):
        X = _data(5, 3, 7)
        k = ml.Laplacian(3, sigma=2.0)
        K = np.asarray(k.symmetric_gram(X))
        D = np.abs(X[:, None, :] - X[None, :, :]).sum(-1)
        np.testing.assert_allclose(K, np.exp(-D / 2.0), rtol=1e-4)

    def test_matern_half_is_exponential(self):
        X = _data(5, 3, 8)
        k = ml.Matern(3, nu=0.5, l=1.3)
        K = np.asarray(k.symmetric_gram(X))
        r = np.sqrt(((X[:, None, :] - X[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(K, np.exp(-r / 1.3), rtol=1e-3, atol=1e-5)

    def test_matern_general_nu_matches_closed_form(self):
        pytest.importorskip("scipy")
        X = _data(5, 3, 9)
        closed = np.asarray(ml.Matern(3, nu=1.5, l=1.1).symmetric_gram(X))
        general = np.asarray(ml.Matern(3, nu=1.5000001, l=1.1).symmetric_gram(X))
        np.testing.assert_allclose(closed, general, rtol=1e-3, atol=1e-4)

    def test_expsemigroup(self):
        rng = np.random.default_rng(10)
        X = rng.uniform(0.1, 2.0, (5, 3)).astype(np.float32)
        k = ml.ExpSemigroup(3, beta=0.5)
        K = np.asarray(k.symmetric_gram(X))
        expect = np.exp(-0.5 * np.sqrt(X[:, None, :] + X[None, :, :]).sum(-1))
        np.testing.assert_allclose(K, expect, rtol=1e-4)

    def test_linear(self):
        X = _data(4, 3, 11)
        K = np.asarray(ml.Linear(3).symmetric_gram(X))
        np.testing.assert_allclose(K, X @ X.T, rtol=1e-4, atol=1e-5)


class TestFeatureMapConsistency:
    """Z·Zᵀ ≈ K for large feature counts — the defining property of
    create_rft (ref: ml/kernels.hpp create_rft + sketch RFT family)."""

    @pytest.mark.parametrize(
        "kernel,tag",
        [
            (ml.Gaussian(6, sigma=2.0), "regular"),
            (ml.Gaussian(6, sigma=2.0), "quasi"),
            (ml.Laplacian(6, sigma=4.0), "regular"),
            (ml.Polynomial(6, q=2, c=0.0, gamma=1.0), "regular"),
        ],
    )
    def test_gram_approximation(self, kernel, tag):
        X = _data(10, 6, 12, scale=0.5)
        K = np.asarray(kernel.symmetric_gram(X))
        S = kernel.create_rft(4096, Context(seed=13), tag)
        Z = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE))
        Kz = Z @ Z.T
        assert np.max(np.abs(Kz - K)) < 0.15, np.max(np.abs(Kz - K))

    def test_linear_jlt(self):
        X = _data(10, 6, 14)
        S = ml.Linear(6).create_rft(2048, Context(seed=15), "regular")
        Z = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE))
        np.testing.assert_allclose(Z @ Z.T, X @ X.T, atol=0.9)


class TestKernelSerialization:
    @pytest.mark.parametrize(
        "k",
        [
            ml.Linear(5),
            ml.Gaussian(5, sigma=2.5),
            ml.Polynomial(5, q=4, c=0.1, gamma=0.3),
            ml.Laplacian(5, sigma=1.5),
            ml.ExpSemigroup(5, beta=0.7),
            ml.Matern(5, nu=1.5, l=2.0),
        ],
    )
    def test_round_trip(self, k):
        k2 = ml.deserialize_kernel(k.to_json())
        assert type(k2) is type(k)
        assert k2.to_dict() == k.to_dict()

    def test_make_kernel(self):
        k = ml.make_kernel("gaussian", 8, sigma=3.0)
        assert isinstance(k, ml.Gaussian) and k.sigma == 3.0
