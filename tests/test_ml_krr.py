"""KRR/RLSC family tests.

Oracles: (a) exact algebraic identities — the solvers produce solutions of
known linear systems, checkable via normal equations; (b) regime agreement —
faster_kernel_ridge must match kernel_ridge (same system, different solver);
(c) end-to-end classification accuracy on separable data (the reference's
skylark_ml-style smoke test).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from libskylark_tpu import Context, ml
from libskylark_tpu import sketch as sk


def _regression_data(n=60, d=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal((d, 1)).astype(np.float32)
    Y = (X @ w + 0.01 * rng.standard_normal((n, 1))).astype(np.float32)
    return X, Y


def _blobs(n_per=40, d=4, seed=1):
    """Two well-separated Gaussian blobs."""
    rng = np.random.default_rng(seed)
    X0 = rng.standard_normal((n_per, d)) - 2.5
    X1 = rng.standard_normal((n_per, d)) + 2.5
    X = np.vstack([X0, X1]).astype(np.float32)
    y = np.array([0] * n_per + [1] * n_per)
    perm = rng.permutation(2 * n_per)
    return X[perm], y[perm]


class TestKernelRidge:
    def test_exact_solves_system(self):
        X, Y = _regression_data()
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        lam = 0.1
        A = ml.kernel_ridge(k, X, Y, lam)
        K = np.asarray(k.symmetric_gram(X))
        resid = (K + lam * np.eye(len(X))) @ np.asarray(A) - Y
        assert np.max(np.abs(resid)) < 1e-3

    @pytest.mark.slow
    def test_faster_matches_exact(self):
        X, Y = _regression_data(seed=2)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        lam = 0.5
        A_exact = np.asarray(ml.kernel_ridge(k, X, Y, lam))
        A_cg = np.asarray(
            ml.faster_kernel_ridge(
                k, X, Y, lam, 128, Context(seed=7),
                ml.KrrParams(tolerance=1e-7, iter_lim=400),
            )
        )
        np.testing.assert_allclose(A_cg, A_exact, rtol=1e-2, atol=1e-3)

    def test_faster_unpreconditioned(self):
        X, Y = _regression_data(seed=3)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        A_exact = np.asarray(ml.kernel_ridge(k, X, Y, 1.0))
        A_cg = np.asarray(
            ml.faster_kernel_ridge(
                k, X, Y, 1.0, 0, Context(seed=8),
                ml.KrrParams(tolerance=1e-7, iter_lim=400),
            )
        )
        np.testing.assert_allclose(A_cg, A_exact, rtol=1e-2, atol=1e-3)


class TestApproximateKernelRidge:
    def test_normal_equations(self):
        """W solves (ZᵀZ + λI)W = ZᵀY for the returned feature map."""
        X, Y = _regression_data(seed=4)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        lam = 0.2
        S, W = ml.approximate_kernel_ridge(k, X, Y, lam, 64, Context(seed=9))
        Z = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE))
        resid = (Z.T @ Z + lam * np.eye(64)) @ np.asarray(W) - Z.T @ Y
        assert np.max(np.abs(resid)) < 1e-3

    @pytest.mark.slow
    def test_sketched_rr_close_to_unsketched(self):
        X, Y = _regression_data(n=200, seed=5)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        ctx = Context(seed=10)
        S, W = ml.approximate_kernel_ridge(k, X, Y, 0.5, 32, ctx)
        S2, W2 = ml.approximate_kernel_ridge(
            k, X, Y, 0.5, 32, Context(seed=10),
            ml.KrrParams(sketched_rr=True, sketch_size=160),
        )
        # Same context seed/counter -> same feature map; sketching only
        # perturbs the solve.
        Z = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE))
        pred1 = Z @ np.asarray(W)
        pred2 = Z @ np.asarray(W2)
        rel = np.linalg.norm(pred1 - pred2) / np.linalg.norm(pred1)
        assert rel < 0.5

    def test_predicts(self):
        X, Y = _regression_data(n=100, seed=6)
        k = ml.Gaussian(X.shape[1], sigma=3.0)
        S, W = ml.approximate_kernel_ridge(k, X, Y, 0.01, 256, Context(seed=11))
        Z = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE))
        pred = Z @ np.asarray(W)
        rel = np.linalg.norm(pred - Y) / np.linalg.norm(Y)
        assert rel < 0.35


class TestSketchedApproximateKernelRidge:
    @pytest.mark.slow
    def test_splits_and_shapes(self):
        X, Y = _regression_data(n=80, seed=7)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        transforms, W = ml.sketched_approximate_kernel_ridge(
            k, X, Y, 0.1, 48, Context(seed=12),
            params=ml.KrrParams(max_split=20),
        )
        assert sum(t.sketch_dim for t in transforms) == 48
        assert len(transforms) > 1
        assert W.shape == (48, 1)

    @pytest.mark.slow
    def test_unbounded_split_schedule(self):
        """max_split=0 -> sinc = input dim, last chunk absorbs <= 2*sinc
        (ref: ml/krr.hpp:246-248)."""
        X, Y = _regression_data(n=50, seed=8)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        transforms, W = ml.sketched_approximate_kernel_ridge(
            k, X, Y, 0.1, 16, Context(seed=13), t=200,
        )
        assert [t.sketch_dim for t in transforms] == [5, 5, 6]


class TestLargeScaleKernelRidge:
    @pytest.mark.slow
    def test_normal_equations_at_convergence(self):
        X, Y = _regression_data(n=70, seed=9)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        lam = 0.3
        transforms, W = ml.large_scale_kernel_ridge(
            k, X, Y, lam, 24, Context(seed=14),
            ml.KrrParams(max_split=16, tolerance=1e-8, iter_lim=500),
        )
        Z = np.concatenate(
            [np.asarray(t.apply(jnp.asarray(X), sk.ROWWISE)) for t in transforms],
            axis=1,
        )
        resid = (Z.T @ Z + lam * np.eye(Z.shape[1])) @ np.asarray(W) - Z.T @ Y
        assert np.max(np.abs(resid)) < 1e-2


class TestRLSC:
    def test_exact_rlsc_separates(self):
        X, y = _blobs()
        k = ml.Gaussian(X.shape[1], sigma=3.0)
        A, coding = ml.kernel_rlsc(k, X, y, 0.1)
        scores = np.asarray(k.gram(X, X)) @ np.asarray(A)
        pred = ml.dummy_decode(jnp.asarray(scores), coding)
        assert (pred == y).mean() > 0.95

    def test_approximate_rlsc_separates(self):
        X, y = _blobs(seed=2)
        k = ml.Gaussian(X.shape[1], sigma=3.0)
        S, W, coding = ml.approximate_kernel_rlsc(
            k, X, y, 0.1, 128, Context(seed=15)
        )
        scores = np.asarray(S.apply(jnp.asarray(X), sk.ROWWISE)) @ np.asarray(W)
        pred = ml.dummy_decode(jnp.asarray(scores), coding)
        assert (pred == y).mean() > 0.95

    def test_faster_rlsc_separates(self):
        X, y = _blobs(seed=3)
        k = ml.Gaussian(X.shape[1], sigma=3.0)
        A, coding = ml.faster_kernel_rlsc(k, X, y, 0.1, 64, Context(seed=16))
        scores = np.asarray(k.gram(X, X)) @ np.asarray(A)
        pred = ml.dummy_decode(jnp.asarray(scores), coding)
        assert (pred == y).mean() > 0.95

    @pytest.mark.slow
    def test_large_scale_rlsc_separates(self):
        X, y = _blobs(seed=4)
        k = ml.Gaussian(X.shape[1], sigma=3.0)
        transforms, W, coding = ml.large_scale_kernel_rlsc(
            k, X, y, 0.1, 64, Context(seed=17),
            ml.RlscParams(max_split=32, iter_lim=200, tolerance=1e-6),
        )
        Z = np.concatenate(
            [np.asarray(t.apply(jnp.asarray(X), sk.ROWWISE)) for t in transforms],
            axis=1,
        )
        pred = ml.dummy_decode(jnp.asarray(Z @ np.asarray(W)), coding)
        assert (pred == y).mean() > 0.95


def test_model_materialize_predict_unchanged():
    """HilbertModel.materialize pins every supporting map's operator; the
    serving predict path must be unchanged (the caches hold the same
    entries the virtual streams generate)."""
    import numpy as np

    from libskylark_tpu.base.context import Context
    from libskylark_tpu.ml.model import HilbertModel
    from libskylark_tpu.sketch.rft import GaussianRFT

    rng = np.random.default_rng(3)
    d, s, k, m = 16, 64, 3, 40
    maps = [GaussianRFT(d, s, Context(seed=91), sigma=2.0)]
    W = rng.standard_normal((s, k)).astype(np.float32)
    model = HilbertModel(maps, scale_maps=False, num_features=s,
                         num_outputs=k, coef=jnp.asarray(W),
                         regression=False)
    X = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    lab0, dv0 = model.predict(X)
    model.materialize()
    assert maps[0]._op_cache is not None
    lab1, dv1 = model.predict(X)
    np.testing.assert_array_equal(np.asarray(lab1), np.asarray(lab0))
    np.testing.assert_allclose(np.asarray(dv1), np.asarray(dv0),
                               atol=1e-5)
    model.dematerialize()
    assert maps[0]._op_cache is None


class TestDeviceResidentLoops:
    """r7: the iterative KRR regimes keep convergence state on device —
    zero host round-trips per iteration. The proof is structural: the
    whole solve traces end-to-end (any per-iteration ``float()``/
    ``block_until_ready``-style sync would raise a concretization error
    under trace), and the sweep/PCG loop is a single ``lax.while_loop``
    in the traced program."""

    def test_bcd_sweeps_have_no_host_syncs(self):
        import jax

        from libskylark_tpu.ml.krr import _bcd_program

        X, Y = _regression_data(n=50, seed=4)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        transforms, _ = ml.large_scale_kernel_ridge(
            k, X, Y, 0.2, 16, Context(seed=21),
            ml.KrrParams(max_split=8, iter_lim=5))
        run = _bcd_program(transforms, 5, 1e-3)
        # tracing IS the no-sync assertion; the loop must be a while
        jaxpr = jax.make_jaxpr(run)(
            jnp.asarray(X), jnp.asarray(Y), jnp.float32(0.2))
        prims = [e.primitive.name for e in jaxpr.jaxpr.eqns]
        assert prims.count("while") == 1

    def test_large_scale_single_executable(self):
        from libskylark_tpu import engine

        engine.reset()
        try:
            X, Y = _regression_data(n=50, seed=4)
            k = ml.Gaussian(X.shape[1], sigma=2.0)
            ml.large_scale_kernel_ridge(
                k, X, Y, 0.2, 16, Context(seed=21),
                ml.KrrParams(max_split=8, iter_lim=5))
            s = engine.stats()
            assert s.executions == 1 and s.misses == 1
        finally:
            engine.reset()

    def test_faster_krr_single_executable_and_serve_many(self):
        from libskylark_tpu import engine

        engine.reset()
        try:
            X, Y = _regression_data(n=60, seed=7)
            k = ml.Gaussian(X.shape[1], sigma=2.0)
            p = ml.KrrParams(tolerance=1e-6, iter_lim=100)
            A1 = ml.faster_kernel_ridge(k, X, Y, 0.5, 32,
                                        Context(seed=7), p)
            assert engine.stats().executions == 1
            # same feature-map allocation => cache hit, no new compile
            A2 = ml.faster_kernel_ridge(k, X, Y, 0.5, 32,
                                        Context(seed=7), p)
            s = engine.stats()
            assert (s.misses, s.hits) == (1, 1)
            np.testing.assert_allclose(np.asarray(A1), np.asarray(A2),
                                       rtol=1e-6, atol=1e-6)
        finally:
            engine.reset()

    def test_large_scale_matches_eager_reference(self):
        """The while_loop rewrite reproduces the pre-r7 eager sweep
        algebra: run the same recurrence in numpy and compare."""
        X, Y = _regression_data(n=40, d=4, seed=12)
        k = ml.Gaussian(X.shape[1], sigma=2.0)
        lam, s = 0.3, 12
        params = ml.KrrParams(max_split=8, tolerance=1e-7, iter_lim=50)
        transforms, W = ml.large_scale_kernel_ridge(
            k, X, Y, lam, s, Context(seed=31), params)
        Zs = [np.asarray(t.apply(jnp.asarray(X), sk.ROWWISE))
              for t in transforms]
        Wb = [np.zeros((Z.shape[1], Y.shape[1]), np.float32) for Z in Zs]
        R, Ls = Y.copy(), []
        import scipy.linalg as sl

        for it in range(50):
            delsize = 0.0
            for c, Z in enumerate(Zs):
                if it == 0:
                    G = Z.T @ Z + lam * np.eye(Z.shape[1], dtype=np.float32)
                    Ls.append(sl.cholesky(G, lower=True))
                ZR = Z.T @ R - lam * Wb[c]
                delW = sl.cho_solve((Ls[c], True), ZR)
                Wb[c] = Wb[c] + delW
                R = R - Z @ delW
                delsize += float(np.sum(delW * delW))
            if it > 0:
                wnorm = np.sqrt(sum(float(np.sum(w * w)) for w in Wb))
                if np.sqrt(delsize) / max(wnorm, 1e-30) < params.tolerance:
                    break
        W_ref = np.concatenate(Wb, axis=0)
        np.testing.assert_allclose(np.asarray(W), W_ref, rtol=1e-3,
                                   atol=1e-4)
