"""ML layer on sharded data: the same code paths must produce the same
models when X lives distributed across the mesh (the reference runs every
solver on distributed matrices; here sharding the input is the analog —
SURVEY.md §2.9 P1/P2)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import libskylark_tpu.parallel as par
from libskylark_tpu.base.context import Context
from libskylark_tpu.ml import kernels
from libskylark_tpu.ml import krr


@pytest.fixture()
def data():
    rng = np.random.default_rng(0)
    n, d = 256, 8
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = np.sin(X[:, 0]).astype(np.float32)
    return X, Y


class TestShardedKRR:
    def test_kernel_ridge_sharded_matches_local(self, data, mesh1d):
        X, Y = data
        k = kernels.Gaussian(X.shape[1], sigma=2.0)
        local = np.asarray(
            krr.kernel_ridge(k, jnp.asarray(X), jnp.asarray(Y), 0.01))
        Xs = par.distribute(X, par.row_sharded(mesh1d))
        Ys = par.distribute(Y, par.vec_sharded(mesh1d))
        sharded = np.asarray(krr.kernel_ridge(k, Xs, Ys, 0.01))
        np.testing.assert_allclose(sharded, local, atol=1e-3, rtol=1e-3)

    def test_approximate_kernel_ridge_sharded(self, data, mesh1d):
        X, Y = data
        k = kernels.Gaussian(X.shape[1], sigma=2.0)
        ctx_a, ctx_b = Context(seed=3), Context(seed=3)
        fmap_l, w_l = krr.approximate_kernel_ridge(
            k, jnp.asarray(X), jnp.asarray(Y), 0.01, s=64, context=ctx_a)
        Xs = par.distribute(X, par.row_sharded(mesh1d))
        fmap_s, w_s = krr.approximate_kernel_ridge(
            k, Xs, jnp.asarray(Y), 0.01, s=64, context=ctx_b)
        np.testing.assert_allclose(np.asarray(w_s), np.asarray(w_l),
                                   atol=1e-3, rtol=1e-3)


class TestShardedKRRRagged:
    """The np=5 discipline for ml/: a 5-device submesh runs the same
    solver paths at a non-power-of-2 device count
    (ref: tests/unit/CMakeLists.txt:31-33 — rank counts 1/4/5/7). Dense
    shardings need divisible extents (250 = 5·50); truly non-dividing
    layouts live in the dist-sparse suite."""

    @pytest.mark.slow
    def test_kernel_ridge_ragged_submesh(self, data, devices):
        X, Y = data
        X, Y = X[:250], Y[:250]
        mesh5 = par.make_mesh(devices=devices[:5])
        k = kernels.Gaussian(X.shape[1], sigma=2.0)
        local = np.asarray(
            krr.kernel_ridge(k, jnp.asarray(X), jnp.asarray(Y), 0.01))
        Xs = par.distribute(X, par.row_sharded(mesh5))
        Ys = par.distribute(Y, par.vec_sharded(mesh5))
        sharded = np.asarray(krr.kernel_ridge(k, Xs, Ys, 0.01))
        np.testing.assert_allclose(sharded, local, atol=1e-3, rtol=1e-3)

    def test_approximate_kernel_ridge_ragged_submesh(self, data, devices):
        X, Y = data
        X, Y = X[:250], Y[:250]
        mesh5 = par.make_mesh(devices=devices[:5])
        k = kernels.Gaussian(X.shape[1], sigma=2.0)
        fmap_l, w_l = krr.approximate_kernel_ridge(
            k, jnp.asarray(X), jnp.asarray(Y), 0.01, s=64,
            context=Context(seed=3))
        Xs = par.distribute(X, par.row_sharded(mesh5))
        fmap_s, w_s = krr.approximate_kernel_ridge(
            k, Xs, jnp.asarray(Y), 0.01, s=64, context=Context(seed=3))
        np.testing.assert_allclose(np.asarray(w_s), np.asarray(w_l),
                                   atol=1e-3, rtol=1e-3)


class TestShardedADMM:
    def test_train_sharded_matches_local(self, data, mesh1d):
        from libskylark_tpu.algorithms.prox import (
            L2Regularizer,
            SquaredLoss,
        )
        from libskylark_tpu.ml.admm import BlockADMMSolver

        X, Y = data
        y = (Y > 0).astype(np.int64)

        def train(Xin):
            s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01,
                                X.shape[1], num_partitions=2)
            s.maxiter = 6
            s.tol = 0.0
            return s.train(Xin, y)

        local = train(jnp.asarray(X))
        sharded = train(par.distribute(X, par.row_sharded(mesh1d)))
        np.testing.assert_allclose(
            np.asarray(sharded.coef), np.asarray(local.coef),
            atol=1e-3, rtol=1e-3)

    def test_train_ragged_submesh_matches_local(self, data, devices):
        """ADMM at the np=5 device count (250 = 5·50 examples)."""
        from libskylark_tpu.algorithms.prox import (
            L2Regularizer,
            SquaredLoss,
        )
        from libskylark_tpu.ml.admm import BlockADMMSolver

        X, Y = data
        X, Y = X[:250], Y[:250]
        y = (Y > 0).astype(np.int64)
        mesh5 = par.make_mesh(devices=devices[:5])

        def train(Xin):
            s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01,
                                X.shape[1], num_partitions=2)
            s.maxiter = 6
            s.tol = 0.0
            return s.train(Xin, y)

        local = train(jnp.asarray(X))
        sharded = train(par.distribute(X, par.row_sharded(mesh5)))
        np.testing.assert_allclose(
            np.asarray(sharded.coef), np.asarray(local.coef),
            atol=1e-3, rtol=1e-3)


class TestShardedKRRNp7:
    """np=7: the remaining rank count of the reference's mpirun sweep
    (ref: tests/unit/CMakeLists.txt:10-46 — np ∈ {1,4,5,7}; 1 and 4 are
    the local and mesh1d cases above, 5 the ragged class). 252 = 7·36
    keeps dense shardings divisible."""

    def test_approximate_kernel_ridge_np7_submesh(self, data, devices):
        X, Y = data
        X, Y = X[:252], Y[:252]
        mesh7 = par.make_mesh(devices=devices[:7])
        k = kernels.Gaussian(X.shape[1], sigma=2.0)
        fmap_l, w_l = krr.approximate_kernel_ridge(
            k, jnp.asarray(X), jnp.asarray(Y), 0.01, s=64,
            context=Context(seed=3))
        Xs = par.distribute(X, par.row_sharded(mesh7))
        fmap_s, w_s = krr.approximate_kernel_ridge(
            k, Xs, jnp.asarray(Y), 0.01, s=64, context=Context(seed=3))
        np.testing.assert_allclose(np.asarray(w_s), np.asarray(w_l),
                                   atol=1e-3, rtol=1e-3)

    @pytest.mark.slow
    def test_admm_np7_submesh_matches_local(self, data, devices):
        from libskylark_tpu.algorithms.prox import (
            L2Regularizer,
            SquaredLoss,
        )
        from libskylark_tpu.ml.admm import BlockADMMSolver

        X, Y = data
        X, Y = X[:252], Y[:252]
        y = (Y > 0).astype(np.int64)
        mesh7 = par.make_mesh(devices=devices[:7])

        def train(Xin):
            s = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01,
                                X.shape[1], num_partitions=2)
            s.maxiter = 6
            s.tol = 0.0
            return s.train(Xin, y)

        local = train(jnp.asarray(X))
        sharded = train(par.distribute(X, par.row_sharded(mesh7)))
        np.testing.assert_allclose(
            np.asarray(sharded.coef), np.asarray(local.coef),
            atol=1e-3, rtol=1e-3)
