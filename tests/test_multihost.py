"""True multi-process collectives: the comm-backend claim exercised.

The reference scales across hosts with Boost.MPI (``mpirun -np N``, ref:
tests/unit/CMakeLists.txt:10-46); the TPU-native analog is
``jax.distributed`` — one logical device pool over N host processes with
XLA routing the collectives. PARITY row #94 claims that path; this test
RUNS it: two OS processes (simulated hosts, 4 virtual CPU devices each)
joined through ``parallel.multihost.initialize_distributed``, a mesh
spanning both, the sketch oracle checked against the host-spanning
sharded apply, and a raw cross-host psum validated analytically.

Runs real subprocesses (cannot share this pytest process: jax.distributed
is once-per-process), so it lives in the slow tier.
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize(
    "nprocs,devs_per_proc",
    [(2, 4),   # one host boundary, intra-host parallelism 4
     (4, 2)],  # THREE host boundaries at the same 8 global devices —
               # axis-ordering/non-adjacent-shard coverage the pairwise
               # case can't give (the rank-count diversity of ref:
               # tests/unit/CMakeLists.txt:10-46, np=1/4/5/7)
)
def test_process_mesh_runs_sketch_oracle(tmp_path, nprocs,
                                         devs_per_proc):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # shared checkpoint root for the cross-host resume step (all
    # simulated hosts see one filesystem, as a pod's workers would a
    # shared store)
    env["SKYLARK_MH_TMP"] = str(tmp_path)
    # the workers set their own device-count XLA flag
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(nprocs), str(port),
             str(devs_per_proc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=HERE,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180 * nprocs)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-2000:]}"
        assert "MULTIHOST_OK" in out, f"proc {pid} no OK:\n{out[-2000:]}"
        assert "CWT cross-host oracle ok" in out
        assert "JLT cross-host oracle ok" in out
        assert "ADMM cross-host oracle ok" in out
        assert "ADMM cross-host checkpoint resume ok" in out
        assert "LSQR cross-host oracle ok" in out
        assert "randSVD cross-host oracle ok" in out
