"""Network serve front door (libskylark_tpu/net/, docs/networking).

Oracles:

- *codec determinism + fidelity*: every supported operand shape
  (strided views, F-order, f64, CSR parts, numpy scalars, transforms,
  nested containers) round-trips bit-equal through the tagged codec,
  and the same logical request packs to byte-identical frames;
- *frame integrity*: a torn frame, a flipped payload byte, or bad
  magic is a :class:`WireProtocolError`, never a mis-decoded value; a
  clean EOF between frames is :class:`PeerClosed`;
- *transport propagation*: tenant / qos_class / deadline / request_id
  cross the wire into ``Router.submit`` exactly as given, and
  structured errors come back as the same exception type with
  ``retry_after_s`` intact;
- *resilience*: a client disconnect mid-request detaches the server
  future without poisoning anything; a GOAWAY drain settles inflight
  work with zero client-visible failures; a reconnect re-send of
  identical bytes coalesces onto the cache/single-flight tier so the
  engine flushes exactly once;
- *observability*: the server-side ``net.serve`` span parents under
  the client's span — one trace end to end.
"""

from __future__ import annotations

import io
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest
import scipy.sparse as sp

from libskylark_tpu import Context, engine, fleet, net, telemetry
from libskylark_tpu import sketch as sk
from libskylark_tpu.base import errors as sk_errors
from libskylark_tpu.base.sparse import SparseMatrix
from libskylark_tpu.engine.serve import ServeOverloadedError
from libskylark_tpu.net import wire
from libskylark_tpu.resilience import faults


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _round_trip(value):
    bodies: list = []
    spec = wire.encode_value(value, bodies)
    frame = wire.encode_frame({"t": "res", "value": spec,
                               "nb": len(bodies)}, tuple(bodies))
    header, out_bodies = wire.read_frame(io.BytesIO(frame).read)
    return wire.decode_value(header["value"], out_bodies)


class TestWireCodec:
    def test_array_shapes_round_trip(self):
        rng = np.random.default_rng(0)
        base = rng.standard_normal((16, 12))
        cases = [
            base.astype(np.float32),
            base,                                   # f64
            np.asfortranarray(base.astype(np.float32)),
            base[::2, ::3],                         # strided view
            base[5],                                # 1-D
            np.arange(7, dtype=np.int64),
            np.float32(2.5),                        # numpy scalar
            np.array(3.0),                          # 0-d
        ]
        for v in cases:
            got = _round_trip(v)
            assert np.array_equal(np.asarray(got), np.asarray(v))
            assert np.asarray(got).dtype == np.asarray(v).dtype

    def test_csr_round_trips_without_densify(self):
        A = sp.random(40, 30, density=0.1, random_state=0,
                      format="csr", dtype=np.float32)
        m = SparseMatrix.from_csr(A.data, A.indices, A.indptr, A.shape)
        bodies: list = []
        spec = wire.encode_value(m, bodies)
        assert spec["k"] == "csr"       # parts, never a dense body
        got = _round_trip(m)
        assert isinstance(got, SparseMatrix)
        assert got.shape == m.shape
        for a, b in zip(got.csr_parts(), m.csr_parts()):
            assert np.array_equal(a, b)

    def test_nested_containers_and_scalars(self):
        v = {"a": [1, 2.5, "x", None, True],
             "b": (np.arange(3), {"c": np.float64(1.5)}),
             "d": sk.COLUMNWISE}
        got = _round_trip(v)
        assert got["a"] == v["a"]
        assert isinstance(got["b"], tuple)
        assert np.array_equal(got["b"][0], np.arange(3))
        assert got["b"][1]["c"] == 1.5
        assert got["d"] is sk.COLUMNWISE

    def test_sketch_transform_round_trips(self):
        T = sk.CWT(64, 16, Context(seed=9))
        got = _round_trip(T)
        A = np.random.default_rng(1).standard_normal(
            (64, 4)).astype(np.float32)
        assert np.array_equal(
            np.asarray(got.apply(A, sk.COLUMNWISE)),
            np.asarray(T.apply(A, sk.COLUMNWISE)))

    def test_unencodable_values_refused(self):
        with pytest.raises(sk_errors.WireProtocolError):
            wire.encode_value(object(), [])
        with pytest.raises(sk_errors.WireProtocolError):
            wire.encode_value({1: "non-str key"}, [])

    def test_request_packing_is_deterministic(self):
        A = np.random.default_rng(2).standard_normal((8, 3))
        f1 = wire.pack_request("sketch_apply", {"A": A, "k": 2}, seq=7)
        f2 = wire.pack_request("sketch_apply", {"A": A, "k": 2}, seq=7)
        assert f1 == f2
        # a different operand changes the transport digest
        f3 = wire.pack_request("sketch_apply", {"A": A + 1, "k": 2},
                               seq=7)
        h1, _ = wire.read_frame(io.BytesIO(f1).read)
        h3, _ = wire.read_frame(io.BytesIO(f3).read)
        assert h1["digest"] != h3["digest"]


class TestFraming:
    def _frame(self):
        return wire.pack_request("ping", {"A": np.arange(4)}, seq=1)

    def test_torn_frame_rejected(self):
        frame = self._frame()
        with pytest.raises(sk_errors.WireProtocolError,
                           match="mid-frame"):
            wire.read_frame(io.BytesIO(frame[:-3]).read)

    def test_bad_crc_rejected(self):
        frame = bytearray(self._frame())
        frame[-1] ^= 0xFF               # flip a payload byte
        with pytest.raises(sk_errors.WireProtocolError, match="CRC"):
            wire.read_frame(io.BytesIO(bytes(frame)).read)

    def test_bad_magic_rejected(self):
        frame = b"XXXX" + self._frame()[4:]
        with pytest.raises(sk_errors.WireProtocolError, match="magic"):
            wire.read_frame(io.BytesIO(frame).read)

    def test_clean_eof_is_peer_closed(self):
        with pytest.raises(wire.PeerClosed):
            wire.read_frame(io.BytesIO(b"").read)

    def test_trailing_bytes_rejected(self):
        frame = self._frame()
        import struct
        import zlib
        payload = frame[12:] + b"junk"
        bad = (wire.MAGIC
               + struct.pack("<II", len(payload), zlib.crc32(payload))
               + payload)
        with pytest.raises(sk_errors.WireProtocolError,
                           match="trailing"):
            wire.read_frame(io.BytesIO(bad).read)

    def test_error_frame_round_trips_retry_fields(self):
        exc = sk_errors.TenantQuotaError(
            "over quota", tenant="team-a", retry_after_s=1.25)
        h, _ = wire.read_frame(io.BytesIO(wire.pack_error(4, exc)).read)
        back = wire.unpack_error(h)
        assert isinstance(back, sk_errors.TenantQuotaError)
        assert back.tenant == "team-a"
        assert back.retry_after_s == 1.25
        over = ServeOverloadedError("shed")
        over.retry_after_s = 0.5
        h2, _ = wire.read_frame(
            io.BytesIO(wire.pack_error(5, over)).read)
        back2 = wire.unpack_error(h2)
        assert isinstance(back2, ServeOverloadedError)
        assert back2.retry_after_s == 0.5


class _StubRouter:
    """Records ``submit`` kwargs and settles through controllable
    futures — the transport-propagation oracle without a fleet."""

    def __init__(self):
        self.calls: list = []
        self.raise_exc = None
        self.hold = False
        self.held: list = []

    def submit(self, endpoint, /, **kwargs):
        self.calls.append((endpoint, kwargs))
        if self.raise_exc is not None:
            raise self.raise_exc
        fut: Future = Future()
        if self.hold:
            self.held.append(fut)
        else:
            fut.set_result(np.arange(3, dtype=np.float32))
        return fut

    def stats(self):
        return {"stub": True}


def _serve_stub(stub, **kw):
    srv = net.NetServer(stub, **kw)
    return srv, net.NetClient(srv.address, retry_budget=1,
                              retry_backoff_s=0.01, seed=0)


class TestTransportPropagation:
    def test_tenant_qos_deadline_cross_the_wire(self):
        stub = _StubRouter()
        srv, c = _serve_stub(stub)
        try:
            out = c.submit("sketch_apply", tenant="team-a",
                           qos_class="interactive", deadline=30.0,
                           timeout=12.0, A=np.ones(2)).result(timeout=10)
            assert np.array_equal(out, np.arange(3, dtype=np.float32))
            endpoint, kw = stub.calls[0]
            assert endpoint == "sketch_apply"
            assert kw["tenant"] == "team-a"
            assert kw["qos_class"] == "interactive"
            assert 25.0 < kw["deadline"] <= 30.0
            assert kw["timeout"] == 12.0
            assert str(kw["request_id"]).startswith("req-")
            assert np.array_equal(kw["A"], np.ones(2))
        finally:
            c.close()
            srv.close()

    def test_quota_error_retry_after_fidelity(self):
        stub = _StubRouter()
        stub.raise_exc = sk_errors.TenantQuotaError(
            "bucket empty", tenant="team-b", retry_after_s=2.5)
        srv, c = _serve_stub(stub)
        try:
            fut = c.submit("sketch_apply", A=np.ones(2))
            with pytest.raises(sk_errors.TenantQuotaError) as ei:
                fut.result(timeout=10)
            assert ei.value.retry_after_s == 2.5
            assert ei.value.tenant == "team-b"
            assert srv.stats()["by_code"].get("115") == 1
        finally:
            c.close()
            srv.close()

    def test_overload_error_survives_the_wire(self):
        stub = _StubRouter()
        exc = ServeOverloadedError("queue full")
        exc.retry_after_s = 0.75
        stub.raise_exc = exc
        srv, c = _serve_stub(stub)
        try:
            with pytest.raises(ServeOverloadedError) as ei:
                c.submit("sketch_apply", A=np.ones(2)).result(timeout=10)
            assert ei.value.retry_after_s == 0.75
        finally:
            c.close()
            srv.close()

    def test_unknown_verb_is_protocol_error(self):
        stub = _StubRouter()
        srv, c = _serve_stub(stub)
        try:
            with pytest.raises(sk_errors.WireProtocolError,
                               match="unknown verb"):
                c.submit("no.such.verb").result(timeout=10)
        finally:
            c.close()
            srv.close()


class TestDisconnectAndDrain:
    def test_disconnect_mid_request_detaches(self):
        stub = _StubRouter()
        stub.hold = True
        srv, c = _serve_stub(stub)
        try:
            fut = c.submit("sketch_apply", A=np.ones(2))
            deadline = time.monotonic() + 10
            while not stub.held and time.monotonic() < deadline:
                time.sleep(0.005)
            assert stub.held, "request never reached the stub router"
            c.close()               # vanish with the request inflight
            with pytest.raises(sk_errors.CommunicationError):
                fut.result(timeout=10)
            # wait for the server to notice the dead peer BEFORE the
            # future settles — that is the detach-mid-request window
            deadline = time.monotonic() + 10
            while (srv.stats()["connections_live"] > 0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert srv.stats()["connections_live"] == 0
            assert srv.stats()["disconnected_inflight"] == 1
            # settling the orphaned future must not poison the server
            stub.held[0].set_result(np.zeros(1, dtype=np.float32))
            stub.hold = False
            c2 = net.NetClient(srv.address)
            try:
                assert c2.ping() == "pong"
            finally:
                c2.close()
        finally:
            c.close()
            srv.close()

    def test_goaway_drain_settles_inflight(self):
        stub = _StubRouter()
        stub.hold = True
        srv, c = _serve_stub(stub)
        try:
            fut = c.submit("sketch_apply", A=np.ones(2))
            deadline = time.monotonic() + 10
            while not stub.held and time.monotonic() < deadline:
                time.sleep(0.005)
            drained: list = []
            t = threading.Thread(
                target=lambda: drained.append(srv.drain(timeout=10)))
            t.start()
            # the drain waits on the inflight response; settle it
            time.sleep(0.05)
            stub.held[0].set_result(np.full(2, 7.0, dtype=np.float32))
            t.join(timeout=15)
            assert drained == [True]
            # zero client-visible failures: the future resolved
            assert np.array_equal(fut.result(timeout=10),
                                  np.full(2, 7.0, dtype=np.float32))
            assert c.client_stats()["goaways_seen"] == 1
            assert srv.stats()["drains"] == 1
            assert srv.stats()["goaways_sent"] == 1
        finally:
            c.close()
            srv.close()

    def test_refused_past_max_connections(self):
        stub = _StubRouter()
        srv = net.NetServer(stub, max_connections=1)
        c1 = net.NetClient(srv.address, retry_budget=0)
        try:
            assert c1.ping() == "pong"
            c2 = net.NetClient(srv.address, retry_budget=0)
            try:
                with pytest.raises((ServeOverloadedError,
                                    sk_errors.CommunicationError)):
                    c2.ping(timeout=10)
            finally:
                c2.close()
            assert srv.stats()["refused"] >= 1
        finally:
            c1.close()
            srv.close()


def _fleet_cache_stats(pool) -> dict:
    from libskylark_tpu.engine import resultcache as rc

    blocks = [pool.get(n).executor.stats().get("cache")
              for n in pool.names()]
    merged = rc.merge_cache_blocks([b for b in blocks if b])
    merged["flushes"] = sum(
        pool.get(n).executor.stats()["flushes"] for n in pool.names())
    return merged


class TestRetryCoalescing:
    def test_reconnect_resend_flushes_exactly_once(self, fresh_engine):
        """The retry-idempotency contract end to end: compute once,
        tear the connection, re-send the identical request — the
        cache/single-flight tier answers, the engine never re-flushes."""
        pool = fleet.ReplicaPool(1, max_batch=8, linger_us=500,
                                 cache=True)
        router = fleet.Router(pool, cache=True)
        srv = net.NetServer(router)
        c = net.NetClient(srv.address, retry_backoff_s=0.01, seed=1)
        try:
            T = sk.CWT(128, 32, Context(seed=5))
            A = np.random.default_rng(3).standard_normal(
                (128, 4)).astype(np.float32)
            first = np.asarray(c.submit(
                "sketch_apply", transform=T, A=A,
                dimension=sk.COLUMNWISE).result(timeout=120))
            deadline = time.monotonic() + 30
            while (_fleet_cache_stats(pool)["entries"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            st0 = _fleet_cache_stats(pool)
            assert st0["flushes"] == 1
            # simulate a torn connection between the two sends
            with c._lock:
                sock = c._sock
            sock.close()
            again = np.asarray(c.submit(
                "sketch_apply", transform=T, A=A,
                dimension=sk.COLUMNWISE).result(timeout=120))
            st1 = _fleet_cache_stats(pool)
            assert st1["flushes"] == 1      # exactly one engine flush
            assert st1["hits"] >= 1
            assert np.array_equal(first, again)
            # exactly ONE recovery: the dead socket is noticed by both
            # the failed sendall and the reader's EOF, and double
            # harvesting would re-send the frame twice (billing two
            # attempts and waking an idle server reader later)
            assert c.client_stats()["transport_retries"] == 1
        finally:
            c.close()
            srv.close()
            router.close()
            pool.shutdown()

    def test_net_read_fault_absorbed_by_retry(self, fresh_engine):
        """A chaos ``net.read`` fault tears one server connection; the
        client's bounded reconnect-resend absorbs it invisibly."""
        pool = fleet.ReplicaPool(1, max_batch=8, linger_us=500,
                                 cache=True)
        router = fleet.Router(pool, cache=True)
        srv = net.NetServer(router)
        c = net.NetClient(srv.address, retry_budget=3,
                          retry_backoff_s=0.01, seed=2)
        try:
            T = sk.CWT(128, 32, Context(seed=6))
            A = np.random.default_rng(4).standard_normal(
                (128, 4)).astype(np.float32)
            plan = {"seed": 1, "faults": [
                {"site": "net.read", "error": "IOError_", "times": 1}]}
            with faults.fault_plan(plan):
                out = np.asarray(c.submit(
                    "sketch_apply", transform=T, A=A,
                    dimension=sk.COLUMNWISE).result(timeout=120))
                fired = faults.fired()
            assert [f[0] for f in fired] == ["net.read"]
            import jax.numpy as jnp
            want = np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
            assert np.array_equal(out, want)
            assert _fleet_cache_stats(pool)["flushes"] == 1
            assert c.client_stats()["transport_retries"] >= 1
        finally:
            c.close()
            srv.close()
            router.close()
            pool.shutdown()


class TestSpanContinuity:
    def test_server_span_parents_under_client_span(self):
        stub = _StubRouter()
        telemetry.set_enabled(True)
        try:
            import libskylark_tpu.telemetry.trace as trace_mod

            trace_mod.clear_finished()
            srv, c = _serve_stub(stub)
            try:
                with trace_mod.span("client.op", force=True,
                                    request_id="req-net-test-1") as sp:
                    ctx = sp.context()
                    c.submit("sketch_apply",
                             A=np.ones(2)).result(timeout=10)
                deadline = time.monotonic() + 10
                serve_spans = []
                while not serve_spans and time.monotonic() < deadline:
                    serve_spans = [s for s in trace_mod.finished_spans()
                                   if s.name == "net.serve"]
                    time.sleep(0.005)
                assert serve_spans, "no net.serve span recorded"
                s = serve_spans[0]
                assert s.trace_id == ctx.trace_id
                assert s.parent_id == ctx.span_id
                assert s.request_id == ctx.request_id
                assert s.attrs["verb"] == "sketch_apply"
            finally:
                c.close()
                srv.close()
        finally:
            telemetry.set_enabled(False)


class TestStatsSurfaces:
    def test_net_stats_and_prometheus(self):
        stub = _StubRouter()
        srv, c = _serve_stub(stub)
        try:
            c.ping()
            ns = net.net_stats()
            assert ns["servers"] >= 1
            assert ns["requests"] >= 1
            assert ns["by_verb"]["ping"]["requests"] >= 1
            telemetry.set_enabled(True)
            try:
                text = telemetry.prometheus_text()
            finally:
                telemetry.set_enabled(False)
            assert "skylark_net_requests" in text
        finally:
            c.close()
            srv.close()

    def test_serve_stats_gains_net_block(self, fresh_engine):
        from libskylark_tpu.engine.serve import serve_stats

        stub = _StubRouter()
        srv, c = _serve_stub(stub)
        try:
            c.ping()
            blk = serve_stats().get("net")
            assert blk is not None and blk["requests"] >= 1
        finally:
            c.close()
            srv.close()
