"""NLA-layer tests: approximate SVD (reconstruction oracle), least squares,
condition estimation, spectral helpers.

Mirrors the reference's SVD reconstruction checks
(ref: tests/unit/test_utils.hpp:61-148, SVDElementalTest.cpp) and the
regression-test spectral bounds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import Context, nla
from libskylark_tpu import parallel as par


def _lowrank(m, n, r, seed=0, noise=0.0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, r)) @ rng.standard_normal((r, n))
    if noise:
        A = A + noise * rng.standard_normal((m, n))
    return A.astype(dtype)


class TestApproximateSVD:
    def test_exact_rank_reconstruction(self):
        """Rank-r matrix recovered to the reference's 1e-4-style tolerance."""
        A = _lowrank(200, 80, 6, seed=1)
        U, S, V = nla.approximate_svd(jnp.asarray(A), 6, Context(seed=3),
                                      nla.ApproximateSVDParams(num_iterations=2))
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(V).T
        err = np.linalg.norm(recon - A) / np.linalg.norm(A)
        assert err < 1e-4

    @pytest.mark.slow
    def test_wide_matrix_branch(self):
        A = _lowrank(60, 300, 5, seed=2)
        U, S, V = nla.approximate_svd(jnp.asarray(A), 5, Context(seed=5),
                                      nla.ApproximateSVDParams(num_iterations=2))
        assert U.shape == (60, 5) and V.shape == (300, 5)
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(V).T
        assert np.linalg.norm(recon - A) / np.linalg.norm(A) < 1e-4

    def test_singular_values_match_exact(self):
        A = _lowrank(150, 100, 20, seed=3, noise=0.01)
        sv_exact = np.linalg.svd(A, compute_uv=False)[:5]
        _, S, _ = nla.approximate_svd(jnp.asarray(A), 5, Context(seed=7),
                                      nla.ApproximateSVDParams(num_iterations=3))
        np.testing.assert_allclose(np.asarray(S), sv_exact, rtol=0.05)

    def test_orthonormal_factors(self):
        A = _lowrank(100, 60, 8, seed=4, noise=0.05)
        U, S, V = nla.approximate_svd(jnp.asarray(A), 8, Context(seed=11),
                                      nla.ApproximateSVDParams(num_iterations=2))
        np.testing.assert_allclose(np.asarray(U.T @ U), np.eye(8), atol=1e-4)
        np.testing.assert_allclose(np.asarray(V.T @ V), np.eye(8), atol=1e-4)
        assert (np.diff(np.asarray(S)) <= 1e-6).all()  # descending

    @pytest.mark.slow
    def test_power_iteration_improves_noisy(self):
        A = _lowrank(300, 200, 10, seed=5, noise=0.5)
        best = np.linalg.svd(A, compute_uv=False)
        tail = np.sqrt((best[10:] ** 2).sum())

        def err(q):
            U, S, V = nla.approximate_svd(
                jnp.asarray(A), 10, Context(seed=13),
                nla.ApproximateSVDParams(num_iterations=q))
            recon = np.asarray(U) * np.asarray(S) @ np.asarray(V).T
            return np.linalg.norm(recon - A)

        e0, e3 = err(0), err(3)
        assert e3 <= e0 + 1e-5
        assert e3 <= 1.05 * tail  # near-optimal with power iterations

    def test_sharded_input(self, mesh1d):
        A = _lowrank(256, 64, 4, seed=6)
        A_sh = par.distribute(A, par.row_sharded(mesh1d))
        U, S, V = nla.approximate_svd(A_sh, 4, Context(seed=17),
                                      nla.ApproximateSVDParams(num_iterations=2))
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(V).T
        assert np.linalg.norm(recon - A) / np.linalg.norm(A) < 1e-3

    def test_jittable(self):
        A = jnp.asarray(_lowrank(80, 40, 4, seed=7))
        ctx = Context(seed=19)
        # pre-allocate so the jitted fn closes over a fixed transform
        f = jax.jit(lambda M: nla.approximate_svd(
            M, 4, Context(seed=19), nla.ApproximateSVDParams(num_iterations=1)))
        U, S, V = f(A)
        recon = np.asarray(U) * np.asarray(S) @ np.asarray(V).T
        assert np.linalg.norm(recon - np.asarray(A)) / np.linalg.norm(A) < 1e-3

    def test_invalid_rank(self):
        with pytest.raises(Exception, match="rank"):
            nla.approximate_svd(jnp.eye(4), 0, Context(0))

    def test_rr_reductions_agree(self):
        """The CQR2-reduced Rayleigh-Ritz (r5 default — the r4 mesh
        hotspot fix) and the reference-algebra direct SVD of the k'×n
        panel (ref: nla/svd.hpp:286-290) must produce the same
        factorization on the same sketch, including on an
        ill-conditioned spectrum (decay past 1/√ε in f32)."""
        rng = np.random.default_rng(21)
        r0 = 48
        decay = 0.82 ** np.arange(r0)
        A = ((rng.standard_normal((300, r0)) * decay)
             @ rng.standard_normal((r0, 160))).astype(np.float32)
        out = {}
        for rr in ("cqr2", "svd"):
            U, S, V = nla.approximate_svd(
                jnp.asarray(A), 8, Context(seed=23),
                nla.ApproximateSVDParams(num_iterations=1, rr=rr))
            np.testing.assert_allclose(np.asarray(U.T @ U), np.eye(8),
                                       atol=1e-4)
            np.testing.assert_allclose(np.asarray(V.T @ V), np.eye(8),
                                       atol=1e-4)
            out[rr] = np.asarray(S)
        np.testing.assert_allclose(out["cqr2"], out["svd"], rtol=1e-4)

    @pytest.mark.parametrize("rr,ortho", [("cqr2", "cqr2"),
                                          ("svd", "qr")])
    def test_ill_conditioned_parity_near_f32_cqr_bound(self, rr, ortho):
        """Parity at a spectrum spanning ~10× past the f32 CholeskyQR
        textbook bound (cond ≲ 1/√ε ≈ 3e3): the top-k singular values
        must match reference algebra (np.linalg.svd) at f32 grade for
        BOTH the mesh-native default (cqr2/cqr2 — accurate far past the
        textbook bound for the truncated spectra randomized SVD meets)
        and the reference-algebra combination rr='svd', ortho='qr'
        (Householder + direct panel SVD — the configuration to reach
        for on EXTREME spectra; docs/nla.rst). ADVICE r5."""
        rng = np.random.default_rng(2)
        m, n, k = 512, 64, 8
        Uq, _ = np.linalg.qr(rng.standard_normal((m, n)))
        Vq, _ = np.linalg.qr(rng.standard_normal((n, n)))
        s = np.logspace(0, -4.5, n)        # cond ≈ 3e4 ≈ 10/√ε_f32
        A = (Uq * s) @ Vq.T
        ref = np.linalg.svd(A, compute_uv=False)[:k]
        U, S, V = nla.approximate_svd(
            jnp.asarray(A, jnp.float32), k, Context(seed=13),
            nla.ApproximateSVDParams(num_iterations=2, rr=rr,
                                     ortho=ortho))
        np.testing.assert_allclose(np.asarray(S), ref, rtol=1e-4)
        # factors stay orthonormal through the ill-conditioned panels
        np.testing.assert_allclose(np.asarray(U.T @ U), np.eye(k),
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(V.T @ V), np.eye(k),
                                   atol=1e-4)

    def test_rr_invalid_value_raises(self):
        with pytest.raises(Exception, match="rr"):
            nla.approximate_svd(
                jnp.asarray(_lowrank(40, 20, 4, seed=9)), 4,
                Context(seed=2), nla.ApproximateSVDParams(rr="bogus"))


class TestSymmetricSVD:
    def test_symmetric_reconstruction(self):
        rng = np.random.default_rng(8)
        Q, _ = np.linalg.qr(rng.standard_normal((80, 80)))
        w = np.zeros(80)
        w[:6] = [10, -8, 6, 4, -2, 1]
        A = ((Q * w) @ Q.T).astype(np.float32)
        V, S = nla.approximate_symmetric_svd(jnp.asarray(A), 6, Context(seed=23),
                                             nla.ApproximateSVDParams(num_iterations=3))
        recon = np.asarray(V) * np.asarray(S) @ np.asarray(V).T
        assert np.linalg.norm(recon - A) / np.linalg.norm(A) < 1e-3
        # eigenvalues with signs, sorted by magnitude
        np.testing.assert_allclose(np.asarray(S), w[:6], rtol=1e-3, atol=1e-3)

    def test_rejects_nonsquare(self):
        with pytest.raises(Exception, match="square"):
            nla.approximate_symmetric_svd(jnp.zeros((3, 4)), 2, Context(0))


class TestLeastSquares:
    def _problem(self, m=2000, n=12, seed=9):
        rng = np.random.default_rng(seed)
        A = rng.standard_normal((m, n)).astype(np.float32)
        x = rng.standard_normal((n,)).astype(np.float32)
        b = A @ x + 0.1 * rng.standard_normal(m).astype(np.float32)
        return A, b

    def test_approximate_ls_residual(self):
        A, b = self._problem()
        x = nla.approximate_least_squares(jnp.asarray(A), jnp.asarray(b),
                                          Context(seed=29))
        res_opt = np.linalg.norm(A @ np.linalg.lstsq(A, b, rcond=None)[0] - b)
        res = np.linalg.norm(A @ np.asarray(x) - b)
        assert res <= 1.5 * res_opt

    def test_fast_ls_high_accuracy(self):
        A, b = self._problem(seed=10)
        x, it = nla.fast_least_squares(jnp.asarray(A), jnp.asarray(b),
                                       Context(seed=31))
        assert int(it) > 0
        x_np = np.linalg.lstsq(A, b, rcond=None)[0]
        res_opt = np.linalg.norm(A @ x_np - b)
        res = np.linalg.norm(A @ np.asarray(x) - b)
        assert res <= 1.0001 * res_opt


class TestCondEst:
    def test_estimates_condition(self):
        rng = np.random.default_rng(11)
        m, n, cond = 300, 40, 50.0
        U, _ = np.linalg.qr(rng.standard_normal((m, n)))
        V, _ = np.linalg.qr(rng.standard_normal((n, n)))
        s = np.logspace(0, -np.log10(cond), n)
        A = ((U * s) @ V.T).astype(np.float32)
        est, smax, smin = nla.estimate_condition(jnp.asarray(A), Context(seed=37),
                                                 max_iter=150)
        assert smax == pytest.approx(1.0, rel=0.05)
        assert est == pytest.approx(cond, rel=0.35)

    def test_deterministic(self):
        A = jnp.asarray(np.random.default_rng(12).standard_normal((50, 10)),
                        dtype=jnp.float32)
        e1 = nla.estimate_condition(A, Context(seed=41))
        e2 = nla.estimate_condition(A, Context(seed=41))
        assert e1 == e2

    def test_sparse_operand_matches_dense(self, mesh1d):
        """Sparse and distributed-sparse operands drive the same
        Golub-Kahan loop through scipy matvecs. Tolerance is loose on
        purpose: the dense path runs BLAS gemv, the sparse path scipy CSC
        matvecs — different accumulation orders can flip the discrete
        convergence checks on some BLAS builds, shifting the stop
        iteration by one tol=1e-3 window."""
        import scipy.sparse as sp

        from libskylark_tpu.base.dist_sparse import distribute_sparse
        from libskylark_tpu.base.sparse import SparseMatrix

        rng = np.random.default_rng(13)
        dense = (rng.standard_normal((120, 20)) *
                 (rng.uniform(size=(120, 20)) < 0.3)).astype(np.float32)
        A = SparseMatrix.from_scipy(sp.csc_matrix(dense))
        e_dense = nla.estimate_condition(jnp.asarray(dense),
                                         Context(seed=43))
        e_sparse = nla.estimate_condition(A, Context(seed=43))
        np.testing.assert_allclose(e_sparse, e_dense, rtol=5e-3)

    @pytest.mark.slow
    def test_dist_sparse_operand_never_materializes(self, mesh2d,
                                                    monkeypatch):
        """DistSparseMatrix operands drive the Golub-Kahan recurrence ON
        DEVICE through spmm/spmm_t (ref: nla/CondEst.hpp:67-305 drives the
        distributed operand) — gathering the operand to one host would cap
        the operand size at one host's memory, so to_local is forbidden
        for the whole run. The f32 device recurrence (with full
        reorthogonalization) must agree with the f64 host path."""
        import scipy.sparse as sp

        from libskylark_tpu.base.dist_sparse import (DistSparseMatrix,
                                                     distribute_sparse)
        from libskylark_tpu.base.sparse import SparseMatrix

        rng = np.random.default_rng(13)
        dense = (rng.standard_normal((120, 20)) *
                 (rng.uniform(size=(120, 20)) < 0.3)).astype(np.float32)
        A = SparseMatrix.from_scipy(sp.csc_matrix(dense))
        e_sparse = nla.estimate_condition(A, Context(seed=43))
        D = distribute_sparse(A, mesh2d, row_axis="rows", col_axis="cols")
        monkeypatch.setattr(
            DistSparseMatrix, "to_local",
            lambda self: (_ for _ in ()).throw(
                AssertionError("condest gathered the operand to host")),
        )
        e_dist = nla.estimate_condition(D, Context(seed=43))
        np.testing.assert_allclose(e_dist, e_sparse, rtol=5e-2)


class TestSpectral:
    def test_chebyshev_points(self):
        x = nla.chebyshev_points(5)
        np.testing.assert_allclose(x, [1.0, np.sqrt(2) / 2, 0.0,
                                       -np.sqrt(2) / 2, -1.0], atol=1e-12)

    def test_chebyshev_points_general_interval(self):
        x = nla.chebyshev_points(5, a=2.0, b=3.0)
        assert x.max() == pytest.approx(3.0) and x.min() == pytest.approx(2.0)
        assert x[2] == pytest.approx(2.5)  # midpoint snapped to center

    def test_diff_matrix_differentiates_polynomials(self):
        """D applied to values of p(x)=x³ must give 3x² exactly (degree < N)."""
        D, x = nla.chebyshev_diff_matrix(8)
        p = x**3
        dp = D @ p
        np.testing.assert_allclose(dp, 3 * x**2, atol=1e-10)

    def test_diff_matrix_rescaled_interval(self):
        D, x = nla.chebyshev_diff_matrix(10, a=0.0, b=2.0)
        assert x.min() == pytest.approx(0.0) and x.max() == pytest.approx(2.0)
        p = x**2
        np.testing.assert_allclose(D @ p, 2 * x, atol=1e-9)
