"""Sharded oracles for the nla/ extras (krank HMT toolkit, randlobpcg,
lowrank): the same-seed computation on a mesh-sharded operand must match
the local one — the reference's redundant-computation oracle
(tests/unit/DenseSketchApplyElementalTest.cpp:44-101 pattern) extended
to the python-skylark-layer algorithms, which previously had local-only
coverage.

Calibration note: the 1e-4 elementwise oracle applies to SKETCH applies
(bit-controlled streams). Downstream orthogonalization/eigensolves
amplify fp accumulation-order differences along noise-floor directions,
so these tests compare conditioning-robust quantities: leading singular
values, subspace projectors, reconstruction quality — the reference's
own posture for its SVD property tests (test_utils.hpp:61-148)."""

import numpy as np
import pytest
import jax.numpy as jnp

import libskylark_tpu.parallel as par
from libskylark_tpu.base.context import Context
from libskylark_tpu.nla.krank import RandomizedRangeFinder, randomized_svd
from libskylark_tpu.nla.lowrank import approximate_dominant_subspace_basis
from libskylark_tpu.nla.randlobpcg import lobpcg_rand_evd


@pytest.fixture
def A_np():
    rng = np.random.default_rng(11)
    U = np.linalg.qr(rng.standard_normal((192, 8)))[0]
    V = np.linalg.qr(rng.standard_normal((32, 8)))[0]
    # gentle 0.7^k decay: steeper spectra (2^-k) leave tail directions
    # whose power-iterated weight falls below f32 eps — unresolvable in
    # EITHER layout, so no cross-layout bound on them is meaningful.
    # Noise sits well under the smallest kept singular value.
    s = 0.7 ** np.arange(8)
    A = (U * s) @ V.T + 1e-5 * rng.standard_normal((192, 32))
    return A.astype(np.float32)


def _sharded(A_np, mesh1d):
    return par.distribute(A_np, par.row_sharded(mesh1d))


def test_range_finder_sharded_matches_local(A_np, mesh1d):
    # s == rank: every basis direction is signal. Oversampled bases
    # (s > rank) carry directions whose power-iterated weight sits below
    # f32 eps — their content depends on intra-op reduction order (and
    # varies with thread scheduling), so no cross-layout bound on them
    # is honest; the adaptive/oversampling behaviors are covered by the
    # local krank suite.
    def run(A):
        rf = RandomizedRangeFinder(A, "power_iteration", {"s": 8, "q": 1},
                                   Context(seed=21))
        return np.asarray(rf.compute())

    Q_l = run(jnp.asarray(A_np))
    Q_s = run(_sharded(A_np, mesh1d))
    rec_l = Q_l @ (Q_l.T @ A_np)
    rec_s = Q_s @ (Q_s.T @ A_np)
    nrm = np.linalg.norm(A_np)
    assert np.linalg.norm(rec_s - rec_l) / nrm < 1e-3
    assert np.linalg.norm(A_np - rec_l) / nrm < 1e-2


def test_krank_randomized_svd_sharded_matches_local(A_np, mesh1d):
    _, S_l, _ = randomized_svd(jnp.asarray(A_np), 6, Context(seed=22), q=1)
    _, S_s, _ = randomized_svd(_sharded(A_np, mesh1d), 6,
                               Context(seed=22), q=1)
    # leading values sit far above the 1e-4 noise floor and must agree
    # tightly; the trailing value rides the floor
    np.testing.assert_allclose(np.asarray(S_s)[:4], np.asarray(S_l)[:4],
                               atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(S_s), np.asarray(S_l),
                               atol=1e-3, rtol=3e-2)


def test_lobpcg_rand_evd_sharded_matches_local(A_np, mesh1d):
    lam_l, _ = lobpcg_rand_evd(jnp.asarray(A_np), 4, Context(seed=23),
                               s=128)
    lam_s, _ = lobpcg_rand_evd(_sharded(A_np, mesh1d), 4,
                               Context(seed=23), s=128)
    # eigenvalues of AᵀA: separated by construction (0.49x per step)
    np.testing.assert_allclose(np.asarray(lam_s), np.asarray(lam_l),
                               atol=1e-4, rtol=1e-2)
    # and both match the analytic spectrum of the synthetic matrix
    true_lam = (0.7 ** np.arange(4)) ** 2
    np.testing.assert_allclose(np.sort(np.asarray(lam_l))[::-1], true_lam,
                               rtol=5e-2)


def test_lowrank_dominant_subspace_sharded_matches_local(A_np, mesh1d):
    def run(A):
        Z, _, _, _ = approximate_dominant_subspace_basis(
            A, k=4, s=16, t=24, context=Context(seed=24))
        return np.asarray(Z)

    Z_l = run(jnp.asarray(A_np))
    Z_s = run(_sharded(A_np, mesh1d))
    np.testing.assert_allclose(Z_s, Z_l, atol=1e-4, rtol=1e-4)


def test_lobpcg_rejects_sketch_smaller_than_cols(A_np):
    from libskylark_tpu.base import errors

    with pytest.raises(errors.InvalidParametersError, match="s >= n"):
        lobpcg_rand_evd(jnp.asarray(A_np), 4, Context(seed=23), s=16)
