"""Tests for the randomized low-rank toolkit, LOBPCG EVD, dominant-subspace
basis, nonlinear ML models, sprand, metrics (the python-skylark layer
equivalents; ref: python-skylark/skylark/nla/krank.py, randlobpcg.py,
lowrank.py, ml/nonlinear.py, sprand.py, metrics.py)."""

import numpy as np
import pytest
import jax.numpy as jnp

from libskylark_tpu.base.context import Context


def _lowrank_matrix(m=120, n=60, k=5, noise=1e-4, seed=0):
    rng = np.random.default_rng(seed)
    U = np.linalg.qr(rng.standard_normal((m, k)))[0]
    V = np.linalg.qr(rng.standard_normal((n, k)))[0]
    s = np.linspace(10, 1, k)
    A = (U * s) @ V.T + noise * rng.standard_normal((m, n))
    return A.astype(np.float32)


class TestRangeFinder:
    @pytest.mark.parametrize("method,params", [
        ("generic", {"s": 12}),
        ("power_iteration", {"s": 12, "q": 2}),
        ("subspace_iteration", {"s": 12, "q": 1}),
        ("fast_generic", {"s": 20}),
    ])
    def test_captures_range(self, method, params):
        from libskylark_tpu.nla.krank import RandomizedRangeFinder

        A = _lowrank_matrix()
        Q = RandomizedRangeFinder(A, method, params, Context(seed=3)).compute()
        Q = np.asarray(Q)
        resid = np.linalg.norm(A - Q @ (Q.T @ A)) / np.linalg.norm(A)
        assert resid < 1e-2, (method, resid)

    def test_adaptive(self):
        from libskylark_tpu.nla.krank import RandomizedRangeFinder

        A = _lowrank_matrix(noise=0)
        Q = RandomizedRangeFinder(
            A, "adaptive", {"epsilon": 1e-3, "r": 8}, Context(seed=3)
        ).compute()
        Q = np.asarray(Q)
        resid = np.linalg.norm(A - Q @ (Q.T @ A)) / np.linalg.norm(A)
        assert resid < 1e-2

    def test_missing_params_raise(self):
        from libskylark_tpu.base import errors
        from libskylark_tpu.nla.krank import RandomizedRangeFinder

        with pytest.raises(errors.InvalidParametersError):
            RandomizedRangeFinder(np.eye(4), "generic", {}, Context(seed=0))

    def test_deterministic(self):
        from libskylark_tpu.nla.krank import RandomizedRangeFinder

        A = _lowrank_matrix()
        Q1 = RandomizedRangeFinder(A, "generic", {"s": 10},
                                   Context(seed=7)).compute()
        Q2 = RandomizedRangeFinder(A, "generic", {"s": 10},
                                   Context(seed=7)).compute()
        np.testing.assert_allclose(np.asarray(Q1), np.asarray(Q2))


class TestRangeAssisted:
    def test_svd_direct(self):
        from libskylark_tpu.nla.krank import (
            RandomizedRangeFinder,
            RangeAssistedSVD,
        )

        A = _lowrank_matrix()
        Q = RandomizedRangeFinder(A, "power_iteration", {"s": 10, "q": 2},
                                  Context(seed=1)).compute()
        U, s, Vt = RangeAssistedSVD(A, Q).compute()
        R = (np.asarray(U) * np.asarray(s)) @ np.asarray(Vt)
        assert np.linalg.norm(R - A) / np.linalg.norm(A) < 1e-2
        sv = np.linalg.svd(A, compute_uv=False)
        np.testing.assert_allclose(np.asarray(s)[:5], sv[:5], rtol=1e-2)

    def test_svd_row_extraction(self):
        from libskylark_tpu.nla.krank import (
            RandomizedRangeFinder,
            RangeAssistedSVD,
        )

        A = _lowrank_matrix(noise=0)
        Q = RandomizedRangeFinder(A, "subspace_iteration", {"s": 8, "q": 1},
                                  Context(seed=1)).compute()
        U, s, Vt = RangeAssistedSVD(A, Q, method="row_extraction").compute()
        sv = np.linalg.svd(A, compute_uv=False)
        np.testing.assert_allclose(np.sort(np.asarray(s))[::-1][:5], sv[:5],
                                   rtol=5e-2)

    def test_evd_direct_and_nystrom(self):
        from libskylark_tpu.nla.krank import (
            RandomizedRangeFinder,
            RangeAssistedEVD,
        )

        B = _lowrank_matrix(80, 80, 4, noise=0)
        A = (B @ B.T).astype(np.float32)  # PSD
        # subspace iteration re-orthogonalizes each step, so the smallest
        # retained eigendirection survives f32 roundoff (plain power
        # iteration loses it at contrast (1/100)^5)
        Q = RandomizedRangeFinder(A, "subspace_iteration", {"s": 8, "q": 1},
                                  Context(seed=2)).compute()
        ew = np.linalg.eigvalsh(A)[::-1]
        for method in ("direct", "nystrom"):
            w, U = RangeAssistedEVD(A, Q, method=method).compute()
            w = np.sort(np.asarray(w))[::-1]
            np.testing.assert_allclose(w[:4], ew[:4], rtol=1e-2)

    def test_evd_one_pass(self):
        from libskylark_tpu.nla.krank import (
            RandomizedRangeFinder,
            RangeAssistedEVD,
        )

        B = _lowrank_matrix(80, 80, 4, noise=0)
        A = (B @ B.T).astype(np.float32)
        ctx = Context(seed=2)
        Q = RandomizedRangeFinder(A, "subspace_iteration", {"s": 8, "q": 1},
                                  ctx).compute()
        w, U = RangeAssistedEVD(A, Q, method="one_pass", params={"s": 16},
                                context=ctx).compute()
        ew = np.linalg.eigvalsh(A)[::-1]
        w = np.sort(np.asarray(w))[::-1]
        # one-pass is the crudest variant; check the well-separated top-3
        np.testing.assert_allclose(w[:3], ew[:3], rtol=0.15)

    def test_randomized_svd_convenience(self):
        from libskylark_tpu.nla.krank import randomized_svd

        A = _lowrank_matrix()
        U, s, Vt = randomized_svd(A, 5, Context(seed=4), q=2)
        assert U.shape == (120, 5) and s.shape == (5,) and Vt.shape == (5, 60)
        R = (np.asarray(U) * np.asarray(s)) @ np.asarray(Vt)
        assert np.linalg.norm(R - A) / np.linalg.norm(A) < 1e-2


class TestRandEVD:
    def test_power_iterations(self):
        from libskylark_tpu.nla.randlobpcg import power_iterations_rand_evd

        A = _lowrank_matrix(200, 30, 5, noise=1e-3)
        lam, Vt = power_iterations_rand_evd(A, 5, Context(seed=5),
                                            power_iters=3)
        ew = np.linalg.eigvalsh(A.T @ A)[::-1]
        np.testing.assert_allclose(np.asarray(lam)[:3], ew[:3], rtol=1e-2)

    def test_lobpcg(self):
        from libskylark_tpu.nla.randlobpcg import lobpcg_rand_evd

        A = _lowrank_matrix(300, 24, 4, noise=1e-3)
        lam, Vt = lobpcg_rand_evd(A, 4, Context(seed=6))
        ew = np.linalg.eigvalsh(A.T @ A)[::-1]
        np.testing.assert_allclose(lam[:2], ew[:2], rtol=5e-2)


class TestLowrank:
    @pytest.mark.slow
    def test_dominant_subspace(self):
        from libskylark_tpu.nla.lowrank import (
            approximate_dominant_subspace_basis,
        )

        A = _lowrank_matrix(150, 40, 4, noise=1e-3)
        Z, S, R, V = approximate_dominant_subspace_basis(
            A, 4, 16, 40, Context(seed=8))
        Z = np.asarray(Z)
        resid = np.linalg.norm(A - Z @ (Z.T @ A), "fro")
        sv = np.linalg.svd(A, compute_uv=False)
        opt = np.sqrt((sv[4:] ** 2).sum())
        assert resid <= 3.0 * opt + 1e-3


def _classification_data(n=300, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (np.sin(X[:, 0]) + X[:, 1] > 0).astype(np.int64)
    return X, y


class TestNonlinear:
    @pytest.mark.slow
    def test_rls(self):
        from libskylark_tpu.ml.kernels import Gaussian
        from libskylark_tpu.ml.metrics import classification_accuracy
        from libskylark_tpu.ml.nonlinear import RLS

        X, y = _classification_data()
        model = RLS(Gaussian(8, sigma=2.0)).train(X[:200], y[:200],
                                                  regularization=0.01)
        pred = model.predict(X[200:])
        assert classification_accuracy(pred, y[200:]) > 80

    def test_sketchrls(self):
        from libskylark_tpu.ml.kernels import Gaussian
        from libskylark_tpu.ml.metrics import classification_accuracy
        from libskylark_tpu.ml.nonlinear import SketchRLS

        X, y = _classification_data()
        # 384 features: at 128 the accuracy sits ON the 75% threshold
        # and flips with the toolchain's random-stream details (70-78%
        # across seeds/jax versions); more features make the kernel
        # approximation — the thing under test — robustly good
        model = SketchRLS(Gaussian(8, sigma=2.0)).train(
            X[:200], y[:200], Context(seed=9), random_features=384,
            regularization=0.01)
        pred = model.predict(X[200:])
        assert classification_accuracy(pred, y[200:]) > 75

    @pytest.mark.parametrize("probdist", ["uniform", "leverages"])
    @pytest.mark.slow
    def test_nystromrls(self, probdist):
        from libskylark_tpu.ml.kernels import Gaussian
        from libskylark_tpu.ml.metrics import classification_accuracy
        from libskylark_tpu.ml.nonlinear import NystromRLS

        X, y = _classification_data()
        model = NystromRLS(Gaussian(8, sigma=2.0)).train(
            X[:200], y[:200], Context(seed=10), random_features=64,
            regularization=0.01, probdist=probdist)
        pred = model.predict(X[200:])
        assert classification_accuracy(pred, y[200:]) > 75

    @pytest.mark.slow
    def test_sketchpcr(self):
        from libskylark_tpu.ml.kernels import Gaussian
        from libskylark_tpu.ml.metrics import classification_accuracy
        from libskylark_tpu.ml.nonlinear import SketchPCR

        X, y = _classification_data()
        model = SketchPCR(Gaussian(8, sigma=2.0)).train(
            X[:200], y[:200], Context(seed=11), rank=40)
        pred = model.predict(X[200:])
        assert classification_accuracy(pred, y[200:]) > 70

    def test_rls_regression(self):
        from libskylark_tpu.ml.kernels import Gaussian
        from libskylark_tpu.ml.metrics import rmse
        from libskylark_tpu.ml.nonlinear import RLS

        rng = np.random.default_rng(3)
        X = rng.standard_normal((150, 4)).astype(np.float32)
        y = np.sin(X[:, 0]).astype(np.float32)
        model = RLS(Gaussian(4, sigma=1.0)).train(
            X[:100], y[:100], regularization=1e-3, multiclass=False)
        pred = model.predict(X[100:])
        assert rmse(pred, y[100:]) < 0.2


class TestSprand:
    def test_sample_density_and_values(self):
        from libskylark_tpu.base.sprand import sample

        S = sample(60, 50, 0.1, [-1, 1], [0.5, 0.5], Context(seed=12))
        assert S.shape == (60, 50)
        assert 0 < S.nnz <= 300
        assert set(np.unique(S.data)) <= {-1.0, 1.0}

    def test_hashmap_shapes(self):
        from libskylark_tpu.base.sprand import hashmap

        S0 = hashmap(8, 40, Context(seed=13))
        assert S0.shape == (8, 40) and S0.nnz == 40
        S1 = hashmap(8, 40, Context(seed=13), dimension=1)
        assert S1.shape == (40, 8) and S1.nnz == 40
        # every item hashed exactly once
        D = np.asarray(S0.todense())
        np.testing.assert_array_equal((D != 0).sum(axis=0), np.ones(40))


class TestModeling:
    def test_linearized_kernel_model(self, tmp_path):
        from libskylark_tpu.algorithms.prox import (
            L2Regularizer,
            SquaredLoss,
        )
        from libskylark_tpu.ml.admm import BlockADMMSolver
        from libskylark_tpu.ml.modeling import LinearizedKernelModel

        X, y = _classification_data(120, 5, seed=4)
        solver = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.01, 5)
        solver.maxiter = 5
        model = solver.train(X, y)
        path = str(tmp_path / "m.json")
        model.save(path)
        lkm = LinearizedKernelModel(path)
        assert lkm.get_input_dimension() == 5
        pred = lkm.predict(X)
        assert np.asarray(pred).shape[0] == 120


class TestReviewRegressions:
    def test_sample_exact_density(self):
        from libskylark_tpu.base.sprand import sample

        S = sample(100, 100, 0.5, [-1, 1], [0.5, 0.5], Context(seed=20))
        assert S.nnz == 5000  # exact, scipy.sparse.rand semantics

    def test_lobpcg_bad_sketch_name(self):
        from libskylark_tpu.base import errors
        from libskylark_tpu.nla.randlobpcg import lobpcg_rand_evd

        A = _lowrank_matrix(100, 20, 3)
        with pytest.raises(errors.InvalidParametersError):
            lobpcg_rand_evd(A, 3, Context(seed=21), sketch="gaussian")

    def test_linearized_model_decodes_labels(self, tmp_path):
        from libskylark_tpu.algorithms.prox import (
            L2Regularizer,
            SquaredLoss,
        )
        from libskylark_tpu.ml.admm import BlockADMMSolver
        from libskylark_tpu.ml.modeling import LinearizedKernelModel

        rng = np.random.default_rng(7)
        X = rng.standard_normal((80, 4)).astype(np.float32)
        raw = np.where(X[:, 0] > 0, 9, 3)
        solver = BlockADMMSolver(SquaredLoss(), L2Regularizer(), 0.001, 4)
        solver.maxiter = 20
        classes = np.unique(raw)
        model = solver.train(X, np.searchsorted(classes, raw))
        model.label_coding = classes.tolist()
        p = str(tmp_path / "m.json")
        model.save(p)
        pred = LinearizedKernelModel(p).predict(X)
        assert set(np.unique(pred)) <= {3, 9}
