"""Execute every example notebook's code cells — notebooks are executable
documentation, as in the reference (ref:
python-skylark/skylark/notebooks/*.ipynb, wired as docs)."""

import pathlib

import nbformat
import pytest

pytestmark = pytest.mark.slow

NB_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples" / "notebooks"
NOTEBOOKS = sorted(NB_DIR.glob("*.ipynb"))


@pytest.mark.parametrize("path", NOTEBOOKS, ids=lambda p: p.stem)
def test_notebook_executes(path):
    nb = nbformat.read(path, as_version=4)
    ns: dict = {}
    for cell in nb.cells:
        if cell.cell_type == "code":
            exec(compile(cell.source, f"{path.name}", "exec"), ns)


def test_notebooks_present():
    assert len(NOTEBOOKS) >= 4
