"""Numerical verification of the fused Pallas generation+matmul kernel.

The kernel (sketch/pallas_dense.py) is the flagship perf component; these
tests pin its numerics WITHOUT TPU hardware via ``interpret=True`` (the
Pallas interpreter executes the same program on CPU):

1. the in-kernel operator generation (``_gen_block``) is bit-identical to
   the XLA-path stream definition (:func:`randgen.dense_block`) — the
   invariant the whole determinism oracle rests on,
2. the fused rowwise/columnwise applies match the XLA path within the
   framework's 1e-4 oracle (ref: tests/unit/test_utils.hpp:48) at the
   "f32" regime (the conservative one; the shipping default "bf16x3" is
   oracle-certified on chip, benchmarks/tpu_validation_r03.txt),
3. the single-pass "bf16" regime's contraction gap is quantified: it is
   bounded by the bf16 rounding model but exceeds the 1e-4 oracle —
   which is why it stays opt-in (sketch/params.py),
4. ragged (non-BLOCK_COLS-multiple N, odd m) inputs zero-pad exactly.

An on-chip variant runs when the default backend is a real TPU
(@pytest.mark.tpu — skipped on the CPU CI mesh).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu.base import randgen
from libskylark_tpu.base.context import Context
from libskylark_tpu.sketch import JLT, CT, ROWWISE, COLUMNWISE
from libskylark_tpu.sketch import params as sketch_params
from libskylark_tpu.sketch import pallas_dense as pd
from libskylark_tpu.sketch.dense import BLOCK_COLS

pl = pytest.importorskip("jax.experimental.pallas")
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

ON_TPU = pd.available()


@pytest.fixture(autouse=True)
def _xla_path_for_oracle():
    """Oracle side must take the XLA path regardless of backend."""
    sketch_params.set_use_pallas(False)
    yield
    sketch_params.set_use_pallas(True)


def _gen_via_kernel(dist, s_dim, n_blocks, key, interpret=True):
    """Materialize S via the in-kernel generator, one block per grid step."""
    kind = pd._DIST_KINDS[type(dist)]
    kern = functools.partial(
        lambda dk, sd, keys_ref, out_ref: out_ref.__setitem__(
            slice(None), pd._gen_block(dk, sd, keys_ref, pl.program_id(0))
        ),
        kind,
        s_dim,
    )
    return pl.pallas_call(
        kern,
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((s_dim, BLOCK_COLS), lambda k: (0, k)),
        out_shape=jax.ShapeDtypeStruct(
            (s_dim, n_blocks * BLOCK_COLS), jnp.float32
        ),
        interpret=interpret,
    )(pd._block_keys(key, n_blocks * BLOCK_COLS))


@pytest.mark.parametrize(
    "dist",
    [randgen.Normal(), randgen.Cauchy(), randgen.Rademacher()],
    ids=["normal", "cauchy", "rademacher"],
)
def test_gen_block_bit_identical(dist):
    """In-kernel Threefry replay == randgen.dense_block, bit for bit."""
    s_dim, n_blocks = 16, 3
    key = Context(seed=11).allocate().key
    got = np.asarray(_gen_via_kernel(dist, s_dim, n_blocks, key))
    want = np.concatenate(
        [
            np.asarray(
                randgen.dense_block(key, dist, s_dim, b, BLOCK_COLS)
            )
            for b in range(n_blocks)
        ],
        axis=1,
    )
    assert np.array_equal(got, want), (
        f"max abs diff {np.abs(got - want).max()}"
    )


@pytest.mark.parametrize("shape", [(64, 512), (64, 768)])
def test_fused_rowwise_matches_xla(shape):
    """Fused A·Sᵀ (interpret, f32 regime) vs the XLA apply, ≤1e-4 oracle."""
    m, n = shape
    s = 96
    ctx = Context(seed=5)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(0).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, ROWWISE))
    got = pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        precision="f32", interpret=True,
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_fused_columnwise_matches_xla():
    n, m, s = 512, 48, 96
    ctx = Context(seed=6)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(1).standard_normal((n, m)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, COLUMNWISE))
    got = pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        precision="f32", interpret=True,
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_fused_ct_cauchy_matches_xla():
    """Cauchy entries are heavy-tailed; relative comparison."""
    m, n, s = 32, 512, 64
    ctx = Context(seed=7)
    ct = CT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(2).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(ct.apply(A, ROWWISE))
    got = pd.rowwise_apply(
        ct._alloc.key, ct.dist, A, s, ct.scale,
        precision="f32", interpret=True,
    )
    assert got is not None
    np.testing.assert_allclose(
        np.asarray(got), want, rtol=1e-4,
        atol=1e-4 * float(np.abs(want).max()),
    )


@pytest.mark.parametrize("shape", [(7, 300), (13, 257), (50, 1000)])
def test_fused_ragged_shapes_exact_padding(shape):
    """Non-dividing m and N: zero-padding must be exact, not approximate
    (the reference's np=5/7 ragged-layout discipline,
    ref: tests/unit/CMakeLists.txt:31-33)."""
    m, n = shape
    s = 32
    ctx = Context(seed=8)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(3).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, ROWWISE))
    got = pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        precision="f32", interpret=True,
    )
    assert got is not None
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_bf16_regime_gap_quantified():
    """The bf16 regime is accurate to the bf16 rounding model (~2⁻⁸
    relative on the contraction) but NOT to the 1e-4 oracle — the measured
    gap is the justification for the f32 default (sketch/params.py)."""
    m, n, s = 32, 2048, 64
    ctx = Context(seed=9)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(4).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, ROWWISE))
    got = np.asarray(
        pd.rowwise_apply(
            jlt._alloc.key, jlt.dist, A, s, jlt.scale,
            precision="bf16", interpret=True,
        )
    )
    scale = np.abs(want).max()
    rel = np.abs(got - want).max() / scale
    # bounded by the bf16 model…
    assert rel < 2.0 ** -6, f"bf16 contraction error {rel} implausibly large"
    # …but not oracle-grade (if this ever starts passing at 1e-4 the
    # interpreter stopped emulating bf16 and the regime split is moot).
    assert rel > 1e-6, "bf16 regime unexpectedly bit-matched the f32 path"


def test_try_pallas_interpret_consistency_via_transform():
    """End to end: T.apply (XLA) == pallas interpret apply on the same
    transform object, both dimensions."""
    n, s = 512, 64
    ctx = Context(seed=10)
    jlt = JLT(n, s, ctx)
    rng = np.random.default_rng(5)
    A_r = jnp.asarray(rng.standard_normal((24, n)), jnp.float32)
    A_c = jnp.asarray(rng.standard_normal((n, 24)), jnp.float32)
    got_r = pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A_r, s, jlt.scale, interpret=True
    )
    got_c = pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, A_c, s, jlt.scale, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got_r), np.asarray(jlt.apply(A_r, ROWWISE)),
        atol=1e-4, rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(got_c), np.asarray(jlt.apply(A_c, COLUMNWISE)),
        atol=1e-4, rtol=1e-4,
    )


@pytest.mark.parametrize("shape", [(24, 512), (13, 300)])
def test_rft_fully_fused_epilogue(shape):
    """Generation + matmul + cos epilogue in ONE kernel must equal the
    production apply (XLA path) — incl. ragged shapes. Normal-frequency
    transforms only: Cauchy frequencies (Laplacian) give heavy-tailed
    phases where f32 cos is ill-conditioned, so the fused path is gated
    off for them (rft.py _try_fused_rowwise)."""
    from libskylark_tpu.sketch.rft import GaussianRFT

    m, n = shape
    s = 64
    T = GaussianRFT(n, s, Context(seed=14), sigma=2.0)
    A = jnp.asarray(
        np.random.default_rng(8).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(T.apply(A, ROWWISE))      # XLA path (fixture)
    got = pd.rft_rowwise_apply(
        T.subkey(0), T.dist, A, s, T.inscale, T.outscale,
        np.asarray(T.row_scales()), np.asarray(T.shifts()),
        precision="f32", interpret=True,
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_rft_projection_rides_the_kernel():
    """The RFT frequency matrix shares the dense-block stream format, so
    the fused kernel path (interpret) must equal the XLA w_panel path
    after the cos featurization."""
    from libskylark_tpu.sketch.rft import GaussianRFT

    n, s, m = 512, 64, 24
    T = GaussianRFT(n, s, Context(seed=13), sigma=2.0)
    A = jnp.asarray(
        np.random.default_rng(7).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(T.apply(A, ROWWISE))          # XLA path (fixture)
    proj = pd.rowwise_apply(
        T.subkey(0), T.dist, A, s, T.inscale,
        precision="f32", interpret=True,
    )
    assert proj is not None
    got = np.asarray(T._featurize(proj, feature_axis=1))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_pipelined_variant_matches_plain(monkeypatch):
    """SKYLARK_PALLAS_PIPELINE=1 routes big-operator applies through the
    double-buffered generation kernel (_kernel_pipe); its output must be
    identical to the plain kernel's (same blocks, same contraction — only
    the generation scheduling differs), incl. the fused cos epilogue."""
    from libskylark_tpu.sketch.rft import GaussianRFT

    m, n, s = 64, 1024, 96
    ctx = Context(seed=21)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(9).standard_normal((m, n)), jnp.float32
    )
    # baselines at the SAME m_tile/scratch config as the pipelined runs
    # below: XLA's CPU gemm may reassociate differently per program
    # shape, so equality is only a pipeline-scheduling oracle when the
    # two sides differ in NOTHING but the pipeline toggle
    monkeypatch.setattr(pd, "_SCRATCH_CAP_BYTES", 0)
    plain = np.asarray(pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        m_tile=16, precision="f32", interpret=True))
    T = GaussianRFT(n, s, Context(seed=22), sigma=2.0)
    plain_cos = np.asarray(pd.rft_rowwise_apply(
        T.subkey(0), T.dist, A, s, T.inscale, T.outscale,
        np.asarray(T.row_scales()), np.asarray(T.shifts()),
        m_tile=16, precision="f32", interpret=True))
    A_c = jnp.asarray(
        np.random.default_rng(10).standard_normal((n, 48)), jnp.float32
    )
    # columnwise baseline BEFORE the pipeline env engages (else both
    # sides would run the pipe kernel and a defect would self-compare)
    plain_c = np.asarray(pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, A_c, s, jlt.scale,
        m_tile=16, precision="f32", interpret=True))

    monkeypatch.setenv("SKYLARK_PALLAS_PIPELINE", "1")
    piped = np.asarray(pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        m_tile=16, precision="f32", interpret=True))
    piped_cos = np.asarray(pd.rft_rowwise_apply(
        T.subkey(0), T.dist, A, s, T.inscale, T.outscale,
        np.asarray(T.row_scales()), np.asarray(T.shifts()),
        m_tile=16, precision="f32", interpret=True))
    np.testing.assert_array_equal(piped, plain)
    np.testing.assert_array_equal(piped_cos, plain_cos)
    piped_c = np.asarray(pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, A_c, s, jlt.scale,
        m_tile=16, precision="f32", interpret=True))
    np.testing.assert_array_equal(piped_c, plain_c)


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU backend")
@pytest.mark.parametrize("precision", ["f32", "bf16x3"])
def test_fused_on_chip_matches_xla(precision):
    """On-chip (Mosaic-compiled, not interpreted) vs the XLA path. The
    bf16x3 case certifies the manual 3-pass bf16 split against the 1e-4
    oracle on real MXU rounding (run with SKYLARK_TEST_TPU=1)."""
    m, n, s = 256, 2048, 128
    ctx = Context(seed=12)
    jlt = JLT(n, s, ctx)
    A = jnp.asarray(
        np.random.default_rng(6).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, ROWWISE))
    got = pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale, precision=precision
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU backend")
def test_fused_on_chip_columnwise():
    """Columnwise orientation on chip at the shipping default regime."""
    precision = "bf16x3"
    n, m, s = 2048, 192, 128
    jlt = JLT(n, s, Context(seed=15))
    A = jnp.asarray(
        np.random.default_rng(7).standard_normal((n, m)), jnp.float32
    )
    want = np.asarray(jlt.apply(A, COLUMNWISE))
    got = pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale, precision=precision
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU backend")
def test_fused_on_chip_rft_epilogue():
    """Generation + matmul + in-VMEM cos epilogue, Mosaic-compiled, vs
    the XLA featurization path."""
    from libskylark_tpu.sketch.rft import GaussianRFT

    m, n, s = 192, 2048, 128
    T = GaussianRFT(n, s, Context(seed=16), sigma=2.0)
    A = jnp.asarray(
        np.random.default_rng(8).standard_normal((m, n)), jnp.float32
    )
    want = np.asarray(T.apply(A, ROWWISE))      # XLA path (fixture)
    got = pd.rft_rowwise_apply(
        T.subkey(0), T.dist, A, s, T.inscale, T.outscale,
        np.asarray(T.row_scales()), np.asarray(T.shifts()),
        precision="bf16x3",
    )
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU backend")
def test_fused_on_chip_pipelined(monkeypatch):
    """The double-buffered generation pipeline, Mosaic-compiled: must be
    bit-identical to the plain kernel on chip (same blocks, same
    contraction — only instruction scheduling differs)."""
    m, n, s = 256, 2048, 128
    jlt = JLT(n, s, Context(seed=17))
    A = jnp.asarray(
        np.random.default_rng(9).standard_normal((m, n)), jnp.float32
    )
    # same m_tile both sides: tile shape could legitimately change MXU
    # accumulation scheduling; only the pipeline flag may differ. An
    # ambient SKYLARK_PALLAS_PIPELINE=1 (e.g. a debugging run) must not
    # make the baseline take the pipe kernel and self-compare.
    monkeypatch.delenv("SKYLARK_PALLAS_PIPELINE", raising=False)
    jax.clear_caches()
    monkeypatch.setattr(pd, "_SCRATCH_CAP_BYTES", 0)
    plain = np.asarray(pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        m_tile=32, precision="bf16x3"))
    monkeypatch.setenv("SKYLARK_PALLAS_PIPELINE", "1")
    # the pipeline flag is read at TRACE time and both calls share static
    # args — drop the jit cache so the second call really retraces
    jax.clear_caches()
    piped = np.asarray(pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        m_tile=32, precision="bf16x3"))
    np.testing.assert_array_equal(piped, plain)


def test_effective_plan_reports_actual_config(monkeypatch):
    """effective_plan must report what the kernel would RUN, not what was
    requested: _qualify silently shrinks over-budget m-tiles and
    _select_pipe can drop the pipeline buffer, so sweep records labeled
    with requested knobs would lie about the measurement (the m-tile
    sweep in benchmarks/ keys its rows off this)."""
    from libskylark_tpu.sketch import params as sketch_params

    dist = randgen.Normal()
    monkeypatch.delenv("SKYLARK_PALLAS_PIPELINE", raising=False)
    # isolate from the COMMITTED plan cache: on a v5e host the seeded
    # flagship entry would hit the headline-shape workload below and
    # flip plan_source to "cache" — this test pins the HEURISTIC report
    monkeypatch.setattr(sketch_params, "_use_plan_cache", False)

    # headline shape, requested tile fits: honored, operator too big to
    # cache (32 MiB > cap), no pipeline without the env. The plan also
    # names itself (plan_id/precision/plan_source — the autotuner
    # cache's reporting surface).
    p = pd.effective_plan(dist, (8192, 8192), jnp.float32, 1024,
                          seq_axis=1, m_tile=1024, interpret=True)
    assert p == {"kernel": True, "m_tile": 1024, "operator_cache": False,
                 "pipelined": False, "precision": "bf16x3",
                 "plan_id": "pallas/mt1024/bf16x3",
                 "plan_source": "heuristic"}

    # requested tile exceeds the VMEM plan: pre-shrunk, and the plan says
    # so (this is the silent adjustment the record must surface)
    p = pd.effective_plan(dist, (8192, 8192), jnp.float32, 1024,
                          seq_axis=1, m_tile=2048, interpret=True)
    assert p["m_tile"] < 2048

    # pipeline honored only in the big-operator regime with the env set
    monkeypatch.setenv("SKYLARK_PALLAS_PIPELINE", "1")
    p = pd.effective_plan(dist, (8192, 8192), jnp.float32, 1024,
                          seq_axis=1, m_tile=1024, interpret=True)
    assert p["pipelined"] is True and p["operator_cache"] is False

    # small operator: VMEM cache engages and suppresses the pipeline
    # (cache already amortizes generation)
    p = pd.effective_plan(dist, (1024, 1024), jnp.float32, 128,
                          seq_axis=1, m_tile=256, interpret=True)
    assert p["operator_cache"] is True and p["pipelined"] is False

    # unsupported dtype: the apply would take the XLA fallback
    p = pd.effective_plan(dist, (1024, 1024), jnp.float64, 128,
                          seq_axis=1, m_tile=256, interpret=True)
    assert p == {"kernel": False, "plan_id": "xla",
                 "plan_source": "heuristic"}


def test_bf16gen2_regime_matches_rounded_operator_oracle():
    """"bf16gen2" (r5, the 2-pass lever for the >=100 GB/s hunt):
    the operator is DEFINED as scale × bf16-rounding of the UNIT
    stream (the kernel contracts unit entries; scale multiplies
    post-contraction — pallas_dense.rowwise_apply), so the oracle is a
    host gemm against exactly that — and the 2-pass data split must be
    f32-grade (1e-4) w.r.t. it, in BOTH orientations. s = 96 makes
    scale = 1/√96 non-dyadic, so rounding the unit stream and rounding
    the scaled panel genuinely differ — the oracle pins WHICH is the
    definition (review finding: at power-of-two scales the two
    coincide and the test would silently under-specify). Against the
    f32-operator apply the same result must differ at the ~2^-8
    operator-rounding level (if it ever matches at 1e-4, the regime
    stopped rounding and its speed claim is moot)."""
    from libskylark_tpu.base import randgen

    m, n, s = 32, 2048, 96
    ctx = Context(seed=10)
    jlt = JLT(n, s, ctx)
    rng = np.random.default_rng(5)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)

    unit = randgen.dense_panel(jlt._alloc.key, jlt.dist, s, 0, n,
                               pd.BLOCK_COLS
                               if hasattr(pd, "BLOCK_COLS") else 256,
                               jnp.float32)
    S_rounded = jlt.scale * (np.asarray(unit)
                             .astype(jnp.bfloat16).astype(np.float64))
    want = np.asarray(A, np.float64) @ S_rounded.T
    got = np.asarray(pd.rowwise_apply(
        jlt._alloc.key, jlt.dist, A, s, jlt.scale,
        precision="bf16gen2", interpret=True))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    want_f32op = np.asarray(jlt.apply(A, ROWWISE), np.float64)
    rel = np.abs(got - want_f32op).max() / np.abs(want_f32op).max()
    assert 2.0 ** -12 < rel < 2.0 ** -6, rel

    Ac = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    want_cw = S_rounded @ np.asarray(Ac, np.float64)
    got_cw = np.asarray(pd.columnwise_apply(
        jlt._alloc.key, jlt.dist, Ac, s, jlt.scale,
        precision="bf16gen2", interpret=True))
    np.testing.assert_allclose(got_cw, want_cw, atol=1e-4, rtol=1e-4)
