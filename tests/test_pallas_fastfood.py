"""Fused Fastfood kernel oracles.

Interpret-mode tests pin the kernel's EXACT semantics against the XLA
chain (`FastRFT._features_rows`) on CPU — same diagonals, permutations,
block order, truncation, cos featurization — so the first live tunnel
window spends its budget on Mosaic compilation and timing, not
semantics (the r3/r4 discipline: never burn a window on a test-file
bug). The @tpu test is the on-chip certification the watcher runs."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu.base.context import Context
from libskylark_tpu.sketch import pallas_fastfood as pf
from libskylark_tpu.sketch.frft import FastGaussianRFT, FastMaternRFT


def _X(m, d, seed=0, scale=0.3):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal((m, d)) * scale,
        jnp.float32)


def _oracle(T, X):
    """The XLA chain is the semantic definition (its own correctness is
    pinned by the explicit-operator oracle in test_sketch_fast.py)."""
    return np.asarray(T._features_rows(X), np.float64)


class TestInterpretOracle:
    @pytest.mark.parametrize("m,d,s", [
        (32, 512, 512),     # single block, no padding
        (32, 512, 1536),    # THREE blocks (block-major order + perms)
        (24, 300, 512),     # d < NB: column padding
        (19, 512, 700),     # ragged rows (row padding) + truncation
    ])
    def test_matches_xla_chain(self, m, d, s):
        T = FastGaussianRFT(d, s, Context(seed=8), sigma=2.5)
        X = _X(m, d, seed=m)
        got = pf.features_rows(T, X, interpret=True, precision="f32")
        assert got is not None and got.shape == (m, s)
        np.testing.assert_allclose(np.asarray(got), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)

    def test_matern_sm_diagonal(self):
        T = FastMaternRFT(512, 1024, Context(seed=9), nu=1.5, l=2.0)
        X = _X(16, 512, seed=3)
        got = pf.features_rows(T, X, interpret=True, precision="f32")
        np.testing.assert_allclose(np.asarray(got), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)

    def test_bf16x3_regime_stays_in_oracle(self):
        """The shipping contraction regime: ±1 Hadamard factors are
        bf16-exact, so the 3-pass split must stay f32-grade through the
        DOUBLE WHT (error compounds across the two transforms)."""
        T = FastGaussianRFT(512, 512, Context(seed=11), sigma=2.0)
        X = _X(32, 512, seed=5)
        got = pf.features_rows(T, X, interpret=True, precision="bf16x3")
        np.testing.assert_allclose(np.asarray(got), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("m,d,s", [
        (32, 512, 512),
        (24, 300, 1536),    # padding + multi-block through the split
    ])
    def test_split_variant_matches_xla_chain(self, m, d, s):
        """The two-kernel fallback (XLA gather between VMEM stages —
        used if Mosaic rejects the fused kernel's in-kernel gather)
        must satisfy the same oracle."""
        T = FastGaussianRFT(d, s, Context(seed=8), sigma=2.5)
        X = _X(m, d, seed=m + 1)
        got = pf.features_rows(T, X, interpret=True, precision="f32",
                               variant="split")
        assert got is not None and pf.last_served_variant == "split"
        np.testing.assert_allclose(np.asarray(got), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)

    def test_variants_agree_bitwise_class(self):
        """Fused and split compute the same chain; at f32 regime the
        two must agree to float-roundoff (the gather position is the
        only structural difference and it is exact)."""
        T = FastGaussianRFT(512, 1024, Context(seed=14))
        X = _X(16, 512, seed=2)
        a = pf.features_rows(T, X, interpret=True, precision="f32",
                             variant="fused")
        b = pf.features_rows(T, X, interpret=True, precision="f32",
                             variant="split")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)

    def test_wht2_bf16x3_remap_is_bit_identical(self):
        """_wht2 remaps bf16x3 → bf16gen2 (2 passes) on the claim that
        the ±1 Hadamard operand's bf16 lo-term is identically zero, so
        bf16x3's middle pass contributes exact zeros. Pin it: force the
        un-remapped 3-pass split through _dot directly and require BIT
        equality with _wht2's remapped result (review finding — the
        claim held only in a docstring)."""
        from libskylark_tpu.sketch.fut import _hadamard_np
        from libskylark_tpu.sketch.pallas_dense import _dot
        from libskylark_tpu.sketch.pallas_fastfood import (_wht2,
                                                           _wht_split)

        mt, NB = 8, 1024
        a, b = _wht_split(NB)
        Ha = jnp.asarray(_hadamard_np(a), jnp.float32)
        Hb = jnp.asarray(_hadamard_np(b), jnp.float32)
        W = jnp.asarray(
            np.random.default_rng(6).standard_normal((mt, NB)),
            jnp.float32)
        got = _wht2(W, Ha, Hb, mt, a, b, "bf16x3")  # remapped to gen2
        dims = (((1,), (0,)), ((), ()))
        Z = _dot(W.reshape(mt * a, b), Hb, dims,
                 "bf16x3").reshape(mt, a, b)
        Zt = jnp.swapaxes(Z, 1, 2)
        Y = _dot(Zt.reshape(mt * b, a), Ha, dims,
                 "bf16x3").reshape(mt, b, a)
        want = jnp.swapaxes(Y, 1, 2).reshape(mt, NB)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_invalid_variant_raises_valueerror(self):
        T = FastGaussianRFT(512, 512, Context(seed=15))
        with pytest.raises(ValueError, match="variant"):
            pf.features_rows(T, _X(8, 512), interpret=True,
                             variant="Split")

    def test_deterministic_across_calls(self):
        T = FastGaussianRFT(512, 512, Context(seed=12))
        X = _X(16, 512, seed=7)
        a = pf.features_rows(T, X, interpret=True)
        b = pf.features_rows(T, X, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_kernel_approximates_gaussian_kernel(self):
        """End-to-end sanity at MC rate — same oracle class as the
        on-chip battery's Fastfood test."""
        d, s, m, sigma = 64, 2048, 12, 3.0
        X = _X(m, d, seed=4)
        T = FastGaussianRFT(d, s, Context(seed=8), sigma=sigma)
        F = np.asarray(
            pf.features_rows(T, X, interpret=True), np.float64)
        got = F @ F.T
        Xn = np.asarray(X, np.float64)
        d2 = ((Xn[:, None, :] - Xn[None, :, :]) ** 2).sum(-1)
        want = np.exp(-d2 / (2 * sigma * sigma))
        assert np.max(np.abs(got - want)) < 0.15


class TestDispatch:
    def test_declines_off_tpu_and_falls_back(self):
        """On the CPU backend supported() is False: the public apply
        must transparently take the XLA chain (and the kernel path must
        return None rather than raise)."""
        from libskylark_tpu.sketch import ROWWISE

        T = FastGaussianRFT(512, 512, Context(seed=13))
        X = _X(8, 512, seed=9)
        assert pf.features_rows(T, X) is None
        out = T.apply(X, ROWWISE)  # dispatch falls through, no error
        np.testing.assert_allclose(np.asarray(out), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)

    def test_declines_dct_core_and_small_nb(self):
        X = _X(8, 300, seed=1)
        assert not pf.supported(
            FastGaussianRFT(300, 512, Context(seed=2), fut="dct"), X)
        assert not pf.supported(
            FastGaussianRFT(64, 128, Context(seed=3)), _X(8, 64))

    def test_plan_m_tile_respects_budget(self):
        mt = pf.plan_m_tile(4096, 1 << 20)
        assert mt is not None and mt % 8 == 0
        assert mt * 4096 * 4 * 8 <= pf._VMEM_BUDGET_BYTES
        assert pf.plan_m_tile(1 << 22, 128) is None  # absurd NB declines


ON_TPU = (pf.available()
          or os.environ.get("SKYLARK_BATTERY_FORCE") == "1")


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU backend")
class TestOnChip:
    def test_mosaic_compiles_and_matches_host_oracle(self):
        """The on-chip certification: real Mosaic lowering, compared to
        the HOST-side explicit chain. Tries the fused kernel (in-kernel
        lane gather — the unproven op) and falls back to the split
        two-kernel pipeline; prints which variant certified so the
        watcher transcript records it. Fails only if NEITHER lowers."""
        d, s, m = 2048, 2048, 64
        T = FastGaussianRFT(d, s, Context(seed=21), sigma=2.0)
        X = _X(m, d, seed=17)
        got = pf.features_rows(T, X, precision="bf16x3", variant="auto")
        if got is None and not pf.available():
            pytest.skip("kernel declined: no TPU pallas backend")
        assert got is not None, \
            "BOTH kernel variants failed Mosaic compile (watcher log)"
        print(f"\nCERTIFIED_VARIANT={pf.last_served_variant}")
        np.testing.assert_allclose(np.asarray(got), _oracle(T, X),
                                   atol=1e-4, rtol=1e-4)
