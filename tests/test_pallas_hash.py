"""Scatter-free CWT/CountSketch Pallas kernel (sketch/pallas_hash.py)
and the serve-bucket kernel-selection seam it feeds.

Oracles, strongest first:

- *stream bit-equality*: the kernel's in-VMEM (h, v) generation
  (``_gen_hv`` over the ``chunk_key_table`` keys) reproduces
  ``randgen.stream_slice`` bit-for-bit — jax.random's own
  fold_in/split/randint/rademacher pipeline replayed through the shared
  integer-op Threefry, across chunk boundaries.
- *exact-accumulation bit-equality* (interpret mode): ``accum="exact"``
  equals ``HashTransform.apply`` AND ``cwt_serve_apply`` bitwise,
  including zero-padded serve lanes and across capacity classes (the
  serve layer's lane-invariance contract).
- *MXU-mode dataflow bit-equality on lattice data*: integer-valued
  inputs make every bucket sum exact, so the one-hot contraction is
  bit-equal to the scatter no matter the accumulation order — this pins
  the whole MXU dataflow bitwise; float data is then 1e-5-close (order
  differs, values don't).
- serve integration: a forced-pallas flush is bit-equal to the
  capacity-1 XLA dispatch, the kernel choice is a static of the
  executable key, declines are counted by reason, and on a CPU host the
  tuner correctly certifies XLA for every serve bucket (the interpret
  penalty) while a TPU device kind ranks the kernel where the model
  says it wins.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr

from libskylark_tpu import Context, engine, tune
from libskylark_tpu import sketch as sk
from libskylark_tpu.base import randgen
from libskylark_tpu.sketch import pallas_hash as ph
from libskylark_tpu.sketch.hash import cwt_serve_apply


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


@pytest.fixture()
def mem_plan_cache():
    """In-memory plan cache (no disk, empty): tests that edit plans must
    not touch the committed benchmarks/plan_cache.json."""
    prev = tune.set_cache(tune.PlanCache(path=None))
    yield tune.get_cache()
    tune.set_cache(prev)


def _cwt_and_ref(n, s, m, seed=7, rowwise=False):
    rng = np.random.default_rng(seed)
    T = sk.CWT(n, s, Context(seed=seed))
    kd = np.asarray(jr.key_data(T.allocation.key), np.uint32)
    shape = (m, n) if rowwise else (n, m)
    A = rng.standard_normal(shape).astype(np.float32)
    dim = sk.ROWWISE if rowwise else sk.COLUMNWISE
    ref = np.asarray(T.apply(jnp.asarray(A), dim))
    return T, kd, A, ref


class TestStreamReplication:
    @pytest.mark.parametrize("s_dim", [16, 100, 128])
    @pytest.mark.parametrize("n", [8, 40, 2048, 5000])
    def test_gen_hv_bit_equals_stream_slice(self, s_dim, n):
        """The in-kernel generation path (plain jnp ops here — the same
        ops Mosaic lowers) replays randgen.stream_slice exactly:
        UniformInt bucket stream, Rademacher value stream, across the
        CHUNK boundary (n=5000 spans two chunks)."""
        key = jr.key(42)
        n_pad = ph._padded_n(n)
        n_tile = min(n_pad, ph.CHUNK)
        n_chunks = n_pad // n_tile
        cols = min(n_tile, ph._GEN_COLS)
        tbl = ph.chunk_key_table(key, n_chunks)
        hs, vs = [], []
        for c in range(n_chunks):
            h, v = ph._gen_hv(tbl, c, s_dim, n_tile, cols)
            hs.append(np.asarray(h).reshape(-1))
            vs.append(np.asarray(v).reshape(-1))
        h_ref = np.asarray(randgen.stream_slice(
            jr.fold_in(key, 0), randgen.UniformInt(0, s_dim - 1), 0, n,
            dtype=jnp.int32))
        v_ref = np.asarray(randgen.stream_slice(
            jr.fold_in(key, 1), randgen.Rademacher(), 0, n,
            dtype=jnp.float32))
        assert np.array_equal(np.concatenate(hs)[:n], h_ref)
        assert np.array_equal(np.concatenate(vs)[:n], v_ref)

    def test_randint_multiplier_matches_jax(self):
        # pow2 spans ≤ 2^16 cancel the high draw entirely
        assert ph._randint_multiplier(16) == 0
        assert ph._randint_multiplier(1 << 16) == 0
        # general spans keep jax's double-draw mix
        assert ph._randint_multiplier(100) == ((65536 % 100) ** 2) % 100


class TestBitEquality:
    @pytest.mark.parametrize("rowwise", [False, True])
    @pytest.mark.parametrize("n,s,m", [(40, 16, 3), (100, 24, 5),
                                       (513, 32, 4)])
    def test_exact_accum_bit_equals_apply(self, n, s, m, rowwise):
        _T, kd, A, ref = _cwt_and_ref(n, s, m, rowwise=rowwise)
        out = np.asarray(ph.cwt_apply(kd, A, s_dim=s, rowwise=rowwise,
                                      accum="exact", interpret=True))
        assert np.array_equal(out, ref)

    @pytest.mark.parametrize("rowwise", [False, True])
    def test_padded_serve_lanes_bit_equal(self, rowwise):
        """Zero-padding the stream axis past the transform's true N —
        exactly what the serve bucket's pow2 class does — leaves the
        kernel bit-equal to cwt_serve_apply over the SAME padded
        operand and to the unpadded transform.apply."""
        n, s, m = 40, 16, 3
        _T, kd, A, ref = _cwt_and_ref(n, s, m, rowwise=rowwise)
        pad = [(0, 13), (0, 0)] if not rowwise else [(0, 0), (0, 13)]
        Ap = np.pad(A, pad)
        sv = np.asarray(cwt_serve_apply(kd, jnp.asarray(Ap), s_dim=s,
                                        rowwise=rowwise))
        out = np.asarray(ph.cwt_apply(kd, Ap, s_dim=s, rowwise=rowwise,
                                      accum="exact", interpret=True))
        assert np.array_equal(out, sv)
        assert np.array_equal(out, ref)

    def test_capacity_invariance_batched(self):
        """Per-lane bits are invariant to the cohort's capacity class:
        the same lane at B=1 and inside a B=3 stack (mixed seeds)
        produces identical bits — the serve lane-invariance contract."""
        lanes = [_cwt_and_ref(40, 16, 3, seed=i) for i in range(3)]
        kds = np.stack([kd for (_, kd, _, _) in lanes])
        As = np.stack([A for (_, _, A, _) in lanes])
        out = np.asarray(ph.cwt_apply_batched(
            kds, As, s_dim=16, rowwise=False, accum="exact",
            interpret=True))
        for i, (_, kd, A, ref) in enumerate(lanes):
            solo = np.asarray(ph.cwt_apply(
                kd, A, s_dim=16, rowwise=False, accum="exact",
                interpret=True))
            assert np.array_equal(out[i], solo)
            assert np.array_equal(out[i], ref)

    def test_mxu_mode_bit_equal_on_lattice_data(self):
        """Integer-valued data makes every bucket sum exact in f32, so
        the MXU one-hot contraction — different accumulation ORDER,
        identical values — is bit-equal to the scatter. This pins the
        entire mxu dataflow bitwise."""
        rng = np.random.default_rng(3)
        T = sk.CWT(200, 24, Context(seed=11))
        kd = np.asarray(jr.key_data(T.allocation.key), np.uint32)
        A = rng.integers(-8, 9, (200, 4)).astype(np.float32)
        ref = np.asarray(T.apply(jnp.asarray(A), sk.COLUMNWISE))
        out = np.asarray(ph.cwt_apply(kd, A, s_dim=24, rowwise=False,
                                      accum="mxu", interpret=True))
        assert np.array_equal(out, ref)

    def test_mxu_mode_close_on_float_data(self):
        _T, kd, A, ref = _cwt_and_ref(1000, 32, 5)
        out = np.asarray(ph.cwt_apply(kd, A, s_dim=32, rowwise=False,
                                      accum="mxu", interpret=True))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestQualifyAndDispatch:
    def test_qualify_reasons(self):
        ok, why = ph.qualify(16, 40, 3, np.float32, interpret=True)
        assert ok and why == "ok"
        ok, why = ph.qualify(16, 40, 3, np.float64, interpret=True)
        assert not ok and "float64" in why
        ok, why = ph.qualify(16, 0, 3, np.float32, interpret=True)
        assert not ok and "degenerate" in why
        ok, why = ph.qualify(16, 40, 3, np.float32, accum="nope")
        assert not ok and "accum" in why
        if not ph.available():
            ok, why = ph.qualify(16, 40, 3, np.float32)
            assert not ok and "TPU" in why

    def test_plan_tiles_shrink_dont_fail(self):
        plan = ph.plan_tiles(40, 3, 16)
        assert plan is not None
        n_pad, n_tile, m_pad, mt = plan
        assert n_pad == 64 and n_tile == 64
        assert m_pad % mt == 0
        # absurd s_dim: no tile fits — decline, never a Mosaic abort
        assert ph.plan_tiles(4096, 8, 50_000_000) is None

    @pytest.mark.skipif(ph.available(), reason="CPU-host dispatch test")
    def test_try_apply_declines_off_tpu(self, monkeypatch,
                                        mem_plan_cache):
        """The direct-apply hook: off-TPU the kernel always declines —
        env override and even a (mis-)certified plan entry cannot route
        an eager apply into uncompileable Mosaic."""
        T = sk.CWT(40, 16, Context(seed=0))
        A = jnp.asarray(np.ones((40, 3), np.float32))
        assert ph.try_apply(T, A, rowwise=False) is None
        monkeypatch.setenv("SKYLARK_HASH_KERNEL", "pallas")
        assert ph.try_apply(T, A, rowwise=False) is None
        monkeypatch.delenv("SKYLARK_HASH_KERNEL")
        w = tune.hash_workload("CWT", A.shape, A.dtype, 16, seq_axis=0)
        mem_plan_cache.put(w, tune.Plan("pallas"), source="measured",
                           value=1.0)
        assert ph.try_apply(T, A, rowwise=False) is None
        # and the public apply still serves (the scatter)
        out = T.apply(A, sk.COLUMNWISE)
        assert np.isfinite(np.asarray(out)).all()


class TestTuneServeBuckets:
    def test_hash_candidates_and_cpu_ranking(self):
        w = tune.hash_workload("CWT", (1000, 8), "float32", 32,
                               seq_axis=0)
        plans = tune.enumerate_candidates(w)
        assert {p.backend for p in plans} == {"pallas", "xla"}
        # on a CPU host the pallas plan means the interpreter: the
        # penalty must rank XLA first, always
        best, cost = tune.rank_candidates(w)[0]
        assert best.backend == "xla"

    def test_tpu_ranking_prefers_kernel_in_its_regime(self):
        # long stream, narrow sketch: the scatter serializes n rows
        # while the one-hot contraction is cheap — kernel wins
        w = tune.serve_workload(
            "sketch_apply", "CWT", "float32", (1024, 64), 32, 16,
            rowwise=False, device_kind="tpu_v5_lite")
        assert tune.rank_candidates(w)[0][0].backend == "pallas"
        # fastfood: fused chain ~9x less HBM traffic than the XLA chain
        wf = tune.serve_workload(
            "fastfood_features", "FastGaussianRFT", "float32",
            (512, 512), 512, 8, device_kind="tpu_v5_lite")
        assert tune.rank_candidates(wf)[0][0].backend == "pallas"

    def test_serve_key_carries_batch_class_legacy_keys_unchanged(self):
        w = tune.serve_workload("sketch_apply", "JLT", "float32",
                                (64, 128), 32, 8, rowwise=True)
        assert w.key().endswith("|b8")
        legacy = tune.dense_workload("normal", (64, 128), "float32", 32,
                                     seq_axis=1)
        assert "|b" not in legacy.key()

    def test_record_ranked_persists_and_yields_to_measured(
            self, mem_plan_cache):
        w = tune.serve_workload("sketch_apply", "CWT", "float32",
                                (64, 8), 16, 4, rowwise=False)
        plan, cost = tune.record_ranked(w)
        ent = mem_plan_cache.entry(w)
        assert ent["source"] == "ranked"
        assert ent["plan"]["backend"] == plan.backend == "xla"
        # a measured certification is never displaced by a re-ranking
        mem_plan_cache.put(w, tune.Plan("pallas"), source="measured",
                           value=2.0)
        tune.record_ranked(w)
        assert mem_plan_cache.entry(w)["source"] == "measured"

    def test_dense_serve_candidates_cross_m_tiles(self):
        w = tune.serve_workload("sketch_apply", "JLT", "float32",
                                (512, 1024), 64, 8, rowwise=True)
        plans = tune.enumerate_candidates(w)
        mts = {p.m_tile for p in plans if p.backend == "pallas"}
        assert mts == {128, 256, 512}
        assert any(p.backend == "xla" for p in plans)


class TestServeKernelSelection:
    def _cwt_reqs(self, k=8, seed=21):
        rng = np.random.default_rng(seed)
        T = sk.CWT(40, 16, Context(seed=seed))
        ops = [rng.standard_normal((40, 3)).astype(np.float32)
               for _ in range(k)]
        return T, ops

    def test_forced_pallas_flush_bit_equal_to_capacity1_xla(
            self, fresh_engine, mem_plan_cache):
        """The CI gate's bit-equality leg: a coalesced kernel-path
        flush equals the capacity-1 forced-XLA dispatch bitwise (exact
        accumulation under the interpreter)."""
        T, ops = self._cwt_reqs()
        with engine.MicrobatchExecutor(max_batch=8, linger_us=1000,
                                       kernel="pallas") as exp:
            futs = [exp.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            pall = [np.asarray(f.result(timeout=60)) for f in futs]
            st = exp.stats()
        assert st["kernel"]["by_backend"]["pallas"]["flushes"] >= 1
        with engine.MicrobatchExecutor(max_batch=1, linger_us=100,
                                       kernel="xla") as ex1:
            for A, p in zip(ops, pall):
                s = np.asarray(ex1.submit_sketch(
                    T, A, dimension=sk.COLUMNWISE).result(timeout=60))
                assert np.array_equal(p, s)

    def test_kernel_choice_is_executable_key_static(self, fresh_engine,
                                                    mem_plan_cache):
        """Forcing the other backend on an identical bucket compiles a
        DIFFERENT executable — the choice token is in the key, so a
        selection flip can never silently reuse the wrong program."""
        T, ops = self._cwt_reqs()
        with engine.MicrobatchExecutor(max_batch=8, linger_us=1000,
                                       kernel="xla") as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            [f.result(timeout=60) for f in futs]
        m0 = engine.stats().misses
        with engine.MicrobatchExecutor(max_batch=8, linger_us=1000,
                                       kernel="pallas") as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            [f.result(timeout=60) for f in futs]
        assert engine.stats().misses > m0
        assert engine.stats().recompiles == 0

    def test_env_override_beats_plan_cache(self, fresh_engine,
                                           mem_plan_cache, monkeypatch):
        T, ops = self._cwt_reqs(k=4)
        w = tune.serve_workload("sketch_apply", "CWT", "float32",
                                (64, 8), 16, 4, rowwise=False)
        mem_plan_cache.put(w, tune.Plan("pallas"), source="measured",
                           value=1.0)
        monkeypatch.setenv("SKYLARK_SERVE_KERNEL", "xla")
        with engine.MicrobatchExecutor(max_batch=4,
                                       linger_us=1000) as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            [f.result(timeout=60) for f in futs]
            st = ex.stats()
        assert st["kernel"]["by_backend"] == {"xla": {"flushes": 1}}

    def test_plan_cache_routes_flush_and_default_is_xla(
            self, fresh_engine, mem_plan_cache):
        """arg > override > cache > default precedence, cache leg: a
        certified pallas entry for EXACTLY this (bucket, capacity)
        routes the flush through the kernel; without one the default
        stays the vmapped XLA path."""
        T, ops = self._cwt_reqs(k=4)
        with engine.MicrobatchExecutor(max_batch=4,
                                       linger_us=1000) as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            xla_out = [np.asarray(f.result(timeout=60)) for f in futs]
            assert (ex.stats()["kernel"]["by_backend"]
                    == {"xla": {"flushes": 1}})
        w = tune.serve_workload("sketch_apply", "CWT", "float32",
                                (64, 8), 16, 4, rowwise=False)
        mem_plan_cache.put(w, tune.Plan("pallas"), source="measured",
                           value=1.0)
        with engine.MicrobatchExecutor(max_batch=4,
                                       linger_us=1000) as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            pal_out = [np.asarray(f.result(timeout=60)) for f in futs]
            assert (ex.stats()["kernel"]["by_backend"]
                    == {"pallas": {"flushes": 1}})
        for a, b in zip(xla_out, pal_out):
            assert np.array_equal(a, b)   # exact accum: bit-equal

    def test_decline_reason_counted(self, fresh_engine, mem_plan_cache):
        """A pallas intent the kernel can't serve (f64) falls back to
        XLA and the reason lands in the by_reason label set."""
        rng = np.random.default_rng(5)
        T = sk.CWT(40, 16, Context(seed=5))
        ops = [rng.standard_normal((40, 3)) for _ in range(2)]  # f64
        with engine.MicrobatchExecutor(max_batch=2, linger_us=500,
                                       kernel="pallas") as ex:
            futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                    for A in ops]
            [f.result(timeout=60) for f in futs]
            st = ex.stats()
        assert st["kernel"]["by_backend"]["xla"]["flushes"] >= 1
        assert any("float64" in r for r in st["kernel"]["by_reason"])
        agg = engine.serve_stats()
        assert agg["kernel"]["by_reason"]

    def test_prometheus_rendering_of_kernel_counters(
            self, fresh_engine, mem_plan_cache):
        """The fleet-operator surface: kernel selection and decline
        reasons render through the by_<label> convention as Prometheus
        label sets — skylark_serve_kernel_flushes{backend="..."} and
        ..._declined_flushes{reason="..."} — so which replicas are on
        the fast path (and why the others are not) is one scrape
        away."""
        from libskylark_tpu.telemetry import export as texp

        rng = np.random.default_rng(29)
        T = sk.CWT(40, 16, Context(seed=29))
        good = [rng.standard_normal((40, 3)).astype(np.float32)
                for _ in range(2)]
        bad = [rng.standard_normal((40, 3)) for _ in range(2)]  # f64
        with engine.MicrobatchExecutor(max_batch=2, linger_us=500,
                                       kernel="pallas") as ex:
            for ops in (good, bad):
                futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                        for A in ops]
                [f.result(timeout=60) for f in futs]
        txt = texp.prometheus_text()
        assert 'skylark_serve_kernel_flushes{backend="pallas"}' in txt
        assert 'skylark_serve_kernel_flushes{backend="xla"}' in txt
        declined = [ln for ln in txt.splitlines()
                    if ln.startswith(
                        "skylark_serve_kernel_declined_flushes{reason=")]
        assert declined and any("float64" in ln for ln in declined)

    def test_zero_recompiles_after_warmup_with_selection(
            self, fresh_engine, mem_plan_cache):
        """The acceptance criterion: selection enabled, every capacity
        class warmed once, then a storm — zero misses, zero
        recompiles."""
        T, ops = self._cwt_reqs(k=16)
        with engine.MicrobatchExecutor(max_batch=8, linger_us=5000,
                                       kernel="pallas") as ex:
            for cap in (1, 2, 4, 8):
                futs = [ex.submit_sketch(T, ops[i],
                                         dimension=sk.COLUMNWISE)
                        for i in range(cap)]
                ex.flush()
                [f.result(timeout=60) for f in futs]
            m0, r0 = engine.stats().misses, engine.stats().recompiles
            for _ in range(3):
                futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                        for A in ops]
                [f.result(timeout=60) for f in futs]
            assert engine.stats().misses == m0
            assert engine.stats().recompiles == r0


class TestPlanEditInvalidation:
    def test_plan_edit_recompiles_measurement_rerecord_does_not(
            self, fresh_engine, mem_plan_cache):
        """The r7 fingerprint contract extended to serve buckets:
        editing a bucket's PLAN re-keys (and recompiles) its flush
        executable exactly once; re-recording a better measurement of
        the SAME plan recompiles nothing."""
        rng = np.random.default_rng(31)
        T = sk.CWT(40, 16, Context(seed=31))
        ops = [rng.standard_normal((40, 3)).astype(np.float32)
               for _ in range(4)]
        w = tune.serve_workload("sketch_apply", "CWT", "float32",
                                (64, 8), 16, 4, rowwise=False)
        mem_plan_cache.put(w, tune.Plan("xla"), source="ranked")
        with engine.MicrobatchExecutor(max_batch=4,
                                       linger_us=1000) as ex:
            def storm():
                futs = [ex.submit_sketch(T, A, dimension=sk.COLUMNWISE)
                        for A in ops]
                return [np.asarray(f.result(timeout=60)) for f in futs]

            first = storm()
            m0 = engine.stats().misses
            # measurement re-record, same plan: fingerprint unchanged
            mem_plan_cache.record_measurement(w, tune.Plan("xla"), 5.0)
            storm()
            assert engine.stats().misses == m0
            # plan EDIT: xla -> pallas — exactly one fresh compile for
            # this bucket's capacity class, results still bit-equal
            mem_plan_cache.put(w, tune.Plan("pallas"),
                               source="measured", value=9.0)
            edited = storm()
            assert engine.stats().misses == m0 + 1
            assert ex.stats()["kernel"]["by_backend"]["pallas"][
                "flushes"] >= 1
            for a, b in zip(first, edited):
                assert np.array_equal(a, b)
            assert engine.stats().recompiles == 0
