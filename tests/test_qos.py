"""Multi-tenant QoS subsystem (libskylark_tpu/qos/, docs/qos).

Oracles:

- *weighted fairness*: deficit round robin drains sustained all-class
  backlog in the 8:4:1 class-weight ratio, never starves a class, and
  is a deterministic pure function of the visible backlog;
- *shed ordering*: best_effort sheds before standard before
  interactive — under DEGRADED (class-ordered bounds) AND under plain
  queue pressure (a best_effort storm can never shed a concurrent
  interactive request — the global-shed unfairness regression);
- *admission*: token buckets are deterministic in the observation
  clock; an over-quota request raises ``TenantQuotaError`` at submit
  and never occupies queue space;
- *adaptive batching*: the controller moves per-bucket linger/batch
  targets toward the class SLO in bounded, hysteretic steps, only
  along already-warm capacity rungs (zero recompiles), and
  ``SKYLARK_QOS_ADAPT=0`` freezes it;
- *heterogeneous endpoints*: graph_ase / graph_ppr / condest /
  lowrank / rlsc_predict are each bit-equal to their capacity-1
  dispatch AND to their eager library twins;
- *tenant propagation*: ``tenant=`` resolves at the router front door
  and the class rides to thread and process replicas;
- *chaos*: a tag-pinned serve.flush fault cannot break class ordering,
  and the qos.* lock sites stay acyclic under the runtime witness.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from libskylark_tpu import Context, engine, fleet, qos, telemetry
from libskylark_tpu import sketch as sk
from libskylark_tpu.base import errors as sk_errors
from libskylark_tpu.base import locks as sk_locks
from libskylark_tpu.engine import bucket as bucketing
from libskylark_tpu.ml import graph as mgraph
from libskylark_tpu.ml import rlsc as mrlsc
from libskylark_tpu.ml.kernels import Gaussian, Linear
from libskylark_tpu.nla import condest as ncondest
from libskylark_tpu.nla import lowrank as nlowrank
from libskylark_tpu.qos.controller import AdaptiveController
from libskylark_tpu.resilience import faults


@pytest.fixture()
def fresh_engine():
    engine.reset()
    yield
    engine.reset()


def _executor(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("linger_us", 1000)
    return engine.MicrobatchExecutor(**kw)


def _sketch_reqs(n_reqs=8, seed=0, n=48, s_dim=16):
    rng = np.random.default_rng(seed)
    ctx = Context(seed=seed)
    T = sk.CWT(n, s_dim, ctx)
    ops = [rng.standard_normal((n, 3 + i % 3)).astype(np.float32)
           for i in range(n_reqs)]
    return T, ops


def _graph(n=20, seed=0):
    rng = np.random.default_rng(seed)
    G = mgraph.Graph()
    for _ in range(4 * n):
        u, v = rng.integers(0, n, 2)
        G.add_edge(int(u), int(v))
    return G


# ---------------------------------------------------------------------------
# tenant registry + token buckets
# ---------------------------------------------------------------------------


class TestTenantRegistry:
    def test_resolve_known_unknown_and_anonymous(self):
        reg = qos.TenantRegistry()
        reg.register("ui", qos.INTERACTIVE)
        reg.register("etl", qos.BEST_EFFORT)
        assert reg.resolve("ui") == ("ui", "interactive")
        assert reg.resolve("etl") == ("etl", "best_effort")
        # unknown tenants and tenant-less requests land in the default
        # class — QoS is opt-in, never a prerequisite
        assert reg.resolve("stranger") == ("stranger", "standard")
        assert reg.resolve(None) == ("", "standard")

    def test_default_class_env_knob(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_QOS_DEFAULT_CLASS", "best_effort")
        reg = qos.TenantRegistry()
        assert reg.resolve(None)[1] == "best_effort"
        monkeypatch.setenv("SKYLARK_QOS_DEFAULT_CLASS", "bogus")
        assert reg.resolve(None)[1] == "standard"   # typo degrades

    def test_token_bucket_determinism(self):
        """Same arrival schedule, same admitted subset — twice."""
        schedule = [0.0, 0.01, 0.02, 0.15, 0.16, 0.3, 1.0, 1.01, 1.02]

        def run():
            tb = qos.TokenBucket(rate=10.0, burst=2)
            return [tb.try_acquire(t)[0] for t in schedule]

        a, b = run(), run()
        assert a == b
        # burst of 2 admits the first two, refills at 10/s thereafter
        assert a[:3] == [True, True, False]
        assert sum(a) < len(a)

    def test_token_bucket_retry_after_is_exact(self):
        tb = qos.TokenBucket(rate=4.0, burst=1)
        assert tb.try_acquire(0.0) == (True, 0.0)
        ok, retry = tb.try_acquire(0.0)
        assert not ok and retry == pytest.approx(0.25)

    def test_admit_raises_quota_error(self):
        reg = qos.TenantRegistry()
        reg.register("bulk", qos.BEST_EFFORT, rate=5.0, burst=1)
        reg.admit("bulk", now=0.0)
        with pytest.raises(sk_errors.TenantQuotaError) as ei:
            reg.admit("bulk", now=0.0)
        assert ei.value.tenant == "bulk"
        assert ei.value.retry_after_s > 0
        assert ei.value.code == 115
        # refilled after the advertised wait
        reg.admit("bulk", now=0.0 + ei.value.retry_after_s + 1e-6)

    def test_rate_default_env_knob(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_QOS_RATE_DEFAULT", "2.0")
        monkeypatch.setenv("SKYLARK_QOS_BURST_DEFAULT", "1")
        reg = qos.TenantRegistry()
        t = reg.register("limited", qos.STANDARD)
        assert t.bucket is not None and t.bucket.rate == 2.0
        reg.admit("limited", now=0.0)
        with pytest.raises(sk_errors.TenantQuotaError):
            reg.admit("limited", now=0.0)

    def test_unlimited_without_rate(self):
        reg = qos.TenantRegistry()
        reg.register("free", qos.INTERACTIVE)
        for _ in range(100):
            reg.admit("free", now=0.0)

    def test_explicit_zero_rate_is_an_error_not_unlimited(
            self, monkeypatch):
        """rate=0 must never silently grant unlimited quota; only a
        non-positive env DEFAULT degrades to unlimited (the typo
        convention)."""
        reg = qos.TenantRegistry()
        with pytest.raises(sk_errors.InvalidParametersError):
            reg.register("abuser", qos.BEST_EFFORT, rate=0.0)
        monkeypatch.setenv("SKYLARK_QOS_RATE_DEFAULT", "0")
        t = reg.register("envzero", qos.STANDARD)
        assert t.bucket is None          # env zero = no default limit
        # explicit burst=0 clamps to the 1-token floor, not to the
        # 2x-rate default a falsy-zero check would silently pick
        tb = qos.TokenBucket(rate=10.0, burst=0.0)
        assert tb.burst == 1.0


# ---------------------------------------------------------------------------
# weighted-fair deficit scheduling (property battery)
# ---------------------------------------------------------------------------


class TestDeficitScheduler:
    def _drain(self, sched, backlog, cost, rounds):
        served = {c: 0 for c in qos.CLASSES}
        for _ in range(rounds):
            c = sched.next_class(backlog, lambda cc: cost)
            assert c is not None
            sched.charge(c, cost)
            served[c] += cost
        return served

    def test_weighted_ratio_under_sustained_backlog(self):
        sched = qos.DeficitScheduler(quantum=4)
        backlog = {c: 10**9 for c in qos.CLASSES}
        served = self._drain(sched, backlog, 4, 13 * 20)
        # 8:4:1 — exact over whole credit rounds, near-exact mid-round
        assert served["interactive"] / served["best_effort"] == \
            pytest.approx(8.0, rel=0.15)
        assert served["standard"] / served["best_effort"] == \
            pytest.approx(4.0, rel=0.15)

    def test_starvation_freedom(self):
        sched = qos.DeficitScheduler(quantum=1)
        backlog = {c: 10**9 for c in qos.CLASSES}
        served = self._drain(sched, backlog, 1, 200)
        assert all(served[c] > 0 for c in qos.CLASSES)

    def test_single_class_work_conservation(self):
        sched = qos.DeficitScheduler()
        assert sched.next_class({"best_effort": 5},
                                lambda c: 5) == "best_effort"

    def test_idle_class_banks_no_credit(self):
        sched = qos.DeficitScheduler(quantum=4)
        both = {"interactive": 10**9, "best_effort": 10**9}
        self._drain(sched, both, 4, 50)
        # best_effort goes idle for many rounds...
        self._drain(sched, {"interactive": 10**9}, 4, 50)
        # ...and must NOT burst past its weight when it returns
        served = self._drain(qos.DeficitScheduler(quantum=4), both, 4,
                             26)
        resumed = self._drain(sched, both, 4, 26)
        assert resumed["best_effort"] <= served["best_effort"] + 4

    def test_determinism(self):
        def run():
            sched = qos.DeficitScheduler(quantum=2)
            out = []
            backlog = {"interactive": 7, "standard": 9,
                       "best_effort": 30}
            while any(v > 0 for v in backlog.values()):
                c = sched.next_class(
                    backlog, lambda cc: min(2, backlog[cc]))
                n = min(2, backlog[c])
                sched.charge(c, n)
                backlog[c] -= n
                out.append((c, n))
            return out

        assert run() == run()

    def test_nothing_ready(self):
        sched = qos.DeficitScheduler()
        assert sched.next_class({}, lambda c: 1) is None

    def test_drain_order_least_protected_first(self):
        assert qos.drain_order(list(qos.CLASSES)) == [
            "best_effort", "standard", "interactive"]


# ---------------------------------------------------------------------------
# class-ordered shedding (the global-shed unfairness fix)
# ---------------------------------------------------------------------------


class TestShedOrdering:
    def test_best_effort_storm_never_sheds_interactive(self,
                                                       fresh_engine):
        """The regression the satellite pins: a best_effort storm
        saturates ITS pressure bound (half the queue) and sheds —
        while concurrent interactive requests keep being admitted and
        completing with zero failures."""
        T, ops = _sketch_reqs(12)
        ex = _executor(max_batch=16, linger_us=10_000_000, max_queue=8)
        try:
            be_shed = 0
            be_futs = []
            for i in range(8):          # storm past the 0.5 bound
                try:
                    be_futs.append(ex.submit_sketch(
                        T, ops[i % len(ops)],
                        qos_class="best_effort"))
                except engine.ServeOverloadedError:
                    be_shed += 1
            assert be_shed >= 4          # pressure bound = 4 of 8
            # concurrent interactive traffic is untouched
            int_futs = [ex.submit_sketch(T, ops[i],
                                         qos_class="interactive")
                        for i in range(3)]
            ex.flush()
            for f in int_futs + be_futs:
                assert np.asarray(f.result(timeout=60)).size
            s = ex.stats()["qos"]["by_class"]
            assert s["interactive"]["shed"] == 0
            assert s["best_effort"]["shed"] == be_shed
        finally:
            ex.shutdown()

    def test_degraded_sheds_in_class_order(self, fresh_engine):
        """Under DEGRADED the bounds are interactive 0.5 > standard
        0.25 > best_effort 0.1 of max_queue: with the queue between
        the bounds, best_effort and standard shed while interactive
        still admits."""
        T, ops = _sketch_reqs(14, n=48)
        plan = {"seed": 0, "faults": [
            {"site": "serve.flush", "error": "IOError_",
             "tag": "bad"}]}
        ex = engine.MicrobatchExecutor(
            max_batch=1, linger_us=10_000_000, max_queue=16,
            failure_window=8, degraded_threshold=0.5)
        try:
            with faults.fault_plan(plan):
                with faults.tag("bad"):
                    futs = [ex.submit_sketch(T, ops[i])
                            for i in range(6)]
                ex.flush()
                [f.exception(timeout=60) for f in futs]
            assert ex.state == engine.DEGRADED
            # queue 4 interactive (bounds: be=1 std=4 int=8) so the
            # exposure sits between the standard and interactive
            # bounds
            held = [ex.submit_sketch(T, ops[6 + i],
                                     qos_class="interactive")
                    for i in range(4)]
            with pytest.raises(engine.ServeOverloadedError,
                               match="shed"):
                ex.submit_sketch(T, ops[9], qos_class="best_effort")
            with pytest.raises(engine.ServeOverloadedError,
                               match="shed"):
                ex.submit_sketch(T, ops[10], qos_class="standard")
            # interactive still admits below ITS bound
            held.append(ex.submit_sketch(T, ops[11],
                                         qos_class="interactive"))
            s = ex.stats()["qos"]["by_class"]
            assert s["best_effort"]["shed"] == 1
            assert s["standard"]["shed"] == 1
            assert s["interactive"]["shed"] == 0
            ex.flush()
            for f in held:
                f.result(timeout=60)
        finally:
            ex.shutdown()

    def test_session_appends_shed_below_interactive(self, fresh_engine,
                                                    tmp_path,
                                                    monkeypatch):
        """r16's session_shed routed through the policy: a DEGRADED
        executor sheds session appends while interactive one-shot
        traffic still serves."""
        monkeypatch.setenv("SKYLARK_SESSION_DIR", str(tmp_path))
        T, ops = _sketch_reqs(10)
        plan = {"seed": 0, "faults": [
            {"site": "serve.flush", "error": "IOError_",
             "tag": "bad"}]}
        ex = engine.MicrobatchExecutor(
            max_batch=1, linger_us=10_000_000, max_queue=16,
            failure_window=8, degraded_threshold=0.5)
        try:
            sid = ex.open_sketch_session("cwt", n=48, s_dim=16, d=3)
            with faults.fault_plan(plan):
                with faults.tag("bad"):
                    futs = [ex.submit_sketch(T, ops[i])
                            for i in range(6)]
                ex.flush()
                [f.exception(timeout=60) for f in futs]
            assert ex.state == engine.DEGRADED
            f = ex.session_append(
                sid, np.ones((2, 3), np.float32), seq=0)
            assert isinstance(f.exception(timeout=10),
                              engine.ServeOverloadedError)
            assert ex.stats()["session_shed"] == 1
            ok = ex.submit_sketch(T, ops[7], qos_class="interactive")
            ex.flush()
            ok.result(timeout=60)
        finally:
            ex.shutdown()

    def test_shed_env_knobs_move_their_own_class(self, fresh_engine,
                                                 monkeypatch):
        """Each SKYLARK_QOS_SHED_* knob moves exactly its own class's
        DEGRADED bound (the ctor scale divides by the standard
        class's DEFAULT, not the live env value — the regression
        where raising the standard knob was a no-op that also shrank
        the other classes' bounds)."""
        ex = _executor(max_queue=100)
        try:
            base = {c: ex._class_shed_bound(c) for c in qos.CLASSES}
            assert base == {"interactive": 50, "standard": 25,
                            "best_effort": 10}
            monkeypatch.setenv("SKYLARK_QOS_SHED_STANDARD", "0.5")
            assert ex._class_shed_bound("standard") == 50
            assert ex._class_shed_bound("interactive") == 50
            assert ex._class_shed_bound("best_effort") == 10
        finally:
            ex.shutdown()

    def test_shed_counters_carry_tenant(self, fresh_engine):
        reg = qos.TenantRegistry()
        reg.register("batchy", qos.BEST_EFFORT)
        T, ops = _sketch_reqs(10)
        ex = _executor(max_batch=16, linger_us=10_000_000, max_queue=4,
                       tenants=reg)
        try:
            shed = 0
            for i in range(6):
                try:
                    ex.submit_sketch(T, ops[i % 4], tenant="batchy")
                except engine.ServeOverloadedError:
                    shed += 1
            assert shed
            s = ex.stats()["qos"]
            assert s["by_tenant"]["batchy"]["shed"] == shed
            assert s["by_tenant"]["batchy"]["admitted"] >= 1
            ex.flush()
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# admission rate limiting through the executor
# ---------------------------------------------------------------------------


class TestExecutorAdmission:
    def test_rate_limited_submit_raises_and_counts(self, fresh_engine):
        reg = qos.TenantRegistry()
        reg.register("bulk", qos.BEST_EFFORT, rate=0.001, burst=1)
        T, ops = _sketch_reqs(4)
        ex = _executor(tenants=reg)
        try:
            ex.submit_sketch(T, ops[0], tenant="bulk")
            with pytest.raises(sk_errors.TenantQuotaError):
                ex.submit_sketch(T, ops[1], tenant="bulk")
            s = ex.stats()["qos"]
            assert s["by_tenant"]["bulk"]["rate_limited"] == 1
            ex.flush()
        finally:
            ex.shutdown()

    def test_preresolved_class_skips_admission(self, fresh_engine):
        """qos_class= marks a front-door-admitted request: the token
        bucket must not be charged twice."""
        reg = qos.TenantRegistry()
        reg.register("bulk", qos.BEST_EFFORT, rate=0.001, burst=1)
        T, ops = _sketch_reqs(4)
        ex = _executor(tenants=reg)
        try:
            for i in range(4):          # would be over quota if billed
                ex.submit_sketch(T, ops[i], tenant="bulk",
                                 qos_class="best_effort")
            ex.flush()
            s = ex.stats()["qos"]["by_class"]["best_effort"]
            assert s["admitted"] == 4 and s["rate_limited"] == 0
        finally:
            ex.shutdown()

    def test_unregistered_tenant_accounts_anonymously(self,
                                                      fresh_engine):
        """Cardinality bound: arbitrary caller tenant strings must
        not grow the per-tenant accounting — unknown tenants land in
        the anonymous bucket, registered ones keep their label."""
        reg = qos.TenantRegistry()
        reg.register("known", qos.INTERACTIVE)
        T, ops = _sketch_reqs(4)
        ex = _executor(tenants=reg)
        try:
            for i in range(3):
                ex.submit_sketch(T, ops[i], tenant=f"user-{i}")
            ex.submit_sketch(T, ops[3], tenant="known")
            ex.flush()
            by_tenant = ex.stats()["qos"]["by_tenant"]
            assert set(by_tenant) == {"known"}
            assert ex.stats()["qos"]["by_class"]["standard"][
                "admitted"] == 3
        finally:
            ex.shutdown()

    def test_unknown_class_degrades_to_default(self, fresh_engine):
        T, ops = _sketch_reqs(2)
        ex = _executor()
        try:
            f = ex.submit_sketch(T, ops[0], qos_class="platinum")
            ex.flush()
            f.result(timeout=60)
            assert ex.stats()["qos"]["by_class"]["standard"][
                "admitted"] == 1
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# weighted-fair serving under overload (integration)
# ---------------------------------------------------------------------------


class TestWeightedFairServing:
    def test_interactive_drains_ahead_under_backlog(self, fresh_engine):
        """Both classes backlogged and ready: the flusher's DRR
        dispatches interactive cohorts first (weight 8 vs 1), so
        interactive completions finish ahead of best_effort ones."""
        T, ops = _sketch_reqs(8, n=64)
        done: dict = {}

        def stamp(cls):
            def cb(_f):
                done.setdefault(cls, []).append(time.monotonic())
            return cb

        ex = _executor(max_batch=4, linger_us=60_000, workers=1,
                       max_queue=1024)
        try:
            futs = []
            # interactive first so best_effort full cohorts cannot
            # take the fast path (higher class pending)
            for i in range(8):
                f = ex.submit_sketch(T, ops[i % 8],
                                     qos_class="interactive")
                f.add_done_callback(stamp("interactive"))
                futs.append(f)
            for i in range(8):
                f = ex.submit_sketch(T, ops[i % 8],
                                     qos_class="best_effort")
                f.add_done_callback(stamp("best_effort"))
                futs.append(f)
            for f in futs:
                f.result(timeout=120)
            assert max(done["interactive"]) <= min(
                done["best_effort"]) + 1e-4
            served = ex.stats()["qos"]["scheduler"]["served"]
            assert served["interactive"] >= 8
        finally:
            ex.shutdown()

    def test_starvation_freedom_under_sustained_overload(
            self, fresh_engine):
        """A continuous interactive stream never starves best_effort:
        its weight is >= 1, so queued best_effort work still drains."""
        T, ops = _sketch_reqs(8, n=64)
        ex = _executor(max_batch=2, linger_us=500, workers=1,
                       max_queue=4096)
        try:
            be = [ex.submit_sketch(T, ops[i % 8],
                                   qos_class="best_effort")
                  for i in range(6)]
            futs = []
            for i in range(60):         # sustained interactive load
                futs.append(ex.submit_sketch(
                    T, ops[i % 8], qos_class="interactive"))
            for f in be:                # best_effort still completes
                f.result(timeout=120)
            ex.flush()
            for f in futs:
                f.result(timeout=120)
            served = ex.stats()["qos"]["scheduler"]["served"]
            assert served["best_effort"] >= 1
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# adaptive batching controller
# ---------------------------------------------------------------------------


def _warm_bucket(ex, T, ops, n_batches=6, **kw):
    for _ in range(n_batches):
        futs = [ex.submit_sketch(T, A, **kw) for A in ops]
        ex.flush()
        [f.result(timeout=60) for f in futs]


class TestAdaptiveController:
    def test_converges_down_when_over_slo(self, fresh_engine,
                                          monkeypatch):
        """p99 over the class SLO: linger halves and the batch target
        steps one warm rung down — after the 2-tick hysteresis."""
        monkeypatch.setenv("SKYLARK_QOS_SLO_STANDARD_MS", "0.0001")
        T, ops = _sketch_reqs(8, n=48)
        ex = _executor(max_batch=8, linger_us=2000)
        ctrl = AdaptiveController(ex, start=False)
        try:
            _warm_bucket(ex, T, ops[:8])
            statics = engine.request_statics(
                "sketch_apply", transform=T, A=ops[0])
            linger0, cap0 = ex.bucket_targets(statics)
            assert ctrl.tick() == 0      # hysteresis: first tick arms
            _warm_bucket(ex, T, ops[:8], n_batches=2)
            assert ctrl.tick() >= 1      # second tick acts
            linger1, cap1 = ex.bucket_targets(statics)
            assert linger1 < linger0
            # batch target stepped down along the WARM ladder only
            obs = ex.qos_bucket_obs()[statics]
            assert cap1 in obs["caps"] or cap1 == cap0
            s = ctrl.stats()
            assert s["adjustments"] >= 1 and not s["frozen"]
        finally:
            ctrl.close()
            ex.shutdown()

    def test_converges_up_on_waste_with_headroom(self, fresh_engine,
                                                 monkeypatch):
        """Far under SLO with high padding waste: linger grows
        (bounded, capped) and the batch target climbs one warm rung."""
        monkeypatch.setenv("SKYLARK_QOS_SLO_STANDARD_MS", "60000")
        T, ops = _sketch_reqs(8, n=33)   # heavy padding at class 64
        ex = _executor(max_batch=8, linger_us=1000)
        ctrl = AdaptiveController(ex, start=False)
        try:
            # warm capacities 2 and 8 so an up-rung exists
            for batch in (ops[:2], ops[:8], ops[:2], ops[:8]):
                futs = [ex.submit_sketch(T, A) for A in batch]
                ex.flush()
                [f.result(timeout=60) for f in futs]
            statics = engine.request_statics(
                "sketch_apply", transform=T, A=ops[0])
            ex.set_bucket_targets(statics, batch_cap=2)
            linger0, _ = ex.bucket_targets(statics)
            ctrl.tick()
            _warm_bucket(ex, T, ops[:2], n_batches=3)
            changed = ctrl.tick()
            if not changed:              # hysteresis may need one more
                _warm_bucket(ex, T, ops[:2], n_batches=3)
                changed = ctrl.tick()
            assert changed >= 1
            linger1, cap1 = ex.bucket_targets(statics)
            assert linger1 > linger0
            assert cap1 in (8, 2)        # warm rung only
            assert linger1 <= ex.linger * 8.0 + 1e-9
        finally:
            ctrl.close()
            ex.shutdown()

    def test_acting_resets_the_evidence_window(self, fresh_engine,
                                               monkeypatch):
        """A step drops the bucket's latency/waste window (warm caps
        persist): the burst that triggered the step cannot keep
        driving same-direction steps from stale samples."""
        monkeypatch.setenv("SKYLARK_QOS_SLO_STANDARD_MS", "0.0001")
        T, ops = _sketch_reqs(8, n=48)
        ex = _executor(max_batch=8, linger_us=2000)
        ctrl = AdaptiveController(ex, start=False)
        try:
            _warm_bucket(ex, T, ops[:8])
            statics = engine.request_statics(
                "sketch_apply", transform=T, A=ops[0])
            ctrl.tick()
            _warm_bucket(ex, T, ops[:8], n_batches=2)
            assert ctrl.tick() >= 1
            obs = ex.qos_bucket_obs()[statics]
            assert obs["p99"] is None        # window dropped
            assert obs["caps"]               # warm rungs persist
            # with no fresh post-change samples, further ticks are
            # no-ops instead of re-scoring the old burst
            assert ctrl.tick() == 0
            assert ctrl.tick() == 0
        finally:
            ctrl.close()
            ex.shutdown()

    def test_freeze_knob(self, fresh_engine, monkeypatch):
        monkeypatch.setenv("SKYLARK_QOS_SLO_STANDARD_MS", "0.0001")
        monkeypatch.setenv("SKYLARK_QOS_ADAPT", "0")
        T, ops = _sketch_reqs(8)
        ex = _executor(max_batch=8, linger_us=2000)
        ctrl = AdaptiveController(ex, start=False)
        try:
            _warm_bucket(ex, T, ops[:8])
            statics = engine.request_statics(
                "sketch_apply", transform=T, A=ops[0])
            before = ex.bucket_targets(statics)
            for _ in range(4):
                assert ctrl.tick() == 0
            assert ex.bucket_targets(statics) == before
            s = ctrl.stats()
            assert s["frozen"] and s["frozen_ticks"] == 4
        finally:
            ctrl.close()
            ex.shutdown()

    def test_zero_recompile_invariant(self, fresh_engine, monkeypatch):
        """Retuning changes targets but compiles nothing: the batch
        target moves only along warm rungs and linger is not a key
        component."""
        monkeypatch.setenv("SKYLARK_QOS_SLO_STANDARD_MS", "0.0001")
        T, ops = _sketch_reqs(8, n=48)
        ex = _executor(max_batch=8, linger_us=2000)
        ctrl = AdaptiveController(ex, start=False)
        try:
            # warm rungs 4 and 8
            for batch in (ops[:4], ops[:8], ops[:4], ops[:8]):
                futs = [ex.submit_sketch(T, A) for A in batch]
                ex.flush()
                [f.result(timeout=60) for f in futs]
            base = engine.stats().to_dict()
            ctrl.tick()
            _warm_bucket(ex, T, ops[:8], n_batches=2)
            assert ctrl.tick() >= 1      # targets moved
            # traffic at the retuned targets: cohorts now cap at the
            # lower rung, which is already compiled
            _warm_bucket(ex, T, ops[:8], n_batches=3)
            after = engine.stats().to_dict()
            assert after["recompiles"] == base["recompiles"]
            assert after["misses"] == base["misses"]
        finally:
            ctrl.close()
            ex.shutdown()

    def test_executor_adaptive_flag_starts_controller(self,
                                                      fresh_engine):
        ex = _executor(adaptive=True)
        try:
            assert ex.stats()["qos"]["controller"] is not None
        finally:
            ex.shutdown()

    def test_capacity_ladder_helper(self):
        assert bucketing.capacity_ladder(8) == (1, 2, 4, 8)
        assert bucketing.capacity_ladder(8, multiple=4) == (4, 8)
        assert bucketing.capacity_ladder(1) == (1,)
        # a non-pow2 max_batch's full-cohort rung (the most common
        # capacity under load) must be on the ladder
        assert bucketing.capacity_ladder(12) == (1, 2, 4, 8, 12)


# ---------------------------------------------------------------------------
# heterogeneous serve endpoints: bit-equality battery
# ---------------------------------------------------------------------------


class TestNewEndpoints:
    def _capacity1(self, submits):
        ex1 = _executor(max_batch=1, linger_us=100)
        try:
            return [np.asarray(s(ex1).result(timeout=120))
                    for s in submits]
        finally:
            ex1.shutdown()

    def test_graph_ase_bit_equality(self, fresh_engine):
        G = _graph(20, seed=1)
        ex = _executor(max_batch=4, linger_us=2000)
        try:
            futs = [ex.submit_graph_ase(G, 3, seed=s)
                    for s in (0, 1, 2, 0)]
            batched = [np.asarray(f.result(timeout=120)) for f in futs]
            cap1 = self._capacity1(
                [lambda e, s=s: e.submit_graph_ase(G, 3, seed=s)
                 for s in (0, 1, 2, 0)])
            for b, c in zip(batched, cap1):
                assert np.array_equal(b, c)
            Xe, indexmap = mgraph.graph_ase_serve(G, 3, seed=0)
            assert np.array_equal(batched[0], Xe)
            assert batched[0].shape == (G.num_vertices(), 3)
            assert len(indexmap) == G.num_vertices()
            # same-seed requests are bit-identical
            assert np.array_equal(batched[0], batched[3])
        finally:
            ex.shutdown()

    def test_graph_ase_embedding_quality(self, fresh_engine):
        """Two dense blocks joined by one edge: the embedding's top
        dimension separates the blocks (a sanity anchor, not a bit
        oracle)."""
        G = mgraph.Graph()
        for blk in (range(0, 8), range(8, 16)):
            blk = list(blk)
            for i in blk:
                for j in blk:
                    if i < j:
                        G.add_edge(i, j)
        G.add_edge(0, 8)
        ex = _executor(max_batch=1, linger_us=100)
        try:
            X, im = ex.submit_graph_ase(G, 2, seed=0,
                                        iters=4).result(timeout=120), \
                G.vertices
            X = np.asarray(X)
            # dominant eigenvector magnitude similar within blocks
            a = np.abs(X[:8, 0]).mean()
            b = np.abs(X[8:, 0]).mean()
            assert a > 0 and b > 0
        finally:
            ex.shutdown()

    def test_graph_ppr_bit_equality_and_mass(self, fresh_engine):
        G = _graph(24, seed=2)
        n = G.num_vertices()
        s0 = np.zeros(n, np.float32)
        s0[0] = 1.0
        s1 = np.zeros(n, np.float32)
        s1[1] = 1.0
        ex = _executor(max_batch=4, linger_us=2000)
        try:
            futs = [ex.submit_graph_ppr(G, s, alpha=0.85, iters=8)
                    for s in (s0, s1, s0)]
            batched = [np.asarray(f.result(timeout=120)) for f in futs]
            cap1 = self._capacity1(
                [lambda e, s=s: e.submit_graph_ppr(G, s, alpha=0.85,
                                                   iters=8)
                 for s in (s0, s1, s0)])
            for b, c in zip(batched, cap1):
                assert np.array_equal(b, c)
            pe, _ = mgraph.graph_ppr_serve(G, s0, alpha=0.85, iters=8)
            assert np.array_equal(batched[0], pe)
            # diffusion sanity: non-negative, seed keeps the largest
            # score, total mass below 1 (teleport absorbs the rest)
            p = batched[0]
            assert (p >= 0).all() and p.argmax() == 0
            assert 0.1 < p.sum() <= 1.0 + 1e-5
        finally:
            ex.shutdown()

    def test_condest_bit_equality_and_accuracy(self, fresh_engine):
        rng = np.random.default_rng(3)
        mats = [rng.standard_normal((24, 10)).astype(np.float32)
                for _ in range(3)]
        ex = _executor(max_batch=4, linger_us=2000)
        try:
            futs = [ex.submit_condest(A, steps=6, seed=1)
                    for A in mats]
            batched = [np.asarray(f.result(timeout=120)) for f in futs]
            cap1 = self._capacity1(
                [lambda e, A=A: e.submit_condest(A, steps=6, seed=1)
                 for A in mats])
            for b, c in zip(batched, cap1):
                assert np.array_equal(b, c)
            et = ncondest.condest_serve(mats[0], steps=6, seed=1)
            assert np.array_equal(batched[0],
                                  np.asarray(et, np.float32))
            # against the f64 host oracle: the fixed-step estimate
            # brackets within the documented estimator tolerance
            ref_cond, ref_max, _ = ncondest.condest(mats[0],
                                                    Context(9))
            assert batched[0][1] == pytest.approx(ref_max, rel=0.2)
            assert 1.0 <= batched[0][0] <= 3.0 * ref_cond
        finally:
            ex.shutdown()

    def test_condest_rejects_excess_steps(self, fresh_engine):
        ex = _executor()
        try:
            with pytest.raises(ValueError, match="steps"):
                ex.submit_condest(np.eye(4, dtype=np.float32),
                                  steps=10)
        finally:
            ex.shutdown()

    def test_lowrank_bit_equality_and_span(self, fresh_engine):
        rng = np.random.default_rng(4)
        ctx = Context(11)
        kern = Linear(10)
        Ts = kern.create_rft(8, ctx)
        Tt = kern.create_rft(12, ctx)
        # low-rank + noise operand at a pow2 row class (bitwise regime)
        U0 = rng.standard_normal((16, 3)).astype(np.float32)
        V0 = rng.standard_normal((3, 10)).astype(np.float32)
        mats = [(U0 @ V0 + 0.01 * rng.standard_normal((16, 10))
                 ).astype(np.float32) for _ in range(3)]
        ex = _executor(max_batch=4, linger_us=2000)
        try:
            futs = [ex.submit_lowrank(Ts, Tt, A, 3) for A in mats]
            batched = [np.asarray(f.result(timeout=120)) for f in futs]
            cap1 = self._capacity1(
                [lambda e, A=A: e.submit_lowrank(Ts, Tt, A, 3)
                 for A in mats])
            for b, c in zip(batched, cap1):
                assert np.array_equal(b, c)
            Ze = nlowrank.lowrank_serve(Ts, Tt, mats[0], 3)
            assert np.array_equal(batched[0], Ze)
            # the basis captures the dominant subspace: projection
            # residual well under the noise-free norm
            Z = batched[0]
            A = mats[0]
            resid = np.linalg.norm(A - Z @ (Z.T @ A))
            assert resid < 0.35 * np.linalg.norm(A)
        finally:
            ex.shutdown()

    def test_rlsc_predict_bit_equality_and_decode(self, fresh_engine):
        rng = np.random.default_rng(5)
        gk = Gaussian(4, 1.0)
        Xtr = rng.standard_normal((12, 4)).astype(np.float32)
        coef = rng.standard_normal((12, 3)).astype(np.float32)
        queries = [rng.standard_normal((5, 4)).astype(np.float32)
                   for _ in range(3)]
        coding = ["cat", "dog", "bird"]
        ex = _executor(max_batch=4, linger_us=2000)
        try:
            futs = [ex.submit_rlsc_predict(gk, Xq, Xtr, coef)
                    for Xq in queries]
            batched = [np.asarray(f.result(timeout=120)) for f in futs]
            cap1 = self._capacity1(
                [lambda e, Xq=Xq: e.submit_rlsc_predict(gk, Xq, Xtr,
                                                        coef)
                 for Xq in queries])
            for b, c in zip(batched, cap1):
                assert np.array_equal(b, c)
                assert b.dtype == np.int32
            et = mrlsc.rlsc_predict(gk, queries[0], Xtr, coef)
            assert np.array_equal(batched[0], et)
            # decoded labels
            fd = ex.submit_rlsc_predict(gk, queries[0], Xtr, coef,
                                        coding=coding)
            labels = fd.result(timeout=120)
            assert list(labels) == [coding[i] for i in batched[0]]
        finally:
            ex.shutdown()

    def test_endpoints_are_distinct_bucket_families(self, fresh_engine):
        G = _graph(16, seed=6)
        s = np.ones(G.num_vertices(), np.float32)
        rng = np.random.default_rng(6)
        A = rng.standard_normal((16, 8)).astype(np.float32)
        st1 = engine.request_statics("graph_ase", A=G, k=2)
        st2 = engine.request_statics("graph_ppr", A=G, s=s)
        st3 = engine.request_statics("condest", A=A, steps=4)
        fams = {st1[0], st2[0], st3[0]}
        assert fams == {"graph_ase", "graph_ppr", "condest"}

    def test_graph_endpoints_accept_scipy(self, fresh_engine):
        import scipy.sparse as sp

        rng = np.random.default_rng(7)
        n = 12
        M = (rng.random((n, n)) < 0.2).astype(np.float32)
        M = np.triu(M, 1)
        M = M + M.T
        S = sp.csr_matrix(M)
        ex = _executor(max_batch=1, linger_us=100)
        try:
            out = np.asarray(
                ex.submit_graph_ase(S, 2, seed=0).result(timeout=120))
            assert out.shape == (n, 2)
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# tenant propagation through the fleet
# ---------------------------------------------------------------------------


class TestFleetPropagation:
    def test_thread_fleet_propagates_class(self, fresh_engine):
        reg = qos.get_registry()
        reg.register("ui-fleet-test", qos.INTERACTIVE)
        reg.register("etl-fleet-test", qos.BEST_EFFORT)
        T, ops = _sketch_reqs(8)
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=500)
        router = fleet.Router(pool)
        try:
            futs = [router.submit_sketch(T, ops[i % 8],
                                         tenant="ui-fleet-test")
                    for i in range(4)]
            futs += [router.submit_sketch(T, ops[i % 8],
                                          tenant="etl-fleet-test")
                     for i in range(4)]
            for f in futs:
                f.result(timeout=120)
            agg = engine.serve_stats()["qos"]
            assert agg["by_class"]["interactive"]["admitted"] >= 4
            assert agg["by_class"]["best_effort"]["admitted"] >= 4
            assert agg["by_tenant"]["ui-fleet-test"]["admitted"] == 4
        finally:
            router.close()
            pool.shutdown()
            reg.unregister("ui-fleet-test")
            reg.unregister("etl-fleet-test")

    def test_router_front_door_rate_limit(self, fresh_engine):
        reg = qos.get_registry()
        reg.register("throttled-fleet", qos.STANDARD, rate=0.001,
                     burst=1)
        T, ops = _sketch_reqs(4)
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=500)
        router = fleet.Router(pool)
        try:
            router.submit_sketch(T, ops[0],
                                 tenant="throttled-fleet").result(
                                     timeout=120)
            with pytest.raises(sk_errors.TenantQuotaError):
                router.submit_sketch(T, ops[1],
                                     tenant="throttled-fleet")
            # the refusal is COUNTED at the front door — the
            # executor-side counting never saw this request
            assert router.stats()["rate_limited"] == 1
            assert fleet.fleet_stats()["rate_limited"] >= 1
        finally:
            router.close()
            pool.shutdown()
            reg.unregister("throttled-fleet")

    def test_best_effort_never_hedges(self, fresh_engine):
        T, ops = _sketch_reqs(4)
        pool = fleet.ReplicaPool(2, max_batch=4, linger_us=500)
        router = fleet.Router(pool, hedge=True, hedge_delay_ms=0.0)
        try:
            futs = [router.submit_sketch(T, ops[i],
                                         qos_class="best_effort")
                    for i in range(4)]
            for f in futs:
                f.result(timeout=120)
            time.sleep(0.2)              # give a hedge time to fire
            assert router.stats()["hedged"] == 0
        finally:
            router.close()
            pool.shutdown()

    @pytest.mark.slow
    def test_process_replica_propagates_class(self, fresh_engine):
        T, ops = _sketch_reqs(4)
        pool = fleet.ReplicaPool(1, backend="process", max_batch=4,
                                 linger_us=500)
        router = fleet.Router(pool)
        try:
            futs = [router.submit_sketch(T, ops[i],
                                         qos_class="interactive",
                                         tenant="remote-ui")
                    for i in range(3)]
            for f in futs:
                f.result(timeout=120)
            child = pool.get(pool.names()[0]).stats()["qos"]
            assert child["by_class"]["interactive"]["admitted"] == 3
            assert child["by_tenant"]["remote-ui"]["admitted"] == 3
        finally:
            router.close()
            pool.shutdown()


# ---------------------------------------------------------------------------
# chaos: class ordering survives injected faults, lock sites acyclic
# ---------------------------------------------------------------------------


class TestChaos:
    def test_flush_fault_does_not_break_class_ordering(self,
                                                       fresh_engine):
        """A tag-pinned serve.flush fault poisons ONE best_effort
        request; every interactive request still completes, bit-equal
        to a fault-free run — and the qos.* lock sites recorded by
        the runtime witness stay acyclic."""
        sk_locks.reset_witness()
        sk_locks.enable_witness(True)
        try:
            reg = qos.TenantRegistry()   # fresh locks: witnessed
            reg.register("chaos-ui", qos.INTERACTIVE)
            reg.register("chaos-etl", qos.BEST_EFFORT, rate=1000.0)
            T, ops = _sketch_reqs(8, n=48)
            plan = {"seed": 13, "faults": [
                {"site": "serve.flush", "error": "SketchError",
                 "tag": "poison"}]}
            ex = engine.MicrobatchExecutor(
                max_batch=4, linger_us=2000, tenants=reg,
                adaptive=True)
            try:
                with faults.fault_plan(plan):
                    good = [ex.submit_sketch(T, ops[i],
                                             tenant="chaos-ui")
                            for i in range(4)]
                    with faults.tag("poison"):
                        bad = ex.submit_sketch(T, ops[4],
                                               tenant="chaos-etl")
                    more = [ex.submit_sketch(T, ops[i],
                                             tenant="chaos-etl")
                            for i in range(5, 8)]
                    ex.flush()
                    assert isinstance(bad.exception(timeout=60),
                                      sk_errors.SketchError)
                    results = [np.asarray(f.result(timeout=60))
                               for f in good + more]
                # fault-free reference run, same operands
                ref_ex = _executor(max_batch=4, linger_us=2000)
                refs = [np.asarray(
                    ref_ex.submit_sketch(T, ops[i]).result(timeout=60))
                    for i in list(range(4)) + list(range(5, 8))]
                ref_ex.shutdown()
                for got, ref in zip(results, refs):
                    assert np.array_equal(got, ref)
                s = ex.stats()["qos"]["by_class"]
                assert s["interactive"]["shed"] == 0
            finally:
                ex.shutdown()
            sk_locks.check_witness()     # qos.* sites acyclic
        finally:
            sk_locks.enable_witness(False)
            sk_locks.reset_witness()

    def test_qos_admit_fault_site(self, fresh_engine):
        T, ops = _sketch_reqs(2)
        plan = {"seed": 0, "faults": [
            {"site": "qos.admit", "error": "IOError_",
             "tag": "bad-admit"}]}
        ex = _executor()
        try:
            with faults.fault_plan(plan):
                with faults.tag("bad-admit"):
                    with pytest.raises(sk_errors.IOError_):
                        ex.submit_sketch(T, ops[0])
                ok = ex.submit_sketch(T, ops[1])
                ex.flush()
                ok.result(timeout=60)
        finally:
            ex.shutdown()


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------


class TestTelemetry:
    def test_prometheus_qos_rendering(self, fresh_engine):
        T, ops = _sketch_reqs(4)
        was_enabled = telemetry.enabled()
        telemetry.set_enabled(True)   # exercise the LIVE instruments
        ex = _executor()
        try:
            futs = [ex.submit_sketch(T, ops[i],
                                     qos_class="interactive")
                    for i in range(3)]
            ex.flush()
            [f.result(timeout=60) for f in futs]
            text = telemetry.prometheus_text()
            # the qos collector aggregates every live executor in the
            # process, so assert presence + a floor, not an exact count
            import re

            m = re.search(
                r'skylark_qos_admitted\{class="interactive"\} (\d+)',
                text)
            assert m and int(m.group(1)) >= 3
            # the live gauge carries a replica label so N executors
            # publish N series instead of clobbering one label key
            assert re.search(
                r'skylark_qos_queue_depth\{class="interactive",'
                r'replica="' + re.escape(ex.name) + r'"\}', text)
            assert "skylark_qos_shed" in text
        finally:
            ex.shutdown()
            telemetry.set_enabled(was_enabled)

    def test_stats_and_collector_shape(self, fresh_engine):
        T, ops = _sketch_reqs(2)
        ex = _executor(adaptive=True)
        try:
            f = ex.submit_sketch(T, ops[0], qos_class="interactive")
            ex.flush()
            f.result(timeout=60)
            q = ex.stats()["qos"]
            assert set(q["by_class"]) == set(qos.CLASSES)
            assert "latency_s" in q["by_class"]["interactive"]
            assert q["scheduler"]["weights"]["interactive"] == 8
            assert q["controller"]["ticks"] >= 0
            agg = engine.serve_stats()["qos"]
            assert agg["by_class"]["interactive"]["admitted"] >= 1
            snap = telemetry.snapshot()
            assert "registry" in snap["collectors"]["qos"]
        finally:
            ex.shutdown()
